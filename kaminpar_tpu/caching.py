"""Shared shape-bucketing + bounded-cache policy (ROADMAP item 5's
"refactor unlock").

Two concerns that every scaling direction (hot-kernel fusion, the
serving layer, multi-chip scale-out) shares used to be scattered:

  * the **shape-bucket padding policy** — device arrays are padded to a
    bounded family of shapes so XLA executables are reused across
    levels, graphs, and requests.  ``pad_size`` (previously
    ``utils/math.pad_size``; re-exported there for its existing callers)
    is THE policy: next power of two with a granularity floor, giving
    O(log n) distinct compiled shapes per graph.  ``bucket_key``
    compacts a request's (n, m, k) into the executable-identity triple
    the jit cache effectively keys on, so executable *reuse* becomes an
    observable hit-rate instead of an invisible property of jax
    internals.
  * the **bounded cache policy** — :class:`BoundedCache`, an LRU with an
    explicit entry cap AND a byte budget, so caches grown by sustained
    traffic (the serving result cache, future plan/executable caches)
    stay bounded instead of OOMing the host after a few hours of load.
    Hit/miss/eviction counters are first-class (`stats()`), and the
    serving layer surfaces them in the run report and the BENCH trend.

Import-light by design (numpy only): the serving layer pulls this in
before any backend exists, and ``utils/math`` re-exports ``pad_size``
from here at interpreter start.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from contextlib import contextmanager
from typing import Any, Dict, Hashable, Optional, Tuple


def _ceil2(x: int) -> int:
    """Smallest power of two >= x (utils/math.ceil2's twin; duplicated
    two lines here so this module stays import-cycle-free — utils/math
    re-exports pad_size from HERE)."""
    if x <= 1:
        return 1
    return 1 << (x - 1).bit_length()


# --- pad policy modes (resilience/memory.py's rung-① lever) ---------------
#
# "bucketed" (the default, and the ONLY mode unless a memory-pressure
# governor engages) is the executable-reuse policy below: next power of
# two over a granularity floor.  "tight" trades that reuse for memory:
# shapes round up only to the granularity multiple, so a padded buffer
# carries at most granularity-1 wasted slots instead of up to ~2x.  The
# mode is thread-local (each run's recovery ladder owns its own policy)
# and scope-managed, so the default path is byte-identical to the
# pre-governor behavior — the jaxpr-equality pins rely on that.

_pad_mode = threading.local()

PAD_POLICIES = ("bucketed", "tight")


def pad_policy() -> str:
    """The calling thread's active pad policy ("bucketed" by default)."""
    return getattr(_pad_mode, "mode", "bucketed")


@contextmanager
def pad_policy_scope(mode: str):
    """Run a block under a pad policy (restores the previous mode on
    exit; used by the OOM recovery ladder around each rung attempt)."""
    if mode not in PAD_POLICIES:
        raise ValueError(f"unknown pad policy {mode!r}")
    prev = pad_policy()
    _pad_mode.mode = mode
    try:
        yield
    finally:
        _pad_mode.mode = prev


def pad_size(x: int, granularity: int = 256) -> int:
    """Shape-bucketed padding: next power of two, but at least x rounded up to
    `granularity`.  Bounds the number of distinct compiled shapes per graph to
    O(log n) as the multilevel hierarchy shrinks the graph ~2x per level.

    Under the "tight" pad policy (pad_policy_scope; engaged only by the
    memory-pressure recovery ladder) the power-of-two step is dropped:
    shapes round up to the next `granularity` multiple only — no-headroom
    buckets that trade executable reuse for device bytes."""
    if x <= granularity:
        return granularity
    if pad_policy() == "tight":
        return ((x + granularity - 1) // granularity) * granularity
    return _ceil2(x)


def pad_k(k: int) -> int:
    """The block-count bucket: k rounded up to a power of two (>= 2).
    Mirrors ops/segments.pad_k_bucket, which additionally builds the
    zero-capacity phantom block weights on device — this host-side twin
    exists so bucket identity can be computed without importing jax."""
    return max(2, 1 << (int(k) - 1).bit_length())


def bucket_key(n: int, m: int, k: int) -> Tuple[int, int, int]:
    """The executable-identity triple of a request: padded node slots,
    padded edge slots, padded block count.  Two requests with the same
    bucket key drive the device phases through the same compiled
    programs (same shapes, same k tables) — the serving layer counts
    reuse of these keys as its executable-cache hit rate."""
    return (pad_size(int(n) + 1), pad_size(max(int(m), 1)), pad_k(k))


def record_padding(n=None, n_pad=None, m=None, m_pad=None,
                   k=None, k_pad=None) -> None:
    """Report one padded launch shape (real vs padded element counts,
    per axis) to the performance observatory.

    The shape-bucket policy lives here, so this is where every pad site
    (device CSR upload, contraction, subgraph slicing, the k bucket,
    dist shards) reports what fraction of the launch was padding — the
    run report's `perf.pad_waste` rows.  Import-light contract intact:
    telemetry is imported lazily and the call is a no-op (one bool
    check) unless the perf layer is enabled."""
    try:
        from .telemetry import perf
    except Exception:
        return
    if perf.enabled():
        perf.record_padding(
            n=n, n_pad=n_pad, m=m, m_pad=m_pad, k=k, k_pad=k_pad
        )


def record_transfer(direction, nbytes, kind="") -> None:
    """Report one host<->device transfer (direction "h2d" or "d2h",
    payload size, chokepoint kind) to the execution ledger's transfer
    leg — the same lazy-import forwarding contract as record_padding,
    so import-light callers (graphs/csr upload, chunk stores) meter
    their boundary traffic without importing telemetry eagerly."""
    try:
        from .telemetry import ledger
    except Exception:
        return
    ledger.transfer(direction, nbytes, kind=kind)


class BoundedCache:
    """A thread-safe LRU cache with an entry cap and a byte budget.

    ``put(key, value, nbytes)`` evicts least-recently-used entries until
    both bounds hold; a single value larger than the byte budget is
    refused (``stats()['oversize']`` counts these) rather than evicting
    the whole cache for one entry.  ``get`` returns None on miss —
    callers that need to distinguish a cached None should wrap values.
    """

    def __init__(self, max_entries: int = 128,
                 max_bytes: int = 256 << 20) -> None:
        self.max_entries = int(max_entries)
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        self._entries: "OrderedDict[Hashable, Tuple[Any, int]]" = (
            OrderedDict()
        )
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.oversize = 0
        # eviction-cause split: `capacity` evictions keep the configured
        # bounds (put overflow), `pressure` evictions were demanded by
        # the memory governor (evict_to) — the serving report separates
        # the two so a shrinking cache under HBM pressure is tellable
        # from ordinary LRU turnover
        self.evictions_capacity = 0
        self.evictions_pressure = 0
        # per-window twins (begin_window): a long-lived serving process
        # reports fresh per-window rates instead of lifetime averages
        # that asymptotically freeze under sustained traffic
        self.w_hits = 0
        self.w_misses = 0
        self.w_evictions = 0
        self.w_oversize = 0
        self.w_evictions_capacity = 0
        self.w_evictions_pressure = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    @property
    def nbytes(self) -> int:
        return self._bytes

    def get(self, key: Hashable) -> Optional[Any]:
        with self._lock:
            ent = self._entries.get(key)
            if ent is None:
                self.misses += 1
                self.w_misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            self.w_hits += 1
            return ent[0]

    def put(self, key: Hashable, value: Any, nbytes: int = 0) -> bool:
        """Insert (replacing any existing entry); returns False when the
        value alone exceeds the byte budget and was refused."""
        nbytes = int(nbytes)
        with self._lock:
            if nbytes > self.max_bytes:
                self.oversize += 1
                self.w_oversize += 1
                return False
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            self._entries[key] = (value, nbytes)
            self._bytes += nbytes
            while (
                len(self._entries) > self.max_entries
                or self._bytes > self.max_bytes
            ):
                _, (_, dropped) = self._entries.popitem(last=False)
                self._bytes -= dropped
                self.evictions += 1
                self.w_evictions += 1
                self.evictions_capacity += 1
                self.w_evictions_capacity += 1
            return True

    def evict(self, key: Hashable) -> bool:
        """Drop one entry (the serving-cache fault's forced-evict mode);
        returns True when something was removed."""
        with self._lock:
            ent = self._entries.pop(key, None)
            if ent is None:
                return False
            self._bytes -= ent[1]
            self.evictions += 1
            self.evictions_capacity += 1
            return True

    def evict_to(self, target_bytes: int, cause: str = "pressure") -> int:
        """Shed least-recently-used entries until the cache holds at most
        ``target_bytes`` (0 sheds every byte-carrying entry; zero-byte
        entries hold no device memory and are left alone).  Returns the
        bytes freed.  The
        memory governor's pressure hook and the OOM recovery ladder call
        this with cause="pressure" — those evictions are counted apart
        from ordinary capacity turnover (`stats()['evictions_pressure']`)
        so an operator can see what HBM pressure cost the cache."""
        target_bytes = max(0, int(target_bytes))
        freed = 0
        with self._lock:
            carrying = [k for k, (_, nb) in self._entries.items() if nb > 0]
            for key in carrying:
                if self._bytes <= target_bytes:
                    break
                _, dropped = self._entries.pop(key)
                self._bytes -= dropped
                freed += dropped
                self.evictions += 1
                self.w_evictions += 1
                if cause == "pressure":
                    self.evictions_pressure += 1
                    self.w_evictions_pressure += 1
                else:
                    self.evictions_capacity += 1
                    self.w_evictions_capacity += 1
        return freed

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0

    def begin_window(self) -> None:
        """Zero the per-window counters (lifetime totals are kept) —
        called by the serving layer's `reset_records()` so each exported
        report window carries its own hit rate."""
        with self._lock:
            self.w_hits = 0
            self.w_misses = 0
            self.w_evictions = 0
            self.w_oversize = 0
            self.w_evictions_capacity = 0
            self.w_evictions_pressure = 0

    def stats(self) -> Dict[str, Any]:
        """Counter snapshot (the run report's cache subsections):
        lifetime totals plus the current window's counters."""
        with self._lock:
            lookups = self.hits + self.misses
            w_lookups = self.w_hits + self.w_misses
            return {
                "entries": len(self._entries),
                "bytes": int(self._bytes),
                "max_entries": self.max_entries,
                "max_bytes": int(self.max_bytes),
                "hits": int(self.hits),
                "misses": int(self.misses),
                "evictions": int(self.evictions),
                "evictions_capacity": int(self.evictions_capacity),
                "evictions_pressure": int(self.evictions_pressure),
                "oversize": int(self.oversize),
                "hit_rate": (
                    round(self.hits / lookups, 4) if lookups else 0.0
                ),
                "window": {
                    "hits": int(self.w_hits),
                    "misses": int(self.w_misses),
                    "evictions": int(self.w_evictions),
                    "evictions_capacity": int(self.w_evictions_capacity),
                    "evictions_pressure": int(self.w_evictions_pressure),
                    "oversize": int(self.w_oversize),
                    "hit_rate": (
                        round(self.w_hits / w_lookups, 4)
                        if w_lookups else 0.0
                    ),
                },
            }


class BucketTracker:
    """Executable-reuse accounting over :func:`bucket_key` triples.

    jax's jit cache is the actual executable store; what it never tells
    you is the *reuse rate* under a request stream.  The tracker counts
    the first sighting of a bucket as a miss (a compile) and every later
    sighting as a hit (executable reuse) — the compile-accounting layer
    (telemetry/compile_account.py) confirms the attribution from the
    other side."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._seen: Dict[Tuple[int, int, int], int] = {}
        self.hits = 0
        self.misses = 0
        # per-window twins (begin_window) — see BoundedCache
        self.w_hits = 0
        self.w_misses = 0

    def observe(self, n: int, m: int, k: int) -> Tuple[int, int, int]:
        """Record one request's bucket; returns the key."""
        key = bucket_key(n, m, k)
        with self._lock:
            if key in self._seen:
                self._seen[key] += 1
                self.hits += 1
                self.w_hits += 1
            else:
                self._seen[key] = 1
                self.misses += 1
                self.w_misses += 1
        return key

    def begin_window(self) -> None:
        """Zero the per-window counters (bucket sightings and lifetime
        totals are kept)."""
        with self._lock:
            self.w_hits = 0
            self.w_misses = 0

    def per_bucket(self) -> Dict[str, int]:
        """Lifetime sightings per bucket ("n/m/k" string keys) — the
        serving latency rollup joins these with its per-class
        histograms."""
        with self._lock:
            return {
                "/".join(str(x) for x in key): int(count)
                for key, count in self._seen.items()
            }

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            lookups = self.hits + self.misses
            w_lookups = self.w_hits + self.w_misses
            return {
                "buckets": len(self._seen),
                "hits": int(self.hits),
                "misses": int(self.misses),
                "hit_rate": (
                    round(self.hits / lookups, 4) if lookups else 0.0
                ),
                "window": {
                    "hits": int(self.w_hits),
                    "misses": int(self.w_misses),
                    "hit_rate": (
                        round(self.w_hits / w_lookups, 4)
                        if w_lookups else 0.0
                    ),
                },
            }


def full_graph_digest(graph) -> str:
    """Exact structural identity of a graph: a hash of the FULL
    adjacency and both weight arrays.  The checkpoint layer's sampling
    ``graph_fingerprint`` is deliberately O(1) — resume only needs to
    catch operator error — but a *result cache* replays stored answers
    to matching keys, so its identity must cover every edge and weight:
    two graphs that differ only in interior edges (beyond the sampled
    head/tail) or in edge weights (which the sampling fingerprint never
    reads) must never share a cached partition.  Compressed containers
    are hashed as their raw encoded byte streams — same bytes, same
    graph — so no decode pass is needed; either way the cost is one
    sequential sweep over host memory, noise next to a partition."""
    import hashlib

    import numpy as np

    # dynamic graph sessions (dynamic/session.py) stamp their evolving
    # delta-chain digest onto the graph object: the chain already covers
    # the base adjacency (hashed once at register) plus every applied
    # DeltaBatch, so a mutate costs O(delta), never a fresh O(m) sweep.
    # The "dyn:" domain prefix keeps chain digests disjoint from the raw
    # hex digests below — a (possibly poisoned) chain hash can never
    # alias the exact digest of a differing plain graph.
    chain = getattr(graph, "_chain_digest", None)
    if chain is not None:
        return str(chain)
    h = hashlib.sha256()

    def _arr(a) -> None:
        if a is None:
            h.update(b"\x00none")
            return
        a = np.ascontiguousarray(np.asarray(a))
        h.update(str(a.dtype).encode())
        h.update(a.tobytes())

    h.update(f"n={int(graph.n)};m={int(graph.m)};".encode())
    if hasattr(graph, "data") and hasattr(graph, "offsets"):
        # CompressedHostGraph: raw codec streams are the exact identity
        h.update(str(getattr(graph, "codec", "?")).encode())
        for name in ("xadj", "offsets", "data", "node_weights",
                     "edge_weights", "wdata", "woffsets"):
            _arr(getattr(graph, name, None))
    else:
        _arr(np.asarray(graph.xadj, dtype=np.int64))
        _arr(graph.adjncy)
        _arr(getattr(graph, "node_weights", None))
        _arr(getattr(graph, "edge_weights", None))
    return h.hexdigest()[:24]


def result_cache_key(graph, ctx) -> Tuple[str, str]:
    """The (graph identity, ctx fingerprint) a cached result is valid
    for.  The graph identity is the PR-5 sampling ``graph_fingerprint``
    (so the cache and the resume machinery agree on the cheap prefix)
    strengthened with :func:`full_graph_digest` — the sampling
    fingerprint alone ignores edge weights and interior structure, which
    a replaying cache cannot afford.  The ctx fingerprint covers seed,
    k, epsilon, preset and every algorithm knob, and excludes the
    resilience/debug subtrees — a per-request deadline does not fork the
    cache key."""
    from .resilience.checkpoint import ctx_fingerprint, graph_fingerprint

    return (
        graph_fingerprint(graph) + ":" + full_graph_digest(graph),
        ctx_fingerprint(ctx),
    )
