"""Device dtype policy — the 32/64-bit weight build switch.

The analog of the reference's KAMINPAR_64BIT_[NODE|EDGE]WEIGHTS CMake
options (CMakeLists.txt:67-75): KAMINPAR_TPU_64BIT=1 in the environment
(before first import) switches every device weight and accumulator to
int64 and enables jax x64.  Node/edge IDS stay int32 either way — an id
count above 2^31 is a separate limit, as in the reference's 64-bit ID
build.  TPU int64 is emulated (~2x per irregular op); the flag exists for
graphs whose total edge weight overflows int32, not as a default.

A leaf module so both graphs.csr and ops.segments can import it without
package-init cycles.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

X64_WEIGHTS = os.environ.get("KAMINPAR_TPU_64BIT", "0").lower() not in (
    "", "0", "false", "off", "no",
)
if X64_WEIGHTS:
    jax.config.update("jax_enable_x64", True)

# Weight accumulator dtype.  int32 matches the reference's default 32-bit
# weight build and is TPU-native; the 64-bit build flips it.
ACC_DTYPE = jnp.int64 if X64_WEIGHTS else jnp.int32
# Device weight storage matches the accumulator.
WEIGHT_DTYPE = ACC_DTYPE
# Largest representable weight (clamp bound for caps read from int64
# host arrays).
WMAX = int(jnp.iinfo(WEIGHT_DTYPE).max)
# Gain/weight sentinel: the minimum of the accumulator dtype.  (Named for
# the default build; under KAMINPAR_TPU_64BIT it is int64's minimum — a
# 32-bit sentinel would collide with real 64-bit gains.)
INT32_MIN = int(jnp.iinfo(ACC_DTYPE).min)
