"""V-cycle partitioner.

Analog of kaminpar-shm/partitioning/deep/vcycle_deep_multilevel.cc:
iterated deep multilevel with community restriction — run deep multilevel
once, then for each configured v-cycle re-coarsen the graph with clustering
restricted to the current blocks (communities), project the partition down,
and refine back up.  Each cycle can only improve the cut because the
community restriction keeps the projected partition valid at every level.
"""

from __future__ import annotations

from typing import List

import jax.numpy as jnp
import numpy as np

from ..dtypes import WEIGHT_DTYPE, WMAX
from ..context import Context
from ..graphs.csr import device_graph_from_host
from ..graphs.host import HostGraph
from ..ops.contraction import contract_clustering
from ..ops.lp import LPConfig, lp_cluster
from ..utils import timer
from ..utils.logger import log_progress
from .deep import DeepMultilevelPartitioner
from .refiner import RefinerPipeline


class VcycleDeepMultilevelPartitioner:
    def __init__(self, ctx: Context, initial_partition=None,
                 max_levels: int | None = None):
        """``initial_partition`` warm-starts the cycles: the initial
        deep multilevel run is skipped and the given (valid, full-k)
        partition seeds cycle 0 — the dynamic-repartitioning driver's
        entry (dynamic/repartition.py).  ``max_levels`` bounds the
        restricted-coarsening depth per cycle (0 = a pure refinement
        pass at the fine level); None = coarsen to the usual threshold.
        """
        self.ctx = ctx
        self.initial_partition = initial_partition
        self.max_levels = max_levels

    def partition(self, graph: HostGraph) -> np.ndarray:
        ctx = self.ctx
        k = ctx.partition.k
        from ..resilience import checkpoint as ckpt

        # checkpoint resume (resilience/checkpoint.py): a `vcycle` stage
        # holds the partition after a completed cycle — skip the initial
        # deep run and every finished cycle.  A kill DURING the initial
        # deep run instead left a `deep`-scheme checkpoint, which the
        # embedded deep driver below resumes on its own.
        resume = ckpt.take_resume("vcycle")
        start_cycle = 0
        part = None
        if resume is not None and "state" in resume["arrays"]:
            part = np.asarray(
                resume["arrays"]["state"]["partition"], dtype=np.int32
            )
            start_cycle = int(resume.get("level") or 0) + 1
            from .. import telemetry

            telemetry.event(
                "resume", scheme="vcycle", stage=resume["stage"],
                level=resume.get("level"),
            )

        if part is None and self.initial_partition is not None:
            # warm start: the previous (session) partition replaces the
            # initial deep run; a checkpoint resume above still wins —
            # kill-and-resume must re-enter the recorded cycle, not
            # restart from the warm seed
            part = np.asarray(self.initial_partition, dtype=np.int32)
            if part.shape != (graph.n,):
                raise ValueError(
                    f"warm-start partition shape {part.shape} != "
                    f"({graph.n},)")
            if len(part) and (int(part.min()) < 0
                              or int(part.max()) >= k):
                raise ValueError(
                    "warm-start partition labels out of range "
                    f"[0, {k})")
        if part is None:
            # initial partition via one full deep multilevel run
            deep_ctx = ctx.copy()
            from ..context import PartitioningMode

            deep_ctx.partitioning.mode = PartitioningMode.DEEP
            deep_ctx.partition = ctx.partition  # share the configured weights
            part = DeepMultilevelPartitioner(deep_ctx).partition(graph)

        from .. import telemetry
        from ..graphs.host import host_partition_metrics

        num_cycles = max(len(ctx.partitioning.vcycles), 1)
        for cycle in range(start_cycle, num_cycles):
            from ..resilience import deadline as deadline_mod

            if deadline_mod.should_stop():
                # anytime wind-down: cycles only improve an already-valid
                # partition — stop starting new ones
                break
            with timer.scoped_timer(f"vcycle-{cycle}"):
                part = self._one_vcycle(graph, part, cycle)
            # cut per cycle only for plain CSR inputs (compressed graphs
            # lack the host edge arrays; the facade decodes before vcycle
            # dispatch, but direct callers may not)
            if telemetry.enabled() and isinstance(graph, HostGraph):
                telemetry.event(
                    "vcycle",
                    cycle=cycle,
                    cut=int(host_partition_metrics(graph, part, k)["cut"]),
                )
            part_now = part
            ckpt.barrier(
                "vcycle", level=cycle, scheme="vcycle",
                payload=lambda: {"state": {
                    "partition": np.asarray(part_now, dtype=np.int32),
                }},
            )
        return part

    def _one_vcycle(
        self, graph: HostGraph, part: np.ndarray, cycle: int
    ) -> np.ndarray:
        """Community-restricted coarsen -> project down -> refine up."""
        from ..telemetry import quality as quality_mod

        # quality observatory: each cycle records its own hierarchy
        # (last finalize wins the report section, so the FINAL cycle's
        # attribution describes the returned partition)
        qh = quality_mod.begin("vcycle")
        try:
            return self._one_vcycle_recorded(graph, part, cycle, qh)
        finally:
            quality_mod.end(qh)

    def _one_vcycle_recorded(
        self, graph: HostGraph, part: np.ndarray, cycle: int, qh
    ) -> np.ndarray:
        from ..telemetry import quality as quality_mod

        ctx = self.ctx
        k = ctx.partition.k
        dgraph = device_graph_from_host(graph)
        padded = np.zeros(dgraph.n_pad, dtype=np.int32)
        padded[: graph.n] = part
        partition = jnp.asarray(padded)

        max_bw = jnp.asarray(
            np.minimum(ctx.partition.max_block_weights, WMAX),
            dtype=WEIGHT_DTYPE,
        )
        min_bw = (
            jnp.asarray(
                np.minimum(ctx.partition.min_block_weights, WMAX),
                dtype=WEIGHT_DTYPE,
            )
            if ctx.partition.min_block_weights is not None
            else None
        )
        lp_cfg = LPConfig(
            num_iterations=ctx.coarsening.clustering.lp.num_iterations,
            participation=ctx.coarsening.clustering.lp.participation,
        )

        # coarsen with community restriction
        levels = []
        current = dgraph
        current_part = partition
        current_n = graph.n
        threshold = max(2 * ctx.coarsening.contraction_limit, 2)
        level = 0
        while current_n > threshold and (
            self.max_levels is None or level < self.max_levels
        ):
            max_cw = max(
                1,
                ctx.coarsening.max_cluster_weight(
                    current_n, ctx.partition.total_node_weight, ctx.partition
                ),
            )
            seed = jnp.int32(
                (ctx.seed * 65713 + cycle * 977 + level * 31337) & 0x7FFFFFFF
            )
            labels = lp_cluster(
                current,
                jnp.asarray(min(max_cw, WMAX), dtype=WEIGHT_DTYPE),
                seed,
                lp_cfg,
                communities=current_part,
            )
            coarse, c_n, c_m = contract_clustering(current, labels)
            if c_n >= (1.0 - ctx.coarsening.convergence_threshold) * current_n:
                break
            # project the partition down: clusters never span blocks
            coarse_part = coarse.project_down(current_part)
            levels.append((current, coarse, current_part))
            quality_mod.note_cmap(
                level=len(levels), cmap=coarse.cmap, fine_n=current_n
            )
            quality_mod.note_contraction(
                level=len(levels), fine_graph=current, coarse=coarse,
                fine_n=current_n, coarse_n=c_n, coarse_m=c_m,
                max_cluster_weight=max_cw,
                total_node_weight=int(ctx.partition.total_node_weight),
            )
            current = coarse.graph
            current_part = coarse_part
            current_n = c_n
            level += 1
            log_progress(f"vcycle coarsening level {level}: n={c_n}")

        # refine back up
        refiner = RefinerPipeline(ctx, k)
        num_levels = len(levels) + 1
        quality_mod.note_projected(len(levels), current, current_part, k=k)
        current_part = refiner.refine(
            current,
            current_part,
            max_bw,
            min_bw,
            seed=ctx.seed + cycle,
            level=len(levels),
            num_levels=num_levels,
        )
        quality_mod.note_refined(len(levels), current, current_part, k=k)
        for lvl in range(len(levels) - 1, -1, -1):
            fine_graph, coarse, _ = levels[lvl]
            current_part = coarse.project_up(current_part)
            quality_mod.note_projected(lvl, fine_graph, current_part, k=k)
            current_part = refiner.refine(
                fine_graph,
                current_part,
                max_bw,
                min_bw,
                seed=ctx.seed + cycle,
                level=lvl,
                num_levels=num_levels,
            )
            quality_mod.note_refined(lvl, fine_graph, current_part, k=k)

        current_part = refiner.enforce_balance_host(
            dgraph, current_part,
            np.asarray(ctx.partition.max_block_weights), where="vcycle",
        )
        quality_mod.finalize_device(qh, dgraph, current_part, graph.n)
        return np.asarray(current_part)[: graph.n]
