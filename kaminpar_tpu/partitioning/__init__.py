from .coarsener import Coarsener  # noqa: F401
from .refiner import RefinerPipeline  # noqa: F401
from .rb import recursive_bipartition  # noqa: F401
