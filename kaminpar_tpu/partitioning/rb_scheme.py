"""Recursive bipartitioning scheme.

Analog of kaminpar-shm/partitioning/rb/rb_multilevel.cc: partition into 2,
recurse per block.  Each bisection is a full sequential multilevel
bipartition (partitioning/rb.py); the finest-level partition is then refined
on device with the context's refiner pipeline.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..dtypes import WEIGHT_DTYPE, WMAX
from ..context import Context
from ..graphs.csr import device_graph_from_host
from ..graphs.host import HostGraph
from ..utils import rng as rng_mod
from ..utils import timer
from .refiner import RefinerPipeline
from .rb import recursive_bipartition


class RBMultilevelPartitioner:
    def __init__(self, ctx: Context):
        self.ctx = ctx

    def partition(self, graph: HostGraph) -> np.ndarray:
        ctx = self.ctx
        k = ctx.partition.k
        rng = rng_mod.host_rng(ctx.seed ^ 0x5B)
        with timer.scoped_timer("recursive-bipartitioning"):
            part = recursive_bipartition(graph, k, ctx, rng)

        # barrier (resilience/checkpoint.py): rb has no multilevel
        # hierarchy to snapshot — the bisection tree IS the work — but
        # the deadline/preemption wind-down still gates the optional
        # top-level polish (the facade's result checkpoint covers
        # durability)
        from ..resilience import checkpoint as ckpt

        proceed = ckpt.barrier("rb-toplevel", scheme="rb")
        if proceed and ctx.partitioning.rb_enable_kway_toplevel_refinement:
            with timer.scoped_timer("toplevel-refinement"):
                dgraph = device_graph_from_host(graph)
                padded = np.zeros(dgraph.n_pad, dtype=np.int32)
                padded[: graph.n] = part
                max_bw = jnp.asarray(
                    np.minimum(ctx.partition.max_block_weights, WMAX),
                    dtype=WEIGHT_DTYPE,
                )
                min_bw = (
                    jnp.asarray(
                        np.minimum(ctx.partition.min_block_weights, WMAX),
                        dtype=WEIGHT_DTYPE,
                    )
                    if ctx.partition.min_block_weights is not None
                    else None
                )
                refiner = RefinerPipeline(ctx, k)
                refined = refiner.refine(
                    dgraph, jnp.asarray(padded), max_bw, min_bw, seed=ctx.seed
                )
            # the balance backstop and final readback live OUTSIDE the
            # refinement span: both are host-phase work, and keeping the
            # device->host pull out of the timed region keeps the span
            # honest about refinement cost (tpulint R1)
            refined = refiner.enforce_balance_host(
                dgraph, refined,
                np.asarray(ctx.partition.max_block_weights), where="rb",
            )
            part = np.asarray(refined)[: graph.n]
        return part
