"""Debug dumps of graph / partition hierarchies.

Analog of kaminpar-shm/partitioning/debug.cc (193 LoC): when the
DebugContext flags (include/kaminpar-shm/kaminpar.h:484-496) are set,
the partitioners write the toplevel/coarsest/per-level graphs as METIS
files and the corresponding partitions as newline-separated block-ID
files into `ctx.debug.dump_dir`.  These dumps double as the framework's
checkpoint analog (SURVEY.md §5: the reference has no runtime
checkpointing; hierarchy dumps are the closest artifact).
"""

from __future__ import annotations

import os

import numpy as np

from ..context import Context
from ..io.metis import write_metis
from ..io.partition import write_partition
from ..utils.logger import log_debug


def _path(ctx: Context, name: str) -> str:
    os.makedirs(ctx.debug.dump_dir, exist_ok=True)
    prefix = ctx.debug.graph_name or "graph"
    return os.path.join(ctx.debug.dump_dir, f"{prefix}.{name}")


def dump_graph(ctx: Context, host_graph, name: str) -> None:
    """debug::dump_graph analog: write a hierarchy level as METIS."""
    path = _path(ctx, f"{name}.metis")
    write_metis(host_graph, path)
    log_debug(f"[debug] dumped graph to {path}")


def dump_partition(ctx: Context, partition, name: str) -> None:
    """debug::dump_partition analog."""
    path = _path(ctx, f"{name}.part")
    write_partition(path, np.asarray(partition))
    log_debug(f"[debug] dumped partition to {path}")


def dump_toplevel_graph(ctx: Context, host_graph) -> None:
    if ctx.debug.dump_toplevel_graph:
        dump_graph(ctx, host_graph, "toplevel")


def dump_toplevel_partition(ctx: Context, partition) -> None:
    if ctx.debug.dump_toplevel_partition:
        dump_partition(ctx, partition, "toplevel")


def dump_coarsest_graph(ctx: Context, host_graph) -> None:
    if ctx.debug.dump_coarsest_graph:
        dump_graph(ctx, host_graph, "coarsest")


def dump_coarsest_partition(ctx: Context, partition) -> None:
    if ctx.debug.dump_coarsest_partition:
        dump_partition(ctx, partition, "coarsest")


def dump_graph_hierarchy(ctx: Context, host_graph, level: int) -> None:
    if ctx.debug.dump_graph_hierarchy:
        dump_graph(ctx, host_graph, f"level{level}")


def dump_partition_hierarchy(ctx: Context, partition, level: int) -> None:
    if ctx.debug.dump_partition_hierarchy:
        dump_partition(ctx, partition, f"level{level}")
