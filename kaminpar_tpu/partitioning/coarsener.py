"""Device multilevel coarsener.

Analog of kaminpar-shm/coarsening/abstract_cluster_coarsener.cc (+
BasicClusterCoarsener): drives lp_cluster -> contract_clustering level by
level, keeps the hierarchy for projection, applies the max-cluster-weight
formula (max_cluster_weights.h) and the shrink/convergence checks
(abstract_cluster_coarsener.cc:98-147).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from functools import partial

import jax

from ..context import Context
from ..graphs.csr import DeviceGraph, WEIGHT_DTYPE
from ..ops.contraction import CoarseGraph, contract_clustering
from ..ops.lp import LPConfig, lp_cluster
from ..utils import timer


# level-handoff projection with the coarse partition donated: when fine
# and coarse levels share a pad bucket (same n_pad), the projected fine
# partition aliases the dead coarse buffer instead of allocating a new
# one.  Only dispatched when shapes actually permit aliasing (the
# caller checks), so XLA never warns about unusable donations; the
# execution ledger's donation audit verifies it was honored.
@partial(jax.jit, donate_argnums=(0,))
def _project_partition_donated(partition, cmap):
    return partition[cmap]


@dataclass
class CoarseningLevel:
    """One hierarchy step.  ``fine_graph``/``coarse`` may be None while
    the level is host-spilled (``spilled`` then holds the coarse host
    CSR + cmap + pad bucket; resilience/memory.py rung 2) — the
    coarsener restores them on demand during uncoarsening."""

    fine_graph: Optional[DeviceGraph]
    coarse: Optional[CoarseGraph]
    fine_n: int
    coarse_n: int
    coarse_m: int
    spilled: Optional[dict] = None


class Coarsener:
    """Cluster coarsener with hierarchy (Coarsener interface,
    kaminpar-shm/coarsening/coarsener.h:20-88)."""

    def __init__(self, ctx: Context, graph: DeviceGraph, n: int):
        self.ctx = ctx
        self.levels: List[CoarseningLevel] = []
        self.current = graph
        self.current_n = n
        # the input level (level 0's fine graph) — uncoarsening falls
        # back to it when the hierarchy below has been host-spilled
        self._input_graph = graph
        # memory governor (resilience/memory.py): the active hierarchy
        # registers as the run's spill target so the barrier pressure
        # hook can shed cold levels; no-op while the governor is dormant
        from ..resilience import memory as memory_mod

        memory_mod.register_spiller(self)
        self.total_node_weight = int(ctx.partition.total_node_weight)
        lp_ctx = ctx.coarsening.clustering.lp
        from ..context import IsolatedNodesStrategy, TwoHopStrategy

        self._lp_cfg = LPConfig(
            num_iterations=lp_ctx.num_iterations,
            participation=lp_ctx.participation,
            allow_tie_moves=lp_ctx.allow_tie_moves,
            use_active_set=lp_ctx.use_active_set,
            two_hop=lp_ctx.two_hop_strategy != TwoHopStrategy.DISABLE,
            cluster_isolated=lp_ctx.isolated_nodes_strategy
            != IsolatedNodesStrategy.KEEP,
            rating=lp_ctx.rating,
            num_slots=lp_ctx.rating_slots,
        )

    def _level_lp_cfg(self, graph: DeviceGraph) -> LPConfig:
        """Per-level rating-engine selection from MEASURED density and
        degree skew (the 1402.3281 adaptivity rule, ops/rating.py).

        Host-side, between launches: n/m are level metadata the driver
        already holds, the max degree is one scalar readback off the
        degrees array the graph already carries.  The chosen engine is
        stamped into the level's LPConfig (trace-time static, so each
        shape bucket compiles the engine it will actually run) and
        exposed as a `rating-engine` telemetry event -> the run
        report's `rating` section."""
        from dataclasses import replace

        from ..ops.rating import select_engine

        # REAL sizes only — never padded shapes: the memory governor's
        # recovery ladder re-buckets the same graph into tighter pads,
        # and a pad-sensitive engine choice would make spilled/reloaded
        # runs diverge from unspilled ones (rung-2 cut-identity test)
        n = max(int(self.current_n), 1)
        m = int(graph.m) or int(graph.src.shape[0])
        avg_degree = m / n
        max_degree = int(jnp.max(graph.degrees))
        degree_skew = max_degree / max(avg_degree, 1e-9)
        engine, reason = select_engine(
            self._lp_cfg.rating, graph.n_pad, n, m,
            num_slots=self._lp_cfg.num_slots,
            avg_degree=avg_degree, degree_skew=degree_skew,
        )
        from .. import telemetry

        telemetry.event(
            "rating-engine",
            level=self.level,
            engine=engine,
            reason=reason,
            avg_degree=round(avg_degree, 2),
            degree_skew=round(degree_skew, 2),
            n=n,
            m=int(graph.m),
        )
        # the RESOLVED engine name is stamped (a handful of distinct
        # cfg values across the hierarchy), never the raw float stats —
        # LPConfig is a static jit argument and a per-level float would
        # force a retrace per level.  The slot budget steps with the
        # measured density (quantized to two values for the same
        # retrace reason): denser levels contest more slots, and a
        # doubled budget costs less than the fallback rounds it avoids
        # (measured on the 600k bench: S=64 at avg degree 18 is both
        # faster and coarsens further than S=32).
        slots = self._lp_cfg.num_slots
        if (
            engine == "scatter"
            and avg_degree > slots / 2
            and 4 * n * slots <= 12 * m  # doubled table stays in budget
        ):
            slots = 2 * slots
        return replace(self._lp_cfg, rating=engine, num_slots=slots)

    @property
    def level(self) -> int:
        return len(self.levels)

    def empty(self) -> bool:
        return not self.levels

    def coarsen(self) -> bool:
        """One coarsening step; returns False when converged (shrink factor
        below convergence_threshold, abstract_cluster_coarsener.cc:118-142)."""
        from ..telemetry import progress as progress_mod

        # label this level's LP progress series (the timer path alone
        # repeats across levels; PASCO-style coarsening-quality curves
        # need the level number)
        with progress_mod.tag(level=self.level):
            return self._coarsen_level()

    def _coarsen_level(self) -> bool:
        c_ctx = self.ctx.coarsening
        max_cluster_weight = max(
            1,
            c_ctx.max_cluster_weight(
                self.current_n, self.total_node_weight, self.ctx.partition
            ),
        )
        seed = jnp.int32(
            (self.ctx.seed * 7919 + self.level * 31337) & 0x7FFFFFFF
        )
        from ..context import CoarseningAlgorithm

        cluster_input = self.current
        if (
            c_ctx.algorithm == CoarseningAlgorithm.SPARSIFICATION_CLUSTERING
            and int(self.current.m) > (1 << 16)
        ):
            # linear-time MGP: cluster on a sparsified copy to bound LP
            # work, but contract the TRUE graph — the hierarchy must hold
            # unmutated graphs (the reference likewise never sparsifies the
            # input level, sparsification_cluster_coarsener.cc)
            from ..ops.sparsify import sparsify_edges

            with timer.scoped_timer("sparsification"):
                cluster_input = sparsify_edges(
                    self.current,
                    jnp.float32(c_ctx.sparsification_keep_ratio),
                    seed ^ jnp.int32(0x51A5),
                )
        mcw = jnp.asarray(
            min(max_cluster_weight, int(jnp.iinfo(WEIGHT_DTYPE).max)),
            dtype=WEIGHT_DTYPE,
        )
        # density-adaptive rating engine for THIS level, from the graph
        # actually being clustered (the sparsified copy when active)
        lp_cfg = self._level_lp_cfg(cluster_input)

        def cluster_once(cap, salt_off):
            if c_ctx.algorithm == CoarseningAlgorithm.OVERLAY_CLUSTERING:
                # OverlayClusterCoarsener (PASCO): intersect several
                # independent clusterings — nodes merge only when every
                # clustering agrees, which guards quality on hard instances
                from ..ops.segments import combine_labels

                labels = None
                for r in range(max(1, c_ctx.clustering.num_overlays)):
                    li = lp_cluster(
                        cluster_input, cap,
                        seed + jnp.int32(7 * r + 1 + salt_off),
                        lp_cfg,
                    )
                    labels = (
                        li if labels is None else combine_labels(labels, li)
                    )
                return labels
            return lp_cluster(
                cluster_input, cap, seed + jnp.int32(salt_off), lp_cfg
            )

        # dispatch is async and block_until_ready is unreliable over the
        # remote backend; a scalar readback inside the scope keeps the
        # LP/contraction attribution honest (otherwise the first host
        # sync in contract_clustering absorbs the whole LP runtime).
        # Only worth a host round-trip when the timer actually records.
        def drain(x):
            if timer.GLOBAL_TIMER.enabled:
                int(jnp.sum(x[:1]))

        with timer.scoped_timer("lp-clustering"):
            labels = cluster_once(mcw, 0)
            drain(labels)
        with timer.scoped_timer("contraction"):
            coarse, c_n, c_m = contract_clustering(self.current, labels)

        # forced-shrink retries (abstract_cluster_coarsener.cc:118-142
        # shrink-factor logic): when clustering stalls but the graph is
        # still far above the contraction limit, relax the cluster weight
        # cap and re-cluster with the SAME configured clusterer — a
        # stalled hierarchy otherwise leaves a huge "coarsest" graph for
        # the sequential initial partitioner
        retries = 0
        while (
            c_n >= (1.0 - c_ctx.convergence_threshold) * self.current_n
            and self.current_n > 4 * c_ctx.contraction_limit
            and retries < 3
        ):
            retries += 1
            mcw = jnp.asarray(
                min(int(mcw) * 2, int(jnp.iinfo(WEIGHT_DTYPE).max)),
                dtype=WEIGHT_DTYPE,
            )
            with timer.scoped_timer("lp-clustering"):
                labels = cluster_once(mcw, retries * 977)
                drain(labels)
            with timer.scoped_timer("contraction"):
                coarse, c_n, c_m = contract_clustering(self.current, labels)

        if (
            c_n >= (1.0 - c_ctx.convergence_threshold) * self.current_n
            and self.current_n > 4 * c_ctx.contraction_limit
        ):
            # last resort before declaring convergence: the hashed-slot
            # engine sees 32 candidate clusters per node where sort2's
            # top-K sees K — on dense near-cap coarse graphs that extra
            # visibility often finds the feasible merges that unstick a
            # limping hierarchy (each extra level costs a full refine
            # pass downstream)
            import dataclasses

            hash_cfg = dataclasses.replace(self._lp_cfg, rating="hash")
            with timer.scoped_timer("lp-clustering"):
                labels = lp_cluster(
                    cluster_input, mcw, seed + jnp.int32(3989), hash_cfg
                )
                drain(labels)
            with timer.scoped_timer("contraction"):
                coarse, c_n, c_m = contract_clustering(self.current, labels)

        if c_n >= (1.0 - c_ctx.convergence_threshold) * self.current_n:
            # converged: drop this level (not enough shrinkage)
            return False
        if (
            c_n >= (1.0 - c_ctx.stall_threshold) * self.current_n
            and self.current_n <= 8 * c_ctx.contraction_limit
        ):
            # limping tail cutoff: near the contraction limit, dense
            # near-cap graphs shrink only ~6-8% per level while every
            # accepted level costs a full refine pass (Jet + LP + a
            # contraction + fresh executables) during uncoarsening —
            # profiled at the 10M bench as the dominant systemic cost.
            # The host initial-partitioning pool handles a 10-16k-node
            # coarsest graph directly, so declare convergence instead of
            # limping to the threshold.
            return False
        # integrity sentinels (resilience/integrity.py): corruption
        # chaos first — `bit-flip:contraction` genuinely mutates a
        # coarse edge weight in flight — then the conservation / range /
        # surjectivity / symmetry checks on the accepted level.  A
        # violation fires BEFORE this level's barrier, so the manifest
        # still points at the last clean one and the retry ladder
        # (integrity.run_with_retry) resumes there.  One separate small
        # jitted reduction, host compares; the LP/contraction jaxprs
        # above are untouched whether integrity is on or off.
        from ..resilience import integrity as integrity_mod

        coarse = integrity_mod.chaos_corrupt_contraction(coarse)
        integrity_mod.check_contraction(
            self.current, coarse.cmap, coarse.graph,
            level=self.level, fine_n=self.current_n, coarse_n=c_n,
        )
        self.levels.append(
            CoarseningLevel(
                fine_graph=self.current,
                coarse=coarse,
                fine_n=self.current_n,
                coarse_n=c_n,
                coarse_m=c_m,
            )
        )
        self.current = coarse.graph
        self.current_n = c_n
        from .. import telemetry

        # per-level resident-buffer accounting (perf.memory.levels):
        # padded shapes and total device-array bytes of the coarse CSR —
        # all host-side array metadata, never a device sync
        g = coarse.graph
        telemetry.event(
            "coarsening-level",
            level=self.level,
            n=int(c_n),
            m=int(c_m),
            retries=retries,
            n_pad=int(g.node_w.shape[0]),
            m_pad=int(g.dst.shape[0]),
            buffer_bytes=int(
                g.row_ptr.nbytes + g.src.nbytes + g.dst.nbytes
                + g.edge_w.nbytes + g.node_w.nbytes
                + coarse.cmap.nbytes
            ),
        )
        # quality observatory (telemetry/quality.py): per-level
        # coarsening-quality metrics — internalized edge weight, cluster
        # sizes vs the cap, weight skew.  A separate small reduction
        # pulled host-side between launches; no-op while disabled, and
        # the LP/contraction jaxprs above are untouched either way.
        from ..telemetry import quality as quality_mod

        quality_mod.note_contraction(
            level=self.level,
            fine_graph=self.levels[-1].fine_graph,
            coarse=coarse,
            fine_n=self.levels[-1].fine_n,
            coarse_n=c_n,
            coarse_m=c_m,
            max_cluster_weight=mcw,
            total_node_weight=self.total_node_weight,
        )
        return True

    def uncoarsen(self, partition: jnp.ndarray) -> Tuple[DeviceGraph, jnp.ndarray]:
        """Pop one level; project the coarse partition up
        (abstract_cluster_coarsener.cc:149-171).  Returns (fine graph,
        fine partition).

        Host-spilled levels are transparently restored: the level below
        (whose coarse graph IS this level's fine graph) is re-uploaded
        into its original pad bucket, and a spilled projection map is
        used straight from the host copy — the projection gather and the
        restored arrays are bitwise-identical to the unspilled run
        (deterministic buckets), so spill/reload is cut-neutral."""
        if len(self.levels) >= 2:
            # the popped level's fine graph lives in the level below
            self._restore_level(len(self.levels) - 2)
        level = self.levels.pop()
        if level.coarse is not None:
            cmap = level.coarse.cmap
        else:
            cmap = jnp.asarray(
                np.asarray(level.spilled["cmap"], dtype=np.int32)
            )
            from ..resilience import memory as memory_mod

            memory_mod.note_reload(int(cmap.nbytes))
        fine = level.fine_graph
        if fine is None:
            fine = (
                self.levels[-1].coarse.graph
                if self.levels else self._input_graph
            )
        # quality observatory: the popped contraction's projection map,
        # host-copied here where it is already in hand (spilled levels
        # are host-side already) — finalize composes these into the
        # coarsening floors.  No-op while disabled.
        from ..telemetry import quality as quality_mod

        quality_mod.note_cmap(
            level=len(self.levels) + 1, cmap=cmap, fine_n=level.fine_n
        )
        if (
            partition.shape == cmap.shape
            and not isinstance(partition, jax.core.Tracer)
        ):
            # same pad bucket: the dead coarse partition's buffer can
            # back the projected fine partition (donation audited)
            from ..telemetry import ledger

            tok = ledger.donation_begin((partition,),
                                        kind="level-handoff")
            fine_part = _project_partition_donated(partition, cmap)
            ledger.donation_end(tok)
        else:
            fine_part = partition[cmap]
        self.current = fine
        self.current_n = level.fine_n
        return fine, fine_part

    # -- host spill / reload (resilience/memory.py rung 2) --------------

    def _level_device_bytes(self, lvl: CoarseningLevel) -> int:
        g = lvl.coarse.graph
        return int(
            g.row_ptr.nbytes + g.src.nbytes + g.dst.nbytes
            + g.edge_w.nbytes + g.node_w.nbytes + lvl.coarse.cmap.nbytes
        )

    def spill_cold_levels(self, keep_last: int = 1) -> int:
        """Serialize every hierarchy level except the newest
        ``keep_last`` as host CSR + cmap and DROP their device arrays
        (the working graph and the checkpoint payload's newest level
        stay resident).  Returns the device bytes freed.  Called by the
        barrier pressure hook (proactively, under budget pressure) and
        unconditionally at rung >= 2."""
        freed = 0
        for i in range(len(self.levels) - max(0, keep_last)):
            lvl = self.levels[i]
            if lvl.coarse is None or lvl.spilled is not None:
                continue
            freed += self._spill_level(i)
        return freed

    def _spill_level(self, i: int) -> int:
        from ..graphs.csr import host_graph_from_device
        from ..resilience import memory as memory_mod

        lvl = self.levels[i]
        g = lvl.coarse.graph
        nbytes = self._level_device_bytes(lvl)
        hg = host_graph_from_device(g)
        lvl.spilled = {
            "xadj": hg.xadj,
            "adjncy": hg.adjncy,
            "node_w": hg.node_weight_array(),
            "edge_w": hg.edge_weight_array(),
            "cmap": np.asarray(lvl.coarse.cmap),
            "n_pad": int(g.n_pad),
            "m_pad": int(g.m_pad),
        }
        # drop the device arrays: this level's coarse graph is also the
        # next level's fine graph (same object) — both refs must go or
        # nothing is freed
        lvl.coarse = None
        if i + 1 < len(self.levels):
            self.levels[i + 1].fine_graph = None
        memory_mod.note_spill(nbytes)
        from .. import telemetry

        telemetry.event(
            "memory-spill", level=i, bytes=nbytes,
            n=lvl.coarse_n, m=lvl.coarse_m,
        )
        return nbytes

    def _restore_level(self, i: int) -> None:
        """Re-upload a spilled level into its ORIGINAL pad bucket (the
        explicit n_pad/m_pad recorded at spill time, so cmaps and
        partitions line up slot-for-slot whatever pad policy is active
        now)."""
        lvl = self.levels[i]
        if lvl.coarse is not None:
            return
        from ..graphs.csr import device_graph_from_host
        from ..graphs.host import HostGraph
        from ..resilience import memory as memory_mod

        sp = lvl.spilled
        edge_w = sp["edge_w"]
        hg = HostGraph(
            xadj=sp["xadj"],
            adjncy=sp["adjncy"],
            node_weights=sp["node_w"],
            edge_weights=edge_w if edge_w.size else None,
        )
        dg = device_graph_from_host(
            hg, n_pad=sp["n_pad"], m_pad=sp["m_pad"]
        )
        lvl.coarse = CoarseGraph(
            graph=dg,
            cmap=jnp.asarray(np.asarray(sp["cmap"], dtype=np.int32)),
        )
        lvl.spilled = None
        if i + 1 < len(self.levels):
            self.levels[i + 1].fine_graph = dg
        nbytes = self._level_device_bytes(lvl)
        memory_mod.note_reload(nbytes)
        from .. import telemetry

        telemetry.event(
            "memory-reload", level=i, bytes=nbytes,
            n=lvl.coarse_n, m=lvl.coarse_m,
        )


# ---------------------------------------------------------------------------
# hierarchy checkpointing (resilience/checkpoint.py): one coarsening level
# serialized as its coarse host CSR + projection map, and the inverse —
# shared by the deep and kway drivers
# ---------------------------------------------------------------------------


def newest_level_snapshot(coarsener: Coarsener) -> dict:
    """Serialize the just-contracted level: the coarse graph's host CSR
    plus the fine->coarse projection map — everything a resume needs to
    rebuild this hierarchy step without re-clustering/re-contracting.
    Pulls the level off device; call only with checkpointing enabled."""
    from ..graphs.csr import host_graph_from_device

    lvl = coarsener.levels[-1]
    hg = host_graph_from_device(lvl.coarse.graph)
    return {
        "xadj": hg.xadj,
        "adjncy": hg.adjncy,
        "node_w": hg.node_weight_array(),
        "edge_w": hg.edge_weight_array(),
        "cmap": np.asarray(lvl.coarse.cmap),
        "dims": np.asarray(
            [lvl.fine_n, lvl.coarse_n, lvl.coarse_m], dtype=np.int64
        ),
        # the pad bucket the saved cmap was sized for: a resume must
        # re-upload into exactly this bucket even when the recovery
        # ladder has switched the ambient pad policy (rung >= 1)
        "pads": np.asarray(
            [lvl.coarse.graph.n_pad, lvl.coarse.graph.m_pad],
            dtype=np.int64,
        ),
    }


def restore_levels(coarsener: Coarsener, dgraph: DeviceGraph, arrays: dict) -> int:
    """Rebuild the coarsener hierarchy from `level-<i>` snapshots:
    re-upload each saved coarse CSR and reattach the projection maps.
    Snapshots record their pad bucket (`pads`), so the rebuilt device
    graphs land in exactly the buckets the saved cmaps/partitions were
    sized for even when the memory governor's ladder has switched the
    ambient pad policy; pre-`pads` snapshots fall back to the
    deterministic default policy (graphs/csr.pad_size) that wrote them.
    Returns the number of levels restored."""
    from ..graphs.csr import device_graph_from_host
    from ..graphs.host import HostGraph
    from ..ops.contraction import CoarseGraph

    level_names = sorted(
        (nm for nm in arrays if nm.startswith("level-")),
        key=lambda s: int(s.split("-", 1)[1]),
    )
    graphs = [dgraph]
    for nm in level_names:
        a = arrays[nm]
        fine_n, coarse_n, coarse_m = (int(x) for x in a["dims"])
        hg = HostGraph(
            xadj=a["xadj"],
            adjncy=a["adjncy"],
            node_weights=a["node_w"],
            edge_weights=a["edge_w"] if a["edge_w"].size else None,
        )
        if "pads" in a:
            n_pad, m_pad = (int(x) for x in a["pads"])
            dg = device_graph_from_host(hg, n_pad=n_pad, m_pad=m_pad)
        else:
            dg = device_graph_from_host(hg)
        coarse = CoarseGraph(
            graph=dg,
            cmap=jnp.asarray(np.asarray(a["cmap"], dtype=np.int32)),
        )
        coarsener.levels.append(
            CoarseningLevel(
                fine_graph=graphs[-1],
                coarse=coarse,
                fine_n=fine_n,
                coarse_n=coarse_n,
                coarse_m=coarse_m,
            )
        )
        graphs.append(dg)
    if coarsener.levels:
        coarsener.current = graphs[-1]
        coarsener.current_n = coarsener.levels[-1].coarse_n
    return len(level_names)
