"""Device multilevel coarsener.

Analog of kaminpar-shm/coarsening/abstract_cluster_coarsener.cc (+
BasicClusterCoarsener): drives lp_cluster -> contract_clustering level by
level, keeps the hierarchy for projection, applies the max-cluster-weight
formula (max_cluster_weights.h) and the shrink/convergence checks
(abstract_cluster_coarsener.cc:98-147).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..context import Context
from ..graphs.csr import DeviceGraph, WEIGHT_DTYPE
from ..ops.contraction import CoarseGraph, contract_clustering
from ..ops.lp import LPConfig, lp_cluster
from ..utils import timer


@dataclass
class CoarseningLevel:
    fine_graph: DeviceGraph
    coarse: CoarseGraph
    fine_n: int
    coarse_n: int
    coarse_m: int


class Coarsener:
    """Cluster coarsener with hierarchy (Coarsener interface,
    kaminpar-shm/coarsening/coarsener.h:20-88)."""

    def __init__(self, ctx: Context, graph: DeviceGraph, n: int):
        self.ctx = ctx
        self.levels: List[CoarseningLevel] = []
        self.current = graph
        self.current_n = n
        self.total_node_weight = int(ctx.partition.total_node_weight)
        lp_ctx = ctx.coarsening.clustering.lp
        from ..context import IsolatedNodesStrategy, TwoHopStrategy

        self._lp_cfg = LPConfig(
            num_iterations=lp_ctx.num_iterations,
            participation=lp_ctx.participation,
            allow_tie_moves=lp_ctx.allow_tie_moves,
            use_active_set=lp_ctx.use_active_set,
            two_hop=lp_ctx.two_hop_strategy != TwoHopStrategy.DISABLE,
            cluster_isolated=lp_ctx.isolated_nodes_strategy
            != IsolatedNodesStrategy.KEEP,
        )

    @property
    def level(self) -> int:
        return len(self.levels)

    def empty(self) -> bool:
        return not self.levels

    def coarsen(self) -> bool:
        """One coarsening step; returns False when converged (shrink factor
        below convergence_threshold, abstract_cluster_coarsener.cc:118-142)."""
        from ..telemetry import progress as progress_mod

        # label this level's LP progress series (the timer path alone
        # repeats across levels; PASCO-style coarsening-quality curves
        # need the level number)
        with progress_mod.tag(level=self.level):
            return self._coarsen_level()

    def _coarsen_level(self) -> bool:
        c_ctx = self.ctx.coarsening
        max_cluster_weight = max(
            1,
            c_ctx.max_cluster_weight(
                self.current_n, self.total_node_weight, self.ctx.partition
            ),
        )
        seed = jnp.int32(
            (self.ctx.seed * 7919 + self.level * 31337) & 0x7FFFFFFF
        )
        from ..context import CoarseningAlgorithm

        cluster_input = self.current
        if (
            c_ctx.algorithm == CoarseningAlgorithm.SPARSIFICATION_CLUSTERING
            and int(self.current.m) > (1 << 16)
        ):
            # linear-time MGP: cluster on a sparsified copy to bound LP
            # work, but contract the TRUE graph — the hierarchy must hold
            # unmutated graphs (the reference likewise never sparsifies the
            # input level, sparsification_cluster_coarsener.cc)
            from ..ops.sparsify import sparsify_edges

            with timer.scoped_timer("sparsification"):
                cluster_input = sparsify_edges(
                    self.current,
                    jnp.float32(c_ctx.sparsification_keep_ratio),
                    seed ^ jnp.int32(0x51A5),
                )
        mcw = jnp.asarray(
            min(max_cluster_weight, int(jnp.iinfo(WEIGHT_DTYPE).max)),
            dtype=WEIGHT_DTYPE,
        )

        def cluster_once(cap, salt_off):
            if c_ctx.algorithm == CoarseningAlgorithm.OVERLAY_CLUSTERING:
                # OverlayClusterCoarsener (PASCO): intersect several
                # independent clusterings — nodes merge only when every
                # clustering agrees, which guards quality on hard instances
                from ..ops.segments import combine_labels

                labels = None
                for r in range(max(1, c_ctx.clustering.num_overlays)):
                    li = lp_cluster(
                        cluster_input, cap,
                        seed + jnp.int32(7 * r + 1 + salt_off),
                        self._lp_cfg,
                    )
                    labels = (
                        li if labels is None else combine_labels(labels, li)
                    )
                return labels
            return lp_cluster(
                cluster_input, cap, seed + jnp.int32(salt_off), self._lp_cfg
            )

        # dispatch is async and block_until_ready is unreliable over the
        # remote backend; a scalar readback inside the scope keeps the
        # LP/contraction attribution honest (otherwise the first host
        # sync in contract_clustering absorbs the whole LP runtime).
        # Only worth a host round-trip when the timer actually records.
        def drain(x):
            if timer.GLOBAL_TIMER.enabled:
                int(jnp.sum(x[:1]))

        with timer.scoped_timer("lp-clustering"):
            labels = cluster_once(mcw, 0)
            drain(labels)
        with timer.scoped_timer("contraction"):
            coarse, c_n, c_m = contract_clustering(self.current, labels)

        # forced-shrink retries (abstract_cluster_coarsener.cc:118-142
        # shrink-factor logic): when clustering stalls but the graph is
        # still far above the contraction limit, relax the cluster weight
        # cap and re-cluster with the SAME configured clusterer — a
        # stalled hierarchy otherwise leaves a huge "coarsest" graph for
        # the sequential initial partitioner
        retries = 0
        while (
            c_n >= (1.0 - c_ctx.convergence_threshold) * self.current_n
            and self.current_n > 4 * c_ctx.contraction_limit
            and retries < 3
        ):
            retries += 1
            mcw = jnp.asarray(
                min(int(mcw) * 2, int(jnp.iinfo(WEIGHT_DTYPE).max)),
                dtype=WEIGHT_DTYPE,
            )
            with timer.scoped_timer("lp-clustering"):
                labels = cluster_once(mcw, retries * 977)
                drain(labels)
            with timer.scoped_timer("contraction"):
                coarse, c_n, c_m = contract_clustering(self.current, labels)

        if (
            c_n >= (1.0 - c_ctx.convergence_threshold) * self.current_n
            and self.current_n > 4 * c_ctx.contraction_limit
        ):
            # last resort before declaring convergence: the hashed-slot
            # engine sees 32 candidate clusters per node where sort2's
            # top-K sees K — on dense near-cap coarse graphs that extra
            # visibility often finds the feasible merges that unstick a
            # limping hierarchy (each extra level costs a full refine
            # pass downstream)
            import dataclasses

            hash_cfg = dataclasses.replace(self._lp_cfg, rating="hash")
            with timer.scoped_timer("lp-clustering"):
                labels = lp_cluster(
                    cluster_input, mcw, seed + jnp.int32(3989), hash_cfg
                )
                drain(labels)
            with timer.scoped_timer("contraction"):
                coarse, c_n, c_m = contract_clustering(self.current, labels)

        if c_n >= (1.0 - c_ctx.convergence_threshold) * self.current_n:
            # converged: drop this level (not enough shrinkage)
            return False
        if (
            c_n >= (1.0 - c_ctx.stall_threshold) * self.current_n
            and self.current_n <= 8 * c_ctx.contraction_limit
        ):
            # limping tail cutoff: near the contraction limit, dense
            # near-cap graphs shrink only ~6-8% per level while every
            # accepted level costs a full refine pass (Jet + LP + a
            # contraction + fresh executables) during uncoarsening —
            # profiled at the 10M bench as the dominant systemic cost.
            # The host initial-partitioning pool handles a 10-16k-node
            # coarsest graph directly, so declare convergence instead of
            # limping to the threshold.
            return False
        self.levels.append(
            CoarseningLevel(
                fine_graph=self.current,
                coarse=coarse,
                fine_n=self.current_n,
                coarse_n=c_n,
                coarse_m=c_m,
            )
        )
        self.current = coarse.graph
        self.current_n = c_n
        from .. import telemetry

        # per-level resident-buffer accounting (perf.memory.levels):
        # padded shapes and total device-array bytes of the coarse CSR —
        # all host-side array metadata, never a device sync
        g = coarse.graph
        telemetry.event(
            "coarsening-level",
            level=self.level,
            n=int(c_n),
            m=int(c_m),
            retries=retries,
            n_pad=int(g.node_w.shape[0]),
            m_pad=int(g.dst.shape[0]),
            buffer_bytes=int(
                g.row_ptr.nbytes + g.src.nbytes + g.dst.nbytes
                + g.edge_w.nbytes + g.node_w.nbytes
                + coarse.cmap.nbytes
            ),
        )
        return True

    def uncoarsen(self, partition: jnp.ndarray) -> Tuple[DeviceGraph, jnp.ndarray]:
        """Pop one level; project the coarse partition up
        (abstract_cluster_coarsener.cc:149-171).  Returns (fine graph,
        fine partition)."""
        level = self.levels.pop()
        fine_part = level.coarse.project_up(partition)
        self.current = level.fine_graph
        self.current_n = level.fine_n
        return level.fine_graph, fine_part


# ---------------------------------------------------------------------------
# hierarchy checkpointing (resilience/checkpoint.py): one coarsening level
# serialized as its coarse host CSR + projection map, and the inverse —
# shared by the deep and kway drivers
# ---------------------------------------------------------------------------


def newest_level_snapshot(coarsener: Coarsener) -> dict:
    """Serialize the just-contracted level: the coarse graph's host CSR
    plus the fine->coarse projection map — everything a resume needs to
    rebuild this hierarchy step without re-clustering/re-contracting.
    Pulls the level off device; call only with checkpointing enabled."""
    from ..graphs.csr import host_graph_from_device

    lvl = coarsener.levels[-1]
    hg = host_graph_from_device(lvl.coarse.graph)
    return {
        "xadj": hg.xadj,
        "adjncy": hg.adjncy,
        "node_w": hg.node_weight_array(),
        "edge_w": hg.edge_weight_array(),
        "cmap": np.asarray(lvl.coarse.cmap),
        "dims": np.asarray(
            [lvl.fine_n, lvl.coarse_n, lvl.coarse_m], dtype=np.int64
        ),
    }


def restore_levels(coarsener: Coarsener, dgraph: DeviceGraph, arrays: dict) -> int:
    """Rebuild the coarsener hierarchy from `level-<i>` snapshots:
    re-upload each saved coarse CSR and reattach the projection maps.
    The pad policy is deterministic (graphs/csr.pad_size), so rebuilt
    device graphs land in the same shape buckets as the originals and
    saved cmaps/partitions line up slot-for-slot.  Returns the number of
    levels restored."""
    from ..graphs.csr import device_graph_from_host
    from ..graphs.host import HostGraph
    from ..ops.contraction import CoarseGraph

    level_names = sorted(
        (nm for nm in arrays if nm.startswith("level-")),
        key=lambda s: int(s.split("-", 1)[1]),
    )
    graphs = [dgraph]
    for nm in level_names:
        a = arrays[nm]
        fine_n, coarse_n, coarse_m = (int(x) for x in a["dims"])
        hg = HostGraph(
            xadj=a["xadj"],
            adjncy=a["adjncy"],
            node_weights=a["node_w"],
            edge_weights=a["edge_w"] if a["edge_w"].size else None,
        )
        dg = device_graph_from_host(hg)
        coarse = CoarseGraph(
            graph=dg,
            cmap=jnp.asarray(np.asarray(a["cmap"], dtype=np.int32)),
        )
        coarsener.levels.append(
            CoarseningLevel(
                fine_graph=graphs[-1],
                coarse=coarse,
                fine_n=fine_n,
                coarse_n=coarse_n,
                coarse_m=coarse_m,
            )
        )
        graphs.append(dg)
    if coarsener.levels:
        coarsener.current = graphs[-1]
        coarsener.current_n = coarsener.levels[-1].coarse_n
    return len(level_names)
