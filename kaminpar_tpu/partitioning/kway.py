"""k-way multilevel partitioner.

Analog of kaminpar-shm/partitioning/kway/kway_multilevel.cc: coarsen on
device until n <= k * contraction_limit (kway_multilevel.cc:144-146), move
the coarsest graph to the host for direct k-way initial partitioning via
recursive bisection, then uncoarsen with device refinement at every level.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
import numpy as np

from ..dtypes import WEIGHT_DTYPE, WMAX
from ..context import Context
from ..graphs.csr import (
    DeviceGraph,
    device_graph_from_host,
    host_graph_from_device,
)
from ..graphs.host import HostGraph
from ..utils import rng as rng_mod
from ..utils import timer
from ..utils.logger import log_progress
from .coarsener import Coarsener
from .refiner import RefinerPipeline
from .rb import recursive_bipartition


class KWayMultilevelPartitioner:
    def __init__(self, ctx: Context):
        self.ctx = ctx

    @staticmethod
    def _ckpt_state_payload(partition, n: int) -> dict:
        """Checkpoint barrier payload: the current partition pulled to
        host (deliberate, checkpoint-only transfer — the barrier defers
        this call, so disabled runs pull nothing)."""
        return {"state": {
            "partition": np.asarray(partition)[:n].astype(np.int32),
        }}

    def partition(self, graph: HostGraph) -> np.ndarray:
        from ..resilience import memory as memory_mod
        from ..telemetry import quality as quality_mod

        # pre-upload budget check (see deep.py): a budget the bucket
        # cannot fit is refused before the upload, not after the OOM
        memory_mod.preflight(
            graph.n, graph.m, self.ctx.partition.k, where="kway"
        )
        # quality observatory: one hierarchy recording scope per run
        # (telemetry/quality.py; no-op while disabled)
        qh = quality_mod.begin("kway")
        try:
            return self._partition_recorded(graph, qh)
        finally:
            quality_mod.end(qh)

    def _partition_recorded(self, graph: HostGraph, qh) -> np.ndarray:
        ctx = self.ctx
        k = ctx.partition.k
        rng = rng_mod.host_rng(ctx.seed)
        from ..resilience import checkpoint as ckpt
        from ..telemetry import quality as quality_mod
        with timer.scoped_timer("device-upload"):
            dgraph = device_graph_from_host(graph)

        max_bw = jnp.asarray(
            np.minimum(ctx.partition.max_block_weights, WMAX),
            dtype=WEIGHT_DTYPE,
        )
        min_bw = (
            jnp.asarray(
                np.minimum(ctx.partition.min_block_weights, WMAX),
                dtype=WEIGHT_DTYPE,
            )
            if ctx.partition.min_block_weights is not None
            else None
        )

        # --- coarsening (kway_multilevel.cc:91-142) ---
        from . import debug

        coarsener = Coarsener(ctx, dgraph, graph.n)
        threshold = max(k * ctx.coarsening.contraction_limit, 1)

        # checkpoint resume (resilience/checkpoint.py): rebuild the
        # recorded hierarchy/partition and skip completed stages
        from .coarsener import newest_level_snapshot, restore_levels

        resume = ckpt.take_resume("kway")
        stage = None
        partition = None
        num_levels = None
        if resume is not None:
            stage = resume["stage"]
            meta = resume.get("meta", {})
            restored = restore_levels(coarsener, dgraph, resume["arrays"])
            num_levels = meta.get("num_levels")
            st = resume["arrays"].get("state")
            if st is not None:
                padded = np.zeros(coarsener.current.n_pad, dtype=np.int32)
                part_host = np.asarray(st["partition"], dtype=np.int32)
                padded[: part_host.shape[0]] = part_host
                partition = jnp.asarray(padded)
            from .. import telemetry

            telemetry.event(
                "resume", scheme="kway", stage=stage,
                level=resume.get("level"), levels_restored=restored,
            )

        if stage is None or stage == "coarsen":
            with timer.scoped_timer("coarsening"):
                while coarsener.current_n > threshold:
                    if not coarsener.coarsen():
                        break
                    log_progress(
                        f"coarsening level {coarsener.level}: "
                        f"n={coarsener.current_n}"
                    )
                    if ctx.debug.dump_graph_hierarchy:
                        debug.dump_graph_hierarchy(
                            ctx,
                            host_graph_from_device(coarsener.current),
                            coarsener.level,
                        )
                    if not ckpt.barrier(
                        "coarsen", level=coarsener.level, scheme="kway",
                        payload=lambda: {
                            f"level-{coarsener.level - 1}":
                                newest_level_snapshot(coarsener)
                        },
                        keep=[
                            f"level-{j}" for j in range(coarsener.level - 1)
                        ],
                    ):
                        break  # deadline wind-down

        if stage in (None, "coarsen"):
            # --- initial partitioning on host (rb to k) ---
            with timer.scoped_timer("initial-partitioning"):
                from .. import telemetry

                telemetry.event(
                    "initial-partitioning",
                    n=int(coarsener.current_n),
                    k=int(k),
                    levels=int(coarsener.level),
                )
                coarsest_host = host_graph_from_device(coarsener.current)
                debug.dump_coarsest_graph(ctx, coarsest_host)
                init_part = recursive_bipartition(coarsest_host, k, ctx, rng)
                debug.dump_coarsest_partition(ctx, init_part)
                part_padded = np.zeros(coarsener.current.n_pad, dtype=np.int32)
                part_padded[: coarsest_host.n] = init_part
                partition = jnp.asarray(part_padded)
                # quality: the coarsest level's entry cut
                quality_mod.note_projected(
                    coarsener.level, coarsener.current, partition, k=k
                )
            num_levels = coarsener.level + 1
            ckpt.barrier(
                "initial", level=coarsener.level, scheme="kway",
                payload=lambda: self._ckpt_state_payload(
                    partition, coarsener.current_n
                ),
                keep=[f"level-{j}" for j in range(coarsener.level)],
                meta={"num_levels": num_levels},
            )

        # --- uncoarsening + refinement (kway_multilevel.cc:70-89) ---
        refiner = RefinerPipeline(ctx, k)
        if num_levels is None:
            num_levels = coarsener.level + 1
        # debug hierarchy dumps are STAGED: collected by reference during
        # the span, pulled to host only after it closes, so the
        # uncoarsening span never carries the readback (tpulint R1)
        pending_dumps = []
        with timer.scoped_timer("uncoarsening"):
            level = coarsener.level
            if stage != "uncoarsen":
                partition = refiner.refine(
                    coarsener.current,
                    partition,
                    max_bw,
                    min_bw,
                    seed=ctx.seed,
                    level=level,
                    num_levels=num_levels,
                )
                quality_mod.note_refined(
                    level, coarsener.current, partition, k=k
                )
                part_now = partition
                ckpt.barrier(
                    "uncoarsen", level=level, scheme="kway",
                    payload=lambda: self._ckpt_state_payload(
                        part_now, coarsener.current_n
                    ),
                    keep=[f"level-{j}" for j in range(level)],
                    meta={"num_levels": num_levels},
                )
            while not coarsener.empty():
                fine_graph, partition = coarsener.uncoarsen(partition)
                level -= 1
                quality_mod.note_projected(level, fine_graph, partition, k=k)
                partition = refiner.refine(
                    fine_graph,
                    partition,
                    max_bw,
                    min_bw,
                    seed=ctx.seed,
                    level=level,
                    num_levels=num_levels,
                )
                quality_mod.note_refined(level, fine_graph, partition, k=k)
                if ctx.debug.dump_partition_hierarchy:
                    pending_dumps.append(
                        (level, partition, coarsener.current_n)
                    )
                part_now = partition
                ckpt.barrier(
                    "uncoarsen", level=level, scheme="kway",
                    payload=lambda: self._ckpt_state_payload(
                        part_now, coarsener.current_n
                    ),
                    keep=[f"level-{j}" for j in range(level)],
                    meta={"num_levels": num_levels},
                )
        for dump_level, dump_part, dump_n in pending_dumps:
            debug.dump_partition_hierarchy(
                ctx, np.asarray(dump_part)[:dump_n], dump_level
            )

        # strict balance backstop on the finest level
        partition = refiner.enforce_balance_host(
            dgraph, partition, np.asarray(ctx.partition.max_block_weights),
            where="kway",
        )
        # quality: coarsening floors + per-level attribution from the
        # final partition (telemetry/quality.py)
        quality_mod.finalize_device(qh, dgraph, partition, graph.n)
        return np.asarray(partition)[: graph.n]
