"""Recursive bipartitioning on the host.

Analog of kaminpar-shm/partitioning/rb/rb_multilevel.cc (as a full scheme)
and of the per-block bipartition splitting used by deep multilevel's
extend_partition (helper.cc:143 extend_partition_recursive).

`recursive_bipartition` splits a graph into k blocks by recursively calling
the sequential multilevel bipartitioner, reproducing the reference's
max-block-weight derivation: the two sides of each bisection get the sums of
their final sub-blocks' unrelaxed max weights, optionally tightened by the
adaptive-epsilon rule (helper.cc:104-147, 'adapted epsilon' strategy of
KaHyPar).
"""

from __future__ import annotations

import math as pymath
from typing import Optional

import numpy as np

from ..context import Context
from ..graphs.host import HostGraph, extract_block_subgraphs
from ..initial import InitialMultilevelBipartitioner
from ..utils import rng as rng_mod


def split_k(k: int) -> tuple:
    """split_integral for block counts: ceil/floor halves."""
    k0 = (k + 1) // 2
    return k0, k - k0


def bipartition_max_block_weights(
    ctx: Context,
    first_sub_block: int,
    num_sub_blocks: int,
    graph_total_node_weight: int,
) -> np.ndarray:
    """Max weights for one 2-way split covering final blocks
    [first_sub_block, first_sub_block + num_sub_blocks)
    (helper.cc:104-147)."""
    p = ctx.partition
    k0, k1 = split_k(num_sub_blocks)
    w0 = p.total_max_block_weights(first_sub_block, first_sub_block + k0)
    w1 = p.total_max_block_weights(
        first_sub_block + k0, first_sub_block + num_sub_blocks
    )
    max_weights = np.array([w0, w1], dtype=np.int64)

    if p.uniform_block_weights and ctx.initial_partitioning.use_adaptive_epsilon:
        base = (
            (1.0 + p.inferred_epsilon())
            * num_sub_blocks
            * p.total_node_weight
            / p.k
            / max(graph_total_node_weight, 1)
        )
        exponent = 1.0 / max(pymath.ceil(pymath.log2(max(num_sub_blocks, 2))), 1)
        adapted_eps = max(base**exponent - 1.0, 0.0001)
        total = int(max_weights.sum())
        ratios = max_weights / max(total, 1)
        perfect = graph_total_node_weight * ratios
        max_weights = np.ceil((1.0 + adapted_eps) * perfect).astype(np.int64)
    return max_weights


def recursive_bipartition(
    graph: HostGraph,
    k: int,
    ctx: Context,
    rng: Optional[np.random.Generator] = None,
    first_sub_block: int = 0,
) -> np.ndarray:
    """Partition `graph` into its final blocks [first_sub_block,
    first_sub_block + k) by recursive bisection; returns block ids relative
    to first_sub_block = 0 .. k-1."""
    if rng is None:
        rng = rng_mod.host_rng(ctx.seed)
    part = np.zeros(graph.n, dtype=np.int32)
    if k <= 1 or graph.n == 0:
        return part

    from .. import telemetry

    telemetry.event(
        "rb-bisection", n=int(graph.n), k=int(k),
        first_sub_block=int(first_sub_block),
    )
    max_weights = bipartition_max_block_weights(
        ctx, first_sub_block, k, graph.total_node_weight
    )
    bipart = InitialMultilevelBipartitioner(ctx.initial_partitioning).bipartition(
        graph, max_weights, rng
    )
    k0, k1 = split_k(k)
    if k0 == 1 and k1 == 1:
        return bipart.astype(np.int32)

    ext = extract_block_subgraphs(graph, bipart.astype(np.int64), 2)
    sub0 = recursive_bipartition(
        ext.subgraphs[0], k0, ctx, rng, first_sub_block
    )
    sub1 = recursive_bipartition(
        ext.subgraphs[1], k1, ctx, rng, first_sub_block + k0
    )
    in0 = bipart == 0
    part[in0] = sub0[ext.node_mapping[in0]]
    part[~in0] = k0 + sub1[ext.node_mapping[~in0]]
    return part
