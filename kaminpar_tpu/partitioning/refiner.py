"""Refinement pipeline (analog of kaminpar-shm/refinement/multi_refiner.cc
+ factories.cc:96-145 create_refiner).

Maps the ordered RefinementAlgorithm list from the context onto the device
kernels: LP refinement (ops/lp.lp_refine), overload/underload balancing
(ops/balancer), Jet (ops/jet).  The host FM refiner plugs in here as well.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..context import Context, RefinementAlgorithm
from ..graphs.csr import DeviceGraph, host_graph_from_device
from ..ops import balancer as balancer_ops
from ..ops import metrics
from ..ops.lp import LPConfig, lp_refine
from ..utils import timer
from ..utils.logger import log_debug, log_warning


class RefinerPipeline:
    """Runs the context's refiner list in order (MultiRefiner analog).

    `light=True` marks refinement of an intermediate k-doubling
    extension (another doubling immediately follows): Jet runs a single
    round there — the partition gets its full-strength refine at the
    final extension of the level."""

    def __init__(self, ctx: Context, k: int, light: bool = False):
        self.ctx = ctx
        self.k = k
        self.light = light
        self._lp_cfg = LPConfig(
            num_iterations=ctx.refinement.lp.num_iterations,
            participation=ctx.refinement.lp.participation,
            allow_tie_moves=False,
            use_active_set=True,
            refinement=True,
        )

    def refine(
        self,
        graph: DeviceGraph,
        partition: jax.Array,
        max_block_weights: jax.Array,
        min_block_weights: Optional[jax.Array],
        seed: int,
        level: int = 0,
        num_levels: int = 1,
    ) -> jax.Array:
        from ..telemetry import progress as progress_mod
        from ..ops.segments import pad_k_bucket
        from ..resilience import maybe_inject

        # `device-oom` chaos injection at refinement entry: OUTSIDE the
        # per-step `refiner` rollback wrappers below, so the failure
        # reaches the facade's memory-governor recovery ladder instead
        # of a step rollback
        maybe_inject("device-oom")
        k, max_block_weights, min_block_weights = pad_k_bucket(
            self.k, max_block_weights, min_block_weights
        )
        # label every refiner's progress series with the uncoarsening
        # level — the timer path repeats per level, the tag does not.
        # num_levels rides along so the quality observatory's verdicts
        # (telemetry/quality.py) can tell a coarse-level stall from a
        # fine-level one, and the active hierarchy id keeps a nested IP
        # run's series (same stream, same level numbering) out of the
        # outer hierarchy's verdict join.
        from ..telemetry import quality as quality_mod

        from ..resilience import integrity as integrity_mod

        with progress_mod.tag(
            level=level, num_levels=num_levels,
            quality_hierarchy=quality_mod.current_id(),
        ):
            # refinement sentinels (resilience/integrity.py): probe
            # (cut, feasibility, label range) before and after the
            # accepted pass — a feasible->feasible pass that RAISED the
            # cut, or a label outside [0, k), is silent corruption, not
            # a degradation.  `bit-flip:partition` chaos mutates the
            # refined vector in flight so the detector is exercised
            # end-to-end.  Separate small jitted reductions; the
            # LP/Jet/balancer jaxprs are untouched either way.
            before = integrity_mod.refine_probe(
                graph, partition, max_block_weights, min_block_weights
            )
            refined = self._refine_tagged(
                graph, partition, k, max_block_weights, min_block_weights,
                seed, level, num_levels,
            )
            refined = integrity_mod.chaos_corrupt_partition(refined)
            after = integrity_mod.refine_probe(
                graph, refined, max_block_weights, min_block_weights
            )
            integrity_mod.check_refinement(
                before, after, k=int(k), level=level
            )
            if after is not None:
                integrity_mod.audit_refine_cut(
                    graph, refined, after[0], level=level
                )
            return refined

    def _refine_tagged(
        self, graph, partition, k, max_block_weights, min_block_weights,
        seed, level, num_levels,
    ):
        from ..resilience import deadline as deadline_mod
        from ..resilience import with_fallback
        from ..utils import statistics

        for i, algorithm in enumerate(self.ctx.refinement.algorithms):
            # anytime wind-down (resilience/deadline.py): once the budget
            # expires or a preemption signal arrived, stop STARTING
            # refiner steps — the drivers' enforce_balance_host and the
            # output gate keep the balance guarantee on the best
            # partition reached so far
            if deadline_mod.should_stop():
                # the quality observatory joins this into the level's
                # refinement-efficacy verdict: a skipped refiner is
                # budget-capped by definition, not stalled
                from .. import telemetry

                from ..telemetry import quality as quality_mod

                telemetry.event(
                    "refine-skipped",
                    level=level,
                    algorithm=algorithm.value,
                    reason="deadline",
                    quality_hierarchy=quality_mod.current_id(),
                )
                log_debug(
                    f"deadline: skipping {algorithm.value} at level "
                    f"{level} (wind-down)"
                )
                break
            salt = jnp.int32((seed * 2654435761 + i * 40503 + level) & 0x7FFFFFFF)
            if algorithm == RefinementAlgorithm.NOOP:
                continue
            step = self._make_step(
                algorithm, graph, k, max_block_weights, min_block_weights,
                salt, seed + i, level, num_levels,
            )
            if step is None:
                continue
            # Jet-style recoverability (the Gilbert et al. / Mt-KaHyPar
            # discipline): a refiner step that fails — device OOM, a
            # refusal, an injected chaos fault — is rolled back to the
            # best-known partition (its input) instead of aborting the
            # run; the balancer step instead degrades to the exact host
            # balancer so the balance guarantee is not lost with it.
            prev = partition
            if algorithm == RefinementAlgorithm.OVERLOAD_BALANCER:
                partition = with_fallback(
                    lambda s=step: s(prev),
                    lambda exc: self._host_balance(
                        graph, prev, np.asarray(max_block_weights)
                    ),
                    site="device-balancer",
                    where=f"level{level}",
                )
            else:
                partition = with_fallback(
                    lambda s=step: s(prev),
                    lambda exc: prev,
                    site="refiner",
                    where=f"{algorithm.value}@level{level}",
                )
            if statistics.enabled():
                statistics.track(
                    f"cut_after_{algorithm.value}",
                    int(metrics.edge_cut(graph, partition)),
                )
                statistics.count(f"runs_{algorithm.value}")
        return partition

    def _make_step(
        self, algorithm, graph, k, max_block_weights, min_block_weights,
        salt, seed, level, num_levels,
    ):
        """One refinement algorithm as a partition -> partition closure
        (the unit the degradation contract wraps); None = skipped."""
        if algorithm == RefinementAlgorithm.LABEL_PROPAGATION:
            def step(partition):
                with timer.scoped_timer("lp-refinement"):
                    return lp_refine(
                        graph, partition, k, max_block_weights, salt,
                        self._lp_cfg,
                    )
        elif algorithm == RefinementAlgorithm.OVERLOAD_BALANCER:
            def step(partition):
                with timer.scoped_timer("overload-balancer"):
                    return balancer_ops.overload_balance(
                        graph,
                        partition,
                        k,
                        max_block_weights,
                        salt,
                        max_rounds=self.ctx.refinement.balancer.max_rounds,
                    )
        elif algorithm == RefinementAlgorithm.UNDERLOAD_BALANCER:
            if min_block_weights is None:
                return None

            def step(partition):
                with timer.scoped_timer("underload-balancer"):
                    return balancer_ops.underload_balance(
                        graph,
                        partition,
                        k,
                        max_block_weights,
                        min_block_weights,
                        salt,
                        max_rounds=self.ctx.refinement.balancer.max_rounds,
                    )
        elif algorithm == RefinementAlgorithm.JET:
            from ..ops.jet import jet_refine

            jet_ctx = self.ctx.refinement.jet
            if self.light:
                jet_ctx = dataclasses.replace(
                    jet_ctx,
                    num_rounds_on_fine_level=1,
                    num_rounds_on_coarse_level=1,
                )

            def step(partition):
                with timer.scoped_timer("jet"):
                    return jet_refine(
                        graph,
                        partition,
                        k,
                        max_block_weights,
                        salt,
                        jet_ctx,
                        level=level,
                        num_levels=num_levels,
                    )
        elif algorithm == RefinementAlgorithm.MTKAHYPAR:
            from ..refinement.mtkahypar import mtkahypar_refine_host

            def step(partition):
                # the host pulls happen BEFORE the span opens: the
                # mtkahypar span times the external refiner, not the
                # device->host transfer (tpulint R1)
                host = host_graph_from_device(graph)
                part_h = np.asarray(partition)[: host.n]
                caps_h = np.asarray(max_block_weights)[: self.k]
                with timer.scoped_timer("mtkahypar"):
                    # host refiners see the real k, not the padded bucket
                    refined = mtkahypar_refine_host(
                        host,
                        part_h,
                        self.k,
                        max_block_weights=caps_h,
                        epsilon=self.ctx.partition.epsilon,
                        seed=seed,
                        threads=self.ctx.parallel.num_workers,
                    )
                    full = np.zeros(graph.n_pad, dtype=np.int32)
                    full[: host.n] = refined
                    return jnp.asarray(full)
        elif algorithm == RefinementAlgorithm.GREEDY_FM:
            # FM earns its host round-trip where moves are worth the
            # most polish: the finest levels (coarse-level structure
            # is Jet's job, and a full FM pass there re-pays ~0.1%
            # cut for full pass cost).  Light intermediate extensions
            # skip it entirely like they skip full Jet.
            if self.light or level > self.ctx.refinement.fm.max_level:
                return None
            from ..refinement.fm import fm_refine_host

            def step(partition):
                with timer.scoped_timer("kway-fm"):
                    return fm_refine_host(
                        graph,
                        partition,
                        self.k,
                        max_block_weights[: self.k],
                        self.ctx.refinement.fm,
                        seed=seed,
                        # reference-style worker pool (fm_refiner.cc:48);
                        # 1 on this dev box (one logical CPU) keeps runs
                        # bitwise-deterministic
                        threads=self.ctx.parallel.num_workers,
                    )
        else:
            log_warning(f"unknown refinement algorithm: {algorithm}")
            return None
        return step

    def _host_balance(
        self,
        graph: DeviceGraph,
        partition: jax.Array,
        max_block_weights: np.ndarray,
    ) -> jax.Array:
        """The exact host balancer as a device-partition transform (the
        device-balancer site's fallback and enforce_balance_host's
        engine)."""
        host = host_graph_from_device(graph)
        n = host.n
        part_h = np.asarray(partition)[:n].copy()
        balanced = balancer_ops.host_balance(
            host.node_weight_array(),
            (host.xadj, host.adjncy, host.edge_weight_array()),
            part_h,
            np.asarray(max_block_weights),
        )
        full = np.zeros(graph.n_pad, dtype=np.int32)
        full[:n] = balanced
        return jnp.asarray(full)

    def enforce_balance_host(
        self,
        graph: DeviceGraph,
        partition: jax.Array,
        max_block_weights: np.ndarray,
        where: str = "",
    ) -> jax.Array:
        """Exact host fallback for the strict balance guarantee
        (README.MD:18) when device balancing rounds stall.  `where`
        labels the calling driver phase in the telemetry event, so a
        degraded balancer in `deep` uncoarsening reads differently from
        one in a `vcycle` restart."""
        over = int(
            metrics.total_overload(
                graph, partition, jnp.asarray(max_block_weights)
            )
        )
        if over == 0:
            return partition
        from .. import telemetry

        # the device balancers stalled with residual overload — a silent
        # quality/perf decision the run report must show
        telemetry.event(
            "balancer-host-fallback",
            residual_overload=over,
            where=where or None,
        )
        log_debug(
            f"host balance fallback{' (' + where + ')' if where else ''}, "
            f"residual overload {over}"
        )
        return self._host_balance(graph, partition, max_block_weights)
