"""Deep multilevel partitioner — the flagship scheme (ESA'21).

Analog of kaminpar-shm/partitioning/deep/deep_multilevel.cc: coarsen on
device until n <= 2 * contraction_limit (the sequential initial-partitioning
threshold, deep_multilevel.cc:170-183 — the host pool bipartitioner plays
the role of the reference's sequential mode), bipartition the coarsest graph
(initial_partition:185), then uncoarsen while *doubling k*: after each
projection, if the graph is large enough for more blocks
(compute_k_for_n, partition_utils.cc:94-101), extend the partition by
bipartitioning each block's induced subgraph (extend_partition,
helper.cc:220-349), then refine at the current k.

Block bookkeeping: each current block b spans the final blocks
[first(b), first(b)+count(b)); extension splits a block into ceil/floor
halves (split_k = math::split_integral), preserving block order, so when
current_k reaches the input k the block ids coincide with final ids.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Tuple

import jax.numpy as jnp
import numpy as np

from ..context import Context
from ..graphs.csr import (
    DeviceGraph,
    WEIGHT_DTYPE,
    device_graph_from_host,
    host_graph_from_device,
)
from ..graphs.host import HostGraph, extract_block_subgraphs
from ..initial import InitialMultilevelBipartitioner
from ..utils import rng as rng_mod
from ..utils import timer
from ..utils.logger import log_progress
from .coarsener import Coarsener
from .refiner import RefinerPipeline
from ..dtypes import WMAX
from .rb import bipartition_max_block_weights, split_k


@dataclass
class _BlockSpan:
    first: int  # first final block
    count: int  # number of final blocks


# Below this many edge slots the old host extraction (small readback +
# numpy) wins over minting device extraction programs; above it the
# device path avoids a full-graph readback per k-doubling
# (subgraph_extractor.h:36-177 analog, ops/subgraphs.py).
DEVICE_EXTEND_MIN_EDGE_SLOTS = 1 << 22


def compute_k_for_n(n: int, ctx: Context) -> int:
    """partition_utils.cc:94-101."""
    C = ctx.coarsening.contraction_limit
    if n < 2 * C:
        return 2
    k_prime = 1 << max(1, (int(np.ceil(np.log2(max(n / C, 2.0))))))
    return int(np.clip(k_prime, 2, ctx.partition.k))


class DeepMultilevelPartitioner:
    def __init__(self, ctx: Context):
        self.ctx = ctx
        self._spans: List[_BlockSpan] = []

    def partition(self, graph: HostGraph) -> np.ndarray:
        from ..resilience import memory as memory_mod
        from ..telemetry import quality as quality_mod

        # pre-upload budget check: refuse the allocation BEFORE bytes
        # land on the device; the facade's recovery ladder catches the
        # structured DeviceOOM and retries at the next rung
        memory_mod.preflight(
            graph.n, graph.m, self.ctx.partition.k, where="deep"
        )
        # quality observatory (telemetry/quality.py): one hierarchy
        # recording scope per driver run — nesting-safe, so a nested IP
        # run inside the dist driver records its own tiny hierarchy
        # without corrupting the outer one; no-op while disabled
        qh = quality_mod.begin("deep")
        try:
            return self._partition_recorded(graph, qh)
        finally:
            quality_mod.end(qh)

    def _partition_recorded(self, graph: HostGraph, qh) -> np.ndarray:
        ctx = self.ctx
        input_k = ctx.partition.k
        rng = rng_mod.host_rng(ctx.seed ^ 0xDEE9)

        from . import debug
        from ..resilience import checkpoint as ckpt
        from ..telemetry import quality as quality_mod
        with timer.scoped_timer("device-upload"):
            from ..graphs.compressed import CompressedHostGraph

            # streamed inputs keep the host footprint at compressed +
            # O(n); the extend path must then avoid full-graph readbacks
            # (see _extend_partition)
            self._streamed_input = isinstance(graph, CompressedHostGraph)
            if isinstance(graph, CompressedHostGraph):
                # TeraPart compute parity: stream the decode chunk-by-
                # chunk to the device — the flat CSR never exists on the
                # host (graphs/csr.device_graph_from_compressed)
                from ..graphs.csr import device_graph_from_compressed

                dgraph = device_graph_from_compressed(graph)
            else:
                dgraph = device_graph_from_host(graph)

        # --- coarsen (deep_multilevel.cc:69-183) ---
        coarsener = Coarsener(ctx, dgraph, graph.n)
        threshold = max(2 * ctx.coarsening.contraction_limit, 2)
        from ..utils.heap_profiler import sample_device_memory

        # --- checkpoint resume: rebuild the recorded hierarchy/state and
        # re-enter at the recorded stage (no completed level re-runs) ---
        resume = ckpt.take_resume("deep")
        stage = None
        partition = None
        spans: List[_BlockSpan] = []
        current_k = 0
        num_levels = None
        if resume is not None:
            stage, partition, spans, current_k, num_levels, rng = (
                self._restore_from_checkpoint(resume, coarsener, dgraph, rng)
            )

        if stage is None or stage == "coarsen":
            with timer.scoped_timer("coarsening"):
                while coarsener.current_n > threshold:
                    if not coarsener.coarsen():
                        break
                    sample_device_memory()  # per-level live-HBM peak
                    log_progress(
                        f"deep coarsening level {coarsener.level}: "
                        f"n={coarsener.current_n}"
                    )
                    if ctx.debug.dump_graph_hierarchy:
                        debug.dump_graph_hierarchy(
                            ctx,
                            host_graph_from_device(coarsener.current),
                            coarsener.level,
                        )
                    if not ckpt.barrier(
                        "coarsen", level=coarsener.level, scheme="deep",
                        payload=lambda: self._ckpt_level_payload(coarsener),
                        keep=[
                            f"level-{j}" for j in range(coarsener.level - 1)
                        ],
                        meta=self._ckpt_meta(current_k, num_levels, rng),
                    ):
                        # deadline wind-down: stop deepening the
                        # hierarchy; IP + projection below stay mandatory
                        break

        if stage in (None, "coarsen"):
            # --- initial bipartition of the coarsest graph (:185) ---
            with timer.scoped_timer("initial-partitioning"):
                coarsest_host = host_graph_from_device(coarsener.current)
                debug.dump_coarsest_graph(ctx, coarsest_host)
                k0, k1 = split_k(input_k)
                spans = (
                    [_BlockSpan(0, k0), _BlockSpan(k0, k1)]
                    if input_k > 1
                    else [_BlockSpan(0, 1)]
                )
                if input_k == 1:
                    part_host = np.zeros(coarsest_host.n, dtype=np.int32)
                else:
                    max_w = bipartition_max_block_weights(
                        ctx, 0, input_k, coarsest_host.total_node_weight
                    )
                    part_host = (
                        InitialMultilevelBipartitioner(
                            ctx.initial_partitioning
                        )
                        .bipartition(coarsest_host, max_w, rng)
                        .astype(np.int32)
                    )
                current_k = len(spans)
                self._spans = spans
                debug.dump_coarsest_partition(ctx, part_host)
                padded = np.zeros(coarsener.current.n_pad, dtype=np.int32)
                padded[: coarsest_host.n] = part_host
                partition = jnp.asarray(padded)
                # quality: the coarsest level's entry cut (the cut the
                # initial partitioner handed uncoarsening)
                quality_mod.note_projected(
                    coarsener.level, coarsener.current, partition,
                    k=current_k,
                )
            num_levels = coarsener.level + 1
            ckpt.barrier(
                "initial", level=coarsener.level, scheme="deep",
                payload=lambda: self._ckpt_state_payload(
                    partition, coarsener.current_n, spans
                ),
                keep=[f"level-{j}" for j in range(coarsener.level)],
                meta=self._ckpt_meta(current_k, num_levels, rng),
            )

        # --- uncoarsen: refine / extend / repeat (:275-365) ---
        if num_levels is None:
            num_levels = coarsener.level + 1
        # debug hierarchy dumps are STAGED: device partitions are
        # collected by reference during the span and pulled to host only
        # after it closes, so the uncoarsening span never carries the
        # readback (tpulint R1).  Debug-only path: the held references
        # keep each level's partition alive until the dump.
        pending_dumps: List[Tuple[int, object, int]] = []
        with timer.scoped_timer("uncoarsening"):
            level = coarsener.level
            if stage != "uncoarsen":
                partition, spans, current_k = self._extend_and_refine(
                    coarsener.current,
                    coarsener.current_n,
                    partition,
                    spans,
                    current_k,
                    rng,
                    level,
                    num_levels,
                )
                quality_mod.note_refined(
                    level, coarsener.current, partition, k=current_k,
                    spans=spans, input_k=input_k,
                )
                ckpt.barrier(
                    "uncoarsen", level=level, scheme="deep",
                    payload=lambda: self._ckpt_state_payload(
                        partition, coarsener.current_n, spans
                    ),
                    keep=[f"level-{j}" for j in range(level)],
                    meta=self._ckpt_meta(current_k, num_levels, rng),
                )
            while not coarsener.empty():
                fine_graph, partition = coarsener.uncoarsen(partition)
                sample_device_memory()  # per-level live-HBM peak
                level -= 1
                quality_mod.note_projected(
                    level, fine_graph, partition, k=current_k
                )
                partition, spans, current_k = self._extend_and_refine(
                    fine_graph,
                    coarsener.current_n,
                    partition,
                    spans,
                    current_k,
                    rng,
                    level,
                    num_levels,
                )
                quality_mod.note_refined(
                    level, fine_graph, partition, k=current_k,
                    spans=spans, input_k=input_k,
                )
                if ctx.debug.dump_partition_hierarchy:
                    pending_dumps.append(
                        (level, partition, coarsener.current_n)
                    )
                part_now = partition
                spans_now = spans
                ckpt.barrier(
                    "uncoarsen", level=level, scheme="deep",
                    payload=lambda: self._ckpt_state_payload(
                        part_now, coarsener.current_n, spans_now
                    ),
                    keep=[f"level-{j}" for j in range(level)],
                    meta=self._ckpt_meta(current_k, num_levels, rng),
                )
        for dump_level, dump_part, dump_n in pending_dumps:
            debug.dump_partition_hierarchy(
                ctx, np.asarray(dump_part)[:dump_n], dump_level
            )

        # final extensions to input_k if not there yet
        while current_k < input_k:
            partition, spans, current_k = self._extend_partition(
                coarsener.current, partition, spans, input_k, rng
            )
            partition = self._refine(
                coarsener.current, partition, current_k, 0, num_levels
            )

        refiner = RefinerPipeline(self.ctx, current_k)
        partition = refiner.enforce_balance_host(
            dgraph, partition,
            np.asarray(self.ctx.partition.max_block_weights), where="deep",
        )
        # quality: push the FINAL partition back up through the recorded
        # cluster maps — the coarsening floors + per-level attribution
        quality_mod.finalize_device(qh, dgraph, partition, graph.n)
        return np.asarray(partition)[: graph.n]

    # -- checkpoint payloads / restore (resilience/checkpoint.py) -------

    def _ckpt_level_payload(self, coarsener: Coarsener) -> dict:
        """The just-contracted level as a named snapshot (the barrier
        defers this payload, so it costs nothing with checkpointing
        disabled)."""
        from .coarsener import newest_level_snapshot

        return {f"level-{coarsener.level - 1}": newest_level_snapshot(coarsener)}

    def _ckpt_state_payload(self, partition, n: int, spans) -> dict:
        return {
            "state": {
                "partition": np.asarray(partition)[:n].astype(np.int32),
                "spans": np.asarray(
                    [[s.first, s.count] for s in spans], dtype=np.int64
                ),
            }
        }

    def _ckpt_meta(self, current_k, num_levels, rng) -> dict:
        return {
            "current_k": int(current_k),
            "num_levels": None if num_levels is None else int(num_levels),
            "rng_state": rng.bit_generator.state,
        }

    def _restore_from_checkpoint(self, resume, coarsener, dgraph, rng):
        """Rebuild the coarsener hierarchy (coarsener.restore_levels) and
        the driver state recorded at the checkpointed barrier: partition,
        block spans, current_k, and the host RNG stream."""
        from .coarsener import restore_levels

        arrays = resume["arrays"]
        meta = resume.get("meta", {})
        stage = resume["stage"]
        num_restored = restore_levels(coarsener, dgraph, arrays)

        partition = None
        spans: List[_BlockSpan] = []
        current_k = 0
        if "state" in arrays:
            st = arrays["state"]
            part_host = np.asarray(st["partition"], dtype=np.int32)
            padded = np.zeros(coarsener.current.n_pad, dtype=np.int32)
            padded[: part_host.shape[0]] = part_host
            partition = jnp.asarray(padded)
            spans = [
                _BlockSpan(int(f), int(c))
                for f, c in np.asarray(st["spans"]).tolist()
            ]
            current_k = int(meta.get("current_k", len(spans)))
            self._spans = spans
        if meta.get("rng_state"):
            rng = np.random.default_rng(0)
            rng.bit_generator.state = meta["rng_state"]
        from .. import telemetry

        telemetry.event(
            "resume",
            scheme="deep",
            stage=stage,
            level=resume.get("level"),
            levels_restored=num_restored,
        )
        log_progress(
            f"resumed deep pipeline at {stage}"
            f"{'' if resume.get('level') is None else ':' + str(resume['level'])}"
            f" ({num_restored} hierarchy level(s) restored)"
        )
        return (
            stage, partition, spans, current_k,
            meta.get("num_levels"), rng,
        )

    # ------------------------------------------------------------------
    def _extend_and_refine(
        self,
        dgraph: DeviceGraph,
        n: int,
        partition,
        spans: List[_BlockSpan],
        current_k: int,
        rng,
        level: int,
        num_levels: int,
    ):
        ctx = self.ctx
        partition = self._refine(dgraph, partition, current_k, level, num_levels)
        desired_k = compute_k_for_n(n, ctx)
        target_k = min(desired_k, ctx.partition.k)
        while current_k < target_k:
            partition, spans, current_k = self._extend_partition(
                dgraph, partition, spans, min(2 * current_k, ctx.partition.k), rng
            )
            if ctx.partitioning.refine_after_extending_partition:
                # with light_intermediate_refinement, extensions that are
                # followed by another doubling get a single-round Jet —
                # the partition is refined again at the next doubling;
                # only the final extension's refine is the real polish
                partition = self._refine(
                    dgraph, partition, current_k, level, num_levels,
                    light=(
                        ctx.partitioning.light_intermediate_refinement
                        and current_k < target_k
                    ),
                )
        return partition, spans, current_k

    def _refine(self, dgraph, partition, k, level, num_levels, light=False):
        ctx = self.ctx
        # block weight caps for the *current* k: each current block's cap is
        # the sum of its final sub-blocks' caps (helper.cc block splitting)
        max_bw, min_bw = self._current_block_weights(k)
        refiner = RefinerPipeline(ctx, k, light=light)
        return refiner.refine(
            dgraph,
            partition,
            max_bw,
            min_bw,
            seed=ctx.seed + level,
            level=level,
            num_levels=num_levels,
        )

    # a real per-block device->host pull, by design: each extracted block
    # subgraph round-trips through the device bipartition pipeline and
    # comes back as a host int8 partition for stitching.  The extension
    # span that calls this IS the staged boundary — the pull is the
    # product, not an accidental sync.
    # tpulint: disable=R1
    def _device_bipartition(
        self, sub: HostGraph, max_block_weights: np.ndarray, rng
    ) -> np.ndarray:
        """Host-graph entry: upload, then run the device bipartition
        (passing `sub` down avoids a readback when coarsening converges
        immediately)."""
        dg = device_graph_from_host(sub)
        part = self._device_bipartition_dev(
            dg, sub.n, sub.m, max_block_weights, rng, host_sub=sub
        )
        return np.asarray(part)[: sub.n].astype(np.int8)

    def _device_bipartition_dev(
        self, dg: DeviceGraph, n: int, m: int,
        max_block_weights: np.ndarray, rng,
        host_sub: HostGraph | None = None,
    ):
        """Bipartition a large block subgraph through the device pipeline:
        LP coarsening + contraction on device until ~2000 nodes, host pool
        bipartition of the coarsest, then per-level 2-way LP refinement on
        device (the large-block replacement for the sequential
        InitialMultilevelBipartitioner inside extend_partition,
        helper.cc:220 — same structure, device-speed hot loops).  Takes
        and returns DEVICE arrays (i32[n_pad], 0/1) — the caller decides
        whether the result ever visits the host."""
        from ..ops.contraction import contract_clustering
        from ..ops.lp import lp_cluster, lp_refine
        from ..ops.subgraphs import host_graph_from_padded

        ctx = self.ctx
        ic = ctx.initial_partitioning.coarsening
        seed = int(rng.integers(0, 2**31 - 1))
        max_w = max_block_weights.astype(np.int64, copy=False)
        mcw = max(1, int(ic.cluster_weight_multiplier * max_w.max()))

        levels = []
        current, cur_n = dg, n
        # hand off to the sequential host pool at the same scale the main
        # pipeline does (deep coarsening threshold = 2 * contraction_limit)
        stop_n = max(2, 2 * ctx.coarsening.contraction_limit)
        while cur_n > stop_n:
            labels = lp_cluster(
                current,
                jnp.asarray(min(mcw, WMAX), dtype=WEIGHT_DTYPE),
                jnp.int32((seed + 31 * len(levels)) & 0x7FFFFFFF),
            )
            coarse, c_n, _ = contract_clustering(current, labels)
            if c_n >= (1.0 - ic.convergence_threshold) * cur_n:
                break
            levels.append((current, coarse))
            current, cur_n = coarse.graph, c_n

        if levels:
            coarsest_host = host_graph_from_device(current)
        elif host_sub is not None:
            coarsest_host = host_sub  # already in hand — no readback
        else:
            coarsest_host = host_graph_from_padded(dg, n, m)
        bp = InitialMultilevelBipartitioner(
            ctx.initial_partitioning
        ).bipartition(coarsest_host, max_w, rng)

        part = np.zeros(current.n_pad, dtype=np.int32)
        part[: coarsest_host.n] = bp
        part = jnp.asarray(part)
        caps = jnp.asarray(np.minimum(max_w, WMAX), dtype=WEIGHT_DTYPE)
        for lvl, (fine_graph, coarse) in enumerate(reversed(levels)):
            part = coarse.project_up(part)
            part = lp_refine(
                fine_graph, part, 2, caps,
                jnp.int32((seed ^ 0x5F3759) + 101 * lvl),
            )
        # Jet polish of the 2-way cut at the subgraph's finest level — the
        # device replacement for the host FM pass the sequential
        # bipartitioner would have run per level (initial_fm_refiner.h:68)
        from ..ops.jet import jet_refine

        return jet_refine(
            dg, part, 2, caps, jnp.int32(seed ^ 0x2545F491),
            ctx.refinement.jet,
        )

    def _current_block_weights(self, k: int):
        ctx = self.ctx
        spans = self._spans
        assert len(spans) == k, (len(spans), k)
        p = ctx.partition
        caps = np.array(
            [
                p.total_max_block_weights(s.first, s.first + s.count)
                for s in spans
            ],
            dtype=np.int64,
        )
        max_bw = jnp.asarray(np.minimum(caps, WMAX), dtype=WEIGHT_DTYPE)
        min_bw = None
        if p.min_block_weights is not None:
            mins = np.array(
                [
                    int(p.min_block_weights[s.first : s.first + s.count].sum())
                    for s in spans
                ],
                dtype=np.int64,
            )
            min_bw = jnp.asarray(np.minimum(mins, WMAX), dtype=WEIGHT_DTYPE)
        return max_bw, min_bw

    def _extend_partition(
        self, dgraph: DeviceGraph, partition, spans, next_k: int, rng
    ):
        """extend_partition (helper.cc:220,349): bipartition each block that
        still spans more than one final block, until current_k == next_k.

        Large levels run the DEVICE extraction (ops/subgraphs.py — no
        full-graph readback); small levels keep the host path, whose
        readback is cheap and whose numpy extraction needs no extra
        device programs.  So does the large-k regime: with hundreds of
        small blocks, per-block device programs would pay the ~87 ms
        launch floor per block — one readback + native bipartitions win.
        STREAMED (compressed) inputs raise the span limit to 128: the
        host readback would blow the compressed-mode memory contract
        (peak RSS tracked 8.4 GB at k=128 through this path), and the
        extra per-block launch floors are what TeraPart parity costs."""
        span_limit = 128 if getattr(self, "_streamed_input", False) else 64
        if (
            dgraph.m_pad >= DEVICE_EXTEND_MIN_EDGE_SLOTS
            and len(spans) <= span_limit
        ):
            return self._extend_partition_device(
                dgraph, partition, spans, next_k, rng
            )
        return self._extend_partition_host(
            dgraph, partition, spans, next_k, rng
        )

    def _extend_partition_device(
        self, dgraph: DeviceGraph, partition, spans, next_k: int, rng
    ):
        """Device-side extend_partition: block-major extraction on device,
        per-block bipartitions (device pipeline for big blocks, host pool
        for small ones — only the small blocks and coarsest sub-levels are
        ever downloaded), partition assembly on device."""
        from ..graphs.csr import shape_floors
        from ..ops.subgraphs import (
            assemble_extended_partition,
            extract_blocks_device,
            host_graph_from_padded,
            scatter_block_bipartition,
            slice_block,
        )

        ctx = self.ctx
        with timer.scoped_timer("extend-partition"):
            current_k = len(spans)
            ext = extract_blocks_device(dgraph, partition, current_k)
            n_floor, m_floor = shape_floors()
            bp_global = jnp.zeros(dgraph.n_pad, dtype=jnp.int32)
            bipartitioner = InitialMultilevelBipartitioner(
                ctx.initial_partitioning
            )
            new_spans: List[_BlockSpan] = []
            base_ids = np.zeros(current_k, dtype=np.int32)
            is_split = np.zeros(current_k, dtype=bool)
            next_id = 0
            for bidx, span in enumerate(spans):
                base_ids[bidx] = next_id
                if span.count <= 1:
                    new_spans.append(span)
                    next_id += 1
                    continue
                is_split[bidx] = True
                sub, n_b, m_b = slice_block(ext, bidx, n_floor, m_floor)
                max_w = bipartition_max_block_weights(
                    ctx, span.first, span.count,
                    int(ext.block_weights[bidx]),
                )
                if n_b >= ctx.partitioning.device_bipartition_threshold:
                    bp = self._device_bipartition_dev(
                        sub, n_b, m_b, max_w, rng
                    )
                else:
                    host_sub = host_graph_from_padded(sub, n_b, m_b)
                    bp_np = bipartitioner.bipartition(host_sub, max_w, rng)
                    padded = np.zeros(sub.n_pad, dtype=np.int32)
                    padded[:n_b] = bp_np
                    bp = jnp.asarray(padded)
                bp_global = scatter_block_bipartition(
                    bp_global, bp, ext.node_start[bidx], jnp.int32(n_b),
                    sub.n_pad,
                )
                k0, k1 = split_k(span.count)
                new_spans.append(_BlockSpan(span.first, k0))
                new_spans.append(_BlockSpan(span.first + k0, k1))
                next_id += 2
            new_part = assemble_extended_partition(
                ext.b, ext.new_id, ext.node_start, bp_global,
                jnp.asarray(base_ids), jnp.asarray(is_split), current_k,
            )
            self._spans = new_spans
            from .. import telemetry

            telemetry.event(
                "extend-partition", k=len(new_spans), extractor="device"
            )
            return new_part, new_spans, len(new_spans)

    def _extend_partition_host(
        self, dgraph: DeviceGraph, partition, spans, next_k: int, rng
    ):
        ctx = self.ctx
        # the host extraction IS the staged boundary: pull graph and
        # partition before opening the span so the timed extension work
        # starts from host arrays
        host = host_graph_from_device(dgraph)
        part = np.asarray(partition)[: host.n].astype(np.int64)
        with timer.scoped_timer("extend-partition"):
            current_k = len(spans)
            ext = extract_block_subgraphs(host, part, current_k)

            new_spans: List[_BlockSpan] = []
            new_ids_base: List[Tuple[int, int]] = []  # (id0, id1 or -1)
            bipartitioner = InitialMultilevelBipartitioner(
                ctx.initial_partitioning
            )
            sub_parts: List = []
            next_id = 0
            pool_jobs: List[Tuple[int, HostGraph, np.ndarray, int]] = []
            workers = max(1, int(ctx.parallel.num_workers))
            for b, span in enumerate(spans):
                # split only while we have not reached next_k blocks overall
                if span.count > 1:
                    sub = ext.subgraphs[b]
                    max_w = bipartition_max_block_weights(
                        ctx, span.first, span.count, sub.total_node_weight
                    )
                    if sub.n >= ctx.partitioning.device_bipartition_threshold:
                        bp = self._device_bipartition(sub, max_w, rng)
                    elif workers > 1:
                        # per-block seeds are PRE-DRAWN so the result is
                        # identical for any worker-pool size (the
                        # reference's per-PE seed discipline,
                        # initial_bipartitioner_worker_pool.h:42)
                        pool_jobs.append(
                            (len(sub_parts), sub, max_w,
                             int(rng.integers(0, 2**31 - 1)))
                        )
                        bp = None
                    else:
                        # single worker: draw from the shared stream —
                        # bitwise-identical to the pre-pool code path
                        bp = bipartitioner.bipartition(sub, max_w, rng)
                    k0, k1 = split_k(span.count)
                    new_ids_base.append((next_id, next_id + 1))
                    new_spans.append(_BlockSpan(span.first, k0))
                    new_spans.append(_BlockSpan(span.first + k0, k1))
                    sub_parts.append(bp)
                    next_id += 2
                else:
                    new_ids_base.append((next_id, -1))
                    new_spans.append(span)
                    sub_parts.append(None)
                    next_id += 1

            # host-pool bipartitions: independent per block — run them on
            # a worker pool (the native bipartitioner releases the GIL
            # for the duration of the C call, so threads scale on real
            # multi-core hosts; this dev box has ONE logical CPU).
            # Each job gets its OWN bipartitioner (the pool's adaptive
            # per-algorithm stats are not thread-safe) and the global
            # timer is quiesced for the pool phase (its scope stack is
            # shared; the outer extend-partition scope still captures
            # the wall time).
            def run_job(job):
                idx, sub, max_w, s = job
                bip = InitialMultilevelBipartitioner(
                    ctx.initial_partitioning
                )
                return idx, bip.bipartition(
                    sub, max_w, np.random.default_rng(s)
                )

            if len(pool_jobs) > 1:
                from concurrent.futures import ThreadPoolExecutor

                was_enabled = timer.GLOBAL_TIMER.enabled
                timer.GLOBAL_TIMER.enabled = False
                try:
                    with ThreadPoolExecutor(max_workers=workers) as pool:
                        for idx, bp in pool.map(run_job, pool_jobs):
                            sub_parts[idx] = bp
                finally:
                    timer.GLOBAL_TIMER.enabled = was_enabled
            else:
                for job in pool_jobs:
                    idx, bp = run_job(job)
                    sub_parts[idx] = bp

            new_part = np.zeros(host.n, dtype=np.int32)
            for b, span in enumerate(spans):
                mask = part == b
                id0, id1 = new_ids_base[b]
                if id1 < 0:
                    new_part[mask] = id0
                else:
                    bp = sub_parts[b]
                    new_part[mask] = np.where(
                        bp[ext.node_mapping[mask]] == 0, id0, id1
                    )

            padded = np.zeros(dgraph.n_pad, dtype=np.int32)
            padded[: host.n] = new_part
            self._spans = new_spans
            from .. import telemetry

            telemetry.event(
                "extend-partition", k=len(new_spans), extractor="host"
            )
            return jnp.asarray(padded), new_spans, len(new_spans)
