"""Configuration tree (analog of include/kaminpar-shm/kaminpar.h Context).

The reference models every algorithmic choice as an enum + plain-struct tree
(Context -> PartitioningContext/CoarseningContext/InitialPartitioningContext/
RefinementContext, include/kaminpar-shm/kaminpar.h:94-562).  We mirror that
with dataclasses; presets.py builds filled-in trees by name.

PartitionContext reproduces the block-weight semantics of
include/kaminpar-shm/kaminpar.h:371-478: max block weights derived from
(1+eps)*ceil(total/k), optional relaxation by the max node weight, inferred
epsilon for custom weight vectors, optional min block weights.
"""

from __future__ import annotations

import enum
import math as pymath
from dataclasses import dataclass, field, replace
from typing import List, Optional

import numpy as np


class PartitioningMode(str, enum.Enum):
    """include/kaminpar-shm/kaminpar.h:94-98 (+ the out-of-core
    streaming scheme, kaminpar_tpu/external/ — no reference analog; the
    semi-external literature's arXiv 1404.4887 scheme mapped onto the
    device pipeline)."""

    DEEP = "deep"
    RB = "rb"
    KWAY = "kway"
    VCYCLE = "vcycle"
    EXTERNAL = "external"


class ClusteringAlgorithm(str, enum.Enum):
    NOOP = "noop"
    LABEL_PROPAGATION = "lp"


class CoarseningAlgorithm(str, enum.Enum):
    NOOP = "noop"
    BASIC_CLUSTERING = "basic"
    OVERLAY_CLUSTERING = "overlay"
    SPARSIFICATION_CLUSTERING = "sparsification"


class ClusterWeightLimit(str, enum.Enum):
    """max_cluster_weights.h ClusterWeightLimit."""

    EPSILON_BLOCK_WEIGHT = "epsilon-block-weight"
    BLOCK_WEIGHT = "static-block-weight"
    ONE = "one"
    ZERO = "zero"


class RefinementAlgorithm(str, enum.Enum):
    NOOP = "noop"
    LABEL_PROPAGATION = "lp"
    OVERLOAD_BALANCER = "overload-balancer"
    UNDERLOAD_BALANCER = "underload-balancer"
    JET = "jet"
    GREEDY_FM = "fm"
    MTKAHYPAR = "mtkahypar"


class TwoHopStrategy(str, enum.Enum):
    DISABLE = "disable"
    CLUSTER = "cluster"
    MATCH = "match"


class IsolatedNodesStrategy(str, enum.Enum):
    KEEP = "keep"
    CLUSTER = "cluster"
    MATCH_DURING_TWO_HOP = "cluster-during-two-hop"


class InitialPartitioningMode(str, enum.Enum):
    SEQUENTIAL = "sequential"
    ASYNCHRONOUS_PARALLEL = "async-parallel"
    SYNCHRONOUS_PARALLEL = "sync-parallel"


class FMStoppingRule(str, enum.Enum):
    SIMPLE = "simple"
    ADAPTIVE = "adaptive"


@dataclass
class LabelPropagationContext:
    """kaminpar.h LabelPropagationCoarseningContext (+ bulk-sync knobs)."""

    num_iterations: int = 5
    # degree-skew knobs: the reference splits high-degree nodes into a
    # second phase (label_propagation.h:1939); the TPU kernel's sorted
    # segmented reduction handles skew uniformly, so these are accepted for
    # context parity but have no effect on the device path
    large_degree_threshold: int = 2**31 - 1
    max_num_neighbors: int = 2**31 - 1
    two_hop_strategy: TwoHopStrategy = TwoHopStrategy.CLUSTER
    two_hop_threshold: float = 0.5
    isolated_nodes_strategy: IsolatedNodesStrategy = (
        IsolatedNodesStrategy.MATCH_DURING_TWO_HOP
    )
    # bulk-synchronous device LP specifics (no reference analog; see ops/lp.py)
    participation: float = 0.5
    allow_tie_moves: bool = True
    use_active_set: bool = True
    # rating engine (ops/rating.py): "auto" = per-level density-adaptive
    # selection (dense / scatter / sort2); "scatter"/"sort2"/"sort"/
    # "hash"/"dense" force one for comparison runs (--lp-rating)
    rating: str = "auto"
    # hashed slots per node row for the scatter/hash engines
    rating_slots: int = 32


@dataclass
class ClusteringContext:
    algorithm: ClusteringAlgorithm = ClusteringAlgorithm.LABEL_PROPAGATION
    lp: LabelPropagationContext = field(default_factory=LabelPropagationContext)
    cluster_weight_limit: ClusterWeightLimit = (
        ClusterWeightLimit.EPSILON_BLOCK_WEIGHT
    )
    cluster_weight_multiplier: float = 1.0
    # desired-cluster-count floor (n / shrink_factor); accepted for preset
    # parity, not yet enforced by the bulk-sync clusterer
    shrink_factor: float = float("inf")
    # terapart-largek: force an extra coarsening level at the k-contraction
    # boundary (presets.cc create_terapart_largek_context)
    forced_kc_level: bool = False
    # overlay coarsening (OverlayClusterCoarsener): number of independent
    # clusterings intersected per level
    num_overlays: int = 2


@dataclass
class CoarseningContext:
    """kaminpar.h CoarseningContext (presets.cc:178-179 defaults)."""

    algorithm: CoarseningAlgorithm = CoarseningAlgorithm.BASIC_CLUSTERING
    clustering: ClusteringContext = field(default_factory=ClusteringContext)
    contraction_limit: int = 2000
    convergence_threshold: float = 0.05
    # TPU-specific limping-tail cutoff: once n <= 8 * contraction_limit,
    # a level shrinking less than this fraction ends coarsening (every
    # accepted level costs a full refine pass during uncoarsening; the
    # host IP pool handles a 10-16k-node coarsest graph directly)
    stall_threshold: float = 0.12
    # linear-time MGP (arXiv 2504.17615; SparsificationClusterCoarsener
    # analog): fraction of edges kept per level before clustering
    sparsification_keep_ratio: float = 0.5

    def max_cluster_weight(
        self, n: int, total_node_weight: int, p_ctx: "PartitionContext"
    ) -> int:
        """compute_max_cluster_weight (max_cluster_weights.h)."""
        limit = self.clustering.cluster_weight_limit
        if limit == ClusterWeightLimit.EPSILON_BLOCK_WEIGHT:
            divisor = min(max(n // max(self.contraction_limit, 1), 2), p_ctx.k)
            w = (
                p_ctx.infer_epsilon(total_node_weight) * total_node_weight
            ) / divisor
        elif limit == ClusterWeightLimit.BLOCK_WEIGHT:
            w = (1.0 + p_ctx.inferred_epsilon()) * total_node_weight / p_ctx.k
        elif limit == ClusterWeightLimit.ONE:
            w = 1.0
        else:
            w = 0.0
        return int(w * self.clustering.cluster_weight_multiplier)


@dataclass
class InitialCoarseningContext:
    """presets.cc:185-189 defaults."""

    contraction_limit: int = 20
    convergence_threshold: float = 0.05
    large_degree_threshold: int = 1_000_000
    cluster_weight_limit: ClusterWeightLimit = ClusterWeightLimit.BLOCK_WEIGHT
    cluster_weight_multiplier: float = 1.0 / 12.0


@dataclass
class InitialRefinementContext:
    """Sequential 2-way FM knobs (presets.cc:196-201)."""

    disabled: bool = False
    stopping_rule: FMStoppingRule = FMStoppingRule.SIMPLE
    num_fruitless_moves: int = 100
    alpha: float = 1.0
    num_iterations: int = 5
    improvement_abortion_threshold: float = 0.0001


@dataclass
class InitialPoolContext:
    """presets.cc:202-211 defaults."""

    refinement: InitialRefinementContext = field(
        default_factory=InitialRefinementContext
    )
    repetition_multiplier: float = 1.0
    min_num_repetitions: int = 10
    min_num_non_adaptive_repetitions: int = 5
    max_num_repetitions: int = 50
    use_adaptive_bipartitioner_selection: bool = True
    enable_bfs_bipartitioner: bool = True
    enable_ggg_bipartitioner: bool = True
    enable_random_bipartitioner: bool = True


@dataclass
class InitialPartitioningContext:
    coarsening: InitialCoarseningContext = field(
        default_factory=InitialCoarseningContext
    )
    pool: InitialPoolContext = field(default_factory=InitialPoolContext)
    refinement: InitialRefinementContext = field(
        default_factory=InitialRefinementContext
    )
    use_adaptive_epsilon: bool = True


@dataclass
class LPRefinementContext:
    num_iterations: int = 5
    participation: float = 0.8


@dataclass
class JetRefinementContext:
    """presets.cc jet defaults (num_iterations=0 means auto by level)."""

    num_iterations: int = 0
    num_fruitless_iterations: int = 12
    fruitless_threshold: float = 0.999
    num_rounds_on_fine_level: int = 1
    num_rounds_on_coarse_level: int = 1
    initial_gain_temp_on_fine_level: float = 0.25
    final_gain_temp_on_fine_level: float = 0.25
    initial_gain_temp_on_coarse_level: float = 0.75
    final_gain_temp_on_coarse_level: float = 0.75


@dataclass
class BalancerContext:
    max_rounds: int = 8


@dataclass
class FMRefinementContext:
    """Host-side k-way FM (refinement/fm) knobs."""

    num_iterations: int = 3
    num_seed_nodes: int = 10
    alpha: float = 1.0
    num_fruitless_moves: int = 100
    # run FM only on levels <= max_level (0 = finest); coarse levels are
    # Jet territory and FM's host pass cost there buys ~0.1% cut
    max_level: int = 1


@dataclass
class RefinementContext:
    algorithms: List[RefinementAlgorithm] = field(
        default_factory=lambda: [
            RefinementAlgorithm.OVERLOAD_BALANCER,
            RefinementAlgorithm.LABEL_PROPAGATION,
            RefinementAlgorithm.UNDERLOAD_BALANCER,
        ]
    )
    lp: LPRefinementContext = field(default_factory=LPRefinementContext)
    jet: JetRefinementContext = field(default_factory=JetRefinementContext)
    balancer: BalancerContext = field(default_factory=BalancerContext)
    fm: FMRefinementContext = field(default_factory=FMRefinementContext)

    def includes_algorithm(self, algorithm: RefinementAlgorithm) -> bool:
        return algorithm in self.algorithms


@dataclass
class PartitioningSchemeContext:
    """kaminpar.h PartitioningContext."""

    mode: PartitioningMode = PartitioningMode.DEEP
    deep_initial_partitioning_mode: InitialPartitioningMode = (
        InitialPartitioningMode.ASYNCHRONOUS_PARALLEL
    )
    deep_initial_partitioning_load: float = 1.0
    refine_after_extending_partition: bool = False
    # single-round Jet for intermediate k-doubling extensions (another
    # doubling follows immediately): ~13% faster end-to-end for ~0.1%
    # cut on the RMAT bench — on for the fast preset, off by default
    light_intermediate_refinement: bool = False
    # extend_partition blocks at least this large are bipartitioned through
    # the device pipeline (LP coarsening + 2-way device refinement) instead
    # of the sequential host pool — the TPU answer to the reference running
    # many host bipartitions in parallel TBB tasks (helper.cc:220)
    device_bipartition_threshold: int = 1 << 14
    vcycles: List[int] = field(default_factory=list)
    restrict_vcycle_refinement: bool = False
    rb_enable_kway_toplevel_refinement: bool = False


@dataclass
class ParallelContext:
    num_workers: int = 1  # host worker threads for initial partitioning


@dataclass
class PartitionContext:
    """Block count and weight constraints
    (include/kaminpar-shm/kaminpar.h:371-478)."""

    k: int = 2
    epsilon: float = 0.03
    n: int = 0
    m: int = 0
    total_node_weight: int = 0
    total_edge_weight: int = 0
    max_node_weight: int = 0
    max_block_weights: Optional[np.ndarray] = None  # relaxed
    unrelaxed_max_block_weights: Optional[np.ndarray] = None
    min_block_weights: Optional[np.ndarray] = None
    uniform_block_weights: bool = True

    def setup(self, graph, k: Optional[int] = None, epsilon: Optional[float] = None,
              max_block_weights: Optional[np.ndarray] = None,
              relax_max_block_weights: bool = True) -> None:
        """PartitionContext::setup (context.cc:27-70)."""
        if k is not None:
            self.k = int(k)
        if epsilon is not None:
            self.epsilon = float(epsilon)
        self.n = graph.n
        self.m = graph.m
        self.total_node_weight = graph.total_node_weight
        self.total_edge_weight = graph.total_edge_weight
        nw = graph.node_weight_array()
        self.max_node_weight = int(nw.max()) if len(nw) else 0

        if max_block_weights is None:
            perfect = pymath.ceil(self.total_node_weight / self.k)
            max_block_weights = np.full(
                self.k, int((1.0 + self.epsilon) * perfect), dtype=np.int64
            )
            self.uniform_block_weights = True
        else:
            max_block_weights = np.asarray(max_block_weights, dtype=np.int64)
            self.k = len(max_block_weights)
            self.uniform_block_weights = False
        self.unrelaxed_max_block_weights = max_block_weights.copy()

        if relax_max_block_weights:
            eps = self.inferred_epsilon()
            relaxed = np.maximum(
                max_block_weights,
                np.ceil(max_block_weights / (1.0 + eps)).astype(np.int64)
                + self.max_node_weight,
            )
            self.max_block_weights = relaxed
        else:
            self.max_block_weights = max_block_weights

    def infer_epsilon(self, actual_total_node_weight: int) -> float:
        """kaminpar.h:427-433."""
        if self.unrelaxed_max_block_weights is None:
            return self.epsilon
        total_max = int(self.unrelaxed_max_block_weights.sum())
        if actual_total_node_weight <= 0:
            return self.epsilon
        return max(total_max / actual_total_node_weight - 1.0, 0.0)

    def inferred_epsilon(self) -> float:
        return self.infer_epsilon(self.total_node_weight)

    def perfectly_balanced_block_weight(self, block: int = 0) -> int:
        if self.unrelaxed_max_block_weights is None:
            return pymath.ceil(self.total_node_weight / self.k)
        return pymath.ceil(
            self.unrelaxed_max_block_weights[block] / (1.0 + self.inferred_epsilon())
        )

    def setup_min_block_weights(self, min_epsilon: float) -> None:
        """context.cc:72-81."""
        self.min_block_weights = np.array(
            [
                pymath.ceil(
                    (1.0 - min_epsilon) * self.perfectly_balanced_block_weight(b)
                )
                for b in range(self.k)
            ],
            dtype=np.int64,
        )

    def total_max_block_weights(self, begin: int, end: int) -> int:
        """kaminpar.h:398-408 (sum of unrelaxed max weights in [begin, end))."""
        return int(self.unrelaxed_max_block_weights[begin:end].sum())

    def max_block_weight(self, block: int = 0) -> int:
        return int(self.max_block_weights[block])


@dataclass
class GraphCompressionContext:
    """Compressed-graph (TeraPart) mode: store the host graph varint-gap
    compressed (graphs/compressed.py); the device path is unchanged."""

    enabled: bool = False


@dataclass
class ResilienceContext:
    """Degradation / output-gate policy (resilience/, docs/robustness.md).

    `output_gate` runs the end-of-pipeline strict-balance validator
    (one O(n + m) host pass; also killable per-run via
    KAMINPAR_TPU_OUTPUT_GATE=0); `repair` lets the gate fix balance
    violations with the greedy host pass (--no-repair disables repair
    but keeps the check, so violations still surface in telemetry).

    Preemption safety (resilience/checkpoint.py, resilience/deadline.py):
    `checkpoint_dir` enables atomic barrier snapshots there; `resume`
    re-enters at the recorded stage when the directory holds a matching
    manifest; `time_budget` (> 0) arms a monotonic deadline checked
    cooperatively at the pipeline barriers — on expiry the run winds
    down and returns a gate-valid partition annotated `anytime: true`;
    `budget_grace` is the DECLARED wind-down allowance on top of it —
    advisory (reported in the anytime section for operators sizing
    preemption windows), the mandatory tail is not forcibly killed."""

    output_gate: bool = True
    repair: bool = True
    checkpoint_dir: str = ""
    resume: bool = False
    time_budget: float = 0.0
    budget_grace: float = 30.0
    #: Hard wall-clock ceiling multiplier (resilience/supervisor.py):
    #: with a cooperative `time_budget` armed, the watchdog's hard
    #: ceiling defaults to max(factor * budget, budget + grace) — the
    #: backstop for hangs the cooperative budget cannot interrupt
    #: (hung launches, hung backend init, stuck native calls).
    #: KAMINPAR_TPU_HARD_DEADLINE_S overrides the derived value; 0
    #: disables the derived ceiling entirely.
    hard_deadline_factor: float = 10.0
    #: Declared device-memory budget in bytes (``--memory-budget``;
    #: 0 = take KAMINPAR_TPU_HBM_BYTES, unset = no budget).  With a
    #: budget in force the memory governor (resilience/memory.py)
    #: enforces it: admission/preflight refuse what cannot fit, the
    #: barrier pressure hook spills proactively, and a DeviceOOM
    #: degrades through the recovery ladder instead of surfacing
    #: RESOURCE_EXHAUSTED.  Excluded from the ctx fingerprint like the
    #: rest of this subtree — a budget never forks checkpoints or
    #: result-cache keys.
    memory_budget: float = 0.0


@dataclass
class ExternalContext:
    """Out-of-core streaming scheme (``--scheme external``,
    kaminpar_tpu/external/, docs/performance.md): the fine graph stays
    in host RAM (compressed chunks / plain CSR / a skagen generator
    spec that regenerates chunks on demand) or on disk, and LP rating +
    contraction stream over fixed-shape padded edge-block chunks on the
    device — only coarse levels are ever device-resident."""

    #: Target edges per streamed chunk.  Every chunk of a level shares
    #: ONE padded edge-block bucket (the max chunk, padded), so the
    #: whole stream drives one compiled executable per phase.
    chunk_edges: int = 1 << 22
    #: Streaming LP rounds per level (bulk-synchronous: moves are rated
    #: against the round-start labels and applied once per round, which
    #: is what makes the result chunk-count invariant).
    lp_rounds: int = 3
    #: Stream at least this many levels before the in-core handoff even
    #: when no memory budget is declared (with a budget, streaming
    #: continues until the coarse level's estimate fits it).
    min_stream_levels: int = 1
    #: Hard cap on streamed levels (stall safety).
    max_stream_levels: int = 32
    #: Disk spill tier: when set, decoded/generated chunks are written
    #: here once and re-read per pass — fine graphs bigger than host
    #: RAM stream from disk instead of being re-decoded/regenerated.
    spill_dir: str = ""


@dataclass
class DynamicContext:
    """Dynamic repartitioning policy (kaminpar_tpu/dynamic/,
    docs/robustness.md "Dynamic sessions"): graphs that mutate between
    requests get a warm-started v-cycle repartition over the previous
    partition instead of a cold run.  The drift estimator (delta edge
    mass touching the cut / total edge mass, plus the post-patch balance
    violation) picks warm vs cold per request; PASCO-style replicas race
    warm against cold and keep the better cut (arXiv 2412.13592's
    replicated-coarsening knob as the escape hatch when drift makes
    warm-starting worse than restarting).

    INCLUDED in the ctx fingerprint (unlike the resilience subtree):
    these knobs change the produced partition, so they must fork
    result-cache keys and checkpoints."""

    #: Accumulated drift above this runs a cold repartition instead of
    #: the warm v-cycle (drift = cut-touching delta mass fraction +
    #: balance violation after the patch).
    drift_threshold: float = 0.25
    #: Replicated repartitioning: 1 = the drift decision alone; G >= 2
    #: races the warm v-cycle against (G - 1) cold replicas (seeds
    #: varied per replica) and keeps the best feasible cut.
    replicas: int = 1
    #: Restricted-coarsening depth of the warm v-cycle (0 = a pure
    #: refinement pass over the previous partition at the fine level —
    #: the fine-level cluster LP dominates cold runs, so bounding the
    #: warm hierarchy is what buys the warm-vs-cold speedup; raise for
    #: higher-drift workloads).
    warm_levels: int = 0
    #: The PR-4 telemetry.diff cut gate applied across a delta: a warm
    #: result whose cut regressed more than this fraction vs the
    #: pre-delta cut escalates to a cold run (and keeps the better).
    cut_gate_threshold: float = 0.10
    #: Whether a gate-violating warm result may escalate to a cold
    #: retry at all (tests pin the no-escalation path).
    cold_fallback: bool = True


@dataclass
class DebugContext:
    """kaminpar.h:484-496."""

    graph_name: str = ""
    dump_toplevel_graph: bool = False
    dump_toplevel_partition: bool = False
    dump_coarsest_graph: bool = False
    dump_coarsest_partition: bool = False
    dump_graph_hierarchy: bool = False
    dump_partition_hierarchy: bool = False
    dump_dir: str = "."


def context_to_dict(obj):
    """Context tree (any dataclass tree, really) -> plain nested dict:
    enums to values, numpy arrays to lists, inf to "inf".  Lives here —
    below the CLI — because library-level consumers need it too (TOML
    round-tripping in cli.py, the checkpoint ctx fingerprint in
    resilience/checkpoint.py)."""
    import dataclasses as _dc
    import enum as _enum

    if _dc.is_dataclass(obj) and not isinstance(obj, type):
        return {
            f.name: context_to_dict(getattr(obj, f.name))
            for f in _dc.fields(obj)
        }
    if isinstance(obj, _enum.Enum):
        return obj.value
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if isinstance(obj, (list, tuple)):
        return [context_to_dict(x) for x in obj]
    if isinstance(obj, float) and obj == float("inf"):
        return "inf"
    return obj


@dataclass
class Context:
    """Root context (include/kaminpar-shm/kaminpar.h:550-562)."""

    preset_name: str = "default"
    partitioning: PartitioningSchemeContext = field(
        default_factory=PartitioningSchemeContext
    )
    partition: PartitionContext = field(default_factory=PartitionContext)
    coarsening: CoarseningContext = field(default_factory=CoarseningContext)
    initial_partitioning: InitialPartitioningContext = field(
        default_factory=InitialPartitioningContext
    )
    refinement: RefinementContext = field(default_factory=RefinementContext)
    parallel: ParallelContext = field(default_factory=ParallelContext)
    compression: GraphCompressionContext = field(
        default_factory=GraphCompressionContext
    )
    resilience: ResilienceContext = field(default_factory=ResilienceContext)
    external: ExternalContext = field(default_factory=ExternalContext)
    dynamic: DynamicContext = field(default_factory=DynamicContext)
    debug: DebugContext = field(default_factory=DebugContext)
    seed: int = 0

    def copy(self) -> "Context":
        import copy as pycopy

        return pycopy.deepcopy(self)
