"""Distributed CLI — the `dKaMinPar` binary analog.

The reference ships a second binary for the distributed solver
(apps/dKaMinPar.cc:663, flags in kaminpar-cli/dkaminpar_arguments.cc)
that adds MPI rank setup and KaGen generator input on top of the shm
CLI surface.  Here the "ranks" are devices of a `jax.sharding.Mesh`:
`-n/--num-devices` picks the mesh size (on CPU, virtual devices via
XLA_FLAGS=--xla_force_host_platform_device_count=N), and the solver is
`parallel.dKaMinPar`.

Run as `python -m kaminpar_tpu.dcli GRAPH -k K [-n N]`.
"""

from __future__ import annotations

import argparse
import sys
import time
from typing import List, Optional

from . import io as io_mod
from .utils import timer
from .utils.logger import OutputLevel


def build_parser() -> argparse.ArgumentParser:
    from .parallel.dist_context import get_dist_preset_names

    p = argparse.ArgumentParser(
        prog="kaminpar_tpu.dcli",
        description="TPU-native distributed deep multilevel graph "
        "partitioner (dKaMinPar analog)",
    )
    p.add_argument(
        "graph", nargs="?",
        help="input graph file, or generator string "
        "'gen:rmat;n=65536;m=1000000;seed=1' (the -G KaGen surface)",
    )
    p.add_argument("-k", "--k", type=int, default=None, help="number of blocks")
    p.add_argument(
        "-e", "--epsilon", type=float, default=None,
        help="max imbalance, e.g. 0.03 (default)",
    )
    p.add_argument(
        "-P", "--preset", default="default",
        choices=sorted(get_dist_preset_names()),
        help="distributed configuration preset",
    )
    p.add_argument(
        "-n", "--num-devices", type=int, default=None,
        help="mesh size (default: all visible devices)",
    )
    p.add_argument("-s", "--seed", type=int, default=0, help="RNG seed")
    p.add_argument(
        "--stream-chunks", type=int, default=0, metavar="N",
        help="generate a 'gen:' input in N streaming chunks (the KaGen "
        "streaming mode, kaminpar-io/dist_skagen.cc: bounded generation "
        "memory, chunking-invariant output; rmat/gnm/rgg2d only)",
    )
    p.add_argument(
        "-f", "--format", default="auto",
        choices=["auto", "metis", "parhip", "compressed"],
        help="input graph format",
    )
    p.add_argument("-o", "--output", default=None, help="partition output file")
    p.add_argument("-q", "--quiet", action="store_true", help="no output")
    p.add_argument(
        "--validate", action="store_true",
        help="validate the input graph before partitioning",
    )
    p.add_argument(
        "-T", "--timers", action="store_true", help="print the timer tree"
    )
    p.add_argument(
        "--machine-timers", action="store_true",
        help="print the timer tree as one machine-readable line",
    )
    p.add_argument(
        "--comm-table", action="store_true",
        help="print the per-phase collective-traffic account "
        "(trace-time accounting; see docs/observability.md)",
    )
    p.add_argument(
        "--checkpoint-dir", default=None, metavar="DIR",
        help="write atomic pipeline-barrier checkpoints under DIR "
        "(rank 0 writes; barrier-consistent stage ids; per-level coarse "
        "CSR/cmap snapshots + a per-rank shard-fingerprint vector in "
        "the manifest — full-hierarchy dist resume, docs/robustness.md)",
    )
    p.add_argument(
        "--resume", action="store_true",
        help="resume from --checkpoint-dir at the recorded dist barrier "
        "(fingerprint-validated; a graph/ctx mismatch OR a changed "
        "device count — detected via the shard fingerprints — degrades "
        "to a logged clean restart, never a wrong answer)",
    )
    p.add_argument(
        "--time-budget", type=float, default=None, metavar="SECS",
        help="anytime mode: wind down at the next pipeline barrier once "
        "SECS have elapsed and return the best partition reached",
    )
    p.add_argument(
        "--budget-grace", type=float, default=None, metavar="SECS",
        help="declared (advisory, reported-not-enforced) wind-down "
        "allowance on top of --time-budget (default 30)",
    )
    p.add_argument(
        "--memory-budget", type=float, default=None, metavar="BYTES",
        help="declared PER-DEVICE memory budget (bytes; or "
        "KAMINPAR_TPU_HBM_BYTES): preflight prices the actual max "
        "padded shard from the sharding plan, and a DeviceOOM on any "
        "rank walks EVERY rank down the cross-rank agreed recovery "
        "ladder together (tight pads -> host-spilled shard hierarchy "
        "-> host-only; docs/robustness.md, dist resilience contract)",
    )
    p.add_argument(
        "--lp-rating", default=None,
        choices=["auto", "scatter", "sort", "hash", "dense"],
        help="dist LP rating engine (default auto resolves to "
        "dense/sort — no per-shard skew measurement, so the scatter "
        "quality gate stays closed; force 'scatter' for RMAT-class "
        "skewed workloads; sort2 needs CSR row spans and is shm-only)",
    )
    p.add_argument(
        "--serve-batch", default=None, metavar="BATCH.json",
        help="serve/batch mode is served by the shm CLI "
        "(python -m kaminpar_tpu --serve-batch); the dist driver "
        "partitions ONE large graph across the mesh per invocation — "
        "this flag exists so the two CLIs stay argument-compatible and "
        "fails with a pointer instead of 'unrecognized argument'",
    )
    p.add_argument(
        "--scheme", default=None, choices=["external"],
        help="the out-of-core streaming scheme is served by the shm CLI "
        "(python -m kaminpar_tpu --scheme external); the dist driver "
        "shards ONE graph across the mesh instead of streaming it — "
        "this flag exists so the two CLIs stay argument-compatible and "
        "fails with a pointer instead of 'unrecognized argument'",
    )
    p.add_argument(
        "--delta-batch", default=None, metavar="DELTAS.json",
        help="dynamic repartitioning (delta chains + warm-started "
        "v-cycle) is served by the shm CLI (python -m kaminpar_tpu "
        "GRAPH -k K --delta-batch DELTAS.json); session graphs are "
        "host-resident CSRs the dist driver does not mutate — "
        "argument-compat flag, fails with a pointer",
    )
    p.add_argument(
        "--dynamic-replicas", type=int, default=None, metavar="G",
        help="the warm-vs-cold replica race belongs to the shm dynamic "
        "CLI (python -m kaminpar_tpu GRAPH -k K --delta-batch ... "
        "--dynamic-replicas G); argument-compat flag, fails with a "
        "pointer",
    )
    p.add_argument(
        "--serve-isolation", default=None,
        choices=["inproc", "process"],
        help="supervised worker execution belongs to the shm serving "
        "CLI (python -m kaminpar_tpu --serve-batch --serve-isolation "
        "process); argument-compat flag, fails with a pointer",
    )
    p.add_argument(
        "--heartbeat-file", default=None, metavar="PATH",
        help="touch PATH's mtime at every dist pipeline barrier (and "
        "from the watchdog tick while nothing is hung) so external "
        "supervisors can tell slow-but-alive from hung — the shm CLI's "
        "flag, honored here too (resilience/supervisor.py)",
    )
    p.add_argument(
        "--metrics-file", default=None, metavar="PATH",
        help="export live metrics (per-phase collective bytes/calls "
        "among them) to PATH in Prometheus text format on a cadence — "
        "the shm CLI's flag, honored here too (also via "
        "KAMINPAR_TPU_METRICS_FILE; telemetry/metrics.py)",
    )
    from . import telemetry

    telemetry.add_cli_args(p)
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.scheme is not None:
        print(
            "error: --scheme external runs on the shm pipeline — use "
            "`python -m kaminpar_tpu GRAPH -k K --scheme external` "
            "(docs/performance.md, out-of-core streaming)",
            file=sys.stderr,
        )
        return 2
    if args.delta_batch is not None or args.dynamic_replicas is not None:
        print(
            "error: dynamic repartitioning runs on the shm pipeline — "
            "use `python -m kaminpar_tpu GRAPH -k K --delta-batch "
            "DELTAS.json [--dynamic-replicas G]` (docs/robustness.md, "
            "dynamic sessions)",
            file=sys.stderr,
        )
        return 2
    if args.serve_batch is not None:
        print(
            "error: serve/batch mode runs on the shm pipeline — use "
            "`python -m kaminpar_tpu --serve-batch BATCH.json` "
            "(docs/robustness.md, serving contract)",
            file=sys.stderr,
        )
        return 2
    if args.serve_isolation is not None:
        print(
            "error: supervised worker isolation is a serving-layer "
            "mode — use `python -m kaminpar_tpu --serve-batch "
            "BATCH.json --serve-isolation process` "
            "(docs/robustness.md, supervision contract)",
            file=sys.stderr,
        )
        return 2
    if args.heartbeat_file:
        from .resilience import supervisor as supervisor_mod

        supervisor_mod.set_heartbeat(args.heartbeat_file)
    from .telemetry import metrics as metrics_mod

    metrics_mod.configure(args.metrics_file)
    if args.graph is None:
        print("error: no graph file given", file=sys.stderr)
        return 1
    if args.k is None:
        print("error: need -k", file=sys.stderr)
        return 1

    t_io = time.perf_counter()
    if args.graph.startswith("gen:"):
        if args.stream_chunks > 0:
            from .io.skagen import hostgraph_from_stream, streamed

            graph = hostgraph_from_stream(
                streamed(args.graph, num_chunks=args.stream_chunks)
            )
        else:
            from .graphs.factories import generate

            graph = generate(args.graph)
    else:
        graph = io_mod.load_graph(args.graph, fmt=args.format)
    io_s = time.perf_counter() - t_io

    if args.validate:
        from .graphs import validate

        validate(graph)

    from . import telemetry
    from .parallel import dKaMinPar, make_mesh

    if args.diff_base and not args.report_json:
        # fail BEFORE the run (cli.py twin): a regression gate that can
        # never fire must not cost a full partition first
        print("error: --diff-base requires --report-json", file=sys.stderr)
        return 2
    telemetry.enable_if_requested(args)
    # fault-plan echo + startup validation (cli.py twin): chaos runs
    # must be unmistakable, and a typo'd plan must fail before the run
    import os as os_mod

    from .resilience import faults as faults_mod

    fault_plan = os_mod.environ.get(faults_mod.ENV_VAR, "")
    if fault_plan:
        try:
            faults_mod.parse_plan(fault_plan)
        except faults_mod.FaultPlanError as e:
            print(f"error: bad {faults_mod.ENV_VAR}: {e}", file=sys.stderr)
            return 1
        if not args.quiet:
            print(
                f"FAULTS plan={fault_plan} (fault injection ACTIVE; "
                "see the report's 'faults' section)"
            )
    if args.resume and not args.checkpoint_dir:
        print("error: --resume requires --checkpoint-dir", file=sys.stderr)
        return 2
    mesh = make_mesh(args.num_devices)
    solver = dKaMinPar(args.preset, mesh=mesh)
    if args.lp_rating is not None:
        solver.ctx.lp_rating = args.lp_rating
    solver.set_graph(graph)
    if args.quiet:
        # instance-scoped: compute_partition applies and restores it
        solver.set_output_level(OutputLevel.QUIET)

    # preemption routing + checkpoint/budget knobs (cli.py twin); the
    # dist driver reads them from the shm resilience context
    from .resilience import deadline as deadline_mod

    deadline_mod.install_signal_handlers()
    res_ctx = solver.ctx.shm.resilience
    if args.checkpoint_dir:
        res_ctx.checkpoint_dir = args.checkpoint_dir
    if args.resume:
        res_ctx.resume = True
    if args.time_budget is not None:
        res_ctx.time_budget = args.time_budget
    if args.budget_grace is not None:
        res_ctx.budget_grace = args.budget_grace
    if args.memory_budget is not None:
        res_ctx.memory_budget = args.memory_budget

    t0 = time.perf_counter()
    try:
        partition = solver.compute_partition(
            k=args.k, epsilon=args.epsilon, seed=args.seed
        )
    except KeyboardInterrupt:
        from .cli import _emergency_interrupt_exit

        return _emergency_interrupt_exit(args, t0)
    wall = time.perf_counter() - t0

    if not args.quiet:
        # the facade logs the single RESULT line (cli.py pattern: the
        # library prints the result, the CLI prints only timings)
        print(f"TIME io={io_s:.3f}s partitioning={wall:.3f}s")
        # cut-loss attribution headline (telemetry/quality.py), printed
        # by the primary process only — same guard as the exporters
        from .telemetry import quality as quality_mod

        if telemetry.is_primary_process():
            quality_line = quality_mod.headline()
            if quality_line:
                print(quality_line)
        if args.timers:
            # dist timer finalize (kaminpar-dist/timer.cc analog):
            # min/avg/max per scope across processes — on one host the
            # three coincide, on a real multi-host mesh they expose
            # imbalance between hosts
            agg = timer.aggregate_across_processes()
            print(timer.render_aggregated(agg))
        if args.machine_timers:
            print("TIMERS " + timer.GLOBAL_TIMER.render_machine())
        if args.comm_table:
            from .parallel.mesh import comm_table

            print(comm_table())

    # non-zero when --diff-base found a regression against the baseline
    # report (telemetry/diff.py); output files are still written below
    rc = telemetry.export_cli_outputs(
        args,
        extra_run={"io_seconds": round(io_s, 3),
                   "partition_seconds": round(wall, 3)},
        quiet=args.quiet,
    )

    if args.output:
        io_mod.write_partition(args.output, partition)
    return rc


if __name__ == "__main__":
    sys.exit(main())
