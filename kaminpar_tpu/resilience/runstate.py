"""The per-run resilience state object (the stream-owning-run gate).

PR 5 kept the checkpoint manager, the suspend counter, and the deadline
budget/stop flags as module globals in ``checkpoint.py`` / ``deadline.py``
— correct for the one-shot CLI process model, but a concurrency hazard
for a multi-request service: two back-to-back runs sharing one process
could consume each other's resume state or stop verdicts, and two
*interleaved* runs (service worker threads) would race on the same
flags outright.

This module moves all of that state onto an explicit :class:`RunState`
object, one per run, held in a ``threading.local`` slot.  The public
function APIs of ``resilience.deadline`` and ``resilience.checkpoint``
are unchanged — every ``should_stop()`` / ``barrier()`` / ``activate()``
call resolves the *current thread's* run state — so the drivers did not
have to change.  What changed structurally:

  * ``deadline.begin_run`` installs a **fresh** RunState instead of
    mutating shared globals: a later run can never observe an earlier
    run's stop verdict, stage bookkeeping, or checkpoint resume state,
    because the earlier run's object is simply no longer reachable.
  * Preemption **signals** (SIGTERM/SIGINT) are process-wide by nature
    and outlive run boundaries, so they live in one lock-guarded
    process-global slot here.  ``should_stop()`` folds it in: every
    run in every thread observes a delivered signal (this is exactly
    the serving layer's drain semantics), while run-local stop reasons
    (budget expiry, ``stop-at`` test hooks, peer agreement) stay
    run-local.  ``clear()`` drops both (test isolation); ``begin_run``
    preserves the signal — a SIGTERM that arrives while the graph is
    still loading must wind down the run that follows (PR-5 contract).
"""

from __future__ import annotations

import threading
from typing import Optional

#: Default DECLARED wind-down grace on top of the budget (see
#: deadline.py, which re-exports it as its own DEFAULT_GRACE_S).
DEFAULT_GRACE_S = 30.0


class RunState:
    """All resilience state owned by ONE run: the armed deadline budget,
    the cooperative stop verdict, the deepest-stage bookkeeping, and the
    active checkpoint manager (+ the nested-run suspend counter)."""

    __slots__ = (
        "budget_s", "grace_s", "t0", "deadline", "stop", "reason",
        "stage", "stage_at_stop", "announced", "manager", "suspend",
        "memory", "dist", "comm",
    )

    def __init__(self) -> None:
        # deadline half (resilience/deadline.py)
        self.budget_s: Optional[float] = None
        self.grace_s: float = DEFAULT_GRACE_S
        self.t0: Optional[float] = None
        self.deadline: Optional[float] = None
        self.stop: bool = False
        self.reason: str = ""
        self.stage: str = ""
        self.stage_at_stop: str = ""
        self.announced: bool = False
        # checkpoint half (resilience/checkpoint.py)
        self.manager = None  # Optional[CheckpointManager]
        self.suspend: int = 0
        # memory-governor half (resilience/memory.py): armed by the
        # facade's begin_run, None while dormant — the barrier pressure
        # hook reads this slot and returns in two attribute lookups
        self.memory = None  # Optional[GovernorState]
        # divergence-sentinel half (resilience/agreement.py): armed
        # only by the stream-owning dist driver, None for shm runs —
        # the barrier audit piggyback reads this slot and returns
        self.dist = None  # Optional[agreement.AuditState]
        # collective-traffic accounting (parallel/mesh.py CommLog):
        # created lazily on the first account_collective/comm_phase
        # touch, so a fresh RunState per run scopes per-request comm
        # attribution for free (the serving layer's isolation fix)
        self.comm = None  # Optional[mesh.CommLog]


_tls = threading.local()

#: Process-wide preemption signal ("sigterm" / "sigint" / "" ).  Set by
#: the signal handlers (and by the serving layer's drain request); read
#: by every run's should_stop().  Deliberately UNLOCKED: signal_stop
#: runs inside a signal handler, where acquiring a mutex the
#: interrupted thread may hold would self-deadlock — single str
#: assignments/reads are atomic under the GIL, and the only writer race
#: (a signal arriving concurrently with a deliberate clear_signal) is
#: an inherently ambiguous ordering either way.
_signal_reason = ""


def current() -> RunState:
    """This thread's run state (created on first touch, so library use
    without an explicit begin_run still has somewhere to keep flags)."""
    run = getattr(_tls, "run", None)
    if run is None:
        run = _tls.run = RunState()
    return run


def begin() -> RunState:
    """Install a FRESH RunState for this thread and return it.  The
    previous run's object (if any) is abandoned unreferenced — its stop
    verdict, stage bookkeeping, checkpoint manager, and resume state are
    structurally unreachable from the new run."""
    run = RunState()
    _tls.run = run
    return run


def signal_stop(reason: str) -> None:
    """Record a process-wide preemption signal (async-signal-safe: one
    assignment).  Every run in every thread observes it."""
    global _signal_reason
    if not _signal_reason:
        _signal_reason = reason


def signal_reason() -> str:
    """The pending process-wide preemption reason ("" when none)."""
    return _signal_reason


def clear_signal() -> None:
    """Drop the process-wide signal flag (tests; deadline.clear)."""
    global _signal_reason
    _signal_reason = ""
