"""Structured degradation exceptions — the failure vocabulary of the
pipeline's optional fast paths.

Every optional accelerator path (native FM/IP via the C-API, routed
lane-gather plans, compressed-graph streaming, device balancers,
distributed collectives) can refuse, crash, or time out.  Instead of a
bare ``except Exception`` at each call site (a tpulint-documented hazard,
docs/static_analysis.md), failures are raised as one of these types and
routed through :func:`kaminpar_tpu.resilience.with_fallback`, which pairs
each registered *site* with its documented fallback and emits a
``degraded`` telemetry event (docs/robustness.md has the full matrix).

The hierarchy is deliberately flat: callers either handle
:class:`DegradationError` (the policy wrapper) or a specific subtype
(tests, site-local handling).  ``injected=True`` marks exceptions raised
by the fault-injection harness (``KAMINPAR_TPU_FAULTS``) so chaos tests
can tell simulated failures from real ones in the telemetry stream.
"""

from __future__ import annotations

from typing import Optional


class DegradationError(RuntimeError):
    """Base of all structured fast-path failures.

    Attributes:
      site      registered fault-site name ("" until the policy wrapper
                stamps it)
      injected  True when raised by the fault-injection harness

    Class attribute ``breaker_relevant``: whether failures of this type
    advance the site's circuit breaker.  Crash-shaped failures (missing
    native lib, OOM, timeout) do; deterministic data-dependent REFUSALS
    (plan blowup on a skewed level, FM refusing a too-large k) do not —
    a legitimate refusal on one input must not disable the fast path
    for the next input.
    """

    breaker_relevant = True

    def __init__(
        self,
        message: str = "",
        *,
        site: Optional[str] = None,
        injected: bool = False,
    ) -> None:
        super().__init__(message or type(self).__name__)
        self.site = site or ""
        self.injected = bool(injected)


class NativeUnavailable(DegradationError):
    """The native (C++/ctypes) component could not be built, loaded, or
    run — missing toolchain, build timeout, or a corrupted build cache.
    Fallback: the pure-numpy/ctypes-free twin of the same entry point."""


class PlanBlowup(DegradationError):
    """A routed lane-gather plan would exceed its slot budget (one
    high-degree hub inflating H*128 past PLAN_MAX_SLOT_RATIO * m).
    Fallback: the plain XLA gather.  A refusal, not a fault: does not
    advance the circuit breaker."""

    breaker_relevant = False


class RefinerRefused(DegradationError):
    """A refiner declined to run at the current (n, k) — e.g. native FM's
    INT64_MIN sentinel when k exceeds the sparse engine's 16-bit packed
    tags and the dense (n, k) table is unaffordable.  Fallback: return
    the partition unchanged (refusal, not failure: no moves were made).
    Does not advance the circuit breaker."""

    breaker_relevant = False


class CollectiveTimeout(DegradationError):
    """A cross-process collective (timer aggregation, metric allgather)
    timed out or failed.  Fallback: continue with local-only data."""


class CheckpointWriteFailed(DegradationError):
    """A checkpoint snapshot or manifest could not be written (disk full,
    permissions, injected fault).  Fallback: the run continues with
    in-memory-only checkpoints — losing durability, never the run."""


class CheckpointCorrupt(DegradationError):
    """A checkpoint snapshot failed its content checksum (truncated or
    bit-rotted file) or the manifest would not parse.  Fallback: the
    previous manifest generation.  A property of stored data, not of the
    process: does not advance the circuit breaker."""

    breaker_relevant = False


class CheckpointMismatch(DegradationError):
    """A checkpoint exists but belongs to a different run: the graph
    fingerprint or the context fingerprint recorded in the manifest does
    not match the current invocation.  Policy: clean restart (ignore the
    checkpoint), never a crash and never a silent resume of foreign
    state.  A refusal, not a fault: does not advance the breaker."""

    breaker_relevant = False


class AdmissionRejected(DegradationError):
    """The serving layer's admission controller refused a request —
    queue depth or estimated-cost cap exceeded, a draining service, an
    open per-request-class breaker, or an injected `serving-admit`
    fault.  Fallback: a structured `rejected` verdict for that request;
    the service keeps serving.  A policy decision, not a fault: does
    not advance the circuit breaker."""

    breaker_relevant = False


class CacheDegraded(DegradationError):
    """A bounded-cache lookup was forced to miss (or an entry forcibly
    evicted) — today only via the `serving-cache` injection site; a
    future persistent cache backend would surface real read failures
    the same way.  Fallback: recompute the request.  Correctness is
    untouched (caches are an optimization), so the breaker ignores it.
    """

    breaker_relevant = False


class DeltaApplyFailed(DegradationError):
    """The in-place CSR delta-apply of a dynamic graph session failed —
    today only via the `dynamic-apply` injection site; a real failure
    class would be a patched bucket disagreeing with the device arrays.
    Fallback: the session rebuilds the CSR and re-uploads into a fresh
    bucket (the bucket-crossing path) — strictly more work, never a
    wrong graph, so the breaker ignores it."""

    breaker_relevant = False


class RankDivergence(DegradationError):
    """The cross-rank divergence sentinel fired: at a dist pipeline
    barrier the ranks disagreed on the stage id, the memory-ladder rung,
    or the run fingerprint (graph/ctx/sharding plan) — one rank silently
    skipped a barrier, took a different recovery path, or is running a
    different problem.  There is no safe local fallback (continuing
    would deadlock a collective or return a wrong answer), so this is a
    structured ABORT carrying ``ranks``, the per-rank state dump the
    sentinel gathered (also annotated into the run report's
    ``dist_resilience`` section before the raise).  Crash-shaped: it
    advances the circuit breaker."""

    def __init__(
        self,
        message: str = "",
        *,
        ranks=None,
        site: Optional[str] = None,
        injected: bool = False,
    ) -> None:
        super().__init__(message, site=site, injected=injected)
        self.ranks = list(ranks or [])


class StageHang(DegradationError):
    """A pipeline stage exceeded its HARD wall-clock ceiling
    (resilience/supervisor.py): a hung backend init, a hung device
    launch, or a supervised worker that stopped answering.  The
    cooperative deadline budget cannot interrupt these — it is checked
    between launches — so the watchdog converts them into this
    structured, breaker-relevant failure instead of an eternal block.

    ``stage`` is the armed stage name, ``scope_path`` the (best-effort)
    dotted timer-scope path that was open when the ceiling expired —
    i.e. where the run was stuck — and ``ceiling_s`` the ceiling that
    was exceeded.  Raised with site ``worker-hang`` by the worker
    supervisor's SIGKILL path; async-delivered (no site) by the
    in-process watchdog.  Crash-shaped: it advances the breaker."""

    def __init__(
        self,
        message: str = "",
        *,
        stage: str = "",
        scope_path: str = "",
        ceiling_s: Optional[float] = None,
        site: Optional[str] = None,
        injected: bool = False,
    ) -> None:
        super().__init__(message, site=site, injected=injected)
        self.stage = stage
        self.scope_path = scope_path
        self.ceiling_s = ceiling_s


class IntegrityViolation(DegradationError):
    """An integrity sentinel or exchange digest detected silent data
    corruption (resilience/integrity.py): a conservation invariant
    broken across a contraction, a partition vector out of range, an
    accepted refinement pass that *increased* the cut, a content digest
    that no longer matches its bytes (spill re-read, worker reply,
    cached result), or a sampled re-execution audit that disagreed with
    the device bitwise.

    ``invariant`` names the violated check (the degradation-matrix row),
    ``level`` the hierarchy level it fired at (None outside the
    multilevel drivers), ``scope_path`` the phase boundary.  NEVER
    absorbed by ``policy.with_fallback`` — a corrupted value has no
    documented fallback twin; the only safe responses are the bounded
    retry-from-last-good-barrier ladder (integrity.run_with_retry) or,
    for exchange digests, a re-fetch from the source of truth.
    Crash-shaped: it advances the circuit breaker."""

    def __init__(
        self,
        message: str = "",
        *,
        invariant: str = "",
        level: Optional[int] = None,
        scope_path: str = "",
        site: Optional[str] = None,
        injected: bool = False,
    ) -> None:
        super().__init__(message, site=site, injected=injected)
        self.invariant = invariant
        self.level = level
        self.scope_path = scope_path


class WorkerCrash(DegradationError):
    """A supervised worker subprocess died — segfault in the native
    library, allocator kill, or an injected SIGKILL (the
    ``worker-crash`` chaos site).  The supervisor detects the death,
    surfaces it as this structured failure for that request alone, and
    keeps draining the queue with a fresh worker.  ``exit_code`` is the
    subprocess's exit code (negative = killed by that signal).
    Crash-shaped: it advances the breaker."""

    #: Exit code of the dead worker (None when it could not be read).
    exit_code: Optional[int] = None


class DeviceOOM(DegradationError):
    """The accelerator (or host, for MemoryError) ran out of memory in an
    optional fast path.  Fallback: the path's smaller-footprint twin
    (host balancer, uncompressed CSR, XLA gather) — and, anywhere under
    ``compute_partition``, the memory governor's recovery ladder
    (resilience/memory.py): the run retries at the next rung instead of
    surfacing RESOURCE_EXHAUSTED.

    ``rungs_exhausted`` is stamped True by the ladder only when every
    rung (including the host-only path) failed — THAT is the
    crash-shaped verdict the serving per-class breaker may latch on; a
    ladder-retryable OOM never escapes the facade, so it can never latch
    anything (the serving boundary additionally refuses to count a
    ``rungs_exhausted=False`` OOM as a crash — the belt-and-braces for a
    governor-disabled process)."""

    #: True only when the recovery ladder ran out of rungs (set by
    #: resilience/memory.py); a plain DeviceOOM is ladder-retryable.
    rungs_exhausted = False


#: Raw-exception markers that classify as DeviceOOM.  XLA surfaces
#: allocator failure as XlaRuntimeError("RESOURCE_EXHAUSTED: ...").
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory")


def classify(exc: BaseException, site: str) -> Optional[DegradationError]:
    """Map a raw exception to a structured degradation, or None.

    * DegradationError passes through (site stamped if missing);
    * MemoryError and XLA RESOURCE_EXHAUSTED become :class:`DeviceOOM`;
    * anything else returns None — the caller must re-raise, NOT swallow
      (an unclassified exception is a bug, not a degradation).
    """
    if isinstance(exc, DegradationError):
        if not exc.site:
            exc.site = site
        return exc
    if isinstance(exc, MemoryError):
        err = DeviceOOM(f"host allocation failed: {exc}", site=site)
        err.__cause__ = exc
        return err
    text = f"{type(exc).__name__}: {exc}"
    if any(marker in text for marker in _OOM_MARKERS):
        err = DeviceOOM(text, site=site)
        err.__cause__ = exc
        return err
    return None
