"""Hang and crash containment: watchdog, heartbeats, supervised workers.

The cooperative deadline budget (resilience/deadline.py) is checked
*between* kernel launches — a hung XLA launch, a hung backend init (the
documented 600 s axon-tunnel class, utils/platform.py), or a segfault
inside the native library never returns control to the barrier that
would have noticed.  This module is the containment layer for exactly
that failure class, in three pieces:

  * **hard wall-clock watchdog** — a single daemon thread holding a
    schedule of *armed stages* (:func:`stage_guard`).  A stage that
    exceeds its hard ceiling is converted into a structured
    :class:`~kaminpar_tpu.resilience.errors.StageHang` carrying the
    stuck timer-scope path: the hang record lands in telemetry + the
    run report, and a ``StageHang`` is async-delivered into the armed
    thread (``PyThreadState_SetAsyncExc``).  Honest limitation: the
    async raise lands at the next *bytecode* boundary — a thread stuck
    inside a C call (a hung device launch) is detected and reported
    (and the heartbeat stalls, below) but cannot be unwound in-process;
    true hard containment is the worker mode;

  * **supervised worker execution** — :class:`WorkerPool` runs compute
    in a spawned, warm-reusable worker subprocess (graph/result
    exchange via the io/snapshot.py npz idiom).  A worker that hangs
    past its ceiling is SIGKILLed by the supervisor and surfaces as a
    structured ``StageHang`` (site ``worker-hang``); a worker that dies
    (segfault, OOM kill, injected SIGKILL) surfaces as
    :class:`~kaminpar_tpu.resilience.errors.WorkerCrash` — in both
    cases the parent keeps draining its queue.  Workers are recycled
    after N requests or past an RSS watermark (leak containment), and
    *classified* in-worker failures (a ladder-retryable DeviceOOM, a
    refiner refusal) are marshalled back and re-raised as their own
    types, so the serving breaker sees exactly the verdicts it would
    have seen in-process;

  * **liveness heartbeats** — ``--heartbeat-file`` (or
    ``KAMINPAR_TPU_HEARTBEAT_FILE``) names a file whose mtime advances
    from the checkpoint-barrier hook and from the watchdog tick *while
    no armed stage has exceeded its ceiling*.  External supervisors
    (k8s liveness probes, systemd ``WatchdogSec``) can therefore tell
    slow-but-alive (mtime advances) from hung (mtime frozen) without
    parsing any output.

Hard-ceiling resolution (:func:`hard_ceiling`): the env override
``KAMINPAR_TPU_HARD_DEADLINE_S`` wins; otherwise a run with a
cooperative budget gets ``max(factor * budget, budget + grace)`` —
the ``budget + grace`` floor keeps a tight anytime budget (say 50 ms)
from arming a ceiling shorter than its own legitimate wind-down tail.
No budget and no env means no ceiling: hang containment is opt-in.

Everything here is host-side: no jax at module import, zero device
work, and a disabled configuration costs one attribute read per hook.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional

ENV_HARD_DEADLINE_S = "KAMINPAR_TPU_HARD_DEADLINE_S"
ENV_HEARTBEAT_FILE = "KAMINPAR_TPU_HEARTBEAT_FILE"

#: Default multiple of the cooperative budget that arms the hard
#: ceiling (ctx.resilience.hard_deadline_factor / ServiceConfig).
DEFAULT_HARD_FACTOR = 10.0

#: Declared wind-down allowance folded into the derived ceiling (the
#: deadline module's advisory grace — the mandatory tail must fit
#: under the hard ceiling or a slow-but-legitimate wind-down would be
#: classified as a hang).
from .runstate import DEFAULT_GRACE_S

#: How long the supervisor waits past a worker's hard ceiling before
#: SIGKILL — the child's own watchdog gets this window to convert a
#: python-level hang into a graceful marshalled StageHang first.
def _kill_grace(ceiling_s: float) -> float:
    return max(1.0, 0.25 * ceiling_s)


#: Worker spawn handshake budget: interpreter start + package import.
WORKER_SPAWN_TIMEOUT_S = 120.0

#: Watchdog tick while stages are armed (also the heartbeat cadence
#: while idle-but-configured).
_TICK_S = 0.2
_IDLE_TICK_S = 1.0


def env_ceiling() -> Optional[float]:
    """The explicit env hard ceiling (None = unset/disabled)."""
    raw = os.environ.get(ENV_HARD_DEADLINE_S, "").strip()
    if not raw:
        return None
    try:
        val = float(raw)
    except ValueError:
        return None
    return val if val > 0 else None


def hard_ceiling(
    budget_s: Optional[float],
    grace_s: Optional[float] = None,
    factor: Optional[float] = None,
) -> Optional[float]:
    """Resolve the hard wall-clock ceiling for a run (None = no
    ceiling).  Env override first; else derived from the cooperative
    budget as ``max(factor * budget, budget + grace)``."""
    env = env_ceiling()
    if env is not None:
        return env
    budget = float(budget_s or 0.0)
    f = DEFAULT_HARD_FACTOR if factor is None else float(factor)
    if budget <= 0 or f <= 0:
        return None
    grace = DEFAULT_GRACE_S if grace_s is None else float(grace_s)
    return max(f * budget, budget + grace)


# ---------------------------------------------------------------------------
# heartbeat
# ---------------------------------------------------------------------------

_hb_lock = threading.Lock()
_hb_path: Optional[str] = None
_hb_count = 0


def set_heartbeat(path: Optional[str]) -> None:
    """Configure (or clear, with None/"") the liveness heartbeat file.
    Called by the CLIs (``--heartbeat-file``) and the serving config;
    the env var is folded in lazily by :func:`heartbeat_path`."""
    global _hb_path
    with _hb_lock:
        _hb_path = path or None
    if _hb_path:
        wd = _watchdog()
        wd.ensure_running()
        with wd._cond:
            wd._cond.notify()  # wake a parked tick loop
        heartbeat_touch()


def heartbeat_path() -> Optional[str]:
    with _hb_lock:
        if _hb_path:
            return _hb_path
    env = os.environ.get(ENV_HEARTBEAT_FILE, "").strip()
    if env:
        set_heartbeat(env)
        return env
    return None


def heartbeat_touch() -> None:
    """Advance the heartbeat file's mtime (one attribute read when no
    file is configured).  Strictly-increasing nanosecond stamps, so
    external ``stat`` pollers never see a frozen mtime from two touches
    inside one clock granule."""
    global _hb_count
    path = _hb_path or heartbeat_path()
    if not path:
        return
    try:
        if not os.path.exists(path):
            with open(path, "a"):
                pass
        now = time.time_ns()
        os.utime(path, ns=(now, now))
    except OSError:
        return
    with _hb_lock:
        _hb_count += 1


def heartbeat_state() -> Dict[str, Any]:
    with _hb_lock:
        return {"file": _hb_path, "count": int(_hb_count)}


# ---------------------------------------------------------------------------
# the watchdog
# ---------------------------------------------------------------------------


class _Armed:
    __slots__ = ("token", "stage", "deadline", "ceiling_s", "thread_id",
                 "interrupt", "notify", "expired")

    def __init__(self, token, stage, deadline, ceiling_s, thread_id,
                 interrupt, notify):
        self.token = token
        self.stage = stage
        self.deadline = deadline
        self.ceiling_s = ceiling_s
        self.thread_id = thread_id
        self.interrupt = interrupt
        self.notify = notify
        self.expired = False


def _scope_path() -> str:
    """Best-effort dotted path of the currently open timer scopes (the
    'where is it stuck' attachment on a hang record).  Read racily from
    the watchdog thread — the armed thread is by definition not making
    progress when this matters."""
    try:
        from ..utils import timer

        return ".".join(n.name for n in timer.GLOBAL_TIMER._stack[1:])
    except Exception:
        return ""


def _async_raise(thread_id: int, exc_class) -> bool:
    """Deliver ``exc_class`` into the thread (next bytecode boundary)."""
    import ctypes

    try:
        res = ctypes.pythonapi.PyThreadState_SetAsyncExc(
            ctypes.c_ulong(thread_id), ctypes.py_object(exc_class)
        )
        if res > 1:  # undocumented multi-thread hit: undo, stay safe
            ctypes.pythonapi.PyThreadState_SetAsyncExc(
                ctypes.c_ulong(thread_id), None
            )
            return False
        return res == 1
    except Exception:
        return False


class Watchdog:
    """One daemon thread, a schedule of armed stages, a hang log."""

    def __init__(self) -> None:
        self._cond = threading.Condition()
        self._armed: Dict[int, _Armed] = {}
        self._next_token = 1
        self._thread: Optional[threading.Thread] = None
        self.armed_total = 0
        self.fired = 0
        self.hangs: List[dict] = []

    # -- arming --------------------------------------------------------

    def arm(self, stage: str, ceiling_s: float, *,
            thread_id: Optional[int] = None, interrupt: bool = True,
            notify=None) -> int:
        with self._cond:
            token = self._next_token
            self._next_token += 1
            self._armed[token] = _Armed(
                token, stage, time.monotonic() + float(ceiling_s),
                float(ceiling_s),
                thread_id if thread_id is not None
                else threading.get_ident(),
                interrupt, notify,
            )
            self.armed_total += 1
            self._cond.notify()
        self.ensure_running()
        return token

    def disarm(self, token: int) -> None:
        with self._cond:
            self._armed.pop(token, None)
            self._cond.notify()

    def ensure_running(self) -> None:
        with self._cond:
            if self._thread is not None and self._thread.is_alive():
                return
            self._thread = threading.Thread(
                target=self._run, name="kmp-watchdog", daemon=True
            )
            self._thread.start()

    # -- the tick loop -------------------------------------------------

    def _run(self) -> None:
        while True:
            with self._cond:
                armed = list(self._armed.values())
                if not armed and not (_hb_path or heartbeat_path()):
                    self._cond.wait()
                    continue
            now = time.monotonic()
            hung = False
            for a in armed:
                if a.expired:
                    hung = True
                elif now >= a.deadline:
                    a.expired = True
                    hung = True
                    self._expire(a)
            if not hung:
                # slow-but-alive: the heartbeat keeps advancing; a stage
                # past its ceiling freezes it, which is the external
                # supervisor's signal to act
                heartbeat_touch()
            with self._cond:
                self._cond.wait(_TICK_S if self._armed else _IDLE_TICK_S)

    def _expire(self, a: _Armed) -> None:
        # recheck membership under the lock: the stage may have
        # finished (and disarmed) between the tick loop's snapshot and
        # now — async-raising into a thread whose stage completed would
        # poison unrelated later code with a spurious StageHang
        with self._cond:
            if a.token not in self._armed:
                return
        self.fired += 1
        path = _scope_path()
        record = {
            "stage": a.stage,
            "path": path,
            "ceiling_s": round(a.ceiling_s, 3),
        }
        self.hangs.append(record)
        try:
            from .. import telemetry

            telemetry.event("stage-hang", **record)
        except Exception:
            pass
        try:
            from ..utils.logger import log_warning

            log_warning(
                f"watchdog: stage '{a.stage}' exceeded its hard ceiling "
                f"({a.ceiling_s:.1f} s) at scope '{path or '?'}' — "
                "raising StageHang"
                + ("" if a.interrupt else " (record only)")
            )
        except Exception:
            pass
        if a.notify is not None:
            try:
                a.notify({"type": "hang", "stage": a.stage, "path": path,
                          "ceiling_s": a.ceiling_s})
            except Exception:
                pass
        if a.interrupt:
            from .errors import StageHang

            with self._cond:
                if a.token not in self._armed:
                    return  # disarmed while we were recording
            _async_raise(a.thread_id, StageHang)


_wd: Optional[Watchdog] = None
_wd_lock = threading.Lock()


def _watchdog() -> Watchdog:
    global _wd
    with _wd_lock:
        if _wd is None:
            _wd = Watchdog()
        return _wd


class stage_guard:
    """Context manager arming the watchdog for one stage.  A None/zero
    ceiling is a complete no-op; on exit the stage is disarmed.  A
    ``StageHang`` that fired for THIS stage is enriched with the stage
    name / scope path / ceiling when it passes through."""

    def __init__(self, stage: str, ceiling_s: Optional[float], *,
                 interrupt: bool = True, notify=None) -> None:
        self.stage = stage
        self.ceiling_s = ceiling_s
        self.interrupt = interrupt
        self.notify = notify
        self._token: Optional[int] = None

    def __enter__(self):
        if self.ceiling_s and self.ceiling_s > 0:
            self._token = _watchdog().arm(
                self.stage, self.ceiling_s,
                interrupt=self.interrupt, notify=self.notify,
            )
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._token is None:
            # never armed (no ceiling): a StageHang passing through
            # belongs to some other guard — don't enrich it
            return False
        _watchdog().disarm(self._token)
        from .errors import StageHang

        if exc is not None and isinstance(exc, StageHang):
            if not exc.stage:
                exc.stage = self.stage
            if exc.ceiling_s is None:
                exc.ceiling_s = self.ceiling_s
            if not exc.scope_path:
                for rec in reversed(_watchdog().hangs):
                    if rec["stage"] == self.stage:
                        exc.scope_path = rec.get("path", "")
                        break
            if (not exc.args or not exc.args[0]
                    or exc.args[0] == type(exc).__name__):
                exc.args = (
                    f"stage '{self.stage}' exceeded its hard wall-clock "
                    f"ceiling ({self.ceiling_s}s) at scope "
                    f"'{exc.scope_path or '?'}'",
                )
        return False


def watchdog_stats() -> Dict[str, Any]:
    wd = _watchdog()
    return {"armed": int(wd.armed_total), "fired": int(wd.fired)}


def hang_log() -> List[dict]:
    return list(_watchdog().hangs)


def record_hang(record: dict) -> None:
    """Append an externally observed hang (the worker supervisor's
    SIGKILL path) to the same log the in-process watchdog writes."""
    wd = _watchdog()
    wd.fired += 1
    wd.hangs.append(dict(record))


# ---------------------------------------------------------------------------
# supervised workers
# ---------------------------------------------------------------------------


class _WorkerHandle:
    def __init__(self, proc, conn) -> None:
        self.proc = proc
        self.conn = conn
        self.requests = 0
        self.rss_bytes = 0


class WorkerPool:
    """Spawned, warm-reusable compute workers for the serving layer.

    The execution model mirrors the service's (serial), so the pool
    holds ONE live worker and respawns it on death/recycle — the
    supervision structure (kill on hang, classify on crash, recycle on
    leak) is the point, not parallelism."""

    def __init__(self, max_requests: int = 32,
                 rss_limit_bytes: int = 4 << 30,
                 spool_dir: Optional[str] = None) -> None:
        import tempfile

        self.max_requests = int(max_requests)
        self.rss_limit_bytes = int(rss_limit_bytes)
        self._own_spool = spool_dir is None
        self._spool = spool_dir or tempfile.mkdtemp(prefix="kmp-workers-")
        self._worker: Optional[_WorkerHandle] = None
        self.stats = {"spawned": 0, "recycled": 0, "killed": 0,
                      "crashed": 0, "requests": 0}

    # -- lifecycle -----------------------------------------------------

    def _spawn(self) -> _WorkerHandle:
        import multiprocessing as mp

        ctx = mp.get_context("spawn")
        parent_conn, child_conn = ctx.Pipe(duplex=True)
        proc = ctx.Process(
            target=_worker_entry, args=(child_conn, self._spool),
            name="kmp-worker", daemon=True,
        )
        proc.start()
        child_conn.close()
        handle = _WorkerHandle(proc, parent_conn)
        self.stats["spawned"] += 1
        from .errors import WorkerCrash

        try:
            if not parent_conn.poll(WORKER_SPAWN_TIMEOUT_S):
                raise EOFError("spawn handshake timeout")
            ready = parent_conn.recv()
            if not isinstance(ready, dict) or ready.get("type") != "ready":
                raise EOFError(f"bad handshake message: {ready!r}")
        except (EOFError, OSError) as e:
            proc.kill()
            proc.join(5)
            self.stats["crashed"] += 1
            raise WorkerCrash(
                f"worker pid {proc.pid} failed its spawn handshake "
                f"({e}; exit code {proc.exitcode})", site="worker-crash",
            ) from e
        _event("spawn", pid=proc.pid)
        return handle

    def _ensure_worker(self) -> _WorkerHandle:
        if self._worker is not None and self._worker.proc.is_alive():
            return self._worker
        self._worker = self._spawn()
        return self._worker

    def _drop_worker(self, *, kill: bool) -> None:
        w = self._worker
        self._worker = None
        if w is None:
            return
        try:
            if kill:
                w.proc.kill()
            elif w.proc.is_alive():
                try:
                    w.conn.send({"type": "exit"})
                except (OSError, ValueError, BrokenPipeError):
                    w.proc.terminate()
            w.proc.join(5)
            if w.proc.is_alive():
                w.proc.kill()
                w.proc.join(5)
        finally:
            try:
                w.conn.close()
            except OSError:
                pass

    def shutdown(self) -> None:
        self._drop_worker(kill=False)
        if self._own_spool:
            import shutil

            shutil.rmtree(self._spool, ignore_errors=True)

    # -- request path --------------------------------------------------

    def run_request(self, request_id: str, source, graph, ctx,
                    k: int, epsilon: float, seed: Optional[int],
                    ceiling_s: Optional[float], trace: bool = False):
        """Run one request in the supervised worker.  Returns
        ``(partition ndarray, info dict)``; raises StageHang (site
        ``worker-hang``) on a hang-kill, WorkerCrash on a worker death,
        and the *re-raised classified type* for marshalled in-worker
        failures (a ladder-retryable DeviceOOM stays a retryable
        DeviceOOM — it must never read as a crash).  With ``trace``
        set, the worker marshals its depth-1 telemetry spans back as
        ``trace_spans`` rows on the result (telemetry/tracing.py's
        worker-boundary contract)."""
        from . import faults
        from .errors import StageHang, WorkerCrash

        # chaos directives (parent-side counters: deterministic across
        # worker respawns): an injected fault at these sites makes the
        # CHILD genuinely hang/die — the supervisor machinery is what
        # is under test, so the failure must be real
        chaos = None
        try:
            faults.maybe_inject("worker-hang")
        except StageHang:
            chaos = "hang"
        try:
            faults.maybe_inject("worker-crash")
        except WorkerCrash:
            chaos = chaos or "crash"
        if chaos == "hang" and not ceiling_s:
            # no hard ceiling means the supervisor would wait forever —
            # a chaos-plan typo must fail the request fast, not hang CI
            raise StageHang(
                f"injected worker-hang for request {request_id}, but no "
                "hard ceiling is armed (set hard_deadline_s / "
                f"{ENV_HARD_DEADLINE_S}) — failing fast instead of "
                "hanging the supervisor", site="worker-hang",
                injected=True,
            )

        worker = self._ensure_worker()
        result_path = os.path.join(self._spool, f"{request_id}-part.npz")
        ship_path: Optional[str] = None
        if isinstance(source, str):
            graph_ref = {"kind": "source", "value": source}
        else:
            ship_path = self._ship_graph(request_id, graph)
            graph_ref = {"kind": "npz", "value": ship_path}
        from ..context import context_to_dict

        try:
            try:
                worker.conn.send({
                    "type": "request",
                    "id": request_id,
                    "graph": graph_ref,
                    "ctx": context_to_dict(ctx),
                    "k": int(k),
                    "epsilon": float(epsilon),
                    "seed": int(seed) if seed is not None else None,
                    "ceiling_s": float(ceiling_s) if ceiling_s else None,
                    "chaos": chaos,
                    "result_path": result_path,
                    "trace": bool(trace),
                })
            except (OSError, ValueError, BrokenPipeError):
                # the worker died between the liveness check and the send
                return self._crash(worker, request_id)
            t0 = time.monotonic()
            kill_after = (
                ceiling_s + _kill_grace(ceiling_s) if ceiling_s else None
            )
            hang_note: Optional[dict] = None
            while True:
                try:
                    has_msg = worker.conn.poll(_TICK_S)
                except (OSError, EOFError):
                    return self._crash(worker, request_id)
                if has_msg:
                    try:
                        reply = worker.conn.recv()
                    except (EOFError, OSError):
                        return self._crash(worker, request_id)
                    kind = reply.get("type")
                    if kind == "hang":
                        # child watchdog noticed; wait for its graceful
                        # in-child raise until kill_after
                        hang_note = reply
                        continue
                    if kind == "result":
                        return self._finish(worker, request_id, reply)
                    if kind == "error":
                        self.stats["requests"] += 1
                        worker.requests += 1
                        if reply.get("error") == "StageHang":
                            # the child's OWN watchdog converted the
                            # hang gracefully (async raise landed) —
                            # the worker survives, but the hang still
                            # goes on record
                            record_hang({
                                "stage": reply.get("stage")
                                or "worker-compute",
                                "path": reply.get("scope_path", ""),
                                "ceiling_s": reply.get("ceiling_s"),
                                "request": request_id,
                                "worker_pid": worker.proc.pid,
                            })
                        self._maybe_recycle(worker)
                        heartbeat_touch()
                        _raise_marshalled(reply)
                    continue  # unknown message kinds are skipped
                if not worker.proc.is_alive():
                    return self._crash(worker, request_id)
                if (
                    kill_after is not None
                    and time.monotonic() - t0 > kill_after
                ):
                    return self._hang_kill(
                        worker, request_id, ceiling_s, hang_note
                    )
        finally:
            # the shipped graph npz is per-request scratch: every exit
            # path (result, crash, hang-kill, marshalled re-raise) is
            # done with it here — a long-lived service must not leak a
            # CSR copy to the spool per request
            if ship_path is not None:
                try:
                    os.unlink(ship_path)
                except OSError:
                    pass

    def _ship_graph(self, request_id: str, graph) -> str:
        import numpy as np

        from ..io.snapshot import write_snapshot

        if not (hasattr(graph, "xadj") and hasattr(graph, "adjncy")):
            # compressed containers / streamed spec wrappers arrive as
            # path/spec strings through the serving layer and take the
            # source branch; a bare exotic object has no cheap exchange
            # format — fail the request with an input-shaped error
            raise ValueError(
                "process isolation needs a CSR graph object or a "
                f"path/spec string, got {type(graph).__name__}"
            )
        arrays = {
            "xadj": np.asarray(graph.xadj),
            "adjncy": np.asarray(graph.adjncy),
        }
        if getattr(graph, "node_weights", None) is not None:
            arrays["node_weights"] = np.asarray(graph.node_weights)
        if getattr(graph, "edge_weights", None) is not None:
            arrays["edge_weights"] = np.asarray(graph.edge_weights)
        path = os.path.join(self._spool, f"{request_id}-graph.npz")
        write_snapshot(path, arrays)
        return path

    def _finish(self, worker: _WorkerHandle, request_id: str, reply: dict):
        import numpy as np

        from ..io.snapshot import SnapshotError, read_snapshot
        from . import integrity

        # `worker-reply-corrupt` chaos mutates the spool file after the
        # worker wrote it; the digest the reply carries is what the
        # parent-side verification catches it with.  A mismatch is a
        # classified IntegrityViolation (`corrupt-result` taxonomy at
        # the serving layer), NOT malformed-input — the worker finished
        # cleanly, the bytes rotted in the exchange.
        integrity.chaos_flip_file("worker-reply-corrupt", reply["path"])
        expect = (
            reply.get("sha256") if integrity.enabled() else None
        )
        try:
            part = np.asarray(
                read_snapshot(reply["path"], expect)["partition"],
                dtype=np.int32,
            )
        except (SnapshotError, ValueError) as exc:
            # keep the worker bookkeeping honest before propagating:
            # the worker itself behaved, only the reply bytes are bad
            try:
                os.unlink(reply["path"])
            except OSError:
                pass
            worker.requests += 1
            self.stats["requests"] += 1
            self._maybe_recycle(worker)
            heartbeat_touch()
            raise integrity.note_digest_mismatch(
                f"worker-reply:{request_id}", str(exc),
                site="worker-reply-corrupt",
            ) from exc
        try:
            os.unlink(reply["path"])
        except OSError:
            pass
        worker.requests += 1
        worker.rss_bytes = int(reply.get("rss_bytes") or 0)
        self.stats["requests"] += 1
        self._maybe_recycle(worker)
        heartbeat_touch()
        return part, reply

    def _maybe_recycle(self, worker: _WorkerHandle) -> None:
        over_count = worker.requests >= self.max_requests
        over_rss = (
            self.rss_limit_bytes > 0
            and worker.rss_bytes > self.rss_limit_bytes
        )
        if not (over_count or over_rss):
            return
        self.stats["recycled"] += 1
        _event(
            "recycle", pid=worker.proc.pid, requests=worker.requests,
            rss_bytes=worker.rss_bytes,
            reason="rss-watermark" if over_rss else "max-requests",
        )
        self._drop_worker(kill=False)

    def _crash(self, worker: _WorkerHandle, request_id: str):
        from .errors import WorkerCrash

        pid = worker.proc.pid
        worker.proc.join(5)
        code = worker.proc.exitcode
        self._drop_worker(kill=True)
        self.stats["crashed"] += 1
        self.stats["requests"] += 1
        _event("crash", pid=pid, exit_code=code, request=request_id)
        heartbeat_touch()
        exc = WorkerCrash(
            f"worker pid {pid} died (exit code {code}) serving request "
            f"{request_id}", site="worker-crash",
        )
        exc.exit_code = code
        raise exc

    def _hang_kill(self, worker: _WorkerHandle, request_id: str,
                   ceiling_s: float, hang_note: Optional[dict]):
        from .errors import StageHang

        pid = worker.proc.pid
        path = (hang_note or {}).get("path", "")
        stage = (hang_note or {}).get("stage", "worker-compute")
        self._drop_worker(kill=True)
        self.stats["killed"] += 1
        self.stats["requests"] += 1
        record = {
            "stage": stage, "path": path,
            "ceiling_s": round(float(ceiling_s), 3),
            "request": request_id, "worker_pid": pid,
        }
        record_hang(record)
        _event("hang-kill", **record)
        heartbeat_touch()
        exc = StageHang(
            f"worker pid {pid} exceeded the hard wall-clock ceiling "
            f"({ceiling_s}s) serving request {request_id} "
            f"(stuck at '{path or stage}'); SIGKILLed",
            site="worker-hang", stage=stage, scope_path=path,
            ceiling_s=float(ceiling_s),
        )
        raise exc


def _event(action: str, **attrs) -> None:
    try:
        from .. import telemetry

        telemetry.event("supervision", action=action, **attrs)
    except Exception:
        pass


def _raise_marshalled(reply: dict) -> None:
    """Re-raise a worker-marshalled failure as its own type, so the
    parent's isolation boundary classifies it exactly as it would have
    in-process (the retryable-OOM / breaker contract)."""
    name = reply.get("error", "RuntimeError")
    detail = reply.get("detail", "")
    from . import errors as res_errors

    cls = getattr(res_errors, name, None)
    if isinstance(cls, type) and issubclass(cls, res_errors.DegradationError):
        exc = cls(detail, site=reply.get("site") or None)
        if isinstance(exc, res_errors.DeviceOOM):
            exc.rungs_exhausted = bool(reply.get("rungs_exhausted"))
        if isinstance(exc, res_errors.StageHang):
            exc.stage = reply.get("stage", "")
            exc.scope_path = reply.get("scope_path", "")
            exc.ceiling_s = reply.get("ceiling_s")
        raise exc
    if name == "GraphFormatError":
        from ..io import GraphFormatError

        raise GraphFormatError(detail)
    import builtins

    cls = getattr(builtins, name, None)
    if isinstance(cls, type) and issubclass(cls, Exception):
        raise cls(detail)
    raise RuntimeError(f"{name}: {detail}")


# ---------------------------------------------------------------------------
# the worker child
# ---------------------------------------------------------------------------


def _worker_entry(conn, spool: str) -> None:
    """Worker-subprocess main loop.  Deliberately light at the top —
    chaos directives (and the exit message) are handled before any
    heavy import, so a crash-injected worker dies in milliseconds."""
    import signal

    try:
        signal.signal(signal.SIGINT, signal.SIG_IGN)  # parent drains
    except (ValueError, OSError):
        pass
    # the watchdog's hang notify fires from its own thread while the
    # main thread may be mid-send in a pathological interleaving — one
    # lock serializes every write to the pipe
    send_lock = threading.Lock()

    def send(payload) -> None:
        with send_lock:
            conn.send(payload)

    send({"type": "ready", "pid": os.getpid()})
    while True:
        try:
            msg = conn.recv()
        except (EOFError, OSError, KeyboardInterrupt):
            return
        if not isinstance(msg, dict) or msg.get("type") == "exit":
            return
        chaos = msg.get("chaos")
        if chaos == "crash":
            # the native-segfault stand-in: die without any cleanup
            os.kill(os.getpid(), signal.SIGKILL)
        if chaos == "hang":
            # a dead-stuck launch: never answer, never exit — the
            # supervisor's SIGKILL is the only way out
            while True:
                time.sleep(0.5)
        try:
            send(_worker_compute(msg, send))
        except BaseException as exc:  # marshal everything; keep serving
            try:
                send(_marshal_error(exc))
            except (OSError, ValueError, BrokenPipeError):
                return


def _marshal_error(exc: BaseException) -> dict:
    from . import errors as res_errors

    err = res_errors.classify(exc, site="")
    reply = {
        "type": "error",
        "error": type(err if err is not None else exc).__name__,
        "detail": str(exc)[:300],
        "site": getattr(err, "site", "") if err is not None else "",
    }
    if isinstance(err, res_errors.DeviceOOM):
        reply["rungs_exhausted"] = bool(err.rungs_exhausted)
    if isinstance(err, res_errors.StageHang):
        reply["stage"] = err.stage
        reply["scope_path"] = err.scope_path
        reply["ceiling_s"] = err.ceiling_s
    return reply


def _worker_compute(msg: dict, send) -> dict:
    import time as _time

    import numpy as np

    from .. import telemetry
    from ..cli import apply_dict_to_context
    from ..context import Context
    from ..io.snapshot import write_snapshot
    from ..kaminpar import KaMinPar
    from ..utils import timer
    from ..utils.logger import OutputLevel

    t0 = _time.perf_counter()
    ctx = Context()
    apply_dict_to_context(ctx, msg["ctx"])
    graph = _child_graph(msg["graph"])
    telemetry.reset()
    telemetry.enable()
    solver = KaMinPar(ctx)
    solver.set_output_level(OutputLevel.QUIET)
    solver.set_graph(graph)
    with stage_guard(
        "worker-compute", msg.get("ceiling_s"), notify=send,
    ):
        part = solver.compute_partition(
            k=msg["k"], epsilon=msg["epsilon"], seed=msg.get("seed"),
        )
    gate_s = timer.GLOBAL_TIMER.elapsed("output-gate")
    metrics = solver.result_metrics(graph, part)
    gate = telemetry.run_info().get("output_gate")
    gate_valid = (
        bool(gate.get("valid"))
        if isinstance(gate, dict) and gate.get("checked") else None
    )
    degraded = sorted({
        e.attrs.get("site", "") for e in telemetry.events("degraded")
    } - {""})
    _, result_sha = write_snapshot(
        msg["result_path"],
        {"partition": np.asarray(part, dtype=np.int32)},
    )
    wall_s = _time.perf_counter() - t0
    # the worker's own span rows for the request trace (fleet
    # observatory): its depth-1 telemetry scopes plus one whole-compute
    # row, all worker-relative ms — the parent re-bases them into the
    # request timeline (tracing.record_worker_reply)
    trace_spans = None
    if msg.get("trace"):
        from ..telemetry import tracing

        trace_spans = tracing.harvest_worker_rows()
        trace_spans.insert(0, {
            "name": "worker-compute",
            "origin": "worker",
            "start_ms": 0.0,
            "duration_ms": round(wall_s * 1000.0, 3),
            "attrs": {"worker_pid": os.getpid()},
        })
    # the worker's execution-ledger headline (launch/transfer totals,
    # pickle-safe) rides the reply so the parent's serving layer can
    # absorb the request's h2d/d2h bytes (telemetry/ledger.absorb)
    try:
        from ..telemetry import ledger

        ledger_summary = ledger.marshal_summary()
    except Exception:
        ledger_summary = None
    return {
        "type": "result",
        "path": msg["result_path"],
        # content digest of the written reply file: the parent verifies
        # it on re-read (resilience/integrity.py exchange contract), so
        # spool-file corruption between processes cannot serve silently
        "sha256": result_sha,
        "metrics": {
            "cut": int(metrics["cut"]),
            "imbalance": float(metrics["imbalance"]),
            "feasible": bool(metrics["feasible"]),
        },
        "gate_valid": gate_valid,
        "gate_s": float(gate_s),
        "degraded_sites": degraded,
        "anytime": solver.last_anytime,
        "rss_bytes": _self_rss_bytes(),
        "wall_s": wall_s,
        "trace_spans": trace_spans,
        "ledger": ledger_summary,
    }


def _child_graph(ref: dict):
    if ref["kind"] == "npz":
        from ..graphs.host import HostGraph
        from ..io.snapshot import read_snapshot

        arrays = read_snapshot(ref["value"])
        return HostGraph(
            arrays["xadj"], arrays["adjncy"],
            arrays.get("node_weights"), arrays.get("edge_weights"),
        )
    src = ref["value"]
    if src.startswith("gen:"):
        from ..graphs.factories import generate

        return generate(src)
    from .. import io as io_mod

    return io_mod.load_graph(src)


def _self_rss_bytes() -> int:
    try:
        import resource

        return int(
            resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
        )
    except Exception:
        return 0


# ---------------------------------------------------------------------------
# report surface
# ---------------------------------------------------------------------------


def summary(pool: Optional[WorkerPool] = None,
            isolation: Optional[str] = None) -> Dict[str, Any]:
    """The run report's ``supervision`` section (schema v10).  Returns
    the well-formed disabled default for a run that configured nothing
    — no pool, no heartbeat, never an armed watchdog stage."""
    wd = _watchdog()
    hb = heartbeat_state()
    enabled = (
        pool is not None
        or bool(hb["file"])
        or wd.armed_total > 0
        or bool(wd.hangs)
    )
    if not enabled:
        return {"enabled": False}
    workers = (
        dict(pool.stats) if pool is not None
        else {"spawned": 0, "recycled": 0, "killed": 0, "crashed": 0,
              "requests": 0}
    )
    return {
        "enabled": True,
        "isolation": isolation or ("process" if pool else "inproc"),
        "workers": workers,
        "hangs": hang_log(),
        "heartbeat": {"file": hb["file"] or "", "count": hb["count"]},
        "watchdog": watchdog_stats(),
    }


def reset() -> None:
    """Clear watchdog/heartbeat statistics and configuration (test
    isolation).  Live WorkerPools are owned by their services and are
    not touched."""
    global _hb_path, _hb_count
    wd = _watchdog()
    with wd._cond:
        wd._armed.clear()
        wd.armed_total = 0
        wd.fired = 0
        wd.hangs = []
        wd._cond.notify()
    with _hb_lock:
        _hb_path = None
        _hb_count = 0
