"""Fault-site registry and the ``KAMINPAR_TPU_FAULTS`` injection harness.

Every optional fast path that can degrade registers a *site* here: a
stable name, the structured exception its failures surface as, and a
one-line description of the fallback (the degradation matrix rendered in
docs/robustness.md).  :func:`kaminpar_tpu.resilience.with_fallback`
refuses unregistered sites, so the registry is the single source of
truth for the chaos suite, the run-report fault-plan echo, and the docs.

Injection plans come from the environment::

    KAMINPAR_TPU_FAULTS=site[@rank=K][:spec][,site[@rank=K][:spec]...]

where ``site`` is a registered name or ``all``, ``@rank=K`` scopes the
rule to process rank K only (``device-oom@rank=1:nth=1`` faults exactly
one rank of a multi-process fleet — the chaos address for "one sick
rank"; on the usual single-process mesh the local rank is 0, and
``KAMINPAR_TPU_SIM_RANK`` lets a smoke impersonate another rank — see
resilience/agreement.py), and ``spec`` is

  * omitted or ``always`` — every call at the site fails,
  * ``nth=K``            — exactly the K-th call at the site fails
                           (1-based; ``all:nth=1`` is the chaos smoke
                           plan: first call at EVERY site fails once),

``all`` covers the degradation-contract sites only: the corruption-chaos
sites (exception type :class:`IntegrityViolation` — ``bit-flip:*``,
``spill-corrupt``, ``cache-poison``, ``worker-reply-corrupt``) must be
named explicitly.  Their detectors RETRY from the last clean barrier
rather than degrade in place, so a batch of them in one run exceeds the
bounded recovery ladder by design (integrity.MAX_RETRIES); the
integrity smoke in check_all.sh exercises them one plan at a time.
  * a float in (0, 1]    — each call fails with that probability,
                           drawn deterministically from the global seed
                           (utils.rng), the site name, and the per-site
                           call counter — reruns inject identically.

The harness is dormant (two dict lookups) when the variable is unset.
"""

from __future__ import annotations

import hashlib
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Type

from .errors import (
    AdmissionRejected,
    CacheDegraded,
    CheckpointCorrupt,
    CheckpointWriteFailed,
    CollectiveTimeout,
    DegradationError,
    DeltaApplyFailed,
    DeviceOOM,
    IntegrityViolation,
    NativeUnavailable,
    PlanBlowup,
    RankDivergence,
    RefinerRefused,
    StageHang,
    WorkerCrash,
)

ENV_VAR = "KAMINPAR_TPU_FAULTS"


@dataclass(frozen=True)
class SiteSpec:
    """One registered degradation site (a row of the degradation matrix)."""

    name: str
    exc: Type[DegradationError]
    fallback: str  # human-readable fallback description (docs + events)
    description: str


# Registered in pipeline order; with_fallback() rejects names not listed
# here.  Adding a site means adding a row HERE plus its wiring, a chaos
# test, and a docs/robustness.md matrix row.
SITES: Dict[str, SiteSpec] = {}


def _register(spec: SiteSpec) -> None:
    SITES[spec.name] = spec


_register(SiteSpec(
    "native-build", NativeUnavailable,
    "ctypes-free mode (numpy codecs, python parsers)",
    "g++ build / dlopen of the native library (native/__init__.py)",
))
_register(SiteSpec(
    "native-ip", NativeUnavailable,
    "pure-numpy multilevel bipartitioner",
    "native sequential initial bipartitioner (initial/bipartitioner.py)",
))
_register(SiteSpec(
    "native-fm", RefinerRefused,
    "numpy FM pass (or unchanged partition on refusal)",
    "native localized batch k-way FM (refinement/fm.py)",
))
_register(SiteSpec(
    "refiner", DeviceOOM,
    "rollback to the pre-step partition (best known)",
    "one refinement algorithm step (partitioning/refiner.py)",
))
_register(SiteSpec(
    "device-balancer", DeviceOOM,
    "exact greedy host balancer",
    "device overload-balancing rounds (ops/balancer.py)",
))
_register(SiteSpec(
    "lane-gather", PlanBlowup,
    "plain XLA gather (no routed plan for the level)",
    "routed lane-gather plan build (ops/lane_gather.py)",
))
_register(SiteSpec(
    "compressed-stream", DeviceOOM,
    "decode to uncompressed host CSR and re-partition",
    "chunk-streamed device upload of a compressed graph (graphs/csr.py)",
))
_register(SiteSpec(
    "collective", CollectiveTimeout,
    "local-only data (skip cross-process aggregation)",
    "host-side cross-process gathers (telemetry/report.py, dist driver)",
))
_register(SiteSpec(
    "checkpoint-write", CheckpointWriteFailed,
    "in-memory-only checkpoints (run continues, durability lost)",
    "atomic snapshot/manifest write at a pipeline barrier "
    "(resilience/checkpoint.py)",
))
_register(SiteSpec(
    "checkpoint-load", CheckpointCorrupt,
    "previous manifest generation (one barrier of progress lost)",
    "snapshot read + checksum validation on --resume "
    "(resilience/checkpoint.py)",
))
_register(SiteSpec(
    "serving-admit", AdmissionRejected,
    "structured `rejected` verdict for that request (service keeps "
    "serving)",
    "serving-layer request admission (serving/service.py)",
))
_register(SiteSpec(
    "serving-cache", CacheDegraded,
    "forced miss/evict: the request recomputes (correctness untouched)",
    "serving-layer result-cache lookup (serving/service.py)",
))
_register(SiteSpec(
    "device-oom", DeviceOOM,
    "memory-governor recovery ladder: retry at the next rung "
    "(tight pads -> spilled hierarchy -> semi-external -> host-only; "
    "dist runs agree the rung across ranks first)",
    "allocator-shaped OOM at device upload / contraction / refinement "
    "(resilience/memory.py ladder; ladder-retryable OOMs never latch "
    "the serving per-class breaker — only rung exhaustion does)",
))
_register(SiteSpec(
    "worker-hang", StageHang,
    "supervisor SIGKILLs the worker past its hard ceiling; the request "
    "fails with verdict `failed`/reason `worker-hang`, the service "
    "keeps draining the queue",
    "supervised worker wall-clock containment (resilience/supervisor.py; "
    "chaos: the child worker genuinely sleeps past the ceiling and the "
    "supervisor's kill path is what is exercised)",
))
_register(SiteSpec(
    "worker-crash", WorkerCrash,
    "worker death is detected, classified, and answered with verdict "
    "`failed`/reason `worker-crash`; a fresh worker serves the next "
    "request",
    "supervised worker crash containment (resilience/supervisor.py; "
    "chaos: the child worker exits via SIGKILL — the native-segfault "
    "stand-in)",
))
_register(SiteSpec(
    "dynamic-apply", DeltaApplyFailed,
    "full CSR rebuild + re-upload into a fresh bucket for that delta "
    "(the bucket-crossing path; strictly more work, never a wrong "
    "graph)",
    "in-place CSR delta application of a dynamic graph session "
    "(dynamic/session.py; deltas that fit the padded bucket's slack "
    "reuse the compiled executables)",
))
# corruption-chaos sites (resilience/integrity.py): injection here does
# NOT raise at the site — the integrity chaos helpers catch the injected
# IntegrityViolation and genuinely mutate bytes in flight, so the
# DETECTORS (sentinels / digests) are what the chaos suite exercises
_register(SiteSpec(
    "bit-flip:contraction", IntegrityViolation,
    "none at the site — the flipped projection-map bit is DETECTED by "
    "the contraction sentinels (edge-weight conservation / cmap range) "
    "and recovered by one retry from the last clean barrier",
    "silent bit-flip in a contraction's projection map "
    "(partitioning/coarsener.py; chaos mutates a cmap entry in flight)",
))
_register(SiteSpec(
    "bit-flip:partition", IntegrityViolation,
    "none at the site — the corrupted partition entry is DETECTED by "
    "the refinement sentinels (partition-range) and recovered by one "
    "retry from the last clean barrier",
    "silent bit-flip in a refined partition vector "
    "(partitioning/refiner.py; chaos mutates a partition entry)",
))
_register(SiteSpec(
    "spill-corrupt", IntegrityViolation,
    "digest mismatch on re-read -> drop the spill file, re-decode the "
    "chunk from its source, rewrite (local recovery; never garbage rows)",
    "chunkstore spill-tier file corruption "
    "(external/chunkstore.py; chaos flips a byte in the spilled file)",
))
_register(SiteSpec(
    "cache-poison", IntegrityViolation,
    "digest mismatch on hit -> forced miss + evict; the request "
    "recomputes (a poisoned entry is never served)",
    "serving result-cache entry corruption "
    "(serving/service.py; chaos flips a bit in the cached partition)",
))
_register(SiteSpec(
    "worker-reply-corrupt", IntegrityViolation,
    "reply digest mismatch -> classified IntegrityViolation for that "
    "request (verdict `failed`/reason `corrupt-result`); the worker "
    "keeps serving",
    "supervised-worker npz reply corruption "
    "(resilience/supervisor.py; chaos flips a byte in the reply file)",
))
_register(SiteSpec(
    "rank-divergence", RankDivergence,
    "none — structured abort with the per-rank state dump (divergence "
    "has no safe local fallback)",
    "cross-rank divergence sentinel at the dist pipeline barriers "
    "(resilience/agreement.py audit)",
))


@dataclass
class _FaultRule:
    site: str  # registered name or "all"
    prob: Optional[float] = None  # None => deterministic (always / nth)
    nth: Optional[int] = None  # 1-based exact call index
    rank: Optional[int] = None  # None => every rank; K => rank K only


@dataclass
class _PlanState:
    raw: str
    rules: List[_FaultRule] = field(default_factory=list)


_plan_cache: Optional[_PlanState] = None
_counters: Dict[str, int] = {}
_injected: List[dict] = []


class FaultPlanError(ValueError):
    """KAMINPAR_TPU_FAULTS could not be parsed (bad site or spec)."""


def parse_plan(raw: str) -> List[_FaultRule]:
    """Parse a fault-plan string; raises FaultPlanError on bad input."""
    rules: List[_FaultRule] = []
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        site, _, spec = part.partition(":")
        site = site.strip()
        # rank scoping: `site@rank=K` restricts the rule to process
        # rank K (the single-sick-rank chaos address)
        rank: Optional[int] = None
        if "@" in site:
            site, _, rank_spec = site.partition("@")
            site = site.strip()
            rank_spec = rank_spec.strip()
            if not rank_spec.startswith("rank="):
                raise FaultPlanError(
                    f"bad rank scope {rank_spec!r} in {part!r} "
                    "(want site@rank=K)"
                )
            try:
                rank = int(rank_spec[5:])
            except ValueError:
                raise FaultPlanError(
                    f"bad rank number in {part!r} (want site@rank=K)"
                )
            if rank < 0:
                raise FaultPlanError(f"rank must be >= 0 in {part!r}")
        if site not in SITES and site != "all":
            # colon-named sites (`bit-flip:contraction`): the first-colon
            # split above took the site's own second segment as the spec
            # — rejoin it when that yields a registered name, leaving the
            # remainder (if any) as the real spec
            head, _, rest = spec.partition(":")
            cand = f"{site}:{head.strip()}"
            if cand in SITES:
                site, spec = cand, rest
        if site != "all" and site not in SITES:
            raise FaultPlanError(
                f"unknown fault site {site!r} (registered: "
                f"{', '.join(SITES)}, or 'all')"
            )
        spec = spec.strip()
        if not spec or spec == "always":
            rules.append(_FaultRule(site, rank=rank))
        elif spec.startswith("nth="):
            try:
                nth = int(spec[4:])
            except ValueError:
                raise FaultPlanError(f"bad nth spec {spec!r} for {site!r}")
            if nth < 1:
                raise FaultPlanError(f"nth must be >= 1 in {part!r}")
            rules.append(_FaultRule(site, nth=nth, rank=rank))
        else:
            try:
                prob = float(spec)
            except ValueError:
                raise FaultPlanError(
                    f"bad fault spec {spec!r} for {site!r} "
                    "(want nothing, 'always', 'nth=K', or a probability)"
                )
            if not 0.0 < prob <= 1.0:
                raise FaultPlanError(f"probability out of (0, 1] in {part!r}")
            rules.append(_FaultRule(site, prob=prob, rank=rank))
    return rules


def _active_plan() -> Optional[_PlanState]:
    """The parsed plan for the CURRENT env value (re-parsed on change)."""
    global _plan_cache
    raw = os.environ.get(ENV_VAR, "")
    if not raw:
        _plan_cache = None
        return None
    if _plan_cache is None or _plan_cache.raw != raw:
        _plan_cache = _PlanState(raw=raw, rules=parse_plan(raw))
    return _plan_cache


def _seeded_draw(site: str, count: int) -> float:
    """Deterministic uniform [0, 1) draw keyed by (seed, site, count)."""
    from ..utils import rng as rng_mod

    seed = rng_mod.get_seed()
    digest = hashlib.sha256(f"{seed}:{site}:{count}".encode()).digest()
    return int.from_bytes(digest[:8], "big") / float(1 << 64)


def maybe_inject(site: str, **attrs) -> None:
    """Raise the site's structured exception if the active fault plan says
    this call fails.  Called by with_fallback at every site entry (and by
    a few deep injection points inside primaries).  No-op without a plan.
    """
    spec = SITES[site]  # KeyError = unregistered site, a programming error
    plan = _active_plan()
    if plan is None:
        return
    count = _counters.get(site, 0) + 1
    _counters[site] = count
    fire = False
    local_rank: Optional[int] = None
    for rule in plan.rules:
        if rule.site != "all" and rule.site != site:
            continue
        if rule.site == "all" and issubclass(spec.exc, IntegrityViolation):
            # `all` plans cover the degradation contract; corruption
            # chaos is opt-in by name (see module docstring) — two
            # corruption sites firing in one run would exhaust the
            # bounded retry ladder by construction, not by bug
            continue
        if rule.rank is not None:
            if local_rank is None:
                from .agreement import rank as _rank

                local_rank = _rank()
            if rule.rank != local_rank:
                continue  # scoped to a different rank: rule inert here
        if rule.nth is not None:
            fire = count == rule.nth
        elif rule.prob is not None:
            fire = _seeded_draw(site, count) < rule.prob
        else:
            fire = True
        if fire:
            break
    if not fire:
        return
    entry = {"site": site, "call": count}
    if rule.rank is not None:
        # a rank-scoped rule fired: record WHERE (unscoped entries keep
        # their historical two-key shape)
        entry["rank"] = int(rule.rank)
    _injected.append(entry)
    raise spec.exc(
        f"injected fault at site '{site}' (call #{count}, "
        f"{ENV_VAR}={plan.raw})",
        site=site,
        injected=True,
    )


def site_spec(site: str) -> SiteSpec:
    """The SiteSpec for a registered name; KeyError on unknown sites."""
    return SITES[site]


def invocation_count(site: str) -> int:
    """How many times the site has been entered (injection bookkeeping
    counts even with no plan active? no — counters only advance while a
    plan is active, so this reads as 'injectable calls seen')."""
    return _counters.get(site, 0)


def injected_log() -> List[dict]:
    """All faults fired so far ({site, call} dicts, in firing order)."""
    return list(_injected)


def reset() -> None:
    """Clear counters and the fired-fault log (test isolation)."""
    global _plan_cache
    _counters.clear()
    _injected.clear()
    _plan_cache = None


def plan_summary() -> dict:
    """The run report's fault-plan echo: the raw plan (or None), the
    registered site list, and every fault fired so far."""
    raw = os.environ.get(ENV_VAR, "") or None
    return {
        "plan": raw,
        "sites": list(SITES),
        "injected": injected_log(),
    }
