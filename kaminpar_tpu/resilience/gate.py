"""Strict-balance output gate: end-of-pipeline partition validation + repair.

The headline contract of the reference solver (README.MD:18) is that
*every* run returns a complete k-way partition strictly satisfying the
balance constraint for unweighted inputs.  With many optional fast paths
that can degrade (resilience/faults.py), the pipeline guarantees that
postcondition HERE, not in each path: ``KaMinPar.compute_partition``
routes its result through this gate before returning.

The gate host-checks, with its own numpy implementation (independent of
ops/metrics and graphs/host.host_partition_metrics, so a metrics bug
cannot self-certify):

  * every node is assigned a block id in [0, k);
  * balance: for unit node weights the STRICT cap
    (1+eps) * ceil(n / k) (= PartitionContext.unrelaxed_max_block_weights
    for uniform setups), otherwise the relaxed per-block caps the
    pipeline was solved against;
  * the edge cut, recomputed from the CSR, matches the driver's value.

On an assignment or balance violation the gate runs the exact greedy
host repair (ops/balancer.host_balance) before returning — unless repair
was disabled (``--no-repair`` / ctx.resilience.repair).  The verdict is
emitted as an ``output-gate`` telemetry event and annotated into the run
report (schema: ``output_gate``).

Compressed inputs are checked chunk-streamed (decode_range), so the gate
never materializes the flat edge list for TeraPart-scale graphs; only a
needed *repair* forces a decode.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np

GATE_ENV = "KAMINPAR_TPU_OUTPUT_GATE"

#: Nodes per decode chunk when recomputing metrics on compressed inputs.
CHUNK_NODES = 1 << 18


def gate_enabled() -> bool:
    """The gate runs unless KAMINPAR_TPU_OUTPUT_GATE=0.  Cost: the
    gate's own O(n + m) host recompute, plus the driver-path metric it
    cross-checks against — which the facade memoizes and reuses for the
    RESULT line, so a gated call pays exactly one extra host sweep."""
    return os.environ.get(GATE_ENV, "") != "0"


def recompute_metrics(graph, partition: np.ndarray, k: int) -> Tuple[int, np.ndarray]:
    """(cut, block_weights) recomputed on the host, independent of the
    driver's metric path.  Streams compressed graphs chunk-by-chunk."""
    from ..graphs.compressed import CompressedHostGraph

    partition = np.asarray(partition)
    bw = np.zeros(max(k, 1), dtype=np.int64)
    np.add.at(
        bw,
        np.clip(partition, 0, max(k - 1, 0)),
        np.asarray(graph.node_weight_array(), dtype=np.int64),
    )
    cut2 = 0  # both directions of every cut edge
    if hasattr(graph, "iter_rows"):
        # generator-spec wrapper (external/chunkstore.StreamedSpecGraph):
        # regenerate node-range chunks — the gate never materializes
        # the synthetic fine graph it validates
        for v0, v1, adj, ew in graph.iter_rows():
            deg = np.asarray(
                graph.xadj[v0 + 1 : v1 + 1] - graph.xadj[v0:v1],
                dtype=np.int64,
            )
            owner = np.repeat(np.arange(v0, v1, dtype=np.int64), deg)
            crosses = partition[owner] != partition[np.asarray(adj)]
            if ew is None:
                cut2 += int(np.count_nonzero(crosses))
            else:
                cut2 += int(np.asarray(ew, dtype=np.int64)[crosses].sum())
    elif isinstance(graph, CompressedHostGraph):
        for v0 in range(0, graph.n, CHUNK_NODES):
            v1 = min(graph.n, v0 + CHUNK_NODES)
            xr, adj, ew = graph.decode_range(v0, v1)
            deg = np.diff(np.asarray(xr, dtype=np.int64))
            owner = np.repeat(np.arange(v0, v1, dtype=np.int64), deg)
            crosses = partition[owner] != partition[np.asarray(adj)]
            if ew is None:
                cut2 += int(np.count_nonzero(crosses))
            else:
                cut2 += int(np.asarray(ew, dtype=np.int64)[crosses].sum())
    elif graph.m:
        xadj = np.asarray(graph.xadj, dtype=np.int64)
        owner = np.repeat(np.arange(graph.n, dtype=np.int64), np.diff(xadj))
        crosses = partition[owner] != partition[graph.adjncy]
        ew = graph.edge_weight_array()
        cut2 += int(np.asarray(ew, dtype=np.int64)[crosses].sum())
    return cut2 // 2, bw


def _strict_caps(graph, p_ctx) -> Tuple[np.ndarray, str]:
    """The caps the gate enforces and the basis label.

    Unit node weights + uniform block weights: the UNRELAXED caps — the
    public (1+eps)*ceil(n/k) contract.  Anything else: the relaxed caps
    the pipeline was actually solved against (the reference's
    feasibility definition for weighted instances)."""
    node_w = np.asarray(graph.node_weight_array())
    unit = bool((node_w == 1).all()) if node_w.size else True
    unrelaxed = p_ctx.unrelaxed_max_block_weights
    if unit and p_ctx.uniform_block_weights and unrelaxed is not None:
        return np.asarray(unrelaxed, dtype=np.int64), "strict-unit"
    return np.asarray(p_ctx.max_block_weights, dtype=np.int64), "relaxed"


def check_and_repair(
    graph,
    partition: np.ndarray,
    p_ctx,
    *,
    repair: bool = True,
    reported_cut: Optional[int] = None,
) -> Tuple[np.ndarray, dict]:
    """Validate (and, on violation, repair) a finished partition.

    Returns (partition, verdict).  The returned partition satisfies the
    assignment invariant always, and the balance invariant whenever
    repair is enabled and the instance is feasible; the verdict records
    what was found and what was done."""
    from .. import telemetry

    k = int(p_ctx.k)
    n = int(graph.n)
    part = np.asarray(partition)
    violations = []

    if part.shape != (n,):
        violations.append(
            f"size: partition has {part.shape} entries, graph has {n} nodes"
        )
    if part.shape != (n,) or not np.issubdtype(part.dtype, np.integer):
        fixed = np.zeros(n, dtype=np.int32)
        m_copy = min(n, part.reshape(-1).shape[0])
        with np.errstate(invalid="ignore"):
            fixed[:m_copy] = np.nan_to_num(
                part.reshape(-1)[:m_copy]
            ).astype(np.int32)
        part = fixed
    out_of_range = (part < 0) | (part >= k)
    num_oor = int(out_of_range.sum())
    if num_oor:
        violations.append(f"assignment: {num_oor} node(s) outside [0, {k})")

    caps, cap_basis = _strict_caps(graph, p_ctx)
    repaired = False
    moved = 0
    if num_oor and repair:
        # out-of-range nodes go to the currently-lightest blocks, then
        # the balance repair below settles weights properly
        part = part.copy()
        _, bw0 = recompute_metrics(graph, np.where(out_of_range, 0, part), k)
        part[out_of_range] = int(np.argmin(bw0))
        repaired = True

    cut, bw = recompute_metrics(graph, np.clip(part, 0, k - 1), k)
    # the cut CROSS-CHECK compares the driver's value against the
    # PRE-repair recompute (both describe the same partition); the
    # repaired partition legitimately has a different cut
    cut_match = None if reported_cut is None else bool(cut == int(reported_cut))
    if cut_match is False:
        violations.append(
            f"cut-mismatch: driver reported {int(reported_cut)}, "
            f"gate recomputed {cut}"
        )
    overload = int(np.maximum(bw - caps, 0).sum())
    if overload:
        violations.append(
            f"balance: total overload {overload} over the {cap_basis} caps"
        )
    if overload and repair:
        part = _greedy_repair(graph, np.clip(part, 0, k - 1), caps)
        repaired = True
    if repaired:
        part = np.ascontiguousarray(part, dtype=np.int32)
        cut, bw = recompute_metrics(graph, part, k)
        overload = int(np.maximum(bw - caps, 0).sum())
        orig = np.asarray(partition).reshape(-1)
        common = min(n, orig.shape[0])
        moved = int(np.count_nonzero(part[:common] != orig[:common])) + (
            n - common
        )
        valid = overload == 0 and not ((part < 0) | (part >= k)).any()
    else:
        # repair disabled (or nothing to repair): the caller's partition
        # is returned UNTOUCHED — --no-repair must not silently clip —
        # and `valid` reports the honest, unclipped state
        part = partition
        valid = (
            overload == 0
            and num_oor == 0
            and np.asarray(partition).shape == (n,)
        )

    perfect = max(1, -(-int(np.asarray(graph.node_weight_array(),
                                       dtype=np.int64).sum()) // max(k, 1)))
    verdict = {
        "checked": True,
        "valid": bool(valid),
        "violations": violations,
        "repaired": repaired,
        "repair_moves": moved,
        "cut_reported": None if reported_cut is None else int(reported_cut),
        "cut_recomputed": int(cut),
        "cut_match": cut_match,
        "imbalance": float(bw.max() / perfect - 1.0) if k else 0.0,
        "max_overload": overload,
        "cap_basis": cap_basis,
    }
    telemetry.event("output-gate", **verdict)
    if repaired or violations:
        from ..utils.logger import log_warning

        log_warning(
            "output gate: "
            + "; ".join(violations)
            + (f" -> repaired ({moved} node(s) moved)" if repaired else
               " (repair disabled)")
        )
    return part, verdict


def _greedy_repair(graph, part: np.ndarray, caps: np.ndarray) -> np.ndarray:
    """Greedy host repair: the exact balancer over the gate's caps.
    Decodes compressed inputs first (repair is the rare path; the check
    itself streams)."""
    from ..graphs.compressed import CompressedHostGraph
    from ..ops import balancer as balancer_ops

    if isinstance(graph, CompressedHostGraph):
        host = graph.decode()
    elif hasattr(graph, "to_host_graph"):
        host = graph.to_host_graph()  # spec wrapper: repair-only decode
    else:
        host = graph
    return balancer_ops.host_balance(
        np.asarray(host.node_weight_array(), dtype=np.int64),
        (
            np.asarray(host.xadj, dtype=np.int64),
            np.asarray(host.adjncy),
            np.asarray(host.edge_weight_array(), dtype=np.int64),
        ),
        np.ascontiguousarray(part, dtype=np.int32),
        np.asarray(caps, dtype=np.int64),
    )


def apply(
    partitioner, graph, partition: np.ndarray, ctx, annotate: bool = True
) -> np.ndarray:
    """The facade hook: gate ``compute_partition``'s result.

    Disabled via KAMINPAR_TPU_OUTPUT_GATE=0 or ctx.resilience.output_gate;
    repair honors ctx.resilience.repair (--no-repair).  Under
    KAMINPAR_TPU_ASSERTS=1 the input CSR is also re-validated
    (graphs/csr.maybe_validate) so a corrupted graph cannot launder a
    'valid' verdict.  ``annotate=False`` for nested runs (shm IP inside
    the dist driver): the gate still checks/repairs and emits its event,
    but must not stamp ITS verdict into the outer run's report section.
    """
    res_ctx = getattr(ctx, "resilience", None)
    if not gate_enabled() or (res_ctx is not None and not res_ctx.output_gate):
        return partition
    from ..graphs import csr as csr_mod

    csr_mod.maybe_validate(graph, where="output-gate")
    reported = partitioner.result_metrics(graph, partition)["cut"]
    repair = res_ctx.repair if res_ctx is not None else True
    part, verdict = check_and_repair(
        graph, partition, ctx.partition, repair=repair, reported_cut=reported
    )
    if annotate:
        from .. import telemetry

        telemetry.annotate(output_gate=verdict)
    return part
