"""Preemption-safe checkpoint/resume at the multilevel pipeline barriers.

On TPU fleets long partitioning runs die to preemption, OOM, or hung
collectives; a kill at uncoarsening level 7 of 9 used to lose everything.
The multilevel hierarchy is a natural sequence of durable snapshots (the
same observation that lets semi-external partitioners stream the
hierarchy through disk): at each barrier — after each coarsening level's
contraction, after initial partitioning, after each uncoarsening level's
refinement — the driver *offers* its current state to the manager here,
which serializes it atomically (io/snapshot.py: temp file + fsync +
rename, per-file SHA-256 checksums) under ``--checkpoint-dir``, updates
a versioned manifest, prunes superseded snapshots, and emits a
``checkpoint`` telemetry event with the byte/wall cost.

``--resume`` validates the manifest — the graph fingerprint AND the
context fingerprint must match the current invocation, else a structured
:class:`~kaminpar_tpu.resilience.errors.CheckpointMismatch` degrades to
a clean restart, never a crash — and the driver re-enters the pipeline
at the recorded stage without re-running completed levels.

Degradation sites (resilience/faults.py):

  * ``checkpoint-write`` — a failed snapshot/manifest write degrades to
    in-memory-only checkpoints: the run continues, durability is lost,
    a ``degraded`` event says so;
  * ``checkpoint-load`` — a truncated/corrupted snapshot on resume falls
    back to the *previous* manifest generation (one barrier of progress
    lost) instead of aborting.

An unusable ``--checkpoint-dir`` (permissions, missing mount) disables
checkpointing for the run with a warning — the native-cache-dir
degradation pattern (native/__init__.py), not an exception.

Everything here is host-side numpy + filesystem work: with no
``--checkpoint-dir`` the barrier hook is two attribute reads and the
driver jaxprs are bit-identical to a checkpoint-free build.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .errors import CheckpointCorrupt, CheckpointMismatch, CheckpointWriteFailed

MANIFEST = "manifest.json"
MANIFEST_PREV = "manifest.prev.json"
MANIFEST_VERSION = 1

#: Debug/test hook: ``KAMINPAR_TPU_STOP_AT=stage[:level]`` requests the
#: graceful deadline wind-down the first time that barrier is crossed —
#: a deterministic stand-in for "preemption notice received here".  A
#: trailing ``!`` (``uncoarsen:2!``) instead simulates a HARD kill:
#: :class:`SimulatedPreemption` is raised right after the barrier's
#: checkpoint lands, as if the process died there — the kill-and-resume
#: equivalence suite drives every barrier kind through both modes.
STOP_AT_ENV = "KAMINPAR_TPU_STOP_AT"


class SimulatedPreemption(RuntimeError):
    """Raised by the STOP_AT test hook's hard mode.  Deliberately NOT a
    DegradationError: like a real SIGKILL it must never be swallowed by
    a fallback policy."""

# The active manager and the nested-run suspend counter live on the
# per-run (thread-local) RunState — see resilience/runstate.py.  The
# function API below is unchanged; back-to-back and interleaved runs
# (the serving layer's request stream) each see only their own state.
from . import runstate


def activate(mgr: Optional["CheckpointManager"]) -> None:
    """Install the run's manager (facade entry; None deactivates).  Only
    the run that owns the telemetry stream activates one — nested runs
    (shm IP inside the dist driver) see no manager, so a checkpoint can
    never record an inner pipeline's stage as the outer run's."""
    runstate.current().manager = mgr


def deactivate() -> None:
    run = runstate.current()
    run.manager = None
    run.suspend = 0


def active() -> Optional["CheckpointManager"]:
    return runstate.current().manager


def suspend() -> None:
    """Blind the barrier hook for the duration of a NESTED pipeline run
    (shm IP inside the dist driver): the inner drivers call barrier()
    like any other, but must neither rewrite the outer run's manifest
    with their own scheme/stage nor consume its resume state.  The
    facade suspends around nested (non-stream-owning) runs and
    unsuspends in its finally; re-entrant (counted)."""
    runstate.current().suspend += 1


def unsuspend() -> None:
    run = runstate.current()
    run.suspend = max(0, run.suspend - 1)


def suspended() -> bool:
    return runstate.current().suspend > 0


def create_manager(res_ctx, graph, ctx) -> Optional["CheckpointManager"]:
    """The facades' shared arm-and-maybe-resume step (shm and dist must
    not drift apart on this policy): build the manager from the
    resilience context, and on `resume` load + validate the recorded
    state — a CheckpointMismatch/CheckpointCorrupt degrades to a logged
    clean restart, never a crash.  Returns None when checkpointing is
    not configured.  The caller still activates it (and only when it
    owns the telemetry stream)."""
    if not res_ctx.checkpoint_dir:
        return None
    mgr = CheckpointManager(
        res_ctx.checkpoint_dir, graph_fingerprint(graph), ctx_fingerprint(ctx)
    )
    if res_ctx.resume and mgr.enabled:
        from .. import telemetry
        from ..utils.logger import log_warning

        try:
            mgr.load_resume_state()
        except (CheckpointMismatch, CheckpointCorrupt) as e:
            log_warning(
                f"--resume: {type(e).__name__}: {e}; starting a clean run"
            )
            telemetry.event(
                "checkpoint", action="clean-restart",
                error=f"{type(e).__name__}: {e}"[:300],
            )
    return mgr


def barrier(
    stage: str,
    level: Optional[int] = None,
    scheme: str = "",
    payload: Optional[Callable[[], dict]] = None,
    keep: Optional[List[str]] = None,
    meta: Optional[dict] = None,
    agree: bool = False,
) -> bool:
    """The single driver hook at every pipeline barrier.

    Notes the stage for the anytime annotation, offers a checkpoint when
    a manager is active (``payload`` is a zero-arg callable returning
    ``{snapshot_name: {array_name: ndarray}}`` so disabled runs build
    nothing and pull nothing off device), honors the STOP_AT test hook,
    and returns False once the deadline wind-down has begun — callers
    stop starting new *optional* work on False (mandatory tail work —
    projection, extension, balance — ignores the verdict).
    ``agree=True`` makes the verdict cross-process-consistent
    (deadline.agreed_stop) — required when the gated work contains
    collectives, or diverging ranks would deadlock mid-wind-down.
    """
    from . import deadline

    run = runstate.current()
    stage_id = stage if level is None else f"{stage}:{int(level)}"
    if not run.suspend:
        # nested (suspended) runs neither track stages nor checkpoint —
        # but they DO honor the wind-down verdict below
        deadline.note_stage(stage_id)
        # divergence sentinel (resilience/agreement.py), armed only by
        # the stream-owning dist driver: one small allgather of
        # [stage-hash, rung, run-fingerprint-hash] per barrier, BEFORE
        # the checkpoint offer — a diverged fleet must abort with the
        # per-rank dump, not persist a skewed manifest.  One attribute
        # read for shm runs.
        from . import agreement

        agreement.maybe_audit(stage_id)
        # liveness heartbeat (resilience/supervisor.py): barrier
        # crossings are the pipeline's proof of forward progress — the
        # heartbeat file's mtime advances here (and from the watchdog
        # tick while nothing is hung), so an external supervisor can
        # tell slow-but-alive from hung.  One attribute read when no
        # heartbeat file is configured.
        from . import supervisor as supervisor_mod

        supervisor_mod.heartbeat_touch()
        # device-memory watermark: the perf observatory samples the
        # resident-bytes figure at exactly these multilevel barriers
        # (host side, between launches; one bool check when disabled)
        from ..telemetry import perf as perf_mod

        mem_sample = None
        if perf_mod.enabled():
            mem_sample = perf_mod.sample_memory(stage_id, level=level)
        mgr = run.manager
        if mgr is not None and mgr.enabled:
            from .. import telemetry

            # build the payload only where it will be written: rank 0
            # (every rank still calls with the same barrier-consistent
            # stage id; non-primary ranks pay two dict lookups) and only
            # while persistence has not degraded to memory-only
            primary = telemetry.is_primary_process()
            new = (
                payload()
                if (payload is not None and primary and not mgr.memory_only)
                else {}
            )
            if primary:
                mgr.offer(
                    stage, level=level, scheme=scheme,
                    new=new, keep=keep or [], meta=meta or {},
                )
        # memory-governor pressure hook (resilience/memory.py): AFTER
        # the checkpoint offer (the newest level must be serialized
        # before its siblings may be spilled), compare the live-bytes
        # watermark against the declared budget and spill/shed
        # proactively.  Two attribute reads while the governor is
        # dormant.
        from . import memory as memory_mod

        memory_mod.on_barrier(
            stage_id,
            live_bytes=(
                mem_sample.get("live_bytes") if mem_sample else None
            ),
        )
        stop_at = os.environ.get(STOP_AT_ENV, "")
        if stop_at:
            hard = stop_at.endswith("!")
            target = stop_at.rstrip("!")
            if target in (stage, stage_id):
                if hard:
                    raise SimulatedPreemption(
                        f"simulated hard preemption at barrier {stage_id}"
                    )
                deadline.request_stop(f"stop-at:{stage_id}")
    if agree:
        return not deadline.agreed_stop()
    return not deadline.should_stop()


def take_resume(scheme: str) -> Optional[dict]:
    """Hand the pending resume state to the driver whose scheme matches
    (consumed on first take, so a clean-restart re-dispatch cannot
    accidentally resume twice).  Suspended (nested) runs never see it —
    an inner IP replica must not restore the outer run's state."""
    run = runstate.current()
    if run.manager is None or run.suspend:
        return None
    return run.manager.take_resume(scheme)


# ---------------------------------------------------------------------------
# fingerprints
# ---------------------------------------------------------------------------


def graph_fingerprint(graph) -> str:
    """Cheap, stable identity of the input graph: sizes, weight totals,
    and boundary samples of the adjacency — O(1)-ish even for TeraPart
    inputs (never a full-graph hash), but enough that resuming against a
    different graph is practically impossible to miss."""
    # dynamic graph sessions (dynamic/session.py) stamp an evolving
    # fingerprint (base fingerprint + delta-chain hash) onto the graph
    # object so checkpoints of a mutated graph key on the exact chain
    # step — the sampling hash below could miss interior-only deltas
    session_fp = getattr(graph, "_session_fp", None)
    if session_fp is not None:
        return str(session_fp)
    h = hashlib.sha256()
    n, m = int(graph.n), int(graph.m)
    h.update(f"n={n};m={m};".encode())
    try:
        nw = np.asarray(graph.node_weight_array(), dtype=np.int64)
        h.update(str(int(nw.sum())).encode())
        h.update(nw[:1024].tobytes())
    except Exception:
        pass
    from ..graphs.compressed import CompressedHostGraph

    if isinstance(graph, CompressedHostGraph):
        xr, adj, _ = graph.decode_range(0, min(n, 2048))
        h.update(np.asarray(xr, dtype=np.int64).tobytes())
        h.update(np.asarray(adj, dtype=np.int64)[:4096].tobytes())
    elif not hasattr(graph, "adjncy"):
        # generator-spec wrapper (external/chunkstore.StreamedSpecGraph):
        # the spec string + degree prefix IS the graph's identity — the
        # adjacency is deterministic from them and never materialized
        h.update(str(getattr(graph, "spec", "")).encode())
        xadj = np.asarray(graph.xadj, dtype=np.int64)
        h.update(xadj[:2048].tobytes())
        h.update(xadj[-2048:].tobytes())
    else:
        xadj = np.asarray(graph.xadj, dtype=np.int64)
        h.update(xadj[:2048].tobytes())
        h.update(xadj[-2048:].tobytes())
        adj = np.asarray(graph.adjncy)
        h.update(adj[:4096].tobytes())
        h.update(adj[-4096:].tobytes())
    return h.hexdigest()[:24]


def ctx_fingerprint(ctx) -> str:
    """Identity of the algorithmic configuration a checkpoint is valid
    for: the full context tree minus the subtrees that may legitimately
    differ between the interrupted and the resuming invocation (the
    resilience knobs themselves — `--resume` flips one — and debug
    dumps).  Seed, k, epsilon, preset, and every algorithm knob are in."""
    from ..context import context_to_dict

    d = context_to_dict(ctx)
    d.pop("resilience", None)
    d.pop("debug", None)
    shm = d.get("shm")  # DistContext nests the shm tree
    if isinstance(shm, dict):
        shm.pop("resilience", None)
        shm.pop("debug", None)
    blob = json.dumps(d, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:24]


# ---------------------------------------------------------------------------
# the manager
# ---------------------------------------------------------------------------


class CheckpointManager:
    """One run's checkpoint state: versioned manifest + named snapshots.

    Snapshot files are immutable and generation-suffixed
    (``<name>-g<G>.npz``); each ``offer`` writes only the *new* snapshots
    for its barrier and carries forward the ``keep`` set by reference, so
    a hierarchy level is serialized exactly once.  The manifest is
    rotated (current -> ``manifest.prev.json``) before the new one is
    written, which is what the corrupted-load fallback and a
    kill-between-renames both recover from.  Files referenced by neither
    manifest are pruned."""

    def __init__(self, directory: str, graph_fp: str, ctx_fp: str):
        self.dir = directory
        self.graph_fp = graph_fp
        self.ctx_fp = ctx_fp
        self.enabled = True
        # set when a write failed (checkpoint-write degrade): offers are
        # still tracked — stats, events, stage bookkeeping — but nothing
        # further is persisted and payloads are no longer even built
        # (the barrier hook skips them)
        self.memory_only = False
        self.generation = 0
        self._snapshots: Dict[str, dict] = {}  # name -> manifest entry
        # pinned snapshot names are carried forward by EVERY offer, on
        # top of the offering driver's own keep list — the external
        # scheme pins its streamed-level projection maps so the in-core
        # deep phase's barriers (which know nothing about them) cannot
        # prune them out of the manifest
        self._pinned: set = set()
        self._resume: Optional[dict] = None
        self._resume_taken = False
        self.stats = {"writes": 0, "bytes": 0, "wall_s": 0.0}
        self._probe_dir()

    # -- setup ----------------------------------------------------------

    def _probe_dir(self) -> None:
        """Unusable checkpoint dir degrades with a warning (the
        native-cache-dir fallback pattern), never an exception."""
        from .. import telemetry
        from ..utils.logger import log_warning

        try:
            os.makedirs(self.dir, exist_ok=True)
            probe = os.path.join(self.dir, f".probe-{os.getpid()}")
            with open(probe, "w") as f:
                f.write("ok")
            os.remove(probe)
        except OSError as e:
            self.enabled = False
            log_warning(
                f"checkpoint dir {self.dir!r} unusable ({e}); "
                "checkpointing DISABLED for this run"
            )
            telemetry.event(
                "checkpoint", action="dir-unusable", dir=self.dir,
                error=str(e)[:200],
            )

    # -- write path -----------------------------------------------------

    def offer(
        self,
        stage: str,
        level: Optional[int],
        scheme: str,
        new: Dict[str, Dict[str, np.ndarray]],
        keep: List[str],
        meta: dict,
    ) -> None:
        """Record one barrier: write new snapshots, carry the keep set
        forward, rotate the manifest, prune.  On multi-process runs only
        rank 0 touches the filesystem; every rank calls with the same
        barrier-consistent stage id, so the recorded stage is the one
        every rank passed."""
        if not self.enabled:
            return
        from .. import telemetry

        if not telemetry.is_primary_process():
            return
        t0 = time.perf_counter()
        self.generation += 1
        gen = self.generation
        entries: Dict[str, dict] = {}
        for name in list(keep) + sorted(self._pinned):
            ent = self._snapshots.get(name)
            if ent is not None:
                entries[name] = ent
        wrote_bytes = 0
        for name, arrays in new.items():
            ent = self._write_snapshot(name, gen, arrays)
            entries[name] = ent
            if not ent.get("memory"):
                wrote_bytes += int(ent["bytes"])
        self._snapshots = entries
        manifest = {
            "version": MANIFEST_VERSION,
            "generation": gen,
            "graph_fingerprint": self.graph_fp,
            "ctx_fingerprint": self.ctx_fp,
            "scheme": scheme,
            "stage": stage,
            "level": level,
            "meta": meta,
            "snapshots": {
                k: v for k, v in entries.items() if not v.get("memory")
            },
        }
        if not self.memory_only:
            self._write_manifest(manifest)
            self._prune()
        wall = time.perf_counter() - t0
        self.stats["writes"] += 1
        self.stats["bytes"] += wrote_bytes
        self.stats["wall_s"] += wall
        from ..telemetry import ledger

        ledger.transfer("d2h", wrote_bytes, kind="checkpoint-spill")
        telemetry.event(
            "checkpoint",
            stage=stage,
            level=level,
            scheme=scheme,
            generation=gen,
            bytes=wrote_bytes,
            wall_s=round(wall, 4),
            memory_only=self.memory_only,
        )

    def _write_snapshot(self, name: str, gen: int, arrays: dict) -> dict:
        """One snapshot through the ``checkpoint-write`` degradation
        site: filesystem failure (or an injected fault) flips the run to
        in-memory-only mode instead of killing it."""
        from ..io.snapshot import write_snapshot
        from .policy import with_fallback

        fname = f"{name}-g{gen}.npz"
        path = os.path.join(self.dir, fname)
        if self.memory_only:
            return {"file": fname, "memory": True}

        def primary():
            try:
                return write_snapshot(path, arrays)
            except OSError as e:
                raise CheckpointWriteFailed(
                    f"snapshot write failed: {path}: {e}"
                ) from e

        def fallback(exc):
            self.memory_only = True
            return None

        written = with_fallback(
            primary, fallback, site="checkpoint-write", where=name,
        )
        if written is None:
            return {"file": fname, "memory": True}
        nbytes, sha = written
        return {"file": fname, "sha256": sha, "bytes": int(nbytes)}

    def _write_manifest(self, manifest: dict) -> None:
        from .policy import with_fallback

        cur = os.path.join(self.dir, MANIFEST)
        prev = os.path.join(self.dir, MANIFEST_PREV)

        def primary():
            try:
                if os.path.exists(cur):
                    os.replace(cur, prev)
                tmp = cur + f".tmp{os.getpid()}"
                with open(tmp, "w") as f:
                    json.dump(manifest, f, indent=1)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, cur)
                from ..io.snapshot import _fsync_dir

                _fsync_dir(self.dir)
                return True
            except OSError as e:
                raise CheckpointWriteFailed(
                    f"manifest write failed: {e}"
                ) from e

        def fallback(exc):
            self.memory_only = True
            return None

        with_fallback(primary, fallback, site="checkpoint-write",
                      where="manifest")

    def _prune(self) -> None:
        """Remove snapshot files referenced by neither the current nor
        the previous manifest (superseded levels, old state files)."""
        referenced = set()
        for mf in (MANIFEST, MANIFEST_PREV):
            try:
                with open(os.path.join(self.dir, mf)) as f:
                    man = json.load(f)
                for ent in man.get("snapshots", {}).values():
                    referenced.add(ent["file"])
            except (OSError, ValueError, KeyError, TypeError):
                continue
        try:
            names = os.listdir(self.dir)
        except OSError:
            return
        for fn in names:
            if not fn.endswith(".npz") or fn in referenced:
                continue
            try:
                os.unlink(os.path.join(self.dir, fn))
            except OSError:
                pass

    # -- load path ------------------------------------------------------

    def load_resume_state(self) -> Optional[dict]:
        """Validate and load the recorded stage for --resume.

        Returns None when the directory holds no checkpoint (a fresh
        start, not an error).  Raises CheckpointMismatch when the
        manifest belongs to a different graph/context (callers degrade
        to a clean restart) and CheckpointCorrupt when both manifest
        generations are unreadable.  A corrupted *snapshot* under the
        newest manifest engages the ``checkpoint-load`` site and falls
        back to the previous generation."""
        from .policy import with_fallback

        cur = os.path.join(self.dir, MANIFEST)
        prev = os.path.join(self.dir, MANIFEST_PREV)
        if not os.path.exists(cur) and not os.path.exists(prev):
            return None

        def load_current():
            return self._load_generation(cur)

        def load_previous(exc):
            if isinstance(exc, CheckpointMismatch):
                raise exc  # a mismatch is semantic; prev matches no better
            if not os.path.exists(prev):
                raise exc if exc is not None else CheckpointCorrupt(
                    "no previous manifest generation to fall back to"
                )
            return self._load_generation(prev)

        state = with_fallback(
            load_current, load_previous, site="checkpoint-load",
        )
        self._resume = state
        self._resume_taken = False
        # continue the generation numbering and snapshot refs of the
        # loaded manifest so the resumed run's keep-lists resolve
        self.generation = int(state["generation"])
        self._snapshots = dict(state["snapshot_entries"])
        from .. import telemetry
        from ..telemetry import ledger

        ledger.transfer(
            "h2d",
            sum(
                int(a.nbytes)
                for arrs in state.get("arrays", {}).values()
                for a in arrs.values()
            ),
            kind="checkpoint-reload",
        )
        telemetry.event(
            "checkpoint",
            action="resumed",
            stage=state["stage"],
            level=state["level"],
            scheme=state["scheme"],
            generation=self.generation,
        )
        return state

    def _load_generation(self, manifest_path: str) -> dict:
        try:
            with open(manifest_path) as f:
                man = json.load(f)
        except (OSError, ValueError) as e:
            raise CheckpointCorrupt(
                f"manifest unreadable: {manifest_path}: {e}"
            ) from e
        if not isinstance(man, dict) or man.get("version") != MANIFEST_VERSION:
            raise CheckpointCorrupt(
                f"manifest version mismatch in {manifest_path}: "
                f"{man.get('version') if isinstance(man, dict) else man!r}"
            )
        if man.get("graph_fingerprint") != self.graph_fp:
            raise CheckpointMismatch(
                "checkpoint belongs to a different graph "
                f"(manifest {man.get('graph_fingerprint')!r}, "
                f"current {self.graph_fp!r})"
            )
        if man.get("ctx_fingerprint") != self.ctx_fp:
            raise CheckpointMismatch(
                "checkpoint belongs to a different configuration "
                f"(manifest {man.get('ctx_fingerprint')!r}, "
                f"current {self.ctx_fp!r})"
            )
        from ..io.snapshot import SnapshotError, read_snapshot

        arrays: Dict[str, Dict[str, np.ndarray]] = {}
        for name, ent in man.get("snapshots", {}).items():
            path = os.path.join(self.dir, ent["file"])
            try:
                arrays[name] = read_snapshot(path, ent.get("sha256"))
            except (OSError, SnapshotError) as e:
                raise CheckpointCorrupt(str(e)) from e
        return {
            "scheme": man.get("scheme", ""),
            "stage": man["stage"],
            "level": man.get("level"),
            "meta": man.get("meta", {}),
            "arrays": arrays,
            "generation": int(man.get("generation", 0)),
            "snapshot_entries": dict(man.get("snapshots", {})),
        }

    def pin(self, names) -> None:
        """Mark snapshots as carried forward by every future offer (on
        top of each offer's own keep list).  Used by the external
        scheme: streamed-level projection maps must survive the in-core
        phase's barriers, whose keep lists don't know about them."""
        self._pinned.update(names)

    def pending_resume(self) -> Optional[dict]:
        """The loaded-but-unconsumed resume state (None once taken) —
        lets a driver VALIDATE driver-specific preconditions (the dist
        shard-fingerprint vector) before any scheme dispatch consumes
        it."""
        if self._resume is None or self._resume_taken:
            return None
        return self._resume

    def drop_resume(self, reason: str) -> None:
        """Discard the pending resume state: a driver-level mismatch
        (e.g. a resume under a different device count, detected via the
        shard fingerprints) degrades to a logged clean restart — the
        CheckpointMismatch policy, applied after load-time validation
        passed.  Never a crash, never a wrong answer."""
        if self._resume is None:
            return
        from .. import telemetry
        from ..utils.logger import log_warning

        log_warning(f"--resume: {reason}; starting a clean run")
        telemetry.event(
            "checkpoint", action="clean-restart", error=reason[:300],
        )
        self._resume = None
        self._resume_taken = False

    def take_resume(self, scheme: str) -> Optional[dict]:
        if (
            self._resume is None
            or self._resume_taken
            or self._resume.get("scheme") != scheme
        ):
            return None
        self._resume_taken = True
        return self._resume

    def take_result_resume(self) -> Optional[np.ndarray]:
        """The final-partition fast path: a run preempted *after* the
        output gate left a `result` stage; resuming returns it without
        re-partitioning."""
        if self._resume is None or self._resume_taken:
            return None
        if self._resume.get("stage") != "result":
            return None
        state = self._resume.get("arrays", {}).get("state")
        if state is None or "partition" not in state:
            return None
        self._resume_taken = True
        return np.asarray(state["partition"], dtype=np.int32)

    # -- reporting ------------------------------------------------------

    def resumed_from(self) -> Optional[str]:
        """The stage this run ACTUALLY resumed from — gated on the state
        having been consumed by a driver, so a loaded-but-unused resume
        (e.g. a dist mid-pipeline stage the dist driver cannot re-enter)
        is not reported as a resume that happened."""
        if self._resume is None or not self._resume_taken:
            return None
        lvl = self._resume.get("level")
        return (
            f"{self._resume['stage']}"
            + ("" if lvl is None else f":{int(lvl)}")
        )

    def summary(self) -> dict:
        """The run report's `checkpoint` section.  `resumed_from` is
        omitted (not null) for non-resumed runs so the schema can type
        it as a plain string."""
        d = {
            "enabled": self.enabled,
            "dir": self.dir,
            "memory_only": self.memory_only,
            "generation": self.generation,
            "writes": int(self.stats["writes"]),
            "bytes": int(self.stats["bytes"]),
            "wall_s": round(float(self.stats["wall_s"]), 4),
            "snapshots": sorted(self._snapshots),
        }
        if self.resumed_from() is not None:
            d["resumed_from"] = self.resumed_from()
        return d
