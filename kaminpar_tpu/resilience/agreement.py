"""Cross-rank agreement primitives and the divergence sentinels.

A multi-chip run is the *most* likely member of the fleet to be
preempted or OOM-killed and — before this module — was the least able
to recover: every cross-rank decision (wind down? retry at which memory
rung? is everyone still at the same pipeline stage?) was either local
(one sick rank deadlocks the survivors inside ``shard_map`` collectives)
or missing entirely (silent rank divergence surfaced, if ever, as a
hung collective or a wrong answer).  This module centralizes the one
idiom every such decision shares — a small host-side allgather over
per-rank scalars, max-reduced into a verdict every rank adopts — and
builds two users on top of it:

  * **agreement** — :func:`agree_max` is the allgather-max primitive
    behind ``deadline.agreed_stop`` (wind-down verdicts) and
    ``memory.agree_rung`` (the cross-rank agreed OOM-ladder rung): any
    rank proposing a higher value raises every rank to it, so control
    flow that gates collective work takes the same branch everywhere.
  * **divergence sentinels** — :func:`audit`, piggybacked on the
    checkpoint barrier hook by the dist driver: one small allgather of
    ``[stage-hash, ladder-rung, run-fingerprint-hash]`` per barrier.
    Ranks that disagree on any of the three have silently diverged
    (missed a barrier, took a different ladder rung, or are running a
    different graph/config), and the sentinel converts that into a
    structured :class:`~kaminpar_tpu.resilience.errors.RankDivergence`
    carrying a per-rank state dump — annotated into the run report
    before the raise, so even the emergency report shows which rank
    went where.

Rank model: ``rank()``/``num_ranks()`` are ``jax.process_index()`` /
``jax.process_count()`` (this repo's usual single-process virtual-device
mesh is one rank).  Two override layers exist for tests and smokes:

  * ``KAMINPAR_TPU_SIM_RANK`` / ``KAMINPAR_TPU_SIM_RANKS`` — pretend to
    be rank K of N (rank-scoped fault addressing, ``site@rank=K``, uses
    this to exercise "the fault fires on rank 1, not on rank 0" in a
    single-process smoke);
  * :func:`set_gather_override` — replace the collective itself, so a
    test can present a *divergent* fleet to the sentinel or a
    higher-rung peer to the ladder agreement without spawning
    processes.

Everything here is host-side numpy between launches; with one process
and no overrides, every gather degenerates to the local row and the
sentinel compares a vector with itself.
"""

from __future__ import annotations

import hashlib
import os
from typing import Callable, List, Optional, Tuple

import numpy as np

from . import runstate

#: Simulation overrides (tests / single-process chaos smokes): pretend
#: to be rank SIM_RANK of SIM_RANKS.  They scope fault addressing and
#: the report's rank stamp; they do NOT spawn processes or change what
#: the real collectives do.
ENV_SIM_RANK = "KAMINPAR_TPU_SIM_RANK"
ENV_SIM_RANKS = "KAMINPAR_TPU_SIM_RANKS"

#: Test hook: replaces the cross-process allgather.  Signature
#: ``f(local_row: np.ndarray[int64]) -> np.ndarray[num_ranks, len]``;
#: install with :func:`set_gather_override`, clear with None.
_gather_override: Optional[Callable[[np.ndarray], np.ndarray]] = None


def set_gather_override(
    fn: Optional[Callable[[np.ndarray], np.ndarray]]
) -> None:
    """Install (or with None clear) the allgather test hook — lets a
    single-process test present a divergent or N-rank fleet to the
    sentinel/agreement layer."""
    global _gather_override
    _gather_override = fn


def rank() -> int:
    """This process's rank: the SIM override when set, else
    ``jax.process_index()`` (0 without a live backend)."""
    raw = os.environ.get(ENV_SIM_RANK, "")
    if raw:
        try:
            return int(raw)
        except ValueError:
            pass
    try:
        from ..utils.platform import process_index

        return process_index()
    except Exception:
        return 0


def num_ranks() -> int:
    """Fleet size: the SIM override when set, else
    ``jax.process_count()`` (1 without a live backend)."""
    raw = os.environ.get(ENV_SIM_RANKS, "")
    if raw:
        try:
            return max(1, int(raw))
        except ValueError:
            pass
    try:
        from ..utils.platform import process_count

        return process_count()
    except Exception:
        return 1


def gather_i64(row) -> np.ndarray:
    """Allgather one small int64 row: returns ``[ranks, len(row)]``.

    The single shared collective of the agreement layer: the test
    override first, the real ``multihost_utils.process_allgather`` on a
    multi-process fleet, and the local row alone (shape ``[1, len]``)
    on the usual one-process mesh — never a device launch."""
    local = np.asarray(row, dtype=np.int64).reshape(-1)
    if _gather_override is not None:
        out = np.asarray(_gather_override(local), dtype=np.int64)
        return out.reshape(-1, local.shape[0])
    from ..utils.platform import process_count

    nproc = process_count()
    if nproc <= 1:
        return local[None, :]
    from jax.experimental import multihost_utils

    return np.asarray(
        multihost_utils.process_allgather(local)
    ).reshape(nproc, -1)


def agree_max(value: int) -> Tuple[int, int]:
    """The allgather-max agreement: every rank contributes ``value`` and
    adopts the fleet maximum.  Returns ``(agreed, triggering_rank)`` —
    the rank whose contribution WAS the maximum (lowest such rank), so
    degradations can name who pulled the fleet down.  Single rank: the
    identity."""
    rows = gather_i64([int(value)])
    vec = rows[:, 0]
    trig = int(np.argmax(vec))
    return int(vec.max()), trig


# ---------------------------------------------------------------------------
# divergence sentinels
# ---------------------------------------------------------------------------


class AuditState:
    """One dist run's sentinel state (held on the thread-local RunState,
    armed only by the stream-owning dist driver)."""

    __slots__ = ("scheme", "fp_hash", "audits", "stage", "divergence")

    def __init__(self, scheme: str, fp_hash: int) -> None:
        self.scheme = scheme
        self.fp_hash = fp_hash
        self.audits = 0
        self.stage = ""
        self.divergence: Optional[dict] = None


def _hash63(text: str) -> int:
    """Stable non-negative 63-bit hash (int64-safe for the gather)."""
    digest = hashlib.sha256(text.encode()).digest()
    return int.from_bytes(digest[:8], "big") & 0x7FFFFFFFFFFFFFFF


def arm(scheme: str, graph_fp: str, ctx_fp: str,
        shard_fps: List[str]) -> AuditState:
    """Arm the divergence sentinels for the calling thread's run (dist
    facade entry): every subsequent checkpoint barrier audits the fleet
    until :func:`disarm`.  The three fingerprints fold into ONE hash —
    ranks running a different graph, config, or sharding plan disagree
    on it at the first barrier."""
    st = AuditState(
        scheme, _hash63(f"{graph_fp}|{ctx_fp}|{'|'.join(shard_fps)}")
    )
    runstate.current().dist = st
    return st


def disarm() -> None:
    runstate.current().dist = None


def state() -> Optional[AuditState]:
    return getattr(runstate.current(), "dist", None)


def maybe_audit(stage_id: str) -> None:
    """The barrier piggyback: a no-op (one attribute read) unless the
    dist driver armed the sentinels; armed, one small allgather of
    ``[stage-hash, rung, fingerprint-hash]`` and an exact comparison.
    Divergence raises :class:`RankDivergence` with the per-rank dump —
    annotated into the run report FIRST, so the dump survives into the
    emergency report of the run the raise unwinds."""
    st = state()
    if st is None:
        return
    from .errors import RankDivergence
    from .faults import maybe_inject

    # chaos site: an injected rank-divergence exercises the abort path
    # without needing a genuinely skewed fleet
    maybe_inject("rank-divergence")
    from . import memory as memory_mod

    mem = memory_mod.state()
    rung = int(mem.rung) if mem is not None else 0
    local = [_hash63(stage_id), rung, st.fp_hash]
    rows = gather_i64(local)
    st.stage = stage_id
    if bool((rows == rows[0]).all()):
        st.audits += 1
        return
    dump = [
        {
            "rank": r,
            "stage_hash": int(rows[r, 0]),
            "rung": int(rows[r, 1]),
            "fingerprint_hash": int(rows[r, 2]),
            # only the local rank knows its stage STRING; peers are
            # identified by hash (enough to see who skewed where)
            **({"stage": stage_id} if r == rank() else {}),
        }
        for r in range(rows.shape[0])
    ]
    fields = []
    if not bool((rows[:, 0] == rows[0, 0]).all()):
        fields.append("stage")
    if not bool((rows[:, 1] == rows[0, 1]).all()):
        fields.append("rung")
    if not bool((rows[:, 2] == rows[0, 2]).all()):
        fields.append("fingerprint")
    st.divergence = {
        "barrier": stage_id,
        "fields": fields,
        "ranks": dump,
    }
    from .. import telemetry
    from ..utils.logger import log_warning

    telemetry.event(
        "rank-divergence", barrier=stage_id, fields=fields,
        ranks=len(dump),
    )
    # stamp the dump NOW: the raise below unwinds past the facade's
    # success-path annotations, and the per-rank dump is exactly what a
    # post-crash report must carry
    telemetry.annotate(dist_resilience=section())
    log_warning(
        f"rank divergence at barrier {stage_id}: ranks disagree on "
        f"{'/'.join(fields)} — aborting with the per-rank dump"
    )
    raise RankDivergence(
        f"ranks diverged at barrier {stage_id} on {'/'.join(fields)}: "
        f"{dump}",
        ranks=dump,
        site="rank-divergence",
    )


def section() -> dict:
    """The run report's ``dist_resilience`` sentinel half (the dist
    driver merges in resume/ladder details).  ``{'enabled': False}``
    when no dist run armed the sentinels on this thread."""
    st = state()
    if st is None:
        return {"enabled": False}
    d = {
        "enabled": True,
        "ranks": num_ranks(),
        "rank": rank(),
        "audits": int(st.audits),
        "last_stage": st.stage,
    }
    if st.divergence is not None:
        d["divergence"] = st.divergence
    return d
