"""The degradation contract: ``with_fallback`` + per-site circuit breaker.

One policy wrapper guards every optional fast path (the sites registered
in :mod:`kaminpar_tpu.resilience.faults`).  The contract it enforces:

  * a site failure is a *structured* exception (errors.classify) — an
    unclassified exception propagates unchanged, it is a bug rather than
    a degradation and must not be swallowed;
  * every engaged fallback emits a ``degraded`` telemetry event naming
    the site, the error, and the documented fallback — degradation is
    never silent;
  * repeated failures open a per-site circuit breaker: after
    BREAKER_THRESHOLD consecutive fallback engagements the primary is
    not attempted again this process (a native library that failed to
    load three times will not be retried on every FM call).

Jet-style recoverability (Gilbert et al., Mt-KaHyPar): refiner failure
is an event to roll back from, not a reason to abort the run — see
RefinerPipeline.refine, which uses this wrapper with a rollback-to-
input-partition fallback per algorithm step.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, Optional, TypeVar

from . import faults
from .errors import DegradationError, classify

T = TypeVar("T")

#: Consecutive fallback engagements before a site's breaker opens.
BREAKER_THRESHOLD = 3


@dataclass
class _Breaker:
    consecutive_failures: int = 0
    open: bool = False
    last_error: str = ""


_breakers: Dict[str, _Breaker] = {}


def breaker_state(site: str) -> dict:
    """The site's breaker as a dict (tests, run-report debugging)."""
    br = _breakers.get(site, _Breaker())
    return {
        "open": br.open,
        "consecutive_failures": br.consecutive_failures,
        "last_error": br.last_error,
    }


def reset_breakers() -> None:
    """Close every breaker (test isolation; also sensible between
    independent CLI invocations in one process)."""
    _breakers.clear()


def _emit_degraded(site: str, spec, *, error: str, detail: str,
                   attempts: int, breaker_open: bool, injected: bool,
                   recovered: bool = False, where: str = "") -> None:
    from .. import telemetry
    from ..utils.logger import log_warning

    telemetry.event(
        "degraded",
        site=site,
        error=error,
        detail=detail[:300],
        fallback="retry(primary)" if recovered else spec.fallback,
        attempts=attempts,
        breaker_open=breaker_open,
        injected=injected,
        recovered=recovered,
        where=where or None,
    )
    what = "recovered by retry" if recovered else f"falling back to {spec.fallback}"
    log_warning(
        f"degraded[{site}{'@' + where if where else ''}]: {error} "
        f"({detail[:120]}); {what}"
        + (" [circuit breaker OPEN]" if breaker_open else "")
    )


def with_fallback(
    primary: Callable[[], T],
    fallback: Optional[Callable[[Optional[DegradationError]], T]],
    site: str,
    retries: int = 0,
    where: str = "",
) -> T:
    """Run ``primary()`` under the site's degradation contract.

    * ``site`` must be registered in faults.SITES (KeyError otherwise).
    * Fault injection fires at the site entry (attempt 0 only — an
      injected fault models a deterministic failure and goes straight to
      the fallback; retries exercise real transient failures).
    * On a classified failure, ``primary`` is retried up to ``retries``
      times; recovery by retry emits a ``degraded`` event with
      ``recovered=True`` (the degradation is visible either way).
    * When all attempts fail: the breaker is advanced, a ``degraded``
      event is emitted, and ``fallback(exc)`` is returned.  With
      ``fallback=None`` the structured exception propagates to the
      caller instead (still never silent).
    * With the breaker open the primary is skipped entirely and
      ``fallback(None)`` is returned immediately.
    * ``where`` labels the call site (e.g. the driver phase) in the
      event, so one site wired through several drivers stays tellable.

    Unclassified exceptions (not a DegradationError, not OOM-shaped)
    propagate unchanged — wrapping a site in a bare ``except Exception``
    instead of this policy is a documented tpulint hazard.
    """
    spec = faults.site_spec(site)
    br = _breakers.setdefault(site, _Breaker())
    if br.open:
        _emit_degraded(
            site, spec, error="circuit-open",
            detail=f"breaker open after {br.consecutive_failures} "
                   f"consecutive failures (last: {br.last_error})",
            attempts=0, breaker_open=True, injected=False, where=where,
        )
        if fallback is None:
            raise spec.exc(
                f"site '{site}' circuit breaker is open "
                f"(last error: {br.last_error})", site=site,
            )
        return fallback(None)

    last: Optional[DegradationError] = None
    for attempt in range(max(0, retries) + 1):
        try:
            if attempt == 0:
                faults.maybe_inject(site)
            result = primary()
        except Exception as exc:  # classified below; unknowns re-raise
            from .errors import IntegrityViolation, StageHang

            if isinstance(exc, IntegrityViolation):
                # detected silent data corruption has no documented
                # fallback twin — absorbing it would serve a wrong
                # answer under a `degraded` verdict.  It propagates to
                # the retry-from-last-good-barrier ladder
                # (integrity.run_with_retry) or the caller's explicit
                # re-fetch path, never into this site's fallback.
                raise
            if isinstance(exc, StageHang) and not exc.injected:
                # an async-delivered watchdog verdict (a hung stage)
                # is a process-level failure that happened to LAND
                # inside this site's primary — it must propagate to
                # the hang-containment boundary, never be absorbed as
                # this site's degradation
                raise
            err = classify(exc, site)
            if err is None:
                raise
            last = err
            continue
        br.consecutive_failures = 0
        if attempt and last is not None:
            _emit_degraded(
                site, spec, error=type(last).__name__, detail=str(last),
                attempts=attempt + 1, breaker_open=False,
                injected=last.injected, recovered=True, where=where,
            )
        return result

    assert last is not None
    if last.breaker_relevant:
        # injected faults advance the breaker too: the chaos suite
        # asserts breaker behavior with the same machinery as real
        # failures.  Refusal-shaped errors (breaker_relevant=False —
        # plan blowups, FM refusals) engage the fallback without
        # latching: the next input may be perfectly servable.
        br.consecutive_failures += 1
        br.last_error = f"{type(last).__name__}: {last}"
        br.open = br.consecutive_failures >= BREAKER_THRESHOLD
    _emit_degraded(
        site, spec, error=type(last).__name__, detail=str(last),
        attempts=max(0, retries) + 1, breaker_open=br.open,
        injected=last.injected, where=where,
    )
    if fallback is None:
        raise last
    return fallback(last)
