"""Silent-data-corruption defense: invariant sentinels, exchange
digests, sampled re-execution audits, and the corruption-chaos helpers.

The resilience stack catches every *loud* failure — crashes, hangs,
OOM, preemption, rank divergence — but a flipped bit in a device
buffer, a truncated spill re-read, or a poisoned cache entry produces a
silently-worse (or invalid) result that sails through every verdict as
``served``.  This module is the quiet half of the failure model, four
legs:

  * **invariant sentinels** — cheap algebraic checks at the existing
    phase boundaries: node/edge-weight conservation across each
    contraction, cmap range/surjectivity, coarse-CSR symmetry,
    partition-vector range ``[0, k)``, and cut non-increase across an
    accepted refinement pass.  Each failure raises a structured
    :class:`~kaminpar_tpu.resilience.errors.IntegrityViolation`
    (invariant name + level + scope) that ``policy.with_fallback``
    NEVER absorbs, and that drives the bounded
    retry-from-last-good-barrier ladder (:func:`run_with_retry`:
    one re-execution from the last clean checkpoint barrier before
    giving up with verdict ``corrupt-result``);

  * **checksummed exchange** — content digests on every host-boundary
    handoff that previously trusted bytes: chunkstore spill files
    (external/chunkstore.py), supervised-worker npz replies
    (resilience/supervisor.py), and serving result-cache entries
    (serving/service.py).  A digest mismatch is a classified
    IntegrityViolation, not a crash, and each boundary has a local
    recovery (re-decode / fail the one request / forced miss + evict);

  * **sampled re-execution audits** — ``KAMINPAR_TPU_AUDIT_FRACTION``
    re-runs a deterministic sample of device reductions on the host
    twin and compares bitwise (integer arithmetic is exact on both
    sides), reported per scope as ``{audited, mismatched}``;

  * **corruption chaos** — :func:`chaos_flip_array` /
    :func:`chaos_flip_file` catch an injected fault at the
    ``bit-flip:*`` / ``spill-corrupt`` / ``cache-poison`` /
    ``worker-reply-corrupt`` sites and genuinely mutate bytes in
    flight, so the detectors above are exercised end-to-end.

Dormancy contract: every sentinel/digest runs host-side between
launches; the device-side checks are SEPARATE small jitted reductions
(the telemetry/quality.py precedent) — the LP / Jet / contraction
jaxprs are bitwise-identical with integrity on, off, or disabled.
``KAMINPAR_TPU_INTEGRITY=0`` is the kill switch (sentinels, digests,
and audits all dormant; chaos injection still mutates, which is how
the "undetected corruption is measurably wrong" half of the chaos
proof runs).
"""

from __future__ import annotations

import hashlib
import os
import time
from typing import Any, Callable, Dict, Optional, TypeVar

import numpy as np

from .errors import IntegrityViolation

ENV_INTEGRITY = "KAMINPAR_TPU_INTEGRITY"
ENV_AUDIT_FRACTION = "KAMINPAR_TPU_AUDIT_FRACTION"

#: Bounded retry ladder: how many re-executions from the last clean
#: barrier one run gets before the verdict is ``corrupt-result``.
MAX_RETRIES = 1

T = TypeVar("T")

# ---------------------------------------------------------------------------
# module state (host-side; reset() for test isolation)
# ---------------------------------------------------------------------------

_stats: Dict[str, Any] = {
    "checks": 0,
    "violations": [],  # [{invariant, level, scope, site, detail}]
    "retries": 0,
    "recovered": 0,
    "verdict": None,  # None | "recovered" | "corrupt-result"
    "wall_s": 0.0,
}
_digests: Dict[str, int] = {"computed": 0, "verified": 0, "mismatched": 0}
_audits: Dict[str, Dict[str, int]] = {}  # scope -> {audited, mismatched}
_audit_counts: Dict[str, int] = {}  # scope -> sampling call counter

# jitted sentinel reductions, cached per (key) — built lazily so this
# module imports without jax (supervisor-style host-side contract)
_jits: Dict[str, Any] = {}


def enabled() -> bool:
    """Sentinels/digests/audits run unless KAMINPAR_TPU_INTEGRITY=0."""
    return os.environ.get(ENV_INTEGRITY, "") != "0"


def audit_fraction() -> float:
    """The sampled re-execution audit fraction (0 = audits off)."""
    raw = os.environ.get(ENV_AUDIT_FRACTION, "").strip()
    if not raw:
        return 0.0
    try:
        val = float(raw)
    except ValueError:
        return 0.0
    return min(max(val, 0.0), 1.0)


def reset() -> None:
    """Clear counters, violations, audits (test isolation).  The jit
    cache survives — compiled sentinel reductions are state-free."""
    _stats.update(
        checks=0, violations=[], retries=0, recovered=0, verdict=None,
        wall_s=0.0,
    )
    _digests.update(computed=0, verified=0, mismatched=0)
    _audits.clear()
    _audit_counts.clear()


class _timed:
    """Accumulate sentinel wall time (the ``integrity_overhead_pct``
    numerator): every host-side check body runs under one of these."""

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        _stats["wall_s"] += time.perf_counter() - self._t0
        return False


# ---------------------------------------------------------------------------
# violations
# ---------------------------------------------------------------------------


def _record_violation(
    invariant: str, detail: str, *, level: Optional[int], scope: str,
    site: str,
) -> None:
    _stats["violations"].append({
        "invariant": invariant,
        "level": level,
        "scope": scope,
        "site": site,
        "detail": detail[:300],
    })
    from .. import telemetry
    from ..utils.logger import log_warning

    telemetry.event(
        "integrity", action="violation", invariant=invariant,
        level=level, scope=scope, site=site or None,
        detail=detail[:300],
    )
    log_warning(
        f"INTEGRITY violation [{invariant}"
        + (f"@level{level}" if level is not None else "")
        + f"] at {scope or '?'}: {detail[:160]}"
    )


def violation(
    invariant: str, detail: str, *, level: Optional[int] = None,
    scope: str = "", site: str = "",
) -> IntegrityViolation:
    """Record + build (the caller raises) a structured violation."""
    _record_violation(invariant, detail, level=level, scope=scope,
                      site=site)
    return IntegrityViolation(
        f"integrity violation [{invariant}] at {scope or '?'}: {detail}",
        invariant=invariant, level=level, scope_path=scope,
        site=site or None,
    )


def check(
    invariant: str, ok: bool, detail: str, *, level: Optional[int] = None,
    scope: str = "",
) -> None:
    """One sentinel predicate: counts, and raises on failure."""
    _stats["checks"] += 1
    if not ok:
        raise violation(invariant, detail, level=level, scope=scope)


# ---------------------------------------------------------------------------
# invariant sentinels (device reductions separate from the pipeline
# jaxprs — the quality-layer dormancy precedent)
# ---------------------------------------------------------------------------


def _contraction_jit():
    fn = _jits.get("contraction")
    if fn is None:
        import jax
        import jax.numpy as jnp

        from ..ops.segments import ACC_DTYPE

        @jax.jit
        def scalars(fine_graph, cmap, coarse_graph):
            # pad convention (graphs/csr.py): pad nodes/edges carry
            # weight 0, so unmasked weight sums are exact
            fine_nw = jnp.sum(fine_graph.node_w.astype(ACC_DTYPE))
            coarse_nw = jnp.sum(coarse_graph.node_w.astype(ACC_DTYPE))
            # every fine edge whose endpoints land in different clusters
            # contributes its weight to exactly one coarse (directed)
            # edge; contraction sums parallels and drops self-loops, so
            # the directed sums match exactly
            n_pad_c = coarse_graph.node_w.shape[0]
            cm = jnp.clip(cmap, 0, n_pad_c - 1)
            cross = jnp.sum(
                jnp.where(
                    cm[fine_graph.src] != cm[fine_graph.dst],
                    fine_graph.edge_w.astype(ACC_DTYPE),
                    0,
                )
            )
            coarse_ew = jnp.sum(coarse_graph.edge_w.astype(ACC_DTYPE))
            n_pad_f = cmap.shape[0]
            real_f = jnp.arange(n_pad_f) < fine_graph.n
            cmap_min = jnp.min(jnp.where(real_f, cmap, 0))
            cmap_max = jnp.max(jnp.where(real_f, cmap, 0))
            hit = jnp.zeros(n_pad_c, dtype=jnp.int32).at[cm].max(
                real_f.astype(jnp.int32), mode="drop"
            )
            real_c = jnp.arange(n_pad_c) < coarse_graph.n
            distinct = jnp.sum(jnp.where(real_c, hit, 0).astype(ACC_DTYPE))
            # CSR symmetry necessary conditions on the coarse graph:
            # equal directed weight both ways, zero self-loop weight
            w = coarse_graph.edge_w.astype(ACC_DTYPE)
            fwd = jnp.sum(
                jnp.where(coarse_graph.src < coarse_graph.dst, w, 0)
            )
            bwd = jnp.sum(
                jnp.where(coarse_graph.src > coarse_graph.dst, w, 0)
            )
            loops = jnp.sum(
                jnp.where(coarse_graph.src == coarse_graph.dst, w, 0)
            )
            return (fine_nw, coarse_nw, cross, coarse_ew, cmap_min,
                    cmap_max, distinct, fwd, bwd, loops)

        fn = _jits["contraction"] = scalars
    return fn


def check_contraction(
    fine_graph, cmap, coarse_graph, *, level: int, fine_n: int,
    coarse_n: int,
) -> None:
    """Contraction sentinels at the coarsening phase boundary.

    One separate jitted reduction returns ten scalars; every compare
    runs host-side.  Conservation is level-local (fine sum vs coarse
    sum of the SAME level) so preprocessing that legitimately drops
    weight before coarsening — isolated-node removal, subgraph
    extraction in deep partitioning — never trips the sentinel.
    No-op when integrity is disabled."""
    if not enabled():
        return
    vals = _contraction_jit()(fine_graph, cmap, coarse_graph)
    with _timed():
        (fine_nw, coarse_nw, cross, coarse_ew, cmap_min, cmap_max,
         distinct, fwd, bwd, loops) = (int(v) for v in vals)
        scope = f"coarsen:{level}"
        check(
            "node-weight-conservation",
            coarse_nw == fine_nw,
            f"coarse node-weight sum {coarse_nw} != fine {fine_nw}",
            level=level, scope=scope,
        )
        check(
            "edge-weight-conservation",
            cross == coarse_ew,
            f"fine cross-cluster edge weight {cross} != coarse edge "
            f"weight {coarse_ew}",
            level=level, scope=scope,
        )
        check(
            "cmap-range",
            0 <= cmap_min and cmap_max < coarse_n,
            f"cmap range [{cmap_min}, {cmap_max}] outside "
            f"[0, {coarse_n})",
            level=level, scope=scope,
        )
        check(
            "cmap-surjective",
            distinct == coarse_n,
            f"{distinct} distinct coarse ids hit, expected {coarse_n}",
            level=level, scope=scope,
        )
        check(
            "coarse-csr-symmetry",
            fwd == bwd and loops == 0,
            f"directed weight {fwd} vs {bwd}, self-loop weight {loops}",
            level=level, scope=scope,
        )
    # sampled re-execution audit: recompute the coarse node weights on
    # the host from the fine weights + projection map (np.bincount) and
    # compare the device scatter bitwise
    if should_audit("contraction-weights"):
        with _timed():
            nw = np.asarray(fine_graph.node_w)[:fine_n].astype(np.int64)
            cm = np.asarray(cmap)[:fine_n].astype(np.int64)
            host_bw = np.bincount(
                np.clip(cm, 0, max(coarse_n - 1, 0)), weights=nw,
                minlength=coarse_n,
            ).astype(np.int64)
            dev_bw = np.asarray(
                coarse_graph.node_w
            )[:coarse_n].astype(np.int64)
            record_audit(
                "contraction-weights",
                mismatched=not np.array_equal(host_bw, dev_bw),
                level=level,
            )


def _refine_jit(has_min: bool):
    key = f"refine:{has_min}"
    fn = _jits.get(key)
    if fn is None:
        import jax
        import jax.numpy as jnp

        from ..ops import metrics

        @jax.jit
        def scalars(graph, partition, max_bw, min_bw=None):
            cut = metrics.edge_cut(graph, partition)
            feas = metrics.is_feasible(graph, partition, max_bw, min_bw)
            real = jnp.arange(partition.shape[0]) < graph.n
            pmin = jnp.min(jnp.where(real, partition, 0))
            pmax = jnp.max(jnp.where(real, partition, 0))
            return cut, feas, pmin, pmax

        if has_min:
            fn = scalars
        else:
            fn = lambda g, p, mx: scalars(g, p, mx)  # noqa: E731
        _jits[key] = fn
    return fn


def refine_probe(graph, partition, max_block_weights, min_block_weights):
    """(cut, feasible, part_min, part_max) for the refinement sentinels
    — one separate jitted reduction, host ints out.  None when
    integrity is disabled."""
    if not enabled():
        return None
    if min_block_weights is None:
        vals = _refine_jit(False)(graph, partition, max_block_weights)
    else:
        vals = _refine_jit(True)(
            graph, partition, max_block_weights, min_block_weights
        )
    cut, feas, pmin, pmax = vals
    return int(cut), bool(feas), int(pmin), int(pmax)


def check_refinement(
    before, after, *, k: int, level: int,
) -> None:
    """Refinement sentinels across one accepted refine pass: partition
    range ``[0, k)`` and cut non-increase.  ``before``/``after`` are
    :func:`refine_probe` tuples (None = disabled, no-op).

    Cut non-increase is guarded on feasibility BOTH sides: a balancer
    legitimately trades cut for balance on an infeasible input, so only
    a feasible->feasible pass that still raised the cut is corrupt."""
    if before is None or after is None:
        return
    with _timed():
        cut_b, feas_b, _, _ = before
        cut_a, feas_a, pmin, pmax = after
        scope = f"refine:{level}"
        check(
            "partition-range",
            0 <= pmin and pmax < k,
            f"partition range [{pmin}, {pmax}] outside [0, {k})",
            level=level, scope=scope,
        )
        check(
            "cut-non-increase",
            not (feas_b and feas_a and cut_a > cut_b),
            f"accepted refinement pass raised the cut {cut_b} -> {cut_a} "
            "on a feasible partition",
            level=level, scope=scope,
        )


def audit_refine_cut(graph, partition, device_cut: int, *,
                     level: int) -> None:
    """Sampled host-twin re-execution of one cut evaluation: recompute
    the edge cut from the host CSR with numpy and compare the device
    value bitwise (integer arithmetic, exact both ways)."""
    if not enabled() or not should_audit("refine-cut"):
        return
    with _timed():
        from ..graphs.csr import host_graph_from_device

        host = host_graph_from_device(graph)
        part = np.asarray(partition)[: host.n]
        xadj = np.asarray(host.xadj, dtype=np.int64)
        owner = np.repeat(
            np.arange(host.n, dtype=np.int64), np.diff(xadj)
        )
        crosses = part[owner] != part[np.asarray(host.adjncy)]
        ew = np.asarray(host.edge_weight_array(), dtype=np.int64)
        host_cut = int(ew[crosses].sum()) // 2
        record_audit(
            "refine-cut", mismatched=host_cut != int(device_cut),
            level=level,
            detail=f"host {host_cut} vs device {int(device_cut)}",
        )


# ---------------------------------------------------------------------------
# sampled audits
# ---------------------------------------------------------------------------


def should_audit(scope: str) -> bool:
    """Deterministic per-scope sampling at KAMINPAR_TPU_AUDIT_FRACTION:
    the draw is keyed by (seed, scope, call index), so reruns audit the
    same calls (the faults.py determinism contract)."""
    frac = audit_fraction()
    if frac <= 0.0 or not enabled():
        return False
    count = _audit_counts.get(scope, 0) + 1
    _audit_counts[scope] = count
    if frac >= 1.0:
        return True
    from ..utils import rng as rng_mod

    seed = rng_mod.get_seed()
    digest = hashlib.sha256(
        f"audit:{seed}:{scope}:{count}".encode()
    ).digest()
    return int.from_bytes(digest[:8], "big") / float(1 << 64) < frac


def record_audit(scope: str, *, mismatched: bool,
                 level: Optional[int] = None, detail: str = "") -> None:
    """Count one audited re-execution; a bitwise mismatch is a
    violation (raised) on top of the per-scope tally."""
    ent = _audits.setdefault(scope, {"audited": 0, "mismatched": 0})
    ent["audited"] += 1
    if mismatched:
        ent["mismatched"] += 1
        raise violation(
            f"audit:{scope}",
            detail or "host re-execution disagreed with the device "
                      "value bitwise",
            level=level, scope=f"audit:{scope}",
        )


# ---------------------------------------------------------------------------
# exchange digests
# ---------------------------------------------------------------------------


def content_digest(*arrays) -> str:
    """sha256 hex over the raw bytes of the given numpy arrays (shape
    and dtype folded in, so a reinterpretation cannot collide)."""
    h = hashlib.sha256()
    for a in arrays:
        a = np.ascontiguousarray(a)
        h.update(f"{a.dtype.str}:{a.shape};".encode())
        h.update(a.tobytes())
    _digests["computed"] += 1
    return h.hexdigest()


def verify_digest(expected: str, *arrays, what: str = "",
                  site: str = "") -> None:
    """Recompute and compare a content digest; mismatch raises a
    classified IntegrityViolation (invariant ``exchange-digest``).
    A missing expected digest verifies vacuously (pre-upgrade data)."""
    if not expected or not enabled():
        return
    with _timed():
        actual = content_digest(*arrays)
        _digests["computed"] -= 1  # verification, not a new stamp
        _digests["verified"] += 1
        if actual != expected:
            _digests["mismatched"] += 1
            raise violation(
                "exchange-digest",
                f"{what or 'payload'}: digest {actual[:16]}... != "
                f"expected {expected[:16]}...",
                scope=what, site=site,
            )


def note_digest_mismatch(what: str, detail: str, *,
                         site: str = "") -> IntegrityViolation:
    """Record an externally detected digest mismatch (io/snapshot.py's
    SnapshotError path) as a classified violation; returns the exception
    for the caller to raise or recover from."""
    _digests["verified"] += 1
    _digests["mismatched"] += 1
    return violation("exchange-digest", f"{what}: {detail}",
                     scope=what, site=site)


# ---------------------------------------------------------------------------
# corruption chaos (faults.py sites; mutation is genuine)
# ---------------------------------------------------------------------------


def chaos_flip_array(site: str, arr: np.ndarray, *,
                     bit: int = 7) -> np.ndarray:
    """Injection hook for in-flight array corruption: when the fault
    plan fires at ``site``, return a copy with one bit of element 0
    flipped (a genuine mutation — the DETECTORS are what chaos tests);
    otherwise return ``arr`` unchanged.  Never raises."""
    from . import faults

    try:
        faults.maybe_inject(site)
    except IntegrityViolation:
        out = np.array(arr, copy=True)
        flat = out.reshape(-1)
        flat[0] = flat[0] ^ type(flat[0])(1 << bit)
        from .. import telemetry

        telemetry.event(
            "integrity", action="chaos-corrupt", site=site,
            kind="array", bit=bit,
        )
        return out
    return arr


def chaos_corrupt_contraction(coarse):
    """``bit-flip:contraction`` chaos: when the fault plan fires, flip
    one bit of the first coarse edge-weight slot (pull, flip,
    re-upload) — an accelerator-SDC stand-in.  The edge-weight
    conservation and CSR-symmetry sentinels are what detect it; with
    integrity disabled the wrong weight silently biases every deeper
    coarsening/refinement decision."""
    from . import faults

    try:
        faults.maybe_inject("bit-flip:contraction")
    except IntegrityViolation:
        import dataclasses

        import jax.numpy as jnp

        ew = np.array(np.asarray(coarse.graph.edge_w), copy=True)
        flat = ew.reshape(-1)
        flat[0] = flat[0] ^ flat.dtype.type(1 << 5)
        graph = dataclasses.replace(
            coarse.graph, edge_w=jnp.asarray(ew)
        )
        from .. import telemetry

        telemetry.event(
            "integrity", action="chaos-corrupt",
            site="bit-flip:contraction", kind="edge-weight", bit=5,
        )
        return dataclasses.replace(coarse, graph=graph)
    return coarse


def chaos_corrupt_partition(partition):
    """``bit-flip:partition`` chaos: when the fault plan fires, flip bit
    20 of the first partition label (pull, flip, re-upload).  Bit 20
    puts the label far outside any padded ``[0, k)`` bucket, so the
    partition-range sentinel fires at the refinement boundary — BEFORE
    the output gate's repair pass could quietly heal it."""
    from . import faults

    try:
        faults.maybe_inject("bit-flip:partition")
    except IntegrityViolation:
        import jax.numpy as jnp

        part = np.array(np.asarray(partition), copy=True)
        flat = part.reshape(-1)
        flat[0] = flat[0] ^ flat.dtype.type(1 << 20)
        from .. import telemetry

        telemetry.event(
            "integrity", action="chaos-corrupt",
            site="bit-flip:partition", kind="partition", bit=20,
        )
        return jnp.asarray(part)
    return partition


def chaos_flip_file(site: str, path: str) -> bool:
    """Injection hook for at-rest byte corruption: when the fault plan
    fires at ``site``, flip one bit of the middle byte of ``path`` in
    place.  Returns True when the file was mutated."""
    from . import faults

    try:
        faults.maybe_inject(site)
    except IntegrityViolation:
        try:
            size = os.path.getsize(path)
            if size <= 0:
                return False
            with open(path, "r+b") as f:
                f.seek(size // 2)
                b = f.read(1)
                f.seek(size // 2)
                f.write(bytes([b[0] ^ 0x40]))
            from .. import telemetry

            telemetry.event(
                "integrity", action="chaos-corrupt", site=site,
                kind="file", path=os.path.basename(path),
            )
            return True
        except OSError:
            return False
    return False


# ---------------------------------------------------------------------------
# the retry-from-last-good-barrier ladder
# ---------------------------------------------------------------------------


def run_with_retry(body: Callable[[], T], *, where: str = "") -> T:
    """Run the pipeline body under the bounded corruption-recovery
    ladder: on the first IntegrityViolation, reload the last clean
    checkpoint barrier (the sentinel fired BEFORE its level's barrier,
    so the newest manifest is by construction pre-corruption) and
    re-execute once; a second violation is the ``corrupt-result``
    verdict and propagates.  Fault counters are deliberately NOT reset,
    so a deterministic ``nth=K`` injection does not re-fire — the
    retried run is clean and (deterministic seeds) cut-identical to an
    uninjected one."""
    if not enabled():
        return body()
    last: Optional[IntegrityViolation] = None
    for attempt in range(MAX_RETRIES + 1):
        try:
            result = body()
        except IntegrityViolation as exc:
            last = exc
            if attempt >= MAX_RETRIES:
                break
            _stats["retries"] += 1
            resumed = _reload_last_barrier()
            try:
                from .. import telemetry

                telemetry.event(
                    "integrity", action="retry",
                    invariant=exc.invariant, level=exc.level,
                    scope=exc.scope_path, where=where or None,
                    resumed_from=resumed,
                )
            except Exception:
                pass
            try:
                from ..utils.logger import log_warning

                log_warning(
                    f"integrity: retrying from "
                    f"{resumed or 'scratch'} after violation "
                    f"[{exc.invariant}]"
                )
            except Exception:
                pass
            continue
        if attempt and last is not None:
            _stats["recovered"] += 1
            _stats["verdict"] = "recovered"
            try:
                from .. import telemetry

                telemetry.event(
                    "integrity", action="recovered",
                    invariant=last.invariant, level=last.level,
                    where=where or None,
                )
            except Exception:
                pass
        return result
    assert last is not None
    _stats["verdict"] = "corrupt-result"
    try:
        from .. import telemetry

        telemetry.event(
            "integrity", action="corrupt-result",
            invariant=last.invariant, level=last.level,
            where=where or None,
        )
    except Exception:
        pass
    raise last


def _reload_last_barrier() -> Optional[str]:
    """Re-arm the run's checkpoint resume state from the last persisted
    manifest (the last clean barrier).  Returns the stage id the retry
    will resume from, or None (no manager / no checkpoint: the retry
    re-executes from scratch, which IS the last clean barrier then)."""
    from . import runstate

    mgr = runstate.current().manager
    if mgr is None or not mgr.enabled or mgr.memory_only:
        return None
    try:
        state = mgr.load_resume_state()
    except Exception:
        return None
    if state is None:
        return None
    lvl = state.get("level")
    return (
        str(state.get("stage", ""))
        + ("" if lvl is None else f":{int(lvl)}")
    )


# ---------------------------------------------------------------------------
# report surface (schema v14 `integrity` section)
# ---------------------------------------------------------------------------


def summary() -> Dict[str, Any]:
    """The run report's ``integrity`` section.  The well-formed
    disabled default when the kill switch is set and nothing ran."""
    active = (
        enabled()
        or _stats["checks"] > 0
        or bool(_stats["violations"])
        or _digests["verified"] > 0
    )
    if not active:
        return {"enabled": False}
    clean = not _stats["violations"]
    return {
        "enabled": bool(enabled()),
        "checks": int(_stats["checks"]),
        "violations": [dict(v) for v in _stats["violations"]],
        "retries": int(_stats["retries"]),
        "recovered": int(_stats["recovered"]),
        "verdict": (
            _stats["verdict"] if _stats["verdict"] is not None
            else ("clean" if clean else "detected")
        ),
        "digests": dict(_digests),
        "audits": {k: dict(v) for k, v in sorted(_audits.items())},
        "audit_fraction": audit_fraction(),
        "wall_s": round(float(_stats["wall_s"]), 6),
    }


def overhead_pct(total_wall_s: float) -> float:
    """Sentinel wall time as a percentage of a run's total wall (the
    bench's always-present ``integrity_overhead_pct`` key)."""
    total = float(total_wall_s)
    if total <= 0:
        return 0.0
    return round(100.0 * float(_stats["wall_s"]) / total, 3)
