"""Deadline budget + cooperative preemption (the anytime contract).

``--time-budget SECS`` installs a *monotonic* deadline that the pipeline
checks cooperatively at its natural barriers (coarsening levels, initial
partitioning, per-level uncoarsening — the same barriers the checkpoint
manager uses) and between refiner algorithm steps.  Nothing is ever
interrupted mid-kernel: on expiry the drivers stop *starting* new
optional work (further coarsening, refinement passes, v-cycles), finish
the mandatory work that validity requires (projection, partition
extension to k, balance enforcement, the output gate/repair), and the
facade annotates the result ``anytime: true`` with the deepest stage
reached.

SIGTERM/SIGINT route through the same path: the CLI installs handlers
that *request a stop* instead of raising, so a preemption notice yields
a valid (possibly lower-quality) partition plus a final checkpoint
instead of a stack trace.  A second signal of the same kind restores the
default behavior (a determined Ctrl-C still kills the process; the CLI
then unwinds open timer scopes and writes an emergency report — see
cli.py / utils/timer.Timer.unwind).

Module-global by design, like the fault harness and the telemetry
stream: one deadline governs one process-wide run; ``clear()`` between
runs (the facade does this) keeps sequential runs independent.
"""

from __future__ import annotations

import signal
import time
from typing import Optional

#: Default DECLARED wind-down grace on top of the budget: the allowance
#: the mandatory tail (extension, gate/repair, final checkpoint, report)
#: is expected to fit.  Advisory — reported in the anytime section so
#: operators can size preemption windows; the cooperative tail is not
#: forcibly interrupted.  Overridable via ctx.resilience.budget_grace.
DEFAULT_GRACE_S = 30.0

_budget_s: Optional[float] = None
_grace_s: float = DEFAULT_GRACE_S
_t0: Optional[float] = None
_deadline: Optional[float] = None
_stop = False
_reason = ""
_stage = ""
_stage_at_stop = ""
_announced = False
_prev_handlers: dict = {}


def install_budget(budget_s: float, grace_s: Optional[float] = None) -> None:
    """Arm a fresh deadline ``budget_s`` seconds from now."""
    global _budget_s, _grace_s, _t0, _deadline, _stop, _reason, _announced
    _budget_s = float(budget_s)
    _grace_s = float(grace_s) if grace_s is not None else DEFAULT_GRACE_S
    _t0 = time.monotonic()
    _deadline = _t0 + _budget_s
    _stop = False
    _reason = ""
    _announced = False


def clear() -> None:
    """Disarm the deadline and any pending stop request (between runs)."""
    global _budget_s, _t0, _deadline, _stop, _reason, _stage, _announced
    global _stage_at_stop
    _budget_s = None
    _t0 = None
    _deadline = None
    _stop = False
    _reason = ""
    _stage = ""
    _stage_at_stop = ""
    _announced = False


def begin_run(budget_s: Optional[float] = None,
              grace_s: Optional[float] = None) -> None:
    """Per-run reset used by the facades (shm and dist): clears stale
    budget/stage state from a previous run, arms a fresh budget when one
    is configured — but PRESERVES a pending preemption signal.  A
    SIGTERM that arrived while the graph was still loading must wind
    down the run that follows, not be silently discarded."""
    pending = _stop and _reason in ("sigterm", "sigint")
    reason = _reason
    clear()
    if budget_s is not None and budget_s > 0:
        install_budget(budget_s, grace_s)
    if pending:
        request_stop(reason)


def agreed_stop() -> bool:
    """Cross-process-consistent wind-down verdict, for control flow that
    gates COLLECTIVE work: every process must take the same branch or a
    shard_map collective deadlocks mid-wind-down.  Per-rank clocks and
    per-rank signal delivery can disagree by a barrier, so the local
    verdicts are max-reduced; any rank stopping stops all.  On a single
    process (this repo's usual mesh driver) it is exactly should_stop().
    """
    local = should_stop()
    try:
        from ..utils.platform import process_count

        if process_count() <= 1:
            return local
        import numpy as np
        from jax.experimental import multihost_utils

        flags = np.asarray(
            multihost_utils.process_allgather(
                np.asarray([1 if local else 0], dtype=np.int32)
            )
        )
        agreed = bool(flags.max())
    except Exception:
        return local
    if agreed and not local:
        request_stop("peer")  # keep local state coherent with the fleet
    return agreed


def request_stop(reason: str) -> None:
    """Ask the pipeline to wind down at its next barrier (signal handlers,
    tests).  Safe to call from a signal handler: sets flags only."""
    global _stop, _reason
    if not _stop:
        _stop = True
        _reason = reason


def should_stop() -> bool:
    """True once the budget has expired or a stop was requested.  The
    first True transition emits a ``deadline`` telemetry event and a log
    line (once), so the wind-down is visible in the run report."""
    global _stop, _reason, _announced, _stage_at_stop
    if not _stop and _deadline is not None and time.monotonic() >= _deadline:
        _stop = True
        _reason = _reason or "budget"
    if _stop and not _announced:
        _announced = True
        _stage_at_stop = _stage  # where the wind-down actually began
        _announce()
    return _stop


def _announce() -> None:
    from .. import telemetry
    from ..utils.logger import log_warning

    telemetry.event(
        "deadline",
        reason=_reason,
        stage=_stage or None,
        budget_s=_budget_s,
        elapsed_s=None if _t0 is None else round(time.monotonic() - _t0, 3),
    )
    log_warning(
        f"deadline: winding down ({_reason}) at stage "
        f"'{_stage or 'start'}' — finishing mandatory work only"
    )


def triggered() -> bool:
    """True when the run wound down early (deadline or stop request)."""
    return _stop


def note_stage(stage: str) -> None:
    """Record the deepest pipeline stage reached (barrier bookkeeping;
    the `anytime` annotation reports it)."""
    global _stage
    _stage = stage


def stage_reached() -> str:
    return _stage


def state() -> dict:
    """The run report's `anytime` section for a wound-down run (None
    values are omitted so the section validates against the schema's
    typed optional properties)."""
    d = {
        "anytime": bool(_stop),
        "reason": _reason or None,
        "stage": _stage_at_stop or _stage or None,
        "budget_s": _budget_s,
        "grace_s": _grace_s if _budget_s is not None else None,
        "elapsed_s": (
            None if _t0 is None else round(time.monotonic() - _t0, 3)
        ),
    }
    return {k: v for k, v in d.items() if v is not None or k == "anytime"}


def grace_s() -> float:
    return _grace_s


def install_signal_handlers() -> None:
    """Route SIGTERM/SIGINT into the cooperative wind-down (CLI entry
    points only — a library must not hijack the host's signals).

    First delivery requests a stop; a second delivery of the same signal
    restores the previous handler and re-raises it, so a stuck run can
    still be killed the classic way.  Idempotent."""
    if _prev_handlers:
        return

    def _handler(signum, frame):
        name = signal.Signals(signum).name
        request_stop(name.lower())
        # second delivery: give the signal back to its old handler
        prev = _prev_handlers.get(signum, signal.SIG_DFL)
        try:
            signal.signal(signum, prev)
        except (ValueError, OSError):
            pass
        # handlers may not log safely in all contexts; stderr write is
        # async-signal-tolerant enough for a one-line notice
        import sys

        sys.stderr.write(
            f"\n[{name}] wind-down requested: finishing at the next "
            "pipeline barrier (send again to force)\n"
        )

    for signum in (signal.SIGTERM, signal.SIGINT):
        try:
            _prev_handlers[signum] = signal.signal(signum, _handler)
        except (ValueError, OSError):
            # not the main thread / unsupported platform: skip silently,
            # the cooperative budget path still works
            _prev_handlers.pop(signum, None)


def uninstall_signal_handlers() -> None:
    """Restore the handlers replaced by install_signal_handlers (tests)."""
    for signum, prev in list(_prev_handlers.items()):
        try:
            signal.signal(signum, prev)
        except (ValueError, OSError):
            pass
    _prev_handlers.clear()
