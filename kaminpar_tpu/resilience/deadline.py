"""Deadline budget + cooperative preemption (the anytime contract).

``--time-budget SECS`` installs a *monotonic* deadline that the pipeline
checks cooperatively at its natural barriers (coarsening levels, initial
partitioning, per-level uncoarsening — the same barriers the checkpoint
manager uses) and between refiner algorithm steps.  Nothing is ever
interrupted mid-kernel: on expiry the drivers stop *starting* new
optional work (further coarsening, refinement passes, v-cycles), finish
the mandatory work that validity requires (projection, partition
extension to k, balance enforcement, the output gate/repair), and the
facade annotates the result ``anytime: true`` with the deepest stage
reached.

SIGTERM/SIGINT route through the same path: the CLI installs handlers
that *request a stop* instead of raising, so a preemption notice yields
a valid (possibly lower-quality) partition plus a final checkpoint
instead of a stack trace.  A second signal of the same kind restores the
default behavior (a determined Ctrl-C still kills the process; the CLI
then unwinds open timer scopes and writes an emergency report — see
cli.py / utils/timer.Timer.unwind).

State model (PR 6): the budget/stop/stage flags live on an explicit
per-run :class:`~kaminpar_tpu.resilience.runstate.RunState` object, one
per run, resolved through a thread-local slot — the function API here is
unchanged, but back-to-back and interleaved runs (the serving layer's
request stream) can no longer consume each other's verdicts.  Signals
are process-wide by nature and live in one shared slot that every run's
``should_stop()`` folds in — a SIGTERM drains *every* run and the
serving queue, which is exactly the drain contract.
"""

from __future__ import annotations

import signal
import time
from typing import Optional

from . import runstate

#: Default DECLARED wind-down grace on top of the budget: the allowance
#: the mandatory tail (extension, gate/repair, final checkpoint, report)
#: is expected to fit.  Advisory — reported in the anytime section so
#: operators can size preemption windows; the cooperative tail is not
#: forcibly interrupted.  Overridable via ctx.resilience.budget_grace.
DEFAULT_GRACE_S = runstate.DEFAULT_GRACE_S

_prev_handlers: dict = {}


def install_budget(budget_s: float, grace_s: Optional[float] = None,
                   hard_factor: Optional[float] = None) -> None:
    """Arm a fresh deadline ``budget_s`` seconds from now (on the
    calling thread's current run).

    The budget here is COOPERATIVE: it is checked between kernel
    launches at the pipeline barriers, so it can never interrupt a hung
    launch, a hung backend init, or a stuck native call.  The hard
    wall-clock watchdog (resilience/supervisor.py) is the backstop for
    that failure class — when a hard ceiling is active for this budget
    a ``watchdog-armed`` telemetry event records it, so a run report
    shows whether hang containment was armed or the budget was on its
    own (docs/robustness.md, "Supervision contract")."""
    run = runstate.current()
    run.budget_s = float(budget_s)
    run.grace_s = float(grace_s) if grace_s is not None else DEFAULT_GRACE_S
    run.t0 = time.monotonic()
    run.deadline = run.t0 + run.budget_s
    run.stop = False
    run.reason = ""
    run.announced = False
    from . import supervisor

    # hard_factor comes from the caller's resilience context (the
    # facade threads ctx.resilience.hard_deadline_factor through
    # begin_run) so the event reports the ceiling that is ACTUALLY
    # armed — factor 0 arms nothing and must emit nothing
    ceiling = supervisor.hard_ceiling(run.budget_s, run.grace_s,
                                      hard_factor)
    if ceiling is not None:
        from .. import telemetry

        telemetry.event(
            "watchdog-armed",
            ceiling_s=round(ceiling, 3),
            budget_s=run.budget_s,
        )


def clear() -> None:
    """Disarm the deadline and any pending stop request — including a
    process-wide signal flag (test isolation between runs)."""
    runstate.begin()
    runstate.clear_signal()


def begin_run(budget_s: Optional[float] = None,
              grace_s: Optional[float] = None,
              hard_factor: Optional[float] = None) -> None:
    """Per-run reset used by the facades (shm and dist): installs a
    FRESH run state — stale budget/stage/stop state from a previous run
    is structurally unreachable, not merely cleared — and arms the
    configured budget.  A pending process-wide preemption signal is
    deliberately NOT dropped: a SIGTERM that arrived while the graph was
    still loading must wind down the run that follows.  ``hard_factor``
    is the caller's ctx.resilience.hard_deadline_factor — it sizes the
    `watchdog-armed` event so the report matches the ceiling the facade
    actually arms."""
    runstate.begin()
    if budget_s is not None and budget_s > 0:
        install_budget(budget_s, grace_s, hard_factor)
    sig = runstate.signal_reason()
    if sig:
        request_stop(sig)


def draining() -> str:
    """The pending process-wide preemption reason ("" when none) — the
    serving layer's drain gate: once set, queued requests are rejected
    with verdict `rejected`/`draining` while the in-flight run finishes
    its mandatory tail through the normal wind-down."""
    return runstate.signal_reason()


def agreed_stop() -> bool:
    """Cross-process-consistent wind-down verdict, for control flow that
    gates COLLECTIVE work: every process must take the same branch or a
    shard_map collective deadlocks mid-wind-down.  Per-rank clocks and
    per-rank signal delivery can disagree by a barrier, so the local
    verdicts are max-reduced; any rank stopping stops all.  On a single
    process (this repo's usual mesh driver) it is exactly should_stop().
    """
    local = should_stop()
    try:
        # one shared agreement primitive (resilience/agreement.py):
        # allgather-max over the per-rank verdicts — the same idiom the
        # memory ladder's agreed rung uses, and the same test hook
        from .agreement import agree_max

        agreed = bool(agree_max(1 if local else 0)[0])
    except Exception:
        return local
    if agreed and not local:
        request_stop("peer")  # keep local state coherent with the fleet
    return agreed


def request_stop(reason: str) -> None:
    """Ask the pipeline to wind down at its next barrier (signal handlers,
    tests, the serving drain).  Safe to call from a signal handler: sets
    flags only.  Signal-shaped reasons are recorded process-wide (every
    run and the serving queue observe them); anything else stops only
    the calling thread's current run."""
    if reason in ("sigterm", "sigint", "draining"):
        runstate.signal_stop(reason)
    run = runstate.current()
    if not run.stop:
        run.stop = True
        run.reason = reason


def should_stop() -> bool:
    """True once the budget has expired, a stop was requested, or a
    process-wide preemption signal is pending.  The first True
    transition emits a ``deadline`` telemetry event and a log line
    (once), so the wind-down is visible in the run report."""
    run = runstate.current()
    if not run.stop:
        sig = runstate.signal_reason()
        if sig:
            run.stop = True
            run.reason = sig
        elif run.deadline is not None and time.monotonic() >= run.deadline:
            run.stop = True
            run.reason = run.reason or "budget"
    if run.stop and not run.announced:
        run.announced = True
        run.stage_at_stop = run.stage  # where the wind-down actually began
        _announce(run)
    return run.stop


def _announce(run) -> None:
    from .. import telemetry
    from ..utils.logger import log_warning

    telemetry.event(
        "deadline",
        reason=run.reason,
        stage=run.stage or None,
        budget_s=run.budget_s,
        elapsed_s=(
            None if run.t0 is None
            else round(time.monotonic() - run.t0, 3)
        ),
    )
    log_warning(
        f"deadline: winding down ({run.reason}) at stage "
        f"'{run.stage or 'start'}' — finishing mandatory work only"
    )


def triggered() -> bool:
    """True when the run wound down early (deadline or stop request)."""
    return runstate.current().stop


def note_stage(stage: str) -> None:
    """Record the deepest pipeline stage reached (barrier bookkeeping;
    the `anytime` annotation reports it)."""
    runstate.current().stage = stage


def stage_reached() -> str:
    return runstate.current().stage


def state() -> dict:
    """The run report's `anytime` section for a wound-down run (None
    values are omitted so the section validates against the schema's
    typed optional properties)."""
    run = runstate.current()
    d = {
        "anytime": bool(run.stop),
        "reason": run.reason or None,
        "stage": run.stage_at_stop or run.stage or None,
        "budget_s": run.budget_s,
        "grace_s": run.grace_s if run.budget_s is not None else None,
        "elapsed_s": (
            None if run.t0 is None
            else round(time.monotonic() - run.t0, 3)
        ),
    }
    return {k: v for k, v in d.items() if v is not None or k == "anytime"}


def grace_s() -> float:
    return runstate.current().grace_s


def install_signal_handlers() -> None:
    """Route SIGTERM/SIGINT into the cooperative wind-down (CLI entry
    points only — a library must not hijack the host's signals).

    First delivery requests a stop; a second delivery of the same signal
    restores the previous handler and re-raises it, so a stuck run can
    still be killed the classic way.  Idempotent."""
    if _prev_handlers:
        return

    def _handler(signum, frame):
        name = signal.Signals(signum).name
        request_stop(name.lower())
        # second delivery: give the signal back to its old handler
        prev = _prev_handlers.get(signum, signal.SIG_DFL)
        try:
            signal.signal(signum, prev)
        except (ValueError, OSError):
            pass
        # handlers may not log safely in all contexts; stderr write is
        # async-signal-tolerant enough for a one-line notice
        import sys

        sys.stderr.write(
            f"\n[{name}] wind-down requested: finishing at the next "
            "pipeline barrier (send again to force)\n"
        )

    for signum in (signal.SIGTERM, signal.SIGINT):
        try:
            _prev_handlers[signum] = signal.signal(signum, _handler)
        except (ValueError, OSError):
            # not the main thread / unsupported platform: skip silently,
            # the cooperative budget path still works
            _prev_handlers.pop(signum, None)


def uninstall_signal_handlers() -> None:
    """Restore the handlers replaced by install_signal_handlers (tests)."""
    for signum, prev in list(_prev_handlers.items()):
        try:
            signal.signal(signum, prev)
        except (ValueError, OSError):
            pass
    _prev_handlers.clear()
