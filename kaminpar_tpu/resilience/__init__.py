"""Graceful degradation, fault injection, and the strict-balance gate.

Three pieces (see docs/robustness.md for the operator view):

  * the **degradation contract** — structured exception types
    (errors.py) plus :func:`with_fallback`, the policy wrapper with
    bounded retry and a per-site circuit breaker (policy.py), wired
    through every optional fast path so a failure degrades visibly (a
    ``degraded`` telemetry event) instead of aborting the run or going
    silent;
  * the **fault-injection harness** — ``KAMINPAR_TPU_FAULTS`` site plans
    (faults.py), deterministic by seed, driving the chaos suite
    (tests/test_resilience.py) and the check_all.sh chaos smoke stage;
  * the **strict-balance output gate** — end-of-pipeline host validation
    of partition invariants with a greedy repair pass (gate.py), so
    ``KaMinPar.compute_partition``'s postcondition holds no matter which
    paths degraded.
"""

from .errors import (  # noqa: F401
    CollectiveTimeout,
    DegradationError,
    DeviceOOM,
    NativeUnavailable,
    PlanBlowup,
    RefinerRefused,
    classify,
)
from .faults import (  # noqa: F401
    ENV_VAR as FAULTS_ENV_VAR,
    FaultPlanError,
    SITES,
    injected_log,
    maybe_inject,
    parse_plan,
    plan_summary,
    site_spec,
)
from .policy import (  # noqa: F401
    BREAKER_THRESHOLD,
    breaker_state,
    reset_breakers,
    with_fallback,
)
from . import gate  # noqa: F401


def reset() -> None:
    """Reset injection counters and circuit breakers (test isolation)."""
    from . import faults as _faults

    _faults.reset()
    reset_breakers()
