"""Graceful degradation, fault injection, and the strict-balance gate.

Three pieces (see docs/robustness.md for the operator view):

  * the **degradation contract** — structured exception types
    (errors.py) plus :func:`with_fallback`, the policy wrapper with
    bounded retry and a per-site circuit breaker (policy.py), wired
    through every optional fast path so a failure degrades visibly (a
    ``degraded`` telemetry event) instead of aborting the run or going
    silent;
  * the **fault-injection harness** — ``KAMINPAR_TPU_FAULTS`` site plans
    (faults.py), deterministic by seed, driving the chaos suite
    (tests/test_resilience.py) and the check_all.sh chaos smoke stage;
  * the **strict-balance output gate** — end-of-pipeline host validation
    of partition invariants with a greedy repair pass (gate.py), so
    ``KaMinPar.compute_partition``'s postcondition holds no matter which
    paths degraded;
  * **preemption-safe checkpoint/resume** — atomic barrier snapshots of
    the multilevel state under ``--checkpoint-dir`` with a versioned,
    checksummed manifest, and ``--resume`` re-entry at the recorded
    stage (checkpoint.py);
  * the **deadline budget / anytime contract** — ``--time-budget`` plus
    SIGTERM/SIGINT routing: cooperative wind-down at the same barriers,
    returning a gate-valid partition annotated ``anytime: true`` instead
    of a stack trace (deadline.py).
"""

from .errors import (  # noqa: F401
    AdmissionRejected,
    CacheDegraded,
    CheckpointCorrupt,
    CheckpointMismatch,
    CheckpointWriteFailed,
    CollectiveTimeout,
    DegradationError,
    DeltaApplyFailed,
    DeviceOOM,
    IntegrityViolation,
    NativeUnavailable,
    PlanBlowup,
    RankDivergence,
    RefinerRefused,
    StageHang,
    WorkerCrash,
    classify,
)
from .faults import (  # noqa: F401
    ENV_VAR as FAULTS_ENV_VAR,
    FaultPlanError,
    SITES,
    injected_log,
    maybe_inject,
    parse_plan,
    plan_summary,
    site_spec,
)
from .policy import (  # noqa: F401
    BREAKER_THRESHOLD,
    breaker_state,
    reset_breakers,
    with_fallback,
)
from . import gate  # noqa: F401
from . import integrity  # noqa: F401
from . import checkpoint  # noqa: F401
from . import deadline  # noqa: F401
from . import agreement  # noqa: F401
from . import supervisor  # noqa: F401


def reset() -> None:
    """Reset injection counters, circuit breakers, the active checkpoint
    manager, any armed deadline, the dist agreement/sentinel state, and
    the supervision watchdog/heartbeat counters (test isolation)."""
    from . import faults as _faults

    _faults.reset()
    reset_breakers()
    integrity.reset()
    checkpoint.deactivate()
    deadline.clear()
    agreement.disarm()
    agreement.set_gather_override(None)
    supervisor.reset()
