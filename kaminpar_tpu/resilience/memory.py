"""Memory-pressure governor: budgeted admission, an OOM recovery ladder,
and host-spilled hierarchies for graphs bigger than HBM.

The source paper's headline claim is bounded-memory scale (~300 GiB of
host RAM for 112B edges); ROADMAP item 4 maps that onto this repo via
the semi-external partitioning literature (arXiv 1404.4887): keep the
fine graph host-resident and stream work to the device.  Before this
module the system had the opposite failure mode — a ``DeviceOOM`` was
*classified* (resilience/errors.py) but only ever handled as a one-shot
site fallback, the whole multilevel hierarchy stayed device-resident for
the entire run, and the serving layer admitted requests with zero memory
model.  The governor turns the PR-7 observability (per-level
``buffer_bytes`` accounting, barrier memory watermarks,
``KAMINPAR_TPU_HBM_BYTES``) into a hard robustness contract:

    **a run either fits its declared memory budget or degrades through a
    deterministic ladder — it never dies with RESOURCE_EXHAUSTED.**

Three mechanisms, one module:

  * **budget + estimator** — :func:`estimate_run_bytes` is a calibrated
    per-phase peak-bytes model for a padded bucket ``(n_pad, m_pad,
    k_pad)`` (coefficients anchored to the coarsener's per-level
    ``buffer_bytes`` accounting and validated against measured
    watermarks in tests/test_memory.py).  It is enforced at two points:
    serving admission (structured ``insufficient-memory`` rejection,
    sized WITHOUT loading the graph) and :func:`preflight` in the
    shm/dist drivers before the device upload.
  * **OOM recovery ladder** — :func:`run_ladder` wraps the facade's core
    partition call.  On a classified ``DeviceOOM`` anywhere under
    ``compute_partition`` it unwinds cleanly (force-closes timer scopes
    opened by the failed attempt via the PR-5 ``Timer.unwind`` idiom,
    sheds the registered bounded caches with ``evict_to``, drops routed
    gather plans, collects garbage) and retries at the next rung:

      ====  =========================================================
      rung  behavior
      ====  =========================================================
      0     normal run (power-of-two shape buckets, resident hierarchy)
      1     tight padding buckets (``caching.pad_policy_scope("tight")``)
      2     \\+ host-spilled hierarchy: coarse levels are dropped from
            device memory at the checkpoint barriers and re-uploaded on
            demand during uncoarsening (cut-identical by construction —
            deterministic pad buckets, same arrays)
      3     semi-external: the fine graph is coarsened HOST-side in
            node-range chunks (the ``io/compressed_binary`` /
            ``device_graph_from_compressed`` edge-block idiom) until the
            coarse graph fits the budget; only the coarse graph and the
            partition vector are ever device-resident
      4     host-only: recursive bisection on the host, no device at all
      ====  =========================================================

    Each engaged rung emits a ``degraded`` telemetry event carrying the
    rung id; the run report gains a ``memory_budget`` section (budget,
    estimate, watermark, rung, spill bytes/reloads).  Only when EVERY
    rung fails is the ``DeviceOOM`` re-raised with
    ``rungs_exhausted=True`` — the one crash-shaped verdict the serving
    per-class breaker may latch on.
  * **proactive pressure** — :func:`on_barrier` (called from the PR-5
    checkpoint barrier hook) compares the live-device-bytes watermark
    against the budget and triggers the rung-2 spill *before* an
    allocation fails, so the common case is graceful, not reactive.

Dormancy contract (pinned by tests/test_memory.py's jaxpr-equality
test): with no declared budget and no ``DeviceOOM`` in flight the
governor is two attribute reads per barrier and a try/except around the
core partition call — jaxprs and cuts are bitwise-identical to a
governor-free build.  ``KAMINPAR_TPU_MEM_GOVERNOR=0`` disables even the
ladder (raw allocator behavior, for debugging).
"""

from __future__ import annotations

import gc
import os
import weakref
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from . import runstate
from .errors import DeviceOOM, classify

#: Kill switch: =0 disables the governor entirely (no ladder, no
#: pressure hook, no admission rule) — raw allocator behavior.
ENV_GOVERNOR = "KAMINPAR_TPU_MEM_GOVERNOR"
#: The declared device-memory budget in bytes (shared with the PR-7
#: observability override — declaring a ceiling now also enforces it).
ENV_BUDGET = "KAMINPAR_TPU_HBM_BYTES"
#: Test hook: force the ladder to START at rung N (0-4).
ENV_FORCE_RUNG = "KAMINPAR_TPU_MEM_RUNG"

#: The ladder's rungs, in engagement order.
RUNG_NORMAL = 0
RUNG_TIGHT_PADS = 1
RUNG_SPILL_HIERARCHY = 2
RUNG_SEMI_EXTERNAL = 3
RUNG_HOST_ONLY = 4

RUNG_NAMES = {
    RUNG_NORMAL: "normal",
    RUNG_TIGHT_PADS: "tight-pads",
    RUNG_SPILL_HIERARCHY: "spill-hierarchy",
    RUNG_SEMI_EXTERNAL: "semi-external",
    RUNG_HOST_ONLY: "host-only",
}

#: Fraction of the budget at which the barrier pressure hook starts
#: shedding caches and spilling hierarchy levels proactively.
PRESSURE_FRACTION = 0.9
#: The semi-external coarsening target: the coarse graph's (spilled-mode)
#: estimate must fit this fraction of the budget before the device
#: pipeline takes over.
STREAM_TARGET_FRACTION = 0.8

# ---------------------------------------------------------------------------
# the peak-bytes estimator
# ---------------------------------------------------------------------------
#
# Calibration (tests/test_memory.py::test_estimator_vs_watermark): the
# model must bound the measured live-device-bytes watermark from above
# (an under-estimate would admit a run the budget cannot hold) while
# staying within 2x of it on the bench shapes (a wild over-estimate
# would reject servable requests).  The resident term is anchored to the
# coarsener's per-level `buffer_bytes` accounting (row_ptr + src + dst +
# edge_w + node_w + cmap); the transient term covers the LP / contraction
# working arrays XLA keeps live between launches (labels, ratings,
# aggregation keys — all n_pad- or m_pad-shaped int32).

#: Resident hierarchy factor over the finest level's CSR.  Levels
#: shrink fast enough (forced-shrink retries, the limping-tail cutoff)
#: that the barrier-sampled watermark sits near ONE fine CSR; 1.5x
#: prices the hierarchy sum with the safety margin the never-under
#: contract needs (calibrated in tests/test_memory.py: the estimate
#: must stay within [1x, 2x] of the measured watermark).
HIERARCHY_FACTOR = 1.5
#: Rung-2 resident factor: the working level + the neighbor being
#: reloaded stay device-resident; the rest of the hierarchy is host.
SPILL_RESIDENT_FACTOR = 1.2
#: n_pad-shaped int32 working arrays live across launches (labels,
#: partition, active sets).
NODE_WORK_ARRAYS = 2
#: m_pad-shaped int32 working arrays held across launches (ratings /
#: aggregation outputs of the contraction).
EDGE_WORK_ARRAYS = 1
#: k_pad-shaped tables (block weights, caps, per-block gains), int64.
K_TABLE_ARRAYS = 8


def _weight_itemsize() -> int:
    try:
        from ..dtypes import WEIGHT_DTYPE

        return int(np.dtype(WEIGHT_DTYPE).itemsize)
    except Exception:
        return 4


def padded_bucket(n: int, m: int, k: int,
                  mode: str = "bucketed") -> Tuple[int, int, int]:
    """The executable-identity bucket ``(n_pad, m_pad, k_pad)`` the run
    would occupy under a pad policy — the unit the estimator prices."""
    from .. import caching

    with caching.pad_policy_scope(mode):
        try:
            from ..graphs.csr import shape_floors

            n_floor, m_floor = shape_floors()
        except Exception:
            n_floor, m_floor = 256, 256
        n_pad = caching.pad_size(int(n) + 1, n_floor)
        m_pad = caching.pad_size(max(int(m), 1), m_floor)
        k_pad = caching.pad_k(max(int(k), 1))
    return n_pad, m_pad, k_pad


def device_csr_bytes(n_pad: int, m_pad: int) -> int:
    """Bytes of one padded device CSR+COO level (the same arrays the
    coarsener's `buffer_bytes` level events count: row_ptr, src, dst,
    edge_w, node_w)."""
    w = _weight_itemsize()
    return 4 * (n_pad + 1) + n_pad * (4 + w) + m_pad * (8 + w)


def estimate_rung_bytes(rung: int, n: int, m: int, k: int) -> int:
    """Peak device bytes of a run at a given ladder rung.

    Rungs 0/1 price the fully resident hierarchy; rungs 2 AND 3 price
    the spilled hierarchy of the graph actually handed to the device —
    at rung 3 that is the coarse graph the host-side coarsening
    produced, and its preflight must price what is really uploaded;
    rung 4 is host-only.  Whether rung 3 can fit a FINE graph at all is
    a different question (the host coarsening shrinks until it fits) —
    :func:`rung_fits` answers that one."""
    if rung >= RUNG_HOST_ONLY:
        return 0
    mode = "bucketed" if rung == RUNG_NORMAL else "tight"
    n_pad, m_pad, k_pad = padded_bucket(n, m, k, mode)
    csr = device_csr_bytes(n_pad, m_pad)
    transient = (
        NODE_WORK_ARRAYS * n_pad * 4
        + EDGE_WORK_ARRAYS * m_pad * 4
        + K_TABLE_ARRAYS * k_pad * 8
    )
    if rung <= RUNG_TIGHT_PADS:
        resident = HIERARCHY_FACTOR * csr
    else:  # spilled hierarchy: working level + the neighbor reloading
        resident = SPILL_RESIDENT_FACTOR * csr
    return int(resident + transient)


def rung_fits(rung: int, n: int, m: int, k: int, budget: int) -> bool:
    """Whether a run over (n, m, k) can fit ``budget`` at a rung.  For
    rungs 0-2 that is the rung estimate itself; rung 3 fits whenever
    the SMALLEST possible device graph (the floor bucket) does — the
    host-side coarsening shrinks the graph until its device share fits;
    rung 4 (host-only) always fits."""
    if rung >= RUNG_HOST_ONLY:
        return True
    if rung == RUNG_SEMI_EXTERNAL:
        fn, fm, fk = padded_bucket(0, 0, k, "tight")
        floor = (
            SPILL_RESIDENT_FACTOR * device_csr_bytes(fn, fm)
            + NODE_WORK_ARRAYS * fn * 4 + EDGE_WORK_ARRAYS * fm * 4
            + K_TABLE_ARRAYS * fk * 8
        )
        return floor <= budget
    return estimate_rung_bytes(rung, n, m, k) <= budget


def estimate_run_bytes(n: int, m: int, k: int, ctx: Any = None) -> int:
    """The admission/report figure: estimated peak device bytes of a
    normal (rung-0) run for the padded bucket of ``(n, m, k)``.  ``ctx``
    is accepted for signature stability (the model currently depends on
    the partition target only through k)."""
    del ctx
    return estimate_rung_bytes(RUNG_NORMAL, n, m, k)


def estimate_stream_bytes(n: int, chunk_edges: int, k: int) -> int:
    """Peak device bytes of the OUT-OF-CORE stream phase
    (external/stream_coarsen.py): two in-flight padded edge-block chunk
    buffers (src_local + dst + weights — the double buffer the async
    dispatch queue holds) plus the fine-level O(n) vectors (labels,
    wanted, cluster weights, node weights, cluster map) and the k
    tables.  This is the figure the external driver shrinks its chunk
    target against, and the serving admission price of an
    external-scheme request — NOT a full-graph estimate, which is
    exactly what the scheme exists to avoid."""
    from .. import caching

    w = _weight_itemsize()
    e_pad = caching.pad_size(max(int(chunk_edges), 1), 4096)
    chunk = e_pad * (4 + 4 + w)
    vectors = int(n) * (4 + 4 + 4 + 2 * w)
    k_pad = caching.pad_k(max(int(k), 1))
    return int(2 * chunk + vectors + K_TABLE_ARRAYS * k_pad * 8)


def min_streamable_bytes(n: int, k: int) -> int:
    """The smallest budget the external scheme can stream a graph under
    (the floor chunk target) — the admission rule for `--scheme
    external` requests: below this not even the O(n) vectors + one
    floor chunk fit, so the request is structurally unserveable."""
    return estimate_stream_bytes(n, 1 << 15, k)


def min_serveable_bytes(n: int, m: int, k: int) -> int:
    """The smallest budget a request can be served DEVICE-RESIDENT under
    (the rung-2 spilled-hierarchy estimate) — the serving admission
    rule: below this, only the streamed/host rungs could run it, which a
    latency-bound service rejects instead (``insufficient-memory``);
    single-shot CLI runs still degrade through all rungs."""
    return estimate_rung_bytes(RUNG_SPILL_HIERARCHY, n, m, k)


# ---------------------------------------------------------------------------
# budget + per-run governor state
# ---------------------------------------------------------------------------


def governor_enabled() -> bool:
    """False only under the KAMINPAR_TPU_MEM_GOVERNOR=0 kill switch."""
    return os.environ.get(ENV_GOVERNOR, "") != "0"


def budget_bytes(ctx: Any = None) -> Optional[int]:
    """The DECLARED device-memory budget: ``ctx.resilience.memory_budget``
    first (the ``--memory-budget`` flag), else ``KAMINPAR_TPU_HBM_BYTES``.
    None when no budget was declared — the ladder still catches OOMs,
    but admission/preflight/pressure have nothing to enforce.  The
    backend's own ``bytes_limit`` is deliberately NOT used here: the
    contract is about a budget the operator declared, and the
    observability layer already reports headroom against the backend
    limit."""
    if ctx is not None:
        res = getattr(ctx, "resilience", None)
        if res is None:  # DistContext nests the shm tree
            res = getattr(getattr(ctx, "shm", None), "resilience", None)
        declared = float(getattr(res, "memory_budget", 0.0) or 0.0)
        if declared > 0:
            return int(declared)
    raw = os.environ.get(ENV_BUDGET, "")
    if raw:
        try:
            return int(float(raw))
        except ValueError:
            return None
    return None


def forced_rung() -> Optional[int]:
    """The KAMINPAR_TPU_MEM_RUNG test hook (None when unset)."""
    raw = os.environ.get(ENV_FORCE_RUNG, "")
    if not raw:
        return None
    try:
        return max(RUNG_NORMAL, min(RUNG_HOST_ONLY, int(raw)))
    except ValueError:
        return None


class GovernorState:
    """One run's memory-governor state (lives on the thread-local
    RunState, so serving requests can never observe each other's rung or
    spill accounting)."""

    __slots__ = (
        "budget", "rung", "initial_rung", "estimate", "bucket",
        "watermark", "pressure_events", "spills", "spill_bytes",
        "reloads", "reload_bytes", "shed_bytes", "exhausted",
        "engaged", "spiller", "graph_shape",
    )

    def __init__(self) -> None:
        self.budget: Optional[int] = None
        self.rung: int = RUNG_NORMAL
        self.initial_rung: int = RUNG_NORMAL
        self.estimate: Optional[int] = None
        self.bucket: str = ""
        self.watermark: int = 0
        self.pressure_events: int = 0
        self.spills: int = 0
        self.spill_bytes: int = 0
        self.reloads: int = 0
        self.reload_bytes: int = 0
        self.shed_bytes: int = 0
        self.exhausted: bool = False
        self.engaged: bool = False  # any rung > 0 or pressure action
        self.spiller: Optional[weakref.ref] = None
        self.graph_shape: Tuple[int, int, int] = (0, 0, 0)


def state() -> Optional[GovernorState]:
    """The calling thread's governor state, or None when no run armed
    one (nested runs, library use without the facade)."""
    return getattr(runstate.current(), "memory", None)


def _ensure_state() -> GovernorState:
    run = runstate.current()
    st = getattr(run, "memory", None)
    if st is None:
        st = GovernorState()
        run.memory = st
    return st


def begin_run(graph: Any, ctx: Any,
              price_shape: Optional[Tuple[int, int]] = None
              ) -> Optional[GovernorState]:
    """Arm the governor for one stream-owning run (facade entry): price
    the run, pick the starting rung (the forced test rung, else the
    lowest rung whose estimate fits the declared budget), and emit the
    `memory-budget` telemetry event when a budget is in force.  Returns
    None (and stays dormant) under the kill switch.

    ``price_shape=(n, m)`` overrides the PRICED shape: the dist driver
    passes its sharding plan's actual max padded shard (the budget is
    per-device and the node/edge arrays shard across the mesh — pricing
    the whole graph would refuse or over-rung a multi-chip run that
    fits after sharding, and pricing ``ceil/devices`` would undercount
    the heaviest rank of a skewed edge distribution)."""
    if not governor_enabled():
        run = runstate.current()
        run.memory = None
        return None
    st = GovernorState()
    runstate.current().memory = st
    st.budget = budget_bytes(ctx)
    if price_shape is not None:
        n, m = int(price_shape[0]), int(price_shape[1])
    else:
        n, m = int(graph.n), int(graph.m)
    k = int(getattr(ctx.partition, "k", 2) or 2)
    st.graph_shape = (n, m, k)
    st.bucket = "/".join(str(x) for x in padded_bucket(n, m, k))
    st.estimate = estimate_run_bytes(n, m, k)
    start = RUNG_NORMAL
    if st.budget:
        while (
            start < RUNG_HOST_ONLY
            and not rung_fits(start, n, m, k, st.budget)
        ):
            start += 1
    hook = forced_rung()
    if hook is not None:
        start = hook
    st.rung = st.initial_rung = start
    if start > RUNG_NORMAL:
        st.engaged = True
        _emit_rung_event(
            st, error="MemoryBudgetExceeded",
            detail=(
                f"rung-0 estimate {st.estimate} > budget {st.budget}"
                if hook is None else f"{ENV_FORCE_RUNG}={hook}"
            ),
            injected=hook is not None,
        )
    if st.budget or start:
        from .. import telemetry

        telemetry.event(
            "memory-budget",
            budget_bytes=st.budget,
            estimate_bytes=st.estimate,
            bucket=st.bucket,
            rung=st.rung,
        )
    return st


def register_spiller(coarsener: Any) -> None:
    """The active multilevel coarsener registers itself so the pressure
    hook can ask it to shed hierarchy levels (weakly referenced — the
    governor must never keep a dead hierarchy alive)."""
    st = state()
    if st is not None:
        st.spiller = weakref.ref(coarsener)


def note_spill(nbytes: int) -> None:
    st = state()
    if st is not None:
        st.spills += 1
        st.spill_bytes += int(nbytes)
        st.engaged = True


def note_reload(nbytes: int) -> None:
    st = state()
    if st is not None:
        st.reloads += 1
        st.reload_bytes += int(nbytes)


# ---------------------------------------------------------------------------
# cache shedding
# ---------------------------------------------------------------------------

#: Weakly-held BoundedCaches the governor may shed under pressure (the
#: serving result cache registers itself; future executable caches too).
_shed_targets: "weakref.WeakSet" = weakref.WeakSet()


def register_shed_target(cache: Any) -> None:
    """Register a BoundedCache-shaped object (``evict_to(target_bytes)``)
    for pressure shedding.  Weak: caches die with their owners."""
    _shed_targets.add(cache)


def shed_caches(target_bytes: int = 0) -> int:
    """Evict every registered cache down to ``target_bytes`` (pressure
    cause); also drops the routed lane-gather plans, which pin O(m)
    device memory for graphs that may already be dead.  Returns the
    cache bytes freed."""
    freed = 0
    for cache in list(_shed_targets):
        try:
            freed += int(cache.evict_to(target_bytes, cause="pressure"))
        except Exception:
            continue
    try:
        from ..ops.lane_gather import clear_plan_cache

        clear_plan_cache()
    except Exception:
        pass
    st = state()
    if st is not None:
        st.shed_bytes += freed
    return freed


def _live_device_bytes() -> int:
    from ..utils import heap_profiler

    return int(heap_profiler.live_device_bytes())


def on_barrier(stage: str, live_bytes: Optional[int] = None) -> None:
    """The proactive-pressure hook, called from the PR-5 checkpoint
    barrier (host side, between launches).  Two attribute reads when the
    governor is dormant.  With a budget in force: track the watermark,
    and once live bytes cross PRESSURE_FRACTION of the budget shed the
    registered caches and spill cold hierarchy levels BEFORE the
    allocator fails.  ``live_bytes`` lets the barrier share the perf
    observatory's live-array sample instead of walking jax.live_arrays
    a second time in the same call."""
    st = state()
    if st is None:
        return
    if st.rung >= RUNG_SPILL_HIERARCHY:
        # rung-2+ runs keep the hierarchy host-spilled unconditionally
        self_spill = st.spiller() if st.spiller is not None else None
        if self_spill is not None:
            self_spill.spill_cold_levels()
    if not st.budget:
        return
    live = (
        int(live_bytes) if live_bytes is not None else _live_device_bytes()
    )
    if live > st.watermark:
        st.watermark = live
    if live <= PRESSURE_FRACTION * st.budget:
        return
    st.pressure_events += 1
    st.engaged = True
    freed = shed_caches(0)
    spilled = 0
    spiller = st.spiller() if st.spiller is not None else None
    if spiller is not None:
        spilled = spiller.spill_cold_levels()
    from .. import telemetry
    from ..utils.logger import log_warning

    telemetry.event(
        "memory-pressure",
        stage=stage,
        live_bytes=live,
        budget_bytes=st.budget,
        cache_bytes_freed=freed,
        spill_bytes=spilled,
    )
    log_warning(
        f"memory pressure at {stage}: live {live} > "
        f"{PRESSURE_FRACTION:.0%} of budget {st.budget} — shed {freed} "
        f"cache bytes, spilled {spilled} hierarchy bytes"
    )


def preflight(n: int, m: int, k: int, where: str = "") -> None:
    """The pre-upload budget check (shm/dist drivers, before the device
    upload): raises a ladder-retryable DeviceOOM when the CURRENT rung's
    estimate cannot fit the declared budget — the allocation is refused
    before a single byte lands on the device, and the facade's ladder
    moves to the next rung.  Dormant without a budget."""
    st = state()
    if st is None or not st.budget:
        return
    est = estimate_rung_bytes(st.rung, n, m, k)
    if est <= st.budget:
        return
    raise DeviceOOM(
        f"preflight{'@' + where if where else ''}: rung-{st.rung} "
        f"estimate {est} bytes exceeds the declared budget "
        f"{st.budget} bytes (n={n}, m={m}, k={k})",
        site="device-oom",
    )


# ---------------------------------------------------------------------------
# the recovery ladder
# ---------------------------------------------------------------------------


def _emit_rung_event(st: GovernorState, error: str, detail: str,
                     injected: bool = False,
                     triggering_rank: Optional[int] = None) -> None:
    from .. import telemetry
    from ..utils.logger import log_warning
    from .faults import SITES

    spec = SITES.get("device-oom")
    attrs = dict(
        site="device-oom",
        error=error,
        detail=detail[:300],
        fallback=spec.fallback if spec else "recovery ladder",
        attempts=st.rung,
        breaker_open=False,
        injected=injected,
        rung=st.rung,
        rung_name=RUNG_NAMES.get(st.rung, str(st.rung)),
    )
    if triggering_rank is not None:
        # agreed dist transitions name the rank whose proposal pulled
        # the fleet to this rung (shm transitions omit the key)
        attrs["triggering_rank"] = int(triggering_rank)
    telemetry.event("degraded", **attrs)
    log_warning(
        f"memory governor: {error} ({detail[:120]}); retrying at rung "
        f"{st.rung} ({RUNG_NAMES.get(st.rung)})"
        + (
            "" if triggering_rank is None
            else f" [agreed; triggered by rank {triggering_rank}]"
        )
    )


def _recover(st: GovernorState, depth: int, err: DeviceOOM) -> None:
    """Unwind one failed rung attempt: force-close the timer scopes it
    left open (Timer.unwind_to — the exception already closed scoped
    ones; this catches scopes opened by code that died between
    __enter__s), shed the bounded caches and gather plans, and collect
    garbage so the dead attempt's device arrays are actually freed
    before the next rung allocates."""
    from ..utils import timer

    timer.GLOBAL_TIMER.unwind_to(depth)
    shed_caches(0)
    if st.rung >= RUNG_SPILL_HIERARCHY:
        # executables pin device memory too; at the aggressive rungs a
        # recompile is cheaper than another OOM
        try:
            import jax

            jax.clear_caches()
        except Exception:
            pass
    gc.collect()


def run_ladder(attempt: Callable[[], np.ndarray], graph: Any, ctx: Any,
               facade: Any) -> np.ndarray:
    """Run the core partition under the OOM recovery ladder.

    ``attempt`` is the normal device pipeline (rungs 0-2 re-run it under
    progressively more frugal policies); rungs 3-4 substitute the
    semi-external and host-only paths.  A non-OOM exception propagates
    unchanged on the first bounce — the ladder only ever absorbs
    allocator failure.  When every rung fails the final DeviceOOM is
    re-raised with ``rungs_exhausted=True`` (the serving breaker's one
    legitimate crash signal)."""
    if not governor_enabled():
        return attempt()
    from ..utils import timer

    st = state()
    start = st.rung if st is not None else RUNG_NORMAL
    rung = start
    while True:
        if st is not None:
            st.rung = rung
        depth = len(timer.GLOBAL_TIMER._stack)
        try:
            return _attempt_at_rung(rung, attempt, graph, ctx, facade)
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as exc:
            err = classify(exc, site="device-oom")
            if not isinstance(err, DeviceOOM):
                raise
            if st is None:
                st = _ensure_state()
                st.rung = rung
            if rung >= RUNG_HOST_ONLY:
                st.exhausted = True
                err.rungs_exhausted = True
                from .. import telemetry
                from ..utils.logger import log_warning

                # stamp the audit trail NOW — the success-path annotate
                # in the facade is unreachable once this raise unwinds,
                # and `exhausted: true` is exactly the state a post-crash
                # (emergency/serving) report must be able to show
                telemetry.annotate(memory_budget=summary())
                log_warning(
                    "memory governor: recovery ladder EXHAUSTED "
                    f"(host-only rung failed: {err})"
                )
                raise err from exc
            rung += 1
            st.rung = rung
            st.engaged = True
            _recover(st, depth, err)
            _emit_rung_event(
                st, error=type(err).__name__, detail=str(err),
                injected=err.injected,
            )


def _attempt_at_rung(rung: int, attempt: Callable[[], np.ndarray],
                     graph: Any, ctx: Any, facade: Any) -> np.ndarray:
    from .. import caching

    if rung == RUNG_NORMAL:
        return attempt()
    if rung in (RUNG_TIGHT_PADS, RUNG_SPILL_HIERARCHY):
        # rung 2's spilling needs no wrapper here: on_barrier consults
        # the run's rung and spills unconditionally at rung >= 2
        with caching.pad_policy_scope("tight"):
            return attempt()
    if rung == RUNG_SEMI_EXTERNAL:
        with caching.pad_policy_scope("tight"):
            return _semi_external_rung(graph, ctx, facade)
    return host_only_partition(graph, ctx)


def _semi_external_rung(graph: Any, ctx: Any, facade: Any) -> np.ndarray:
    """Rung 3's primary is the DEVICE-STREAMED external subsystem
    (kaminpar_tpu/external/): LP rating + contraction over padded
    edge-block chunks with only the O(n) vectors device-resident — the
    ROADMAP item-4 path at device speed.  The host-only numpy LP loop
    (:func:`semi_external_partition`) is demoted to its FALLBACK: a
    non-OOM failure of the streamed subsystem (missing codec, a
    malformed source) degrades to it with a ``degraded`` event; a
    DeviceOOM propagates so the ladder moves on to host-only."""
    from ..external.driver import external_partition

    try:
        return external_partition(graph, ctx, facade)
    except (KeyboardInterrupt, SystemExit):
        raise
    except Exception as exc:
        err = classify(exc, site="device-oom")
        if isinstance(err, DeviceOOM):
            raise  # the ladder's business: next rung is host-only
        from .. import telemetry
        from ..utils.logger import log_warning

        telemetry.event(
            "degraded",
            site="semi-external-stream",
            error=type(exc).__name__,
            detail=str(exc)[:300],
            fallback="host-chunked numpy LP (semi_external_partition)",
            attempts=1,
            breaker_open=False,
            injected=False,
        )
        log_warning(
            f"semi-external stream failed ({type(exc).__name__}: "
            f"{str(exc)[:120]}); falling back to the host-chunked LP path"
        )
        return semi_external_partition(graph, ctx, facade)


# ---------------------------------------------------------------------------
# the distributed (cross-rank agreed) ladder
# ---------------------------------------------------------------------------

#: The dist driver's rung order: semi-external is skipped (host-chunked
#: coarsening has no sharded-contraction analog — a dist run that
#: cannot even hold the spilled shard hierarchy goes straight to the
#: host-only path, which needs no device at all).
DIST_RUNG_ORDER = (
    RUNG_NORMAL, RUNG_TIGHT_PADS, RUNG_SPILL_HIERARCHY, RUNG_HOST_ONLY,
)


def _next_dist_rung(rung: int) -> int:
    for r in DIST_RUNG_ORDER:
        if r > rung:
            return r
    return RUNG_HOST_ONLY


def agree_rung(proposed: int) -> Tuple[int, int]:
    """The cross-rank rung agreement: allgather-max over the per-rank
    proposals (the ``deadline.agreed_stop`` idiom, shared through
    resilience/agreement.py) so a DeviceOOM on ANY rank unwinds every
    rank to the same rung instead of deadlocking the survivors inside
    ``shard_map`` collectives.  Returns ``(agreed, triggering_rank)`` —
    the rank whose proposal WAS the max; agreement failure (sick
    control link) degrades to the local proposal."""
    from .agreement import agree_max, rank

    try:
        return agree_max(int(proposed))
    except Exception:
        return int(proposed), rank()


def run_dist_ladder(attempt: Callable[[], np.ndarray], graph: Any,
                    ctx: Any, solver: Any) -> np.ndarray:
    """The dist facade's OOM recovery ladder (the :func:`run_ladder`
    twin with cross-rank agreed rung transitions).

    Rungs: 0 normal -> 1 tight pads -> 2 tight pads + host-spilled
    shard hierarchy (the dist driver registers itself as the spiller:
    per-level DistGraphs are dropped at the barriers and rebuilt
    deterministically on demand during uncoarsening — cut-identical by
    construction) -> 4 host-only recursive bisection.  On a classified
    DeviceOOM the failing rank PROPOSES the next rung and every rank
    adopts the allgather-max (:func:`agree_rung`); the ``degraded``
    event carries the triggering rank.  Rung exhaustion re-raises with
    ``rungs_exhausted=True``, exactly like the shm ladder.

    Multi-process caveat: the agreement gather is only symmetric when
    EVERY rank's attempt raised — which is how allocator failure
    surfaces under jax's distributed runtime (a collective whose peer
    died aborts on the survivors, so each process's attempt() raises
    and each enters this except path in the same ladder round).  A rank
    that fails WITHOUT surfacing fleet-wide is outside this protocol's
    reach; the divergence sentinel at the next barrier (agreed rung is
    one of its audited fields) is the backstop that converts that into
    a structured RankDivergence instead of a silent hang."""
    if not governor_enabled():
        return attempt()
    from ..utils import timer

    st = state()
    rung = st.rung if st is not None else RUNG_NORMAL
    if rung == RUNG_SEMI_EXTERNAL:
        # the forced-rung test hook (or a budget-driven start rung) may
        # name the shm-only rung: the dist order maps it to host-only
        rung = RUNG_HOST_ONLY
    while True:
        if st is not None:
            st.rung = rung
        depth = len(timer.GLOBAL_TIMER._stack)
        try:
            return _attempt_dist_at_rung(rung, attempt, graph, ctx, solver)
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as exc:
            err = classify(exc, site="device-oom")
            if not isinstance(err, DeviceOOM):
                raise
            if st is None:
                st = _ensure_state()
                st.rung = rung
            if rung >= RUNG_HOST_ONLY:
                st.exhausted = True
                err.rungs_exhausted = True
                from .. import telemetry
                from ..utils.logger import log_warning

                telemetry.annotate(memory_budget=summary())
                log_warning(
                    "memory governor: dist recovery ladder EXHAUSTED "
                    f"(host-only rung failed: {err})"
                )
                raise err from exc
            proposed = _next_dist_rung(rung)
            agreed, trig = agree_rung(proposed)
            # never retry BELOW the local proposal (a lagging peer's
            # verdict must not re-run the rung that just OOMed here)
            rung = max(proposed, int(agreed))
            st.rung = rung
            st.engaged = True
            _recover(st, depth, err)
            _emit_rung_event(
                st, error=type(err).__name__, detail=str(err),
                injected=err.injected, triggering_rank=trig,
            )


def _attempt_dist_at_rung(rung: int, attempt: Callable[[], np.ndarray],
                          graph: Any, ctx: Any, solver: Any) -> np.ndarray:
    from .. import caching

    if rung == RUNG_NORMAL:
        return attempt()
    if rung in (RUNG_TIGHT_PADS, RUNG_SPILL_HIERARCHY):
        # rung 2's shard spilling needs no wrapper here: on_barrier
        # consults the run's rung and asks the registered spiller (the
        # dist driver) to drop cold per-level DistGraphs
        with caching.pad_policy_scope("tight"):
            return attempt()
    # host-only takes the SHM context tree (DistContext nests it;
    # ctx.partition already delegates there, but recursive bisection
    # also reads the shm initial-partitioning knobs)
    return host_only_partition(graph, getattr(ctx, "shm", ctx))


# ---------------------------------------------------------------------------
# rung 3: semi-external partitioning (host-chunked coarsening)
# ---------------------------------------------------------------------------


def _node_chunks(graph: Any, chunk_nodes: int):
    """Stream ``(v0, v1, deg, adj, ew)`` node-range blocks of a host or
    compressed graph — the same edge-block idiom as
    ``graphs.csr.device_graph_from_compressed`` and the chunk-streamed
    gate recompute: peak host memory is one block, never the flat edge
    list (for compressed inputs)."""
    n = int(graph.n)
    from ..graphs.compressed import CompressedHostGraph

    if isinstance(graph, CompressedHostGraph):
        for v0 in range(0, n, chunk_nodes):
            v1 = min(n, v0 + chunk_nodes)
            xr, adj, ew = graph.decode_range(v0, v1)
            deg = np.diff(np.asarray(xr, dtype=np.int64))
            yield v0, v1, deg, np.asarray(adj), (
                None if ew is None else np.asarray(ew)
            )
    else:
        xadj = np.asarray(graph.xadj, dtype=np.int64)
        ew_all = graph.edge_weights
        for v0 in range(0, n, chunk_nodes):
            v1 = min(n, v0 + chunk_nodes)
            lo, hi = int(xadj[v0]), int(xadj[v1])
            deg = np.diff(xadj[v0: v1 + 1])
            yield v0, v1, deg, np.asarray(graph.adjncy[lo:hi]), (
                None if ew_all is None else np.asarray(ew_all[lo:hi])
            )


# pure-host numpy kernel: the np.asarray casts view host-resident chunk
# arrays (the semi-external graph never touches the device), so calling
# this inside a timed span introduces no hidden device sync.
# tpulint: disable=R1
def _host_lp_cluster(graph: Any, max_cluster_weight: int,
                     num_iterations: int = 2,
                     chunk_nodes: int = 1 << 17) -> np.ndarray:
    """Chunked host label propagation: one pass over the edge blocks per
    iteration, exact per-chunk best-neighbor-label ratings (lexsort +
    reduceat — the numpy twin of the device segment aggregation), moves
    gated by the cluster weight cap.  Deterministic (no RNG): ties break
    toward the lower label via the stable sort.  Returns compacted
    cluster labels."""
    n = int(graph.n)
    node_w = np.asarray(graph.node_weight_array(), dtype=np.int64)
    labels = np.arange(n, dtype=np.int64)
    cl_w = node_w.copy()
    cap = int(max_cluster_weight)
    for _ in range(max(1, num_iterations)):
        moved = 0
        for v0, v1, deg, adj, ew in _node_chunks(graph, chunk_nodes):
            if len(adj) == 0:
                continue
            rows = np.repeat(np.arange(v0, v1, dtype=np.int64), deg)
            tl = labels[adj]
            w = (
                np.ones(len(adj), dtype=np.int64) if ew is None
                else np.asarray(ew, dtype=np.int64)
            )
            order = np.lexsort((tl, rows))
            r, t, w = rows[order], tl[order], w[order]
            new_grp = np.empty(len(r), dtype=bool)
            new_grp[0] = True
            new_grp[1:] = (r[1:] != r[:-1]) | (t[1:] != t[:-1])
            starts = np.flatnonzero(new_grp)
            rating = np.add.reduceat(w, starts)
            gr, gt = r[starts], t[starts]
            # per-row best rating (stable: ties pick the lower label)
            o2 = np.lexsort((gt, -rating, gr))
            gr2, gt2 = gr[o2], gt[o2]
            firsts = np.flatnonzero(
                np.r_[True, gr2[1:] != gr2[:-1]]
            )
            best_row, best_lab = gr2[firsts], gt2[firsts]
            cur = labels[best_row]
            nw = node_w[best_row]
            ok = (best_lab != cur) & (cl_w[best_lab] + nw <= cap)
            if not ok.any():
                continue
            rows_ok, labs_ok, nw_ok = best_row[ok], best_lab[ok], nw[ok]
            # exact cap enforcement (per-chunk prefix pass): order the
            # chunk's joins by (target label, node id) and accept per
            # target the maximal prefix whose CUMULATIVE weight fits the
            # remaining headroom.  Departures in the same pass free no
            # headroom (conservative), so the cap is never exceeded —
            # the vectorized apply used to overshoot it by up to a
            # chunk's worth of concurrent joins.
            order2 = np.lexsort((rows_ok, labs_ok))
            rows_ok, labs_ok = rows_ok[order2], labs_ok[order2]
            nw_ok = nw_ok[order2]
            grp = np.flatnonzero(np.r_[True, labs_ok[1:] != labs_ok[:-1]])
            cum = np.cumsum(nw_ok)
            base = np.repeat(
                cum[grp] - nw_ok[grp],
                np.diff(np.r_[grp, len(labs_ok)]),
            )
            accept = (cum - base) <= (cap - cl_w[labs_ok])
            if not accept.any():
                continue
            rows_ok, labs_ok = rows_ok[accept], labs_ok[accept]
            np.subtract.at(cl_w, labels[rows_ok], node_w[rows_ok])
            labels[rows_ok] = labs_ok
            np.add.at(cl_w, labs_ok, node_w[rows_ok])
            moved += int(len(rows_ok))
        if moved == 0:
            break
    _, compact = np.unique(labels, return_inverse=True)
    return compact.astype(np.int64)


# pure-host numpy kernel, same contract as _host_lp_cluster above.
# tpulint: disable=R1
def _host_contract(graph: Any, labels: np.ndarray,
                   chunk_nodes: int = 1 << 17):
    """Chunked host contraction: aggregate inter-cluster edges block by
    block (per-chunk dedup, periodic re-dedup of the accumulator so the
    host high-water stays ~O(coarse m + chunk)).  Returns the coarse
    HostGraph and the fine->coarse map."""
    from ..graphs.host import HostGraph

    c_n = int(labels.max()) + 1 if len(labels) else 0
    node_w = np.asarray(graph.node_weight_array(), dtype=np.int64)
    cw = np.zeros(c_n, dtype=np.int64)
    np.add.at(cw, labels, node_w)

    acc_key = np.empty(0, dtype=np.int64)
    acc_w = np.empty(0, dtype=np.int64)

    def dedup(keys, weights):
        uk, inv = np.unique(keys, return_inverse=True)
        uw = np.zeros(len(uk), dtype=np.int64)
        np.add.at(uw, inv, weights)
        return uk, uw

    for v0, v1, deg, adj, ew in _node_chunks(graph, chunk_nodes):
        if len(adj) == 0:
            continue
        rows = np.repeat(np.arange(v0, v1, dtype=np.int64), deg)
        cu, cv = labels[rows], labels[adj]
        keep = cu != cv
        key = cu[keep] * c_n + cv[keep]
        w = (
            np.ones(int(keep.sum()), dtype=np.int64) if ew is None
            else np.asarray(ew, dtype=np.int64)[keep]
        )
        k2, w2 = dedup(key, w)
        acc_key = np.concatenate([acc_key, k2])
        acc_w = np.concatenate([acc_w, w2])
        if len(acc_key) > 4 * max(len(k2), 1 << 20):
            acc_key, acc_w = dedup(acc_key, acc_w)
    acc_key, acc_w = dedup(acc_key, acc_w)
    cu = (acc_key // c_n).astype(np.int64)
    cv = (acc_key % c_n).astype(np.int32)
    xadj = np.zeros(c_n + 1, dtype=np.int64)
    np.add.at(xadj, cu + 1, 1)
    np.cumsum(xadj, out=xadj)
    coarse = HostGraph(
        xadj=xadj,
        adjncy=cv,
        node_weights=cw,
        edge_weights=acc_w,
    )
    return coarse, labels.astype(np.int32)


def semi_external_partition(graph: Any, ctx: Any, facade: Any) -> np.ndarray:
    """Rung 3: coarsen the fine graph HOST-side in node-range chunks
    until the coarse graph's spilled-mode estimate fits the budget, run
    the normal device pipeline on the coarse graph, and project the
    partition back through the host cmaps.  Only the coarse graph and
    the partition vector are ever device-resident; the fine graph stays
    in host RAM (compressed inputs are streamed block-wise and never
    decoded whole)."""
    from .. import telemetry
    from ..utils import timer
    from ..utils.logger import log_progress

    st = state()
    budget = st.budget if st is not None else None
    k = int(ctx.partition.k)
    target = (
        int(budget * STREAM_TARGET_FRACTION) if budget else None
    )
    cmaps: List[np.ndarray] = []
    current = graph
    cap = max(
        1,
        int(ctx.coarsening.max_cluster_weight(
            int(graph.n), int(ctx.partition.total_node_weight),
            ctx.partition,
        )),
    )
    with timer.scoped_timer("semi-external-coarsening"):
        for level in range(32):
            n, m = int(current.n), int(current.m)
            fits = (
                target is None
                or estimate_rung_bytes(RUNG_SPILL_HIERARCHY, n, m, k)
                <= target
            )
            if fits or n <= max(2 * ctx.coarsening.contraction_limit, 2):
                break
            labels = _host_lp_cluster(current, cap)
            c_n = int(labels.max()) + 1 if len(labels) else 0
            if c_n >= 0.95 * n:
                # clustering stalled: relax the cap (the forced-shrink
                # retry of the device coarsener) before giving up
                cap *= 2
                labels = _host_lp_cluster(current, cap)
                c_n = int(labels.max()) + 1 if len(labels) else 0
                if c_n >= 0.95 * n:
                    break
            current, cmap = _host_contract(current, labels)
            cmaps.append(cmap)
            log_progress(
                f"semi-external level {level}: n={current.n} "
                f"m={current.m} (host-resident)"
            )
    telemetry.event(
        "semi-external",
        levels=len(cmaps),
        coarse_n=int(current.n),
        coarse_m=int(current.m),
    )
    # `current` is the host-coarsened graph — or the original when
    # nothing could be coarsened away host-side; either way it goes to
    # the device pipeline (spill mode still active) and an OOM there
    # moves the ladder on to host-only
    part = facade._partition_core_resilient(current, ctx)
    part = np.asarray(part, dtype=np.int32)
    with timer.scoped_timer("semi-external-projection"):
        for cmap in reversed(cmaps):
            part = part[cmap]
    return part


# ---------------------------------------------------------------------------
# rung 4: host-only partitioning
# ---------------------------------------------------------------------------


def host_only_partition(graph: Any, ctx: Any) -> np.ndarray:
    """Rung 4: recursive bisection entirely on the host (the sequential
    pool bipartitioner) — no device arrays at all.  Quality is the
    initial-partitioning pool's, not the refined pipeline's; the output
    gate still validates and repairs balance downstream."""
    from .. import telemetry
    from ..graphs.compressed import CompressedHostGraph
    from ..partitioning.rb import recursive_bipartition
    from ..utils import rng as rng_mod
    from ..utils import timer

    hg = graph.decode() if isinstance(graph, CompressedHostGraph) else graph
    k = int(ctx.partition.k)
    telemetry.event("host-only-partition", n=int(hg.n), m=int(hg.m), k=k)
    with timer.scoped_timer("host-only-partitioning"):
        part = recursive_bipartition(
            hg, k, ctx, rng_mod.host_rng(ctx.seed ^ 0x40F7)
        )
    return np.asarray(part, dtype=np.int32)


# ---------------------------------------------------------------------------
# reporting
# ---------------------------------------------------------------------------


def summary() -> dict:
    """The run report's ``memory_budget`` section.  ``enabled`` is True
    when a budget was declared OR the ladder engaged (an OOM recovery
    with no declared budget is still worth auditing)."""
    st = state()
    if st is None:
        return {"enabled": False}
    d: Dict[str, Any] = {
        "enabled": bool(st.budget or st.engaged),
        "rung": int(st.rung),
        "rung_name": RUNG_NAMES.get(st.rung, str(st.rung)),
        "initial_rung": int(st.initial_rung),
        "exhausted": bool(st.exhausted),
        "spills": {
            "count": int(st.spills),
            "bytes": int(st.spill_bytes),
            "reloads": int(st.reloads),
            "reload_bytes": int(st.reload_bytes),
        },
        "pressure_events": int(st.pressure_events),
        "shed_cache_bytes": int(st.shed_bytes),
    }
    if st.budget is not None:
        d["budget_bytes"] = int(st.budget)
    if st.estimate is not None:
        d["estimate_bytes"] = int(st.estimate)
    if st.bucket:
        d["bucket"] = st.bucket
    if st.watermark:
        d["watermark_bytes"] = int(st.watermark)
    return d
