from .fm import fm_refine_host  # noqa: F401
