"""Gain caches — pluggable strategies mirroring kaminpar-shm/refinement/gains/.

The reference keeps per-(node, block) connection weights so FM/Jet can
query move gains in O(1) and update them incrementally as nodes move:
`gain(u, from, to) = conn(u, to) - conn(u, from)`, with strategies trading
memory for speed (sparse_gain_cache.h:54 dense per-node×block,
compact_hashing_gain_cache.h:34 default, on_the_fly_gain_cache.h:25
recompute-on-demand, delta_gain_caches.h:202 speculative overlays).

TPU translation:
  * DeviceDenseGainCache — the SparseGainCache analog: a dense
    i32[n_pad, k] connection matrix on device, built with one
    segment_sum, updated after each bulk-synchronous move round with two
    more (the `move()` protocol, executed for a whole round's movers at
    once).  The per-round update touches only edges incident to movers,
    like the reference's per-move delta updates — O(moved edges), not
    O(m).  Feeds Jet-style refiners at small/medium k.
  * on-the-fly — the default for whole-graph device refiners: LP/Jet
    recompute ratings per round via ops/segments.aggregate_by_key (no
    materialized n×k table); this module adds `on_the_fly_gains` as the
    explicit strategy entry point.
  * HostDenseGainCache — numpy (n, k) cache with incremental updates for
    the host FM refiner, replacing full per-node recomputation.
  * HostDeltaGainCache — speculative overlay over a HostDenseGainCache
    (delta_gain_caches.h analog): moves applied to the delta are visible
    through `gain()` but do not touch the base cache until `commit()`.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..graphs.csr import DeviceGraph
from ..ops.segments import ACC_DTYPE, INT32_MIN


# ---------------------------------------------------------------------------
# Device dense gain cache (SparseGainCache analog)
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("k",))
def build_dense_gain_cache(
    graph: DeviceGraph, partition: jax.Array, k: int
) -> jax.Array:
    """conn[u, b] = total weight of u's edges into block b.

    One flat segment_sum over the COO edge list (the bulk analog of
    SparseGainCache::initialize's per-node aggregation)."""
    n_pad = graph.n_pad
    if n_pad * k >= 2**31:
        raise ValueError("n_pad * k must fit in int32")
    part_c = jnp.clip(partition, 0, k - 1)
    flat = graph.src.astype(jnp.int32) * k + part_c[graph.dst]
    conn = jax.ops.segment_sum(
        graph.edge_w.astype(ACC_DTYPE), flat, num_segments=n_pad * k
    )
    return conn.reshape(n_pad, k)


@partial(jax.jit, static_argnames=("k",))
def update_dense_gain_cache(
    conn: jax.Array,
    graph: DeviceGraph,
    old_partition: jax.Array,
    new_partition: jax.Array,
    k: int,
) -> jax.Array:
    """Incremental update after a bulk move round (the move() protocol,
    sparse_gain_cache.h): for every edge (u, v) whose target v moved
    a -> b, conn[u, a] -= w(uv) and conn[u, b] += w(uv).  Cost is
    O(edges incident to movers); unmoved rounds are a no-op."""
    n_pad = graph.n_pad
    old_c = jnp.clip(old_partition, 0, k - 1)
    new_c = jnp.clip(new_partition, 0, k - 1)
    moved = old_c[graph.dst] != new_c[graph.dst]
    w = jnp.where(moved, graph.edge_w, 0).astype(ACC_DTYPE)
    sub = graph.src.astype(jnp.int32) * k + old_c[graph.dst]
    add = graph.src.astype(jnp.int32) * k + new_c[graph.dst]
    flat = conn.reshape(-1)
    flat = flat.at[sub].add(-w, mode="drop")
    flat = flat.at[add].add(w, mode="drop")
    return flat.reshape(n_pad, k)


@partial(jax.jit, static_argnames=("k",))
def best_moves_from_cache(
    conn: jax.Array,
    partition: jax.Array,
    node_w: jax.Array,
    block_weights: jax.Array,
    max_block_weights: jax.Array,
    k: int,
) -> Tuple[jax.Array, jax.Array]:
    """Per-node (best_target, gain) from a dense cache under the block
    weight caps (gain(u, from, to) = conn[u,to] - conn[u,from]).
    Infeasible rows return target -1 / gain INT32_MIN."""
    n_pad = conn.shape[0]
    part_c = jnp.clip(partition, 0, k - 1)
    own = jnp.take_along_axis(conn, part_c[:, None], axis=1)[:, 0]
    cap = jnp.broadcast_to(max_block_weights, (k,)).astype(ACC_DTYPE)
    fits = (
        block_weights[None, :].astype(ACC_DTYPE)
        + node_w[:, None].astype(ACC_DTYPE)
        <= cap[None, :]
    )
    is_own = jnp.arange(k, dtype=jnp.int32)[None, :] == part_c[:, None]
    score = jnp.where(fits & ~is_own, conn, INT32_MIN)
    best = jnp.argmax(score, axis=1).astype(jnp.int32)
    best_w = jnp.max(score, axis=1)
    has = best_w > INT32_MIN
    gain = jnp.where(has, best_w - own, INT32_MIN)
    return jnp.where(has, best, -1), gain


def on_the_fly_gains(
    graph: DeviceGraph, partition: jax.Array, k: int
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """OnTheFlyGainCache strategy (on_the_fly_gain_cache.h:25): no
    materialized table — returns the aggregate_by_key triple
    (seg_g, key_g, w_g) enumerating each node's adjacent blocks with
    connection weights, exactly what LP/Jet rounds consume."""
    from ..ops.segments import aggregate_by_key

    part_c = jnp.clip(partition, 0, k - 1)
    return aggregate_by_key(graph.src, part_c[graph.dst], graph.edge_w)


# ---------------------------------------------------------------------------
# Host caches (FM support)
# ---------------------------------------------------------------------------


class HostDenseGainCache:
    """Dense (n, k) connection matrix on host with incremental move
    updates — the host FM's gain authority (DenseGainCache analog).

    Invariant (gain_cache_test.cc's validation property): after any move
    sequence applied through `apply_move`, `self.conn` equals a fresh
    rebuild from the current partition."""

    def __init__(self, host_graph, partition: np.ndarray, k: int):
        self.g = host_graph
        self.k = k
        n = host_graph.n
        self.src = host_graph.edge_sources()
        self.dst = host_graph.adjncy
        self.ew = host_graph.edge_weight_array()
        # int32 matches the device ACC_DTYPE; entries are bounded by a
        # node's weighted degree
        self.conn = np.zeros((n, k), dtype=np.int32)
        np.add.at(
            self.conn,
            (self.src, np.asarray(partition, np.int64)[self.dst]),
            self.ew,
        )

    def gain(self, u: int, b_from: int, b_to: int) -> int:
        return int(self.conn[u, b_to] - self.conn[u, b_from])

    def best_move(
        self,
        u: int,
        part: np.ndarray,
        node_w: np.ndarray,
        bw: np.ndarray,
        max_bw: np.ndarray,
    ) -> Optional[Tuple[int, int]]:
        """Best feasible (gain, target) for u, O(k)."""
        b = int(part[u])
        row = self.conn[u]
        feas = bw + node_w[u] <= max_bw
        feas[b] = False
        if not feas.any():
            return None
        masked = np.where(feas, row, -(1 << 62))
        t = int(np.argmax(masked))
        if masked[t] <= -(1 << 62):
            return None
        return int(row[t] - row[b]), t

    def apply_move(self, u: int, b_from: int, b_to: int) -> None:
        """Move u and update the neighbors' rows (move(), O(deg(u)))."""
        lo, hi = int(self.g.xadj[u]), int(self.g.xadj[u + 1])
        neigh = self.dst[lo:hi]
        w = self.ew[lo:hi]
        np.subtract.at(self.conn, (neigh, b_from), w)
        np.add.at(self.conn, (neigh, b_to), w)


class HostOnTheFlyGainCache:
    """On-the-fly strategy for host FM (on_the_fly_gain_cache.h:25): no
    table — best_move recomputes from the adjacency in O(deg + k).  Used
    when the dense (n, k) table would not fit comfortably in memory."""

    def __init__(self, host_graph, partition: np.ndarray, k: int):
        self.g = host_graph
        self.k = k
        self.dst = host_graph.adjncy
        self.ew = host_graph.edge_weight_array()
        self.part = partition  # shared, caller mutates it before apply_move

    def best_move(self, u, part, node_w, bw, max_bw):
        lo, hi = int(self.g.xadj[u]), int(self.g.xadj[u + 1])
        if lo == hi:
            return None
        conn = np.zeros(self.k, dtype=np.int64)
        np.add.at(conn, part[self.dst[lo:hi]], self.ew[lo:hi])
        b = int(part[u])
        own = conn[b]
        feas = bw + node_w[u] <= max_bw
        feas[b] = False
        masked = np.where(feas, conn, -(1 << 62))
        t = int(np.argmax(masked))
        if masked[t] <= -(1 << 62):
            return None
        return int(conn[t] - own), t

    def apply_move(self, u: int, b_from: int, b_to: int) -> None:
        pass  # nothing cached


# dense table above this many entries falls back to on-the-fly
DENSE_CACHE_MAX_ENTRIES = 1 << 26


def create_host_gain_cache(host_graph, partition: np.ndarray, k: int):
    """Strategy picker (the factories.cc gain-cache dispatch analog):
    dense when the (n, k) table is affordable, on-the-fly otherwise."""
    if host_graph.n * k <= DENSE_CACHE_MAX_ENTRIES:
        return HostDenseGainCache(host_graph, partition, k)
    return HostOnTheFlyGainCache(host_graph, partition, k)


class HostDeltaGainCache:
    """Speculative overlay (delta_gain_caches.h:202 analog): FM batches
    try moves against the delta; `commit()` folds them into the base,
    `clear()` discards them."""

    def __init__(self, base: HostDenseGainCache):
        self.base = base
        self._delta: Dict[Tuple[int, int], int] = {}
        self._moves: list[Tuple[int, int, int]] = []

    def _conn(self, u: int, b: int) -> int:
        return int(self.base.conn[u, b]) + self._delta.get((u, b), 0)

    def gain(self, u: int, b_from: int, b_to: int) -> int:
        return self._conn(u, b_to) - self._conn(u, b_from)

    def apply_move(self, u: int, b_from: int, b_to: int) -> None:
        g = self.base.g
        lo, hi = int(g.xadj[u]), int(g.xadj[u + 1])
        for v, w in zip(self.base.dst[lo:hi], self.base.ew[lo:hi]):
            v = int(v)
            self._delta[(v, b_from)] = self._delta.get((v, b_from), 0) - int(w)
            self._delta[(v, b_to)] = self._delta.get((v, b_to), 0) + int(w)
        self._moves.append((u, b_from, b_to))

    def commit(self) -> None:
        for u, b_from, b_to in self._moves:
            self.base.apply_move(u, b_from, b_to)
        self.clear()

    def clear(self) -> None:
        self._delta.clear()
        self._moves.clear()
