"""Gated Mt-KaHyPar refinement adapter.

The reference optionally delegates refinement to the external Mt-KaHyPar
library behind the KAMINPAR_BUILD_WITH_MTKAHYPAR build flag
(kaminpar-shm/refinement/adapters/mtkahypar_refiner.cc:182); when the
flag is off the refiner slot still exists but selecting it fails.  The
analog here: if the `mtkahypar` Python package is importable we hand the
current partition to it for k-way refinement; otherwise selecting the
`mtkahypar` refinement algorithm raises with a clear message (the
runtime version of "not built with Mt-KaHyPar support").

Like the reference adapter (mtkahypar_refiner.cc builds the target graph
with its node and edge weights and forwards the block-weight caps), node
weights, edge weights, and the per-block maximum weights all cross the
boundary — refinement runs on coarse graphs, where unit weights would
optimize the wrong objective.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np


def mtkahypar_available() -> bool:
    try:
        import mtkahypar  # noqa: F401

        return True
    except ImportError:
        return False


_MTK_INSTANCE = None  # (threads, Initializer): init once per process


def mtkahypar_refine_host(
    host_graph,
    partition: np.ndarray,
    k: int,
    max_block_weights: Optional[Sequence[int]] = None,
    epsilon: float = 0.03,
    seed: int = 0,
    threads: int = 1,
) -> np.ndarray:
    """Improve `partition` with Mt-KaHyPar's k-way refinement
    (mtkahypar_refiner.cc refine analog).  Requires the external
    `mtkahypar` package.  `max_block_weights` (when given) is forwarded
    as individual target block weights; otherwise `epsilon` is used."""
    try:
        import mtkahypar
    except ImportError as e:
        raise RuntimeError(
            "the 'mtkahypar' refiner needs the external mtkahypar package "
            "(reference analog: built without KAMINPAR_BUILD_WITH_MTKAHYPAR)"
        ) from e

    global _MTK_INSTANCE
    if _MTK_INSTANCE is None or _MTK_INSTANCE[0] != threads:
        _MTK_INSTANCE = (threads, mtkahypar.initialize(int(threads)))
    mtk = _MTK_INSTANCE[1]
    ctx = mtk.context_from_preset(mtkahypar.PresetType.DEFAULT)
    ctx.set_partitioning_parameters(k, float(epsilon), mtkahypar.Objective.CUT)
    if max_block_weights is not None:
        ctx.set_individual_target_block_weights(
            [int(w) for w in max_block_weights]
        )
    mtkahypar.set_seed(int(seed))

    src = host_graph.edge_sources()
    dst = host_graph.adjncy
    ew = host_graph.edge_weight_array()
    fwd = src < dst  # one record per undirected edge, weight preserved
    g = mtk.create_graph(
        ctx,
        int(host_graph.n),
        int(fwd.sum()),
        [(int(u), int(v)) for u, v in zip(src[fwd], dst[fwd])],
        [int(w) for w in host_graph.node_weight_array()],
        [int(w) for w in ew[fwd]],
    )
    pg = g.create_partitioned_graph(k, [int(b) for b in partition])
    pg.improve_partition(ctx, 1)
    return np.asarray(
        [pg.block_id(u) for u in range(host_graph.n)], dtype=np.int32
    )
