"""k-way FM refinement (host).

Analog of kaminpar-shm/refinement/fm/ (FMRefiner + LocalizedFMRefiner,
fm_refiner.cc:48-110): the reference runs parallel localized FM with
thread-local delta partitions and a shared border-node queue.  FM's
priority-queue-driven, one-node-at-a-time control flow has no efficient TPU
mapping (the reference's own Jet paper makes the same observation — Jet is
its bulk-synchronous replacement and runs on device here, ops/jet.py).  FM
therefore stays host-side, mirroring the reference's *sequential* FM
structure with a global gain PQ over border nodes, best-prefix rollback and
the simple stopping rule (num_fruitless_moves).

The per-node gain bookkeeping uses the dense gain cache
(refinement/gains.HostDenseGainCache, the DenseGainCache strategy): an
(n, k) connection matrix built once per pass and updated incrementally on
each move, so best-move queries are O(k) instead of O(deg).
"""

from __future__ import annotations

import heapq
from typing import Optional

import numpy as np

from ..context import FMRefinementContext
from ..graphs.csr import DeviceGraph, host_graph_from_device
from ..graphs.host import HostGraph
from ..telemetry import progress as progress_mod
from .gains import create_host_gain_cache


def fm_refine_host(
    dgraph: DeviceGraph,
    partition,
    k: int,
    max_block_weights,
    ctx: FMRefinementContext,
    seed: int = 0,
    threads: int = 1,
):
    """Refine a device partition with host FM; returns a device partition.

    Runs ctx.num_iterations passes; each pass processes border nodes from a
    global max-gain PQ with best-prefix rollback (FMRefiner::refine
    structure, fm_refiner.cc)."""
    import jax.numpy as jnp

    graph = host_graph_from_device(dgraph)
    n = graph.n
    # explicit copy: jax->numpy views are read-only and the native FM
    # refines the partition in place
    part = np.array(np.asarray(partition)[:n], dtype=np.int32, copy=True)
    max_bw = np.asarray(max_block_weights)[:k].astype(np.int64)

    import os

    def _numpy_fm() -> np.ndarray:
        node_w = graph.node_weight_array()
        edge_w = graph.edge_weight_array()
        rng = np.random.default_rng(seed)
        rec = progress_mod.capture()
        t0 = progress_mod.now()
        gains, moves, prefixes = [], [], []
        for _ in range(max(1, ctx.num_iterations)):
            improvement, n_moves, best_prefix = _fm_pass(
                graph, part, node_w, edge_w, max_bw, k, ctx, rng
            )
            if rec:
                gains.append(int(improvement))
                moves.append(int(n_moves))
                prefixes.append(int(best_prefix))
            if improvement <= 0:
                break
        if rec:
            # host algorithm: per-pass series, same stream and shape as
            # the device loops' buffers (gain = committed cut delta,
            # moved = attempted moves, best_prefix = kept moves)
            progress_mod.emit_host(
                "fm",
                {"gain": gains, "moved": moves, "best_prefix": prefixes},
                t0=t0, engine="numpy",
            )
        return part

    if os.environ.get("KAMINPAR_TPU_NO_NATIVE_FM", "") == "1":
        # explicit opt-out, not a degradation: no fallback event
        part = _numpy_fm()
    else:
        from ..resilience import (
            NativeUnavailable,
            RefinerRefused,
            with_fallback,
        )

        def _native_fm() -> np.ndarray:
            from .. import native

            t0 = progress_mod.now()
            # native localized BATCH FM (fm.cpp — the reference's
            # parallel localized scheme minus threads: seeded regions
            # grown against a delta gain overlay, best prefixes
            # committed); refines `part` in place
            improvement = native.fm_refine(
                graph, part, k, max_bw, ctx, seed, threads=threads
            )
            if improvement is None:
                raise NativeUnavailable(
                    "native FM library unavailable (build failed or "
                    "no toolchain)"
                )
            if improvement == native.FM_REFUSED:
                # fm_refine already recorded the fm-refused telemetry
                # event; surface the refusal as a structured exception
                # so the policy wrapper routes it — NOT as zero gain
                raise RefinerRefused(
                    f"native FM refused to run at n={graph.n}, k={k}"
                )
            if progress_mod.capture():
                # the C engine reports one total: a single-point series
                # keeps native and numpy runs alignable in the report
                progress_mod.emit_host(
                    "fm", {"gain": [int(improvement)]}, t0=t0,
                    engine="native",
                )
            return part

        def _fm_fallback(exc) -> np.ndarray:
            # a REFUSAL (k above the sparse engine's 16-bit tag limit
            # with the dense table unaffordable) returns the partition
            # unchanged: the numpy pass's dense (n, k) gain cache is
            # unaffordable at exactly these k.  Everything else
            # (unavailable native lib, OOM) runs the numpy FM twin.
            if isinstance(exc, RefinerRefused) and not exc.injected:
                return part
            return _numpy_fm()

        part = with_fallback(_native_fm, _fm_fallback, site="native-fm")

    padded = np.zeros(dgraph.n_pad, dtype=np.int32)
    padded[:n] = part
    return jnp.asarray(padded)


def _fm_pass(graph, part, node_w, edge_w, max_bw, k, ctx, rng):
    """One FM pass; returns (committed gain, attempted moves, kept
    best-prefix length) — the per-pass progress triple."""
    n = graph.n
    src = graph.edge_sources()
    bw = np.zeros(k, dtype=np.int64)
    np.add.at(bw, part, node_w)

    # border nodes: incident to a cut edge
    cut_edge = part[src] != part[graph.adjncy]
    border = np.unique(src[cut_edge])
    if len(border) == 0:
        return 0, 0, 0

    cache = create_host_gain_cache(graph, part, k)
    pq = []
    tie = rng.random(n)
    in_pq = np.zeros(n, dtype=bool)
    for u in border:
        mv = cache.best_move(int(u), part, node_w, bw, max_bw)
        if mv is not None:
            heapq.heappush(pq, (-mv[0], tie[u], int(u), mv[1]))
            in_pq[u] = True

    locked = np.zeros(n, dtype=bool)
    moves = []
    cur_delta = 0
    best_delta = 0
    best_len = 0
    fruitless = 0

    while pq:
        negg, _, u, t = heapq.heappop(pq)
        if locked[u]:
            continue
        # gains may be stale: re-query the cache and re-push if changed
        mv = cache.best_move(u, part, node_w, bw, max_bw)
        if mv is None:
            continue
        gain, t = mv
        if -negg != gain:
            heapq.heappush(pq, (-gain, tie[u], u, t))
            continue
        if bw[t] + node_w[u] > max_bw[t]:
            continue

        b = int(part[u])
        part[u] = t
        bw[b] -= node_w[u]
        bw[t] += node_w[u]
        cache.apply_move(u, b, t)
        locked[u] = True
        cur_delta += gain
        moves.append((u, b))
        if cur_delta > best_delta:
            best_delta = cur_delta
            best_len = len(moves)
            fruitless = 0
        else:
            fruitless += 1
            if fruitless >= ctx.num_fruitless_moves:
                break

        # re-queue unlocked neighbors (their cached rows just changed)
        lo, hi = int(graph.xadj[u]), int(graph.xadj[u + 1])
        for v in graph.adjncy[lo:hi]:
            v = int(v)
            if not locked[v]:
                mv = cache.best_move(v, part, node_w, bw, max_bw)
                if mv is not None:
                    heapq.heappush(pq, (-mv[0], tie[v], v, mv[1]))

    # rollback to best prefix
    for u, b in moves[best_len:]:
        t = int(part[u])
        part[u] = b
        bw[t] -= node_w[u]
        bw[b] += node_w[u]
    return best_delta, len(moves), best_len
