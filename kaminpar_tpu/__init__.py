"""kaminpar-tpu: a TPU-native balanced k-way graph partitioning framework.

Re-implements the capabilities of KaMinPar (deep multilevel graph
partitioning; see SURVEY.md) with a JAX/XLA/Pallas compute path: the hot
kernels — size-constrained label propagation, cluster contraction, LP/Jet
refinement, balancing — run as segmented sort/scatter array programs on a
device-resident CSR graph; sequential initial bipartitioning and the
multilevel orchestration run on the host; multi-chip scaling uses
jax.sharding meshes with XLA collectives instead of MPI.
"""

from .graphs import (  # noqa: F401
    HostGraph,
    DeviceGraph,
    from_edge_list,
    from_csr,
    device_graph_from_host,
    host_graph_from_device,
    validate,
)
from .io import load_graph  # noqa: F401
from . import telemetry  # noqa: F401
from .context import Context  # noqa: F401
from .presets import create_context_by_preset_name, get_preset_names  # noqa: F401
from .kaminpar import KaMinPar, context_from_preset  # noqa: F401

__version__ = "0.1.0"
