"""METIS text graph format reader/writer.

Analog of kaminpar-io/metis_parser.cc (format per docs/graph_file_format.md):
header `n m [fmt]` where fmt ∈ {00, 10, 01, 11} flags node/edge weights;
one line per node, 1-based neighbor ids, optional leading node weight and
per-neighbor edge weight.  Comment lines start with '%'.

The reference uses an mmap-based char tokenizer (kaminpar-io/util/
file_toker.h); here the fast path is a single `np.fromstring`-style parse of
the whole token stream, which is within a small factor of mmap tokenization
for the graph sizes a single TPU host ingests.
"""

from __future__ import annotations

import numpy as np

from ..graphs.host import HostGraph
from .errors import GraphFormatError


def parse_metis(text: str) -> HostGraph:
    # keep empty lines: a node with no neighbors is an empty line.
    # Original 1-based line numbers ride along so every violation can
    # name its line (GraphFormatError contract).
    lines = [
        (i + 1, l.strip())
        for i, l in enumerate(text.splitlines())
        if not l.lstrip().startswith("%")
    ]
    while lines and not lines[0][1]:
        lines.pop(0)
    if not lines:
        raise GraphFormatError("empty METIS file", line=1)

    header_ln, header_text = lines[0]
    header = header_text.split()
    if len(header) < 2:
        raise GraphFormatError(
            "header must be 'n m [fmt]'", line=header_ln
        )
    try:
        n = int(header[0])
        m_undirected = int(header[1])
    except ValueError:
        raise GraphFormatError(
            f"non-integer header token in {header_text!r}", line=header_ln
        ) from None
    if n < 0 or m_undirected < 0:
        raise GraphFormatError("negative n or m in header", line=header_ln)
    m2 = m_undirected * 2  # file stores undirected edge count
    # a corrupted header cannot commandeer an astronomic allocation:
    # every directed edge needs at least two characters of body
    if m2 > 2 * max(len(text), 1):
        raise GraphFormatError(
            f"header claims {m2} directed edges but the file is only "
            f"{len(text)} bytes",
            line=header_ln,
        )
    fmt = header[2] if len(header) > 2 else "0"
    has_node_weights = len(fmt) >= 2 and fmt[-2] == "1"
    has_edge_weights = fmt[-1] == "1"

    if len(lines) - 1 < n:
        raise GraphFormatError(
            f"expected {n} node lines, found {len(lines) - 1} "
            "(truncated file?)",
            line=lines[-1][0],
        )

    # token-stream fast path: per node line, tokens are
    # [vw] (v [ew]) (v [ew]) ...
    per_line_tokens = []
    for ln, l in lines[1 : n + 1]:
        try:
            per_line_tokens.append(np.array(l.split(), dtype=np.int64))
        except OverflowError:
            raise GraphFormatError(
                "weight or id overflows 64-bit", line=ln
            ) from None
        except ValueError:
            raise GraphFormatError("non-integer token", line=ln) from None
    line_numbers = [ln for ln, _ in lines[1 : n + 1]]
    degrees = np.zeros(n, dtype=np.int64)
    stride = 2 if has_edge_weights else 1
    for i, toks in enumerate(per_line_tokens):
        cnt = len(toks) - (1 if has_node_weights else 0)
        if cnt < 0 or cnt % stride:
            raise GraphFormatError(
                "malformed adjacency (token count does not match the "
                "header's weight flags)",
                line=line_numbers[i],
            )
        degrees[i] = cnt // stride

    xadj = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(degrees, out=xadj[1:])
    m = int(xadj[-1])
    if m != m2:
        # tolerated like the reference tolerates trailing data, but warn-level
        # strictness: mismatch is almost always a broken file
        raise GraphFormatError(
            f"header claims {m2} directed edges, file has {m}",
            line=header_ln,
        )

    adjncy = np.empty(m, dtype=np.int32)
    edge_weights = np.empty(m, dtype=np.int64) if has_edge_weights else None
    node_weights = np.empty(n, dtype=np.int64) if has_node_weights else None

    for i, toks in enumerate(per_line_tokens):
        off = 0
        if has_node_weights:
            if toks[0] < 0:
                raise GraphFormatError(
                    f"negative node weight {int(toks[0])}",
                    line=line_numbers[i],
                )
            node_weights[i] = toks[0]
            off = 1
        body = toks[off:]
        s, e = xadj[i], xadj[i + 1]
        if has_edge_weights:
            adjncy[s:e] = body[0::2] - 1
            edge_weights[s:e] = body[1::2]
        else:
            adjncy[s:e] = body - 1

    if m and (adjncy.min() < 0 or adjncy.max() >= n):
        bad = int(
            np.flatnonzero((adjncy < 0) | (adjncy >= n))[0]
        )
        node = int(np.searchsorted(xadj, bad, side="right")) - 1
        raise GraphFormatError(
            f"neighbor id {int(adjncy[bad]) + 1} out of range [1, {n}]",
            line=line_numbers[node],
        )
    if edge_weights is not None and m and edge_weights.min() < 0:
        bad = int(np.flatnonzero(edge_weights < 0)[0])
        node = int(np.searchsorted(xadj, bad, side="right")) - 1
        raise GraphFormatError(
            "negative edge weight", line=line_numbers[node]
        )
    return HostGraph(
        xadj=xadj,
        adjncy=adjncy,
        node_weights=node_weights,
        edge_weights=edge_weights,
    )


def load_metis(path: str) -> HostGraph:
    with open(path, "rb") as f:
        raw = f.read()
    try:
        graph = _parse_metis_native(raw)
        if graph is not None:
            return graph
        return parse_metis(raw.decode("latin-1"))
    except GraphFormatError as e:
        raise e.with_path(path) from None


def _parse_metis_native(raw: bytes) -> HostGraph | None:
    """One-pass native tokenizer (the file_toker.h analog,
    kaminpar_tpu/native/codec.cpp kmp_parse_metis_body); None -> fall back
    to the Python parser."""
    from .. import native

    lib = native.get_lib()
    if lib is None:
        return None
    # split off the header line (skipping leading comments)
    pos = 0
    header = None
    while pos < len(raw):
        eol = raw.find(b"\n", pos)
        if eol < 0:
            eol = len(raw)
        line = raw[pos:eol].strip()
        pos = eol + 1
        if line and not line.startswith(b"%"):
            header = line.split()
            break
    if header is None or len(header) < 2:
        return None
    try:
        n = int(header[0])
        m2 = int(header[1]) * 2
    except ValueError:
        raise GraphFormatError(
            "non-integer header token", line=1
        ) from None
    if n < 0 or m2 < 0:
        raise GraphFormatError("negative n or m in header", line=1)
    if m2 > 2 * max(len(raw), 1):
        raise GraphFormatError(
            f"header claims {m2} directed edges but the file is only "
            f"{len(raw)} bytes",
            line=1,
        )
    fmt = header[2].decode() if len(header) > 2 else "0"
    has_vw = len(fmt) >= 2 and fmt[-2] == "1"
    has_ew = fmt[-1] == "1"

    body = raw[pos:]
    xadj = np.zeros(n + 1, dtype=np.int64)
    adjncy = np.zeros(max(m2, 1), dtype=np.int32)
    vw = np.zeros(n if has_vw else 1, dtype=np.int64)
    ew = np.zeros(max(m2, 1) if has_ew else 1, dtype=np.int64)
    m = lib.kmp_parse_metis_body(
        body, len(body), n, int(has_vw), int(has_ew), m2,
        xadj, adjncy, vw, ew,
    )
    if m < 0:
        # -m is the 1-based NODE index whose line is malformed; the
        # native tokenizer does not track comment lines, so report the
        # node index rather than a possibly-off-by-comments line number
        raise GraphFormatError(
            f"malformed adjacency on node {-m} (line {-m} + header/"
            "comment lines)"
        )
    if m != m2:
        raise GraphFormatError(
            f"header claims {m2} directed edges, file has {m}", line=1
        )
    if m and (adjncy[:m].min() < 0 or adjncy[:m].max() >= n):
        raise GraphFormatError("neighbor id out of range")
    if has_vw and n and vw.min() < 0:
        raise GraphFormatError("negative node weight")
    if has_ew and m and ew[:m].min() < 0:
        raise GraphFormatError("negative edge weight")
    return HostGraph(
        xadj=xadj,
        adjncy=adjncy[:m],
        node_weights=vw if has_vw else None,
        edge_weights=ew[:m] if has_ew else None,
    )


def write_metis(graph: HostGraph, path: str) -> None:
    n, m = graph.n, graph.m
    has_nw = graph.node_weights is not None
    has_ew = graph.edge_weights is not None
    fmt = f"{int(has_nw)}{int(has_ew)}"
    with open(path, "w") as f:
        header = f"{n} {m // 2}"
        if has_nw or has_ew:
            header += f" {fmt}"
        f.write(header + "\n")
        nw = graph.node_weights
        ew = graph.edge_weights
        if not has_nw and not has_ew and m > 0:
            # vectorized fast path: one token stream with '\n' as the
            # separator after each row's last edge, then blank lines
            # spliced back in for isolated nodes (which METIS encodes as
            # empty lines — see tests/test_io.py)
            deg = graph.degrees()
            tokens = np.char.mod("%d", graph.adjncy.astype(np.int64) + 1)
            sep = np.full(m, " ", dtype="U1")
            row_ends = np.asarray(graph.xadj[1:], dtype=np.int64)[deg > 0] - 1
            sep[row_ends] = "\n"
            body = "".join(np.char.add(tokens, sep))
            lines = body.split("\n")[:-1]  # one entry per nonempty row
            it = iter(lines)
            f.write("\n".join(next(it) if d else "" for d in deg > 0) + "\n")
        else:
            for u in range(n):
                parts = []
                if has_nw:
                    parts.append(str(int(nw[u])))
                for e in range(int(graph.xadj[u]), int(graph.xadj[u + 1])):
                    parts.append(str(int(graph.adjncy[e]) + 1))
                    if has_ew:
                        parts.append(str(int(ew[e])))
                f.write(" ".join(parts) + "\n")
