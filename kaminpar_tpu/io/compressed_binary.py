"""On-disk compressed graph format (graph_compression_binary.cc analog).

Stores the varint-gap streams of a CompressedHostGraph plus weights in a
single .npz container with a magic key, so compressed graphs load without
re-encoding (the reference's `--input-format compressed` path).

Two load paths:

  * **eager** (the default): every member materializes into host RAM up
    front — fine for graphs the host holds comfortably;
  * **lazy/mmap** (``load_compressed(path, lazy=True)``, used by the
    out-of-core ``--scheme external`` driver): ZIP_STORED members are
    ``np.memmap``-ed at their byte offset inside the container, so
    ``decode_range`` touches only the pages a chunk needs and a
    disk-backed fine graph streams WITHOUT the full-file RAM spike the
    eager path pays.  Containers written with ``compress=False`` are
    fully mmapable; deflated members (``np.savez_compressed``) cannot be
    randomly accessed and fall back to an eager read per member.
"""

from __future__ import annotations

import zipfile

import numpy as np

from ..graphs.compressed import CompressedHostGraph

MAGIC = "kaminpar-tpu-compressed-v1"

_MEMBERS = ("xadj", "offsets", "data", "node_weights", "edge_weights",
            "wdata", "woffsets")


def write_compressed(path: str, graph: CompressedHostGraph,
                     compress: bool = True) -> None:
    """Write the container.  ``compress=False`` stores members raw
    (ZIP_STORED) so ``load_compressed(..., lazy=True)`` can mmap them —
    the on-disk tier of the external scheme trades the codec's own
    compression (the byte streams are already varint-packed) for
    chunk-granular random access."""
    arrays = {
        "magic": np.frombuffer(MAGIC.encode(), dtype=np.uint8),
        "xadj": graph.xadj,
        "offsets": graph.offsets,
        "data": graph.data,
        "codec": np.frombuffer(graph.codec.encode(), dtype=np.uint8),
    }
    if graph.node_weights is not None:
        arrays["node_weights"] = np.asarray(graph.node_weights)
    if graph.edge_weights is not None:
        arrays["edge_weights"] = np.asarray(graph.edge_weights)
    if graph.wdata is not None:
        arrays["wdata"] = graph.wdata
        arrays["woffsets"] = graph.woffsets
    (np.savez_compressed if compress else np.savez)(path, **arrays)


def _mmap_npy_member(path: str, info: "zipfile.ZipInfo"):
    """np.memmap one ZIP_STORED .npy member at its in-container byte
    offset (None when the member cannot be mapped: deflated, fortran,
    object dtype, or an unknown npy version)."""
    with open(path, "rb") as f:
        f.seek(info.header_offset)
        lh = f.read(30)
        if len(lh) < 30 or lh[:4] != b"PK\x03\x04":
            return None
        name_len = int.from_bytes(lh[26:28], "little")
        extra_len = int.from_bytes(lh[28:30], "little")
        f.seek(info.header_offset + 30 + name_len + extra_len)
        try:
            version = np.lib.format.read_magic(f)
            if version == (1, 0):
                shape, fortran, dtype = (
                    np.lib.format.read_array_header_1_0(f)
                )
            elif version == (2, 0):
                shape, fortran, dtype = (
                    np.lib.format.read_array_header_2_0(f)
                )
            else:
                return None
        except ValueError:
            return None
        if fortran or dtype.hasobject:
            return None
        offset = f.tell()
    if int(np.prod(shape, dtype=np.int64)) == 0:
        return np.zeros(shape, dtype=dtype)
    return np.memmap(path, dtype=dtype, mode="r", offset=offset,
                     shape=shape)


def _lazy_members(path: str) -> dict:
    """name -> mmapped array for every mappable member."""
    out = {}
    with zipfile.ZipFile(path) as zf:
        for info in zf.infolist():
            if not info.filename.endswith(".npy"):
                continue
            key = info.filename[:-4]
            if key not in _MEMBERS:
                continue
            if info.compress_type != zipfile.ZIP_STORED:
                continue
            arr = _mmap_npy_member(path, info)
            if arr is not None:
                out[key] = arr
    return out


def load_compressed(path: str, lazy: bool = False) -> CompressedHostGraph:
    """Load a container.  ``lazy=True`` memory-maps every ZIP_STORED
    member (chunk-granular page-in via decode_range) and eager-loads
    only what cannot be mapped; the default materializes everything up
    front (the historical behavior)."""
    lazy_map = _lazy_members(path) if lazy else {}
    with np.load(path) as z:
        if "magic" not in z or bytes(z["magic"]).decode() != MAGIC:
            raise ValueError(f"{path} is not a kaminpar-tpu compressed graph")

        def get(name):
            if name in lazy_map:
                return lazy_map[name]
            return z[name] if name in z else None

        return CompressedHostGraph(
            xadj=get("xadj"),
            offsets=get("offsets"),
            data=get("data"),
            node_weights=get("node_weights"),
            edge_weights=get("edge_weights"),
            codec=bytes(z["codec"]).decode() if "codec" in z else "gap",
            wdata=get("wdata"),
            woffsets=get("woffsets"),
        )


def is_compressed_file(path: str) -> bool:
    try:
        with np.load(path) as z:
            return "magic" in z and bytes(z["magic"]).decode() == MAGIC
    except (OSError, ValueError, zipfile.BadZipFile):
        return False
