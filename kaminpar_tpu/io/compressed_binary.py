"""On-disk compressed graph format (graph_compression_binary.cc analog).

Stores the varint-gap streams of a CompressedHostGraph plus weights in a
single .npz container with a magic key, so compressed graphs load without
re-encoding (the reference's `--input-format compressed` path).
"""

from __future__ import annotations

import numpy as np

from ..graphs.compressed import CompressedHostGraph

MAGIC = "kaminpar-tpu-compressed-v1"


def write_compressed(path: str, graph: CompressedHostGraph) -> None:
    arrays = {
        "magic": np.frombuffer(MAGIC.encode(), dtype=np.uint8),
        "xadj": graph.xadj,
        "offsets": graph.offsets,
        "data": graph.data,
        "codec": np.frombuffer(graph.codec.encode(), dtype=np.uint8),
    }
    if graph.node_weights is not None:
        arrays["node_weights"] = np.asarray(graph.node_weights)
    if graph.edge_weights is not None:
        arrays["edge_weights"] = np.asarray(graph.edge_weights)
    if graph.wdata is not None:
        arrays["wdata"] = graph.wdata
        arrays["woffsets"] = graph.woffsets
    np.savez_compressed(path, **arrays)


def load_compressed(path: str) -> CompressedHostGraph:
    with np.load(path) as z:
        if "magic" not in z or bytes(z["magic"]).decode() != MAGIC:
            raise ValueError(f"{path} is not a kaminpar-tpu compressed graph")
        return CompressedHostGraph(
            xadj=z["xadj"],
            offsets=z["offsets"],
            data=z["data"],
            node_weights=z["node_weights"] if "node_weights" in z else None,
            edge_weights=z["edge_weights"] if "edge_weights" in z else None,
            codec=bytes(z["codec"]).decode() if "codec" in z else "gap",
            wdata=z["wdata"] if "wdata" in z else None,
            woffsets=z["woffsets"] if "woffsets" in z else None,
        )


def is_compressed_file(path: str) -> bool:
    import zipfile

    try:
        with np.load(path) as z:
            return "magic" in z and bytes(z["magic"]).decode() == MAGIC
    except (OSError, ValueError, zipfile.BadZipFile):
        return False
