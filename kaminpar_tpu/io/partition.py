"""Partition file IO (analog of include/kaminpar-io/kaminpar_io.h:37-54).

A partition file is one block id per line, node order = graph order.
Block-size files store one block weight per line.
"""

from __future__ import annotations

import numpy as np


def read_partition(path: str) -> np.ndarray:
    return np.loadtxt(path, dtype=np.int32, ndmin=1)


def write_partition(path: str, partition: np.ndarray) -> None:
    np.savetxt(path, np.asarray(partition, dtype=np.int32), fmt="%d")


def write_block_sizes(path: str, partition: np.ndarray, k: int) -> None:
    sizes = np.bincount(np.asarray(partition), minlength=k)
    np.savetxt(path, sizes, fmt="%d")


def read_block_sizes(path: str) -> np.ndarray:
    return np.loadtxt(path, dtype=np.int64, ndmin=1)


def write_remapping(path: str, mapping: np.ndarray) -> None:
    """One new node id per line (kaminpar_io.h write_remapping analog;
    used to persist e.g. the degree-bucket permutation)."""
    np.savetxt(path, np.asarray(mapping, dtype=np.int64), fmt="%d")
