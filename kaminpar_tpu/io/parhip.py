"""ParHiP binary graph format reader/writer.

Analog of kaminpar-io/parhip_parser.cc; layout per docs/graph_file_format.md:
24-byte header of three uint64 (version bitfield, n, m), then byte offsets
([n+1] * EID bytes, relative to file start), adjacency (m * NID), optional
node weights (n * NWGT), optional edge weights (m * EWGT).

Version bitfield (LSB first):
  bit 0: edge weights ABSENT (1 = unweighted)
  bit 1: node weights ABSENT
  bit 2: edge ids 32-bit (1) / 64-bit (0)
  bit 3: node ids 32-bit (1) / 64-bit (0)
  bit 4: node weights 32-bit (1)
  bit 5: edge weights 32-bit (1)
"""

from __future__ import annotations

import numpy as np

from ..graphs.host import HostGraph
from .errors import GraphFormatError

_HEADER_BYTES = 24


def load_parhip(path: str) -> HostGraph:
    with open(path, "rb") as f:
        data = f.read()
    try:
        return parse_parhip(data)
    except GraphFormatError as e:
        raise e.with_path(path) from None


def _take(data: bytes, dtype, count: int, pos: int, what: str) -> np.ndarray:
    """frombuffer with an explicit truncation error naming the section
    and the byte offset where the file ran out."""
    need = pos + count * np.dtype(dtype).itemsize
    if len(data) < need:
        raise GraphFormatError(
            f"truncated ParHiP file: {what} needs bytes [{pos}, {need}), "
            f"file has {len(data)}",
            offset=len(data),
        )
    return np.frombuffer(data, dtype=dtype, count=count, offset=pos)


def parse_parhip(data: bytes) -> HostGraph:
    if len(data) < _HEADER_BYTES:
        raise GraphFormatError(
            "truncated ParHiP file: missing 24-byte header",
            offset=len(data),
        )
    version, n, m = np.frombuffer(data[:_HEADER_BYTES], dtype=np.uint64)
    version = int(version)
    n, m = int(n), int(m)

    has_edge_weights = not (version & 1)
    has_node_weights = not (version >> 1 & 1)
    eid_t = np.uint32 if version >> 2 & 1 else np.uint64
    nid_t = np.uint32 if version >> 3 & 1 else np.uint64
    nw_t = np.int32 if version >> 4 & 1 else np.int64
    ew_t = np.int32 if version >> 5 & 1 else np.int64

    pos = _HEADER_BYTES
    offsets = _take(data, eid_t, n + 1, pos, f"offset array (n={n})")
    pos += (n + 1) * np.dtype(eid_t).itemsize
    # offsets are byte addresses of first neighbor; normalize to edge
    # indices.  int64 view: corrupted huge uint64 values wrap and are
    # caught by the monotonicity / alignment / total checks below.
    nid_size = np.dtype(nid_t).itemsize
    o64 = offsets.astype(np.int64)
    if n and (np.diff(o64) < 0).any():
        bad = int(np.flatnonzero(np.diff(o64) < 0)[0])
        raise GraphFormatError(
            f"non-monotone neighborhood offsets at node {bad}",
            offset=_HEADER_BYTES + bad * np.dtype(eid_t).itemsize,
        )
    rel = o64 - int(o64[0])
    if (rel % nid_size != 0).any():
        raise GraphFormatError(
            f"offsets not aligned to the {nid_size}-byte neighbor id size",
            offset=_HEADER_BYTES,
        )
    xadj = rel // nid_size
    if xadj[-1] != m:
        raise GraphFormatError(
            f"offsets end at edge {int(xadj[-1])} but header claims m={m}",
            offset=_HEADER_BYTES,
        )

    adj_raw = _take(data, nid_t, m, pos, f"adjacency (m={m})")
    if m and int(adj_raw.max()) >= n:
        bad = int(np.flatnonzero(adj_raw >= np.uint64(n))[0])
        raise GraphFormatError(
            f"neighbor id {int(adj_raw[bad])} out of range [0, {n})",
            offset=pos + bad * nid_size,
        )
    adjncy = adj_raw.astype(np.int32)
    pos += m * nid_size

    node_weights = None
    if has_node_weights:
        node_weights = _take(
            data, nw_t, n, pos, f"node weights (n={n})"
        ).astype(np.int64)
        if n and node_weights.min() < 0:
            bad = int(np.flatnonzero(node_weights < 0)[0])
            raise GraphFormatError(
                f"negative node weight at node {bad}",
                offset=pos + bad * np.dtype(nw_t).itemsize,
            )
        pos += n * np.dtype(nw_t).itemsize

    edge_weights = None
    if has_edge_weights:
        edge_weights = _take(
            data, ew_t, m, pos, f"edge weights (m={m})"
        ).astype(np.int64)
        if m and edge_weights.min() < 0:
            bad = int(np.flatnonzero(edge_weights < 0)[0])
            raise GraphFormatError(
                f"negative edge weight at edge {bad}",
                offset=pos + bad * np.dtype(ew_t).itemsize,
            )

    return HostGraph(
        xadj=xadj,
        adjncy=adjncy,
        node_weights=node_weights,
        edge_weights=edge_weights,
    )


def write_parhip(graph: HostGraph, path: str, use_32bit: bool = True) -> None:
    n, m = graph.n, graph.m
    has_nw = graph.node_weights is not None
    has_ew = graph.edge_weights is not None
    version = 0
    if not has_ew:
        version |= 1
    if not has_nw:
        version |= 2
    eid_t = np.uint32 if use_32bit else np.uint64
    nid_t = np.uint32 if use_32bit else np.uint64
    if use_32bit:
        version |= 4 | 8 | 16 | 32
    nw_t = np.int32 if use_32bit else np.int64
    ew_t = np.int32 if use_32bit else np.int64

    nid_size = np.dtype(nid_t).itemsize
    base = _HEADER_BYTES + (n + 1) * np.dtype(eid_t).itemsize
    offsets = (graph.xadj.astype(np.int64) * nid_size + base).astype(eid_t)
    with open(path, "wb") as f:
        f.write(np.array([version, n, m], dtype=np.uint64).tobytes())
        f.write(offsets.tobytes())
        f.write(graph.adjncy.astype(nid_t).tobytes())
        if has_nw:
            f.write(graph.node_weights.astype(nw_t).tobytes())
        if has_ew:
            f.write(graph.edge_weights.astype(ew_t).tobytes())
