"""ParHiP binary graph format reader/writer.

Analog of kaminpar-io/parhip_parser.cc; layout per docs/graph_file_format.md:
24-byte header of three uint64 (version bitfield, n, m), then byte offsets
([n+1] * EID bytes, relative to file start), adjacency (m * NID), optional
node weights (n * NWGT), optional edge weights (m * EWGT).

Version bitfield (LSB first):
  bit 0: edge weights ABSENT (1 = unweighted)
  bit 1: node weights ABSENT
  bit 2: edge ids 32-bit (1) / 64-bit (0)
  bit 3: node ids 32-bit (1) / 64-bit (0)
  bit 4: node weights 32-bit (1)
  bit 5: edge weights 32-bit (1)
"""

from __future__ import annotations

import numpy as np

from ..graphs.host import HostGraph

_HEADER_BYTES = 24


def load_parhip(path: str) -> HostGraph:
    with open(path, "rb") as f:
        data = f.read()
    return parse_parhip(data)


def parse_parhip(data: bytes) -> HostGraph:
    if len(data) < _HEADER_BYTES:
        raise ValueError("truncated ParHiP file")
    version, n, m = np.frombuffer(data[:_HEADER_BYTES], dtype=np.uint64)
    version = int(version)
    n, m = int(n), int(m)

    has_edge_weights = not (version & 1)
    has_node_weights = not (version >> 1 & 1)
    eid_t = np.uint32 if version >> 2 & 1 else np.uint64
    nid_t = np.uint32 if version >> 3 & 1 else np.uint64
    nw_t = np.int32 if version >> 4 & 1 else np.int64
    ew_t = np.int32 if version >> 5 & 1 else np.int64

    pos = _HEADER_BYTES
    offsets = np.frombuffer(data, dtype=eid_t, count=n + 1, offset=pos)
    pos += (n + 1) * np.dtype(eid_t).itemsize
    # offsets are byte addresses of first neighbor; normalize to edge indices
    nid_size = np.dtype(nid_t).itemsize
    xadj = (offsets.astype(np.int64) - int(offsets[0])) // nid_size
    if xadj[-1] != m:
        raise ValueError("ParHiP offsets inconsistent with edge count")

    adjncy = np.frombuffer(data, dtype=nid_t, count=m, offset=pos).astype(np.int32)
    pos += m * nid_size

    node_weights = None
    if has_node_weights:
        node_weights = np.frombuffer(data, dtype=nw_t, count=n, offset=pos).astype(
            np.int64
        )
        pos += n * np.dtype(nw_t).itemsize

    edge_weights = None
    if has_edge_weights:
        edge_weights = np.frombuffer(data, dtype=ew_t, count=m, offset=pos).astype(
            np.int64
        )

    return HostGraph(
        xadj=xadj,
        adjncy=adjncy,
        node_weights=node_weights,
        edge_weights=edge_weights,
    )


def write_parhip(graph: HostGraph, path: str, use_32bit: bool = True) -> None:
    n, m = graph.n, graph.m
    has_nw = graph.node_weights is not None
    has_ew = graph.edge_weights is not None
    version = 0
    if not has_ew:
        version |= 1
    if not has_nw:
        version |= 2
    eid_t = np.uint32 if use_32bit else np.uint64
    nid_t = np.uint32 if use_32bit else np.uint64
    if use_32bit:
        version |= 4 | 8 | 16 | 32
    nw_t = np.int32 if use_32bit else np.int64
    ew_t = np.int32 if use_32bit else np.int64

    nid_size = np.dtype(nid_t).itemsize
    base = _HEADER_BYTES + (n + 1) * np.dtype(eid_t).itemsize
    offsets = (graph.xadj.astype(np.int64) * nid_size + base).astype(eid_t)
    with open(path, "wb") as f:
        f.write(np.array([version, n, m], dtype=np.uint64).tobytes())
        f.write(offsets.tobytes())
        f.write(graph.adjncy.astype(nid_t).tobytes())
        if has_nw:
            f.write(graph.node_weights.astype(nw_t).tobytes())
        if has_ew:
            f.write(graph.edge_weights.astype(ew_t).tobytes())
