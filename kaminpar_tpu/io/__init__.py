"""Graph and partition IO (analog of kaminpar-io)."""

from __future__ import annotations

import os

from .errors import GraphFormatError  # noqa: F401
from .metis import load_metis, parse_metis, write_metis  # noqa: F401
from .parhip import load_parhip, parse_parhip, write_parhip  # noqa: F401
from .compressed_binary import (  # noqa: F401
    is_compressed_file,
    load_compressed,
    write_compressed,
)
from .partition import (  # noqa: F401
    read_partition,
    write_partition,
    read_block_sizes,
    write_block_sizes,
    write_remapping,
)
from ..graphs.host import HostGraph


def load_graph(path: str, fmt: str = "auto", ordering: str = "natural",
               lazy: bool = False):
    """Load a graph by file format (kaminpar_io.h read_graph analog).
    fmt: 'metis', 'parhip', 'compressed', or 'auto' (sniff by extension
    then content).  'compressed' returns a CompressedHostGraph.
    ordering: 'natural' keeps file order; 'degree-buckets' rearranges
    nodes into exponentially-spaced degree buckets (NodeOrdering
    analog; not applicable to compressed containers).  ``lazy`` asks
    the compressed loader to mmap the container chunk-granularly
    (the external scheme's disk tier) instead of materializing it."""
    if ordering not in ("natural", "degree-buckets"):
        raise ValueError(f"unknown node ordering: {ordering}")
    if fmt == "auto":
        ext = os.path.splitext(path)[1].lower()
        if ext in (".metis", ".graph", ".txt"):
            fmt = "metis"
        elif ext in (".parhip", ".bgf", ".bin"):
            fmt = "parhip"
        elif ext == ".npz" or is_compressed_file(path):
            fmt = "compressed"
        else:
            with open(path, "rb") as f:
                head = f.read(64)
            fmt = "metis" if _looks_like_text(head) else "parhip"
    if fmt == "compressed" and ordering != "natural":
        raise ValueError("ordering is not supported for compressed containers")
    if fmt == "metis":
        graph = load_metis(path)
    elif fmt == "parhip":
        graph = load_parhip(path)
    elif fmt == "compressed":
        return load_compressed(path, lazy=lazy)
    else:
        raise ValueError(f"unknown graph format: {fmt}")
    if ordering == "degree-buckets":
        from ..graphs.host import apply_permutation, degree_bucket_permutation

        graph = apply_permutation(graph, degree_bucket_permutation(graph))
    return graph


def _looks_like_text(head: bytes) -> bool:
    try:
        head.decode("ascii")
        return True
    except UnicodeDecodeError:
        return False
