"""Graph and partition IO (analog of kaminpar-io)."""

from __future__ import annotations

import os

from .metis import load_metis, parse_metis, write_metis  # noqa: F401
from .parhip import load_parhip, parse_parhip, write_parhip  # noqa: F401
from .compressed_binary import (  # noqa: F401
    is_compressed_file,
    load_compressed,
    write_compressed,
)
from .partition import (  # noqa: F401
    read_partition,
    write_partition,
    read_block_sizes,
    write_block_sizes,
)
from ..graphs.host import HostGraph


def load_graph(path: str, fmt: str = "auto"):
    """Load a graph by file format (kaminpar_io.h read_graph analog).
    fmt: 'metis', 'parhip', 'compressed', or 'auto' (sniff by extension
    then content).  'compressed' returns a CompressedHostGraph."""
    if fmt == "auto":
        ext = os.path.splitext(path)[1].lower()
        if ext in (".metis", ".graph", ".txt"):
            fmt = "metis"
        elif ext in (".parhip", ".bgf", ".bin"):
            fmt = "parhip"
        elif ext == ".npz" or is_compressed_file(path):
            fmt = "compressed"
        else:
            with open(path, "rb") as f:
                head = f.read(64)
            fmt = "metis" if _looks_like_text(head) else "parhip"
    if fmt == "metis":
        return load_metis(path)
    if fmt == "parhip":
        return load_parhip(path)
    if fmt == "compressed":
        return load_compressed(path)
    raise ValueError(f"unknown graph format: {fmt}")


def _looks_like_text(head: bytes) -> bool:
    try:
        head.decode("ascii")
        return True
    except UnicodeDecodeError:
        return False
