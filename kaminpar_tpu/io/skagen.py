"""Streamed synthetic graph generation — the KaGen-streaming analog.

The reference's dKaMinPar can consume a synthetic graph *streamed in
chunks* from the external KaGen library (kaminpar-io/dist_skagen.cc:
``read_or_generate_graph`` pulls per-PE streaming chunks so no process
ever materializes the global edge list).  This module is the
framework's native equivalent:

* every generator is **chunk-deterministic**: the assembled graph is
  bitwise identical for ANY number of chunks (the KaGen contract) —
  edge batches are derived from fixed-size counter blocks with
  per-block seeds, and RGG point sets come from a deterministic
  recursive binomial split over the cell grid, so any chunk can
  regenerate exactly the points/edges it needs without global state;
* a chunk yields the CSR rows of a contiguous vertex range with peak
  memory O(m / num_chunks + batch), trading regeneration work for
  memory exactly like KaGen's streaming mode;
* :func:`hostgraph_from_stream` assembles chunks into a
  :class:`HostGraph` without ever building the global directed edge
  list (the usual ``from_edge_list`` path allocates 2m edge triples
  before sorting; the streamed path peaks at one chunk).

Supported generator kinds mirror ``graphs/factories.py``'s in-process
surface where streaming is meaningful: ``rmat``, ``gnm`` (counter-block
edge regeneration) and ``rgg2d`` (cell-local point regeneration).
Preferential attachment (``ba``) is inherently sequential and has no
streaming form, in KaGen or here.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator, Optional, Tuple

import numpy as np

from ..graphs.host import NODE_DTYPE, WEIGHT_DTYPE, HostGraph

# Fixed counter-block size: edge draws [i*B, (i+1)*B) always come from
# the block-i RNG regardless of chunking, which is what makes the
# output chunking-invariant.
EDGE_BLOCK = 1 << 18


def _block_rng(seed: int, tag: int, index: int) -> np.random.Generator:
    return np.random.default_rng(
        np.random.SeedSequence(entropy=(0x5CA9E, seed & 0xFFFFFFFF, tag, index))
    )


@dataclass(frozen=True)
class GraphChunk:
    """CSR rows of the contiguous vertex range [v_begin, v_end)."""

    v_begin: int
    v_end: int
    xadj: np.ndarray  # int64[v_end - v_begin + 1], chunk-relative offsets
    adjncy: np.ndarray  # global neighbor ids
    adjwgt: np.ndarray  # merged multiplicities (parallel edges sum)


class StreamedGraph:
    """Lazy chunked view of a synthetic graph (one KaGen stream)."""

    def __init__(self, kind: str, n: int, num_chunks: int, seed: int,
                 params: dict):
        if num_chunks < 1:
            raise ValueError("num_chunks must be >= 1")
        self.kind = kind
        self.n = int(n)
        self.num_chunks = int(min(num_chunks, max(self.n, 1)))
        self.seed = int(seed)
        self.params = params
        self._cell_counts_cache: Optional[np.ndarray] = None

    # -- vertex ranges ----------------------------------------------------
    def chunk_range(self, c: int) -> Tuple[int, int]:
        base, rem = divmod(self.n, self.num_chunks)
        v0 = c * base + min(c, rem)
        return v0, v0 + base + (1 if c < rem else 0)

    # -- chunk materialization -------------------------------------------
    def chunk(self, c: int) -> GraphChunk:
        if not (0 <= c < self.num_chunks):
            raise IndexError(c)
        v0, v1 = self.chunk_range(c)
        if self.kind in ("rmat", "gnm"):
            src, dst = self._edge_chunk(v0, v1)
        elif self.kind in ("rgg2d", "rgg3d"):
            src, dst = self._rgg_chunk(v0, v1)
        else:  # pragma: no cover - guarded by streamed()
            raise ValueError(self.kind)
        return _rows_from_directed(v0, v1, self.n, src, dst)

    @property
    def _dim(self) -> int:
        return 3 if self.kind == "rgg3d" else 2

    def chunks(self) -> Iterator[GraphChunk]:
        for c in range(self.num_chunks):
            yield self.chunk(c)

    # -- counter-block edge generators (rmat / gnm) ----------------------
    def _edge_block(self, i: int) -> Tuple[np.ndarray, np.ndarray]:
        """Directed edge draws of counter block i.  Chunking invariance
        only needs block content to be a deterministic function of
        (seed, block index): every consumer computes the same cnt for
        block i, so the final partial block draws exactly cnt values."""
        m = int(self.params["m"])
        lo = i * EDGE_BLOCK
        cnt = min(EDGE_BLOCK, m - lo)
        rng = _block_rng(self.seed, 1, i)
        if self.kind == "rmat":
            scale = self.params["scale"]
            probs = self.params["probs"]
            u = np.zeros(cnt, dtype=np.int64)
            v = np.zeros(cnt, dtype=np.int64)
            for _ in range(scale):
                quad = rng.choice(4, size=cnt, p=probs)
                u = (u << 1) | (quad >> 1)
                v = (v << 1) | (quad & 1)
        else:  # gnm
            u = rng.integers(0, self.n, cnt, dtype=np.int64)
            v = rng.integers(0, self.n, cnt, dtype=np.int64)
        return u, v

    def _edge_chunk(self, v0: int, v1: int) -> Tuple[np.ndarray, np.ndarray]:
        """All directed edges with source in [v0, v1): both directions of
        every undirected draw are considered, self-loops dropped."""
        m = int(self.params["m"])
        nblocks = (m + EDGE_BLOCK - 1) // EDGE_BLOCK
        srcs, dsts = [], []
        for i in range(nblocks):
            u, v = self._edge_block(i)
            keep = u != v
            u, v = u[keep], v[keep]
            for a, b in ((u, v), (v, u)):
                sel = (a >= v0) & (a < v1)
                if sel.any():
                    srcs.append(a[sel])
                    dsts.append(b[sel])
        if not srcs:
            z = np.zeros(0, dtype=np.int64)
            return z, z
        return np.concatenate(srcs), np.concatenate(dsts)

    # -- RGG2D/RGG3D: deterministic cell grid ----------------------------
    def _cell_counts(self) -> np.ndarray:
        """Points per cell via a deterministic recursive binomial split of
        n — depends only on (seed, n, ncell), so it is computed once per
        StreamedGraph and cached (O(#cells) memory; the per-PE equivalent
        of KaGen's distributed splitting)."""
        if self._cell_counts_cache is not None:
            return self._cell_counts_cache
        ncell = self.params["ncell"]
        total_cells = ncell ** self._dim
        counts = np.zeros(total_cells, dtype=np.int64)
        stack = [(0, total_cells, self.n)]
        while stack:
            lo, hi, cnt = stack.pop()
            if cnt == 0:
                continue
            if hi - lo == 1:
                counts[lo] = cnt
                continue
            mid = (lo + hi) // 2
            rng = _block_rng(self.seed, 2, lo * (total_cells + 1) + hi)
            left = int(rng.binomial(cnt, (mid - lo) / (hi - lo)))
            stack.append((lo, mid, left))
            stack.append((mid, hi, cnt - left))
        self._cell_counts_cache = counts
        return counts

    def _cell_coords(self, cell: int) -> Tuple[int, ...]:
        """Decode a flat cell id into grid coordinates (row-major: the 2D
        decode matches the original divmod(cell, ncell) layout)."""
        ncell = self.params["ncell"]
        coords = []
        for _ in range(self._dim):
            cell, c = divmod(cell, ncell)
            coords.append(c)
        return tuple(reversed(coords))

    def _cell_points(self, cell: int, count: int) -> np.ndarray:
        ncell = self.params["ncell"]
        rng = _block_rng(self.seed, 3, cell)
        pts = rng.random((count, self._dim))
        return (pts + np.array(self._cell_coords(cell))) / ncell

    def _neighbor_cells(self, cell: int):
        from itertools import product

        ncell = self.params["ncell"]
        coords = self._cell_coords(cell)
        for deltas in product((-1, 0, 1), repeat=self._dim):
            nb = [c + d for c, d in zip(coords, deltas)]
            if all(0 <= c < ncell for c in nb):
                flat = 0
                for c in nb:
                    flat = flat * ncell + c
                yield flat

    def _rgg_chunk(self, v0: int, v1: int) -> Tuple[np.ndarray, np.ndarray]:
        """Directed edges with source in [v0, v1).  Vertex ids are
        cell-major (prefix sums of the deterministic cell counts); only
        the cells overlapping the range plus their 3^dim-neighborhoods
        are regenerated."""
        radius = self.params["radius"]
        counts = self._cell_counts()
        starts = np.zeros(len(counts) + 1, dtype=np.int64)
        np.cumsum(counts, out=starts[1:])
        # owned cells: those whose vertex span intersects [v0, v1)
        own_cells = np.nonzero((starts[1:] > v0) & (starts[:-1] < v1))[0]
        if len(own_cells) == 0:
            z = np.zeros(0, dtype=np.int64)
            return z, z
        # regenerate owned + neighbor cells once
        need = set()
        for cell in own_cells:
            need.update(self._neighbor_cells(int(cell)))
        pts = {c: self._cell_points(c, int(counts[c])) for c in sorted(need)}
        r2 = radius * radius
        srcs, dsts = [], []
        for cell in own_cells:
            a_pts = pts[int(cell)]
            if len(a_pts) == 0:
                continue
            a_ids = starts[cell] + np.arange(len(a_pts), dtype=np.int64)
            a_sel = (a_ids >= v0) & (a_ids < v1)
            if not a_sel.any():
                continue
            for b_cell in self._neighbor_cells(int(cell)):
                b_pts = pts[b_cell]
                if len(b_pts) == 0:
                    continue
                b_ids = starts[b_cell] + np.arange(
                    len(b_pts), dtype=np.int64
                )
                d2 = ((a_pts[:, None, :] - b_pts[None, :, :]) ** 2).sum(-1)
                ii, jj = np.nonzero(d2 <= r2)
                keep = a_sel[ii] & (a_ids[ii] != b_ids[jj])
                if keep.any():
                    srcs.append(a_ids[ii][keep])
                    dsts.append(b_ids[jj][keep])
        if not srcs:
            z = np.zeros(0, dtype=np.int64)
            return z, z
        return np.concatenate(srcs), np.concatenate(dsts)


def _rows_from_directed(
    v0: int, v1: int, n: int, src: np.ndarray, dst: np.ndarray
) -> GraphChunk:
    """Sort + merge the chunk's directed edges into CSR rows (parallel
    edges merge by multiplicity sum — the from_edge_list convention)."""
    span = v1 - v0
    if len(src) == 0:
        return GraphChunk(
            v0, v1, np.zeros(span + 1, dtype=np.int64),
            np.zeros(0, dtype=NODE_DTYPE), np.zeros(0, dtype=WEIGHT_DTYPE),
        )
    # multiplier n (not a power-of-two constant): (span * n + dst) stays
    # within int64 up to n ~ 3e9, the same bound as from_edge_list's key
    key = (src - v0) * np.int64(n) + dst
    order = np.argsort(key, kind="stable")
    key, src, dst = key[order], src[order], dst[order]
    uniq = np.empty(len(key), dtype=bool)
    uniq[0] = True
    uniq[1:] = key[1:] != key[:-1]
    seg = np.cumsum(uniq) - 1
    wgt = np.bincount(seg, minlength=seg[-1] + 1).astype(WEIGHT_DTYPE)
    src_u, dst_u = src[uniq], dst[uniq]
    xadj = np.zeros(span + 1, dtype=np.int64)
    np.add.at(xadj, src_u - v0 + 1, 1)
    np.cumsum(xadj, out=xadj)
    return GraphChunk(v0, v1, xadj, dst_u.astype(NODE_DTYPE), wgt)


def streamed(spec: str, num_chunks: int = 8,
             seed: Optional[int] = None) -> StreamedGraph:
    """Build a streamed generator from a KaGen-style option string
    (the same surface as ``graphs.factories.generate``):
    ``"rmat;n=65536;m=1000000;seed=1"``, ``"gnm;n=4096;m=30000"``,
    ``"rgg2d;n=1024;avg_degree=8"``."""
    from ..graphs.factories import (
        RMAT_DEFAULT_ABC,
        parse_gen_spec,
        rgg2d_radius,
        rgg3d_radius,
    )

    kind, kw = parse_gen_spec(spec)
    if seed is None:
        seed = int(kw.pop("seed", 1))
    else:
        kw.pop("seed", None)
    n = int(kw.pop("n"))
    if kind == "rmat":
        scale = int(np.log2(n))
        if 1 << scale != n:
            raise ValueError("rmat n must be a power of two")
        a = kw.pop("a", RMAT_DEFAULT_ABC[0])
        b = kw.pop("b", RMAT_DEFAULT_ABC[1])
        cc = kw.pop("c", RMAT_DEFAULT_ABC[2])
        params = {
            "m": int(kw.pop("m")),
            "scale": scale,
            "probs": np.array([a, b, cc, 1.0 - a - b - cc]),
        }
    elif kind == "gnm":
        params = {"m": int(kw.pop("m"))}
    elif kind == "rgg2d":
        radius = rgg2d_radius(n, float(kw.pop("avg_degree", 8.0)))
        params = {"radius": radius, "ncell": max(1, int(1.0 / radius))}
    elif kind == "rgg3d":
        radius = rgg3d_radius(n, float(kw.pop("avg_degree", 8.0)))
        params = {"radius": radius, "ncell": max(1, int(1.0 / radius))}
    else:
        raise ValueError(
            f"generator '{kind}' has no streaming form "
            "(available: rmat, gnm, rgg2d, rgg3d)"
        )
    if kw:
        raise ValueError(f"unknown option(s) for {kind}: {sorted(kw)}")
    return StreamedGraph(kind, n, num_chunks, seed, params)


def hostgraph_from_stream(sg: StreamedGraph) -> HostGraph:
    """Assemble the stream into a HostGraph chunk by chunk.  Peak extra
    memory is one chunk plus the output CSR — the global 2m-triple edge
    list of the from_edge_list path is never built."""
    xadj = np.zeros(sg.n + 1, dtype=np.int64)
    adj_parts, wgt_parts = [], []
    for ch in sg.chunks():
        deg = ch.xadj[1:] - ch.xadj[:-1]
        xadj[ch.v_begin + 1 : ch.v_end + 1] = deg
        adj_parts.append(ch.adjncy)
        wgt_parts.append(ch.adjwgt)
    np.cumsum(xadj, out=xadj)
    adjncy = (
        np.concatenate(adj_parts) if adj_parts
        else np.zeros(0, dtype=NODE_DTYPE)
    )
    wgt = (
        np.concatenate(wgt_parts) if wgt_parts
        else np.zeros(0, dtype=WEIGHT_DTYPE)
    )
    unit = bool(len(wgt) == 0 or (wgt == 1).all())
    return HostGraph(
        xadj=xadj, adjncy=adjncy,
        edge_weights=None if unit else wgt,
    )
