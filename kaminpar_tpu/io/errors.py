"""Structured graph-format errors for the IO parsers.

A malformed input file (truncated bytes, non-monotone offsets,
out-of-range neighbor ids, overflowing weights) must surface as ONE
exception type that names where in the file the problem is — not as an
IndexError or OverflowError thrown from deep inside numpy, which reads
as a parser bug rather than a data problem.  GraphFormatError subclasses
ValueError so pre-existing callers that caught ValueError keep working.
"""

from __future__ import annotations

from typing import Optional


class GraphFormatError(ValueError):
    """A graph file violates its format.

    Attributes:
      path    file path when known (loaders attach it)
      line    1-based line number for text formats (METIS)
      offset  byte offset for binary formats (ParHiP)
    """

    def __init__(
        self,
        message: str,
        *,
        path: Optional[str] = None,
        line: Optional[int] = None,
        offset: Optional[int] = None,
    ) -> None:
        self.reason = message
        self.path = path
        self.line = line
        self.offset = offset
        super().__init__(self._render())

    def _render(self) -> str:
        where = []
        if self.path is not None:
            where.append(str(self.path))
        if self.line is not None:
            where.append(f"line {self.line}")
        if self.offset is not None:
            where.append(f"byte {self.offset}")
        loc = ", ".join(where)
        return f"{self.reason} ({loc})" if loc else self.reason

    def with_path(self, path: str) -> "GraphFormatError":
        """A copy carrying the file path (loaders call this so parse_*
        stays path-agnostic)."""
        return GraphFormatError(
            self.reason, path=path, line=self.line, offset=self.offset
        )
