"""Durable array snapshots for checkpoint/resume (resilience/checkpoint.py).

Same single-file ``.npz`` container idiom as the compressed graph format
(io/compressed_binary.py): a magic key plus named numpy arrays, written
with ``np.savez_compressed`` so level CSRs and partitions deflate well.
What this module adds on top is the *durability* contract a preemption-
safe checkpoint needs:

  * **atomic**: the snapshot is written to a temp file in the target
    directory, fsync'd, then ``os.replace``'d over the final name (and
    the directory entry fsync'd), so a kill mid-write can never leave a
    half-written file under the final name;
  * **verifiable**: the writer returns the byte count and the SHA-256 of
    the written file; the reader re-hashes and refuses content that does
    not match the manifest's recorded checksum (a truncated or bit-
    rotted snapshot surfaces as a structured error, never as garbage
    arrays deep in the pipeline).
"""

from __future__ import annotations

import hashlib
import os
import tempfile
from typing import Dict, Tuple

import numpy as np

SNAPSHOT_MAGIC = "kaminpar-tpu-snapshot-v1"


class SnapshotError(ValueError):
    """A snapshot file is unreadable, has no magic, or fails its
    checksum.  Mapped to resilience.CheckpointCorrupt by the manager."""


def write_snapshot(path: str, arrays: Dict[str, np.ndarray]) -> Tuple[int, str]:
    """Atomically write ``arrays`` as an npz snapshot at ``path``.

    Returns ``(num_bytes, sha256_hex)`` of the written file.  Raises
    OSError on filesystem failure (the caller maps it to the
    ``checkpoint-write`` degradation site).
    """
    directory = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez_compressed(
                f,
                magic=np.frombuffer(SNAPSHOT_MAGIC.encode(), dtype=np.uint8),
                **{k: np.asarray(v) for k, v in arrays.items()},
            )
            f.flush()
            os.fsync(f.fileno())
        # hash in fixed chunks (zipfile seeks back to patch headers, so
        # tee-hashing during the write would record the wrong bytes; a
        # whole-file read would spike host RAM by the snapshot size on
        # the hour-class hierarchies checkpointing exists for)
        nbytes, sha = _hash_file(tmp)
        os.replace(tmp, path)
        tmp = None
        _fsync_dir(directory)
    finally:
        if tmp is not None:
            try:
                os.unlink(tmp)
            except OSError:
                pass
    return nbytes, sha


_HASH_CHUNK = 1 << 22  # 4 MiB


def _hash_file(path: str):
    """(num_bytes, sha256_hex) of a file, read in fixed chunks."""
    h = hashlib.sha256()
    n = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(_HASH_CHUNK)
            if not chunk:
                break
            h.update(chunk)
            n += len(chunk)
    return n, h.hexdigest()


def read_snapshot(
    path: str, expect_sha256: str | None = None
) -> Dict[str, np.ndarray]:
    """Read a snapshot, verifying magic and (optionally) the checksum
    (chunked — the file is not buffered whole for hashing).

    Raises SnapshotError on a missing magic or checksum mismatch and
    OSError on filesystem failure.
    """
    if expect_sha256 is not None:
        _, got = _hash_file(path)
        if got != expect_sha256:
            raise SnapshotError(
                f"{path}: checksum mismatch (manifest {expect_sha256[:12]}…, "
                f"file {got[:12]}…) — truncated or corrupted snapshot"
            )
    try:
        with np.load(path) as z:
            if "magic" not in z or bytes(z["magic"]).decode() != SNAPSHOT_MAGIC:
                raise SnapshotError(f"{path}: not a kaminpar-tpu snapshot")
            return {k: z[k] for k in z.files if k != "magic"}
    except (ValueError, OSError) as e:  # zip/npz layer failures
        if isinstance(e, SnapshotError):
            raise
        raise SnapshotError(f"{path}: unreadable snapshot ({e})") from e


def _fsync_dir(directory: str) -> None:
    """fsync a directory entry so a rename survives power loss; best
    effort on filesystems that refuse O_RDONLY dir fsync."""
    try:
        dfd = os.open(directory, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(dfd)
    except OSError:
        pass
    finally:
        os.close(dfd)
