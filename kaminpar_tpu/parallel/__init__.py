"""Multi-chip (distributed) layer.

The TPU-native re-design of the reference's distributed stack
(kaminpar-dist + kaminpar-mpi): instead of MPI ranks exchanging sparse
all-to-alls over ghost-node halos (kaminpar-dist/graphutils/communication.h),
the graph is sharded over a `jax.sharding.Mesh` axis and every exchange is
an XLA collective inside `shard_map` — `psum` for cluster/block weight
control and cut reduction, `all_gather` for label/ghost synchronization.
"""

from .mesh import make_mesh, make_torus_mesh, NODE_AXIS
from .dist_graph import (
    DistGraph,
    dist_graph_from_compressed,
    dist_graph_from_host,
)
from .dist_lp import dist_lp_cluster, dist_lp_cluster_from, dist_lp_refine
from .dist_metrics import dist_edge_cut
from .dist_coloring import dist_greedy_coloring
from .dist_clp import dist_colored_lp_refine
from .dist_balancer import dist_node_balance
from .dist_cluster_balancer import dist_cluster_balance
from .dist_jet import dist_jet_refine
from .dist_hem import dist_hem_cluster, dist_hem_lp_cluster
from .dist_context import (
    DistContext,
    DistClusteringAlgorithm,
    DistRefinementAlgorithm,
    create_dist_context_by_preset_name,
    create_dist_clusterer,
    create_dist_refiner,
    get_dist_preset_names,
)
from .dist_partitioner import dKaMinPar

__all__ = [
    "make_mesh",
    "make_torus_mesh",
    "NODE_AXIS",
    "DistGraph",
    "dist_graph_from_compressed",
    "dist_graph_from_host",
    "dist_lp_cluster",
    "dist_lp_cluster_from",
    "dist_lp_refine",
    "dist_edge_cut",
    "dist_greedy_coloring",
    "dist_colored_lp_refine",
    "dist_node_balance",
    "dist_cluster_balance",
    "dist_jet_refine",
    "dist_hem_cluster",
    "dist_hem_lp_cluster",
    "DistContext",
    "DistClusteringAlgorithm",
    "DistRefinementAlgorithm",
    "create_dist_context_by_preset_name",
    "create_dist_clusterer",
    "create_dist_refiner",
    "get_dist_preset_names",
    "dKaMinPar",
]
