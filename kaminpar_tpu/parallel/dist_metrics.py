"""Distributed quality metrics.

The analog of kaminpar-dist/metrics.cc: each PE computes its local share of
the cut and the result is allreduced — here a `psum` over the mesh axis.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

# version-portable shard_map (mesh.shard_map_compat): the
# replication-check flag is spelled check_vma / check_rep depending on
# the installed jax — the compat shim keeps every dist kernel usable on
# both instead of dying with a TypeError at the first collective
from .mesh import shard_map_compat as _shard_map

from ..ops.segments import ACC_DTYPE
from .dist_graph import DistGraph
from .mesh import NODE_AXIS


@partial(jax.jit, static_argnames=("mesh",))
def _dist_edge_cut_impl(mesh, graph: DistGraph, labels: jax.Array) -> jax.Array:
    """Edge cut of a (replicated) labeling over a sharded graph.

    Every undirected edge is stored once per endpoint, so the psum of local
    directed cut weight counts each cut edge twice (metrics.cc:37 divides
    the same way).
    """

    def local(src_l, dst_l, ew_l, labels):
        cut = jnp.sum(
            jnp.where(labels[src_l] != labels[dst_l], ew_l, 0).astype(ACC_DTYPE)
        )
        return lax.psum(cut, NODE_AXIS)

    total = _shard_map(
        local,
        mesh=mesh,
        in_specs=(P(NODE_AXIS), P(NODE_AXIS), P(NODE_AXIS), P()),
        out_specs=P(),
        check_vma=False,
    )(graph.src, graph.dst, graph.edge_w, labels)
    return total // 2


def dist_edge_cut(graph: DistGraph, labels: jax.Array) -> jax.Array:
    return _dist_edge_cut_impl(graph.src.sharding.mesh, graph, labels)
