"""Node-sharded device graph — the TPU twin of DistributedCSRGraph.

The reference distributes nodes in contiguous global ranges per PE with
local ghost copies of remote endpoints (kaminpar-dist/datastructures/
distributed_csr_graph.h:25-92, ghost_node_mapper.h:311).  On a device mesh
the same 1D distribution becomes array sharding: node arrays are sharded
over the mesh axis, and each device holds the (padded) edge list of its own
node range.  There is no explicit ghost table — remote label lookups are
gathers into a replicated label vector that is rebuilt with `all_gather`
after every bulk-synchronous round, which is the collective form of the
reference's `synchronize_ghost_node_clusters` halo exchange
(kaminpar-dist/coarsening/clustering/lp/global_lp_clusterer.cc:585-594).

Layout invariants (device d of D, n_loc = n_pad / D, m_loc = m_tot / D):
  * device d owns global nodes [d*n_loc, (d+1)*n_loc);
  * `src`/`dst`/`edge_w` slots [d*m_loc, (d+1)*m_loc) hold exactly the
    edges whose source is owned by d (both directions of an undirected
    edge exist, each stored at its own endpoint, like the reference's
    per-PE CSR rows);
  * pad edge slots have src = first owned node, dst = global pad node
    n_pad - 1, weight 0 — inert in ratings and cuts;
  * the global pad node n_pad - 1 is never a real node (the builder
    guarantees n_pad > n).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..graphs.host import HostGraph
from ..utils.math import pad_size, round_up
from .mesh import NODE_AXIS


@jax.tree_util.register_dataclass
@dataclass
class DistGraph:
    """Sharded COO graph over a 1D mesh.

    Fields:
      src, dst, edge_w : i32[m_tot]  edge arrays, sharded over the mesh axis
                         (device d holds slots [d*m_loc, (d+1)*m_loc))
      node_w           : i32[n_pad]  node weights, sharded over the mesh axis
      n, m             : i32 scalars (replicated true counts)
    """

    src: jax.Array
    dst: jax.Array
    edge_w: jax.Array
    node_w: jax.Array
    n: jax.Array
    m: jax.Array

    @property
    def n_pad(self) -> int:
        return self.node_w.shape[0]

    @property
    def m_tot(self) -> int:
        return self.src.shape[0]


def dist_graph_from_host(
    graph: HostGraph,
    mesh: Mesh,
    n_pad: Optional[int] = None,
) -> DistGraph:
    """Shard a host graph onto `mesh` in contiguous node ranges.

    The analog of dKaMinPar's vtxdist/xadj/adjncy ingestion
    (kaminpar-dist/dkaminpar.cc:400-448), minus the ghost mapping (see
    module docstring).
    """
    D = mesh.devices.size
    n, m = graph.n, graph.m
    if n_pad is None:
        n_pad = round_up(pad_size(n + 1), D)
    else:
        n_pad = round_up(n_pad, D)
    if n_pad < n + 1:
        raise ValueError("n_pad too small")
    n_loc = n_pad // D
    pad_node = n_pad - 1

    src = graph.edge_sources().astype(np.int64)
    dst = graph.adjncy.astype(np.int64)
    ew = graph.edge_weight_array().astype(np.int64)

    owner = src // n_loc
    counts = np.bincount(owner, minlength=D) if m else np.zeros(D, np.int64)
    m_loc = pad_size(int(counts.max()) if m else 1)

    src_t = np.empty((D, m_loc), dtype=np.int32)
    dst_t = np.full((D, m_loc), pad_node, dtype=np.int32)
    ew_t = np.zeros((D, m_loc), dtype=np.int32)
    for d in range(D):
        src_t[d, :] = d * n_loc  # pad fill: first owned node, weight 0
        sel = owner == d
        c = int(counts[d])
        src_t[d, :c] = src[sel]
        dst_t[d, :c] = dst[sel]
        ew_t[d, :c] = ew[sel]

    node_w = np.zeros(n_pad, dtype=np.int32)
    node_w[:n] = graph.node_weight_array().astype(np.int32)

    shard = NamedSharding(mesh, P(NODE_AXIS))
    repl = NamedSharding(mesh, P())
    return DistGraph(
        src=jax.device_put(src_t.reshape(-1), shard),
        dst=jax.device_put(dst_t.reshape(-1), shard),
        edge_w=jax.device_put(ew_t.reshape(-1), shard),
        node_w=jax.device_put(node_w, shard),
        n=jax.device_put(jnp.int32(n), repl),
        m=jax.device_put(jnp.int32(m), repl),
    )
