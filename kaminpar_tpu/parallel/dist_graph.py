"""Node-sharded device graph — the TPU twin of DistributedCSRGraph.

The reference distributes nodes in contiguous global ranges per PE with
local ghost copies of remote endpoints (kaminpar-dist/datastructures/
distributed_csr_graph.h:25-92, ghost_node_mapper.h:311).  On a device mesh
the same 1D distribution becomes array sharding: node arrays are sharded
over the mesh axis, each device holds the (padded) edge list of its own
node range, and an explicit ghost table (built here) lets per-round label
synchronization exchange ONLY interface values — mesh.halo_exchange is
the static-shape XLA form of the reference's
`synchronize_ghost_node_clusters` sparse alltoall
(kaminpar-dist/coarsening/clustering/lp/global_lp_clusterer.cc:585-594).

Layout invariants (device d of D, n_loc = n_pad / D, m_loc = m_tot / D):
  * device d owns global nodes [d*n_loc, (d+1)*n_loc);
  * `src`/`dst`/`edge_w` slots [d*m_loc, (d+1)*m_loc) hold exactly the
    edges whose source is owned by d (both directions of an undirected
    edge exist, each stored at its own endpoint, like the reference's
    per-PE CSR rows);
  * pad edge slots have src = first owned node, dst = global pad node
    n_pad - 1, weight 0 — inert in ratings and cuts;
  * the global pad node n_pad - 1 is never a real node (the builder
    guarantees n_pad > n).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..dtypes import WEIGHT_DTYPE
from ..graphs.host import HostGraph
from ..caching import pad_size
from ..utils.math import round_up
from .mesh import NODE_AXIS


@jax.tree_util.register_dataclass
@dataclass
class DistGraph:
    """Sharded COO graph over a 1D mesh, with a ghost-halo table.

    Fields:
      src, dst, edge_w : i32[m_tot]  edge arrays, sharded over the mesh axis
                         (device d holds slots [d*m_loc, (d+1)*m_loc))
      node_w           : i32[n_pad]  node weights, sharded over the mesh axis
      n, m             : i32 scalars (replicated true counts)

    Ghost-halo model (distributed_csr_graph.h:44-92, ghost_node_mapper.h):
      dst_local : i32[m_tot]    each edge's endpoint in LOCAL index space —
                                [0, n_loc) for owned nodes, n_loc + g for
                                ghost slot g; sharded like dst
      ghost_gid : i32[D*g_loc]  global node id of each ghost slot (device-
                                local table; pad slots: n_pad - 1)
      send_idx  : i32[D*D, s_max] per device d (dim0 block d): row p holds
                                the LOCAL indices of d-owned interface
                                nodes whose values peer p needs (pad: -1)
      recv_map  : i32[D*D, s_max] per device d: row p maps peer p's j-th
                                sent value to a local ghost slot (pad:
                                g_loc, dropped by the scatter)
    Per-round label exchange then costs O(interface) collective volume
    (see mesh.halo_exchange) instead of an O(n) all_gather.
    """

    src: jax.Array
    dst: jax.Array
    edge_w: jax.Array
    node_w: jax.Array
    n: jax.Array
    m: jax.Array
    dst_local: jax.Array
    ghost_gid: jax.Array
    send_idx: jax.Array
    recv_map: jax.Array

    @property
    def n_pad(self) -> int:
        return self.node_w.shape[0]

    @property
    def m_tot(self) -> int:
        return self.src.shape[0]

    @property
    def num_devices(self) -> int:
        """Mesh size D; send_idx is a [D*D, s_max] per-peer block table."""
        import math

        D = math.isqrt(self.send_idx.shape[0])
        assert D * D == self.send_idx.shape[0], (
            "send_idx must have D*D peer rows"
        )
        return D

    @property
    def g_loc(self) -> int:
        """Ghost slots per device."""
        return self.ghost_gid.shape[0] // max(self.num_devices, 1)

    @property
    def s_max(self) -> int:
        return self.send_idx.shape[1]


def shard_sizes(
    xadj: np.ndarray, D: int, n_pad: Optional[int] = None,
) -> Tuple[int, int, List[int]]:
    """The sizing half of the 1D contiguous-range sharding plan:
    ``(n_loc, m_loc, per-shard true edge counts)`` for a CSR with row
    offsets ``xadj`` over ``D`` devices.  ``m_loc`` is the ACTUAL max
    padded shard — the padded bucket of the heaviest device's edge
    slice, not ``ceil(m / D)``: skewed edge distributions (RMAT hubs
    landing in one node range) make the uniform estimate undercount the
    rank that matters.  Shared by :func:`_assemble_dist_graph`, the
    dist driver's ``memory.preflight`` pricing, and the shard
    fingerprints, so the three can never disagree about the layout."""
    xadj = np.asarray(xadj, dtype=np.int64)
    n = len(xadj) - 1
    if n_pad is None:
        n_pad = round_up(pad_size(n + 1), D)
    else:
        n_pad = round_up(n_pad, D)
    n_loc = n_pad // D
    counts: List[int] = []
    for d in range(D):
        v0, v1 = min(d * n_loc, n), min((d + 1) * n_loc, n)
        counts.append(int(xadj[v1] - xadj[v0]))
    m_loc = pad_size(max(max(counts, default=1), 1))
    return n_loc, m_loc, counts


def shard_fingerprints(graph, D: int) -> List[str]:
    """Per-rank shard fingerprints of the 1D sharding plan: one short
    hash per device over (fleet size, shard index, owned node range,
    shard edge count, pad sizes, boundary row offsets).  Recorded in
    every dist checkpoint barrier's manifest meta; a resume under a
    DIFFERENT device count (or a repartitioned input) produces a
    different vector — the dist driver detects that and degrades to a
    logged clean restart instead of restoring shard state that no
    longer lines up (docs/robustness.md, dist resilience contract).
    Works on plain and compressed host graphs (both carry ``xadj``);
    O(D) hashes over O(1) samples each, never a full-graph pass."""
    xadj = np.asarray(graph.xadj, dtype=np.int64)
    n = len(xadj) - 1
    n_loc, m_loc, counts = shard_sizes(xadj, D)
    fps: List[str] = []
    for d in range(D):
        v0, v1 = min(d * n_loc, n), min((d + 1) * n_loc, n)
        h = hashlib.sha256()
        h.update(
            f"D={D};d={d};v0={v0};v1={v1};edges={counts[d]};"
            f"n_loc={n_loc};m_loc={m_loc};".encode()
        )
        h.update(xadj[v0: min(v1 + 1, v0 + 257)].tobytes())
        fps.append(h.hexdigest()[:16])
    return fps


def dist_graph_bytes(dg: DistGraph) -> int:
    """Total device bytes of a DistGraph's arrays (spill accounting)."""
    total = 0
    for name in ("src", "dst", "edge_w", "node_w", "dst_local",
                 "ghost_gid", "send_idx", "recv_map"):
        arr = getattr(dg, name)
        total += int(np.dtype(arr.dtype).itemsize) * int(
            np.prod(arr.shape)
        )
    return total


def dist_graph_from_host(
    graph: HostGraph,
    mesh: Mesh,
    n_pad: Optional[int] = None,
) -> DistGraph:
    """Shard a host graph onto `mesh` in contiguous node ranges.

    The analog of dKaMinPar's vtxdist/xadj/adjncy ingestion
    (kaminpar-dist/dkaminpar.cc:400-448), minus the ghost mapping (see
    module docstring).
    """

    def rows(v0: int, v1: int):
        lo, hi = int(graph.xadj[v0]), int(graph.xadj[v1])
        ew = graph.edge_weights
        return graph.adjncy[lo:hi], (None if ew is None else ew[lo:hi])

    return _assemble_dist_graph(
        np.asarray(graph.xadj, dtype=np.int64),
        graph.node_weight_array(),
        rows,
        mesh,
        n_pad,
    )


def dist_graph_from_compressed(
    cgraph,
    mesh: Mesh,
    n_pad: Optional[int] = None,
) -> DistGraph:
    """Shard a CompressedHostGraph onto `mesh`, decoding one node-range
    shard at a time — the ingestion analog of the reference's
    DistributedCompressedGraph (kaminpar-dist/datastructures/
    distributed_compressed_graph.h: each PE's local neighborhoods stay
    compressed; here the compressed stream is the host-resident source
    of truth and only one shard's plain rows exist at a time while the
    device arrays are filled).  Bitwise-identical to
    ``dist_graph_from_host(cgraph.decode(), mesh)``."""

    def rows(v0: int, v1: int):
        return cgraph.decode_range(v0, v1)[1:]

    return _assemble_dist_graph(
        np.asarray(cgraph.xadj, dtype=np.int64),
        cgraph.node_weight_array(),
        rows,
        mesh,
        n_pad,
    )


def _assemble_dist_graph(
    xadj: np.ndarray,
    node_weights: np.ndarray,
    rows,
    mesh: Mesh,
    n_pad: Optional[int] = None,
) -> DistGraph:
    """Shared shard-streaming assembly: `rows(v0, v1)` yields the
    (adjncy, edge_w|None) slice of node range [v0, v1).  Because device
    d owns the contiguous node range [d*n_loc, (d+1)*n_loc) and CSR rows
    are source-sorted, each shard's edges are exactly one rows() slice —
    the global 2m int64 (src, dst, w) triple arrays of the old
    implementation are never materialized."""
    D = mesh.devices.size
    n = len(xadj) - 1
    m = int(xadj[-1])
    if n_pad is None:
        n_pad = round_up(pad_size(n + 1), D)
    else:
        n_pad = round_up(n_pad, D)
    if n_pad < n + 1:
        raise ValueError("n_pad too small")
    pad_node = n_pad - 1

    degrees = xadj[1:] - xadj[:-1]
    n_loc, m_loc, _shard_edges = shard_sizes(xadj, D, n_pad=n_pad)
    # pad-waste attribution for the sharded layout: every device pads
    # its node range to n_loc and its edge slice to the max shard's
    # bucket, so padded slots are D * per-shard slots against the m
    # real edges — this row captures shard skew AND bucket rounding
    from ..caching import record_padding

    record_padding(n=n + 1, n_pad=n_pad, m=m, m_pad=m_loc * D)

    src_t = np.empty((D, m_loc), dtype=np.int32)
    dst_t = np.full((D, m_loc), pad_node, dtype=np.int32)
    ew_t = np.zeros((D, m_loc), dtype=np.dtype(WEIGHT_DTYPE))
    ghosts_per_dev = []
    for d in range(D):
        src_t[d, :] = d * n_loc  # pad fill: first owned node, weight 0
        v0, v1 = min(d * n_loc, n), min((d + 1) * n_loc, n)
        adjn, ew = rows(v0, v1)
        c = len(adjn)
        if c:
            src_t[d, :c] = np.repeat(
                np.arange(v0, v1, dtype=np.int32), degrees[v0:v1]
            )
            dst_t[d, :c] = adjn
            ew_t[d, :c] = 1 if ew is None else ew
        # ghost universe of d: remote endpoints of its edges (the pad
        # node included — its label never matters, weight-0 edges only)
        dst_d = dst_t[d]
        remote = dst_d[(dst_d < d * n_loc) | (dst_d >= (d + 1) * n_loc)]
        ghosts_per_dev.append(np.unique(remote))

    g_loc = max(1, pad_size(max((len(g) for g in ghosts_per_dev), default=1), 1))
    # interface lists: send_cnt[p][d] = p-owned nodes that are ghosts on d
    s_needed = 1
    for d in range(D):
        gh = ghosts_per_dev[d]
        own = np.clip(gh // n_loc, 0, D - 1)
        if len(gh):
            s_needed = max(s_needed, int(np.bincount(own, minlength=D).max()))
    s_max = pad_size(s_needed, 1)

    dstloc_t = np.full((D, m_loc), 0, dtype=np.int32)
    ghost_gid_t = np.full((D, g_loc), pad_node, dtype=np.int32)
    send_idx_t = np.full((D, D, s_max), -1, dtype=np.int32)
    recv_map_t = np.full((D, D, s_max), g_loc, dtype=np.int32)
    for d in range(D):
        gh = ghosts_per_dev[d]
        ghost_gid_t[d, : len(gh)] = gh
        dst_d = dst_t[d]
        is_owned = (dst_d >= d * n_loc) & (dst_d < (d + 1) * n_loc)
        loc = np.where(
            is_owned,
            dst_d - d * n_loc,
            n_loc + np.searchsorted(gh, dst_d) if len(gh) else 0,
        )
        dstloc_t[d] = loc.astype(np.int32)
        own = np.clip(gh // n_loc, 0, D - 1) if len(gh) else np.zeros(0, int)
        for p in range(D):
            mine = np.where(own == p)[0]  # ghost slots on d owned by p
            send_idx_t[p, d, : len(mine)] = (gh[mine] - p * n_loc).astype(
                np.int32
            )
            recv_map_t[d, p, : len(mine)] = mine.astype(np.int32)

    node_w = np.zeros(n_pad, dtype=np.dtype(WEIGHT_DTYPE))
    node_w[:n] = np.asarray(node_weights).astype(np.dtype(WEIGHT_DTYPE))

    shard = NamedSharding(mesh, P(NODE_AXIS))
    repl = NamedSharding(mesh, P())
    return DistGraph(
        src=jax.device_put(src_t.reshape(-1), shard),
        dst=jax.device_put(dst_t.reshape(-1), shard),
        edge_w=jax.device_put(ew_t.reshape(-1), shard),
        node_w=jax.device_put(node_w, shard),
        n=jax.device_put(jnp.int32(n), repl),
        m=jax.device_put(jnp.int32(m), repl),
        dst_local=jax.device_put(dstloc_t.reshape(-1), shard),
        ghost_gid=jax.device_put(ghost_gid_t.reshape(-1), shard),
        send_idx=jax.device_put(send_idx_t.reshape(D * D, s_max), shard),
        recv_map=jax.device_put(recv_map_t.reshape(D * D, s_max), shard),
    )
