"""Mesh-subgroup replication for distributed deep multilevel.

Analog of the reference's PE-group splitting
(kaminpar-dist/partitioning/deep_multilevel.cc:79-153 +
kaminpar-dist/graphutils/replicator.cc:26-34 replicate_graph /
distribute_best_partition): once the coarse graph is too small to keep
every PE busy, the reference splits the PEs into subgroups that coarsen
independent replicas of the graph and later keeps the best partition.

The TPU mesh realization avoids a second mesh axis entirely: G replicas
of the n-node graph are laid out as ONE block-diagonal union graph
(replica g's node v becomes union node g*n + v).  Sharding the union
over the existing 1D node axis hands each D/G-device subgroup one
replica, and every dist kernel (LP clustering, sharded contraction,
refinement) runs on the union unchanged — components are disjoint, so
no collective ever mixes replicas, and the halo exchange carries no
cross-replica traffic.  Replicas diverge because every hashed decision
(tie-breaking, participation sampling) keys on the node id, which is
offset per replica — the id offset IS the per-replica seed.

Refinement on the union keeps replicas independent by giving replica g
the block-id range [g*k, (g+1)*k) with tiled weight caps, so balancers
and refiners enforce each replica's constraints separately.
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

from ..graphs.host import HostGraph


def choose_replication_factor(n: int, num_devices: int, min_nodes_per_device: int) -> int:
    """Smallest power-of-two G in [2, D] that restores >= min_nodes_per
    _device nodes per device (G*n/D >= threshold); 1 when the graph is
    still big enough (or D == 1)."""
    D = int(num_devices)
    if D < 2 or n <= 0 or min_nodes_per_device <= 0:
        return 1
    if n >= D * min_nodes_per_device:
        return 1
    G = 2
    while G < D and G * n < D * min_nodes_per_device:
        G *= 2
    return min(G, D)


def union_graph(graph: HostGraph, G: int) -> HostGraph:
    """Block-diagonal union of G copies of `graph` (replica g's node v
    -> union node g*n + v).  The replicate_graph analog — instead of
    sending the graph to every PE subgroup, the union's natural node
    sharding places one copy per subgroup."""
    n, m = graph.n, graph.m
    xadj = graph.xadj
    u_xadj = np.concatenate(
        [[0]] + [xadj[1:] + g * m for g in range(G)]
    ).astype(np.int64)
    u_adjncy = np.concatenate(
        [graph.adjncy.astype(np.int64) + g * n for g in range(G)]
    ).astype(np.int64 if G * n > np.iinfo(np.int32).max else np.int32)
    nw = graph.node_weights
    ew = graph.edge_weights
    return HostGraph(
        xadj=u_xadj,
        adjncy=u_adjncy,
        node_weights=None if nw is None else np.tile(np.asarray(nw), G),
        edge_weights=None if ew is None else np.tile(np.asarray(ew), G),
    )


def replica_bounds_after_contraction(
    cmap: np.ndarray, bounds: List[int]
) -> List[int]:
    """Coarse-side replica boundaries.  Coarse ids are dense ranks of
    leader node ids (ascending), and replica g's leaders all lie in
    [bounds[g], bounds[g+1]), so its coarse ids are the contiguous range
    [new_bounds[g], new_bounds[g+1])."""
    new_bounds = [0]
    for g in range(len(bounds) - 1):
        lo, hi = bounds[g], bounds[g + 1]
        new_bounds.append(
            int(cmap[lo:hi].max()) + 1 if hi > lo else new_bounds[-1]
        )
    return new_bounds


def slice_replica(graph: HostGraph, lo: int, hi: int) -> HostGraph:
    """Extract replica component [lo, hi) of a union graph (edges of a
    disjoint component never leave it)."""
    xadj = graph.xadj
    e0, e1 = int(xadj[lo]), int(xadj[hi])
    nw = graph.node_weights
    ew = graph.edge_weights
    return HostGraph(
        xadj=(xadj[lo : hi + 1] - xadj[lo]).astype(np.int64),
        adjncy=(graph.adjncy[e0:e1] - lo).astype(np.int32),
        node_weights=None if nw is None else np.asarray(nw)[lo:hi],
        edge_weights=None if ew is None else np.asarray(ew)[e0:e1],
    )


def best_replica_partition(
    split_graph: HostGraph,
    union_partition: np.ndarray,
    G: int,
    k: int,
) -> Tuple[np.ndarray, int, int]:
    """distribute_best_partition analog: evaluate each replica's
    partition of the (identical) split-level graph and return
    (partition in [0, k), winning replica, its cut).  `union_partition`
    holds replica g's blocks in the id range [g*k, (g+1)*k)."""
    n = split_graph.n
    src = split_graph.edge_sources()
    ew = split_graph.edge_weight_array()
    adj = split_graph.adjncy
    best = None
    for g in range(G):
        part_g = union_partition[g * n : (g + 1) * n] - g * k
        cut = int(ew[part_g[src] != part_g[adj]].sum() // 2)
        if best is None or cut < best[2]:
            best = (part_g, g, cut)
    return best
