"""Distributed deep-multilevel partitioner facade (dKaMinPar analog).

Mirrors kaminpar-dist's orchestration (kaminpar-dist/dkaminpar.cc:496
compute_partition + partitioning/deep_multilevel.cc):

  coarsening   distributed LP clustering over the device mesh
               (parallel/dist_lp.dist_lp_cluster — the GlobalLPClusteringImpl
               analog), followed by contraction.  The reference migrates
               coarse nodes/edges between PEs with sparse alltoalls
               (global_cluster_contraction.cc); here graphs that fit one
               device are contracted by the DEVICE kernel (the sort-based
               dedup in ops/contraction — labels are consistent across
               devices, so a single device-resident contraction replaces
               per-PE rating maps), and only the coarse CSR is pulled back
               to re-shard onto the mesh for the next level.  Graphs above
               the single-device budget run the SHARDED contraction
               (parallel/dist_contraction.py: per-shard dedup + one
               all_to_all coarse-edge migration) so the fine edge list
               never materializes on one device; either way coarse levels
               are geometrically smaller and the fine-level LP rounds
               (the dominant cost) stay fully on-device.

  initial      the coarsest graph is partitioned by the shared-memory
  partitioning KaMinPar pipeline — exactly the reference's scheme of
               replicating the coarsest graph onto every PE and running shm
               KaMinPar (deep_multilevel.cc:125-176, kaminpar_initial_
               partitioner.cc); with a replicated-per-device mesh there is
               one host, so replication is the identity.

  uncoarsening project up through the stored cluster maps and run
               distributed LP refinement per level (the batched LP refiner
               analog, kaminpar-dist/refinement/lp/lp_refiner.cc).
"""

from __future__ import annotations

from typing import List, Optional, Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from ..dtypes import WEIGHT_DTYPE, WMAX
from ..context import Context
from ..graphs.csr import device_graph_from_host, host_graph_from_device
from ..graphs.host import HostGraph
from ..ops.contraction import contract_clustering
from .dist_contraction import dist_contract_clustering
from ..ops.segments import MAX_FUSED_EDGE_SLOTS
from ..utils import timer
from ..utils.logger import log
from .dist_context import (
    DistContext,
    create_dist_clusterer,
    create_dist_context_by_preset_name,
    create_dist_refiner,
)
from .dist_graph import (
    DistGraph,
    dist_graph_from_compressed,
    dist_graph_from_host,
)
from .dist_metrics import dist_edge_cut
from .mesh import make_mesh


class dKaMinPar:
    """Distributed partitioner with the dKaMinPar builder surface
    (include/kaminpar-dist/dkaminpar.h:516+)."""

    def __init__(
        self,
        ctx: Union[DistContext, Context, str, None] = None,
        mesh: Optional[Mesh] = None,
        n_devices: Optional[int] = None,
    ):
        if ctx is None:
            ctx = create_dist_context_by_preset_name("default")
        elif isinstance(ctx, str):
            ctx = create_dist_context_by_preset_name(ctx)
        elif isinstance(ctx, Context):  # shm context: wrap (legacy surface)
            ctx = DistContext(shm=ctx)
        self.ctx = ctx
        self.mesh = mesh if mesh is not None else make_mesh(n_devices)
        self._graph: Optional[HostGraph] = None
        # (source graph, decoded HostGraph) — keyed on the source object
        self._plain_cache: Optional[Tuple[object, HostGraph]] = None
        self._fine_dg: Optional[DistGraph] = None
        # set by _replicated_phase when mesh-subgroup replication fires
        self._replication_info: Optional[dict] = None
        # the live coarsening hierarchy (_DistLevel list) — held on the
        # instance so the memory governor's spiller hook can drop cold
        # per-level DistGraphs at the barriers (rung >= 2)
        self._levels: Optional[List["_DistLevel"]] = None
        # per-rank shard fingerprints of the input's 1D sharding plan
        # (dist_graph.shard_fingerprints), stamped into every dist
        # checkpoint barrier's manifest meta
        self._shard_fps: List[str] = []

    def set_graph(self, graph) -> "dKaMinPar":
        """Accepts a HostGraph or a CompressedHostGraph.  A compressed
        graph is KEPT compressed (the DistributedCompressedGraph analog,
        kaminpar-dist/datastructures/distributed_compressed_graph.h):
        the finest-level device ingestion streams one decoded node-range
        shard at a time (dist_graph_from_compressed), and the plain fine
        CSR materializes lazily only if a host-side consumer demands it
        — in the terapart regime (kway mode, graph above the
        single-device contraction budget, singleton post-passes not
        firing) it never does."""
        self._graph = graph
        self._plain_cache = None
        self._fine_dg = None
        return self

    def _is_compressed(self, g) -> bool:
        from ..graphs.compressed import CompressedHostGraph

        return isinstance(g, CompressedHostGraph)

    def _plain(self, g) -> HostGraph:
        """Materialize a possibly-compressed fine graph (cached, keyed
        on the source object so a different graph can never be handed
        someone else's decode)."""
        if not self._is_compressed(g):
            return g
        if self._plain_cache is None or self._plain_cache[0] is not g:
            self._plain_cache = (g, g.decode())
        return self._plain_cache[1]

    def set_output_level(self, level) -> "dKaMinPar":
        """Instance-scoped output level (dkaminpar.h set_output_level
        analog): applied to the process-global logger only while
        compute_partition runs."""
        from ..utils.logger import OutputLevel

        self._output_level = OutputLevel(level)
        return self

    def copy_graph(self, vtxdist, xadj, adjncy, vwgt=None, adjwgt=None):
        """ParMETIS-style ingestion (dkaminpar.cc:400-448).  vtxdist is
        accepted for API parity; the host assembles the global graph."""
        self._graph = HostGraph(
            xadj=np.asarray(xadj),
            adjncy=np.asarray(adjncy, dtype=np.int32),
            node_weights=None if vwgt is None else np.asarray(vwgt),
            edge_weights=None if adjwgt is None else np.asarray(adjwgt),
        )
        self._plain_cache = None
        self._fine_dg = None
        return self

    def compute_partition(
        self,
        k: Optional[int] = None,
        epsilon: Optional[float] = None,
        seed: Optional[int] = None,
    ) -> np.ndarray:
        if self._graph is None:
            raise RuntimeError("no graph set")
        graph = self._graph
        ctx = self.ctx
        if seed is not None:
            ctx.seed = int(seed)
        ctx.partition.setup(graph, k=k, epsilon=epsilon)
        k = ctx.partition.k

        from .. import telemetry
        from ..utils.logger import output_level, set_output_level

        import time as _time

        t_run0 = _time.perf_counter()
        owns_stream = timer.GLOBAL_TIMER.idle()
        if owns_stream:
            from .mesh import reset_comm_log

            # per-run observability: without these resets, a second
            # compute in the same process reports the first run's traced
            # comm rows and doubled timer scopes attributed to one run's
            # seed/k/result — the report must misattribute nothing, even
            # if cache-hit runs then show an empty comm table (the
            # documented COMM_CAVEAT tradeoff)
            reset_comm_log()
            timer.GLOBAL_TIMER.reset()
            telemetry.reset()
            telemetry.annotate(
                seed=int(ctx.seed),
                k=int(k),
                epsilon=float(ctx.partition.epsilon),
                mode=self.ctx.mode.value,
                devices=int(self.mesh.devices.size),
                graph={"n": int(graph.n), "m": int(graph.m)},
            )

        # preemption safety (kaminpar.py twin): the stream-owning run may
        # arm a deadline and a checkpoint manager; stage ids below are
        # derived from loop indices every rank computes identically
        # (barrier-consistent), and the manager lets only rank 0 write.
        from ..resilience import checkpoint as ckpt_mod
        from ..resilience import deadline as deadline_mod

        mgr = None
        res_ctx = self.ctx.shm.resilience
        from ..resilience import agreement as agreement_mod
        from ..resilience import memory as memory_mod

        if owns_stream:
            # same arm-and-maybe-resume policy as the shm facade
            # (checkpoint.create_manager / deadline.begin_run keep the
            # two from drifting apart)
            ckpt_mod.deactivate()
            deadline_mod.begin_run(
                res_ctx.time_budget or None, res_ctx.budget_grace,
                getattr(res_ctx, "hard_deadline_factor", None),
            )
            mgr = ckpt_mod.create_manager(res_ctx, graph, self.ctx)
            if mgr is not None:
                ckpt_mod.activate(mgr)
            # the per-rank shard fingerprints of the 1D sharding plan:
            # stamped into every dist barrier's manifest meta, and the
            # key that detects a resume under a DIFFERENT device count
            # below (docs/robustness.md, dist resilience contract)
            from .dist_graph import shard_fingerprints, shard_sizes

            devices = max(1, int(self.mesh.devices.size))
            self._shard_fps = shard_fingerprints(graph, devices)
            if mgr is not None:
                pending = mgr.pending_resume()
                if pending is not None and pending.get("scheme") == "dist":
                    recorded = pending.get("meta", {}).get("shards")
                    if (
                        recorded is not None
                        and list(recorded) != list(self._shard_fps)
                    ):
                        # shard state (cmaps, per-level layouts) from a
                        # different sharding plan cannot be restored
                        # without risking a wrong answer: logged clean
                        # restart, never a silent mis-resume
                        mgr.drop_resume(
                            "dist shard fingerprints changed (checkpoint "
                            f"has {len(list(recorded))} shard(s), current "
                            f"mesh has {len(self._shard_fps)}) — device "
                            "count or input sharding differs"
                        )
            # memory governor (resilience/memory.py): the budget
            # (KAMINPAR_TPU_HBM_BYTES / --memory-budget) is PER-DEVICE
            # and dist_graph shards the node/edge arrays across the
            # mesh, so price the ACTUAL max padded shard from the
            # sharding plan — ceil(n/D)/ceil(m/D) undercounts the
            # heaviest rank of a skewed edge distribution, and pricing
            # the whole graph refuses multi-chip runs that fit after
            # sharding
            n_loc, m_loc, _ = shard_sizes(
                np.asarray(graph.xadj, dtype=np.int64), devices
            )
            memory_mod.begin_run(
                graph, self.ctx, price_shape=(n_loc, m_loc)
            )
            memory_mod.register_spiller(self)
            memory_mod.preflight(n_loc, m_loc, k, where="dist")
            # divergence sentinels (resilience/agreement.py): every dist
            # barrier audits [stage, rung, run fingerprint] across the
            # fleet — silent rank divergence becomes a structured
            # RankDivergence with a per-rank dump
            agreement_mod.arm(
                "dist",
                ckpt_mod.graph_fingerprint(graph),
                ckpt_mod.ctx_fingerprint(self.ctx),
                self._shard_fps,
            )

        prior_level = output_level()
        try:
            set_output_level(
                getattr(self, "_output_level", prior_level)
            )
            with timer.scoped_timer("dist-partitioning"):
                # a run preempted after its final barrier resumes
                # instantly from the `result` snapshot; mid-pipeline
                # dist stages re-enter at the recorded barrier via the
                # dist-scheme resume inside _partition_recorded (full-
                # hierarchy dist resume, docs/robustness.md).  The core
                # runs under the cross-rank agreed OOM recovery ladder:
                # a DeviceOOM on any rank unwinds every rank to the
                # same rung (tight pads -> host-spilled shard
                # hierarchy -> host-only) instead of deadlocking the
                # survivors inside shard_map collectives.
                resumed = (
                    mgr.take_result_resume() if mgr is not None else None
                )
                if resumed is not None and resumed.shape == (graph.n,):
                    partition = resumed
                else:
                    partition = memory_mod.run_dist_ladder(
                        lambda: self._partition(graph, k),
                        graph, self.ctx, self,
                    )

            # strict-balance output gate (resilience/gate.py): the dist
            # result now passes the same end-of-pipeline validation +
            # greedy repair as the shm facade's (compressed inputs are
            # chunk-stream recomputed, never decoded whole)
            from ..resilience import gate as output_gate

            if output_gate.gate_enabled() and res_ctx.output_gate:
                # already host-side: the pipeline returns numpy
                partition = np.asarray(partition, dtype=np.int32)
                with timer.scoped_timer("output-gate"):
                    partition, gate_verdict = output_gate.check_and_repair(
                        graph, partition, ctx.partition,
                        repair=res_ctx.repair,
                    )
                if owns_stream:
                    telemetry.annotate(output_gate=gate_verdict)

            if self._is_compressed(graph) and self._fine_dg is not None:
                # still-compressed input: cut from the finest-level
                # sharded graph (no CSR materialization), imbalance from
                # node weights alone
                full = np.zeros(self._fine_dg.n_pad, dtype=np.int32)
                full[: graph.n] = partition
                # `collective` degradation site: the sharded cut
                # reduction can time out / OOM on a sick link — degrade
                # to the host-side cut (decoding if needed) rather than
                # losing the whole run at the metrics step
                from ..resilience import with_fallback

                fine_dg = self._fine_dg
                cut = with_fallback(
                    lambda: dist_edge_cut_of(fine_dg, jnp.asarray(full)),
                    lambda exc: self._host_cut(
                        self._plain(graph), partition
                    ),
                    site="collective",
                    where="dist-result-cut",
                )
                import math as pymath

                nw = graph.node_weight_array()
                bw = np.zeros(k, dtype=np.int64)
                np.add.at(bw, partition, nw)
                # same definition as host_partition_metrics (ceil'd
                # perfect weight) so the two RESULT paths cannot drift
                perfect = max(1, pymath.ceil(int(nw.sum()) / k))
                imbalance = float(bw.max() / perfect - 1.0)
                feasible = bool((bw <= ctx.partition.max_block_weights).all())
                # the finest sharded arrays are only retained for this
                # metrics call — release the device memory
                self._fine_dg = None
            else:
                from ..graphs.host import host_partition_metrics

                res = host_partition_metrics(self._plain(graph), partition, k)
                cut, imbalance = res["cut"], res["imbalance"]
                feasible = bool(
                    (res["block_weights"] <= ctx.partition.max_block_weights)
                    .all()
                )
            if owns_stream:  # nested runs don't own the stream
                telemetry.annotate(
                    result={
                        "cut": int(cut),
                        "imbalance": float(imbalance),
                        "feasible": feasible,
                    }
                )
            if owns_stream:
                # per-rank memory rollup (perf.memory.ranks): collective
                # — every process gathers its live-HBM figure, so the
                # report shows residency skew between ranks the same way
                # the aggregated timers show wall skew.  perf.enabled()
                # is env+telemetry state, identical on all ranks.
                from ..telemetry import perf as perf_mod

                if perf_mod.enabled():
                    telemetry.annotate(
                        perf_ranks=perf_mod.rank_memory_rollup()
                    )
                # per-rank quality rollup (quality.ranks): collective —
                # every rank contributes its attribution headline, so
                # the dist report shows where cut responsibility sits
                # per rank next to the residency/wall skew
                from ..telemetry import quality as quality_mod

                if quality_mod.enabled():
                    telemetry.annotate(
                        quality_ranks=quality_mod.rank_rollup()
                    )
                if mgr is not None and mgr.enabled:
                    final_part = partition
                    ckpt_mod.barrier(
                        "result", scheme="dist-facade",
                        payload=lambda: {"state": {
                            "partition": np.asarray(
                                final_part, dtype=np.int32
                            ),
                        }},
                    )
                if deadline_mod.triggered():
                    telemetry.annotate(anytime=deadline_mod.state())
                if mgr is not None:
                    telemetry.annotate(checkpoint=mgr.summary())
                mem_summary = memory_mod.summary()
                if mem_summary.get("enabled"):
                    telemetry.annotate(memory_budget=mem_summary)
                # dist resilience audit trail (schema v8): sentinel
                # counters + the shard-fingerprint vector + the agreed
                # ladder rung + what (if anything) was resumed
                dist_sect = agreement_mod.section()
                if dist_sect.get("enabled"):
                    dist_sect["shard_fingerprints"] = list(self._shard_fps)
                    dist_sect["ladder"] = {
                        "agreed": True,
                        "rung": int(mem_summary.get("rung", 0) or 0),
                    }
                    if mgr is not None and mgr.resumed_from() is not None:
                        dist_sect["resumed_from"] = mgr.resumed_from()
                    telemetry.annotate(dist_resilience=dist_sect)
                ckpt_mod.deactivate()
            log(
                f"RESULT cut={cut} imbalance={imbalance:.6f} "
                f"k={k} devices={self.mesh.devices.size}"
            )
            # request tracing (telemetry/tracing.py): when a serving
            # request drove this compute, attach a rank-annotated span
            # to its trace — the agreement rollup's rank model
            # (agreement.py rank() = process_index, 0 without a live
            # multi-process backend) so multi-rank timelines stay
            # attributable per process
            from ..telemetry import tracing
            from ..utils.platform import process_index

            tid = tracing.current()
            if tid:
                tracing.span(
                    tid, "dist-compute", start=t_run0,
                    duration_s=_time.perf_counter() - t_run0,
                    origin="dist", rank=int(process_index()),
                    devices=int(self.mesh.devices.size), k=int(k),
                )
        finally:
            set_output_level(prior_level)
            if owns_stream:
                agreement_mod.disarm()
            self._levels = None
        return partition

    # -- multilevel driver ------------------------------------------------

    def _partition(self, graph: HostGraph, k: int) -> np.ndarray:
        from ..telemetry import quality as quality_mod

        # quality observatory (telemetry/quality.py): the dist driver
        # records its own hierarchy; nested shm IP runs open (and close)
        # their own scopes below this one without corrupting it
        qh = quality_mod.begin("dist")
        try:
            return self._partition_recorded(graph, k, qh)
        finally:
            quality_mod.end(qh)

    def _quality_cut(self, dg, n: int, partition) -> Optional[int]:
        """Sharded cut of a host partition, only when the quality layer
        is live (collective — quality.enabled() is env+telemetry state,
        identical on all ranks, so every rank calls or none does)."""
        from ..telemetry import quality as quality_mod

        if not quality_mod.enabled():
            return None
        full = np.zeros(dg.n_pad, dtype=np.int32)
        full[: int(n)] = partition
        return dist_edge_cut_of(dg, jnp.asarray(full))

    def _partition_recorded(
        self, graph: HostGraph, k: int, qh
    ) -> np.ndarray:
        from ..resilience import checkpoint as ckpt
        from ..telemetry import quality as quality_mod

        ctx = self.ctx
        c_ctx = ctx.coarsening
        total_node_weight = ctx.partition.total_node_weight
        clusterer = create_dist_clusterer(ctx)
        refiner = create_dist_refiner(ctx)

        from ..context import PartitioningMode

        deep = self.ctx.mode == PartitioningMode.DEEP

        # --- full-hierarchy dist resume: rebuild the recorded level
        # stack (coarse host CSRs + cmaps by reference; the sharded
        # DistGraphs are deterministic caches rebuilt on demand) and
        # re-enter at the recorded dist barrier — no completed level
        # re-runs (docs/robustness.md, dist resilience contract)
        resume = ckpt.take_resume("dist")
        r_stage: Optional[str] = None
        r_level: Optional[int] = None
        levels: List[_DistLevel] = []
        current = graph
        partition: Optional[np.ndarray] = None
        spans = None
        current_k: Optional[int] = None
        num_levels_meta: Optional[int] = None
        if resume is not None:
            r_stage = resume["stage"]
            r_level = resume.get("level")
            meta = resume.get("meta", {})
            levels, current = self._restore_dist_levels(
                graph, resume["arrays"]
            )
            state = resume["arrays"].get("state")
            if (
                r_stage in ("dist-initial", "dist-uncoarsen")
                and state is not None
                and "partition" in state
                and "spans" in state  # pre-v12 dist states lack spans:
                # fall through to the level-only (or clean) restart
            ):
                partition = np.asarray(state["partition"], dtype=np.int32)
                spans = [
                    (int(f), int(c))
                    for f, c in np.asarray(state["spans"]).tolist()
                ]
                current_k = int(meta.get("current_k", len(spans)))
                num_levels_meta = meta.get("num_levels")
            else:
                # only hierarchy levels were recorded: re-enter the
                # coarsening loop where it left off
                r_stage = "dist-coarsen"
            # replay the cluster maps into the quality recorder so the
            # final attribution composes over the FULL hierarchy
            for i, lvl in enumerate(levels):
                quality_mod.note_cmap(
                    level=i + 1, cmap=lvl.cmap, fine_n=lvl.fine_host.n
                )
            from .. import telemetry

            telemetry.event(
                "resume", scheme="dist", stage=r_stage, level=r_level,
                levels_restored=len(levels),
            )
            log(
                f"resumed dist pipeline at {r_stage}"
                f"{'' if r_level is None else ':' + str(r_level)} "
                f"({len(levels)} hierarchy level(s) restored)"
            )
        self._levels = levels

        # coarsening (deep_multilevel.cc:75-118 analog); skipped
        # entirely when the resume restored a partition already
        skip_to_uncoarsen = partition is not None
        threshold = max(2 * c_ctx.contraction_limit, k)
        if not skip_to_uncoarsen:
            with timer.scoped_timer("dist-coarsening"):
                while current.n > threshold:
                    if deep and self._replication_factor(current.n) > 1:
                        # the graph is too small to keep every device
                        # busy: hand over to the mesh-subgroup
                        # replication phase (deep_multilevel.cc:79-153
                        # analog) below
                        break
                    if self._is_compressed(current):
                        # still-compressed fine level: stream shards from
                        # the compressed rows (bitwise-identical result)
                        dg = dist_graph_from_compressed(current, self.mesh)
                        self._fine_dg = dg
                    else:
                        dg = dist_graph_from_host(current, self.mesh)
                    mcw = max(
                        1,
                        c_ctx.max_cluster_weight(
                            current.n, total_node_weight, ctx.partition
                        ),
                    )
                    lvl_seed = (
                        ctx.seed * 7919 + len(levels) * 31337
                    ) & 0x7FFFFFFF
                    from .mesh import comm_phase

                    with comm_phase(f"coarsening-L{len(levels)}"):
                        labels = clusterer(
                            dg, min(mcw, WMAX), jnp.int32(lvl_seed)
                        )
                    # singleton post-passes (two-hop + isolated packing)
                    # — the reference runs them wherever LP clusters
                    # (label_propagation.h:872-1191); without them
                    # low-degree graphs under-coarsen on the mesh
                    from .dist_lp import dist_singleton_postpasses

                    fine = current  # may be compressed; _plain caches
                    # the device labels go in raw: the post-pass owns its
                    # own pull (the staged host boundary), so the span
                    # never carries a caller-side np.asarray
                    labels = dist_singleton_postpasses(
                        current, labels, min(mcw, WMAX),
                        materialize=lambda: self._plain(fine),
                    )
                    contracted = self._contract_level(current, dg, labels)
                    if contracted is None:  # converged
                        break
                    coarse, cmap = contracted
                    fine_n = int(current.n)
                    levels.append(_DistLevel(current, cmap, dg, self.mesh))
                    quality_mod.note_cmap(
                        level=len(levels), cmap=cmap, fine_n=fine_n
                    )
                    if quality_mod.enabled():
                        # coarsening-quality stats, host-side; compressed
                        # fine levels skip the edge-weight sum (no decode)
                        quality_mod.note_contraction_host(
                            level=len(levels), coarse_host=coarse,
                            cmap=cmap, fine_n=fine_n,
                            max_cluster_weight=mcw,
                            total_node_weight=int(total_node_weight),
                            fine_edge_weight=(
                                None if self._is_compressed(current)
                                else int(current.edge_weight_array().sum())
                            ),
                        )
                    current = coarse
                    lvl_no = len(levels)
                    if not ckpt.barrier(
                        "dist-coarsen", level=lvl_no, scheme="dist",
                        # the level snapshot: coarse host CSR + cmap —
                        # deferred (disabled runs build nothing), prior
                        # levels carried forward by reference
                        payload=lambda c=coarse, cm=cmap, fn=fine_n,
                        no=lvl_no: _dist_level_payload(no, c, cm, fn),
                        keep=[f"dist-level-{j}" for j in range(1, lvl_no)],
                        meta=self._dist_meta(num_levels=lvl_no),
                        agree=True,  # next level clusters collectively
                    ):
                        break  # deadline wind-down: stop deepening

        # mesh-subgroup replication (deep_multilevel.cc:79-153 +
        # replicator.cc analog): the graph is too small for the whole
        # mesh, so G replicas coarsen + IP + refine independently on
        # D/G-device subgroups (one block-diagonal union graph — see
        # parallel/replication.py) and the best replica's partition
        # continues into the main uncoarsening below
        replicated = False
        if (
            not skip_to_uncoarsen
            and deep
            and current.n > threshold
            and self._replication_factor(current.n) > 1
        ):
            with timer.scoped_timer("dist-replicated-coarsening"):
                # a compressed input can reach this point un-decoded (the
                # loop breaks before the streaming branch); the union
                # builder needs plain CSR rows
                partition, ip_k = self._replicated_phase(
                    self._plain(current), k, clusterer, threshold
                )
            replicated = True

        # DEEP mode partitions the coarsest at a reduced k' and doubles k
        # on the mesh during uncoarsening; KWAY partitions at full k.
        # With no dist levels there is nothing to double over — the shm
        # IP result IS the final partition, so it must run at full k.
        if skip_to_uncoarsen:
            ip_k = int(current_k)  # the resumed partition's k
        elif replicated:
            pass
        elif deep and levels:
            from ..partitioning.deep import compute_k_for_n

            ip_k = max(2, min(k, compute_k_for_n(current.n, self.ctx.shm)))
        else:
            ip_k = k
        if spans is None:
            spans = self._initial_spans(ip_k, k)

        # initial partitioning: shm pipeline on the coarsest graph.  The
        # reference replicates the coarsest graph onto every PE, runs shm
        # KaMinPar per PE with that PE's seed, and keeps the best cut
        # (replicate_graph_everywhere + distribute_best_partition,
        # kaminpar-dist/partitioning/deep_multilevel.cc:125-176).  When
        # the mesh-subgroup replication phase ran, each replica already
        # carried its own IP and the best partition was selected there;
        # otherwise one host plays all PEs with independent seeded runs.
        best_cut = None
        if not replicated and not skip_to_uncoarsen:
            with timer.scoped_timer("dist-initial-partitioning"):
                num_replicas = max(1, min(self.mesh.devices.size, 4))
                partition = None
                for r in range(num_replicas):
                    cand = self._initial_partition(
                        self._plain(current), ip_k, k, spans,
                        (self.ctx.seed * 31 + r * 7907) & 0x7FFFFFFF,
                    )
                    cut = self._host_cut(self._plain(current), cand)
                    if best_cut is None or cut < best_cut:
                        partition, best_cut = cand, cut
        if not skip_to_uncoarsen:
            part_ip, spans_ip = partition, spans
            ckpt.barrier(
                "dist-initial", level=len(levels), scheme="dist",
                payload=lambda: _dist_state_payload(part_ip, spans_ip),
                keep=[
                    f"dist-level-{j}" for j in range(1, len(levels) + 1)
                ],
                meta=self._dist_meta(
                    num_levels=len(levels), current_k=int(ip_k),
                ),
            )
        # quality: the coarsest level's cut — dist runs no coarsest-level
        # refinement, so projected == refined there (both recorded so
        # the level still gets an attribution row)
        coarsest_cut = (
            (self._replication_info or {}).get("cut") if replicated
            else best_cut
        )
        if coarsest_cut is not None:
            quality_mod.note_projected(
                len(levels), cut=coarsest_cut, k=ip_k
            )
            quality_mod.note_refined(
                len(levels), cut=coarsest_cut, k=ip_k,
                spans=spans, input_k=k,
            )

        # uncoarsening + distributed refinement (deep_multilevel.cc:181+):
        # project up, refine at the current k, and in DEEP mode extend the
        # partition on the mesh while the level's size supports more
        # blocks (the extend_partition lineage, helper.cc:220)
        current_k = ip_k
        # num_levels is the FULL hierarchy depth — after a resume whose
        # keep-list already pruned consumed levels, len(levels) < depth,
        # and the per-level seeds below must match the uninterrupted
        # run's (cut-identical resume)
        num_levels = (
            int(num_levels_meta) if num_levels_meta else len(levels)
        )
        start_level = (
            int(r_level) if r_stage == "dist-uncoarsen" and r_level
            is not None else len(levels)
        )
        with timer.scoped_timer("dist-uncoarsening"):
            for level in range(start_level - 1, -1, -1):
                lvl = levels[level]
                dg = lvl.dg()  # rebuilt on demand when spilled/resumed
                fine_host = lvl.fine_host
                partition = partition[lvl.cmap]  # project up
                level_idx = num_levels - 1 - level
                cut = self._quality_cut(dg, fine_host.n, partition)
                if cut is not None:
                    quality_mod.note_projected(level, cut=cut, k=current_k)
                seed = (self.ctx.seed * 92821 + level_idx) & 0x7FFFFFFF
                partition = self._refine_dist(
                    refiner, dg, fine_host, partition, current_k, spans,
                    seed, level,
                )
                if deep:
                    from ..partitioning.deep import compute_k_for_n

                    target_k = min(
                        k, compute_k_for_n(fine_host.n, self.ctx.shm)
                    )
                    while current_k < target_k:
                        partition, spans, current_k = self._extend_on_mesh(
                            fine_host, partition, spans
                        )
                        partition = self._refine_dist(
                            refiner, dg, fine_host, partition, current_k,
                            spans, seed ^ (0x9E37 + current_k), level,
                        )
                cut = self._quality_cut(dg, fine_host.n, partition)
                if cut is not None:
                    quality_mod.note_refined(
                        level, cut=cut, k=current_k,
                        spans=spans, input_k=k,
                    )
                part_now, spans_now, k_now = partition, spans, current_k
                ckpt.barrier(
                    "dist-uncoarsen", level=level, scheme="dist",
                    payload=lambda: _dist_state_payload(part_now, spans_now),
                    # levels 0..level-1 are still pending; their fine
                    # CSRs/cmaps live in snapshots 1..level
                    keep=[f"dist-level-{j}" for j in range(1, level + 1)],
                    meta=self._dist_meta(
                        num_levels=num_levels, current_k=int(k_now),
                    ),
                )
        # final extensions to k (finest level).  `skip_to_uncoarsen`
        # joins the condition: a resume at dist-uncoarsen:0 with
        # current_k < k has already PRUNED every level snapshot (the
        # keep list at the finest barrier is empty), so `levels` is
        # empty — but the restored partition lives on the input graph
        # and must extend on the mesh exactly like the uninterrupted
        # run would; the shm fallback below would discard it
        if (
            deep
            and (levels or replicated or skip_to_uncoarsen)
            and current_k < k
        ):
            if levels:
                lvl0 = levels[0]
                dg, fine_host = lvl0.dg(), lvl0.fine_host
            else:
                # replication fired at the input level (or a finest-
                # barrier resume restored an all-levels-pruned state):
                # no dist levels exist, but the finest-level graph
                # (= the input) still extends on the mesh
                fine_host = self._plain(current)
                dg = dist_graph_from_host(fine_host, self.mesh)
            while current_k < k:
                partition, spans, current_k = self._extend_on_mesh(
                    fine_host, partition, spans
                )
                partition = self._refine_dist(
                    refiner, dg, fine_host, partition, current_k, spans,
                    (self.ctx.seed * 48947 + current_k) & 0x7FFFFFFF, 0,
                )
        elif current_k < k:
            # no dist levels (tiny graph): the shm IP already ran at ip_k;
            # fall back to a full-k shm partition
            from ..kaminpar import KaMinPar

            shm = KaMinPar(self.ctx.shm.copy())
            partition = shm.set_graph(self._plain(graph)).compute_partition(
                k=k, epsilon=self.ctx.partition.epsilon, seed=self.ctx.seed
            )
            current_k = k
        # quality: coarsening floors from the final partition.  A
        # still-compressed input is not decoded just for the floors —
        # the attribution keeps the recorded cut rows only (documented
        # caveat, docs/observability.md).
        if not self._is_compressed(graph):
            quality_mod.finalize_host(qh, graph, partition)
        elif self._plain_cache is not None and self._plain_cache[0] is graph:
            quality_mod.finalize_host(qh, self._plain_cache[1], partition)
        return partition

    # -- deep-mode helpers -------------------------------------------------

    def _initial_partition(self, host, ip_k, k, spans, seed) -> np.ndarray:
        """Coarsest-graph initial partitioner dispatch (the
        create_initial_partitioner seam, kaminpar-dist/factories.cc:72-88:
        KAMINPAR / MTKAHYPAR / RANDOM)."""
        from .dist_context import DistInitialPartitioningAlgorithm as Alg

        algo = getattr(
            self.ctx, "initial_partitioning", Alg.KAMINPAR
        )
        if algo == Alg.RANDOM:
            # random_initial_partitioner.cc: uniform block per node; any
            # imbalance is left to the balancers/refiners downstream
            rng = np.random.RandomState(seed & 0x7FFFFFFF)
            return rng.randint(0, ip_k, host.n).astype(np.int32)
        if algo == Alg.MTKAHYPAR:
            # mtkahypar_initial_partitioner.cc — gated on the external
            # package exactly like the refinement adapter
            from ..refinement.mtkahypar import (
                mtkahypar_available,
                mtkahypar_refine_host,
            )

            if not mtkahypar_available():
                raise RuntimeError(
                    "initial_partitioning=mtkahypar requires the external "
                    "'mtkahypar' package (the analog of building the "
                    "reference with KAMINPAR_BUILD_WITH_MTKAHYPAR)"
                )
            rng = np.random.RandomState(seed & 0x7FFFFFFF)
            start = rng.randint(0, ip_k, host.n).astype(np.int32)
            return mtkahypar_refine_host(
                host, start, ip_k,
                epsilon=self.ctx.partition.epsilon, seed=seed,
            ).astype(np.int32)
        return self._shm_ip(host, ip_k, k, spans, seed)

    def _shm_ip(self, host, ip_k, k, spans, seed) -> np.ndarray:
        """One seeded shm-KaMinPar run on a coarsest(-replica) graph with
        span-aware caps (when ip_k does not divide k the current blocks
        carry UNEQUAL final-block counts, and the IP must balance to
        those targets or the first refinement inherits systematic
        overloads).  Quiet, without leaking the global logger level."""
        from ..kaminpar import KaMinPar
        from ..utils.logger import OutputLevel, output_level, set_output_level

        outer_level = output_level()
        try:
            shm = KaMinPar(self.ctx.shm.copy())
            shm.set_output_level(OutputLevel.QUIET)
            shm.set_graph(host)
            p_ = self.ctx.partition
            ip_caps = np.array(
                [
                    p_.total_max_block_weights(first, first + count)
                    for first, count in spans
                ],
                dtype=np.int64,
            )
            return shm.compute_partition(
                k=ip_k,
                epsilon=self.ctx.partition.epsilon,
                max_block_weights=(None if ip_k == k else ip_caps),
                seed=seed,
            )
        finally:
            set_output_level(outer_level)

    def _replication_factor(self, n: int) -> int:
        from .replication import choose_replication_factor

        return choose_replication_factor(
            n,
            int(self.mesh.devices.size),
            int(getattr(self.ctx, "replication_min_nodes_per_device", 0)),
        )

    # host-boundary contract: contraction hands the coarse graph and its
    # cmap back to the host to re-shard the next level — the pulls ARE
    # the phase the dist-coarsening span times
    # tpulint: disable=R1
    def _contract_level(self, current: HostGraph, dg, labels):
        """Contract one coarsening level; returns (coarse, cmap) or None
        when the clustering converged (coarse nearly as big as fine)."""
        c_ctx = self.ctx.coarsening
        if current.m <= MAX_FUSED_EDGE_SLOTS:
            # contraction on DEVICE (sort-based dedup kernel; see module
            # docstring): only the coarse CSR is pulled back, to re-shard
            # it for the next level's 1D node distribution (the
            # reference's migrate step, global_cluster_contraction.cc:1100+)
            fine_dev = device_graph_from_host(self._plain(current))
            lab_dev = jnp.asarray(labels)[: fine_dev.n_pad]
            if lab_dev.shape[0] < fine_dev.n_pad:
                lab_dev = jnp.concatenate([
                    lab_dev,
                    jnp.arange(lab_dev.shape[0], fine_dev.n_pad,
                               dtype=jnp.int32),
                ])
            coarse_dev, c_n, _c_m = contract_clustering(fine_dev, lab_dev)
            if c_n >= (1.0 - c_ctx.convergence_threshold) * current.n:
                return None
            cmap = np.asarray(coarse_dev.cmap)[: current.n]
            coarse = host_graph_from_device(coarse_dev.graph)
        else:
            # beyond the single-device budget: SHARDED contraction
            # (per-shard dedup + coarse-edge migrate all_to_all,
            # parallel/dist_contraction.py — the
            # global_cluster_contraction.cc:1100+ analog); the fine edge
            # list never leaves its shards
            coarse, cmap = dist_contract_clustering(
                dg, current.n, current.node_weight_array(),
                np.asarray(labels),
            )
            if coarse.n >= (1.0 - c_ctx.convergence_threshold) * current.n:
                return None
        return coarse, cmap

    # host-boundary contract: the replica phase selects + pulls the best
    # replica's partition to host for the main uncoarsening — the
    # dist-replicated-coarsening span times this hybrid phase
    # tpulint: disable=R1
    def _replicated_phase(
        self, split_host: HostGraph, k: int, clusterer, threshold: int,
    ):
        """Coarsen G replicas of `split_host` as one block-diagonal union
        over the mesh, IP each replica, refine the replica hierarchies in
        lockstep union launches, and return the best replica's partition
        at the split level (deep_multilevel.cc:79-153 +
        replicator.cc:26-34; see parallel/replication.py for why a union
        graph realizes PE-subgroup splitting on a device mesh).

        Returns (partition i32[split_host.n] in [0, ip_k), ip_k)."""
        from ..partitioning.deep import compute_k_for_n
        from .dist_lp import dist_singleton_postpasses
        from .replication import (
            best_replica_partition,
            replica_bounds_after_contraction,
            slice_replica,
            union_graph,
        )

        ctx = self.ctx
        c_ctx = ctx.coarsening
        n_split = split_host.n
        G = self._replication_factor(n_split)
        # the partition re-enters the main uncoarsening at the split
        # level, so it must carry the k that level supports — each
        # replica's internal shm deep pipeline builds up to ip_k exactly
        # like a reference PE subgroup does
        ip_k = max(2, min(k, compute_k_for_n(n_split, ctx.shm)))
        spans = self._initial_spans(ip_k, k)
        union = union_graph(split_host, G)
        bounds = [g * n_split for g in range(G + 1)]
        self._replication_info = {
            "G": G, "split_n": n_split, "ip_k": ip_k,
        }

        # --- coarsen the union until every replica reaches the IP size;
        # replicas diverge through id-keyed hashing (the id offset is the
        # per-replica seed)
        u_levels = []
        current, cur_bounds = union, bounds
        while max(
            cur_bounds[g + 1] - cur_bounds[g] for g in range(G)
        ) > threshold:
            dg = dist_graph_from_host(current, self.mesh)
            n_rep = max(
                cur_bounds[g + 1] - cur_bounds[g] for g in range(G)
            )
            # per-REPLICA size keeps the cluster-weight cap identical to
            # the unreplicated semantics (clusters never span replicas)
            mcw = max(
                1,
                c_ctx.max_cluster_weight(
                    n_rep, ctx.partition.total_node_weight, ctx.partition
                ),
            )
            lvl_seed = (
                ctx.seed * 7919 + (9601 + len(u_levels)) * 31337
            ) & 0x7FFFFFFF
            from .mesh import comm_phase

            with comm_phase(f"replicated-coarsening-L{len(u_levels)}"):
                labels = np.array(
                    clusterer(dg, min(mcw, WMAX), jnp.int32(lvl_seed))
                )
            # singleton post-passes must not merge across replicas (the
            # isolated-node bins are global) — run them per component
            for g in range(G):
                lo, hi = cur_bounds[g], cur_bounds[g + 1]
                sub = slice_replica(current, lo, hi)
                sub_lab = labels[lo:hi] - lo
                labels[lo:hi] = lo + dist_singleton_postpasses(
                    sub, sub_lab, min(mcw, WMAX)
                )
            contracted = self._contract_level(current, dg, labels)
            if contracted is None:
                break
            coarse, cmap = contracted
            u_levels.append((dg, cmap, current))
            cur_bounds = replica_bounds_after_contraction(cmap, cur_bounds)
            current = coarse

        # --- per-replica IP (each subgroup's seeded shm run).  Always the
        # KAMINPAR algorithm here regardless of ctx.initial_partitioning:
        # the union refinement that follows is positive-gain LP only (see
        # below — balancers could cross replicas), so a balance-ignorant
        # RANDOM start could never be repaired before the best-replica
        # cut comparison, which requires comparably feasible candidates.
        union_part = np.zeros(current.n, dtype=np.int32)
        for g in range(G):
            lo, hi = cur_bounds[g], cur_bounds[g + 1]
            sub = slice_replica(current, lo, hi)
            cand = self._shm_ip(
                sub, ip_k, k, spans,
                (ctx.seed * 31 + g * 7907) & 0x7FFFFFFF,
            )
            union_part[lo:hi] = cand.astype(np.int32) + g * ip_k

        # --- uncoarsen the replica hierarchies in lockstep: one union
        # refinement per level with per-replica block-id ranges and tiled
        # caps, so every subgroup refines its own replica simultaneously.
        # POSITIVE-GAIN LP only: a foreign replica's block always has
        # connection 0 (components are disjoint), so strictly-improving
        # moves can never cross replicas — balancers/Jet could (they
        # accept zero-connection moves for balance) and would corrupt
        # the per-replica block-id ranges
        from ..ops.segments import pad_k_bucket
        from .dist_lp import dist_lp_refine

        base_caps = np.asarray(self._span_caps(spans))
        k_u, union_caps, _ = pad_k_bucket(
            G * ip_k, jnp.asarray(np.tile(base_caps, G))
        )
        for level_idx, (dg, cmap, fine_host) in enumerate(
            reversed(u_levels)
        ):
            union_part = union_part[cmap]
            full = np.zeros(dg.n_pad, dtype=np.int32)
            full[: fine_host.n] = union_part
            seed = (ctx.seed * 50411 + level_idx * 73) & 0x7FFFFFFF
            refined = dist_lp_refine(
                dg, jnp.asarray(full), k_u, union_caps, seed,
                num_iterations=ctx.lp_num_iterations,
            )
            union_part = np.asarray(refined)[: fine_host.n]
        # defensive: every node must still carry a block of ITS replica
        rep_of_node = np.repeat(np.arange(G), n_split)
        if not (
            (union_part >= rep_of_node * ip_k)
            & (union_part < (rep_of_node + 1) * ip_k)
        ).all():
            raise AssertionError(
                "union refinement moved a node across replicas"
            )

        # --- keep the best replica (distribute_best_partition analog) --
        part, g_best, cut = best_replica_partition(
            split_host, union_part, G, ip_k
        )
        self._replication_info.update(
            {"levels": len(u_levels), "best_replica": g_best, "cut": cut}
        )
        from .. import telemetry

        telemetry.event("replicated-coarsening", **self._replication_info)
        log(
            f"replicated coarsening: G={G} replicas x "
            f"{int(self.mesh.devices.size) // G} devices, "
            f"{len(u_levels)} levels, best replica {g_best} cut {cut}"
        )
        return part.astype(np.int32), ip_k

    def _initial_spans(self, current_k: int, final_k: int):
        """Block spans (first final block, count) for the current blocks —
        the shm deep partitioner's bookkeeping (partitioning/deep.py)."""
        from ..partitioning.rb import split_k

        spans: List[Tuple[int, int]] = []

        def rec(first: int, count: int, blocks: int):
            if blocks == 1:
                spans.append((first, count))
                return
            b0 = blocks // 2 + (blocks & 1)
            k0, k1 = split_k(count)
            rec(first, k0, b0)
            rec(first + k0, k1, blocks - b0)

        rec(0, final_k, current_k)
        return spans

    def _span_caps(self, spans) -> jnp.ndarray:
        p = self.ctx.partition
        caps = np.array(
            [
                p.total_max_block_weights(first, first + count)
                for first, count in spans
            ],
            dtype=np.int64,
        )
        return jnp.asarray(np.minimum(caps, WMAX), dtype=WEIGHT_DTYPE)

    # host-boundary contract: distributed refinement returns the refined
    # partition to host per level (the caller projects it up host-side)
    # — the readback is the handoff the dist-uncoarsening span times
    # tpulint: disable=R1
    def _refine_dist(
        self, refiner, dg, fine_host, partition, current_k, spans, seed,
        level,
    ) -> np.ndarray:
        from .mesh import comm_phase
        from ..resilience import deadline as deadline_mod

        if deadline_mod.agreed_stop():
            # anytime wind-down: skip the optional collective refinement
            # round — by the AGREED verdict, so every rank skips or none
            # does (a divergent skip would deadlock the collectives);
            # projection/extension (mandatory for a valid k-way result)
            # still run in the caller
            return partition

        full = np.zeros(dg.n_pad, dtype=np.int32)
        full[: fine_host.n] = partition
        with comm_phase(f"refinement-L{level}-k{current_k}"):
            refined = refiner(
                dg, jnp.asarray(full), current_k, self._span_caps(spans),
                seed, level=level,
            )
        return np.asarray(refined)[: fine_host.n]

    def _extend_on_mesh(self, fine_host: HostGraph, partition, spans):
        """Double k by bipartitioning every multi-span block's induced
        subgraph — the extend_partition lineage (helper.cc:220).  The
        reference extracts block subgraphs onto PE GROUPS and runs shm
        KaMinPar per group (kaminpar-dist/graphutils/subgraph_extractor.cc
        :872, deep_multilevel.cc:181+); on a one-host mesh the group
        parallelism collapses to a loop, so blocks are extracted on the
        host and bipartitioned by the native sequential multilevel
        bipartitioner (native/ip.cpp), after which the caller's
        distributed refinement at the doubled k polishes on the mesh."""
        from ..graphs.host import extract_block_subgraphs
        from ..initial import InitialMultilevelBipartitioner
        from ..partitioning.deep import DeepMultilevelPartitioner
        from ..partitioning.rb import bipartition_max_block_weights, split_k

        fine_host = self._plain(fine_host)  # extraction needs plain rows
        rng = np.random.default_rng(
            (self.ctx.seed * 63018038201 + len(spans)) & 0x7FFFFFFF
        )
        current_k = len(spans)
        ext = extract_block_subgraphs(
            fine_host, partition.astype(np.int64), current_k
        )
        bipartitioner = InitialMultilevelBipartitioner(
            self.ctx.shm.initial_partitioning
        )
        # large blocks route through the shm deep partitioner's device
        # bipartition pipeline, exactly like the shm extension does
        deep_helper = DeepMultilevelPartitioner(self.ctx.shm)
        device_threshold = self.ctx.shm.partitioning.device_bipartition_threshold
        n = fine_host.n
        new_part = np.zeros(n, dtype=np.int32)
        new_spans: List[Tuple[int, int]] = []
        next_id = 0
        for b, (first, count) in enumerate(spans):
            mask = partition == b
            if count <= 1:
                new_part[mask] = next_id
                new_spans.append((first, count))
                next_id += 1
                continue
            sub = ext.subgraphs[b]
            max_w = bipartition_max_block_weights(
                self.ctx.shm, first, count, sub.total_node_weight
            )
            if sub.n >= device_threshold:
                bp = deep_helper._device_bipartition(sub, max_w, rng)
            else:
                bp = bipartitioner.bipartition(sub, max_w, rng)
            k0, k1 = split_k(count)
            new_part[mask] = np.where(
                bp[ext.node_mapping[mask]] == 0, next_id, next_id + 1
            )
            new_spans.append((first, k0))
            new_spans.append((first + k0, k1))
            next_id += 2
        return new_part, new_spans, len(new_spans)

    def _host_cut(self, graph: HostGraph, partition: np.ndarray) -> int:
        src = graph.edge_sources()
        ew = graph.edge_weight_array()
        return int(ew[partition[src] != partition[graph.adjncy]].sum() // 2)

    # -- dist resilience (resilience/{checkpoint,memory,agreement}.py) --

    def _dist_meta(self, num_levels: int,
                   current_k: Optional[int] = None) -> dict:
        """Barrier manifest meta: the per-rank shard-fingerprint vector
        (device-count-change detection on resume), the FULL hierarchy
        depth (per-level seeds must survive keep-list pruning), and the
        current k."""
        meta = {
            "shards": list(self._shard_fps),
            "num_levels": int(num_levels),
        }
        if current_k is not None:
            meta["current_k"] = int(current_k)
        return meta

    def _restore_dist_levels(self, graph, arrays):
        """Rebuild the dist hierarchy from ``dist-level-<i>`` snapshots:
        chain the coarse host CSRs (snapshot i holds contraction i's
        coarse graph + cmap; the fine side of level 0 is the input
        graph, carried by reference through the graph fingerprint).
        The sharded DistGraphs are NOT serialized — dist_graph_from_host
        is deterministic, so each level's is rebuilt on demand, exactly
        like the rung-2 spill path.  Returns (levels, coarsest)."""
        names = sorted(
            (nm for nm in arrays if nm.startswith("dist-level-")),
            key=lambda s: int(s.rsplit("-", 1)[1]),
        )
        levels: List[_DistLevel] = []
        fine = graph
        for nm in names:
            a = arrays[nm]
            coarse = HostGraph(
                xadj=np.asarray(a["xadj"], dtype=np.int64),
                adjncy=np.asarray(a["adjncy"], dtype=np.int32),
                node_weights=np.asarray(a["node_w"]),
                edge_weights=(
                    np.asarray(a["edge_w"]) if a["edge_w"].size else None
                ),
            )
            levels.append(
                _DistLevel(
                    fine, np.asarray(a["cmap"], dtype=np.int32), None,
                    self.mesh,
                )
            )
            fine = coarse
        return levels, fine

    def spill_cold_levels(self) -> int:
        """Memory-governor spiller hook (resilience/memory.py rung >= 2
        and the barrier pressure path): drop EVERY per-level sharded
        DistGraph — during coarsening the next level builds its own
        (the loop's local still references the hot one), and
        uncoarsening rebuilds each level's on demand from its host CSR
        (deterministic builder => cut-identical).  Also releases the
        retained finest-level sharded graph of a compressed input (the
        result cut then degrades to the host path).  Returns the device
        bytes released."""
        from .dist_graph import dist_graph_bytes

        freed = 0
        for lvl in self._levels or []:
            freed += lvl.spill()
        if self._fine_dg is not None:
            freed += dist_graph_bytes(self._fine_dg)
            self._fine_dg = None
        if freed:
            from .. import telemetry
            from ..resilience import memory as memory_mod

            memory_mod.note_spill(freed)
            telemetry.event(
                "memory-spill", bytes=freed, kind="dist-levels",
            )
        return freed


class _DistLevel:
    """One dist coarsening level: the fine-side host graph (by
    reference; plain or compressed), the fine->coarse cluster map, and
    the sharded DistGraph over the fine graph.  The DistGraph is a
    deterministic CACHE (dist_graph_from_host / _from_compressed always
    rebuild the identical arrays), so the rung-2 spill and the
    full-hierarchy resume both drop it and rebuild on demand —
    cut-identical by construction."""

    __slots__ = ("fine_host", "cmap", "_dg", "_mesh")

    def __init__(self, fine_host, cmap, dg, mesh):
        self.fine_host = fine_host
        self.cmap = np.asarray(cmap, dtype=np.int32)
        self._dg = dg
        self._mesh = mesh

    def dg(self) -> DistGraph:
        if self._dg is None:
            from ..graphs.compressed import CompressedHostGraph
            from .dist_graph import dist_graph_bytes

            if isinstance(self.fine_host, CompressedHostGraph):
                self._dg = dist_graph_from_compressed(
                    self.fine_host, self._mesh
                )
            else:
                self._dg = dist_graph_from_host(self.fine_host, self._mesh)
            nbytes = dist_graph_bytes(self._dg)
            from .. import telemetry
            from ..resilience import memory as memory_mod

            memory_mod.note_reload(nbytes)
            telemetry.event(
                "memory-reload", bytes=nbytes, kind="dist-level",
            )
        return self._dg

    def spill(self) -> int:
        """Drop the sharded arrays (0 when already spilled)."""
        if self._dg is None:
            return 0
        from .dist_graph import dist_graph_bytes

        nbytes = dist_graph_bytes(self._dg)
        self._dg = None
        return nbytes


def dist_edge_cut_of(graph: DistGraph, labels) -> int:
    """Convenience wrapper mirroring dist::metrics::edge_cut."""
    return int(dist_edge_cut(graph, labels))


def _ckpt_partition_payload(partition) -> dict:
    """Checkpoint barrier payload: the current (already host-side)
    partition — deferred by the barrier, so disabled runs build nothing."""
    return {"state": {"partition": np.asarray(partition, dtype=np.int32)}}


def _dist_level_payload(level_no: int, coarse: HostGraph, cmap, fine_n: int,
                        ) -> dict:
    """One dist hierarchy level as a named snapshot (contraction
    ``level_no``'s coarse host CSR + fine->coarse cmap) — the dist twin
    of partitioning/coarsener.newest_level_snapshot.  Deferred by the
    barrier, so disabled runs build nothing; levels are serialized once
    and carried forward by reference (``keep``)."""
    return {
        f"dist-level-{int(level_no)}": {
            "xadj": np.asarray(coarse.xadj, dtype=np.int64),
            "adjncy": np.asarray(coarse.adjncy, dtype=np.int32),
            "node_w": np.asarray(coarse.node_weight_array()),
            "edge_w": np.asarray(coarse.edge_weight_array()),
            "cmap": np.asarray(cmap, dtype=np.int32),
            "dims": np.asarray(
                [int(fine_n), int(coarse.n), int(coarse.m)], dtype=np.int64
            ),
        }
    }


def _dist_state_payload(partition, spans) -> dict:
    """The dist driver's state snapshot: the current partition plus the
    block spans (first final block, count) the current k was built
    from — everything a dist-initial / dist-uncoarsen re-entry needs
    beyond the hierarchy levels."""
    return {
        "state": {
            "partition": np.asarray(partition, dtype=np.int32),
            "spans": np.asarray(
                [[int(f), int(c)] for f, c in spans], dtype=np.int64
            ),
        }
    }
