"""Distributed deep-multilevel partitioner facade (dKaMinPar analog).

Mirrors kaminpar-dist's orchestration (kaminpar-dist/dkaminpar.cc:496
compute_partition + partitioning/deep_multilevel.cc):

  coarsening   distributed LP clustering over the device mesh
               (parallel/dist_lp.dist_lp_cluster — the GlobalLPClusteringImpl
               analog), followed by contraction.  The reference migrates
               coarse nodes/edges between PEs with sparse alltoalls
               (global_cluster_contraction.cc); here graphs that fit one
               device are contracted by the DEVICE kernel (the sort-based
               dedup in ops/contraction — labels are consistent across
               devices, so a single device-resident contraction replaces
               per-PE rating maps), and only the coarse CSR is pulled back
               to re-shard onto the mesh for the next level.  Graphs above
               the single-device budget run the SHARDED contraction
               (parallel/dist_contraction.py: per-shard dedup + one
               all_to_all coarse-edge migration) so the fine edge list
               never materializes on one device; either way coarse levels
               are geometrically smaller and the fine-level LP rounds
               (the dominant cost) stay fully on-device.

  initial      the coarsest graph is partitioned by the shared-memory
  partitioning KaMinPar pipeline — exactly the reference's scheme of
               replicating the coarsest graph onto every PE and running shm
               KaMinPar (deep_multilevel.cc:125-176, kaminpar_initial_
               partitioner.cc); with a replicated-per-device mesh there is
               one host, so replication is the identity.

  uncoarsening project up through the stored cluster maps and run
               distributed LP refinement per level (the batched LP refiner
               analog, kaminpar-dist/refinement/lp/lp_refiner.cc).
"""

from __future__ import annotations

from typing import List, Optional, Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from ..dtypes import WEIGHT_DTYPE, WMAX
from ..context import Context
from ..graphs.csr import device_graph_from_host, host_graph_from_device
from ..graphs.host import HostGraph
from ..ops.contraction import contract_clustering
from .dist_contraction import dist_contract_clustering
from ..ops.segments import MAX_FUSED_EDGE_SLOTS
from ..utils import timer
from ..utils.logger import log
from .dist_context import (
    DistContext,
    create_dist_clusterer,
    create_dist_context_by_preset_name,
    create_dist_refiner,
)
from .dist_graph import (
    DistGraph,
    dist_graph_from_compressed,
    dist_graph_from_host,
)
from .dist_metrics import dist_edge_cut
from .mesh import make_mesh


class dKaMinPar:
    """Distributed partitioner with the dKaMinPar builder surface
    (include/kaminpar-dist/dkaminpar.h:516+)."""

    def __init__(
        self,
        ctx: Union[DistContext, Context, str, None] = None,
        mesh: Optional[Mesh] = None,
        n_devices: Optional[int] = None,
    ):
        if ctx is None:
            ctx = create_dist_context_by_preset_name("default")
        elif isinstance(ctx, str):
            ctx = create_dist_context_by_preset_name(ctx)
        elif isinstance(ctx, Context):  # shm context: wrap (legacy surface)
            ctx = DistContext(shm=ctx)
        self.ctx = ctx
        self.mesh = mesh if mesh is not None else make_mesh(n_devices)
        self._graph: Optional[HostGraph] = None
        # (source graph, decoded HostGraph) — keyed on the source object
        self._plain_cache: Optional[Tuple[object, HostGraph]] = None
        self._fine_dg: Optional[DistGraph] = None
        # set by _replicated_phase when mesh-subgroup replication fires
        self._replication_info: Optional[dict] = None

    def set_graph(self, graph) -> "dKaMinPar":
        """Accepts a HostGraph or a CompressedHostGraph.  A compressed
        graph is KEPT compressed (the DistributedCompressedGraph analog,
        kaminpar-dist/datastructures/distributed_compressed_graph.h):
        the finest-level device ingestion streams one decoded node-range
        shard at a time (dist_graph_from_compressed), and the plain fine
        CSR materializes lazily only if a host-side consumer demands it
        — in the terapart regime (kway mode, graph above the
        single-device contraction budget, singleton post-passes not
        firing) it never does."""
        self._graph = graph
        self._plain_cache = None
        self._fine_dg = None
        return self

    def _is_compressed(self, g) -> bool:
        from ..graphs.compressed import CompressedHostGraph

        return isinstance(g, CompressedHostGraph)

    def _plain(self, g) -> HostGraph:
        """Materialize a possibly-compressed fine graph (cached, keyed
        on the source object so a different graph can never be handed
        someone else's decode)."""
        if not self._is_compressed(g):
            return g
        if self._plain_cache is None or self._plain_cache[0] is not g:
            self._plain_cache = (g, g.decode())
        return self._plain_cache[1]

    def set_output_level(self, level) -> "dKaMinPar":
        """Instance-scoped output level (dkaminpar.h set_output_level
        analog): applied to the process-global logger only while
        compute_partition runs."""
        from ..utils.logger import OutputLevel

        self._output_level = OutputLevel(level)
        return self

    def copy_graph(self, vtxdist, xadj, adjncy, vwgt=None, adjwgt=None):
        """ParMETIS-style ingestion (dkaminpar.cc:400-448).  vtxdist is
        accepted for API parity; the host assembles the global graph."""
        self._graph = HostGraph(
            xadj=np.asarray(xadj),
            adjncy=np.asarray(adjncy, dtype=np.int32),
            node_weights=None if vwgt is None else np.asarray(vwgt),
            edge_weights=None if adjwgt is None else np.asarray(adjwgt),
        )
        self._plain_cache = None
        self._fine_dg = None
        return self

    def compute_partition(
        self,
        k: Optional[int] = None,
        epsilon: Optional[float] = None,
        seed: Optional[int] = None,
    ) -> np.ndarray:
        if self._graph is None:
            raise RuntimeError("no graph set")
        graph = self._graph
        ctx = self.ctx
        if seed is not None:
            ctx.seed = int(seed)
        ctx.partition.setup(graph, k=k, epsilon=epsilon)
        k = ctx.partition.k

        from .. import telemetry
        from ..utils.logger import output_level, set_output_level

        owns_stream = timer.GLOBAL_TIMER.idle()
        if owns_stream:
            from .mesh import reset_comm_log

            # per-run observability: without these resets, a second
            # compute in the same process reports the first run's traced
            # comm rows and doubled timer scopes attributed to one run's
            # seed/k/result — the report must misattribute nothing, even
            # if cache-hit runs then show an empty comm table (the
            # documented COMM_CAVEAT tradeoff)
            reset_comm_log()
            timer.GLOBAL_TIMER.reset()
            telemetry.reset()
            telemetry.annotate(
                seed=int(ctx.seed),
                k=int(k),
                epsilon=float(ctx.partition.epsilon),
                mode=self.ctx.mode.value,
                devices=int(self.mesh.devices.size),
                graph={"n": int(graph.n), "m": int(graph.m)},
            )

        # preemption safety (kaminpar.py twin): the stream-owning run may
        # arm a deadline and a checkpoint manager; stage ids below are
        # derived from loop indices every rank computes identically
        # (barrier-consistent), and the manager lets only rank 0 write.
        from ..resilience import checkpoint as ckpt_mod
        from ..resilience import deadline as deadline_mod

        mgr = None
        res_ctx = self.ctx.shm.resilience
        if owns_stream:
            # same arm-and-maybe-resume policy as the shm facade
            # (checkpoint.create_manager / deadline.begin_run keep the
            # two from drifting apart)
            ckpt_mod.deactivate()
            deadline_mod.begin_run(
                res_ctx.time_budget or None, res_ctx.budget_grace
            )
            mgr = ckpt_mod.create_manager(res_ctx, graph, self.ctx)
            if mgr is not None:
                ckpt_mod.activate(mgr)
            # memory governor (resilience/memory.py): the dist driver
            # has no recovery ladder — distributed rung semantics would
            # need a cross-rank agreed rung — but the pre-upload budget
            # check still refuses an upload the declared budget cannot
            # hold with a structured DeviceOOM instead of letting the
            # allocator die mid-shard (documented limit,
            # docs/robustness.md)
            from ..resilience import memory as memory_mod

            memory_mod.begin_run(graph, self.ctx)
            # the budget (KAMINPAR_TPU_HBM_BYTES / --memory-budget) is
            # PER-DEVICE and dist_graph shards the node/edge arrays
            # across the mesh, so price the per-rank shard, not the
            # whole graph — otherwise any multi-chip run whose total
            # footprint exceeds one device's budget is refused even
            # though it fits after sharding
            devices = max(1, int(self.mesh.devices.size))
            memory_mod.preflight(
                -(-graph.n // devices), -(-graph.m // devices), k,
                where="dist",
            )

        prior_level = output_level()
        try:
            set_output_level(
                getattr(self, "_output_level", prior_level)
            )
            with timer.scoped_timer("dist-partitioning"):
                # a run preempted after its final barrier resumes
                # instantly from the `result` snapshot; mid-pipeline dist
                # stages are recorded for the audit trail but re-enter at
                # the start (docs/robustness.md documents the limit)
                resumed = (
                    mgr.take_result_resume() if mgr is not None else None
                )
                if resumed is not None and resumed.shape == (graph.n,):
                    partition = resumed
                else:
                    partition = self._partition(graph, k)

            if self._is_compressed(graph) and self._fine_dg is not None:
                # still-compressed input: cut from the finest-level
                # sharded graph (no CSR materialization), imbalance from
                # node weights alone
                full = np.zeros(self._fine_dg.n_pad, dtype=np.int32)
                full[: graph.n] = partition
                # `collective` degradation site: the sharded cut
                # reduction can time out / OOM on a sick link — degrade
                # to the host-side cut (decoding if needed) rather than
                # losing the whole run at the metrics step
                from ..resilience import with_fallback

                fine_dg = self._fine_dg
                cut = with_fallback(
                    lambda: dist_edge_cut_of(fine_dg, jnp.asarray(full)),
                    lambda exc: self._host_cut(
                        self._plain(graph), partition
                    ),
                    site="collective",
                    where="dist-result-cut",
                )
                import math as pymath

                nw = graph.node_weight_array()
                bw = np.zeros(k, dtype=np.int64)
                np.add.at(bw, partition, nw)
                # same definition as host_partition_metrics (ceil'd
                # perfect weight) so the two RESULT paths cannot drift
                perfect = max(1, pymath.ceil(int(nw.sum()) / k))
                imbalance = float(bw.max() / perfect - 1.0)
                feasible = bool((bw <= ctx.partition.max_block_weights).all())
                # the finest sharded arrays are only retained for this
                # metrics call — release the device memory
                self._fine_dg = None
            else:
                from ..graphs.host import host_partition_metrics

                res = host_partition_metrics(self._plain(graph), partition, k)
                cut, imbalance = res["cut"], res["imbalance"]
                feasible = bool(
                    (res["block_weights"] <= ctx.partition.max_block_weights)
                    .all()
                )
            if owns_stream:  # nested runs don't own the stream
                telemetry.annotate(
                    result={
                        "cut": int(cut),
                        "imbalance": float(imbalance),
                        "feasible": feasible,
                    }
                )
            if owns_stream:
                # per-rank memory rollup (perf.memory.ranks): collective
                # — every process gathers its live-HBM figure, so the
                # report shows residency skew between ranks the same way
                # the aggregated timers show wall skew.  perf.enabled()
                # is env+telemetry state, identical on all ranks.
                from ..telemetry import perf as perf_mod

                if perf_mod.enabled():
                    telemetry.annotate(
                        perf_ranks=perf_mod.rank_memory_rollup()
                    )
                # per-rank quality rollup (quality.ranks): collective —
                # every rank contributes its attribution headline, so
                # the dist report shows where cut responsibility sits
                # per rank next to the residency/wall skew
                from ..telemetry import quality as quality_mod

                if quality_mod.enabled():
                    telemetry.annotate(
                        quality_ranks=quality_mod.rank_rollup()
                    )
                if mgr is not None and mgr.enabled:
                    final_part = partition
                    ckpt_mod.barrier(
                        "result", scheme="dist-facade",
                        payload=lambda: {"state": {
                            "partition": np.asarray(
                                final_part, dtype=np.int32
                            ),
                        }},
                    )
                if deadline_mod.triggered():
                    telemetry.annotate(anytime=deadline_mod.state())
                if mgr is not None:
                    telemetry.annotate(checkpoint=mgr.summary())
                mem_summary = memory_mod.summary()
                if mem_summary.get("enabled"):
                    telemetry.annotate(memory_budget=mem_summary)
                ckpt_mod.deactivate()
            log(
                f"RESULT cut={cut} imbalance={imbalance:.6f} "
                f"k={k} devices={self.mesh.devices.size}"
            )
        finally:
            set_output_level(prior_level)
        return partition

    # -- multilevel driver ------------------------------------------------

    def _partition(self, graph: HostGraph, k: int) -> np.ndarray:
        from ..telemetry import quality as quality_mod

        # quality observatory (telemetry/quality.py): the dist driver
        # records its own hierarchy; nested shm IP runs open (and close)
        # their own scopes below this one without corrupting it
        qh = quality_mod.begin("dist")
        try:
            return self._partition_recorded(graph, k, qh)
        finally:
            quality_mod.end(qh)

    def _quality_cut(self, dg, n: int, partition) -> Optional[int]:
        """Sharded cut of a host partition, only when the quality layer
        is live (collective — quality.enabled() is env+telemetry state,
        identical on all ranks, so every rank calls or none does)."""
        from ..telemetry import quality as quality_mod

        if not quality_mod.enabled():
            return None
        full = np.zeros(dg.n_pad, dtype=np.int32)
        full[: int(n)] = partition
        return dist_edge_cut_of(dg, jnp.asarray(full))

    def _partition_recorded(
        self, graph: HostGraph, k: int, qh
    ) -> np.ndarray:
        from ..telemetry import quality as quality_mod

        ctx = self.ctx
        c_ctx = ctx.coarsening
        total_node_weight = ctx.partition.total_node_weight
        clusterer = create_dist_clusterer(ctx)
        refiner = create_dist_refiner(ctx)

        from ..context import PartitioningMode

        deep = self.ctx.mode == PartitioningMode.DEEP

        # coarsening (deep_multilevel.cc:75-118 analog)
        levels: List[Tuple[DistGraph, np.ndarray, HostGraph]] = []
        current = graph
        threshold = max(2 * c_ctx.contraction_limit, k)
        with timer.scoped_timer("dist-coarsening"):
            while current.n > threshold:
                if deep and self._replication_factor(current.n) > 1:
                    # the graph is too small to keep every device busy:
                    # hand over to the mesh-subgroup replication phase
                    # (deep_multilevel.cc:79-153 analog) below
                    break
                if self._is_compressed(current):
                    # still-compressed fine level: stream shards from the
                    # compressed rows (bitwise-identical result)
                    dg = dist_graph_from_compressed(current, self.mesh)
                    self._fine_dg = dg
                else:
                    dg = dist_graph_from_host(current, self.mesh)
                mcw = max(
                    1,
                    c_ctx.max_cluster_weight(
                        current.n, total_node_weight, ctx.partition
                    ),
                )
                lvl_seed = (ctx.seed * 7919 + len(levels) * 31337) & 0x7FFFFFFF
                from .mesh import comm_phase

                with comm_phase(f"coarsening-L{len(levels)}"):
                    labels = clusterer(dg, min(mcw, WMAX), jnp.int32(lvl_seed))
                # singleton post-passes (two-hop + isolated packing) —
                # the reference runs them wherever LP clusters
                # (label_propagation.h:872-1191); without them low-degree
                # graphs under-coarsen on the mesh
                from .dist_lp import dist_singleton_postpasses

                fine = current  # may be compressed; _plain caches decode
                labels = dist_singleton_postpasses(
                    current, np.asarray(labels), min(mcw, WMAX),
                    materialize=lambda: self._plain(fine),
                )
                contracted = self._contract_level(current, dg, labels)
                if contracted is None:  # converged
                    break
                coarse, cmap = contracted
                levels.append((dg, cmap, current))
                quality_mod.note_cmap(
                    level=len(levels), cmap=cmap, fine_n=current.n
                )
                if quality_mod.enabled():
                    # coarsening-quality stats, host-side; compressed
                    # fine levels skip the edge-weight sum (no decode)
                    quality_mod.note_contraction_host(
                        level=len(levels), coarse_host=coarse, cmap=cmap,
                        fine_n=current.n, max_cluster_weight=mcw,
                        total_node_weight=int(total_node_weight),
                        fine_edge_weight=(
                            None if self._is_compressed(current)
                            else int(current.edge_weight_array().sum())
                        ),
                    )
                current = coarse
                from ..resilience import checkpoint as ckpt

                if not ckpt.barrier(
                    "dist-coarsen", level=len(levels), scheme="dist",
                    agree=True,  # next level clusters collectively
                ):
                    break  # deadline wind-down: stop deepening

        # mesh-subgroup replication (deep_multilevel.cc:79-153 +
        # replicator.cc analog): the graph is too small for the whole
        # mesh, so G replicas coarsen + IP + refine independently on
        # D/G-device subgroups (one block-diagonal union graph — see
        # parallel/replication.py) and the best replica's partition
        # continues into the main uncoarsening below
        replicated = False
        if (
            deep
            and current.n > threshold
            and self._replication_factor(current.n) > 1
        ):
            with timer.scoped_timer("dist-replicated-coarsening"):
                # a compressed input can reach this point un-decoded (the
                # loop breaks before the streaming branch); the union
                # builder needs plain CSR rows
                partition, ip_k = self._replicated_phase(
                    self._plain(current), k, clusterer, threshold
                )
            replicated = True

        # DEEP mode partitions the coarsest at a reduced k' and doubles k
        # on the mesh during uncoarsening; KWAY partitions at full k.
        # With no dist levels there is nothing to double over — the shm
        # IP result IS the final partition, so it must run at full k.
        if replicated:
            pass
        elif deep and levels:
            from ..partitioning.deep import compute_k_for_n

            ip_k = max(2, min(k, compute_k_for_n(current.n, self.ctx.shm)))
        else:
            ip_k = k
        spans = self._initial_spans(ip_k, k)

        # initial partitioning: shm pipeline on the coarsest graph.  The
        # reference replicates the coarsest graph onto every PE, runs shm
        # KaMinPar per PE with that PE's seed, and keeps the best cut
        # (replicate_graph_everywhere + distribute_best_partition,
        # kaminpar-dist/partitioning/deep_multilevel.cc:125-176).  When
        # the mesh-subgroup replication phase ran, each replica already
        # carried its own IP and the best partition was selected there;
        # otherwise one host plays all PEs with independent seeded runs.
        if not replicated:
            with timer.scoped_timer("dist-initial-partitioning"):
                num_replicas = max(1, min(self.mesh.devices.size, 4))
                partition = None
                best_cut = None
                for r in range(num_replicas):
                    cand = self._initial_partition(
                        self._plain(current), ip_k, k, spans,
                        (self.ctx.seed * 31 + r * 7907) & 0x7FFFFFFF,
                    )
                    cut = self._host_cut(self._plain(current), cand)
                    if best_cut is None or cut < best_cut:
                        partition, best_cut = cand, cut
        from ..resilience import checkpoint as ckpt

        ckpt.barrier("dist-initial", level=len(levels), scheme="dist")
        # quality: the coarsest level's cut — dist runs no coarsest-level
        # refinement, so projected == refined there (both recorded so
        # the level still gets an attribution row)
        coarsest_cut = (
            (self._replication_info or {}).get("cut") if replicated
            else best_cut
        )
        if coarsest_cut is not None:
            quality_mod.note_projected(
                len(levels), cut=coarsest_cut, k=ip_k
            )
            quality_mod.note_refined(
                len(levels), cut=coarsest_cut, k=ip_k,
                spans=spans, input_k=k,
            )

        # uncoarsening + distributed refinement (deep_multilevel.cc:181+):
        # project up, refine at the current k, and in DEEP mode extend the
        # partition on the mesh while the level's size supports more
        # blocks (the extend_partition lineage, helper.cc:220)
        current_k = ip_k
        num_levels = len(levels)
        with timer.scoped_timer("dist-uncoarsening"):
            for level_idx, (dg, cmap, fine_host) in enumerate(
                reversed(levels)
            ):
                partition = partition[cmap]  # project up
                level = num_levels - 1 - level_idx
                cut = self._quality_cut(dg, fine_host.n, partition)
                if cut is not None:
                    quality_mod.note_projected(level, cut=cut, k=current_k)
                seed = (self.ctx.seed * 92821 + level_idx) & 0x7FFFFFFF
                partition = self._refine_dist(
                    refiner, dg, fine_host, partition, current_k, spans,
                    seed, level,
                )
                if deep:
                    from ..partitioning.deep import compute_k_for_n

                    target_k = min(
                        k, compute_k_for_n(fine_host.n, self.ctx.shm)
                    )
                    while current_k < target_k:
                        partition, spans, current_k = self._extend_on_mesh(
                            fine_host, partition, spans
                        )
                        partition = self._refine_dist(
                            refiner, dg, fine_host, partition, current_k,
                            spans, seed ^ (0x9E37 + current_k), level,
                        )
                cut = self._quality_cut(dg, fine_host.n, partition)
                if cut is not None:
                    quality_mod.note_refined(
                        level, cut=cut, k=current_k,
                        spans=spans, input_k=k,
                    )
                part_now, k_now = partition, current_k
                ckpt.barrier(
                    "dist-uncoarsen", level=level, scheme="dist",
                    payload=lambda: _ckpt_partition_payload(part_now),
                    meta={"current_k": int(k_now)},
                )
        # final extensions to k (finest level)
        if deep and (levels or replicated) and current_k < k:
            if levels:
                dg, _, fine_host = levels[0]
            else:
                # replication fired at the input level: no dist levels
                # exist, but the split-level graph (= the input) still
                # extends on the mesh — the shm fallback below would
                # discard the replicated phase's partition
                fine_host = self._plain(current)
                dg = dist_graph_from_host(fine_host, self.mesh)
            while current_k < k:
                partition, spans, current_k = self._extend_on_mesh(
                    fine_host, partition, spans
                )
                partition = self._refine_dist(
                    refiner, dg, fine_host, partition, current_k, spans,
                    (self.ctx.seed * 48947 + current_k) & 0x7FFFFFFF, 0,
                )
        elif current_k < k:
            # no dist levels (tiny graph): the shm IP already ran at ip_k;
            # fall back to a full-k shm partition
            from ..kaminpar import KaMinPar

            shm = KaMinPar(self.ctx.shm.copy())
            partition = shm.set_graph(self._plain(graph)).compute_partition(
                k=k, epsilon=self.ctx.partition.epsilon, seed=self.ctx.seed
            )
            current_k = k
        # quality: coarsening floors from the final partition.  A
        # still-compressed input is not decoded just for the floors —
        # the attribution keeps the recorded cut rows only (documented
        # caveat, docs/observability.md).
        if not self._is_compressed(graph):
            quality_mod.finalize_host(qh, graph, partition)
        elif self._plain_cache is not None and self._plain_cache[0] is graph:
            quality_mod.finalize_host(qh, self._plain_cache[1], partition)
        return partition

    # -- deep-mode helpers -------------------------------------------------

    def _initial_partition(self, host, ip_k, k, spans, seed) -> np.ndarray:
        """Coarsest-graph initial partitioner dispatch (the
        create_initial_partitioner seam, kaminpar-dist/factories.cc:72-88:
        KAMINPAR / MTKAHYPAR / RANDOM)."""
        from .dist_context import DistInitialPartitioningAlgorithm as Alg

        algo = getattr(
            self.ctx, "initial_partitioning", Alg.KAMINPAR
        )
        if algo == Alg.RANDOM:
            # random_initial_partitioner.cc: uniform block per node; any
            # imbalance is left to the balancers/refiners downstream
            rng = np.random.RandomState(seed & 0x7FFFFFFF)
            return rng.randint(0, ip_k, host.n).astype(np.int32)
        if algo == Alg.MTKAHYPAR:
            # mtkahypar_initial_partitioner.cc — gated on the external
            # package exactly like the refinement adapter
            from ..refinement.mtkahypar import (
                mtkahypar_available,
                mtkahypar_refine_host,
            )

            if not mtkahypar_available():
                raise RuntimeError(
                    "initial_partitioning=mtkahypar requires the external "
                    "'mtkahypar' package (the analog of building the "
                    "reference with KAMINPAR_BUILD_WITH_MTKAHYPAR)"
                )
            rng = np.random.RandomState(seed & 0x7FFFFFFF)
            start = rng.randint(0, ip_k, host.n).astype(np.int32)
            return mtkahypar_refine_host(
                host, start, ip_k,
                epsilon=self.ctx.partition.epsilon, seed=seed,
            ).astype(np.int32)
        return self._shm_ip(host, ip_k, k, spans, seed)

    def _shm_ip(self, host, ip_k, k, spans, seed) -> np.ndarray:
        """One seeded shm-KaMinPar run on a coarsest(-replica) graph with
        span-aware caps (when ip_k does not divide k the current blocks
        carry UNEQUAL final-block counts, and the IP must balance to
        those targets or the first refinement inherits systematic
        overloads).  Quiet, without leaking the global logger level."""
        from ..kaminpar import KaMinPar
        from ..utils.logger import OutputLevel, output_level, set_output_level

        outer_level = output_level()
        try:
            shm = KaMinPar(self.ctx.shm.copy())
            shm.set_output_level(OutputLevel.QUIET)
            shm.set_graph(host)
            p_ = self.ctx.partition
            ip_caps = np.array(
                [
                    p_.total_max_block_weights(first, first + count)
                    for first, count in spans
                ],
                dtype=np.int64,
            )
            return shm.compute_partition(
                k=ip_k,
                epsilon=self.ctx.partition.epsilon,
                max_block_weights=(None if ip_k == k else ip_caps),
                seed=seed,
            )
        finally:
            set_output_level(outer_level)

    def _replication_factor(self, n: int) -> int:
        from .replication import choose_replication_factor

        return choose_replication_factor(
            n,
            int(self.mesh.devices.size),
            int(getattr(self.ctx, "replication_min_nodes_per_device", 0)),
        )

    def _contract_level(self, current: HostGraph, dg, labels):
        """Contract one coarsening level; returns (coarse, cmap) or None
        when the clustering converged (coarse nearly as big as fine)."""
        c_ctx = self.ctx.coarsening
        if current.m <= MAX_FUSED_EDGE_SLOTS:
            # contraction on DEVICE (sort-based dedup kernel; see module
            # docstring): only the coarse CSR is pulled back, to re-shard
            # it for the next level's 1D node distribution (the
            # reference's migrate step, global_cluster_contraction.cc:1100+)
            fine_dev = device_graph_from_host(self._plain(current))
            lab_dev = jnp.asarray(labels)[: fine_dev.n_pad]
            if lab_dev.shape[0] < fine_dev.n_pad:
                lab_dev = jnp.concatenate([
                    lab_dev,
                    jnp.arange(lab_dev.shape[0], fine_dev.n_pad,
                               dtype=jnp.int32),
                ])
            coarse_dev, c_n, _c_m = contract_clustering(fine_dev, lab_dev)
            if c_n >= (1.0 - c_ctx.convergence_threshold) * current.n:
                return None
            cmap = np.asarray(coarse_dev.cmap)[: current.n]
            coarse = host_graph_from_device(coarse_dev.graph)
        else:
            # beyond the single-device budget: SHARDED contraction
            # (per-shard dedup + coarse-edge migrate all_to_all,
            # parallel/dist_contraction.py — the
            # global_cluster_contraction.cc:1100+ analog); the fine edge
            # list never leaves its shards
            coarse, cmap = dist_contract_clustering(
                dg, current.n, current.node_weight_array(),
                np.asarray(labels),
            )
            if coarse.n >= (1.0 - c_ctx.convergence_threshold) * current.n:
                return None
        return coarse, cmap

    def _replicated_phase(
        self, split_host: HostGraph, k: int, clusterer, threshold: int,
    ):
        """Coarsen G replicas of `split_host` as one block-diagonal union
        over the mesh, IP each replica, refine the replica hierarchies in
        lockstep union launches, and return the best replica's partition
        at the split level (deep_multilevel.cc:79-153 +
        replicator.cc:26-34; see parallel/replication.py for why a union
        graph realizes PE-subgroup splitting on a device mesh).

        Returns (partition i32[split_host.n] in [0, ip_k), ip_k)."""
        from ..partitioning.deep import compute_k_for_n
        from .dist_lp import dist_singleton_postpasses
        from .replication import (
            best_replica_partition,
            replica_bounds_after_contraction,
            slice_replica,
            union_graph,
        )

        ctx = self.ctx
        c_ctx = ctx.coarsening
        n_split = split_host.n
        G = self._replication_factor(n_split)
        # the partition re-enters the main uncoarsening at the split
        # level, so it must carry the k that level supports — each
        # replica's internal shm deep pipeline builds up to ip_k exactly
        # like a reference PE subgroup does
        ip_k = max(2, min(k, compute_k_for_n(n_split, ctx.shm)))
        spans = self._initial_spans(ip_k, k)
        union = union_graph(split_host, G)
        bounds = [g * n_split for g in range(G + 1)]
        self._replication_info = {
            "G": G, "split_n": n_split, "ip_k": ip_k,
        }

        # --- coarsen the union until every replica reaches the IP size;
        # replicas diverge through id-keyed hashing (the id offset is the
        # per-replica seed)
        u_levels = []
        current, cur_bounds = union, bounds
        while max(
            cur_bounds[g + 1] - cur_bounds[g] for g in range(G)
        ) > threshold:
            dg = dist_graph_from_host(current, self.mesh)
            n_rep = max(
                cur_bounds[g + 1] - cur_bounds[g] for g in range(G)
            )
            # per-REPLICA size keeps the cluster-weight cap identical to
            # the unreplicated semantics (clusters never span replicas)
            mcw = max(
                1,
                c_ctx.max_cluster_weight(
                    n_rep, ctx.partition.total_node_weight, ctx.partition
                ),
            )
            lvl_seed = (
                ctx.seed * 7919 + (9601 + len(u_levels)) * 31337
            ) & 0x7FFFFFFF
            from .mesh import comm_phase

            with comm_phase(f"replicated-coarsening-L{len(u_levels)}"):
                labels = np.array(
                    clusterer(dg, min(mcw, WMAX), jnp.int32(lvl_seed))
                )
            # singleton post-passes must not merge across replicas (the
            # isolated-node bins are global) — run them per component
            for g in range(G):
                lo, hi = cur_bounds[g], cur_bounds[g + 1]
                sub = slice_replica(current, lo, hi)
                sub_lab = labels[lo:hi] - lo
                labels[lo:hi] = lo + dist_singleton_postpasses(
                    sub, sub_lab, min(mcw, WMAX)
                )
            contracted = self._contract_level(current, dg, labels)
            if contracted is None:
                break
            coarse, cmap = contracted
            u_levels.append((dg, cmap, current))
            cur_bounds = replica_bounds_after_contraction(cmap, cur_bounds)
            current = coarse

        # --- per-replica IP (each subgroup's seeded shm run).  Always the
        # KAMINPAR algorithm here regardless of ctx.initial_partitioning:
        # the union refinement that follows is positive-gain LP only (see
        # below — balancers could cross replicas), so a balance-ignorant
        # RANDOM start could never be repaired before the best-replica
        # cut comparison, which requires comparably feasible candidates.
        union_part = np.zeros(current.n, dtype=np.int32)
        for g in range(G):
            lo, hi = cur_bounds[g], cur_bounds[g + 1]
            sub = slice_replica(current, lo, hi)
            cand = self._shm_ip(
                sub, ip_k, k, spans,
                (ctx.seed * 31 + g * 7907) & 0x7FFFFFFF,
            )
            union_part[lo:hi] = cand.astype(np.int32) + g * ip_k

        # --- uncoarsen the replica hierarchies in lockstep: one union
        # refinement per level with per-replica block-id ranges and tiled
        # caps, so every subgroup refines its own replica simultaneously.
        # POSITIVE-GAIN LP only: a foreign replica's block always has
        # connection 0 (components are disjoint), so strictly-improving
        # moves can never cross replicas — balancers/Jet could (they
        # accept zero-connection moves for balance) and would corrupt
        # the per-replica block-id ranges
        from ..ops.segments import pad_k_bucket
        from .dist_lp import dist_lp_refine

        base_caps = np.asarray(self._span_caps(spans))
        k_u, union_caps, _ = pad_k_bucket(
            G * ip_k, jnp.asarray(np.tile(base_caps, G))
        )
        for level_idx, (dg, cmap, fine_host) in enumerate(
            reversed(u_levels)
        ):
            union_part = union_part[cmap]
            full = np.zeros(dg.n_pad, dtype=np.int32)
            full[: fine_host.n] = union_part
            seed = (ctx.seed * 50411 + level_idx * 73) & 0x7FFFFFFF
            refined = dist_lp_refine(
                dg, jnp.asarray(full), k_u, union_caps, seed,
                num_iterations=ctx.lp_num_iterations,
            )
            union_part = np.asarray(refined)[: fine_host.n]
        # defensive: every node must still carry a block of ITS replica
        rep_of_node = np.repeat(np.arange(G), n_split)
        if not (
            (union_part >= rep_of_node * ip_k)
            & (union_part < (rep_of_node + 1) * ip_k)
        ).all():
            raise AssertionError(
                "union refinement moved a node across replicas"
            )

        # --- keep the best replica (distribute_best_partition analog) --
        part, g_best, cut = best_replica_partition(
            split_host, union_part, G, ip_k
        )
        self._replication_info.update(
            {"levels": len(u_levels), "best_replica": g_best, "cut": cut}
        )
        from .. import telemetry

        telemetry.event("replicated-coarsening", **self._replication_info)
        log(
            f"replicated coarsening: G={G} replicas x "
            f"{int(self.mesh.devices.size) // G} devices, "
            f"{len(u_levels)} levels, best replica {g_best} cut {cut}"
        )
        return part.astype(np.int32), ip_k

    def _initial_spans(self, current_k: int, final_k: int):
        """Block spans (first final block, count) for the current blocks —
        the shm deep partitioner's bookkeeping (partitioning/deep.py)."""
        from ..partitioning.rb import split_k

        spans: List[Tuple[int, int]] = []

        def rec(first: int, count: int, blocks: int):
            if blocks == 1:
                spans.append((first, count))
                return
            b0 = blocks // 2 + (blocks & 1)
            k0, k1 = split_k(count)
            rec(first, k0, b0)
            rec(first + k0, k1, blocks - b0)

        rec(0, final_k, current_k)
        return spans

    def _span_caps(self, spans) -> jnp.ndarray:
        p = self.ctx.partition
        caps = np.array(
            [
                p.total_max_block_weights(first, first + count)
                for first, count in spans
            ],
            dtype=np.int64,
        )
        return jnp.asarray(np.minimum(caps, WMAX), dtype=WEIGHT_DTYPE)

    def _refine_dist(
        self, refiner, dg, fine_host, partition, current_k, spans, seed,
        level,
    ) -> np.ndarray:
        from .mesh import comm_phase
        from ..resilience import deadline as deadline_mod

        if deadline_mod.agreed_stop():
            # anytime wind-down: skip the optional collective refinement
            # round — by the AGREED verdict, so every rank skips or none
            # does (a divergent skip would deadlock the collectives);
            # projection/extension (mandatory for a valid k-way result)
            # still run in the caller
            return partition

        full = np.zeros(dg.n_pad, dtype=np.int32)
        full[: fine_host.n] = partition
        with comm_phase(f"refinement-L{level}-k{current_k}"):
            refined = refiner(
                dg, jnp.asarray(full), current_k, self._span_caps(spans),
                seed, level=level,
            )
        return np.asarray(refined)[: fine_host.n]

    def _extend_on_mesh(self, fine_host: HostGraph, partition, spans):
        """Double k by bipartitioning every multi-span block's induced
        subgraph — the extend_partition lineage (helper.cc:220).  The
        reference extracts block subgraphs onto PE GROUPS and runs shm
        KaMinPar per group (kaminpar-dist/graphutils/subgraph_extractor.cc
        :872, deep_multilevel.cc:181+); on a one-host mesh the group
        parallelism collapses to a loop, so blocks are extracted on the
        host and bipartitioned by the native sequential multilevel
        bipartitioner (native/ip.cpp), after which the caller's
        distributed refinement at the doubled k polishes on the mesh."""
        from ..graphs.host import extract_block_subgraphs
        from ..initial import InitialMultilevelBipartitioner
        from ..partitioning.deep import DeepMultilevelPartitioner
        from ..partitioning.rb import bipartition_max_block_weights, split_k

        fine_host = self._plain(fine_host)  # extraction needs plain rows
        rng = np.random.default_rng(
            (self.ctx.seed * 63018038201 + len(spans)) & 0x7FFFFFFF
        )
        current_k = len(spans)
        ext = extract_block_subgraphs(
            fine_host, partition.astype(np.int64), current_k
        )
        bipartitioner = InitialMultilevelBipartitioner(
            self.ctx.shm.initial_partitioning
        )
        # large blocks route through the shm deep partitioner's device
        # bipartition pipeline, exactly like the shm extension does
        deep_helper = DeepMultilevelPartitioner(self.ctx.shm)
        device_threshold = self.ctx.shm.partitioning.device_bipartition_threshold
        n = fine_host.n
        new_part = np.zeros(n, dtype=np.int32)
        new_spans: List[Tuple[int, int]] = []
        next_id = 0
        for b, (first, count) in enumerate(spans):
            mask = partition == b
            if count <= 1:
                new_part[mask] = next_id
                new_spans.append((first, count))
                next_id += 1
                continue
            sub = ext.subgraphs[b]
            max_w = bipartition_max_block_weights(
                self.ctx.shm, first, count, sub.total_node_weight
            )
            if sub.n >= device_threshold:
                bp = deep_helper._device_bipartition(sub, max_w, rng)
            else:
                bp = bipartitioner.bipartition(sub, max_w, rng)
            k0, k1 = split_k(count)
            new_part[mask] = np.where(
                bp[ext.node_mapping[mask]] == 0, next_id, next_id + 1
            )
            new_spans.append((first, k0))
            new_spans.append((first + k0, k1))
            next_id += 2
        return new_part, new_spans, len(new_spans)

    def _host_cut(self, graph: HostGraph, partition: np.ndarray) -> int:
        src = graph.edge_sources()
        ew = graph.edge_weight_array()
        return int(ew[partition[src] != partition[graph.adjncy]].sum() // 2)


def dist_edge_cut_of(graph: DistGraph, labels) -> int:
    """Convenience wrapper mirroring dist::metrics::edge_cut."""
    return int(dist_edge_cut(graph, labels))


def _ckpt_partition_payload(partition) -> dict:
    """Checkpoint barrier payload: the current (already host-side)
    partition — deferred by the barrier, so disabled runs build nothing."""
    return {"state": {"partition": np.asarray(partition, dtype=np.int32)}}
