"""Distributed colored LP refinement over the device mesh.

Analog of the reference's ColoredLPRefiner
(kaminpar-dist/refinement/lp/clp_refiner.cc): label propagation made
race-free by processing one color class of a greedy node coloring per
superstep — two adjacent nodes are never in the same class, so the gains
computed at the start of a superstep stay exact for every node moved in it
(the reference motivates the design the same way, clp_refiner.cc:1-70).

Per superstep (color c):
  1. nodes of color c rate adjacent blocks from the owner-sharded
     partition state (part_l + ghost slice — local segmented reduction
     over the device's edge shard);
  2. positive-gain moves under the per-block weight caps are selected;
  3. capacity safety across devices uses the same psum'd demand throttle as
     dist_lp (the reference instead commits probabilistically and rolls
     back, clp_refiner.cc `handle_node` + move rollback);
  4. one O(interface) mesh.halo_exchange republishes the changed labels
     to ghosts, one `psum` folds the block-weight deltas — the collective
     form of the reference's ghost-block sync
     (graphutils/synchronization.h:21).  The single O(n) all_gather runs
     at loop exit.

The whole refinement — coloring supersteps x iterations — is one
`shard_map`'d XLA program.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

# version-portable shard_map (mesh.shard_map_compat): the
# replication-check flag is spelled check_vma / check_rep depending on
# the installed jax — the compat shim keeps every dist kernel usable on
# both instead of dying with a TypeError at the first collective
from .mesh import shard_map_compat as _shard_map

from ..ops.segments import (
    ACC_DTYPE,
    accept_prefix_by_capacity,
    aggregate_by_key,
    argmax_per_segment,
    connection_to_label,
    hash_u32,
    move_weight_delta,
)
from .dist_coloring import dist_greedy_coloring
from .dist_graph import DistGraph
from .mesh import account_collective, NODE_AXIS, halo_exchange, throttled_local_capacity


@partial(jax.jit, static_argnames=("mesh", "k", "num_iterations"))
def _dist_clp_impl(
    mesh,
    graph: DistGraph,
    partition: jax.Array,
    colors: jax.Array,
    num_colors: jax.Array,
    k: int,
    max_block_weights: jax.Array,
    seed: jax.Array,
    num_iterations: int,
):
    def per_device(src_l, dst_l, dstloc_l, ew_l, nw_l, n, ghost_gid_l,
                   send_idx_l, recv_map_l, part0, colors, num_colors,
                   cap, seed):
        n_loc = nw_l.shape[0]
        g_loc = ghost_gid_l.shape[0]
        d = lax.axis_index(NODE_AXIS)
        offset = (d * n_loc).astype(jnp.int32)
        node_ids_l = offset + jnp.arange(n_loc, dtype=jnp.int32)
        seg = src_l - offset
        dstloc_c = jnp.clip(dstloc_l, 0, n_loc + g_loc - 1)
        colors_l = lax.dynamic_slice(colors, (offset,), (n_loc,))
        part_l0 = lax.dynamic_slice(part0, (offset,), (n_loc,))
        ghost0 = part0[jnp.clip(ghost_gid_l, 0, part0.shape[0] - 1)]

        bw0 = lax.psum(
            jax.ops.segment_sum(
                nw_l.astype(ACC_DTYPE),
                jnp.clip(part_l0, 0, k - 1),
                num_segments=k,
            ),
            NODE_AXIS,
        )

        def superstep(part_l, ghost, bw, c, salt):
            eligible = (colors_l == c) & (node_ids_l < n)

            neigh_block = jnp.concatenate([part_l, ghost])[dstloc_c]
            seg_g, key_g, w_g = aggregate_by_key(seg, neigh_block, ew_l)
            key_c = jnp.clip(key_g, 0, k - 1)
            seg_c = jnp.clip(seg_g, 0, n_loc - 1)
            fits = (
                bw[key_c] + nw_l[seg_c].astype(ACC_DTYPE) <= cap[key_c]
            )
            is_current = key_g == part_l[seg_c]
            feasible = (seg_g >= 0) & (is_current | fits)
            best, best_w = argmax_per_segment(
                seg_g, key_g, w_g, n_loc, tie_salt=salt, feasible=feasible
            )
            w_cur = connection_to_label(seg_g, key_g, w_g, part_l, n_loc)
            gain = best_w - w_cur
            wants = eligible & (best >= 0) & (best != part_l) & (gain > 0)
            target_l = jnp.where(wants, best, -1)

            local_cap = throttled_local_capacity(target_l, nw_l, bw, cap)
            prio_l = hash_u32(node_ids_l, salt ^ 0x165667B1)
            accept_l = accept_prefix_by_capacity(
                target_l, prio_l, nw_l, local_cap
            )

            new_part_l = jnp.where(accept_l, target_l, part_l)
            new_ghost = halo_exchange(
                new_part_l, send_idx_l, recv_map_l, g_loc
            )
            delta = lax.psum(
                move_weight_delta(part_l, target_l, accept_l, nw_l, k),
                NODE_AXIS,
            )
            return new_part_l, new_ghost, bw + delta

        def iter_body(i, carry):
            part_l, ghost, bw = carry

            def color_cond_body(state):
                c, part_l, ghost, bw = state
                salt = (
                    seed.astype(jnp.int32) * 48271
                    + i * 16807
                    + c * 1566083941
                ) & 0x7FFFFFFF
                part_l, ghost, bw = superstep(part_l, ghost, bw, c, salt)
                return (c + 1, part_l, ghost, bw)

            _, part_l, ghost, bw = lax.while_loop(
                lambda s: s[0] < num_colors,
                color_cond_body,
                (jnp.int32(0), part_l, ghost, bw),
            )
            return (part_l, ghost, bw)

        part_l, _, _ = lax.fori_loop(
            0, num_iterations, iter_body, (part_l0, ghost0, bw0)
        )
        # ONE O(n) gather at loop exit
        account_collective(
            "all_gather(partition)", part_l.size * 4, shape=part_l.shape
        )
        return lax.all_gather(part_l, NODE_AXIS, tiled=True)

    return _shard_map(
        per_device,
        mesh=mesh,
        in_specs=(
            P(NODE_AXIS), P(NODE_AXIS), P(NODE_AXIS), P(NODE_AXIS),
            P(NODE_AXIS), P(), P(NODE_AXIS), P(NODE_AXIS), P(NODE_AXIS),
            P(), P(), P(), P(), P(),
        ),
        out_specs=P(),
        check_vma=False,
    )(
        graph.src, graph.dst, graph.dst_local, graph.edge_w, graph.node_w,
        graph.n, graph.ghost_gid, graph.send_idx, graph.recv_map,
        partition, colors, num_colors, max_block_weights, seed,
    )


def dist_colored_lp_refine(
    graph: DistGraph,
    partition: jax.Array,
    k: int,
    max_block_weights,
    seed,
    num_iterations: int = 5,
    colors: jax.Array | None = None,
    num_colors: jax.Array | None = None,
) -> jax.Array:
    """Colored LP refinement (ColoredLPRefiner analog).  Computes a greedy
    coloring unless one is supplied, then runs `num_iterations` sweeps over
    the color classes.  Returns the refined partition, replicated."""
    if colors is None or num_colors is None:
        colors, num_colors = dist_greedy_coloring(graph, seed)
    part0 = jnp.clip(jnp.asarray(partition, jnp.int32), 0, k - 1)
    return _dist_clp_impl(
        graph.src.sharding.mesh,
        graph,
        part0,
        colors,
        num_colors,
        k,
        jnp.asarray(max_block_weights, ACC_DTYPE),
        jnp.asarray(seed),
        num_iterations,
    )
