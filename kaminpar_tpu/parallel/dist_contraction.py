"""Sharded distributed cluster contraction over the device mesh.

Analog of the reference's global contraction
(kaminpar-dist/coarsening/contraction/global_cluster_contraction.cc, in
particular the coarse node/edge migration alltoalls at :1100+): build the
coarse graph from a clustering WITHOUT ever materializing the fine graph
on one device.  Per device inside `shard_map`:

  1. map the local edge shard to coarse endpoints (labels and the dense
     leader->coarse-id map are replicated — both are O(n) arrays the
     driver already holds);
  2. locally deduplicate (cu, cv) pairs with one sort-based
     aggregate_by_key — the per-PE rating-map dedup of the reference;
  3. MIGRATE: bucket the deduplicated rows by HASH(cu, cv) mod D and
     exchange them with ONE static [D, cap] all_to_all — the
     reference's sparse alltoall of coarse edges.  Hashing the PAIR
     (not cu ownership chunks) is the skew defense: a star-like
     clustering concentrates all coarse edges on one cu, but its
     (cu, cv) pairs still spread uniformly because cv varies — no
     single device's buckets can be flooded by one heavy coarse node
     (the reference instead rebalances explicit node ownership,
     global_cluster_contraction.cc:1100+; a uniform hash needs no
     balancing pass at all);
  4. merge rows arriving from different source devices with a second
     aggregate_by_key; every (cu, cv) pair now lives exactly once, on
     its hash owner.

The host driver assembles the per-shard results into the coarse CSR
(one lexsort of coarse-sized rows — shards hold disjoint pair sets but
interleaved cu ranges) — the coarse graph is geometrically smaller, and
the fine edge list never leaves its shards.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

# version-portable shard_map (mesh.shard_map_compat): the
# replication-check flag is spelled check_vma / check_rep depending on
# the installed jax — the compat shim keeps every dist kernel usable on
# both instead of dying with a TypeError at the first collective
from .mesh import shard_map_compat as _shard_map

from ..graphs.host import HostGraph
from ..ops.segments import ACC_DTYPE, aggregate_by_key, hash_u32
from .dist_graph import DistGraph
from .mesh import NODE_AXIS

# output rows per device = OUT_FACTOR * m_loc; with hash-bucketed pairs
# a device's merged coarse rows concentrate only if the HASH does, so
# this is a safety net, not a skew knob — the driver checks the returned
# count and raises rather than truncating
OUT_FACTOR = 2

# per-peer migrate bucket capacity = max(m_loc * BUCKET_SLACK // D,
# BUCKET_MIN): O(m_loc/D) per device instead of O(m_loc) per PEER, so
# total buffer memory stays O(m_loc * slack) — the point of sharding.
# Residual overflows (count per bucket) are detected and raise.
BUCKET_SLACK = 4
BUCKET_MIN = 1 << 16


@partial(jax.jit, static_argnames=("mesh",))
def _dist_contract_edges_impl(mesh, graph: DistGraph, labels, cmap_full):
    D = int(mesh.devices.size)
    n_pad = graph.n_pad

    def per_device(src_l, dst_l, ew_l, n, labels, cmap_full):
        cap = src_l.shape[0]  # m_loc
        # 1. coarse endpoints of the local edge shard
        lab_src = labels[jnp.clip(src_l, 0, n_pad - 1)]
        lab_dst = labels[jnp.clip(dst_l, 0, n_pad - 1)]
        cu = cmap_full[jnp.clip(lab_src, 0, n_pad - 1)]
        cv = cmap_full[jnp.clip(lab_dst, 0, n_pad - 1)]
        keep = (src_l < n) & (dst_l < n) & (cu != cv)

        # 2. local dedup (rows compacted to the front, sorted by (cu, cv)).
        # Invalid rows use a LARGE sentinel, not -1: aggregate_by_key sorts
        # groups by key ascending, and the valid rows must form the PREFIX
        big = jnp.int32(n_pad)
        seg = jnp.where(keep, cu, big)
        seg_g, key_g, w_g = aggregate_by_key(seg, jnp.where(keep, cv, big), ew_l)
        rows_valid = (seg_g >= 0) & (seg_g < big)

        # 3. migrate: bucket rows by hash(cu, cv) mod D — uniform across
        # devices regardless of coarse-degree skew (see module doc); the
        # same pair hashes identically everywhere, so duplicates still
        # meet.  Rows are re-sorted by target so the in-bucket position
        # is index minus the target's first index.  Bucket capacity is
        # O(m_loc/D) (+slack), not m_loc — total send+recv memory stays
        # O(m_loc), the point of a sharded contraction; residual
        # overflows are detected, not truncated
        bcap = max(cap * BUCKET_SLACK // D, BUCKET_MIN)
        pair_h = hash_u32(
            seg_g ^ (key_g * jnp.int32(-1640531527)), 0x5C0A
        )
        tgt = jnp.where(rows_valid, pair_h % D, D).astype(jnp.int32)
        tgt, seg_g, key_g, w_g = lax.sort(
            (tgt, seg_g, key_g, w_g), num_keys=1
        )
        rows_valid = tgt < D
        idx = jnp.arange(cap, dtype=jnp.int32)
        start = jax.ops.segment_min(
            jnp.where(rows_valid, idx, cap), tgt, num_segments=D + 1
        )
        pos = idx - start[jnp.clip(tgt, 0, D - 1)]
        overflow = jnp.sum(
            (rows_valid & (pos >= bcap)).astype(jnp.int32)
        )
        flat = jnp.where(
            rows_valid & (pos < bcap), tgt * bcap + pos, D * bcap
        )

        def to_buckets(vals, fill):
            buf = (
                jnp.full(D * bcap + 1, fill, dtype=vals.dtype)
                .at[flat]
                .set(jnp.where(rows_valid, vals, fill), mode="drop")
            )
            return buf[: D * bcap].reshape(D, bcap)

        send_cu = to_buckets(seg_g, jnp.int32(-1))
        send_cv = to_buckets(key_g, jnp.int32(-1))
        send_w = to_buckets(w_g, jnp.zeros((), ACC_DTYPE))
        from .mesh import account_collective

        account_collective(
            "all_to_all(contraction-edges)",
            sum(b.size * b.dtype.itemsize for b in (send_cu, send_cv, send_w)),
            shape=send_cu.shape,
        )
        recv_cu = lax.all_to_all(send_cu, NODE_AXIS, 0, 0, tiled=True)
        recv_cv = lax.all_to_all(send_cv, NODE_AXIS, 0, 0, tiled=True)
        recv_w = lax.all_to_all(send_w, NODE_AXIS, 0, 0, tiled=True)

        # 4. merge duplicates arriving from different source devices (the
        # same large-sentinel rule keeps valid rows as the prefix).  A
        # bucket overflow anywhere poisons `count` past out_cap so the
        # driver raises instead of silently dropping rows.
        seg2 = recv_cu.reshape(-1)
        cv2 = recv_cv.reshape(-1)
        seg_f, key_f, w_f = aggregate_by_key(
            jnp.where(seg2 >= 0, seg2, big),
            jnp.where(seg2 >= 0, cv2, big),
            recv_w.reshape(-1),
        )
        valid_f = (seg_f >= 0) & (seg_f < big)
        out_cap = OUT_FACTOR * cap
        total_overflow = lax.psum(overflow, NODE_AXIS)
        count = jnp.where(
            total_overflow > 0,
            jnp.int32(out_cap + 1),
            jnp.sum(valid_f.astype(jnp.int32)),
        )
        return seg_f[:out_cap], key_f[:out_cap], w_f[:out_cap], count[None]

    return _shard_map(
        per_device,
        mesh=mesh,
        in_specs=(
            P(NODE_AXIS), P(NODE_AXIS), P(NODE_AXIS),
            P(), P(), P(),
        ),
        out_specs=(P(NODE_AXIS), P(NODE_AXIS), P(NODE_AXIS), P(NODE_AXIS)),
        check_vma=False,
    )(
        graph.src, graph.dst, graph.edge_w, graph.n,
        labels, cmap_full,
    )


def dist_contract_clustering(
    graph: DistGraph,
    dg_host_n: int,
    node_w: np.ndarray,
    labels: np.ndarray,
) -> Tuple[HostGraph, np.ndarray]:
    """Contract a clustering of the sharded graph; returns (coarse
    HostGraph, cmap fine->coarse).  The coarse edge list is produced by
    the sharded migrate kernel above; only coarse-sized data reaches the
    host."""
    n_pad = graph.n_pad
    lab = np.asarray(labels, dtype=np.int64)
    used = np.zeros(n_pad, dtype=bool)
    used[lab[:dg_host_n]] = True
    # coarse ids <= n, ID domain  # tpulint: disable=R3
    cmap_full = (np.cumsum(used) - 1).astype(np.int32)
    c_n = int(used.sum())
    cmap = cmap_full[lab[:dg_host_n]]

    cu_s, cv_s, w_s, counts = _dist_contract_edges_impl(
        graph.src.sharding.mesh, graph, jnp.asarray(lab, jnp.int32),
        jnp.asarray(cmap_full),
    )
    D = int(graph.src.sharding.mesh.devices.size)
    cu_s = np.asarray(cu_s).reshape(D, -1)
    cv_s = np.asarray(cv_s).reshape(D, -1)
    w_s = np.asarray(w_s).reshape(D, -1)
    counts = np.asarray(counts).reshape(-1)
    out_cap = cu_s.shape[1]
    if (counts > out_cap).any():
        raise RuntimeError(
            "sharded contraction overflow: a migrate bucket or a device's "
            f"merged coarse rows exceed capacity ({out_cap}); raise "
            "dist_contraction.OUT_FACTOR / BUCKET_SLACK"
        )
    # shards hold disjoint (cu, cv) pair sets but interleaved cu ranges
    # (hash bucketing), so canonicalize with one coarse-sized lexsort
    parts_cu = [cu_s[d, : counts[d]] for d in range(D)]
    parts_cv = [cv_s[d, : counts[d]] for d in range(D)]
    parts_w = [w_s[d, : counts[d]] for d in range(D)]
    g_cu = np.concatenate(parts_cu) if parts_cu else np.zeros(0, np.int64)
    g_cv = np.concatenate(parts_cv)
    g_w = np.concatenate(parts_w).astype(np.int64)
    order = np.lexsort((g_cv, g_cu))
    g_cu, g_cv, g_w = g_cu[order], g_cv[order], g_w[order]

    c_node_w = np.zeros(c_n, dtype=np.int64)
    np.add.at(c_node_w, cmap, np.asarray(node_w[:dg_host_n], dtype=np.int64))
    xadj = np.zeros(c_n + 1, dtype=np.int64)
    np.add.at(xadj, g_cu.astype(np.int64) + 1, 1)
    xadj = np.cumsum(xadj)
    coarse = HostGraph(
        xadj=xadj,
        adjncy=g_cv.astype(np.int32),
        node_weights=c_node_w,
        edge_weights=(
            g_w if len(g_w) and not (g_w == 1).all() else None
        ),
    )
    return coarse, cmap
