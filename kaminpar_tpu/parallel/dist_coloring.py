"""Distributed greedy node coloring over the device mesh.

Analog of kaminpar-dist/algorithms/greedy_node_coloring.h
(compute_node_coloring), the prerequisite of the colored LP refiner
(clp_refiner.cc).  The reference colors nodes in parallel supersteps and
fixes conflicts across PE boundaries afterwards; the TPU version runs
Jones-Plassmann rounds to completion inside one `shard_map` program:

  round r: every still-uncolored node whose random priority is a strict
  local minimum among its uncolored neighbors receives color r.

Each color class is an independent set by construction (two adjacent nodes
can never both be priority minima in the same round), which is the property
the colored LP refiner relies on.  Random priorities make the expected
number of rounds O(log n); the loop is a `lax.while_loop` keyed on the
count of uncolored nodes, so the whole coloring is one device program.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

# version-portable shard_map (mesh.shard_map_compat): the
# replication-check flag is spelled check_vma / check_rep depending on
# the installed jax — the compat shim keeps every dist kernel usable on
# both instead of dying with a TypeError at the first collective
from .mesh import shard_map_compat as _shard_map

from ..ops.segments import hash_u32
from .dist_graph import DistGraph
from .mesh import NODE_AXIS, halo_exchange


@partial(jax.jit, static_argnames=("mesh", "max_rounds"))
def _dist_coloring_impl(mesh, graph: DistGraph, seed, max_rounds: int):
    def per_device(src_l, dst_l, dstloc_l, ew_l, nw_l, n, ghost_gid_l,
                   send_idx_l, recv_map_l, seed):
        n_loc = nw_l.shape[0]
        g_loc = ghost_gid_l.shape[0]
        d = lax.axis_index(NODE_AXIS)
        offset = (d * n_loc).astype(jnp.int32)
        node_ids_l = offset + jnp.arange(n_loc, dtype=jnp.int32)
        seg = src_l - offset
        seg_c = jnp.clip(seg, 0, n_loc - 1)
        dstloc_c = jnp.clip(dstloc_l, 0, n_loc + g_loc - 1)
        is_real_l = node_ids_l < n

        # fixed random priority per pass (Jones-Plassmann); ties broken by
        # id.  Priorities are pure hashes of GLOBAL ids, so neighbors'
        # priorities come straight from dst_l — only the colored/uncolored
        # status needs the ghost halo.
        prio_l = hash_u32(node_ids_l, seed)
        neigh_prio_hash = hash_u32(dst_l, seed)

        def cond(state):
            rnd, _, _, uncolored = state
            return (rnd < max_rounds) & (uncolored != 0)

        def body(state):
            rnd, colors_l, ghost_colors, _ = state
            un_l = (colors_l < 0) & is_real_l

            # priority of uncolored neighbors (colored/pad neighbors are
            # inert); lexicographic (prio, id) strict-minimum test via two
            # segment mins — uint64 keys are unavailable without x64.
            # pad edges point at the pad node, which is never colored —
            # exclude it (dst_l < n) or it blocks its endpoint forever
            tab = jnp.concatenate([colors_l, ghost_colors])
            neigh_un = (tab[dstloc_c] < 0) & (dst_l < n)
            neigh_prio = jnp.where(
                neigh_un, neigh_prio_hash, jnp.iinfo(jnp.int32).max
            )
            min_p = jax.ops.segment_min(
                neigh_prio, seg_c, num_segments=n_loc
            )
            at_min = neigh_un & (neigh_prio == min_p[seg_c])
            min_id = jax.ops.segment_min(
                jnp.where(at_min, dst_l, jnp.iinfo(jnp.int32).max),
                seg_c,
                num_segments=n_loc,
            )
            winner = un_l & (
                (prio_l < min_p)
                | ((prio_l == min_p) & (node_ids_l < min_id))
            )

            new_colors_l = jnp.where(winner, rnd, colors_l)
            new_ghost = halo_exchange(
                new_colors_l, send_idx_l, recv_map_l, g_loc
            )
            uncolored = lax.psum(
                jnp.sum(((new_colors_l < 0) & is_real_l).astype(jnp.int32)),
                NODE_AXIS,
            )
            return (rnd + 1, new_colors_l, new_ghost, uncolored)

        colors0_l = jnp.full(n_loc, -1, dtype=jnp.int32)
        ghost0 = jnp.full(g_loc, -1, dtype=jnp.int32)
        rounds, colors_l, _, _ = lax.while_loop(
            cond, body, (jnp.int32(0), colors0_l, ghost0, jnp.int32(1))
        )
        # leftovers past max_rounds (pathological priority chains): each
        # gets its OWN fresh color so the independent-set guarantee of
        # every color class survives even without convergence.  The
        # device-prefix offsets come from an O(D) gather of counts.
        leftover = (colors_l < 0) & is_real_l
        count_l = jnp.sum(leftover.astype(jnp.int32))
        counts = lax.all_gather(count_l, NODE_AXIS)  # [D]
        # leftover-node count <= n, ID domain  # tpulint: disable=R3
        prefix = jnp.sum(jnp.where(
            jnp.arange(counts.shape[0]) < d, counts, 0
        )).astype(jnp.int32)
        rank = jnp.cumsum(leftover.astype(jnp.int32)) - leftover.astype(
            jnp.int32
        )
        colors_l = jnp.where(leftover, rounds + prefix + rank, colors_l)
        # exit-only O(n) gather
        colors = lax.all_gather(colors_l, NODE_AXIS, tiled=True)
        num_colors = jnp.max(colors) + 1
        return colors, num_colors

    return _shard_map(
        per_device,
        mesh=mesh,
        in_specs=(
            P(NODE_AXIS), P(NODE_AXIS), P(NODE_AXIS), P(NODE_AXIS),
            P(NODE_AXIS), P(), P(NODE_AXIS), P(NODE_AXIS), P(NODE_AXIS),
            P(),
        ),
        out_specs=(P(), P()),
        check_vma=False,
    )(
        graph.src, graph.dst, graph.dst_local, graph.edge_w, graph.node_w,
        graph.n, graph.ghost_gid, graph.send_idx, graph.recv_map, seed,
    )


def dist_greedy_coloring(
    graph: DistGraph, seed, max_rounds: int = 512
) -> Tuple[jax.Array, jax.Array]:
    """Color the sharded graph; returns (colors i32[n_pad] replicated,
    num_colors i32 scalar).  Pad/virtual nodes keep color -1."""
    return _dist_coloring_impl(
        graph.src.sharding.mesh, graph, jnp.asarray(seed, jnp.uint32),
        max_rounds,
    )
