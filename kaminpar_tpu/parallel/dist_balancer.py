"""Distributed greedy node balancer over the device mesh.

Analog of the reference's NodeBalancer
(kaminpar-dist/refinement/balancer/node_balancer.cc): overloaded blocks
shed their lowest-loss border nodes into blocks with headroom until the
partition is feasible.  The reference merges per-PE candidate priority
queues through a binary reduction tree (balancer/reductions.h) and picks
moves on rank 0; the TPU version keeps the same shape with two
static-size collectives per round:

  * each device rates its owned nodes from the ghost-halo partition
    state (no replicated arrays) and locally sorts out its TOP-T move
    candidates by relative gain — the per-PE priority queue;
  * the [T] candidate tuples are all_gather'd (O(D*T) volume, the
    reduction-tree replacement) and EVERY device runs the identical
    capacity-respecting prefix commit
    (ops/segments.accept_prefix_by_capacity), so no broadcast is needed;
  * owners apply their accepted rows and push the changed labels to
    ghosts via mesh.halo_exchange (O(interface)).

A round therefore never moves an O(n) array across the mesh; if more
than T nodes per device must move, the next round picks the next batch —
exactly the reference's round structure (node_balancer.cc rounds).
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

# version-portable shard_map (mesh.shard_map_compat): the
# replication-check flag is spelled check_vma / check_rep depending on
# the installed jax — the compat shim keeps every dist kernel usable on
# both instead of dying with a TypeError at the first collective
from .mesh import shard_map_compat as _shard_map

from ..ops.balancer import relative_gain_key
from ..ops.segments import (
    ACC_DTYPE,
    accept_prefix_by_capacity,
    aggregate_by_key,
    argmax_per_segment,
    connection_to_label,
)
from .dist_graph import DistGraph
from .mesh import account_collective, NODE_AXIS, halo_exchange

# Per-device candidate budget per round (the per-PE PQ size).  Small
# enough that the gathered tuple set stays KBs; the round loop batches
# larger rebalances (the loop runs until feasibility or a dry round, so
# the cap bounds per-round volume, not total throughput).
BALANCER_CANDIDATES_PER_DEVICE = 4096


def topk_candidate_commit(
    target_l, order_l, w_l, srcb_l, overload, headroom, T, k, d,
):
    """Shared top-T candidate protocol of the distributed balancers: sort
    the local candidates by `order_l` (ascending = best), all_gather the
    top-T tuples (O(D*T) — the reduction-tree replacement), run the
    identical capacity-respecting two-sided prefix commit on every
    device, and hand back this device's accepted rows.

    `target_l` must be -1 for non-candidates.  Returns (accepted_T
    bool[T], tgt_T i32[T], lid_T i32[T], accept [D*T], cw_g, tgt_g,
    src_block over the gathered rows) — callers apply their rows and
    derive post-move weights from the gathered arrays."""
    n_loc = target_l.shape[0]
    sort_key = jnp.where(target_l >= 0, order_l, jnp.float32(jnp.inf))
    lid = jnp.arange(n_loc, dtype=jnp.int32)
    key_s, tgt_s, w_s, lid_s = lax.sort(
        (sort_key, target_l, w_l, lid), num_keys=1
    )
    key_T, tgt_T, w_T, lid_T = key_s[:T], tgt_s[:T], w_s[:T], lid_s[:T]
    srcb_T = jnp.where(tgt_T >= 0, srcb_l[jnp.clip(lid_T, 0, n_loc - 1)], -1)

    tgt_g = lax.all_gather(tgt_T, NODE_AXIS, tiled=True)
    key_g = lax.all_gather(key_T, NODE_AXIS, tiled=True)
    w_g = lax.all_gather(w_T, NODE_AXIS, tiled=True)
    srcb_g = lax.all_gather(srcb_T, NODE_AXIS, tiled=True)

    src_block = jnp.where(tgt_g >= 0, jnp.clip(srcb_g, 0, k - 1), -1)
    accept_out = accept_prefix_by_capacity(
        src_block, key_g, w_g, overload, reach=True
    )
    target2 = jnp.where(accept_out, tgt_g, -1)
    accept_in = accept_prefix_by_capacity(target2, key_g, w_g, headroom)
    accept = accept_out & accept_in
    mine = lax.dynamic_slice(accept, (d * T,), (T,))
    accepted_T = mine & (tgt_T >= 0)
    return accepted_T, tgt_T, lid_T, accept, w_g, tgt_g, src_block


def dist_balance_round(
    src_l, dst_l, dstloc_l, ew_l, nw_l, n, part_l, ghost_part,
    send_idx_l, recv_map_l, k, cap, salt,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One balancing round, executed per device inside shard_map.

    Operates on the owner-sharded partition (part_l i32[n_loc] + ghost
    slice ghost_part i32[g_loc]); returns (new part_l, new ghost_part,
    global #moved, still_overloaded).  A round moves at most D*T nodes;
    the caller's loop keys on (moved, still_overloaded) so larger
    rebalances batch across rounds instead of being dropped."""
    n_loc = nw_l.shape[0]
    g_loc = ghost_part.shape[0]
    d = lax.axis_index(NODE_AXIS)
    offset = (d * n_loc).astype(jnp.int32)
    node_ids_l = offset + jnp.arange(n_loc, dtype=jnp.int32)
    seg = src_l - offset
    tab = jnp.concatenate([part_l, ghost_part])

    bw = lax.psum(
        jax.ops.segment_sum(
            nw_l.astype(ACC_DTYPE), jnp.clip(part_l, 0, k - 1), num_segments=k
        ),
        NODE_AXIS,
    )
    overload = jnp.maximum(bw - cap, 0)
    headroom = jnp.maximum(cap - bw, 0)

    in_overloaded = (overload[jnp.clip(part_l, 0, k - 1)] > 0) & (
        node_ids_l < n
    )

    # local candidate rating (node_balancer.cc: highest relative gain into a
    # non-overloaded block with room)
    neigh_block = tab[jnp.clip(dstloc_l, 0, n_loc + g_loc - 1)]
    seg_g, key_g, w_g = aggregate_by_key(seg, neigh_block, ew_l)
    key_c = jnp.clip(key_g, 0, k - 1)
    seg_c = jnp.clip(seg_g, 0, n_loc - 1)
    tgt_ok = (
        (seg_g >= 0)
        & (key_g != part_l[seg_c])
        & (overload[key_c] == 0)
        & (nw_l[seg_c].astype(ACC_DTYPE) <= headroom[key_c])
    )
    best, best_w = argmax_per_segment(
        seg_g, key_g, w_g, n_loc, tie_salt=salt, feasible=tgt_ok
    )
    w_own = connection_to_label(seg_g, key_g, w_g, part_l, n_loc)

    fallback = jnp.argmax(headroom).astype(jnp.int32)
    fallback_ok = nw_l.astype(ACC_DTYPE) <= headroom[fallback]
    use_fallback = (best < 0) & fallback_ok
    target_l = jnp.where(use_fallback, fallback, best)
    gain_l = jnp.where(use_fallback, -w_own, best_w - w_own)
    mover_l = in_overloaded & (target_l >= 0)
    target_l = jnp.where(mover_l, target_l, -1)

    # ---- shared top-T gather + identical deterministic commit ----------
    order_l = -relative_gain_key(gain_l, nw_l)  # ascending = best first
    T = min(BALANCER_CANDIDATES_PER_DEVICE, n_loc)
    do, tgt_T, lid_T, accept, w_g, tgt_g, src_block = topk_candidate_commit(
        target_l, order_l, nw_l, part_l, overload, headroom, T, k, d,
    )

    # ---- apply my accepted rows; push changed labels to ghosts ---------
    new_part_l = part_l.at[lid_T].set(
        jnp.where(
            do, jnp.clip(tgt_T, 0, k - 1),
            part_l[jnp.clip(lid_T, 0, n_loc - 1)],
        ),
        mode="drop",
    )
    new_ghost = halo_exchange(new_part_l, send_idx_l, recv_map_l, g_loc)
    # post-move overload status from the gathered accepted rows, so the
    # round loop can run to feasibility without a second weight reduction
    moved_w = jnp.where(accept, w_g, 0).astype(ACC_DTYPE)
    delta_in = jax.ops.segment_sum(
        moved_w, jnp.clip(tgt_g, 0, k - 1), num_segments=k
    )
    delta_out = jax.ops.segment_sum(
        moved_w, jnp.clip(src_block, 0, k - 1), num_segments=k
    )
    still_overloaded = jnp.any(
        bw - delta_out + delta_in > cap
    )
    return (
        new_part_l, new_ghost, jnp.sum(accept.astype(jnp.int32)),
        still_overloaded,
    )


@partial(jax.jit, static_argnames=("mesh", "k", "max_rounds"))
def _dist_node_balance_impl(mesh, graph, partition, k, cap, seed, max_rounds):
    def per_device(src_l, dst_l, dstloc_l, ew_l, nw_l, n, ghost_gid_l,
                   send_idx_l, recv_map_l, part0, cap, seed):
        n_loc = nw_l.shape[0]
        d = lax.axis_index(NODE_AXIS)
        offset = (d * n_loc).astype(jnp.int32)
        part_l0 = lax.dynamic_slice(part0, (offset,), (n_loc,))
        ghost0 = part0[jnp.clip(ghost_gid_l, 0, part0.shape[0] - 1)]

        def cond(state):
            i, _, _, moved, still = state
            return (i < max_rounds) & (moved != 0) & still

        def body(state):
            i, part_l, ghost, _, _ = state
            salt = (seed.astype(jnp.int32) * 62089911 + i * 7919) & 0x7FFFFFFF
            part_l, ghost, moved, still = dist_balance_round(
                src_l, dst_l, dstloc_l, ew_l, nw_l, n, part_l, ghost,
                send_idx_l, recv_map_l, k, cap, salt,
            )
            return (i + 1, part_l, ghost, moved, still)

        _, part_l, _, _, _ = lax.while_loop(
            cond, body,
            (jnp.int32(0), part_l0, ghost0, jnp.int32(1), jnp.array(True)),
        )
        # ONE O(n) gather at loop exit
        account_collective(
            "all_gather(partition)", part_l.size * 4, shape=part_l.shape
        )
        return lax.all_gather(part_l, NODE_AXIS, tiled=True)

    return _shard_map(
        per_device,
        mesh=mesh,
        in_specs=(
            P(NODE_AXIS), P(NODE_AXIS), P(NODE_AXIS), P(NODE_AXIS),
            P(NODE_AXIS), P(), P(NODE_AXIS), P(NODE_AXIS), P(NODE_AXIS),
            P(), P(), P(),
        ),
        out_specs=P(),
        check_vma=False,
    )(
        graph.src, graph.dst, graph.dst_local, graph.edge_w, graph.node_w,
        graph.n, graph.ghost_gid, graph.send_idx, graph.recv_map,
        partition, cap, seed,
    )


def dist_node_balance(
    graph: DistGraph,
    partition: jax.Array,
    k: int,
    max_block_weights,
    seed,
    max_rounds: int = 64,
) -> jax.Array:
    """Balance an infeasible partition on the mesh (NodeBalancer analog).
    Returns the replicated balanced partition.  The loop exits as soon as
    the partition is feasible or a round moves nothing, so the higher
    round cap only spends launches when a big overload needs batching
    through the per-round D*T candidate budget."""
    return _dist_node_balance_impl(
        graph.src.sharding.mesh,
        graph,
        jnp.asarray(partition, jnp.int32),
        k,
        jnp.asarray(max_block_weights, ACC_DTYPE),
        jnp.asarray(seed),
        max_rounds,
    )
