"""Distributed greedy node balancer over the device mesh.

Analog of the reference's NodeBalancer
(kaminpar-dist/refinement/balancer/node_balancer.cc): overloaded blocks
shed their lowest-loss border nodes into blocks with headroom until the
partition is feasible.  The reference merges per-PE candidate priority
queues through a binary reduction tree (balancer/reductions.h) and picks
moves on rank 0; the TPU version exploits that every device can afford the
whole O(n) candidate vector: local shards rate their own nodes, one
`all_gather` replicates the candidate set, and the capacity-respecting
prefix pass (ops/segments.accept_prefix_by_capacity) — computed identically
on every device — replaces the reduction tree.  One round is therefore two
collectives (candidate all_gather + block-weight psum) instead of the
reference's log-P reduction + broadcast.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

try:  # jax >= 0.6
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

from ..ops.balancer import relative_gain_key
from ..ops.segments import (
    ACC_DTYPE,
    accept_prefix_by_capacity,
    aggregate_by_key,
    argmax_per_segment,
    connection_to_label,
)
from .dist_graph import DistGraph
from .mesh import NODE_AXIS


def dist_balance_round(
    src_l, dst_l, ew_l, nw_l, n, part, k, cap, salt
) -> Tuple[jax.Array, jax.Array]:
    """One balancing round, executed per device inside shard_map.

    `part` is the replicated i32[n_pad] partition; returns the new
    replicated partition and the global number of moved nodes."""
    n_loc = nw_l.shape[0]
    n_pad = part.shape[0]
    d = lax.axis_index(NODE_AXIS)
    offset = (d * n_loc).astype(jnp.int32)
    node_ids_l = offset + jnp.arange(n_loc, dtype=jnp.int32)
    seg = src_l - offset
    part_l = lax.dynamic_slice(part, (offset,), (n_loc,))

    bw = lax.psum(
        jax.ops.segment_sum(
            nw_l.astype(ACC_DTYPE), jnp.clip(part_l, 0, k - 1), num_segments=k
        ),
        NODE_AXIS,
    )
    overload = jnp.maximum(bw - cap, 0)
    headroom = jnp.maximum(cap - bw, 0)

    in_overloaded = (overload[jnp.clip(part_l, 0, k - 1)] > 0) & (
        node_ids_l < n
    )

    # local candidate rating (node_balancer.cc: highest relative gain into a
    # non-overloaded block with room)
    neigh_block = part[dst_l]
    seg_g, key_g, w_g = aggregate_by_key(seg, neigh_block, ew_l)
    key_c = jnp.clip(key_g, 0, k - 1)
    seg_c = jnp.clip(seg_g, 0, n_loc - 1)
    tgt_ok = (
        (seg_g >= 0)
        & (key_g != part_l[seg_c])
        & (overload[key_c] == 0)
        & (nw_l[seg_c].astype(ACC_DTYPE) <= headroom[key_c])
    )
    best, best_w = argmax_per_segment(
        seg_g, key_g, w_g, n_loc, tie_salt=salt, feasible=tgt_ok
    )
    w_own = connection_to_label(seg_g, key_g, w_g, part_l, n_loc)

    fallback = jnp.argmax(headroom).astype(jnp.int32)
    fallback_ok = nw_l.astype(ACC_DTYPE) <= headroom[fallback]
    use_fallback = (best < 0) & fallback_ok
    target_l = jnp.where(use_fallback, fallback, best)
    gain_l = jnp.where(use_fallback, -w_own, best_w - w_own)
    mover_l = in_overloaded & (target_l >= 0)
    target_l = jnp.where(mover_l, target_l, -1)

    # replicate the candidate set; every device runs the identical
    # deterministic commit (the reduction-tree replacement)
    target = lax.all_gather(target_l, NODE_AXIS, tiled=True)
    gain = lax.all_gather(gain_l, NODE_AXIS, tiled=True)
    nw = lax.all_gather(nw_l, NODE_AXIS, tiled=True)

    order_key = -relative_gain_key(gain, nw)
    src_block = jnp.where(target >= 0, jnp.clip(part, 0, k - 1), -1)
    accept_out = accept_prefix_by_capacity(
        src_block, order_key, nw, overload, reach=True
    )
    target2 = jnp.where(accept_out, target, -1)
    accept_in = accept_prefix_by_capacity(target2, order_key, nw, headroom)
    accept = accept_out & accept_in

    new_part = jnp.where(accept, jnp.clip(target, 0, k - 1), part)
    return new_part, jnp.sum(accept.astype(jnp.int32))


@partial(jax.jit, static_argnames=("mesh", "k", "max_rounds"))
def _dist_node_balance_impl(mesh, graph, partition, k, cap, seed, max_rounds):
    def per_device(src_l, dst_l, ew_l, nw_l, n, part0, cap, seed):
        def cond(state):
            i, part, moved = state
            return (i < max_rounds) & (moved != 0)

        def body(state):
            i, part, _ = state
            salt = (seed.astype(jnp.int32) * 62089911 + i * 7919) & 0x7FFFFFFF
            part, moved = dist_balance_round(
                src_l, dst_l, ew_l, nw_l, n, part, k, cap, salt
            )
            return (i + 1, part, moved)

        _, part, _ = lax.while_loop(
            cond, body, (jnp.int32(0), part0, jnp.int32(1))
        )
        return part

    return _shard_map(
        per_device,
        mesh=mesh,
        in_specs=(P(NODE_AXIS),) * 4 + (P(),) * 4,
        out_specs=P(),
        check_vma=False,
    )(
        graph.src, graph.dst, graph.edge_w, graph.node_w, graph.n,
        partition, cap, seed,
    )


def dist_node_balance(
    graph: DistGraph,
    partition: jax.Array,
    k: int,
    max_block_weights,
    seed,
    max_rounds: int = 16,
) -> jax.Array:
    """Balance an infeasible partition on the mesh (NodeBalancer analog).
    Returns the replicated balanced partition."""
    return _dist_node_balance_impl(
        graph.src.sharding.mesh,
        graph,
        jnp.asarray(partition, jnp.int32),
        k,
        jnp.asarray(max_block_weights, ACC_DTYPE),
        jnp.asarray(seed),
        max_rounds,
    )
