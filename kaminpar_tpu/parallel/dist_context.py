"""Distributed context, presets, and factories.

Mirrors the reference's distributed configuration surface:
  * enums — kaminpar-dist factories.cc:55-204 (partitioner, clusterer,
    refiner dispatch) and include/kaminpar-dist/dkaminpar.h:73-512;
  * presets — kaminpar-dist/presets.cc:18-46 (default / strong / largek /
    xterapart / europar23-fast / europar23-strong);
  * factories — the enum -> implementation seam, the plugin boundary the
    shared-memory side has in kaminpar-shm/factories.cc.

The distributed context embeds a shared-memory `Context` (used for the
coarsest-graph initial partitioning, exactly like the reference runs shm
KaMinPar on the replicated coarsest graph) plus the dist-specific
clusterer/refiner selections.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field, replace
from typing import Callable, List

from ..context import Context, JetRefinementContext, PartitioningMode
from ..ops.lp import LPConfig
from ..presets import create_context_by_preset_name


class DistClusteringAlgorithm(str, enum.Enum):
    """kaminpar-dist factories.cc clusterer dispatch."""

    GLOBAL_NOOP = "global-noop"
    GLOBAL_LP = "global-lp"
    GLOBAL_HEM = "global-hem"
    GLOBAL_HEM_LP = "global-hem-lp"
    LOCAL_NOOP = "local-noop"
    LOCAL_LP = "local-lp"


class DistRefinementAlgorithm(str, enum.Enum):
    """kaminpar-dist factories.cc refiner dispatch."""

    NOOP = "noop"
    BATCHED_LP = "lp"
    COLORED_LP = "colored-lp"
    JET = "jet"
    NODE_BALANCER = "node-balancer"
    CLUSTER_BALANCER = "cluster-balancer"


class DistInitialPartitioningAlgorithm(str, enum.Enum):
    """kaminpar-dist factories.cc:72-88 initial partitioner dispatch."""

    KAMINPAR = "kaminpar"
    RANDOM = "random"
    MTKAHYPAR = "mtkahypar"


@dataclass
class DistContext:
    """dKaMinPar configuration (include/kaminpar-dist/dkaminpar.h Context
    analog).  `shm` configures coarsening limits, partition constraints and
    the coarsest-graph initial partitioning."""

    shm: Context = field(default_factory=lambda: create_context_by_preset_name("default"))
    # DEEP (deep_multilevel.cc lineage: coarsest partitioned at a reduced
    # k' with block spans, k doubled by mesh-side extension during
    # uncoarsening) or KWAY (kway_multilevel.cc: full k at the coarsest)
    mode: PartitioningMode = PartitioningMode.DEEP
    clustering: DistClusteringAlgorithm = DistClusteringAlgorithm.GLOBAL_LP
    refinement: List[DistRefinementAlgorithm] = field(
        default_factory=lambda: [
            DistRefinementAlgorithm.NODE_BALANCER,
            DistRefinementAlgorithm.BATCHED_LP,
        ]
    )
    jet: JetRefinementContext = field(default_factory=JetRefinementContext)
    initial_partitioning: DistInitialPartitioningAlgorithm = (
        DistInitialPartitioningAlgorithm.KAMINPAR
    )
    lp_num_iterations: int = 5
    clp_num_iterations: int = 5
    hem_rounds: int = 5
    # LP rating engine over the sharded COO layout (ops/rating.py):
    # "auto" resolves to dense / sort — the dist path has no measured
    # degree skew, and select_engine's skew quality gate keeps scatter
    # out without one; force "scatter" explicitly (--lp-rating) on
    # workloads known to be RMAT-class skewed.  sort2 is unavailable
    # here (no CSR row spans)
    lp_rating: str = "auto"
    # mesh-subgroup replication (deep_multilevel.cc:79-153 + replicator.cc
    # replicate_graph / distribute_best_partition analog): once the graph
    # drops below this many nodes PER DEVICE, G replicas coarsen
    # independently on D/G-device subgroups as one block-diagonal union
    # (parallel/replication.py) and the best replica's partition is kept.
    # 0 disables (coarse levels then idle most of the mesh).
    replication_min_nodes_per_device: int = 2048

    # convenience passthroughs used by the driver
    @property
    def seed(self) -> int:
        return self.shm.seed

    @seed.setter
    def seed(self, value: int) -> None:
        self.shm.seed = int(value)

    @property
    def coarsening(self):
        return self.shm.coarsening

    @property
    def partition(self):
        return self.shm.partition

    def copy(self) -> "DistContext":
        import copy as _copy

        return _copy.deepcopy(self)


def _base(shm_preset: str = "default") -> DistContext:
    return DistContext(shm=create_context_by_preset_name(shm_preset))


def create_dist_default_context() -> DistContext:
    """presets.cc create_default_context (dist): global LP coarsening,
    balancer + batched LP refinement."""
    return _base("default")


def create_dist_strong_context() -> DistContext:
    """presets.cc create_strong_context: adds Jet refinement on top of the
    default pipeline (europar23-strong lineage)."""
    ctx = _base("default")
    ctx.refinement = [
        DistRefinementAlgorithm.NODE_BALANCER,
        DistRefinementAlgorithm.BATCHED_LP,
        DistRefinementAlgorithm.JET,
    ]
    return ctx


def create_dist_fast_context() -> DistContext:
    ctx = _base("fast")
    ctx.lp_num_iterations = 3
    return ctx


def create_dist_largek_context() -> DistContext:
    return _base("largek")


def create_dist_xterapart_context() -> DistContext:
    """Memory-frugal preset: compressed shm pipeline on the coarsest
    graph (presets.cc create_xterapart_context lineage)."""
    return _base("terapart")


def create_dist_jet_context() -> DistContext:
    ctx = _base("default")
    ctx.refinement = [
        DistRefinementAlgorithm.NODE_BALANCER,
        DistRefinementAlgorithm.JET,
    ]
    return ctx


def create_dist_colored_lp_context() -> DistContext:
    ctx = _base("default")
    ctx.refinement = [
        DistRefinementAlgorithm.NODE_BALANCER,
        DistRefinementAlgorithm.COLORED_LP,
    ]
    return ctx


def create_dist_cluster_balancer_context() -> DistContext:
    """Hybrid balancing pipeline (factories.cc HYBRID_CLUSTER_BALANCER
    lineage): node balancer first, cluster balancer for the overloads
    single-node moves cannot fix, then batched LP."""
    ctx = _base("default")
    ctx.refinement = [
        DistRefinementAlgorithm.NODE_BALANCER,
        DistRefinementAlgorithm.CLUSTER_BALANCER,
        DistRefinementAlgorithm.BATCHED_LP,
    ]
    return ctx


def create_dist_noref_context() -> DistContext:
    ctx = _base("noref")
    ctx.refinement = []
    return ctx


_DIST_PRESETS = {
    "default": create_dist_default_context,
    "strong": create_dist_strong_context,
    "fast": create_dist_fast_context,
    "largek": create_dist_largek_context,
    "xterapart": create_dist_xterapart_context,
    "europar23-fast": create_dist_default_context,
    "europar23-strong": create_dist_strong_context,
    "jet": create_dist_jet_context,
    "colored-lp": create_dist_colored_lp_context,
    "cluster-balancer": create_dist_cluster_balancer_context,
    "noref": create_dist_noref_context,
}


def create_dist_context_by_preset_name(name: str) -> DistContext:
    try:
        return _DIST_PRESETS[name]()
    except KeyError:
        raise ValueError(
            f"unknown dist preset '{name}' (available: {sorted(_DIST_PRESETS)})"
        ) from None


def get_dist_preset_names():
    return set(_DIST_PRESETS)


# -- factories (kaminpar-dist/factories.cc analog) ------------------------


def create_dist_clusterer(ctx: DistContext) -> Callable:
    """Returns clusterer(graph, max_cluster_weight, seed) -> labels.

    Imports (including jax) are lazy so building a config object can
    never initialize a backend — config construction must stay safe in
    embedding hosts with a restricted JAX_PLATFORMS (see utils.platform).
    """
    import jax.numpy as jnp

    from .dist_hem import dist_hem_cluster, dist_hem_lp_cluster
    from .dist_lp import dist_lp_cluster

    algo = ctx.clustering
    if algo in (
        DistClusteringAlgorithm.GLOBAL_NOOP,
        DistClusteringAlgorithm.LOCAL_NOOP,
    ):
        return lambda graph, mcw, seed: jnp.arange(
            graph.n_pad, dtype=jnp.int32
        )
    if algo == DistClusteringAlgorithm.GLOBAL_LP:
        cfg = LPConfig(rating=ctx.lp_rating)
        return lambda graph, mcw, seed: dist_lp_cluster(
            graph, mcw, seed, cfg=cfg, num_iterations=ctx.lp_num_iterations
        )
    if algo == DistClusteringAlgorithm.LOCAL_LP:
        cfg = LPConfig(dist_local_only=True, rating=ctx.lp_rating)
        return lambda graph, mcw, seed: dist_lp_cluster(
            graph, mcw, seed, cfg=cfg, num_iterations=ctx.lp_num_iterations
        )
    if algo == DistClusteringAlgorithm.GLOBAL_HEM:
        return lambda graph, mcw, seed: dist_hem_cluster(
            graph, mcw, seed, num_rounds=ctx.hem_rounds
        )
    if algo == DistClusteringAlgorithm.GLOBAL_HEM_LP:
        return lambda graph, mcw, seed: dist_hem_lp_cluster(
            graph, mcw, seed, hem_rounds=ctx.hem_rounds
        )
    raise ValueError(f"unhandled clustering algorithm {algo}")


def create_dist_refiner(ctx: DistContext) -> Callable:
    """Returns refiner(graph, partition, k, max_block_weights, seed, level)
    running the configured refinement pipeline in order
    (factories.cc create_refiner + MultiRefiner analog)."""
    from .dist_balancer import dist_node_balance
    from .dist_clp import dist_colored_lp_refine
    from .dist_cluster_balancer import dist_cluster_balance
    from .dist_jet import dist_jet_refine
    from .dist_lp import dist_lp_refine

    algorithms = list(ctx.refinement)

    def refine(graph, partition, k, max_block_weights, seed, level=0):
        # k is shape-defining for the dist kernels too (see pad_k_bucket)
        from ..ops.segments import pad_k_bucket

        k, max_block_weights, _ = pad_k_bucket(k, max_block_weights)
        part = partition
        for j, algo in enumerate(algorithms):
            s = (int(seed) * 1013904223 + j * 12345) & 0x7FFFFFFF
            if algo == DistRefinementAlgorithm.NOOP:
                continue
            elif algo == DistRefinementAlgorithm.NODE_BALANCER:
                part = dist_node_balance(
                    graph, part, k, max_block_weights, s
                )
            elif algo == DistRefinementAlgorithm.CLUSTER_BALANCER:
                part = dist_cluster_balance(
                    graph, part, k, max_block_weights, s
                )
            elif algo == DistRefinementAlgorithm.BATCHED_LP:
                part = dist_lp_refine(
                    graph, part, k, max_block_weights, s,
                    num_iterations=ctx.lp_num_iterations,
                )
            elif algo == DistRefinementAlgorithm.COLORED_LP:
                part = dist_colored_lp_refine(
                    graph, part, k, max_block_weights, s,
                    num_iterations=ctx.clp_num_iterations,
                )
            elif algo == DistRefinementAlgorithm.JET:
                part = dist_jet_refine(
                    graph, part, k, max_block_weights, s,
                    ctx=ctx.jet, level=level,
                )
            else:  # pragma: no cover
                raise ValueError(f"unhandled refinement algorithm {algo}")
        return part

    return refine
