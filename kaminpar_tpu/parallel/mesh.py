"""Device-mesh construction.

The analog of the reference's MPI communicator setup (kaminpar-mpi/
wrapper.h, definitions.h): one 1D mesh axis over which the node space is
sharded.  The reference distributes nodes in contiguous ranges per PE
(`node_distribution`, kaminpar-dist/datastructures/distributed_csr_graph.h:
25-92); the mesh axis plays the role of the PE dimension, and XLA
collectives over it ride ICI on real hardware (DCN across slices).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

NODE_AXIS = "nodes"


def make_mesh(
    n_devices: Optional[int] = None,
    devices: Optional[Sequence[jax.Device]] = None,
    axis_name: str = NODE_AXIS,
) -> Mesh:
    """1D mesh over the first `n_devices` available devices."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        if len(devices) < n_devices:
            raise ValueError(
                f"need {n_devices} devices, have {len(devices)}; on CPU set "
                f"XLA_FLAGS=--xla_force_host_platform_device_count={n_devices}"
            )
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (axis_name,))
