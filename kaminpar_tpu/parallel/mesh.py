"""Device-mesh construction and shared collective commit helpers.

The analog of the reference's MPI communicator setup (kaminpar-mpi/
wrapper.h, definitions.h): one 1D mesh axis over which the node space is
sharded.  The reference distributes nodes in contiguous ranges per PE
(`node_distribution`, kaminpar-dist/datastructures/distributed_csr_graph.h:
25-92); the mesh axis plays the role of the PE dimension, and XLA
collectives over it ride ICI on real hardware (DCN across slices).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh

from ..ops.segments import ACC_DTYPE

NODE_AXIS = "nodes"


def throttled_local_capacity(
    target_l: jax.Array,
    node_w_l: jax.Array,
    weights: jax.Array,
    cap: jax.Array,
    axis_name: str = NODE_AXIS,
) -> jax.Array:
    """Cross-device capacity throttle (the control_cluster_weights analog,
    kaminpar-dist/.../global_lp_clusterer.cc:429): each device sums the
    weight its movers demand per target bucket, the demands are `psum`'d,
    and the device's local capacity share is scaled by headroom/demand —
    so the *total* weight accepted across devices provably stays within
    headroom.  The 1-1e-6 factor guards float rounding in the scale; the
    demand<=headroom fast path keeps the common case exact.

    Returns the per-bucket local capacity to feed accept_prefix_by_capacity.
    Shared by the batched and colored distributed LP refiners.
    """
    C = cap.shape[0]
    demand_l = jax.ops.segment_sum(
        jnp.where(target_l >= 0, node_w_l, 0).astype(ACC_DTYPE),
        jnp.clip(target_l, 0, C - 1),
        num_segments=C,
    )
    demand = lax.psum(demand_l, axis_name)
    headroom = jnp.maximum(cap - weights.astype(ACC_DTYPE), 0)
    frac = headroom.astype(jnp.float32) / jnp.maximum(demand, 1).astype(
        jnp.float32
    )
    scaled = jnp.floor(
        demand_l.astype(jnp.float32) * jnp.minimum(frac, 1.0) * (1.0 - 1e-6)
    ).astype(ACC_DTYPE)
    local_cap = jnp.where(demand <= headroom, demand_l, scaled)
    return jnp.minimum(local_cap, headroom)


def halo_exchange(
    vals_l: jax.Array,
    send_idx_l: jax.Array,
    recv_map_l: jax.Array,
    g_loc: int,
    axis_name: str = NODE_AXIS,
) -> jax.Array:
    """Interface→ghost value exchange (the synchronize_ghost_node_* sparse
    alltoall of the reference, kaminpar-dist/graphutils/communication.h:242)
    as one static-shape XLA all_to_all.

    Per device inside shard_map: gather the owned values each peer needs
    (send_idx_l[p] = local indices destined to peer p, pad -1), all_to_all
    the [D, s_max] buffer, scatter received values into ghost slots
    (recv_map_l[p][j] = ghost slot of peer p's j-th value; pad g_loc is
    dropped).  Collective volume O(interface), not O(n).

    `vals_l` may be [n_loc] (one value per node) or stacked [C, n_loc] —
    several per-node quantities share one collective launch (per-launch
    latency dominates on small interfaces).  Returns [g_loc] or
    [C, g_loc] accordingly.
    """
    stacked = vals_l.ndim == 2
    v = vals_l if stacked else vals_l[None]
    n_loc = v.shape[1]
    sendbuf = v[:, jnp.clip(send_idx_l, 0, n_loc - 1)]  # [C, D, s_max]
    recvbuf = lax.all_to_all(sendbuf, axis_name, 1, 1, tiled=True)
    out = (
        jnp.zeros((v.shape[0], g_loc), v.dtype)
        .at[:, recv_map_l.reshape(-1)]
        .set(recvbuf.reshape(v.shape[0], -1), mode="drop")
    )
    return out if stacked else out[0]


def make_mesh(
    n_devices: Optional[int] = None,
    devices: Optional[Sequence[jax.Device]] = None,
    axis_name: str = NODE_AXIS,
) -> Mesh:
    """1D mesh over the first `n_devices` available devices."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        if len(devices) < n_devices:
            raise ValueError(
                f"need {n_devices} devices, have {len(devices)}; on CPU set "
                f"XLA_FLAGS=--xla_force_host_platform_device_count={n_devices}"
            )
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (axis_name,))


def make_torus_mesh(
    rows: int,
    cols: int,
    axis_name: str = NODE_AXIS,
) -> Mesh:
    """1D node axis snaked over a 2D ICI torus.

    The reference reduces alltoall message count by routing through a
    √P×√P PE grid (kaminpar-mpi/grid_alltoall.h:1-45, 2-hop row then
    column exchange).  On TPU the analogous win comes from *placement*,
    not an extra protocol layer: XLA already implements collectives with
    optimal ICI routing, so the job here is to order the devices so that
    ring neighbors on the single logical node axis are physical ICI
    neighbors on the torus.  `jax.experimental.mesh_utils` assigns
    devices to the (rows, cols) grid topology-aware; snaking the rows
    (reversing every other one) makes the flattened order a Hamiltonian
    path of the torus, so `ppermute` shifts and `all_gather` rings ride
    single-hop ICI links.  All dist kernels keep their single
    `NODE_AXIS` view; no 2-hop re-implementation is needed.
    """
    from jax.experimental import mesh_utils

    try:
        grid = mesh_utils.create_device_mesh((rows, cols))
    except (AssertionError, ValueError, NotImplementedError):
        devices = jax.devices()
        if len(devices) < rows * cols:
            raise ValueError(
                f"need {rows * cols} devices, have {len(devices)}"
            ) from None
        grid = np.asarray(devices[: rows * cols]).reshape(rows, cols)
    flat = snake_flatten(np.asarray(grid))
    return Mesh(flat, (axis_name,))


def snake_flatten(grid: np.ndarray) -> np.ndarray:
    """Flatten a 2D grid into a Hamiltonian path of the torus: every
    other row reversed, so consecutive entries are always grid
    neighbors (and the wrap-around hop is a torus link)."""
    rows = [
        grid[r, ::-1] if r % 2 else grid[r, :] for r in range(grid.shape[0])
    ]
    return np.concatenate(rows)
