"""Device-mesh construction and shared collective commit helpers.

The analog of the reference's MPI communicator setup (kaminpar-mpi/
wrapper.h, definitions.h): an (X, Y) mesh grid whose flattened order is
the PE dimension.  The reference distributes nodes in contiguous ranges
per PE (`node_distribution`, kaminpar-dist/datastructures/
distributed_csr_graph.h:25-92); collectives name both mesh axes, so XLA
routes them over both ICI axes on real hardware (DCN across slices) —
the compiler-level counterpart of the reference's grid alltoall.
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh

from ..ops.segments import ACC_DTYPE

# The node space is sharded over a 2D (X, Y) device grid — the TPU
# analog of the reference's 2D PE grid for grid-alltoall routing
# (kaminpar-mpi/grid_alltoall.h:1-45).  Every collective names BOTH
# axes: jax flattens them row-major, so 1D meshes are simply (1, D)
# grids and all dist kernels keep a single flat PE view, while true 2D
# meshes let XLA route each collective hierarchically over the two ICI
# axes (the row-then-column exchange of the reference, implemented by
# the compiler instead of a protocol layer).
NODE_AXIS_X = "nodes_x"
NODE_AXIS_Y = "nodes_y"
NODE_AXIS = (NODE_AXIS_X, NODE_AXIS_Y)


def _resolve_shard_map():
    """``shard_map`` plus the name of its replication-check flag across
    jax versions: ``check_vma`` (new), ``check_rep`` (older), or None
    (oldest — no flag at all).  Before this shim every dist kernel
    passed ``check_vma=False`` unconditionally, so on a check_rep-era
    jax the ENTIRE dist pipeline died with a TypeError at the first
    collective — the seed's documented env-failure class, and exactly
    the kind of avoidable hard failure the resilience layer exists to
    remove."""
    try:  # jax >= 0.6 exposes shard_map at top level
        from jax import shard_map as sm
    except ImportError:  # pragma: no cover
        from jax.experimental.shard_map import shard_map as sm
    import inspect

    try:
        params = inspect.signature(sm).parameters
    except (TypeError, ValueError):  # pragma: no cover
        params = {}
    if "check_vma" in params:
        flag = "check_vma"
    elif "check_rep" in params:
        flag = "check_rep"
    else:  # pragma: no cover
        flag = None
    return sm, flag


_SHARD_MAP, _SHARD_MAP_FLAG = _resolve_shard_map()


def shard_map_compat(f, mesh, in_specs, out_specs, check_vma=False):
    """Version-portable ``shard_map``: the dist kernels always disable
    the replication check (their psum'd scalars are replicated by
    construction and the check costs trace time), and this wrapper
    spells the flag however the installed jax does."""
    kwargs = {}
    if _SHARD_MAP_FLAG is not None:
        kwargs[_SHARD_MAP_FLAG] = check_vma
    return _SHARD_MAP(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
    )

# --- communication accounting -------------------------------------------
#
# A static per-phase model of the collective traffic (the dist layer's
# answer to VERDICT r4 #5/#6: project ICI-vs-compute balance instead of
# asserting it).  Collective helpers register (op, payload bytes, traced
# shape) at TRACE time — inside a lax.while_loop body that is once per
# ROUND, so entries read as "bytes per round per device".  Keying by the
# traced shape keeps shape-bucket retraces as separate rows instead of
# silently double-counting one phase (ADVICE round 5 low #4); the dual
# caveat — a phase whose jitted program is an executable-cache hit
# registers NOTHING — cannot be fixed at trace time and is therefore
# stamped on every rendering (COMM_CAVEAT).  Enabled only while a
# `comm_phase` scope is open; `comm_table()` / `comm_records()` render
# the account, and every new traced key emits a `jit-trace` telemetry
# event (attr retrace=True when the same phase+op re-traced at a new
# shape).

COMM_CAVEAT = (
    "collectives are accounted at TRACE time: a phase whose jitted "
    "program is an executable-cache hit registers zero bytes, and "
    "figures inside round loops are per round per device"
)

class CommLog:
    """One run's collective-traffic account, held on
    ``runstate.current().comm`` (the PR-6 thread-local idiom): a fresh
    RunState per run — the facades' ``deadline.begin_run`` — scopes
    per-request comm attribution structurally, fixing the serving-layer
    aggregation bug where one batch's requests shared a module-global
    log (``reset_comm_log`` was never called between requests)."""

    __slots__ = ("log", "phase_stack", "opens")

    def __init__(self) -> None:
        # (phase, op, traced shape) -> [traced calls, payload bytes]
        self.log: Dict[Tuple[str, str, tuple], List[int]] = {}
        self.phase_stack: List[str] = []
        # phase name -> number of times its scope was OPENED.  A phase
        # opened more often than it traced ran (at least partly) on
        # cached executables; a phase opened with ZERO traced keys is a
        # pure cache hit — its traffic happened, but trace-time
        # accounting cannot see it.  comm_table() marks those rows
        # explicitly (ADVICE round 5 low #4).
        self.opens: Dict[str, int] = {}


def _comm() -> CommLog:
    """This thread's run-scoped account (created on first touch)."""
    from ..resilience import runstate

    run = runstate.current()
    if run.comm is None:
        run.comm = CommLog()
    return run.comm


@contextmanager
def comm_phase(name: str):
    """Attribute collective traffic registered inside to phase `name`."""
    c = _comm()
    c.phase_stack.append(name)
    try:
        yield
    finally:
        c.phase_stack.pop()
        c.opens[name] = c.opens.get(name, 0) + 1


def account_collective(op: str, nbytes: int, shape=None) -> None:
    """Register one traced collective of `nbytes` payload per device.

    `shape` is the traced payload shape (static at trace time); passing
    it keys the account by (phase, op, shape) so a shape-bucket retrace
    lands in its own row."""
    c = _comm()
    if not c.phase_stack:
        return
    phase = c.phase_stack[-1]
    key = (phase, op, tuple(int(d) for d in shape) if shape else ())
    entry = c.log.get(key)
    if entry is None:
        entry = c.log[key] = [0, 0]
        from .. import telemetry

        telemetry.event(
            "jit-trace",
            phase=phase,
            op=op,
            shape=list(key[2]),
            retrace=any(
                k[0] == phase and k[1] == op and k is not key
                for k in c.log
            ),
        )
    entry[0] += 1
    entry[1] += int(nbytes)
    from ..telemetry import metrics

    if metrics.enabled():
        metrics.inc(
            "kmp_comm_bytes_total",
            "Traced collective payload bytes per device, by phase "
            "(trace-time account; see COMM_CAVEAT).",
            value=int(nbytes), phase=phase,
        )
        metrics.inc(
            "kmp_comm_calls_total",
            "Traced collective calls, by phase (trace-time account).",
            phase=phase,
        )


def reset_comm_log() -> None:
    """Clear THIS run's account (kept for callers that re-measure
    within one run; a new run gets a fresh log via its RunState)."""
    c = _comm()
    c.log.clear()
    c.opens.clear()


def phase_opens() -> Dict[str, int]:
    """How many times each comm_phase scope was opened (run-report
    `comm.phase_opens`; compare against per-phase traced_calls to spot
    executable-cache reuse)."""
    return dict(_comm().opens)


def cache_hit_phases() -> List[str]:
    """Phases that were opened but traced NO collective: their programs
    were executable-cache hits, so the account shows zero bytes for
    traffic that really happened."""
    c = _comm()
    traced = {phase for (phase, _op, _shape) in c.log}
    return sorted(p for p in c.opens if p not in traced)


def comm_records() -> List[dict]:
    """The account as structured rows (run-report `comm.records`)."""
    return [
        {
            "phase": phase,
            "op": op,
            "shape": list(shape),
            "traced_calls": calls,
            "payload_bytes_per_device": nbytes,
        }
        for (phase, op, shape), (calls, nbytes)
        in sorted(_comm().log.items())
    ]


def comm_phase_totals() -> Dict[str, Dict[str, int]]:
    """Per-phase rollup of the account ({phase: {bytes_total, calls}})
    — the run report's `comm.phases` rows and the MULTICHIP bench
    line's per-phase keys."""
    totals: Dict[str, Dict[str, int]] = {}
    for (phase, _op, _shape), (calls, nbytes) in sorted(
        _comm().log.items()
    ):
        t = totals.setdefault(phase, {"bytes_total": 0, "calls": 0})
        t["bytes_total"] += int(nbytes)
        t["calls"] += int(calls)
    return totals


def comm_table() -> str:
    """Render the per-phase collective account (traced ops; for ops
    inside round loops the figures are per round per device).  Phases
    whose scope was opened but traced nothing are listed explicitly as
    cache hits instead of being indistinguishable from silent phases."""
    c = _comm()
    hit_phases = cache_hit_phases()
    if not c.log and not hit_phases:
        return "(comm accounting: no collectives traced)"
    lines = [
        f"(caveat: {COMM_CAVEAT})",
        "phase | collective | traced shape | traced calls | "
        "payload bytes/device",
    ]
    phase_calls: Dict[str, int] = {}
    for (phase, op, shape), (calls, nbytes) in sorted(c.log.items()):
        shp = "x".join(str(d) for d in shape) if shape else "-"
        lines.append(f"{phase} | {op} | {shp} | {calls} | {nbytes}")
        phase_calls[phase] = phase_calls.get(phase, 0) + calls
    # opens > total traced calls PROVES at least one opening traced
    # nothing (per-row comparison would mislabel a phase that traces a
    # different shape on each opening); one summary line per such phase
    for phase, total in sorted(phase_calls.items()):
        opens = c.opens.get(phase, 0)
        if opens > total:
            lines.append(
                f"{phase} | (partly cache-hit: opened {opens}x, traced "
                f"{total} call(s); remaining openings reused cached "
                f"executables) | - | 0 | 0"
            )
    for phase in hit_phases:
        lines.append(
            f"{phase} | (cache-hit: executable reused, traffic not "
            f"re-traced) | - | 0 | 0 (opened {c.opens[phase]}x)"
        )
    return "\n".join(lines)


def throttled_local_capacity(
    target_l: jax.Array,
    node_w_l: jax.Array,
    weights: jax.Array,
    cap: jax.Array,
    axis_name=NODE_AXIS,
) -> jax.Array:
    """Cross-device capacity throttle (the control_cluster_weights analog,
    kaminpar-dist/.../global_lp_clusterer.cc:429): each device sums the
    weight its movers demand per target bucket, the demands are `psum`'d,
    and the device's local capacity share is scaled by headroom/demand —
    so the *total* weight accepted across devices provably stays within
    headroom.  The 1-1e-6 factor guards float rounding in the scale; the
    demand<=headroom fast path keeps the common case exact.

    Returns the per-bucket local capacity to feed accept_prefix_by_capacity.
    Shared by the batched and colored distributed LP refiners.
    """
    C = cap.shape[0]
    demand_l = jax.ops.segment_sum(
        jnp.where(target_l >= 0, node_w_l, 0).astype(ACC_DTYPE),
        jnp.clip(target_l, 0, C - 1),
        num_segments=C,
    )
    account_collective(
        "psum(cluster-demand)",
        demand_l.size * demand_l.dtype.itemsize,
        shape=demand_l.shape,
    )
    demand = lax.psum(demand_l, axis_name)
    headroom = jnp.maximum(cap - weights.astype(ACC_DTYPE), 0)
    frac = headroom.astype(jnp.float32) / jnp.maximum(demand, 1).astype(
        jnp.float32
    )
    scaled = jnp.floor(
        demand_l.astype(jnp.float32) * jnp.minimum(frac, 1.0) * (1.0 - 1e-6)
    ).astype(ACC_DTYPE)
    local_cap = jnp.where(demand <= headroom, demand_l, scaled)
    return jnp.minimum(local_cap, headroom)


def halo_exchange(
    vals_l: jax.Array,
    send_idx_l: jax.Array,
    recv_map_l: jax.Array,
    g_loc: int,
    axis_name=NODE_AXIS,
) -> jax.Array:
    """Interface→ghost value exchange (the synchronize_ghost_node_* sparse
    alltoall of the reference, kaminpar-dist/graphutils/communication.h:242)
    as one static-shape XLA all_to_all.

    Per device inside shard_map: gather the owned values each peer needs
    (send_idx_l[p] = local indices destined to peer p, pad -1), all_to_all
    the [D, s_max] buffer, scatter received values into ghost slots
    (recv_map_l[p][j] = ghost slot of peer p's j-th value; pad g_loc is
    dropped).  Collective volume O(interface), not O(n).

    `vals_l` may be [n_loc] (one value per node) or stacked [C, n_loc] —
    several per-node quantities share one collective launch (per-launch
    latency dominates on small interfaces).  Returns [g_loc] or
    [C, g_loc] accordingly.
    """
    stacked = vals_l.ndim == 2
    v = vals_l if stacked else vals_l[None]
    n_loc = v.shape[1]
    sendbuf = v[:, jnp.clip(send_idx_l, 0, n_loc - 1)]  # [C, D, s_max]
    account_collective(
        "all_to_all(halo)",
        sendbuf.size * sendbuf.dtype.itemsize,
        shape=sendbuf.shape,
    )
    recvbuf = lax.all_to_all(sendbuf, axis_name, 1, 1, tiled=True)
    out = (
        jnp.zeros((v.shape[0], g_loc), v.dtype)
        .at[:, recv_map_l.reshape(-1)]
        .set(recvbuf.reshape(v.shape[0], -1), mode="drop")
    )
    return out if stacked else out[0]


def make_mesh(
    n_devices: Optional[object] = None,
    devices: Optional[Sequence[jax.Device]] = None,
    axis_names: Tuple[str, str] = NODE_AXIS,
) -> Mesh:
    """(X, Y) device mesh over which the node space is sharded.

    `n_devices` is either an int D (a flat (1, D) grid — the common
    single-axis case) or a (rows, cols) tuple for a genuine 2D grid.
    For 2D grids `jax.experimental.mesh_utils` assigns devices
    topology-aware where it can, so the two named axes ride the two
    physical ICI axes and every cross-mesh collective decomposes into
    the row/column exchange pattern of the reference's grid alltoall
    (kaminpar-mpi/grid_alltoall.h:1-45) inside XLA.
    """
    explicit_devices = devices is not None
    if devices is None:
        from ..utils import platform

        devices = platform.devices()
    if isinstance(n_devices, tuple):
        rows, cols = n_devices
        if len(devices) < rows * cols:
            raise ValueError(
                f"need {rows * cols} devices, have {len(devices)}"
            )
        if explicit_devices:
            # the caller picked the devices (and their order): honor it
            grid = np.asarray(devices[: rows * cols]).reshape(rows, cols)
            return Mesh(grid, axis_names)
        from jax.experimental import mesh_utils

        try:
            grid = np.asarray(mesh_utils.create_device_mesh((rows, cols)))
        except (AssertionError, ValueError, NotImplementedError):
            grid = np.asarray(devices[: rows * cols]).reshape(rows, cols)
        return Mesh(grid, axis_names)
    if n_devices is not None:
        if len(devices) < n_devices:
            raise ValueError(
                f"need {n_devices} devices, have {len(devices)}; on CPU set "
                f"XLA_FLAGS=--xla_force_host_platform_device_count={n_devices}"
            )
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices).reshape(1, -1), axis_names)


def make_torus_mesh(
    rows: int,
    cols: int,
    axis_names: Tuple[str, str] = NODE_AXIS,
) -> Mesh:
    """A (rows, cols) 2D ICI-torus mesh — make_mesh((rows, cols))."""
    return make_mesh((rows, cols), axis_names=axis_names)
