"""Distributed cluster balancer over the device mesh.

Analog of the reference's ClusterBalancer
(kaminpar-dist/refinement/balancer/cluster_balancer.cc, move-cluster
construction in balancer/clusters.cc): when single-node moves cannot
rebalance an overloaded block — a border node is too heavy, or every
individual move has prohibitive loss — whole *move clusters* of connected
nodes are relocated at once.

The reference builds move clusters locally per PE (clusters.cc; clusters
never span PEs) and selects moves globally through per-block priority
queues merged over a binary reduction tree.  The TPU redesign keeps both
halves but expresses them bulk-synchronously:

  build    per device, a few LP-style merge rounds agglomerate the owned
           nodes of overloaded blocks into clusters no heavier than the
           per-block shed limit — the segmented-reduction form of
           clusters.cc's greedy cluster growing.  Clusters never span
           devices or blocks, exactly like the reference's.

  rate     per cluster: connection weight to every adjacent block via one
           aggregate_by_key keyed by (cluster leader, neighbor block);
           intra-cluster edges are excluded (they move with the cluster),
           edges to the home block are the loss term (the reference's
           cluster gain, cluster_balancer.cc ClustersMemoryContext).

  select   each device locally sorts out its TOP-T cluster candidates by
           relative gain (the per-PE priority queue) and all_gathers the
           [T] candidate tuples — O(D*T) volume, not O(n); every device
           runs the identical capacity-respecting prefix commit
           (ops/segments.accept_prefix_by_capacity) — the collective
           replacement for the reduction tree + rank-0 pick + broadcast.

  apply    members adopt their leader's accepted target locally (clusters
           never span devices); one O(interface) mesh.halo_exchange
           republishes the changed labels to ghosts.  The single O(n)
           all_gather runs at loop exit.

Used by the hybrid refinement pipeline when the node balancer alone cannot
reach feasibility (factories.cc HYBRID_CLUSTER_BALANCER lineage).
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

# version-portable shard_map (mesh.shard_map_compat): the
# replication-check flag is spelled check_vma / check_rep depending on
# the installed jax — the compat shim keeps every dist kernel usable on
# both instead of dying with a TypeError at the first collective
from .mesh import shard_map_compat as _shard_map

from ..ops.balancer import relative_gain_key
from ..ops.segments import (
    ACC_DTYPE,
    accept_prefix_by_capacity,
    aggregate_by_key,
    argmax_per_segment,
    hash_u32,
)
from .dist_balancer import topk_candidate_commit
from .dist_graph import DistGraph
from .mesh import NODE_AXIS, halo_exchange


def _build_local_clusters(
    src_l, dst_l, ew_l, nw_l, offset, n_loc, part_l, part_tab,
    in_overloaded, limit_of_block, k, salt, merge_rounds, dstloc_c,
):
    """Agglomerate owned overloaded-block nodes into move clusters.

    Returns i32[n_loc] cluster labels in *global node id* space: every
    participating node points at a leader owned by this device, within its
    own block; non-participants keep label -1.  Cluster weight never
    exceeds the block's shed limit (`limit_of_block`), mirroring the
    reference's cluster size strategy (clusters.cc build options).
    """
    node_ids_l = offset + jnp.arange(n_loc, dtype=jnp.int32)
    # local-local edges inside one overloaded block participate in merging
    dst_local = (dst_l >= offset) & (dst_l < offset + n_loc)
    seg = jnp.clip(src_l - offset, 0, n_loc - 1)
    labels = jnp.where(in_overloaded, node_ids_l, -1)
    # per-cluster weight, indexed by local leader slot
    cw = jnp.where(in_overloaded, nw_l, 0).astype(ACC_DTYPE)
    limit_l = limit_of_block[jnp.clip(part_l, 0, k - 1)]

    def body(i, state):
        labels, cw = state
        rsalt = (salt + i * jnp.int32(0x9E37)) & 0x7FFFFFFF
        lab_src = labels[seg]
        lab_dst = jnp.where(
            dst_local,
            labels[jnp.clip(dst_l - offset, 0, n_loc - 1)],
            -1,
        )
        same_block = dst_local & (part_tab[dstloc_c] == part_l[seg])
        # rate cluster-to-cluster: rows live at the *leader's* slot, so a
        # cluster weighs all its members' edges when picking a merge target
        key = jnp.where(
            same_block & (lab_dst >= 0) & (lab_src >= 0) & (lab_dst != lab_src),
            lab_dst,
            -1,
        )
        seg_m = jnp.where(key >= 0, lab_src - offset, -1)
        seg_g, key_g, w_g = aggregate_by_key(seg_m, key, ew_l)
        seg_gc = jnp.clip(seg_g, 0, n_loc - 1)
        my_lab = seg_g + offset  # group rows sit at leader slots
        fits = (
            cw[jnp.clip(key_g - offset, 0, n_loc - 1)] + cw[seg_gc]
            <= limit_l[seg_gc]
        )
        # hashed merge direction: 2-cycles become merges, not swaps
        dir_ok = hash_u32(key_g, rsalt) < hash_u32(my_lab, rsalt)
        feasible = (seg_g >= 0) & (key_g >= 0) & fits & dir_ok
        best, _ = argmax_per_segment(
            seg_g, key_g, w_g, n_loc, tie_salt=rsalt, feasible=feasible
        )
        is_leader = labels == node_ids_l
        wants = is_leader & (best >= 0)
        # accept under the target cluster's remaining limit headroom, so
        # simultaneous joins cannot blow past the shed limit
        headroom = jnp.maximum(limit_l - cw, 0)
        target_slot = jnp.where(wants, best - offset, -1)
        prio = hash_u32(node_ids_l, rsalt ^ 0x7F4A7C15)
        accept = accept_prefix_by_capacity(target_slot, prio, cw, headroom)
        # break chains: if the target leader itself joins someone this
        # round, cancel joins into it — accepted joins then have depth 1
        # and members can follow with a single pointer hop
        accept = accept & ~accept[jnp.clip(best - offset, 0, n_loc - 1)]
        new_leader_of_leader = jnp.where(accept, best, node_ids_l)
        lab_c = jnp.clip(labels - offset, 0, n_loc - 1)
        new_labels = jnp.where(
            labels >= 0, new_leader_of_leader[lab_c], labels
        )
        new_cw = jax.ops.segment_sum(
            jnp.where(new_labels >= 0, nw_l, 0).astype(ACC_DTYPE),
            jnp.clip(new_labels - offset, 0, n_loc - 1),
            num_segments=n_loc,
        )
        return new_labels, new_cw

    labels, cw = lax.fori_loop(0, merge_rounds, body, (labels, cw))
    return labels, cw


CLUSTER_CANDIDATES_PER_DEVICE = 2048


def dist_cluster_balance_round(
    src_l, dst_l, dstloc_l, ew_l, nw_l, n, part_l, ghost_part,
    send_idx_l, recv_map_l, k, cap, salt, merge_rounds,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """One cluster-balancing round inside shard_map: build clusters, rate,
    top-T candidate gather + identical commit, apply locally.  Operates on
    the owner-sharded partition; returns (part_l, ghost_part, #moved,
    still_overloaded)."""
    n_loc = nw_l.shape[0]
    g_loc = ghost_part.shape[0]
    d = lax.axis_index(NODE_AXIS)
    offset = (d * n_loc).astype(jnp.int32)
    node_ids_l = offset + jnp.arange(n_loc, dtype=jnp.int32)
    seg = src_l - offset
    dstloc_c = jnp.clip(dstloc_l, 0, n_loc + g_loc - 1)
    part_tab = jnp.concatenate([part_l, ghost_part])

    bw = lax.psum(
        jax.ops.segment_sum(
            nw_l.astype(ACC_DTYPE), jnp.clip(part_l, 0, k - 1), num_segments=k
        ),
        NODE_AXIS,
    )
    overload = jnp.maximum(bw - cap, 0)
    headroom = jnp.maximum(cap - bw, 0)
    max_headroom = jnp.max(headroom)
    # shed limit: clusters heavier than the block's overload overshoot the
    # rebalance; heavier than every target's headroom are unplaceable
    limit_of_block = jnp.maximum(jnp.minimum(overload, max_headroom), 1)

    in_overloaded = (overload[jnp.clip(part_l, 0, k - 1)] > 0) & (
        node_ids_l < n
    )

    labels_l, cw_l = _build_local_clusters(
        src_l, dst_l, ew_l, nw_l, offset, n_loc, part_l, part_tab,
        in_overloaded, limit_of_block, k, salt, merge_rounds, dstloc_c,
    )

    # -- rate clusters against adjacent blocks ---------------------------
    seg_c = jnp.clip(seg, 0, n_loc - 1)
    lab_of_src = labels_l[seg_c]
    dst_local = (dst_l >= offset) & (dst_l < offset + n_loc)
    lab_of_dst = jnp.where(
        dst_local, labels_l[jnp.clip(dst_l - offset, 0, n_loc - 1)], -2
    )
    intra = (lab_of_src >= 0) & (lab_of_dst == lab_of_src)
    # rating rows live at the *leader's* local slot
    leader_slot = jnp.where(lab_of_src >= 0, lab_of_src - offset, -1)
    key_block = jnp.where(
        (lab_of_src >= 0) & ~intra & (dst_l < n), part_tab[dstloc_c], -1
    )
    seg_m = jnp.where(key_block >= 0, leader_slot, -1)
    seg_g, key_g, w_g = aggregate_by_key(seg_m, key_block, ew_l)
    seg_gc = jnp.clip(seg_g, 0, n_loc - 1)
    key_gc = jnp.clip(key_g, 0, k - 1)

    own_block = part_l[seg_gc]
    is_leader = (labels_l == node_ids_l) & (labels_l >= 0)
    tgt_ok = (
        (seg_g >= 0)
        & (key_g >= 0)
        & (key_g != own_block)
        & (overload[key_gc] == 0)
        & (cw_l[seg_gc] <= headroom[key_gc])
    )
    best, best_w = argmax_per_segment(
        seg_g, key_g, w_g, n_loc, tie_salt=salt ^ 0x2545F, feasible=tgt_ok
    )
    # loss term: external connection to the home block
    own_match = (seg_g >= 0) & (key_g == own_block)
    w_own = jax.ops.segment_max(
        jnp.where(own_match, w_g, 0),
        jnp.where(own_match, seg_g, n_loc),
        num_segments=n_loc + 1,
    )[:n_loc]
    w_own = jnp.maximum(w_own, 0)

    # a cluster with no adjacent feasible block may still shed into the
    # max-headroom block if it fits (the balancer's zero-gain fallback)
    fallback = jnp.argmax(headroom).astype(jnp.int32)
    fb_ok = (cw_l <= headroom[fallback]) & (fallback != part_l) & (
        overload[fallback] == 0
    )
    use_fb = (best < 0) & fb_ok
    target_l = jnp.where(use_fb, fallback, best)
    gain_l = jnp.where(use_fb, -w_own, best_w - w_own)

    cand = is_leader & (target_l >= 0)
    target_l = jnp.where(cand, target_l, -1)
    gain_l = jnp.where(cand, gain_l, 0)
    cwc_l = jnp.where(cand, cw_l, 0)

    # -- shared top-T gather + identical commit (see dist_balancer) ------
    order_l = -relative_gain_key(gain_l, cwc_l)
    T = min(CLUSTER_CANDIDATES_PER_DEVICE, n_loc)
    do, tgt_T, lid_T, accept, cw_g, tgt_g, src_block = topk_candidate_commit(
        target_l, order_l, cwc_l, part_l, overload, headroom, T, k, d,
    )

    # -- apply: members follow their leader (always local) ---------------
    accepted_leader = (
        jnp.zeros(n_loc, dtype=jnp.bool_)
        .at[lid_T]
        .set(do, mode="drop")
    )
    tgt_of_leader = (
        jnp.full(n_loc, -1, dtype=jnp.int32)
        .at[lid_T]
        .set(jnp.where(do, tgt_T, -1), mode="drop")
    )
    lab_slot = jnp.clip(labels_l - offset, 0, n_loc - 1)
    member_moves = (labels_l >= 0) & accepted_leader[lab_slot]
    new_part_l = jnp.where(
        member_moves, jnp.clip(tgt_of_leader[lab_slot], 0, k - 1), part_l
    )
    new_ghost = halo_exchange(new_part_l, send_idx_l, recv_map_l, g_loc)
    moved = jnp.sum(accept.astype(jnp.int32))
    # post-move block weights from the gathered accepted candidates —
    # saves the cond() a second cross-device weight reduction
    moved_w = jnp.where(accept, cw_g, 0)
    delta_in = jax.ops.segment_sum(
        moved_w, jnp.clip(tgt_g, 0, k - 1), num_segments=k
    )
    delta_out = jax.ops.segment_sum(
        moved_w, jnp.clip(src_block, 0, k - 1), num_segments=k
    )
    still_overloaded = jnp.any(bw - delta_out + delta_in > cap)
    return new_part_l, new_ghost, moved, still_overloaded


@partial(
    jax.jit, static_argnames=("mesh", "k", "max_rounds", "merge_rounds")
)
def _dist_cluster_balance_impl(
    mesh, graph, partition, k, cap, seed, max_rounds, merge_rounds
):
    def per_device(src_l, dst_l, dstloc_l, ew_l, nw_l, n, ghost_gid_l,
                   send_idx_l, recv_map_l, part0, cap, seed):
        n_loc = nw_l.shape[0]
        d = lax.axis_index(NODE_AXIS)
        offset = (d * n_loc).astype(jnp.int32)
        part_l0 = lax.dynamic_slice(part0, (offset,), (n_loc,))
        ghost0 = part0[jnp.clip(ghost_gid_l, 0, part0.shape[0] - 1)]

        def cond(state):
            i, _, _, moved, still_overloaded = state
            return (i < max_rounds) & (moved != 0) & still_overloaded

        def body(state):
            i, part_l, ghost, _, _ = state
            salt = (seed.astype(jnp.int32) * 48611 + i * 104729) & 0x7FFFFFFF
            part_l, ghost, moved, still = dist_cluster_balance_round(
                src_l, dst_l, dstloc_l, ew_l, nw_l, n, part_l, ghost,
                send_idx_l, recv_map_l, k, cap, salt, merge_rounds,
            )
            return (i + 1, part_l, ghost, moved, still)

        _, part_l, _, _, _ = lax.while_loop(
            cond, body,
            (jnp.int32(0), part_l0, ghost0, jnp.int32(1), jnp.array(True)),
        )
        # ONE O(n) gather at loop exit
        return lax.all_gather(part_l, NODE_AXIS, tiled=True)

    return _shard_map(
        per_device,
        mesh=mesh,
        in_specs=(
            P(NODE_AXIS), P(NODE_AXIS), P(NODE_AXIS), P(NODE_AXIS),
            P(NODE_AXIS), P(), P(NODE_AXIS), P(NODE_AXIS), P(NODE_AXIS),
            P(), P(), P(),
        ),
        out_specs=P(),
        check_vma=False,
    )(
        graph.src, graph.dst, graph.dst_local, graph.edge_w, graph.node_w,
        graph.n, graph.ghost_gid, graph.send_idx, graph.recv_map,
        partition, cap, seed,
    )


def dist_cluster_balance(
    graph: DistGraph,
    partition: jax.Array,
    k: int,
    max_block_weights,
    seed,
    max_rounds: int = 8,
    merge_rounds: int = 3,
) -> jax.Array:
    """Rebalance by moving whole clusters of nodes (ClusterBalancer
    analog, kaminpar-dist/refinement/balancer/cluster_balancer.cc).
    No-op on already-feasible partitions.  Returns the replicated
    partition."""
    return _dist_cluster_balance_impl(
        graph.src.sharding.mesh,
        graph,
        jnp.asarray(partition, jnp.int32),
        k,
        jnp.asarray(max_block_weights, ACC_DTYPE),
        jnp.asarray(seed),
        max_rounds,
        merge_rounds,
    )
