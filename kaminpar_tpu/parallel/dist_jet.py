"""Distributed Jet refinement over the device mesh.

Analog of the reference's distributed Jet refiner
(kaminpar-dist/refinement/jet/jet_refiner.cc), which runs the same
find/filter/execute/rebalance scheme as the shared-memory Jet
(see ops/jet.py) with ghost-synchronized block IDs.  Bulk-synchronous Jet
is already the natural fit for SPMD; the partition state is OWNER-SHARDED
(part_l i32[n_loc] + ghost slice i32[g_loc]) and every per-iteration
collective is O(interface) or O(k):

  1. find: candidate moves for owned nodes from the local edge shard +
     ghost block table (local segmented reductions);
  2. filter: the afterburner needs each interface neighbor's (candidate
     gain, destination) — one stacked mesh.halo_exchange, the reference's
     sparse alltoall (graphutils/communication.h:242);
  3. execute: accepted moves apply locally; one halo exchange republishes
     the changed labels to ghosts;
  4. rebalance with the distributed node balancer
     (parallel/dist_balancer.dist_balance_round — top-T candidate gather,
     O(D*T));
  5. best-partition snapshots by the psum'd edge cut, rollback at round
     end (jet_refiner.cc best-partition snapshots).

The one O(n) all_gather runs at loop exit.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

# version-portable shard_map (mesh.shard_map_compat): the
# replication-check flag is spelled check_vma / check_rep depending on
# the installed jax — the compat shim keeps every dist kernel usable on
# both instead of dying with a TypeError at the first collective
from .mesh import shard_map_compat as _shard_map

from ..context import JetRefinementContext
from ..telemetry import progress as progress_mod
from ..ops.segments import (
    ACC_DTYPE,
    INT32_MIN,
    aggregate_by_key,
    argmax_per_segment,
    connection_to_label,
)
from .dist_balancer import dist_balance_round
from .dist_graph import DistGraph
from .mesh import account_collective, NODE_AXIS, halo_exchange


def _local_cut(part_l, ghost_part, seg, dstloc_l, ew_l):
    """Global edge cut from the owner-sharded state: each undirected edge
    is stored at both endpoints, so the psum counts every cut edge twice."""
    n_loc = part_l.shape[0]
    tab = jnp.concatenate([part_l, ghost_part])
    own = part_l[jnp.clip(seg, 0, n_loc - 1)]
    nb = tab[jnp.clip(dstloc_l, 0, tab.shape[0] - 1)]
    local = jnp.sum(jnp.where(own != nb, ew_l, 0).astype(ACC_DTYPE))
    account_collective("psum(cut)", local.dtype.itemsize, shape=local.shape)
    return lax.psum(local, NODE_AXIS) // 2


def _jet_iteration_dist(
    src_l, dst_l, dstloc_l, ew_l, nw_l, n, part_l, ghost_part, lock_l,
    k, cap, gain_temp, salt, send_idx_l, recv_map_l,
):
    n_loc = nw_l.shape[0]
    g_loc = ghost_part.shape[0]
    d = lax.axis_index(NODE_AXIS)
    offset = (d * n_loc).astype(jnp.int32)
    node_ids_l = offset + jnp.arange(n_loc, dtype=jnp.int32)
    seg = src_l - offset
    seg_c = jnp.clip(seg, 0, n_loc - 1)
    dstloc_c = jnp.clip(dstloc_l, 0, n_loc + g_loc - 1)
    tab = jnp.concatenate([part_l, ghost_part])
    is_real_l = node_ids_l < n

    # ---- find (jet_refiner.cc:104-131) ----
    neigh_block = tab[dstloc_c]
    seg_g, key_g, w_g = aggregate_by_key(seg, neigh_block, ew_l)
    sgc = jnp.clip(seg_g, 0, n_loc - 1)
    is_ext = (seg_g >= 0) & (key_g != part_l[sgc])
    best, best_conn = argmax_per_segment(
        seg_g, key_g, w_g, n_loc, tie_salt=salt, feasible=is_ext
    )
    conn_own = connection_to_label(seg_g, key_g, w_g, part_l, n_loc)
    gain_l = best_conn - conn_own
    threshold = -jnp.floor(gain_temp * conn_own.astype(jnp.float32)).astype(
        jnp.int32
    )
    candidate_l = is_real_l & (best >= 0) & (lock_l == 0) & (gain_l > threshold)
    next_part_l = jnp.where(candidate_l, best, part_l)

    # ---- filter: afterburner — one stacked halo exchange publishes the
    # interface nodes' (candidate gain, destination) to their ghosts ----
    gain_cand_l = jnp.where(candidate_l, gain_l, INT32_MIN)
    ghost_gain, ghost_next = halo_exchange(
        jnp.stack([gain_cand_l, next_part_l]), send_idx_l, recv_map_l, g_loc
    )
    gain_tab = jnp.concatenate([gain_cand_l, ghost_gain])
    next_tab = jnp.concatenate([next_part_l, ghost_next])

    gain_u = gain_cand_l[seg_c]
    gain_v = gain_tab[dstloc_c]
    v_is_cand = gain_v > INT32_MIN
    # total order across devices: global ids break ties
    v_before_u = v_is_cand & (
        (gain_v > gain_u) | ((gain_v == gain_u) & (dst_l < src_l))
    )
    block_v = jnp.where(v_before_u, next_tab[dstloc_c], tab[dstloc_c])
    to_u = next_part_l[seg_c]
    from_u = part_l[seg_c]
    contrib = jnp.where(
        to_u == block_v, ew_l, jnp.where(from_u == block_v, -ew_l, 0)
    )
    adj_gain = jax.ops.segment_sum(
        jnp.where(candidate_l[seg_c], contrib, 0), seg_c, num_segments=n_loc
    )
    accept_l = candidate_l & (adj_gain > 0)

    # ---- execute ----
    new_part_l = jnp.where(accept_l, next_part_l, part_l)
    new_ghost = halo_exchange(new_part_l, send_idx_l, recv_map_l, g_loc)
    new_lock_l = accept_l.astype(jnp.int32)
    return new_part_l, new_ghost, new_lock_l


@partial(
    jax.jit,
    static_argnames=(
        "mesh", "k", "num_rounds", "max_iterations", "max_fruitless",
        "balancer_rounds", "record",
    ),
)
def _dist_jet_impl(
    mesh, graph, partition, k, cap, seed,
    initial_gain_temp, final_gain_temp, fruitless_threshold,
    num_rounds, max_iterations, max_fruitless, balancer_rounds,
    record=False,
):
    def per_device(src_l, dst_l, dstloc_l, ew_l, nw_l, n, ghost_gid_l,
                   send_idx_l, recv_map_l, part0, cap, seed):
        n_loc = nw_l.shape[0]
        d = lax.axis_index(NODE_AXIS)
        offset = (d * n_loc).astype(jnp.int32)
        seg = src_l - offset
        part_l0 = lax.dynamic_slice(part0, (offset,), (n_loc,))
        ghost0 = part0[jnp.clip(ghost_gid_l, 0, part0.shape[0] - 1)]

        def is_feasible(part_l):
            bw = lax.psum(
                jax.ops.segment_sum(
                    nw_l.astype(ACC_DTYPE),
                    jnp.clip(part_l, 0, k - 1),
                    num_segments=k,
                ),
                NODE_AXIS,
            )
            return jnp.all(bw <= cap)

        # best-partition snapshots track the best FEASIBLE cut; an
        # infeasible input must not pin the snapshot (its cut can be
        # arbitrarily low — e.g. everything in one block cuts nothing)
        best_cut0 = jnp.where(
            is_feasible(part_l0),
            _local_cut(part_l0, ghost0, seg, dstloc_l, ew_l),
            jnp.iinfo(ACC_DTYPE).max,
        )

        def round_body(rnd, carry):
            part_l, ghost, best_l, best_cut, round_stats = carry
            gain_temp = jnp.where(
                num_rounds > 1,
                initial_gain_temp
                + (final_gain_temp - initial_gain_temp)
                * rnd.astype(jnp.float32)
                / jnp.float32(max(num_rounds - 1, 1)),
                initial_gain_temp,
            )

            def iter_cond(state):
                i, fruitless, *_ = state
                return (i < max_iterations) & (fruitless < max_fruitless)

            def iter_body(state):
                (i, fruitless, part_l, ghost, lock_l, best_l, best_cut,
                 stats) = state
                salt = (
                    seed.astype(jnp.int32) * 31321
                    + rnd * 2221
                    + i * 1566083941
                ) & 0x7FFFFFFF
                part_l, ghost, lock_l = _jet_iteration_dist(
                    src_l, dst_l, dstloc_l, ew_l, nw_l, n, part_l, ghost,
                    lock_l, k, cap, gain_temp, salt, send_idx_l, recv_map_l,
                )

                # run the balancer to feasibility (or a dry round), not a
                # fixed count: a round moves at most D*T nodes, so big
                # post-move overloads need batching.  Feasible partitions
                # exit after the first (cheap) overload check.
                def bal_cond(state):
                    j, _, _, moved, still = state
                    return (j < 4 * balancer_rounds) & (moved != 0) & still

                def bal_body(state):
                    j, p, g_, _, _ = state
                    s = (salt + j * 7919) & 0x7FFFFFFF
                    p2, g2, moved, still = dist_balance_round(
                        src_l, dst_l, dstloc_l, ew_l, nw_l, n, p, g_,
                        send_idx_l, recv_map_l, k, cap, s,
                    )
                    return (j + 1, p2, g2, moved, still)

                _, part_l, ghost, _, _ = lax.while_loop(
                    bal_cond, bal_body,
                    (
                        jnp.int32(0), part_l, ghost, jnp.int32(1),
                        ~is_feasible(part_l),
                    ),
                )
                cut = _local_cut(part_l, ghost, seg, dstloc_l, ew_l)
                # sentinel-aware, as in ops/jet.py: until a feasible
                # partition exists, improvement = reaching feasibility
                has_best = best_cut < jnp.iinfo(ACC_DTYPE).max
                improved_enough = jnp.where(
                    has_best,
                    (best_cut - cut).astype(jnp.float32)
                    > (1.0 - fruitless_threshold)
                    * jnp.abs(best_cut).astype(jnp.float32),
                    is_feasible(part_l),
                )
                fruitless = jnp.where(improved_enough, 0, fruitless + 1)
                is_best = (cut <= best_cut) & is_feasible(part_l)
                best_l = jnp.where(is_best, part_l, best_l)
                best_cut = jnp.where(is_best, cut, best_cut)
                if stats is not None:  # trace-time guard (no extra carry)
                    # cut and fruitless are already psum'd/replicated, so
                    # the series adds NO collectives; rows are indexed by
                    # the global iteration across rounds
                    stats = progress_mod.record(
                        stats, rnd * max_iterations + i, cut, fruitless
                    )
                return (
                    i + 1, fruitless, part_l, ghost, lock_l, best_l,
                    best_cut, stats
                )

            lock0 = jnp.zeros(n_loc, dtype=jnp.int32)
            (_, _, part_l, ghost, _, best_l, best_cut,
             round_stats) = lax.while_loop(
                iter_cond,
                iter_body,
                (
                    jnp.int32(0), jnp.int32(0), part_l, ghost, lock0,
                    best_l, best_cut, round_stats,
                ),
            )
            # rollback to best; re-sync ghosts from it
            ghost_best = halo_exchange(best_l, send_idx_l, recv_map_l,
                                       ghost.shape[0])
            return (best_l, ghost_best, best_l, best_cut, round_stats)

        stats0 = (
            progress_mod.new_buffer(num_rounds * max_iterations, 2)
            if record else None
        )
        _, _, best_l, _, stats = lax.fori_loop(
            0, num_rounds, round_body,
            (part_l0, ghost0, part_l0, best_cut0, stats0),
        )
        # ONE O(n) gather at loop exit
        account_collective(
            "all_gather(partition)", best_l.size * 4, shape=best_l.shape
        )
        gathered = lax.all_gather(best_l, NODE_AXIS, tiled=True)
        if stats is None:
            return gathered
        return gathered, stats

    return _shard_map(
        per_device,
        mesh=mesh,
        in_specs=(
            P(NODE_AXIS), P(NODE_AXIS), P(NODE_AXIS), P(NODE_AXIS),
            P(NODE_AXIS), P(), P(NODE_AXIS), P(NODE_AXIS), P(NODE_AXIS),
            P(), P(), P(),
        ),
        out_specs=(P(), P()) if record else P(),
        check_vma=False,
    )(
        graph.src, graph.dst, graph.dst_local, graph.edge_w, graph.node_w,
        graph.n, graph.ghost_gid, graph.send_idx, graph.recv_map,
        partition, cap, seed,
    )


def dist_jet_refine(
    graph: DistGraph,
    partition: jax.Array,
    k: int,
    max_block_weights,
    seed,
    ctx: JetRefinementContext | None = None,
    level: int = 0,
    balancer_rounds: int = 4,
) -> jax.Array:
    """Distributed Jet refinement entry point (dist jet_refiner.cc analog);
    temperature schedule picked by level like the shm version."""
    if ctx is None:
        ctx = JetRefinementContext()
    if level > 0:
        rounds = ctx.num_rounds_on_coarse_level
        t0, t1 = (
            ctx.initial_gain_temp_on_coarse_level,
            ctx.final_gain_temp_on_coarse_level,
        )
    else:
        rounds = ctx.num_rounds_on_fine_level
        t0, t1 = (
            ctx.initial_gain_temp_on_fine_level,
            ctx.final_gain_temp_on_fine_level,
        )
    max_iterations = ctx.num_iterations if ctx.num_iterations > 0 else 64
    max_fruitless = (
        ctx.num_fruitless_iterations
        if ctx.num_fruitless_iterations > 0
        else 2**30
    )
    return progress_mod.instrumented(
        lambda rec: _dist_jet_impl(
            graph.src.sharding.mesh,
            graph,
            jnp.clip(jnp.asarray(partition, jnp.int32), 0, k - 1),
            k,
            jnp.asarray(max_block_weights, ACC_DTYPE),
            jnp.asarray(seed),
            jnp.float32(t0),
            jnp.float32(t1),
            jnp.float32(ctx.fruitless_threshold),
            int(rounds),
            int(max_iterations),
            int(max_fruitless),
            int(balancer_rounds),
            record=rec,
        ),
        "dist-jet", ("cut", "fruitless"),
        rounds=int(rounds), iterations_per_round=int(max_iterations),
    )
