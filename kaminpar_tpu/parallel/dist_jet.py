"""Distributed Jet refinement over the device mesh.

Analog of the reference's distributed Jet refiner
(kaminpar-dist/refinement/jet/jet_refiner.cc), which runs the same
find/filter/execute/rebalance scheme as the shared-memory Jet
(see ops/jet.py) with ghost-synchronized block IDs.  Bulk-synchronous Jet
is already the natural fit for SPMD: per iteration each device

  1. finds candidate moves for its owned nodes from the replicated
     partition (local segmented reductions over its edge shard);
  2. publishes per-node (candidate gain) via `all_gather` — the ghost sync
     that the reference does with a sparse alltoall — and runs the
     afterburner filter locally (each edge is stored at both endpoints, so
     every device sees all edges incident to its nodes);
  3. executes accepted moves and republishes the label slices;
  4. rebalances with the distributed node balancer
     (parallel/dist_balancer.dist_balance_round);
  5. tracks the best partition by the psum'd edge cut and rolls back to it
     at the end of each round (jet_refiner.cc best-partition snapshots).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

try:  # jax >= 0.6
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

from ..context import JetRefinementContext
from ..ops.segments import (
    ACC_DTYPE,
    INT32_MIN,
    aggregate_by_key,
    argmax_per_segment,
    connection_to_label,
)
from .dist_balancer import dist_balance_round
from .dist_graph import DistGraph
from .mesh import NODE_AXIS


def _local_cut(part, src_l, dst_l, ew_l):
    """Global edge cut: each undirected edge is stored at both endpoints,
    so the psum of local sums counts every cut edge twice."""
    local = jnp.sum(
        jnp.where(part[src_l] != part[dst_l], ew_l, 0).astype(ACC_DTYPE)
    )
    return lax.psum(local, NODE_AXIS) // 2


def _jet_iteration_dist(
    src_l, dst_l, ew_l, nw_l, n, part, lock_l, k, cap, gain_temp, salt
):
    n_loc = nw_l.shape[0]
    d = lax.axis_index(NODE_AXIS)
    offset = (d * n_loc).astype(jnp.int32)
    node_ids_l = offset + jnp.arange(n_loc, dtype=jnp.int32)
    seg = src_l - offset
    part_l = lax.dynamic_slice(part, (offset,), (n_loc,))
    is_real_l = node_ids_l < n

    # ---- find (jet_refiner.cc:104-131) ----
    neigh_block = part[dst_l]
    seg_g, key_g, w_g = aggregate_by_key(seg, neigh_block, ew_l)
    seg_c = jnp.clip(seg_g, 0, n_loc - 1)
    is_ext = (seg_g >= 0) & (key_g != part_l[seg_c])
    best, best_conn = argmax_per_segment(
        seg_g, key_g, w_g, n_loc, tie_salt=salt, feasible=is_ext
    )
    conn_own = connection_to_label(seg_g, key_g, w_g, part_l, n_loc)
    gain_l = best_conn - conn_own
    threshold = -jnp.floor(gain_temp * conn_own.astype(jnp.float32)).astype(
        jnp.int32
    )
    candidate_l = is_real_l & (best >= 0) & (lock_l == 0) & (gain_l > threshold)
    next_part_l = jnp.where(candidate_l, best, part_l)

    # ---- filter: afterburner needs every candidate's (gain, destination)
    # — the ghost sync, here two tiled all_gathers ----
    gain_full = lax.all_gather(
        jnp.where(candidate_l, gain_l, INT32_MIN), NODE_AXIS, tiled=True
    )
    next_part = lax.all_gather(next_part_l, NODE_AXIS, tiled=True)

    gain_u = gain_full[src_l]
    gain_v = gain_full[dst_l]
    v_is_cand = gain_v > INT32_MIN
    v_before_u = v_is_cand & (
        (gain_v > gain_u) | ((gain_v == gain_u) & (dst_l < src_l))
    )
    block_v = jnp.where(v_before_u, next_part[dst_l], part[dst_l])
    to_u = next_part[src_l]
    from_u = part[src_l]
    contrib = jnp.where(
        to_u == block_v, ew_l, jnp.where(from_u == block_v, -ew_l, 0)
    )
    adj_gain = jax.ops.segment_sum(
        jnp.where(candidate_l[jnp.clip(seg, 0, n_loc - 1)], contrib, 0),
        jnp.clip(seg, 0, n_loc - 1),
        num_segments=n_loc,
    )
    accept_l = candidate_l & (adj_gain > 0)

    # ---- execute ----
    new_part_l = jnp.where(accept_l, next_part_l, part_l)
    new_part = lax.all_gather(new_part_l, NODE_AXIS, tiled=True)
    new_lock_l = accept_l.astype(jnp.int32)
    return new_part, new_lock_l


@partial(
    jax.jit,
    static_argnames=(
        "mesh", "k", "num_rounds", "max_iterations", "max_fruitless",
        "balancer_rounds",
    ),
)
def _dist_jet_impl(
    mesh, graph, partition, k, cap, seed,
    initial_gain_temp, final_gain_temp, fruitless_threshold,
    num_rounds, max_iterations, max_fruitless, balancer_rounds,
):
    def per_device(src_l, dst_l, ew_l, nw_l, n, part0, cap, seed):
        n_loc = nw_l.shape[0]
        d = lax.axis_index(NODE_AXIS)
        offset = (d * n_loc).astype(jnp.int32)

        def is_feasible(part):
            part_l = lax.dynamic_slice(part, (offset,), (n_loc,))
            bw = lax.psum(
                jax.ops.segment_sum(
                    nw_l.astype(ACC_DTYPE),
                    jnp.clip(part_l, 0, k - 1),
                    num_segments=k,
                ),
                NODE_AXIS,
            )
            return jnp.all(bw <= cap)

        # best-partition snapshots track the best FEASIBLE cut; an
        # infeasible input must not pin the snapshot (its cut can be
        # arbitrarily low — e.g. everything in one block cuts nothing)
        best0 = part0
        best_cut0 = jnp.where(
            is_feasible(part0),
            _local_cut(part0, src_l, dst_l, ew_l),
            jnp.iinfo(ACC_DTYPE).max,
        )

        def round_body(rnd, carry):
            part, best, best_cut = carry
            gain_temp = jnp.where(
                num_rounds > 1,
                initial_gain_temp
                + (final_gain_temp - initial_gain_temp)
                * rnd.astype(jnp.float32)
                / jnp.float32(max(num_rounds - 1, 1)),
                initial_gain_temp,
            )

            def iter_cond(state):
                i, fruitless, *_ = state
                return (i < max_iterations) & (fruitless < max_fruitless)

            def iter_body(state):
                i, fruitless, part, lock_l, best, best_cut = state
                salt = (
                    seed.astype(jnp.int32) * 31321
                    + rnd * 2221
                    + i * 1566083941
                ) & 0x7FFFFFFF
                part, lock_l = _jet_iteration_dist(
                    src_l, dst_l, ew_l, nw_l, n, part, lock_l, k, cap,
                    gain_temp, salt,
                )

                def bal_body(j, p):
                    s = (salt + j * 7919) & 0x7FFFFFFF
                    p2, _ = dist_balance_round(
                        src_l, dst_l, ew_l, nw_l, n, p, k, cap, s
                    )
                    return p2

                part = lax.fori_loop(0, balancer_rounds, bal_body, part)
                cut = _local_cut(part, src_l, dst_l, ew_l)
                # sentinel-aware, as in ops/jet.py: until a feasible
                # partition exists, improvement = reaching feasibility
                has_best = best_cut < jnp.iinfo(ACC_DTYPE).max
                improved_enough = jnp.where(
                    has_best,
                    (best_cut - cut).astype(jnp.float32)
                    > (1.0 - fruitless_threshold)
                    * jnp.abs(best_cut).astype(jnp.float32),
                    is_feasible(part),
                )
                fruitless = jnp.where(improved_enough, 0, fruitless + 1)
                is_best = (cut <= best_cut) & is_feasible(part)
                best = jnp.where(is_best, part, best)
                best_cut = jnp.where(is_best, cut, best_cut)
                return (i + 1, fruitless, part, lock_l, best, best_cut)

            lock0 = jnp.zeros(n_loc, dtype=jnp.int32)
            (_, _, part, _, best, best_cut) = lax.while_loop(
                iter_cond,
                iter_body,
                (jnp.int32(0), jnp.int32(0), part, lock0, best, best_cut),
            )
            return (best, best, best_cut)

        _, best, _ = lax.fori_loop(
            0, num_rounds, round_body, (part0, best0, best_cut0)
        )
        return best

    return _shard_map(
        per_device,
        mesh=mesh,
        in_specs=(P(NODE_AXIS),) * 4 + (P(),) * 4,
        out_specs=P(),
        check_vma=False,
    )(
        graph.src, graph.dst, graph.edge_w, graph.node_w, graph.n,
        partition, cap, seed,
    )


def dist_jet_refine(
    graph: DistGraph,
    partition: jax.Array,
    k: int,
    max_block_weights,
    seed,
    ctx: JetRefinementContext | None = None,
    level: int = 0,
    balancer_rounds: int = 4,
) -> jax.Array:
    """Distributed Jet refinement entry point (dist jet_refiner.cc analog);
    temperature schedule picked by level like the shm version."""
    if ctx is None:
        ctx = JetRefinementContext()
    if level > 0:
        rounds = ctx.num_rounds_on_coarse_level
        t0, t1 = (
            ctx.initial_gain_temp_on_coarse_level,
            ctx.final_gain_temp_on_coarse_level,
        )
    else:
        rounds = ctx.num_rounds_on_fine_level
        t0, t1 = (
            ctx.initial_gain_temp_on_fine_level,
            ctx.final_gain_temp_on_fine_level,
        )
    max_iterations = ctx.num_iterations if ctx.num_iterations > 0 else 64
    max_fruitless = (
        ctx.num_fruitless_iterations
        if ctx.num_fruitless_iterations > 0
        else 2**30
    )
    return _dist_jet_impl(
        graph.src.sharding.mesh,
        graph,
        jnp.clip(jnp.asarray(partition, jnp.int32), 0, k - 1),
        k,
        jnp.asarray(max_block_weights, ACC_DTYPE),
        jnp.asarray(seed),
        jnp.float32(t0),
        jnp.float32(t1),
        jnp.float32(ctx.fruitless_threshold),
        int(rounds),
        int(max_iterations),
        int(max_fruitless),
        int(balancer_rounds),
    )
