"""Distributed bulk-synchronous label propagation over a device mesh.

The TPU re-design of the reference's distributed LP
(kaminpar-dist/distributed_label_propagation.h + coarsening/clustering/lp/
global_lp_clusterer.cc): where the reference interleaves local async LP
chunks with two communication steps per chunk —

  * `control_cluster_weights` (weight-delta sparse alltoall + allreduce,
    global_lp_clusterer.cc:429,174), and
  * `synchronize_ghost_node_clusters` (interface→PE sparse alltoall,
    global_lp_clusterer.cc:585-594)

— this kernel runs whole-graph bulk-synchronous rounds inside `shard_map`
where those two exchanges become exactly two XLA collectives per round:

  * a `psum` of per-cluster join demand + weight deltas (weight control),
  * an O(interface) halo exchange of the interface nodes' labels
    (mesh.halo_exchange — ghost sync; labels are owner-sharded, one
    all_gather runs at loop exit only).

Cluster-weight safety across devices uses demand throttling instead of the
reference's overshoot-and-rollback: each round every device computes its
local join demand per cluster, the global demand is `psum`'d, and each
device's local capacity share is scaled by headroom/demand before the
capacity-respecting prefix commit (ops/segments.accept_prefix_by_capacity).
Total accepted weight per cluster is then provably <= headroom, so the max
cluster weight is never exceeded — strictly stronger than the reference's
relaxed protocol, which tolerates transient overshoot.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

# version-portable shard_map (mesh.shard_map_compat): the
# replication-check flag is spelled check_vma / check_rep depending on
# the installed jax — the compat shim keeps every dist kernel usable on
# both instead of dying with a TypeError at the first collective
from .mesh import shard_map_compat as _shard_map

from ..ops.lp import LPConfig
from ..telemetry import progress as progress_mod
from ..ops.segments import (
    ACC_DTYPE,
    INT32_MIN,
    accept_prefix_by_capacity,
    aggregate_by_key,
    argmax_per_segment,
    best_from_dense,
    best_from_rating_table,
    dense_block_ratings,
    connection_to_label,
    connection_to_own_label,
    hash_u32,
    hashed_rating_table,
    move_weight_delta,
)
from .dist_graph import DistGraph
from .mesh import NODE_AXIS, halo_exchange, throttled_local_capacity


def _dist_lp_round(
    src_l: jax.Array,
    dst_l: jax.Array,
    dstloc_l: jax.Array,
    ew_l: jax.Array,
    nw_l: jax.Array,
    n: jax.Array,
    labels_l: jax.Array,
    ghost_lab: jax.Array,
    send_idx_l: jax.Array,
    recv_map_l: jax.Array,
    weights: jax.Array,
    cap: jax.Array,
    active_l: jax.Array,
    movable_l: jax.Array,
    salt: jax.Array,
    cfg: LPConfig,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array, jax.Array]:
    """One round, executed per device inside shard_map — ghost-halo model.

    Labels are OWNER-SHARDED: labels_l i32[n_loc] holds the owned nodes'
    labels, ghost_lab i32[g_loc] the (synchronized) labels of this
    device's ghost nodes, and the local label table concat(labels_l,
    ghost_lab) is indexed by dstloc_l.  Label values stay GLOBAL cluster
    ids.  The per-round collectives are the O(interface) halo exchanges
    (mesh.halo_exchange — the synchronize_ghost_node_clusters analog) and
    one dense psum of per-cluster weight deltas; nothing is all_gather'd.
    weights/cap i32[C] stay replicated (the dense-reduce weight-control
    tradeoff: a psum rides ICI at reduction bandwidth, while the
    reference's sparse owner messages have no static-shape XLA form).

    Returns (labels_l, ghost_lab, weights, active_l, num_wanting).
    """
    n_loc = nw_l.shape[0]
    g_loc = ghost_lab.shape[0]
    C = weights.shape[0]
    d = lax.axis_index(NODE_AXIS)
    offset = (d * n_loc).astype(jnp.int32)
    node_ids_l = offset + jnp.arange(n_loc, dtype=jnp.int32)
    lab_tab = jnp.concatenate([labels_l, ghost_lab])

    # -- rate: per-owned-node best cluster over the local edge shard,
    # same engine dispatch as the single-chip lp_round (ops/lp.py): the
    # device holds every edge of its owned nodes, so hashed winner sums
    # and dense tables are exact locally
    from ..ops.rating import select_engine

    neighbor_cluster = lab_tab[jnp.clip(dstloc_l, 0, n_loc + g_loc - 1)]
    seg = src_l - offset
    if cfg.rating == "sort2":
        # sort2 needs CSR row spans, which the sharded COO layout does not
        # carry — reject the explicit request rather than silently running
        # a different engine
        raise ValueError(
            "rating='sort2' is not available on the distributed path; "
            "use 'scatter', 'hash', 'sort', or 'auto'"
        )
    # the engine flag is trace-time static: cfg threads through
    # shard_map as a closure constant, so every device compiles the
    # same engine (row_spans=False removes the sort2 row engines)
    engine, _ = select_engine(
        cfg.rating, C, n_loc, src_l.shape[0],
        num_slots=cfg.num_slots, row_spans=False,
    )
    barred_l = jnp.zeros(n_loc, dtype=bool)
    if engine == "scatter":
        # scatter-add slot tables (ops/rating.py): each device holds
        # every edge of its owned nodes, so the per-row elimination
        # passes are exact locally; still-contested rows are barred
        # from moving this round, and the round falls back to the
        # exact sort rating when too many owned rows are barred (the
        # predicate is LOCAL by design — a lax.cond inside shard_map
        # must not branch on a collective, and per-device engine
        # divergence is fine: the commit protocol is engine-agnostic)
        from ..ops.rating import best_from_slots, scatter_slot_ratings

        in_range = (seg >= 0) & (seg < n_loc)
        # rows are the n_loc OWNED nodes, labels are GLOBAL cluster ids
        # (C-wide) — label_space keeps the winner packing and clipping
        # in the global domain
        slot_label, slot_w, fully_rated = scatter_slot_ratings(
            jnp.clip(seg, 0, n_loc - 1), neighbor_cluster, ew_l,
            n_loc, cfg.num_slots, salt, valid=in_range, label_space=C,
        )
        label_range = None
        if cfg.dist_local_only:
            label_range = (offset, offset + n_loc)

        def scatter_rate(_):
            b, bw, w_own = best_from_slots(
                slot_label, slot_w, labels_l, weights, nw_l, cap,
                salt, label_range=label_range,
            )
            return b, bw, w_own, ~fully_rated

        def sort_rate(_):
            seg_g, key_g, w_g = aggregate_by_key(
                jnp.where(in_range, seg, -1), neighbor_cluster, ew_l
            )
            key_c = jnp.clip(key_g, 0, C - 1)
            seg_c = jnp.clip(seg_g, 0, n_loc - 1)
            fits = (
                weights[key_c].astype(ACC_DTYPE)
                + nw_l[seg_c].astype(ACC_DTYPE)
                <= cap[key_c]
            )
            feasible = (seg_g >= 0) & (key_g != labels_l[seg_c]) & fits
            if cfg.dist_local_only:
                owned = (key_g >= offset) & (key_g < offset + n_loc)
                feasible = feasible & owned
            b, bw = argmax_per_segment(
                seg_g, key_g, w_g, n_loc, tie_salt=salt, feasible=feasible
            )
            w_own = connection_to_label(seg_g, key_g, w_g, labels_l, n_loc)
            return b, bw, w_own, jnp.zeros(n_loc, dtype=bool)

        # local node counts <= n_loc, ID domain  # tpulint: disable=R3
        n_bar = jnp.sum(active_l & ~fully_rated, dtype=jnp.int32)
        # local node counts <= n_loc, ID domain  # tpulint: disable=R3
        n_act = jnp.sum(active_l, dtype=jnp.int32)
        use_scatter = n_bar.astype(jnp.float32) <= (
            jnp.float32(cfg.scatter_fallback) * n_act.astype(jnp.float32)
        )
        best, best_w, w_cur, barred_l = lax.cond(
            use_scatter, scatter_rate, sort_rate, None
        )
        best = jnp.where(barred_l, -1, best)
        best_w = jnp.where(barred_l, INT32_MIN, best_w)
    elif engine == "dense":
        conn = dense_block_ratings(
            seg, jnp.clip(dstloc_l, 0, n_loc + g_loc - 1), ew_l, lab_tab,
            n_loc, C,
        )
        allowed = None
        if cfg.dist_local_only:
            # LocalLPClusterer: only clusters led by owned nodes
            col = jnp.arange(C, dtype=jnp.int32)
            allowed = (col >= offset) & (col < offset + n_loc)
        best, best_w, w_cur = best_from_dense(
            conn, labels_l, weights, nw_l, cap, salt, allowed=allowed
        )
    elif engine == "hash":
        slot_label, slot_w = hashed_rating_table(
            seg, neighbor_cluster, ew_l, n_loc, cfg.num_slots, salt
        )
        label_range = None
        if cfg.dist_local_only:
            # LocalLPClusterer semantics: only join clusters led by an
            # owned node, so clusters never span device boundaries
            label_range = (offset, offset + n_loc)
        best, best_w = best_from_rating_table(
            slot_label, slot_w, labels_l, weights, nw_l, cap,
            salt ^ 0x51AB, label_range=label_range,
        )
        w_cur = connection_to_own_label(
            seg, neighbor_cluster, ew_l, labels_l, n_loc
        )
    else:  # sort
        seg_g, key_g, w_g = aggregate_by_key(seg, neighbor_cluster, ew_l)
        key_c = jnp.clip(key_g, 0, C - 1)
        seg_c = jnp.clip(seg_g, 0, n_loc - 1)
        fits = (
            weights[key_c].astype(ACC_DTYPE) + nw_l[seg_c].astype(ACC_DTYPE)
            <= cap[key_c]
        )
        feasible = (seg_g >= 0) & (key_g != labels_l[seg_c]) & fits
        if cfg.dist_local_only:
            owned = (key_g >= offset) & (key_g < offset + n_loc)
            feasible = feasible & owned
        best, best_w = argmax_per_segment(
            seg_g, key_g, w_g, n_loc, tie_salt=salt, feasible=feasible
        )
        w_cur = connection_to_label(seg_g, key_g, w_g, labels_l, n_loc)

    # -- select (same policy as the single-chip lp_round) ----------------
    gain = best_w - w_cur
    tie_dir_ok = hash_u32(best, salt ^ 0x5BD1) < hash_u32(labels_l, salt ^ 0x5BD1)
    if cfg.refinement:
        improves = gain > 0
    else:
        improves = (gain > 0) | (
            cfg.allow_tie_moves & (gain == 0) & (best_w > 0) & tie_dir_ok
        )
    participate = hash_u32(node_ids_l, salt ^ 0x27D4) < jnp.int32(
        cfg.participation * 2147483647.0
    )
    wants = (
        (best >= 0)
        & (best != labels_l)
        & improves
        & active_l
        & movable_l
        & (node_ids_l < n)
    )
    target_l = jnp.where(wants & participate, best, -1)

    dstloc_c = jnp.clip(dstloc_l, 0, n_loc + g_loc - 1)
    if cfg.refinement:
        # afterburner (shared with ops/lp.py lp_round): bulk-synchronous
        # adjacent moves can jointly increase the cut; costs one halo-
        # exchange pair per round (gain + target of interface nodes).
        # `wants` stays unmasked so filtered or unsampled nodes remain in
        # the convergence count/active set.
        # NOTE: INT32_MIN must stay the module-level import — a local
        # re-import here would shadow it for the WHOLE function and
        # break the scatter engine's earlier use (UnboundLocalError,
        # surfaced once the shard_map compat shim made this path
        # reachable on check_rep-era jax)
        from ..ops.segments import afterburner_filter

        gain_cand_l = jnp.where(target_l >= 0, gain, INT32_MIN)
        # exchanged ghost slots all receive real values (send lists are
        # complete); slots never referenced by any edge keep the scatter
        # fill, which no contribution reads.  One stacked launch for both.
        ghost_gain, ghost_target = halo_exchange(
            jnp.stack([gain_cand_l, target_l]), send_idx_l, recv_map_l, g_loc
        )
        gain_tab = jnp.concatenate([gain_cand_l, ghost_gain])
        target_tab = jnp.concatenate([target_l, ghost_target])
        adj_gain = afterburner_filter(
            seg, dstloc_c, ew_l, labels_l[jnp.clip(seg, 0, n_loc - 1)],
            neighbor_cluster, gain_tab, target_tab, seg, n_loc,
            # ordering must be a TOTAL order across devices: use global ids
            src_order=src_l, dst_order=dst_l,
        )
        target_l = jnp.where(adj_gain > 0, target_l, -1)

    # -- weight control: psum'd demand, throttled local capacity ---------
    local_cap = throttled_local_capacity(target_l, nw_l, weights, cap)

    prio_l = hash_u32(node_ids_l, salt ^ 0x165667B1)
    accept_l = accept_prefix_by_capacity(target_l, prio_l, nw_l, local_cap)

    # -- apply + the collectives (halo sync / weight control) ------------
    new_labels_l = jnp.where(accept_l, target_l, labels_l)
    moved_l = accept_l.astype(jnp.int32)
    if cfg.use_active_set:
        # labels + moved flags share one stacked exchange
        new_ghost_lab, ghost_moved = halo_exchange(
            jnp.stack([new_labels_l, moved_l]), send_idx_l, recv_map_l, g_loc
        )
    else:
        new_ghost_lab = halo_exchange(
            new_labels_l, send_idx_l, recv_map_l, g_loc
        )
        ghost_moved = None

    from .mesh import account_collective

    delta_l = move_weight_delta(labels_l, target_l, accept_l, nw_l, C)
    account_collective(
        "psum(weight-delta)",
        delta_l.size * delta_l.dtype.itemsize,
        shape=delta_l.shape,
    )
    delta = lax.psum(delta_l, NODE_AXIS)
    new_weights = (weights.astype(ACC_DTYPE) + delta).astype(weights.dtype)

    # -- active set (label_propagation.h:507-513 analog) -----------------
    if cfg.use_active_set:
        moved_tab = jnp.concatenate([moved_l, ghost_moved])
        neigh_moved = jax.ops.segment_max(
            moved_tab[dstloc_c], seg, num_segments=n_loc
        )
        # barred rows (scatter engine) stay active for the re-salted
        # slots next round — same retention rule as the shm kernel
        new_active_l = (
            ((moved_l | neigh_moved) > 0)
            | (wants & ~accept_l)
            | (barred_l & active_l)
        )
    else:
        new_active_l = jnp.ones_like(active_l)

    account_collective("psum(convergence)", 4, shape=())
    num_wanting = lax.psum(jnp.sum(wants.astype(jnp.int32)), NODE_AXIS)
    return new_labels_l, new_ghost_lab, new_weights, new_active_l, num_wanting


def _dist_lp_loop(
    mesh: Mesh,
    graph: DistGraph,
    labels0: jax.Array,
    weights0: jax.Array,
    cap: jax.Array,
    seed: jax.Array,
    cfg: LPConfig,
    iters: int,
    movable: Optional[jax.Array] = None,
    record: bool = False,
):
    """shard_map'd multi-round loop; returns replicated labels [n_pad]
    (plus a replicated progress buffer when `record`).

    `movable` (replicated bool[n_pad], optional) freezes nodes where False
    — used by the HEM+LP hybrid to pin matched pairs.

    `record` threads a per-round progress buffer through the carry
    (telemetry/progress.py).  The recorded stat — globally-wanting
    movers — is the already-psum'd convergence scalar, so the
    instrumented trace adds NO collectives; the buffer is replicated and
    rides the existing exit gather's launch.  False (the default) keeps
    the jaxpr identical to the uninstrumented loop."""
    if movable is None:
        movable = jnp.ones(graph.n_pad, dtype=bool)
    g_loc = graph.g_loc

    def per_device(src_l, dst_l, dstloc_l, ew_l, nw_l, n, ghost_gid_l,
                   send_idx_l, recv_map_l, labels0, weights0, cap, seed,
                   movable):
        n_loc = nw_l.shape[0]
        d = lax.axis_index(NODE_AXIS)
        offset = (d * n_loc).astype(jnp.int32)
        movable_l = lax.dynamic_slice(movable, (offset,), (n_loc,))
        # owner-sharded label state: owned slice + initial halo pull of
        # the ghosts' labels (labels0 is replicated only HERE, at entry)
        labels_l0 = lax.dynamic_slice(labels0, (offset,), (n_loc,))
        ghost_lab0 = labels0[jnp.clip(ghost_gid_l, 0, labels0.shape[0] - 1)]
        stats0 = progress_mod.new_buffer(iters, 1) if record else None

        def cond(state):
            i, _, _, _, _, moved, _ = state
            return (i < iters) & (moved != 0)

        def body(state):
            i, labels_l, ghost_lab, weights, active_l, _, stats = state
            salt = (seed.astype(jnp.int32) * 131071 + i * 1566083941) & 0x7FFFFFFF
            labels_l, ghost_lab, weights, active_l, moved = _dist_lp_round(
                src_l, dst_l, dstloc_l, ew_l, nw_l, n, labels_l, ghost_lab,
                send_idx_l, recv_map_l, weights, cap, active_l, movable_l,
                salt, cfg,
            )
            if stats is not None:  # trace-time guard (None adds no carry)
                stats = progress_mod.record(stats, i, moved)
            return (i + 1, labels_l, ghost_lab, weights, active_l, moved,
                    stats)

        active0 = jnp.ones(n_loc, dtype=bool)
        init = (
            jnp.int32(0), labels_l0, ghost_lab0, weights0, active0,
            jnp.int32(1), stats0,
        )
        _, labels_l, _, _, _, _, stats = lax.while_loop(cond, body, init)
        # ONE O(n) gather at loop exit — the per-round collectives above
        # are all O(interface)
        from .mesh import account_collective

        account_collective(
            "all_gather(labels)", labels_l.size * 4, shape=labels_l.shape
        )
        gathered = lax.all_gather(labels_l, NODE_AXIS, tiled=True)
        if stats is None:
            return gathered
        return gathered, stats

    mapped = _shard_map(
        per_device,
        mesh=mesh,
        in_specs=(
            P(NODE_AXIS), P(NODE_AXIS), P(NODE_AXIS), P(NODE_AXIS),
            P(NODE_AXIS), P(), P(NODE_AXIS), P(NODE_AXIS), P(NODE_AXIS),
            P(), P(), P(), P(), P(),
        ),
        out_specs=(P(), P()) if record else P(),
        check_vma=False,
    )
    return mapped(
        graph.src, graph.dst, graph.dst_local, graph.edge_w, graph.node_w,
        graph.n, graph.ghost_gid, graph.send_idx, graph.recv_map,
        labels0, weights0, cap, seed, movable,
    )


@partial(jax.jit, static_argnames=("mesh", "cfg", "num_iterations", "record"))
def _dist_lp_cluster_impl(mesh, graph, max_cluster_weight, seed, cfg,
                          num_iterations, record=False):
    n_pad = graph.n_pad
    labels0 = jnp.arange(n_pad, dtype=jnp.int32)
    weights0 = graph.node_w.astype(ACC_DTYPE)  # cluster c starts = node c
    cap = jnp.broadcast_to(
        jnp.asarray(max_cluster_weight, ACC_DTYPE), (n_pad,)
    )
    iters = num_iterations if num_iterations is not None else cfg.num_iterations
    return _dist_lp_loop(mesh, graph, labels0, weights0, cap, seed, cfg,
                         iters, record=record)


def dist_lp_cluster(
    graph: DistGraph,
    max_cluster_weight,
    seed,
    cfg: LPConfig = LPConfig(),
    num_iterations: Optional[int] = None,
) -> jax.Array:
    """Distributed size-constrained LP clustering (GlobalLPClusteringImpl
    analog, global_lp_clusterer.cc:54-594).  Returns i32[n_pad] cluster
    labels, replicated.  The singleton post-passes (two-hop /
    isolated-node clustering) run host-side on the replicated result —
    see dist_singleton_postpasses (the dist driver applies them per
    level)."""
    return progress_mod.instrumented(
        lambda rec: _dist_lp_cluster_impl(
            graph.src.sharding.mesh, graph,
            jnp.asarray(max_cluster_weight), jnp.asarray(seed), cfg,
            num_iterations, record=rec,
        ),
        "dist-lp", ("moved",), phase="cluster",
    )


@partial(jax.jit, static_argnames=("mesh", "cfg", "num_iterations", "record"))
def _dist_lp_cluster_from_impl(mesh, graph, labels0, movable,
                               max_cluster_weight, seed, cfg,
                               num_iterations, record=False):
    n_pad = graph.n_pad
    labels0 = jnp.asarray(labels0, jnp.int32)
    weights0 = jax.ops.segment_sum(
        graph.node_w.astype(ACC_DTYPE),
        jnp.clip(labels0, 0, n_pad - 1),
        num_segments=n_pad,
    )
    cap = jnp.broadcast_to(
        jnp.asarray(max_cluster_weight, ACC_DTYPE), (n_pad,)
    )
    iters = num_iterations if num_iterations is not None else cfg.num_iterations
    return _dist_lp_loop(
        mesh, graph, labels0, weights0, cap, seed, cfg, iters,
        movable=movable, record=record,
    )


def dist_lp_cluster_from(
    graph: DistGraph,
    labels0: jax.Array,
    movable: jax.Array,
    max_cluster_weight,
    seed,
    cfg: LPConfig = LPConfig(),
    num_iterations: Optional[int] = None,
) -> jax.Array:
    """LP clustering from a given initial clustering with frozen nodes
    (`movable == False`).  Used by the HEM+LP hybrid clusterer."""
    return progress_mod.instrumented(
        lambda rec: _dist_lp_cluster_from_impl(
            graph.src.sharding.mesh, graph, labels0, movable,
            jnp.asarray(max_cluster_weight), jnp.asarray(seed), cfg,
            num_iterations, record=rec,
        ),
        "dist-lp", ("moved",), phase="cluster-from",
    )


@partial(jax.jit,
         static_argnames=("mesh", "k", "cfg", "num_iterations", "record"))
def _dist_lp_refine_impl(mesh, graph, partition, k, max_block_weights, seed,
                         cfg, num_iterations, record=False):
    part0 = jnp.clip(partition, 0, k - 1).astype(jnp.int32)
    # replicated block weights via one psum'd local segment-sum
    def local_bw(nw_l, part):
        d = lax.axis_index(NODE_AXIS)
        n_loc = nw_l.shape[0]
        offset = (d * n_loc).astype(jnp.int32)
        part_l = lax.dynamic_slice(part, (offset,), (n_loc,))
        bw = jax.ops.segment_sum(
            nw_l.astype(ACC_DTYPE), part_l, num_segments=k
        )
        return lax.psum(bw, NODE_AXIS)

    bw0 = _shard_map(
        local_bw,
        mesh=mesh,
        in_specs=(P(NODE_AXIS), P()),
        out_specs=P(),
        check_vma=False,
    )(graph.node_w, part0)
    cap = jnp.asarray(max_block_weights, ACC_DTYPE)
    iters = num_iterations if num_iterations is not None else cfg.num_iterations
    return _dist_lp_loop(mesh, graph, part0, bw0, cap, seed, cfg, iters,
                         record=record)


def dist_lp_refine(
    graph: DistGraph,
    partition: jax.Array,
    k: int,
    max_block_weights,
    seed,
    cfg: LPConfig = LPConfig(refinement=True),
    num_iterations: Optional[int] = None,
) -> jax.Array:
    """Distributed LP refinement (the batched LP refiner analog,
    kaminpar-dist/refinement/lp/lp_refiner.cc): blocks fixed to k, moves
    need strictly positive gain under per-block max weights."""
    if not cfg.refinement:
        cfg = dataclasses.replace(cfg, refinement=True, allow_tie_moves=False)
    return progress_mod.instrumented(
        lambda rec: _dist_lp_refine_impl(
            graph.src.sharding.mesh, graph, partition, k,
            jnp.asarray(max_block_weights), jnp.asarray(seed), cfg,
            num_iterations, record=rec,
        ),
        "dist-lp", ("moved",), phase="refine",
    )


def dist_singleton_postpasses(
    host_graph,
    labels,
    max_cluster_weight: int,
    threshold: float = 0.5,
    materialize=None,
):
    """Two-hop + isolated-node post-passes for the DIST clustering path
    (label_propagation.h:872-1191 — the reference runs them wherever LP
    clusters, including the distributed clusterer).  Low-degree graphs
    under-coarsen on the mesh without them.

    Operates on the replicated label array the dist clusterer returns,
    host-side — the dist driver already holds the host graph to re-shard
    each level, so this is one more O(m) numpy pass, not a new
    device<->host round trip.  Mirrors the single-chip semantics: only
    fires when the singleton fraction exceeds `threshold`
    (lp_clusterer.cc two-hop gate); singletons sharing a FAVORED cluster
    merge into weight-capped bins; isolated nodes pack into weight-capped
    bins.  Bin membership is exact for arbitrary node weights: within
    each quotient bin a capacity-respecting prefix accepts members until
    the cap, and rejected (straddling) nodes stay singleton — the same
    exactness rule as the device pass (ops/lp.cluster_isolated_nodes).
    Returns the updated labels (modified copy).

    `host_graph` may be a still-compressed graph (it is only asked for
    n / node weights before the early-out); `materialize`, when given,
    supplies the plain-CSR graph lazily the first time the passes
    actually fire — the compressed dist ingestion path
    (dist_partitioner) uses this so a non-firing level never decodes.

    `labels` may be the device array straight off the clusterer: this
    function owns the device->host pull (the staged host boundary), so
    callers inside timed spans never carry a bare np.asarray.
    """
    import numpy as np

    cap = max(int(max_cluster_weight), 1)
    n = host_graph.n
    lab = np.asarray(labels[:n], dtype=np.int64).copy()
    node_w = host_graph.node_weight_array().astype(np.int64)
    sizes = np.bincount(lab, minlength=n)
    is_singleton = (lab == np.arange(n)) & (sizes[np.arange(n)] == 1)
    if is_singleton.sum() < threshold * n:
        out = np.asarray(labels).copy()
        out[:n] = lab
        return out
    if materialize is not None:
        host_graph = materialize()
    elif not hasattr(host_graph, "edge_sources"):
        # still-compressed graph with no materializer and the threshold
        # fired: decode once — the passes below walk plain CSR arrays
        host_graph = host_graph.decode()

    def _bin_merge(ids: np.ndarray, group: np.ndarray) -> None:
        """Merge `ids` (each currently singleton) into weight-capped bins
        WITHIN each `group` value: sub-bin by cumulative-weight quotient,
        then accept a capacity-respecting prefix per (group, sub-bin);
        the first accepted member leads, straddlers stay singleton."""
        if len(ids) == 0:
            return
        order = np.lexsort((ids, group))
        ids_s, grp_s = ids[order], group[order]
        w = node_w[ids_s]
        csum = np.cumsum(w)
        firstg = np.ones(len(ids_s), dtype=bool)
        firstg[1:] = grp_s[1:] != grp_s[:-1]
        base = np.where(firstg, csum - w, 0)
        np.maximum.accumulate(base, out=base)
        within = csum - base  # cumulative weight inside the group
        sub = (within - w) // cap  # quotient sub-bins
        # prefix-accept inside each (group, sub-bin): reject straddlers
        firstb = firstg | np.concatenate([[True], sub[1:] != sub[:-1]])
        base_b = np.where(firstb, csum - w, 0)
        np.maximum.accumulate(base_b, out=base_b)
        within_b = csum - base_b
        ok = within_b <= cap
        # leader: first ACCEPTED member of each (group, sub-bin)
        idx = np.arange(len(ids_s))
        lead = np.where(firstb & ok, idx, -1)
        np.maximum.accumulate(lead, out=lead)
        do = ok & (lead >= 0)
        lead_ids = ids_s[np.clip(lead, 0, len(ids_s) - 1)]
        do &= lead_ids != ids_s
        # reject members whose sub-bin leader was itself rejected: a
        # leader slot is valid only if its own `ok` holds (firstb & ok
        # produced it, so it does by construction)
        lab[ids_s[do]] = lab[lead_ids[do]]

    deg = host_graph.degrees()
    # --- isolated nodes: pack into one global sequence of bins ----------
    iso_ids = np.flatnonzero(is_singleton & (deg == 0))
    _bin_merge(iso_ids, np.zeros(len(iso_ids), dtype=np.int64))

    # --- two-hop: singletons grouped by FAVORED cluster -----------------
    sing_ids = np.flatnonzero(is_singleton & (deg > 0))
    if len(sing_ids):
        src = host_graph.edge_sources()
        ew = host_graph.edge_weight_array().astype(np.int64)
        sing_mask = np.zeros(n, dtype=bool)
        sing_mask[sing_ids] = True
        keep = sing_mask[src]
        s, c, w = src[keep], lab[host_graph.adjncy[keep]], ew[keep]
        # favored cluster per singleton: argmax summed connection
        key = s.astype(np.int64) * n + c
        order = np.argsort(key, kind="stable")
        key_s, s_s, c_s, w_s = key[order], s[order], c[order], w[order]
        if len(key_s):
            new_grp = np.empty(len(key_s), dtype=bool)
            new_grp[0] = True
            new_grp[1:] = key_s[1:] != key_s[:-1]
            gid = np.cumsum(new_grp) - 1
            g_w = np.bincount(gid, weights=w_s).astype(np.int64)
            g_s = s_s[new_grp]
            g_c = c_s[new_grp]
            order2 = np.lexsort((g_w, g_s))
            gs2 = g_s[order2]
            last = np.empty(len(gs2), dtype=bool)
            last[:-1] = gs2[:-1] != gs2[1:]
            last[-1] = True
            src_of_max = gs2[last]
            fav_of_max = g_c[order2][last]
            fav = fav_of_max[np.searchsorted(src_of_max, sing_ids)]
            _bin_merge(sing_ids, fav)

    out = np.asarray(labels).copy()
    out[:n] = lab
    return out
