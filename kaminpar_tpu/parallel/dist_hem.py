"""Distributed heavy-edge matching (HEM) clustering over the device mesh.

Analog of the reference's HEMClusterer
(kaminpar-dist/coarsening/clustering/hem/hem_clusterer.h:15): contract
heavy edges by matching each node to its heaviest available neighbor.  The
reference orders nodes with a greedy coloring and matches color classes in
supersteps; the TPU version uses bulk-synchronous *handshake* rounds, the
classic SPMD matching scheme:

  round: every unmatched node proposes to its heaviest unmatched neighbor
  (weight-cap permitting); mutual proposals (u -> v and v -> u) become
  matches, labelled min(u, v).

Handshaking matches at least every locally-heaviest mutual edge per round,
so a few rounds capture most of the matching weight (the reference runs one
pass per color class for the same effect).  `dist_hem_lp_cluster` is the
HEM+LP hybrid (HEMLPClusterer analog): matching first, then LP rounds with
the matched pairs frozen, which lets low-degree leftovers agglomerate.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

try:  # jax >= 0.6
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

from ..ops.lp import LPConfig
from ..ops.segments import (
    ACC_DTYPE,
    aggregate_by_key,
    argmax_per_segment,
)
from .dist_graph import DistGraph
from .mesh import NODE_AXIS


@partial(jax.jit, static_argnames=("mesh", "num_rounds"))
def _dist_hem_impl(mesh, graph: DistGraph, max_cluster_weight, seed,
                   num_rounds: int):
    n_pad = graph.n_pad

    def per_device(src_l, dst_l, ew_l, nw_l, n, cap, seed):
        n_loc = nw_l.shape[0]
        d = lax.axis_index(NODE_AXIS)
        offset = (d * n_loc).astype(jnp.int32)
        node_ids_l = offset + jnp.arange(n_loc, dtype=jnp.int32)
        seg = src_l - offset
        is_real_l = node_ids_l < n
        nw_full = lax.all_gather(nw_l, NODE_AXIS, tiled=True)

        def round_body(rnd, labels):
            # matched nodes carry a foreign label (or own one as a leader
            # with a partner); a node is available iff it is a singleton
            # leader of itself and nobody joined it
            matched = labels != jnp.arange(n_pad, dtype=jnp.int32)
            # a leader whose id was adopted by someone else is matched too
            adopted = jnp.zeros(n_pad, dtype=jnp.int32).at[
                jnp.clip(labels, 0, n_pad - 1)
            ].max(matched.astype(jnp.int32))
            available = ~matched & (adopted == 0)

            labels_l = lax.dynamic_slice(labels, (offset,), (n_loc,))
            avail_l = lax.dynamic_slice(available, (offset,), (n_loc,))

            # propose: heaviest available neighbor under the weight cap
            salt = (seed.astype(jnp.int32) * 69621 + rnd * 7919) & 0x7FFFFFFF
            seg_g, key_g, w_g = aggregate_by_key(seg, dst_l, ew_l)
            feas_g = (
                available[jnp.clip(key_g, 0, n_pad - 1)]
                & (
                    nw_full[jnp.clip(key_g, 0, n_pad - 1)].astype(ACC_DTYPE)
                    + nw_l[jnp.clip(seg_g, 0, n_loc - 1)].astype(ACC_DTYPE)
                    <= cap
                )
                & (seg_g >= 0)
            )
            prop_l, _ = argmax_per_segment(
                seg_g, key_g, w_g, n_loc, tie_salt=salt, feasible=feas_g
            )
            prop_l = jnp.where(avail_l & is_real_l, prop_l, -1)
            prop = lax.all_gather(prop_l, NODE_AXIS, tiled=True)

            # handshake: mutual proposals match; label both min(u, v)
            partner = jnp.where(
                (prop_l >= 0)
                & (prop[jnp.clip(prop_l, 0, n_pad - 1)] == node_ids_l),
                prop_l,
                -1,
            )
            new_labels_l = jnp.where(
                partner >= 0, jnp.minimum(node_ids_l, partner), labels_l
            )
            return lax.all_gather(new_labels_l, NODE_AXIS, tiled=True)

        labels0 = jnp.arange(n_pad, dtype=jnp.int32)
        return lax.fori_loop(0, num_rounds, round_body, labels0)

    return _shard_map(
        per_device,
        mesh=mesh,
        in_specs=(P(NODE_AXIS),) * 4 + (P(),) * 3,
        out_specs=P(),
        check_vma=False,
    )(
        graph.src, graph.dst, graph.edge_w, graph.node_w, graph.n,
        max_cluster_weight, seed,
    )


def dist_hem_cluster(
    graph: DistGraph,
    max_cluster_weight,
    seed,
    num_rounds: int = 5,
) -> jax.Array:
    """Heavy-edge matching clustering (HEMClusterer analog).  Returns
    i32[n_pad] cluster labels, replicated: matched pairs share min(u, v),
    unmatched nodes stay singletons."""
    return _dist_hem_impl(
        graph.src.sharding.mesh,
        graph,
        jnp.asarray(max_cluster_weight, ACC_DTYPE),
        jnp.asarray(seed),
        num_rounds,
    )


def dist_hem_lp_cluster(
    graph: DistGraph,
    max_cluster_weight,
    seed,
    hem_rounds: int = 5,
    cfg: LPConfig = LPConfig(),
) -> jax.Array:
    """HEM followed by LP with matched pairs frozen (HEMLPClusterer
    analog): matching grabs the heavy edges exactly, LP agglomerates the
    leftovers."""
    from .dist_lp import dist_lp_cluster_from

    labels = dist_hem_cluster(graph, max_cluster_weight, seed,
                              num_rounds=hem_rounds)
    movable = labels == jnp.arange(graph.n_pad, dtype=jnp.int32)
    # leaders that received a partner must stay put as well
    adopted = jnp.zeros(graph.n_pad, dtype=jnp.int32).at[
        jnp.clip(labels, 0, graph.n_pad - 1)
    ].max((~movable).astype(jnp.int32))
    movable = movable & (adopted == 0)
    return dist_lp_cluster_from(
        graph, labels, movable, max_cluster_weight, seed, cfg
    )
