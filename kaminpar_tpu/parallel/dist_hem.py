"""Distributed heavy-edge matching (HEM) clustering over the device mesh.

Analog of the reference's HEMClusterer
(kaminpar-dist/coarsening/clustering/hem/hem_clusterer.h:15): contract
heavy edges by matching each node to its heaviest available neighbor.  The
reference orders nodes with a greedy coloring and matches color classes in
supersteps; the TPU version uses bulk-synchronous *handshake* rounds, the
classic SPMD matching scheme:

  round: every unmatched node proposes to its heaviest unmatched neighbor
  (weight-cap permitting); mutual proposals (u -> v and v -> u) become
  matches, labelled min(u, v).

Handshaking matches at least every locally-heaviest mutual edge per round,
so a few rounds capture most of the matching weight (the reference runs one
pass per color class for the same effect).  `dist_hem_lp_cluster` is the
HEM+LP hybrid (HEMLPClusterer analog): matching first, then LP rounds with
the matched pairs frozen, which lets low-degree leftovers agglomerate.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

# version-portable shard_map (mesh.shard_map_compat): the
# replication-check flag is spelled check_vma / check_rep depending on
# the installed jax — the compat shim keeps every dist kernel usable on
# both instead of dying with a TypeError at the first collective
from .mesh import shard_map_compat as _shard_map

from ..ops.lp import LPConfig
from ..ops.segments import (
    ACC_DTYPE,
    aggregate_by_key,
    argmax_per_segment,
)
from .dist_graph import DistGraph
from .mesh import NODE_AXIS, halo_exchange


@partial(jax.jit, static_argnames=("mesh", "num_rounds"))
def _dist_hem_impl(mesh, graph: DistGraph, max_cluster_weight, seed,
                   num_rounds: int):
    def per_device(src_l, dst_l, dstloc_l, ew_l, nw_l, n, ghost_gid_l,
                   send_idx_l, recv_map_l, cap, seed):
        n_loc = nw_l.shape[0]
        g_loc = ghost_gid_l.shape[0]
        d = lax.axis_index(NODE_AXIS)
        offset = (d * n_loc).astype(jnp.int32)
        node_ids_l = offset + jnp.arange(n_loc, dtype=jnp.int32)
        seg = src_l - offset
        seg_c = jnp.clip(seg, 0, n_loc - 1)
        dstloc_c = jnp.clip(dstloc_l, 0, n_loc + g_loc - 1)
        is_real_l = node_ids_l < n
        # static ghost node weights: one exchange at entry
        ghost_nw = halo_exchange(nw_l, send_idx_l, recv_map_l, g_loc)
        nw_tab = jnp.concatenate([nw_l, ghost_nw])

        def round_body(rnd, state):
            labels_l, avail_l, ghost_avail = state
            salt = (seed.astype(jnp.int32) * 69621 + rnd * 7919) & 0x7FFFFFFF
            avail_tab = jnp.concatenate([avail_l, ghost_avail])

            # propose: heaviest available neighbor under the weight cap.
            # Grouping key is the LOCAL slot so the chosen partner's own
            # proposal can be read from the halo table below
            seg_g, key_g, w_g = aggregate_by_key(seg, dstloc_c, ew_l)
            key_c = jnp.clip(key_g, 0, n_loc + g_loc - 1)
            feas_g = (
                (avail_tab[key_c] > 0)
                & (
                    nw_tab[key_c].astype(ACC_DTYPE)
                    + nw_l[jnp.clip(seg_g, 0, n_loc - 1)].astype(ACC_DTYPE)
                    <= cap
                )
                & (seg_g >= 0)
            )
            prop_slot_l, _ = argmax_per_segment(
                seg_g, key_g, w_g, n_loc, tie_salt=salt, feasible=feas_g
            )
            proposes = (avail_l > 0) & is_real_l & (prop_slot_l >= 0)
            slot_c = jnp.clip(prop_slot_l, 0, n_loc + g_loc - 1)
            # the partner's GLOBAL id, from the slot (owned or ghost)
            prop_gid_l = jnp.where(
                proposes,
                jnp.where(
                    prop_slot_l < n_loc,
                    offset + prop_slot_l,
                    ghost_gid_l[jnp.clip(prop_slot_l - n_loc, 0, g_loc - 1)],
                ),
                -1,
            )
            # publish proposals (as global ids) to ghosts, then handshake:
            # mutual proposals match; label both min(u, v)
            ghost_prop = halo_exchange(
                prop_gid_l, send_idx_l, recv_map_l, g_loc
            )
            prop_tab = jnp.concatenate([prop_gid_l, ghost_prop])
            partner_gid = jnp.where(
                proposes & (prop_tab[slot_c] == node_ids_l), prop_gid_l, -1
            )
            matched = partner_gid >= 0
            new_labels_l = jnp.where(
                matched, jnp.minimum(node_ids_l, partner_gid), labels_l
            )
            new_avail_l = jnp.where(matched, 0, avail_l)
            new_ghost_avail = halo_exchange(
                new_avail_l, send_idx_l, recv_map_l, g_loc
            )
            return (new_labels_l, new_avail_l, new_ghost_avail)

        labels0_l = node_ids_l
        avail0_l = is_real_l.astype(jnp.int32)
        ghost_avail0 = halo_exchange(avail0_l, send_idx_l, recv_map_l, g_loc)
        labels_l, _, _ = lax.fori_loop(
            0, num_rounds, round_body, (labels0_l, avail0_l, ghost_avail0)
        )
        # exit-only O(n) gather
        return lax.all_gather(labels_l, NODE_AXIS, tiled=True)

    return _shard_map(
        per_device,
        mesh=mesh,
        in_specs=(
            P(NODE_AXIS), P(NODE_AXIS), P(NODE_AXIS), P(NODE_AXIS),
            P(NODE_AXIS), P(), P(NODE_AXIS), P(NODE_AXIS), P(NODE_AXIS),
            P(), P(),
        ),
        out_specs=P(),
        check_vma=False,
    )(
        graph.src, graph.dst, graph.dst_local, graph.edge_w, graph.node_w,
        graph.n, graph.ghost_gid, graph.send_idx, graph.recv_map,
        max_cluster_weight, seed,
    )


def dist_hem_cluster(
    graph: DistGraph,
    max_cluster_weight,
    seed,
    num_rounds: int = 5,
) -> jax.Array:
    """Heavy-edge matching clustering (HEMClusterer analog).  Returns
    i32[n_pad] cluster labels, replicated: matched pairs share min(u, v),
    unmatched nodes stay singletons."""
    return _dist_hem_impl(
        graph.src.sharding.mesh,
        graph,
        jnp.asarray(max_cluster_weight, ACC_DTYPE),
        jnp.asarray(seed),
        num_rounds,
    )


def dist_hem_lp_cluster(
    graph: DistGraph,
    max_cluster_weight,
    seed,
    hem_rounds: int = 5,
    cfg: LPConfig = LPConfig(),
) -> jax.Array:
    """HEM followed by LP with matched pairs frozen (HEMLPClusterer
    analog): matching grabs the heavy edges exactly, LP agglomerates the
    leftovers."""
    from .dist_lp import dist_lp_cluster_from

    labels = dist_hem_cluster(graph, max_cluster_weight, seed,
                              num_rounds=hem_rounds)
    movable = labels == jnp.arange(graph.n_pad, dtype=jnp.int32)
    # leaders that received a partner must stay put as well
    adopted = jnp.zeros(graph.n_pad, dtype=jnp.int32).at[
        jnp.clip(labels, 0, graph.n_pad - 1)
    ].max((~movable).astype(jnp.int32))
    movable = movable & (adopted == 0)
    return dist_lp_cluster_from(
        graph, labels, movable, max_cluster_weight, seed, cfg
    )
