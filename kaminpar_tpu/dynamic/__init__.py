"""Dynamic repartitioning: graph sessions, delta ingestion, and
warm-started v-cycle repartition (ROADMAP item 5(a)).

Three modules:

  * :mod:`.session` — :class:`GraphSession` (mutable host graph + last
    gate-valid partition + the evolving base-fingerprint/delta-chain
    identity) and :class:`DeltaBatch` (validated edge/vertex/weight
    mutations applied through the padded-bucket-aware CSR patch path);
  * :mod:`.repartition` — the warm/cold/replica policy: neighbor-
    majority seeding of new vertices, the drift estimator, the
    warm-started v-cycle pass (partitioning/vcycle.py plumbing,
    checkpoint barriers included), the PASCO-style replica race, and
    the PR-4 ``telemetry.diff`` cut gate across each delta;
  * :mod:`.driver` — the ``--delta-batch`` chain driver with
    kill-and-resume chain state, synthetic churn batches, and the
    schema-v11 ``dynamic`` report section shared with the serving
    layer's session-scoped request kinds (serving/service.py
    ``register`` / ``mutate`` / ``repartition``).
"""

from .repartition import (  # noqa: F401
    RepartitionOutcome,
    repartition,
    seed_new_vertices,
)
from .session import DeltaBatch, GraphSession, chain_digest  # noqa: F401
from .driver import (  # noqa: F401
    load_delta_file,
    random_delta_batch,
    run_chain,
    summarize,
    synth_chain,
)
