"""Delta-chain driving + the ``dynamic`` report section (leg c glue).

``run_chain`` is the end-to-end driver behind ``cli.py --delta-batch``:
register the base graph (initial cold partition), then per delta batch
apply -> warm/cold repartition, with the per-step checkpoint/resume
story layered on the facade's own manager:

  * each step's compute runs under the session's **evolving
    fingerprint**, so the facade's checkpoint manifest keys on the
    exact chain position — a kill mid-step resumes THAT step through
    the ordinary ``--resume`` machinery;
  * after every completed step the chain driver writes its own
    **chain state** (step index, chain hash, partition, per-step
    partition digests) under ``<checkpoint-dir>/dynamic/`` (a
    subdirectory, so the manager's snapshot pruning never touches it);
    a resume fast-forwards by re-applying the (deterministic) deltas,
    re-folding the recorded repartition markers, verifying the rebuilt
    chain hash against the stored one, and restoring the partition —
    the interrupted step is then the first to recompute.

``random_delta_batch`` synthesizes churn batches (tests, bench, the
check_all smoke); ``summarize`` assembles the schema-v11 ``dynamic``
report section shared by this driver and the serving layer.
"""

from __future__ import annotations

import json
import os
from typing import Callable, List, Optional, Tuple

import numpy as np

from .repartition import RepartitionOutcome, repartition
from .session import DeltaBatch, GraphSession

CHAIN_STATE_DIR = "dynamic"
CHAIN_STATE_JSON = "chain-state.json"
CHAIN_STATE_NPZ = "chain-part.npz"


def random_delta_batch(graph, seed: int, edge_churn: float = 0.01,
                       insert_frac: float = 0.5,
                       vertex_adds: int = 0,
                       weighted: bool = False,
                       uniform_frac: float = 0.0) -> DeltaBatch:
    """A synthetic churn batch: delete about ``edge_churn *
    (1 - insert_frac)`` of the undirected edges and insert about
    ``edge_churn * insert_frac`` new ones (plus optional appended
    vertices, each wired to a random existing node so seeding has
    neighbors to vote with).  Deterministic in (graph, seed).

    Inserts default to **triadic closure** (new edges close wedges:
    two neighbors of a shared node), which is how real dynamic graphs
    churn — and what keeps the churn warm-startable.  ``uniform_frac``
    mixes in uniformly random endpoint pairs, which in a structured
    graph are almost all *intrinsic cut edges* no refinement can
    remove: the adversarial end of the drift spectrum (tests use it to
    force the cold/escalation paths)."""
    rng = np.random.default_rng(seed)
    n = graph.n
    src = graph.edge_sources().astype(np.int64)
    dst = np.asarray(graph.adjncy, dtype=np.int64)
    xadj = np.asarray(graph.xadj, dtype=np.int64)
    half = src < dst
    und = np.stack([src[half], dst[half]], axis=1)
    m_und = len(und)
    ops = max(1, int(m_und * edge_churn))
    n_ins = max(1, int(ops * insert_frac))
    # insert_frac=1.0 means a pure-growth batch — no hidden delete
    # (callers sizing a batch to cross a padded bucket exactly rely on
    # the net growth being the insert count)
    n_del = ops - n_ins if insert_frac >= 1.0 else max(1, ops - n_ins)

    deletes = und[rng.choice(m_und, size=max(min(n_del, m_und), 0),
                             replace=False)] if m_und else und[:0]

    n_total = n + vertex_adds
    existing = set(int(a) * (n_total + 1) + int(b) for a, b in und)

    def _take(cand: np.ndarray, want: int,
              out: List[Tuple[int, int]]) -> None:
        if not len(cand):
            return
        lo = np.minimum(cand[:, 0], cand[:, 1])
        hi = np.maximum(cand[:, 0], cand[:, 1])
        ok = lo != hi
        for a, b in zip(lo[ok], hi[ok]):
            key = int(a) * (n_total + 1) + int(b)
            if key in existing:
                continue
            existing.add(key)
            out.append((int(a), int(b)))
            if len(out) >= want:
                return

    inserts: List[Tuple[int, int]] = []
    n_uni = int(round(n_ins * max(0.0, min(1.0, uniform_frac))))
    n_tri = n_ins - n_uni
    deg = (xadj[1:] - xadj[:-1]).astype(np.int64)
    wedge_nodes = np.flatnonzero(deg >= 2)
    guard = 0
    while len(inserts) < n_tri and len(wedge_nodes) and guard < 50:
        guard += 1
        u = wedge_nodes[rng.integers(0, len(wedge_nodes),
                                     size=4 * n_tri)]
        o1 = rng.integers(0, deg[u])
        o2 = rng.integers(0, deg[u] - 1)
        o2 = np.where(o2 >= o1, o2 + 1, o2)  # two DISTINCT neighbors
        cand = np.stack([dst[xadj[u] + o1], dst[xadj[u] + o2]], axis=1)
        _take(cand, n_tri, inserts)
    guard = 0
    while len(inserts) < n_ins and guard < 50:
        guard += 1
        _take(rng.integers(0, n_total, size=(4 * n_ins, 2)),
              n_ins, inserts)
    ins = np.asarray(inserts, dtype=np.int64).reshape(-1, 2)
    # every appended vertex gets at least one edge to an existing node
    extra = []
    for v in range(n, n_total):
        if not len(ins) or not (ins == v).any():
            u = int(rng.integers(0, n))
            key = min(u, v) * (n_total + 1) + max(u, v)
            if key not in existing:
                existing.add(key)
                extra.append((min(u, v), max(u, v)))
    if extra:
        ins = np.concatenate(
            [ins, np.asarray(extra, dtype=np.int64)], axis=0)
    return DeltaBatch(
        edge_inserts=ins,
        insert_weights=(
            rng.integers(1, 4, size=len(ins)) if weighted else None),
        edge_deletes=deletes,
        vertex_adds=vertex_adds,
    )


def synth_chain(graph, steps: int, seed: int, edge_churn: float = 0.01,
                vertex_adds_every: int = 0,
                uniform_frac: float = 0.0) -> List[DeltaBatch]:
    """A chain of churn batches, each synthesized against the graph AS
    MUTATED by its predecessors (a batch generated from the base graph
    would delete edges an earlier batch already removed).  Used by the
    tests, the bench dynamic measurement, and the check_all smoke."""
    scratch = GraphSession("synth", graph, k=2)
    out: List[DeltaBatch] = []
    try:
        for i in range(steps):
            adds = (
                1 if vertex_adds_every
                and (i + 1) % vertex_adds_every == 0 else 0
            )
            b = random_delta_batch(
                scratch.graph, seed=seed + i, edge_churn=edge_churn,
                vertex_adds=adds, uniform_frac=uniform_frac,
            )
            scratch.apply(b)
            out.append(b)
    finally:
        # the scratch session stamped ITS identity onto the caller's
        # graph object; strip it so the caller's checkpoint/cache
        # identity is unchanged by this synthesis pass
        for attr in ("_session_fp", "_chain_digest"):
            if hasattr(graph, attr):
                delattr(graph, attr)
    return out


def load_delta_file(path: str) -> List[DeltaBatch]:
    """Parse a ``--delta-batch`` JSON file: either a bare array of
    delta objects or ``{"deltas": [...]}`` (DeltaBatch.from_dict wire
    form).  Raises io.GraphFormatError on malformed content."""
    from ..io.errors import GraphFormatError

    try:
        with open(path) as f:
            spec = json.load(f)
    except (OSError, ValueError) as e:
        raise GraphFormatError(
            f"unreadable delta-batch file: {e}", path=path) from e
    if isinstance(spec, dict):
        spec = spec.get("deltas")
    if not isinstance(spec, list) or not spec:
        raise GraphFormatError(
            "delta-batch file must be a non-empty array of delta "
            "objects (or {\"deltas\": [...]})", path=path)
    try:
        return [DeltaBatch.from_dict(d) for d in spec]
    except GraphFormatError as e:
        raise e.with_path(path)


def summarize(sessions: List[GraphSession],
              decisions: List[dict]) -> dict:
    """The schema-v11 ``dynamic`` report section, shared by the chain
    driver and the serving layer ({'enabled': False} when nothing
    dynamic ever ran)."""
    if not sessions and not decisions:
        return {"enabled": False}
    counts = {"warm": 0, "cold": 0, "replica": 0, "escalated": 0}
    trajectory: List[Optional[int]] = []
    for d in decisions:
        mode = d.get("mode")
        if mode in counts:
            counts[mode] += 1
        if d.get("escalated"):
            counts["escalated"] += 1
        if "cut" in d:
            trajectory.append(d["cut"])
    return {
        "enabled": True,
        "sessions": [s.summary() for s in sessions],
        "decisions": list(decisions),
        "counts": {
            **counts,
            "deltas": sum(s.deltas_applied for s in sessions),
            "in_place": sum(s.in_place for s in sessions),
            "rebuilds": sum(s.rebuilds for s in sessions),
        },
        "cut_trajectory": trajectory,
    }


# ---------------------------------------------------------------------------
# chain state (the driver's own durable record; per-step compute
# checkpoints belong to the facade's manager)
# ---------------------------------------------------------------------------


def _chain_paths(checkpoint_dir: str) -> Tuple[str, str]:
    d = os.path.join(checkpoint_dir, CHAIN_STATE_DIR)
    return (os.path.join(d, CHAIN_STATE_JSON),
            os.path.join(d, CHAIN_STATE_NPZ))


def _save_chain_state(checkpoint_dir: str, session: GraphSession,
                      step: int, part_digests: List[str],
                      cuts: List[int],
                      decisions: Optional[List[dict]] = None) -> None:
    jpath, npath = _chain_paths(checkpoint_dir)
    os.makedirs(os.path.dirname(jpath), exist_ok=True)
    tmp = npath + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, partition=np.asarray(
            session.partition, dtype=np.int32))
    os.replace(tmp, npath)
    state = {
        "step": int(step),
        "chain": session.chain,
        "k": int(session.k),
        "cut": session.last_cut,
        "cuts": [int(c) for c in cuts],
        "part_digests": list(part_digests),
        # the decision rows of every COMPLETED step: a resume restores
        # them so the final report's trajectory covers the whole chain,
        # not just the recomputed tail
        "decisions": list(decisions or []),
        "counters": {
            "deltas_applied": session.deltas_applied,
            "in_place": session.in_place,
            "rebuilds": session.rebuilds,
            "repartitions": session.repartitions,
        },
    }
    tmpj = jpath + ".tmp"
    with open(tmpj, "w") as f:
        json.dump(state, f)
    os.replace(tmpj, jpath)


def _load_chain_state(checkpoint_dir: str) -> Optional[dict]:
    jpath, npath = _chain_paths(checkpoint_dir)
    try:
        with open(jpath) as f:
            state = json.load(f)
        with np.load(npath) as z:
            state["partition"] = np.asarray(
                z["partition"], dtype=np.int32)
        return state
    except (OSError, ValueError, KeyError):
        return None


def run_chain(graph, batches: List[DeltaBatch], ctx, *,
              k: int, epsilon: Optional[float] = None,
              seed: Optional[int] = None,
              session_id: str = "chain",
              quiet: bool = True,
              step_cb: Optional[Callable[[int, dict], None]] = None,
              ) -> Tuple[np.ndarray, dict]:
    """Drive register + the whole delta chain.  Returns (final
    partition, dynamic report section).  ``step_cb(step, row)`` fires
    per completed step (-1 = the initial register) for CLI printing."""
    from .. import telemetry
    from ..kaminpar import KaMinPar
    from ..utils.logger import OutputLevel

    # work on a private copy: the driver clears the resume flag after
    # the first recomputed step, and that must never leak into a
    # caller-owned context reused for another chain
    ctx = ctx.copy()
    checkpoint_dir = ctx.resilience.checkpoint_dir or ""
    resume = bool(ctx.resilience.resume) and bool(checkpoint_dir)

    session = GraphSession(session_id, graph, k=k)
    decisions: List[dict] = []
    part_digests: List[str] = []
    cuts: List[int] = []
    start_step = 0
    resumed_from: Optional[int] = None

    restored = _load_chain_state(checkpoint_dir) if resume else None
    if restored is not None and int(restored.get("k", -1)) == int(k):
        # fast-forward: re-apply the (deterministic) deltas up to the
        # recorded step, re-fold the stored repartition markers, and
        # verify the rebuilt chain hash before trusting the partition
        rec_step = int(restored["step"])
        digs = list(restored.get("part_digests") or [])
        try:
            for i in range(rec_step + 1):
                if i < len(digs):
                    if i > 0:
                        session.apply(batches[i - 1])
                    session.fold_repartition_marker(k, digs[i])
        except Exception:
            session = GraphSession(session_id, graph, k=k)
            restored = None
        if restored is not None and session.chain == restored["chain"] \
                and len(restored["partition"]) == session.graph.n:
            session.partition = restored["partition"]
            # the saved boundary is post-commit: the drift accumulators
            # were 0 there, but the replayed applies just re-filled
            # them (with no partition, ALL replayed mass counts as
            # cut-touching) — reset, or the first recomputed step's
            # drift is inflated by the whole replayed chain
            session.reset_pending_drift()
            session.last_cut = (
                None if restored.get("cut") is None
                else int(restored["cut"]))
            # the replay re-applied the deltas, but its in-place/rebuild
            # split can differ from the pre-kill truth (e.g. the
            # original run had a dynamic-apply fault plan active) — the
            # REPORTED history must be what actually happened
            counters = restored.get("counters") or {}
            session.repartitions = int(counters.get("repartitions", 0))
            session.deltas_applied = int(counters.get(
                "deltas_applied", session.deltas_applied))
            session.in_place = int(counters.get(
                "in_place", session.in_place))
            session.rebuilds = int(counters.get(
                "rebuilds", session.rebuilds))
            cuts = [int(c) for c in restored.get("cuts") or []]
            decisions = list(restored.get("decisions") or [])
            part_digests = digs
            start_step = rec_step + 1
            resumed_from = rec_step
            # note: this event is wiped by the next compute's stream
            # reset; the DURABLE record is `resumed_from_step` in the
            # returned section below
            telemetry.event(
                "dynamic", action="chain-resume", session=session_id,
                step=rec_step, chain=session.chain,
            )
        else:
            # stale/corrupt chain state: logged clean restart, exactly
            # like a checkpoint fingerprint mismatch
            from ..utils.logger import log_warning

            log_warning(
                "dynamic: chain state did not match the replayed delta "
                "chain; restarting the chain cleanly")
            session = GraphSession(session_id, graph, k=k)

    import hashlib

    def _commit_step(step: int, row: dict) -> None:
        decisions.append(row)
        cuts.append(int(row["cut"]))
        part_digests.append(hashlib.sha256(
            np.asarray(session.partition, dtype=np.int32).tobytes()
        ).hexdigest()[:16])
        if checkpoint_dir:
            _save_chain_state(
                checkpoint_dir, session, step, part_digests, cuts,
                decisions)
        if step_cb is not None:
            step_cb(step, row)

    if start_step == 0:
        # register: the base graph's initial (cold) partition — the
        # session's first gate-valid baseline.  NOT wrapped in a timer
        # scope: the facade decides stream ownership (checkpoint
        # manager, telemetry annotations, the gate verdict) by
        # GLOBAL_TIMER.idle(), and an open scope would demote the
        # register run to "nested" — unresumable and unannotated
        import time as _time

        t_reg = _time.perf_counter()
        solver = KaMinPar(ctx)
        if quiet:
            solver.set_output_level(OutputLevel.QUIET)
        solver.set_graph(session.graph)
        part = solver.compute_partition(k=k, epsilon=epsilon,
                                        seed=seed)
        reg_wall = _time.perf_counter() - t_reg
        metrics = solver.result_metrics(session.graph, part)
        gate_valid = telemetry.gate_verdict()
        session.commit_partition(
            part, int(metrics["cut"]), gate_valid=gate_valid)
        row = {
            "session": session_id, "step": 0, "mode": "cold",
            "drift": None, "cut_before": None,
            "cut": int(metrics["cut"]),
            "feasible": bool(metrics["feasible"]),
            "stable": None, "escalated": False, "seeded": 0,
            "wall_s": round(reg_wall, 4),
            "warm_wall_s": None, "cold_wall_s": round(reg_wall, 4),
        }
        if gate_valid is not None:
            row["gate_valid"] = gate_valid
        telemetry.event(
            "dynamic", action="register", session=session_id,
            n=session.graph.n, m=session.graph.m, cut=row["cut"],
        )
        _commit_step(0, row)
        start_step = 1
        # later steps must not consume this run's resume state again
        ctx.resilience.resume = False

    from ..resilience import deadline as deadline_mod

    for i, batch in enumerate(batches):
        step = i + 1
        if step < start_step:
            continue
        if deadline_mod.draining():
            # SIGTERM/drain between steps: the chain stops at a
            # committed step boundary — the state on disk resumes it
            telemetry.event(
                "dynamic", action="chain-drain", session=session_id,
                step=step,
            )
            break
        apply_info = session.apply(batch)
        outcome: RepartitionOutcome = repartition(
            session, ctx, k=k, epsilon=epsilon,
            seed=(seed + step) if seed is not None else None,
            quiet=quiet,
        )
        row = outcome.to_row(session_id, step=step)
        row["in_place"] = bool(apply_info["in_place"])
        _commit_step(step, row)
        # only the FIRST recomputed step may resume a mid-step manifest
        ctx.resilience.resume = False

    section = summarize([session], decisions)
    if resumed_from is not None:
        section["resumed_from_step"] = int(resumed_from)
    return np.asarray(session.partition), section
