"""Warm-started v-cycle repartitioning policy (dynamic leg b).

Per repartition request the policy is:

  1. **seed** — vertices added since the last repartition get labels by
     weighted neighbor-majority vote (ties to the smaller block, like
     the quality observatory's majority), isolated newcomers fill
     blocks by headroom;
  2. **drift estimate** — accumulated delta edge mass touching the cut
     / total edge mass, plus the post-patch balance violation
     (session.drift_estimate);
  3. **decide** — drift under ``ctx.dynamic.drift_threshold`` runs the
     warm path (the v-cycle driver with the previous partition as its
     initial state and a bounded restricted-coarsening depth,
     partitioning/vcycle.py — checkpoint barriers included); above it,
     a cold run; ``ctx.dynamic.replicas >= 2`` races warm against cold
     replicas PASCO-style (arXiv 2412.13592) and keeps the better cut;
  4. **gate** — the result is asserted stable against the pre-delta
     cut via the PR-4 ``telemetry.diff`` cut gate; an unstable warm
     result escalates to a cold retry (``ctx.dynamic.cold_fallback``)
     and the better of the two is kept.

Every decision emits a ``dynamic`` telemetry event (after the compute,
so the facade's per-run stream reset cannot swallow it) and the outcome
is committed back into the session (partition + chain marker).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np

from .session import GraphSession


@dataclass
class RepartitionOutcome:
    """One repartition decision + result — the run report's
    ``dynamic.decisions`` row."""

    partition: np.ndarray
    cut: int
    imbalance: float
    feasible: bool
    gate_valid: Optional[bool]
    mode: str  # warm | cold | replica
    drift: Optional[float]
    cut_before: Optional[int]
    stable: Optional[bool]
    escalated: bool
    seeded: int
    wall_s: float
    warm_wall_s: Optional[float]
    cold_wall_s: Optional[float]
    replica_cuts: List[int] = field(default_factory=list)
    anytime: Optional[dict] = None
    degraded_sites: List[str] = field(default_factory=list)

    def to_row(self, session_id: str = "", step: Optional[int] = None
               ) -> dict:
        row = {
            "session": session_id,
            "mode": self.mode,
            "drift": (None if self.drift is None
                      else round(float(self.drift), 6)),
            "cut_before": (None if self.cut_before is None
                           else int(self.cut_before)),
            "cut": int(self.cut),
            "feasible": bool(self.feasible),
            "stable": self.stable,
            "escalated": bool(self.escalated),
            "seeded": int(self.seeded),
            "wall_s": round(float(self.wall_s), 4),
            "warm_wall_s": (None if self.warm_wall_s is None
                            else round(float(self.warm_wall_s), 4)),
            "cold_wall_s": (None if self.cold_wall_s is None
                            else round(float(self.cold_wall_s), 4)),
        }
        if step is not None:
            row["step"] = int(step)
        if self.gate_valid is not None:
            row["gate_valid"] = bool(self.gate_valid)
        if self.replica_cuts:
            row["replica_cuts"] = [int(c) for c in self.replica_cuts]
        if self.degraded_sites:
            row["degraded_sites"] = list(self.degraded_sites)
        return row


def seed_new_vertices(graph, partition, k: int,
                      max_block_weights=None) -> tuple:
    """Label every ``-1`` entry of ``partition`` by weighted
    neighbor-majority vote (a few bounded passes cover chains of new
    vertices voting for each other); newcomers with no labeled neighbor
    fill blocks by headroom.  Returns (partition, seeded_count)."""
    from ..kaminpar import _fill_blocks_by_headroom

    part = np.asarray(partition, dtype=np.int32).copy()
    total_seeded = int((part < 0).sum())
    if total_seeded == 0:
        return part, 0
    xadj = np.asarray(graph.xadj, dtype=np.int64)
    adj = np.asarray(graph.adjncy, dtype=np.int64)
    ew = graph.edge_weight_array()
    for _ in range(3):
        un = np.flatnonzero(part < 0)
        if not len(un):
            break
        deg = (xadj[un + 1] - xadj[un]).astype(np.int64)
        idx = np.repeat(xadj[un], deg) + (
            np.arange(int(deg.sum()), dtype=np.int64)
            - np.repeat(np.cumsum(deg) - deg, deg)
        )
        rows = np.repeat(np.arange(len(un), dtype=np.int64), deg)
        lab = part[adj[idx]]
        valid = lab >= 0
        if not valid.any():
            break
        votes = np.zeros((len(un), k), dtype=np.int64)
        np.add.at(votes, (rows[valid], lab[valid]),
                  ew[idx][valid].astype(np.int64))
        got = votes.max(axis=1) > 0
        if not got.any():
            break
        # argmax ties break to the smaller block id by construction
        part[un[got]] = votes[got].argmax(axis=1).astype(np.int32)
    un = np.flatnonzero(part < 0)
    if len(un):
        nw = graph.node_weight_array()
        bw = np.zeros(k, dtype=np.int64)
        labeled = part >= 0
        np.add.at(bw, part[labeled], nw[labeled])
        caps = (
            np.asarray(max_block_weights, dtype=np.int64)
            if max_block_weights is not None
            else np.full(k, np.int64(2) * max(
                int(nw.sum()) // max(k, 1), 1), dtype=np.int64)
        )
        part[un] = _fill_blocks_by_headroom(nw[un], bw, caps)
    return part, total_seeded


def _default_caps(graph, k: int, epsilon: float) -> np.ndarray:
    total = int(graph.total_node_weight)
    perfect = max(1, -(-total // max(k, 1)))
    return np.full(k, int(perfect * (1.0 + epsilon)) + 1, dtype=np.int64)


def repartition(session: GraphSession, ctx=None, *,
                k: Optional[int] = None,
                epsilon: Optional[float] = None,
                seed: Optional[int] = None,
                quiet: bool = True) -> RepartitionOutcome:
    """Run the warm/cold/replica policy for the session's current graph
    and commit the result back into the session."""
    from .. import telemetry
    from ..context import PartitioningMode
    from ..kaminpar import KaMinPar
    from ..presets import create_context_by_preset_name
    from ..telemetry.diff import diff_reports
    from ..utils.logger import OutputLevel

    if ctx is None:
        ctx = create_context_by_preset_name("default")
    dctx = ctx.dynamic
    k = int(k) if k else int(session.k)
    k_changed = k != session.k
    session.set_k(k)
    # epsilon=None defers to the configured ctx.partition.epsilon
    # (PartitionContext.setup keeps it), matching the single-shot path
    eps = (float(epsilon) if epsilon is not None
           else float(ctx.partition.epsilon))
    caps = _default_caps(session.graph, k, eps)

    seeded = 0
    warm_seed_part = None
    if session.partition is not None and not k_changed:
        warm_seed_part, seeded = seed_new_vertices(
            session.graph, session.partition, k,
            max_block_weights=caps,
        )
    drift = session.drift_estimate(caps) if warm_seed_part is not None \
        else None

    if warm_seed_part is None:
        mode = "cold"
    elif int(dctx.replicas) > 1:
        mode = "replica"
    elif drift is not None and drift > float(dctx.drift_threshold):
        mode = "cold"
    else:
        mode = "warm"

    def _run(run_mode: str, warm_part=None, seed_offset: int = 0,
             checkpoint: bool = True) -> dict:
        run_ctx = ctx.copy()
        if warm_part is not None:
            run_ctx.partitioning.mode = PartitioningMode.VCYCLE
        if not checkpoint:
            # only the primary attempt owns the per-step manifest —
            # racers/escalations re-run deterministically on resume
            run_ctx.resilience.checkpoint_dir = ""
            run_ctx.resilience.resume = False
        solver = KaMinPar(run_ctx)
        if quiet:
            solver.set_output_level(OutputLevel.QUIET)
        solver.set_graph(session.graph)
        if warm_part is not None:
            solver.set_initial_partition(
                warm_part, max_levels=int(dctx.warm_levels))
        t0 = time.perf_counter()
        part = solver.compute_partition(
            k=k, epsilon=epsilon,  # None keeps the ctx-configured value
            seed=(seed + seed_offset) if seed is not None else None,
        )
        wall = time.perf_counter() - t0
        metrics = solver.result_metrics(session.graph, part)
        gate_valid = telemetry.gate_verdict()
        sites = sorted({
            e.attrs.get("site", "") for e in telemetry.events("degraded")
        } - {""})
        return {
            "kind": run_mode,
            "part": part,
            "cut": int(metrics["cut"]),
            "imbalance": float(metrics["imbalance"]),
            "feasible": bool(metrics["feasible"]),
            "gate_valid": gate_valid,
            "wall_s": wall,
            "anytime": solver.last_anytime,
            "degraded": sites,
        }

    runs: List[dict] = []
    warm_wall = cold_wall = None
    if mode == "cold":
        runs.append(_run("cold"))
        cold_wall = runs[-1]["wall_s"]
    elif mode == "warm":
        runs.append(_run("warm", warm_part=warm_seed_part))
        warm_wall = runs[-1]["wall_s"]
    else:  # replica race: warm + (replicas - 1) cold twins
        runs.append(_run("warm", warm_part=warm_seed_part))
        warm_wall = runs[-1]["wall_s"]
        for r in range(max(int(dctx.replicas) - 1, 1)):
            runs.append(_run("cold", seed_offset=r + 1, checkpoint=False))
            cold_wall = runs[-1]["wall_s"]

    def _better(a: dict, b: dict) -> dict:
        if a["feasible"] != b["feasible"]:
            return a if a["feasible"] else b
        return a if a["cut"] <= b["cut"] else b

    best = runs[0]
    for other in runs[1:]:
        best = _better(best, other)

    cut_before = session.last_cut

    def _stable(cand: dict) -> Optional[bool]:
        if cut_before is None:
            return None
        _, failures = diff_reports(
            {"result": {"cut": int(cut_before), "feasible": True}},
            {"result": {"cut": int(cand["cut"]),
                        "feasible": bool(cand["feasible"])}},
            cut_threshold=float(dctx.cut_gate_threshold),
        )
        return not failures

    stable = _stable(best)
    escalated = False
    if (
        mode == "warm" and stable is False and bool(dctx.cold_fallback)
    ):
        # the diff gate rejected the warm result: escalate to a cold
        # run and keep the better of the two (PASCO's escape hatch for
        # drift the estimator under-called)
        cold = _run("cold", checkpoint=False)
        cold_wall = cold["wall_s"]
        escalated = True
        best = _better(best, cold)
        stable = _stable(best)

    session.commit_partition(
        best["part"], best["cut"], gate_valid=best["gate_valid"])

    outcome = RepartitionOutcome(
        partition=best["part"],
        cut=best["cut"],
        imbalance=best["imbalance"],
        feasible=best["feasible"],
        gate_valid=best["gate_valid"],
        mode=mode,
        drift=drift,
        cut_before=cut_before,
        stable=stable,
        escalated=escalated,
        seeded=seeded,
        wall_s=sum(r["wall_s"] for r in runs) + (
            cold_wall if escalated else 0.0),
        warm_wall_s=warm_wall,
        cold_wall_s=cold_wall,
        replica_cuts=[r["cut"] for r in runs] if mode == "replica"
        else [],
        anytime=best.get("anytime"),
        degraded_sites=best["degraded"],
    )
    # emitted AFTER the compute: the facade resets the telemetry stream
    # at compute entry, so this lands in the (final) run's stream and
    # survives into its report
    telemetry.event(
        "dynamic", action="repartition", session=session.id,
        mode=mode, drift=outcome.to_row()["drift"],
        cut_before=outcome.cut_before, cut=outcome.cut,
        stable=stable, escalated=escalated, seeded=seeded,
    )
    return outcome
