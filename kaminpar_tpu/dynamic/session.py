"""Graph sessions and delta ingestion (dynamic repartitioning, leg a).

A :class:`GraphSession` owns a *mutable* host graph, its last gate-valid
partition, and an evolving identity: the base graph's cheap
``graph_fingerprint`` plus a running **delta-chain hash** — every
applied :class:`DeltaBatch` (and every committed repartition) folds its
own digest into the chain, so the PR-5 checkpoint machinery and the
PR-6 result cache key correctly on mutated graphs *without ever
re-hashing the full adjacency per mutate* (the chain digest is stamped
onto the session's graph object and ``caching.full_graph_digest`` /
``checkpoint.graph_fingerprint`` read it back; the ``dyn:`` prefix
domain-separates chain digests from raw adjacency digests, so a chain
hash can never alias a differing plain graph).

Delta application exploits the padded-bucket slack from
``caching.pad_size``: a delta whose patched (n, m) stays inside the
current executable bucket commits **in place** — the compiled device
programs for this session keep matching (``BucketTracker``-visible as a
cache hit) — while a bucket-crossing delta rebuilds and re-uploads into
a fresh bucket (tracker miss, device epoch bumped).  The in-place
commit runs under the registered ``dynamic-apply`` degradation site: an
injected (or real) failure falls back to the rebuild path, never a
wrong graph.

Malformed deltas surface through the ``io.GraphFormatError`` taxonomy
(out-of-range endpoints, self loops, duplicate inserts, deleting or
re-weighting a nonexistent edge, non-positive weights), so the serving
isolation boundary classifies them as ``malformed-input`` exactly like
a bad graph file.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Dict, Optional

import numpy as np

from .. import caching
from ..graphs.host import HostGraph, from_edge_list
from ..io.errors import GraphFormatError


def _as_pairs(a, what: str) -> np.ndarray:
    if a is None:
        return np.zeros((0, 2), dtype=np.int64)
    arr = np.asarray(a, dtype=np.int64)
    if arr.size == 0:
        return np.zeros((0, 2), dtype=np.int64)
    if arr.ndim != 2 or arr.shape[1] != 2:
        raise GraphFormatError(f"{what} must be an (e, 2) pair array")
    return arr


def _as_ids(a) -> np.ndarray:
    if a is None:
        return np.zeros(0, dtype=np.int64)
    return np.asarray(a, dtype=np.int64).reshape(-1)


@dataclass
class DeltaBatch:
    """One atomic mutation of a session graph.

    Application order within a batch: vertex adds (new ids are appended
    at ``n`` .. ``n + vertex_adds - 1`` and may be referenced by the
    edge operations of the *same* batch) -> edge deletes -> edge weight
    updates -> node weight updates -> edge inserts -> vertex removes
    (surviving nodes are compacted, ids above a removed id shift down).
    Pairs are undirected (both CSR directions are patched)."""

    #: (e, 2) undirected pairs to insert (must not already exist).
    edge_inserts: np.ndarray = field(
        default_factory=lambda: np.zeros((0, 2), dtype=np.int64))
    #: per-insert weights (None = unit).
    insert_weights: Optional[np.ndarray] = None
    #: (e, 2) undirected pairs to delete (must exist).
    edge_deletes: np.ndarray = field(
        default_factory=lambda: np.zeros((0, 2), dtype=np.int64))
    #: (e, 2) undirected pairs whose weight changes (must exist) ...
    edge_weight_updates: np.ndarray = field(
        default_factory=lambda: np.zeros((0, 2), dtype=np.int64))
    #: ... to these weights.
    update_weights: Optional[np.ndarray] = None
    #: number of new vertices appended (isolated unless edges of this
    #: batch reference them).
    vertex_adds: int = 0
    #: weights of the added vertices (None = unit).
    add_weights: Optional[np.ndarray] = None
    #: vertex ids to remove (incident edges are deleted, survivors
    #: compacted).
    vertex_removes: np.ndarray = field(
        default_factory=lambda: np.zeros(0, dtype=np.int64))
    #: (i, 2) rows of (vertex id, new weight).
    node_weight_updates: np.ndarray = field(
        default_factory=lambda: np.zeros((0, 2), dtype=np.int64))

    def __post_init__(self) -> None:
        self.edge_inserts = _as_pairs(self.edge_inserts, "edge_inserts")
        self.edge_deletes = _as_pairs(self.edge_deletes, "edge_deletes")
        self.edge_weight_updates = _as_pairs(
            self.edge_weight_updates, "edge_weight_updates")
        self.vertex_removes = _as_ids(self.vertex_removes)
        self.node_weight_updates = _as_pairs(
            self.node_weight_updates, "node_weight_updates")
        self.vertex_adds = int(self.vertex_adds)
        if self.vertex_adds < 0:
            raise GraphFormatError("vertex_adds must be >= 0")
        if self.insert_weights is not None:
            self.insert_weights = _as_ids(self.insert_weights)
            if len(self.insert_weights) != len(self.edge_inserts):
                raise GraphFormatError(
                    "insert_weights length != edge_inserts length")
        if self.update_weights is None and len(self.edge_weight_updates):
            raise GraphFormatError(
                "edge_weight_updates requires update_weights")
        if self.update_weights is not None:
            self.update_weights = _as_ids(self.update_weights)
            if len(self.update_weights) != len(self.edge_weight_updates):
                raise GraphFormatError(
                    "update_weights length != edge_weight_updates length")
        if self.add_weights is not None:
            self.add_weights = _as_ids(self.add_weights)
            if len(self.add_weights) != self.vertex_adds:
                raise GraphFormatError(
                    "add_weights length != vertex_adds")

    @property
    def empty(self) -> bool:
        return not (
            len(self.edge_inserts) or len(self.edge_deletes)
            or len(self.edge_weight_updates) or self.vertex_adds
            or len(self.vertex_removes) or len(self.node_weight_updates)
        )

    @classmethod
    def from_dict(cls, d: dict) -> "DeltaBatch":
        """Parse the JSON wire form (``--delta-batch`` files, serving
        ``delta`` request fields).  Unknown keys are format errors."""
        if not isinstance(d, dict):
            raise GraphFormatError("delta must be a JSON object")
        known = {
            "edge_inserts", "insert_weights", "edge_deletes",
            "edge_weight_updates", "update_weights", "vertex_adds",
            "add_weights", "vertex_removes", "node_weight_updates",
        }
        unknown = sorted(set(d) - known)
        if unknown:
            raise GraphFormatError(f"unknown delta key(s): {unknown}")
        try:
            return cls(**{k: d[k] for k in known if k in d})
        except (TypeError, ValueError) as e:
            if isinstance(e, GraphFormatError):
                raise
            raise GraphFormatError(f"malformed delta: {e}") from e

    def to_dict(self) -> dict:
        out: Dict[str, object] = {}
        for key in ("edge_inserts", "edge_deletes", "edge_weight_updates",
                    "vertex_removes", "node_weight_updates"):
            arr = getattr(self, key)
            if len(arr):
                out[key] = np.asarray(arr).tolist()
        for key in ("insert_weights", "update_weights", "add_weights"):
            arr = getattr(self, key)
            if arr is not None and len(arr):
                out[key] = np.asarray(arr).tolist()
        if self.vertex_adds:
            out["vertex_adds"] = int(self.vertex_adds)
        return out

    def digest(self) -> str:
        """Content hash of the batch — the token folded into the
        session's delta-chain hash (one sweep over the DELTA arrays,
        never the full adjacency)."""
        h = hashlib.sha256()
        for key in ("edge_inserts", "insert_weights", "edge_deletes",
                    "edge_weight_updates", "update_weights",
                    "add_weights", "vertex_removes",
                    "node_weight_updates"):
            arr = getattr(self, key)
            h.update(key.encode())
            if arr is None:
                h.update(b"\x00none")
            else:
                h.update(np.ascontiguousarray(
                    np.asarray(arr, dtype=np.int64)).tobytes())
        h.update(f"adds={self.vertex_adds}".encode())
        return h.hexdigest()[:24]


@dataclass
class _Patched:
    """A patch result, computed pure before either commit path runs."""

    graph: HostGraph
    partition: Optional[np.ndarray]  # -1 marks unseeded new vertices
    bucket: tuple
    delta_mass: int
    cut_touch_mass: int
    new_unseeded: int


def chain_digest(parent: str, token: str) -> str:
    """One link of the delta-chain hash: H(parent, token)."""
    return hashlib.sha256(f"{parent}:{token}".encode()).hexdigest()[:24]


class GraphSession:
    """A mutable graph + its partition + its evolving identity."""

    def __init__(self, session_id: str, graph: HostGraph, k: int = 2,
                 validate: bool = False) -> None:
        from ..graphs.host import validate as validate_graph
        from ..resilience.checkpoint import graph_fingerprint

        if not isinstance(graph, HostGraph):
            raise GraphFormatError(
                "dynamic sessions need a plain host CSR graph "
                f"(got {type(graph).__name__}); compressed containers "
                "and streamed specs have no patchable adjacency"
            )
        if validate:
            validate_graph(graph)
        # the session takes OWNERSHIP of the graph object (deltas
        # mutate it); a stale identity stamp from a previous session
        # over the same object must not leak into this session's base
        # identity — strip before hashing
        for attr in ("_session_fp", "_chain_digest"):
            if hasattr(graph, attr):
                delattr(graph, attr)
        self.id = str(session_id)
        self.k = int(k)
        #: the balance tolerance this session's partitions were
        #: computed under (None = the ctx default); set by the serving
        #: register path so later repartitions without an explicit
        #: epsilon keep the SESSION's contract, not the wire default
        self.epsilon: Optional[float] = None
        self.base_fingerprint = graph_fingerprint(graph)
        # the base link of the chain is the FULL adjacency digest — paid
        # exactly once at register; every later identity is O(delta)
        self._chain = chain_digest(
            "base", caching.full_graph_digest(graph))
        self.graph = graph
        self.partition: Optional[np.ndarray] = None
        self.last_cut: Optional[int] = None
        self.last_gate_valid: Optional[bool] = None
        self.deltas_applied = 0
        self.in_place = 0
        self.rebuilds = 0
        self.repartitions = 0
        self.device_epoch = 0  # bumped on every bucket-crossing rebuild
        self.tracker = caching.BucketTracker()
        self._bucket = caching.bucket_key(graph.n, max(graph.m, 1), self.k)
        self.tracker.observe(graph.n, max(graph.m, 1), self.k)
        # drift accumulators since the last committed repartition
        self._pending_mass = 0
        self._pending_cut_mass = 0
        self._stamp()

    # -- identity ------------------------------------------------------

    @property
    def chain(self) -> str:
        return self._chain

    def digest(self) -> str:
        """The session's cache-identity digest.  ``dyn:``-prefixed so it
        can never collide with a plain ``full_graph_digest`` hex string
        of some other (differing) graph — the anti-aliasing guard."""
        return f"dyn:{self._chain}"

    def fingerprint(self) -> str:
        """The checkpoint-identity fingerprint: base fingerprint + the
        chain, so every chain step keys its own manifest."""
        return hashlib.sha256(
            f"dyn:{self.base_fingerprint}:{self._chain}".encode()
        ).hexdigest()[:24]

    def _stamp(self) -> None:
        """Stamp the evolving identity onto the graph object itself —
        the shortcut ``checkpoint.graph_fingerprint`` and
        ``caching.full_graph_digest`` read, which is what keeps a
        mutate O(delta) instead of O(m)."""
        self.graph._session_fp = self.fingerprint()
        self.graph._chain_digest = self.digest()

    # -- delta application ---------------------------------------------

    def apply(self, batch: DeltaBatch) -> dict:
        """Validate + apply one batch.  Returns the apply record:
        ``{"in_place": bool, "n": int, "m": int, "bucket": str,
        "delta_mass": int, "cut_touch_mass": int}``."""
        from ..resilience import with_fallback

        patched = self._patch(batch)
        crossed = patched.bucket != self._bucket
        committed_in_place = False
        if not crossed:
            # the in-place ELIGIBILITY probe is the registered
            # degradation site: an injected `dynamic-apply` fault (or a
            # real in-place failure — a patched bucket disagreeing with
            # the device arrays would be checked here) degrades to the
            # rebuild path.  The probe is deliberately side-effect-free
            # and the commit runs exactly once OUTSIDE the site, so a
            # mid-probe failure can never leave a half-committed
            # session or double-fold the chain
            committed_in_place = bool(with_fallback(
                lambda: self._probe_in_place(patched),
                lambda exc: False,
                site="dynamic-apply", where=self.id,
            ))
        self._commit(batch, patched, in_place=committed_in_place)
        return {
            "in_place": bool(committed_in_place),
            "n": int(self.graph.n),
            "m": int(self.graph.m),
            "bucket": "/".join(str(x) for x in self._bucket),
            "delta_mass": int(patched.delta_mass),
            "cut_touch_mass": int(patched.cut_touch_mass),
        }

    def _probe_in_place(self, patched: _Patched) -> bool:
        """Eligibility check for the in-place commit (pure; raises
        DeltaApplyFailed on a genuine in-place failure — none exist
        today beyond injection, but the hook is where a bucket/device
        agreement check belongs)."""
        return True

    def _commit(self, batch: DeltaBatch, patched: _Patched,
                in_place: bool) -> bool:
        self.graph = patched.graph
        self.partition = patched.partition
        self.deltas_applied += 1
        if in_place:
            self.in_place += 1
        else:
            self.rebuilds += 1
            self.device_epoch += 1
        self._bucket = patched.bucket
        # executable-identity accounting: a same-bucket commit is a
        # tracker HIT (compiled programs reused), a crossing is a miss
        self.tracker.observe(
            patched.graph.n, max(patched.graph.m, 1), self.k)
        self._pending_mass += patched.delta_mass
        self._pending_cut_mass += patched.cut_touch_mass
        self._chain = chain_digest(self._chain, batch.digest())
        self._stamp()
        return True

    # -- repartition bookkeeping ---------------------------------------

    def drift_estimate(self, max_block_weights=None) -> Optional[float]:
        """Accumulated drift since the last committed repartition:
        cut-touching delta mass / total edge mass, plus the balance
        violation of the current (seeded) partition when caps are
        given.  None when the session has no partition yet (a cold run
        is the only option)."""
        if self.partition is None:
            return None
        # delta masses count each undirected edge once; the CSR stores
        # both directions, so the undirected total is half of it
        total = max(int(self.graph.total_edge_weight) // 2, 1)
        drift = self._pending_cut_mass / total
        if max_block_weights is not None:
            part = np.asarray(self.partition)
            labeled = part >= 0
            caps = np.asarray(max_block_weights, dtype=np.int64)
            bw = np.zeros(len(caps), dtype=np.int64)
            np.add.at(
                bw, part[labeled],
                self.graph.node_weight_array()[labeled])
            with np.errstate(divide="ignore"):
                viol = float((bw / np.maximum(caps, 1) - 1.0).max())
            drift += max(0.0, viol)
        return float(drift)

    def set_k(self, k: int) -> None:
        """Re-target the session's block count (the executable bucket
        keys on k, so a change re-anchors the in-place/rebuild
        accounting)."""
        if int(k) != self.k:
            self.k = int(k)
            self._bucket = caching.bucket_key(
                self.graph.n, max(self.graph.m, 1), self.k)
            self.tracker.observe(
                self.graph.n, max(self.graph.m, 1), self.k)

    def commit_partition(self, partition: np.ndarray, cut: int,
                         gate_valid: Optional[bool] = None) -> None:
        """Record a repartition result and fold it into the chain (two
        histories that repartitioned at different points must never
        share an identity — the partition state is part of it)."""
        partition = np.asarray(partition, dtype=np.int32)
        if partition.shape != (self.graph.n,):
            raise ValueError(
                f"partition shape {partition.shape} != ({self.graph.n},)")
        self.partition = partition
        self.last_cut = int(cut)
        self.last_gate_valid = gate_valid
        self.repartitions += 1
        self._pending_mass = 0
        self._pending_cut_mass = 0
        part_digest = hashlib.sha256(partition.tobytes()).hexdigest()[:16]
        self._chain = chain_digest(
            self._chain, f"repart:{self.k}:{part_digest}")
        self._stamp()

    def fold_repartition_marker(self, k: int, part_digest: str) -> None:
        """Replay one repartition link from a stored digest (the chain
        driver's resume path rebuilds the identity without re-running
        the repartitions)."""
        self._chain = chain_digest(self._chain, f"repart:{k}:{part_digest}")
        self._stamp()

    def reset_pending_drift(self) -> None:
        """Zero the drift accumulators to a committed-step boundary.
        The chain driver's resume path calls this after replaying
        deltas: the replayed applies accumulate delta mass (and with no
        partition restored yet, ALL of it counts as cut-touching), but
        the saved boundary is always post-commit where the accumulators
        were 0 — without the reset the first recomputed step's drift is
        inflated by the whole replayed chain and can flip its warm/cold
        decision vs the uninterrupted run."""
        self._pending_mass = 0
        self._pending_cut_mass = 0

    def summary(self) -> dict:
        """The session's row in the run report's ``dynamic`` section."""
        return {
            "id": self.id,
            "n": int(self.graph.n),
            "m": int(self.graph.m),
            "k": int(self.k),
            "deltas_applied": int(self.deltas_applied),
            "in_place": int(self.in_place),
            "rebuilds": int(self.rebuilds),
            "repartitions": int(self.repartitions),
            "chain": self.digest(),
            "bucket": "/".join(str(x) for x in self._bucket),
            "cut": self.last_cut if self.last_cut is None
            else int(self.last_cut),
        }

    # -- the CSR patch (pure; raises GraphFormatError) ------------------

    def _patch(self, batch: DeltaBatch) -> _Patched:
        g = self.graph
        n0, m0 = g.n, g.m
        n1 = n0 + batch.vertex_adds
        part = self.partition

        def _check_pairs(pairs: np.ndarray, what: str) -> None:
            if not len(pairs):
                return
            if pairs.min() < 0 or pairs.max() >= n1:
                raise GraphFormatError(
                    f"{what}: endpoint id out of range [0, {n1})")
            if (pairs[:, 0] == pairs[:, 1]).any():
                raise GraphFormatError(f"{what}: self loops not allowed")

        _check_pairs(batch.edge_inserts, "edge_inserts")
        _check_pairs(batch.edge_deletes, "edge_deletes")
        _check_pairs(batch.edge_weight_updates, "edge_weight_updates")
        for name, w in (("insert_weights", batch.insert_weights),
                        ("update_weights", batch.update_weights),
                        ("add_weights", batch.add_weights)):
            if w is not None and len(w) and w.min() < 1:
                raise GraphFormatError(f"{name}: weights must be >= 1")
        rm = np.unique(batch.vertex_removes)
        if len(rm) != len(batch.vertex_removes):
            raise GraphFormatError("vertex_removes: duplicate ids")
        if len(rm) and (rm.min() < 0 or rm.max() >= n1):
            raise GraphFormatError(
                f"vertex_removes: id out of range [0, {n1})")
        nwu = batch.node_weight_updates
        if len(nwu):
            if nwu[:, 0].min() < 0 or nwu[:, 0].max() >= n1:
                raise GraphFormatError(
                    f"node_weight_updates: id out of range [0, {n1})")
            if nwu[:, 1].min() < 1:
                raise GraphFormatError(
                    "node_weight_updates: weights must be >= 1")

        # current directed COO (both directions of every edge present)
        src = g.edge_sources().astype(np.int64)
        dst = g.adjncy.astype(np.int64)
        w = g.edge_weight_array().copy()
        keys = src * n1 + dst
        order = np.argsort(keys, kind="stable")
        skeys = keys[order]

        def _locate(pairs: np.ndarray, what: str) -> np.ndarray:
            """Directed-pair -> COO index; GraphFormatError on a miss."""
            pk = pairs[:, 0] * n1 + pairs[:, 1]
            pos = np.searchsorted(skeys, pk)
            pos_c = np.minimum(pos, max(len(skeys) - 1, 0))
            ok = len(skeys) > 0
            hit = (pos < len(skeys)) & (
                skeys[pos_c] == pk if ok else np.zeros(len(pk), bool))
            if not hit.all():
                bad = pairs[~hit][0]
                raise GraphFormatError(
                    f"{what}: edge ({int(bad[0] if bad[0] < bad[1] else bad[1])}, "
                    f"{int(max(bad))}) does not exist")
            return order[pos_c]

        def _both_dirs(pairs: np.ndarray) -> np.ndarray:
            return np.concatenate([pairs, pairs[:, ::-1]], axis=0)

        delta_mass = 0
        cut_touch = 0

        def _touch(pairs: np.ndarray, mass: np.ndarray) -> int:
            """Delta mass incident to the current cut: endpoints in
            different blocks, or touching an unlabeled/new vertex."""
            if part is None or not len(pairs):
                return int(mass.sum()) if len(pairs) else 0
            pu = np.where(pairs[:, 0] < n0, pairs[:, 0], -1)
            pv = np.where(pairs[:, 1] < n0, pairs[:, 1], -1)
            lu = np.where(pu >= 0, np.asarray(part)[pu], -1)
            lv = np.where(pv >= 0, np.asarray(part)[pv], -1)
            crossing = (lu != lv) | (lu < 0) | (lv < 0)
            return int(mass[crossing].sum())

        def _check_unique(pairs: np.ndarray, what: str) -> None:
            lo = np.minimum(pairs[:, 0], pairs[:, 1])
            hi = np.maximum(pairs[:, 0], pairs[:, 1])
            ck = lo * n1 + hi
            if len(np.unique(ck)) != len(ck):
                raise GraphFormatError(f"{what}: duplicate pair in batch")

        keep = np.ones(m0, dtype=bool)
        if len(batch.edge_deletes):
            _check_unique(batch.edge_deletes, "edge_deletes")
            idx = _locate(_both_dirs(batch.edge_deletes), "edge_deletes")
            keep[idx] = False
            half = idx[: len(batch.edge_deletes)]
            delta_mass += int(w[half].sum())
            cut_touch += _touch(batch.edge_deletes, w[half])
        if len(batch.edge_weight_updates):
            _check_unique(batch.edge_weight_updates, "edge_weight_updates")
            upd_dir = _both_dirs(batch.edge_weight_updates)
            idx = _locate(upd_dir, "edge_weight_updates")
            if not keep[idx].all():
                raise GraphFormatError(
                    "edge_weight_updates: edge also deleted in this batch")
            old_half = w[idx[: len(batch.edge_weight_updates)]].copy()
            w[idx] = np.concatenate(
                [batch.update_weights, batch.update_weights])
            dmass = np.abs(
                batch.update_weights.astype(np.int64) - old_half)
            delta_mass += int(dmass.sum())
            cut_touch += _touch(batch.edge_weight_updates, dmass)

        ins_src = ins_dst = ins_w = None
        if len(batch.edge_inserts):
            ins = batch.edge_inserts
            ins_w_half = (
                batch.insert_weights.astype(np.int64)
                if batch.insert_weights is not None
                else np.ones(len(ins), dtype=np.int64)
            )
            # canonical undirected key: duplicates within the batch
            # (including reversed restatements) are format errors
            lo = np.minimum(ins[:, 0], ins[:, 1])
            hi = np.maximum(ins[:, 0], ins[:, 1])
            ck = lo * n1 + hi
            if len(np.unique(ck)) != len(ck):
                raise GraphFormatError(
                    "edge_inserts: duplicate pair in batch")
            dk = _both_dirs(ins)
            pk = dk[:, 0] * n1 + dk[:, 1]
            pos = np.searchsorted(skeys, pk)
            pos_c = np.minimum(pos, max(len(skeys) - 1, 0))
            exists = (
                (pos < len(skeys)) & (skeys[pos_c] == pk)
                if len(skeys) else np.zeros(len(pk), bool)
            )
            # an edge deleted in this same batch may be re-inserted
            exists &= keep[order[pos_c]] if len(skeys) else False
            if exists.any():
                bad = dk[exists][0]
                raise GraphFormatError(
                    f"edge_inserts: edge ({int(min(bad))}, "
                    f"{int(max(bad))}) already exists")
            ins_src = dk[:, 0]
            ins_dst = dk[:, 1]
            ins_w = np.concatenate([ins_w_half, ins_w_half])
            delta_mass += int(ins_w_half.sum())
            cut_touch += _touch(ins, ins_w_half)

        # assemble the patched directed COO
        new_src = src[keep]
        new_dst = dst[keep]
        new_w = w[keep]
        if ins_src is not None:
            new_src = np.concatenate([new_src, ins_src])
            new_dst = np.concatenate([new_dst, ins_dst])
            new_w = np.concatenate([new_w, ins_w])

        # node weights: stay None (unit) when nothing weight-shaped
        # touches them, so unit graphs keep their compact form
        unit_adds = batch.add_weights is None or not len(batch.add_weights)
        need_nw = (
            g.node_weights is not None or len(nwu) or not unit_adds
        )
        nw = None
        if need_nw:
            nw = np.concatenate([
                g.node_weight_array(),
                (batch.add_weights if batch.add_weights is not None
                 else np.ones(batch.vertex_adds, dtype=np.int64)),
            ]) if batch.vertex_adds else g.node_weight_array().copy()
            if len(nwu):
                nw = np.asarray(nw).copy()
                nw[nwu[:, 0]] = nwu[:, 1]

        new_part = None
        if part is not None:
            new_part = np.concatenate([
                np.asarray(part, dtype=np.int32),
                np.full(batch.vertex_adds, -1, dtype=np.int32),
            ])

        if len(rm):
            # removed vertices take their incident edge mass with them
            node_keep = np.ones(n1, dtype=bool)
            node_keep[rm] = False
            e_rm = ~(node_keep[new_src] & node_keep[new_dst])
            if e_rm.any():
                gone_w = new_w[e_rm]
                gone_pairs = np.stack(
                    [new_src[e_rm], new_dst[e_rm]], axis=1)
                half = gone_pairs[:, 0] < gone_pairs[:, 1]
                delta_mass += int(gone_w[half].sum())
                cut_touch += _touch(gone_pairs[half], gone_w[half])
            remap = np.cumsum(node_keep) - 1
            new_src = remap[new_src[~e_rm]]
            new_dst = remap[new_dst[~e_rm]]
            new_w = new_w[~e_rm]
            if nw is not None:
                nw = np.asarray(nw)[node_keep]
            if new_part is not None:
                new_part = new_part[node_keep]
            n_new = int(node_keep.sum())
        else:
            n_new = n1

        patched_graph = from_edge_list(
            n_new,
            np.stack([new_src, new_dst], axis=1),
            edge_weights=new_w,
            node_weights=nw,
            symmetrize=False,
        )
        unseeded = (
            int((new_part < 0).sum()) if new_part is not None else 0
        )
        return _Patched(
            graph=patched_graph,
            partition=new_part,
            bucket=caching.bucket_key(
                n_new, max(patched_graph.m, 1), self.k),
            delta_mass=delta_mass,
            cut_touch_mass=cut_touch,
            new_unseeded=unseeded,
        )
