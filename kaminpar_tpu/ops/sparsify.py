"""Edge sparsification on device (linear-time MGP).

Analog of the reference's SparsificationClusterCoarsener
(kaminpar-shm/coarsening/sparsification_cluster_coarsener.cc, arXiv
2504.17615): bounds per-level work by dropping a fraction of edges before
clustering.  The TPU version flips one hashed coin per *undirected* edge
(both directions share the hash of the unordered endpoint pair, so symmetry
is preserved), turns dropped edges into inert pad edges, and rescales kept
edge weights by 1/keep_ratio so expected cut weights are preserved — the
unbiased-sparsifier trick the paper uses.

Dropped edges keep their slots AND their src (static shapes, and the CSR
row spans stay exact — the sort2 rating engine reads per-node results at
row boundaries, segments.py rating_top3_by_sort); only dst is repointed
to the pad node and the weight zeroed, which makes them inert in ratings,
cuts, and contractions.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from ..graphs.csr import DeviceGraph
from .segments import hash_u32


@jax.jit
def sparsify_edges(
    graph: DeviceGraph, keep_ratio: jax.Array, salt: jax.Array
) -> DeviceGraph:
    """Drop each undirected edge with probability 1 - keep_ratio."""
    n_pad = graph.n_pad
    pad_node = n_pad - 1
    lo = jnp.minimum(graph.src, graph.dst)
    hi = jnp.maximum(graph.src, graph.dst)
    h = hash_u32(lo * jnp.int32(1_000_003) + hi, salt)
    ratio = keep_ratio.astype(jnp.float32)
    # clamp below int32 max to a float32-representable bound so the cast
    # cannot wrap; ratio >= 1 keeps everything exactly
    thresh = jnp.minimum(ratio * 2147483647.0, 2147483392.0).astype(jnp.int32)
    keep = (h < thresh) | (ratio >= 1.0)
    is_real = graph.src < graph.n
    drop = is_real & ~keep

    scale = 1.0 / jnp.maximum(ratio, 1e-6)
    new_w = jnp.clip(
        jnp.round(graph.edge_w.astype(jnp.float32) * scale),
        0,
        2147483392.0,  # largest float32 below 2**31
    ).astype(graph.edge_w.dtype)

    return DeviceGraph(
        row_ptr=graph.row_ptr,
        src=graph.src,  # keep: CSR row spans must stay exact for sort2
        dst=jnp.where(drop, pad_node, graph.dst),
        edge_w=jnp.where(drop, 0, jnp.where(is_real, new_w, 0)),
        node_w=graph.node_w,
        n=graph.n,
        m=graph.m,
    )
