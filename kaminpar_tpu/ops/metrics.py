"""Partition quality metrics on device.

Analog of kaminpar-shm/metrics.{h,cc}: edge_cut (metrics.cc:37, a TBB
parallel reduction there — a masked segment sum here), imbalance,
total_overload, is_feasible / is_balanced (metrics.h:17-86).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..graphs.csr import DeviceGraph
from .segments import ACC_DTYPE


def block_weights(
    graph: DeviceGraph, partition: jax.Array, k: int
) -> jax.Array:
    """Sum of node weights per block, int64[k].  Pad nodes carry weight 0 so
    no masking is needed (csr.py padding convention)."""
    part = jnp.clip(partition, 0, k - 1)
    return jax.ops.segment_sum(
        graph.node_w.astype(ACC_DTYPE), part, num_segments=k
    )


def edge_cut(graph: DeviceGraph, partition: jax.Array) -> jax.Array:
    """Total weight of cut edges (each undirected edge counted once).
    Mirrors shm::metrics::edge_cut (metrics.cc:37)."""
    cut2 = jnp.sum(
        jnp.where(
            partition[graph.src] != partition[graph.dst],
            graph.edge_w.astype(ACC_DTYPE),
            0,
        )
    )
    return cut2 // 2


def imbalance(graph: DeviceGraph, partition: jax.Array, k: int) -> jax.Array:
    """max_b weight(b) / ceil(total/k) - 1 (metrics.h imbalance)."""
    bw = block_weights(graph, partition, k)
    total = graph.total_node_weight()
    perfect = (total + k - 1) // k
    return bw.max().astype(jnp.float32) / jnp.maximum(perfect, 1).astype(
        jnp.float32
    ) - 1.0


def total_overload(
    graph: DeviceGraph, partition: jax.Array, max_block_weights: jax.Array
) -> jax.Array:
    """Sum of max(0, weight(b) - L_max(b)) (metrics.h total_overload)."""
    k = max_block_weights.shape[0]
    bw = block_weights(graph, partition, k)
    return jnp.sum(jnp.maximum(bw - max_block_weights.astype(ACC_DTYPE), 0))


def is_balanced(
    graph: DeviceGraph, partition: jax.Array, max_block_weights: jax.Array
) -> jax.Array:
    return total_overload(graph, partition, max_block_weights) == 0


def is_feasible(
    graph: DeviceGraph,
    partition: jax.Array,
    max_block_weights: jax.Array,
    min_block_weights: jax.Array | None = None,
) -> jax.Array:
    """Balanced above and (optionally) below (metrics.h is_feasible)."""
    k = max_block_weights.shape[0]
    bw = block_weights(graph, partition, k)
    ok = jnp.all(bw <= max_block_weights.astype(ACC_DTYPE))
    if min_block_weights is not None:
        ok = ok & jnp.all(bw >= min_block_weights.astype(ACC_DTYPE))
    return ok
