"""Partition quality metrics on device.

Analog of kaminpar-shm/metrics.{h,cc}: edge_cut (metrics.cc:37, a TBB
parallel reduction there — a masked segment sum here), imbalance,
total_overload, is_feasible / is_balanced (metrics.h:17-86).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..graphs.csr import DeviceGraph
from .segments import ACC_DTYPE


def block_weights(
    graph: DeviceGraph, partition: jax.Array, k: int
) -> jax.Array:
    """Sum of node weights per block, int64[k].  Pad nodes carry weight 0 so
    no masking is needed (csr.py padding convention)."""
    part = jnp.clip(partition, 0, k - 1)
    return jax.ops.segment_sum(
        graph.node_w.astype(ACC_DTYPE), part, num_segments=k
    )


def edge_cut(graph: DeviceGraph, partition: jax.Array) -> jax.Array:
    """Total weight of cut edges (each undirected edge counted once).
    Mirrors shm::metrics::edge_cut (metrics.cc:37)."""
    cut2 = jnp.sum(
        jnp.where(
            partition[graph.src] != partition[graph.dst],
            graph.edge_w.astype(ACC_DTYPE),
            0,
        )
    )
    return cut2 // 2


#: Jitted twin of :func:`edge_cut` for the host-driven observability
#: paths (telemetry/quality.py evaluates per-level projected / refined /
#: floor cuts between launches): one compiled reduction per shape
#: bucket, reused across levels, entirely separate from the LP / Jet /
#: contraction programs (their jaxprs stay bitwise-identical whether
#: the quality layer runs or not).
edge_cut_jit = jax.jit(edge_cut)


@jax.jit
def coarsening_stats(
    fine_graph: DeviceGraph, coarse_graph: DeviceGraph, cmap: jax.Array
):
    """Per-contraction coarsening-quality scalars (telemetry/quality.py):

    returns (fine_edge_weight, coarse_edge_weight, max_cluster_size,
    singleton_clusters, max_cluster_weight) — both edge-weight sums
    count each undirected edge twice (pad edges carry weight 0), so
    1 - coarse/fine is the exact internalized-edge-weight ratio; the
    cluster-size figures come from the projection map and the coarse
    node weights ARE the cluster weights."""
    fine_ew = jnp.sum(fine_graph.edge_w.astype(ACC_DTYPE))
    coarse_ew = jnp.sum(coarse_graph.edge_w.astype(ACC_DTYPE))
    n_pad_f = cmap.shape[0]
    n_pad_c = coarse_graph.node_w.shape[0]
    is_real_f = jnp.arange(n_pad_f) < fine_graph.n
    sizes = jax.ops.segment_sum(
        is_real_f.astype(ACC_DTYPE),
        jnp.clip(cmap, 0, n_pad_c - 1),
        num_segments=n_pad_c,
    )
    is_real_c = jnp.arange(n_pad_c) < coarse_graph.n
    max_size = jnp.max(jnp.where(is_real_c, sizes, 0))
    singletons = jnp.sum(
        jnp.where(is_real_c & (sizes == 1), 1, 0).astype(ACC_DTYPE)
    )
    max_w = jnp.max(
        jnp.where(is_real_c, coarse_graph.node_w.astype(ACC_DTYPE), 0)
    )
    return fine_ew, coarse_ew, max_size, singletons, max_w


def imbalance(graph: DeviceGraph, partition: jax.Array, k: int) -> jax.Array:
    """max_b weight(b) / ceil(total/k) - 1 (metrics.h imbalance)."""
    bw = block_weights(graph, partition, k)
    total = graph.total_node_weight()
    perfect = (total + k - 1) // k
    return bw.max().astype(jnp.float32) / jnp.maximum(perfect, 1).astype(
        jnp.float32
    ) - 1.0


def total_overload(
    graph: DeviceGraph, partition: jax.Array, max_block_weights: jax.Array
) -> jax.Array:
    """Sum of max(0, weight(b) - L_max(b)) (metrics.h total_overload)."""
    k = max_block_weights.shape[0]
    bw = block_weights(graph, partition, k)
    return jnp.sum(jnp.maximum(bw - max_block_weights.astype(ACC_DTYPE), 0))


def is_balanced(
    graph: DeviceGraph, partition: jax.Array, max_block_weights: jax.Array
) -> jax.Array:
    return total_overload(graph, partition, max_block_weights) == 0


def is_feasible(
    graph: DeviceGraph,
    partition: jax.Array,
    max_block_weights: jax.Array,
    min_block_weights: jax.Array | None = None,
) -> jax.Array:
    """Balanced above and (optionally) below (metrics.h is_feasible)."""
    k = max_block_weights.shape[0]
    bw = block_weights(graph, partition, k)
    ok = jnp.all(bw <= max_block_weights.astype(ACC_DTYPE))
    if min_block_weights is not None:
        ok = ok & jnp.all(bw >= min_block_weights.astype(ACC_DTYPE))
    return ok
