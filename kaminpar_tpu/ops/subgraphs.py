"""Device-side block-induced subgraph extraction.

The TPU counterpart of the reference's preallocated-SubgraphMemory
extraction (kaminpar-shm/graphutils/subgraph_extractor.h:36-177), used by
deep multilevel's extend_partition (helper.cc:220,349).  Round 2 extracted
subgraphs on the host, which meant a FULL graph readback (hundreds of MB
through the remote tunnel) at every k-doubling — 42.8 s of the 10M-edge
run.  Here the extraction is one device program:

  * nodes are permuted block-major (one n-wide stable sort by block id),
    giving each node a local index inside its block;
  * edges are filtered to intra-block and sorted by (block, local src)
    (one m-wide 2-key sort), giving each block a contiguous CSR slice;
  * per-block node/edge counts and block weights come back to the host in
    ONE small readback (k-length arrays) — the only host<->device traffic
    that scales with k, not with the graph.

Each block's subgraph is then packaged into the standard padded
DeviceGraph layout by `slice_block` (per-shape-bucket programs shared
across blocks and levels), and the doubled partition is assembled back on
device by `assemble_extended_partition` — the inverse permutation never
leaves the device.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..graphs.csr import DeviceGraph, NODE_DTYPE
from ..caching import pad_size
from .segments import ACC_DTYPE


class BlockExtraction(NamedTuple):
    """Device-side extraction state (all arrays stay on device).

    b         : i32[n_pad]   block of each node (k for pad nodes)
    new_id    : i32[n_pad]   local index of each node within its block
    node_start: i32[k+2]     prefix starts of the block-major node order
    edge_start: i32[k+2]     prefix starts of the block-major edge order
    ls_s/ld_s : i32[m_pad]   block-sorted edges, LOCAL endpoint ids
    w_s       : [m_pad]      block-sorted edge weights
    node_w_s  : [n_pad]      block-major node weights
    rowcount_s: i32[n_pad]   block-major per-node intra-block degree
    node_counts/edge_counts/block_weights : host numpy [k+1]
    """

    b: jax.Array
    new_id: jax.Array
    node_start: jax.Array
    edge_start: jax.Array
    ls_s: jax.Array
    ld_s: jax.Array
    w_s: jax.Array
    node_w_s: jax.Array
    rowcount_s: jax.Array
    node_counts: np.ndarray
    edge_counts: np.ndarray
    block_weights: np.ndarray


@partial(jax.jit, static_argnames=("k",))
def _extract_kernel(graph: DeviceGraph, partition: jax.Array, k: int):
    n_pad = graph.n_pad
    m_pad = graph.m_pad
    node_ids = jnp.arange(n_pad, dtype=NODE_DTYPE)
    is_real = node_ids < graph.n
    b = jnp.where(is_real, jnp.clip(partition, 0, k - 1), k).astype(
        NODE_DTYPE
    )

    # ---- block-major node order (stable: ids stay ascending per block)
    b_s, perm = lax.sort((b, node_ids), num_keys=1)
    node_counts = jax.ops.segment_sum(
        jnp.ones(n_pad, dtype=NODE_DTYPE), b, num_segments=k + 1
    )
    node_start = jnp.concatenate(
        [jnp.zeros(1, NODE_DTYPE), jnp.cumsum(node_counts)]
    ).astype(NODE_DTYPE)
    pos = jnp.arange(n_pad, dtype=NODE_DTYPE)
    new_id_sorted = pos - node_start[b_s]
    new_id = (
        jnp.zeros(n_pad, dtype=NODE_DTYPE)
        .at[perm]
        .set(new_id_sorted, mode="drop")
    )
    node_w_s = graph.node_w[perm]
    block_weights = jax.ops.segment_sum(
        graph.node_w.astype(ACC_DTYPE), b, num_segments=k + 1
    )

    # ---- intra-block edges, block-major, local endpoints
    bs = b[graph.src]
    bd = b[graph.dst]
    valid = graph.edge_mask()
    keep = valid & (bs == bd) & (bs < k)
    ekey = jnp.where(keep, bs, k).astype(NODE_DTYPE)
    ls = jnp.where(keep, new_id[graph.src], 0).astype(NODE_DTYPE)
    ld = jnp.where(keep, new_id[graph.dst], 0).astype(NODE_DTYPE)
    w = jnp.where(keep, graph.edge_w, 0)
    ekey_s, ls_s, ld_s, w_s = lax.sort((ekey, ls, ld, w), num_keys=2)
    edge_counts = jax.ops.segment_sum(
        jnp.ones(m_pad, dtype=NODE_DTYPE), ekey, num_segments=k + 1
    )
    edge_start = jnp.concatenate(
        [jnp.zeros(1, NODE_DTYPE), jnp.cumsum(edge_counts)]
    ).astype(NODE_DTYPE)

    # ---- per-node intra-block degree in block-major order
    edge_pos = jnp.where(keep, node_start[bs] + new_id[graph.src], n_pad)
    rowcount_s = jax.ops.segment_sum(
        jnp.ones(m_pad, dtype=NODE_DTYPE), edge_pos, num_segments=n_pad + 1
    )[:n_pad]

    return (
        b, new_id, node_start, edge_start, ls_s, ld_s, w_s, node_w_s,
        rowcount_s, node_counts, edge_counts, block_weights,
    )


def extract_blocks_device(
    graph: DeviceGraph, partition: jax.Array, k: int
) -> BlockExtraction:
    """Run the extraction kernel; one small host readback for the counts."""
    (
        b, new_id, node_start, edge_start, ls_s, ld_s, w_s, node_w_s,
        rowcount_s, node_counts_d, edge_counts_d, block_weights_d,
    ) = _extract_kernel(graph, partition, k)
    return BlockExtraction(
        b=b,
        new_id=new_id,
        node_start=node_start,
        edge_start=edge_start,
        ls_s=ls_s,
        ld_s=ld_s,
        w_s=w_s,
        node_w_s=node_w_s,
        rowcount_s=rowcount_s,
        node_counts=np.asarray(node_counts_d),
        edge_counts=np.asarray(edge_counts_d),
        block_weights=np.asarray(block_weights_d),
    )


@partial(jax.jit, static_argnames=("n_pad_sub", "m_pad_sub"))
def _slice_block_kernel(
    ls_s: jax.Array,
    ld_s: jax.Array,
    w_s: jax.Array,
    node_w_s: jax.Array,
    rowcount_s: jax.Array,
    node_start_b: jax.Array,
    n_b: jax.Array,
    edge_start_b: jax.Array,
    m_b: jax.Array,
    n_pad_sub: int,
    m_pad_sub: int,
):
    """Package one block's slice of the block-major arrays into the
    standard padded DeviceGraph layout (pad node = n_pad_sub - 1)."""
    pad_node = n_pad_sub - 1
    ni = jnp.arange(n_pad_sub, dtype=NODE_DTYPE)
    n_mask = ni < n_b
    npos = jnp.clip(node_start_b + ni, 0, node_w_s.shape[0] - 1)
    node_w = jnp.where(n_mask, node_w_s[npos], 0).astype(node_w_s.dtype)
    rowcount = jnp.where(n_mask, rowcount_s[npos], 0).astype(NODE_DTYPE)
    row_ptr = jnp.concatenate(
        [jnp.zeros(1, NODE_DTYPE), jnp.cumsum(rowcount).astype(NODE_DTYPE)]
    )
    row_ptr = jnp.minimum(row_ptr, m_b).astype(NODE_DTYPE)

    ei = jnp.arange(m_pad_sub, dtype=NODE_DTYPE)
    e_mask = ei < m_b
    epos = jnp.clip(edge_start_b + ei, 0, ls_s.shape[0] - 1)
    src = jnp.where(e_mask, ls_s[epos], pad_node).astype(NODE_DTYPE)
    dst = jnp.where(e_mask, ld_s[epos], pad_node).astype(NODE_DTYPE)
    edge_w = jnp.where(e_mask, w_s[epos], 0).astype(w_s.dtype)
    return row_ptr, src, dst, edge_w, node_w


def slice_block(
    ext: BlockExtraction, block: int, n_floor: int, m_floor: int
) -> Tuple[DeviceGraph, int, int]:
    """Build block `block`'s subgraph as a padded DeviceGraph.
    Returns (subgraph, n_b, m_b)."""
    n_b = int(ext.node_counts[block])
    m_b = int(ext.edge_counts[block])
    n_pad_sub = pad_size(n_b + 1, n_floor)
    m_pad_sub = pad_size(max(m_b, 1), m_floor)
    from ..caching import record_padding

    record_padding(n=n_b + 1, n_pad=n_pad_sub, m=m_b, m_pad=m_pad_sub)
    row_ptr, src, dst, edge_w, node_w = _slice_block_kernel(
        ext.ls_s, ext.ld_s, ext.w_s, ext.node_w_s, ext.rowcount_s,
        ext.node_start[block], jnp.int32(n_b),
        ext.edge_start[block], jnp.int32(m_b),
        n_pad_sub, m_pad_sub,
    )
    sub = DeviceGraph(
        row_ptr=row_ptr,
        src=src,
        dst=dst,
        edge_w=edge_w,
        node_w=node_w,
        n=jnp.int32(n_b),
        m=jnp.int32(m_b),
    )
    return sub, n_b, m_b


def host_graph_from_padded(sub: DeviceGraph, n_b: int, m_b: int):
    """Download a (small) padded subgraph and trim on the host.  A plain
    array transfer — no per-shape device slicing programs."""
    from ..graphs.host import HostGraph

    xadj = np.asarray(sub.row_ptr)[: n_b + 1].astype(np.int64)
    adjncy = np.asarray(sub.dst)[:m_b].astype(np.int32)
    edge_w = np.asarray(sub.edge_w)[:m_b].astype(np.int64)
    node_w = np.asarray(sub.node_w)[:n_b].astype(np.int64)
    return HostGraph(
        xadj=xadj,
        adjncy=adjncy,
        node_weights=None if (node_w == 1).all() else node_w,
        edge_weights=None if m_b == 0 or (edge_w == 1).all() else edge_w,
    )


@partial(jax.jit, static_argnames=("k",))
def assemble_extended_partition(
    b: jax.Array,
    new_id: jax.Array,
    node_start: jax.Array,
    bp_global: jax.Array,
    base_id: jax.Array,
    is_split: jax.Array,
    k: int,
) -> jax.Array:
    """new_part[v] = base_id[b(v)] + (bp of v if its block was split).

    `bp_global` holds each split block's bipartition in block-major node
    order (see scatter in the driver); non-split blocks read 0."""
    bv = jnp.clip(b, 0, k - 1)
    pos = jnp.clip(node_start[bv] + new_id, 0, bp_global.shape[0] - 1)
    side = jnp.where(is_split[bv], bp_global[pos], 0)
    return (base_id[bv] + side).astype(jnp.int32)


@partial(jax.jit, static_argnames=("n_pad_sub",))
def scatter_block_bipartition(
    bp_global: jax.Array,
    bp_sub: jax.Array,
    node_start_b: jax.Array,
    n_b: jax.Array,
    n_pad_sub: int,
) -> jax.Array:
    """Write one block's bipartition (padded local array) into the
    block-major global buffer."""
    ni = jnp.arange(n_pad_sub, dtype=NODE_DTYPE)
    tgt = jnp.where(ni < n_b, node_start_b + ni, bp_global.shape[0])
    return bp_global.at[tgt].set(
        jnp.where(ni < n_b, bp_sub[:n_pad_sub].astype(jnp.int32), 0),
        mode="drop",
    )
