"""Pallas TPU kernels for the regular-access hot ops.

The pipeline's irregular ops (edge gathers, scatters) have no Pallas
path on TPU — vector fancy-indexing is rejected by the Mosaic lowering
("Cannot do int indexing on TPU"), so the XLA gather is their floor
(see docs/performance.md).  The *regular* hot op that does benefit is
the dense (n, k) best-block reduction used by every refinement round
(segments.best_from_dense): XLA materializes ~6 (n, k) temporaries
(feasibility mask, score, two maxes, tie hashes, winner mask) through
HBM, while one Pallas kernel streams a (TILE_N, k) block through VMEM
once and emits the three n-vectors directly.

The kernel is numerically identical to the XLA path (verified on
device) and ~8x faster *standalone*: 0.13 s vs ~1 s for the XLA chain
at n=2^20, k=16.  Inside the big fused refinement programs, however,
XLA's own fusion already keeps the chain in registers/VMEM and the
measured Jet iteration time is unchanged — while the embedded
pallas_call changes every program hash and forces a full recompile of
the persistent cache.  The dispatch is therefore OPT-IN: set
KAMINPAR_TPU_PALLAS=1 to route `best_from_dense` through this kernel
on TPU (no community mask, k <= 128, n_pad % 1024 == 0).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# the hash MUST be the same function as the XLA path's tie-break — a
# diverging copy would produce different partitions under the opt-in
# (it is plain jnp ops, kernel-safe; no import cycle: segments imports
# this module only lazily inside best_from_dense)
from .segments import INT32_MIN, hash_u32 as _hash_u32

TILE_N = 1024  # 1D int32 XLA layout tile on TPU (Mosaic requires matching blocks)


def eligible(n_pad: int, k: int) -> bool:
    """Kernel preconditions (single source for the dispatch guard)."""
    return n_pad % TILE_N == 0 and k <= 128


def _kernel(
    salt_ref,
    conn_ref,
    labels_ref,
    cw_ref,
    node_w_ref,
    cap_ref,
    allowed_ref,
    best_ref,
    best_w_ref,
    w_own_ref,
    *,
    k: int,
    require_fit: bool,
):
    conn = conn_ref[...]  # (TILE_N, k)
    labels = labels_ref[...]  # (TILE_N,)
    salt = salt_ref[0]
    cols = jax.lax.broadcasted_iota(jnp.int32, (TILE_N, k), 1)
    lab_col = jnp.clip(labels, 0, k - 1)[:, None]

    own = cols == lab_col
    # w_own via masked reduction: conn[row, label[row]] without indexing
    w_own = jnp.max(jnp.where(own, conn, INT32_MIN), axis=1)

    feas = ~own
    feas = feas & (allowed_ref[...][None, :] != 0)
    if require_fit:
        fits = (
            cw_ref[...][None, :] + node_w_ref[...][:, None]
            <= cap_ref[...][None, :]
        )
        feas = feas & fits

    score = jnp.where(feas, conn, INT32_MIN)
    best_w = jnp.max(score, axis=1)
    has = best_w > INT32_MIN
    is_best = feas & (score == best_w[:, None])
    tb = _hash_u32(cols, salt)
    best_tb = jnp.max(jnp.where(is_best, tb, -1), axis=1)
    winner = is_best & (tb == best_tb[:, None])
    best = jnp.max(jnp.where(winner, cols, -1), axis=1)

    best_ref[...] = jnp.where(has, best, -1)
    best_w_ref[...] = jnp.where(has, best_w, INT32_MIN)
    w_own_ref[...] = w_own


@functools.partial(
    jax.jit, static_argnames=("require_fit", "interpret")
)
def best_from_dense_pallas(
    conn,
    labels,
    cluster_weights,
    node_w,
    cap,
    salt,
    require_fit: bool = True,
    allowed=None,
    interpret: bool = False,
):
    """Pallas twin of segments.best_from_dense (no `communities` mask)."""
    n_pad, k = conn.shape
    assert n_pad % TILE_N == 0, n_pad
    cap_b = jnp.broadcast_to(
        jnp.asarray(cap, dtype=jnp.int32), (k,)
    )
    allowed_i = (
        jnp.ones((k,), dtype=jnp.int32)
        if allowed is None
        else jnp.asarray(allowed).astype(jnp.int32)
    )
    salt_arr = jnp.asarray(salt, dtype=jnp.int32).reshape((1,))
    grid = (n_pad // TILE_N,)
    row_block = pl.BlockSpec((TILE_N, k), lambda i: (i, 0))
    vec_block = pl.BlockSpec((TILE_N,), lambda i: (i,))
    k_block = pl.BlockSpec((k,), lambda i: (0,))
    out = pl.pallas_call(
        functools.partial(_kernel, k=k, require_fit=require_fit),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1,), lambda i: (0,)),  # salt
            row_block,  # conn
            vec_block,  # labels
            k_block,  # cluster_weights
            vec_block,  # node_w
            k_block,  # cap
            k_block,  # allowed
        ],
        out_specs=[vec_block, vec_block, vec_block],
        out_shape=[
            jax.ShapeDtypeStruct((n_pad,), jnp.int32),
            jax.ShapeDtypeStruct((n_pad,), jnp.int32),
            jax.ShapeDtypeStruct((n_pad,), jnp.int32),
        ],
        interpret=interpret,
    )(
        salt_arr,
        conn.astype(jnp.int32),
        jnp.asarray(labels, dtype=jnp.int32),
        jnp.asarray(cluster_weights, dtype=jnp.int32),
        jnp.asarray(node_w, dtype=jnp.int32),
        cap_b,
        allowed_i,
    )
    return tuple(out)


@functools.lru_cache(maxsize=1)
def pallas_available() -> bool:
    """Opt-in check + one-time probe that the kernel compiles here."""
    if not os.environ.get("KAMINPAR_TPU_PALLAS"):
        return False
    try:
        if jax.devices()[0].platform not in ("tpu", "axon"):
            return False
        conn = jnp.zeros((TILE_N, 4), dtype=jnp.int32)
        r = best_from_dense_pallas(
            conn,
            jnp.zeros(TILE_N, dtype=jnp.int32),
            jnp.zeros(4, dtype=jnp.int32),
            jnp.zeros(TILE_N, dtype=jnp.int32),
            jnp.zeros(4, dtype=jnp.int32),
            jnp.int32(0),
        )
        jax.block_until_ready(r)
        return True
    except Exception:  # pragma: no cover - backend specific
        return False
