"""Jet refinement on device.

Analog of kaminpar-shm/refinement/jet/jet_refiner.cc, itself an
implementation of "Jet: Multilevel Graph Partitioning on GPUs" (Gilbert et
al.) — the reference's most TPU-amenable refiner, and here it runs as a
fully fused device loop.  Per iteration (jet_refiner.cc:100-214):

  1. find:     every unlocked border node picks its best external block;
               it becomes a candidate if best_gain > -floor(temp * conn_own)
               (the gain temperature admits slightly-negative moves);
  2. filter    ("afterburner"): each candidate's gain is re-evaluated
               assuming every neighbor with strictly better (gain, id) order
               is already at its tentative destination; only candidates with
               positive adjusted gain are locked in;
  3. execute:  apply locked moves in bulk;
  4. rebalance with the overload balancer;
  5. keep the best-cut partition seen; stop after `num_fruitless_iterations`
     without sufficient improvement (fruitless_threshold) and roll back.

The candidate/filter/execute steps are already bulk-synchronous in the
reference (it is a GPU algorithm run on CPU threads); the TPU version
expresses them as whole-graph segment reductions, and the iteration loop is
a lax.while_loop so an entire Jet pass is one XLA program.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..context import JetRefinementContext
from ..graphs.csr import DeviceGraph
from ..telemetry import progress as progress_mod
from .balancer import overload_balance_round
from .metrics import edge_cut
# the dense rate+argmax core is shared with LP through ops/rating.py —
# one public home for every rating engine (see its module docstring)
from .rating import best_from_dense, dense_block_ratings
from .segments import (
    ACC_DTYPE,
    INT32_MIN,
    MAX_FUSED_EDGE_SLOTS,
    expand_active_rows,
    packed_afterburner_gain,
    packed_afterburner_gain_rows,
    prune_candidates_to_budget,
)

# Below this many edge slots the incremental machinery is not worth the
# extra programs (mirrors ops/lp.DELTA_MIN_EDGE_SLOTS).
DELTA_MIN_EDGE_SLOTS = 1 << 22

# Largest dense (n_pad, k) conn table Jet will materialize (int32
# entries; 2^28 = 1 GiB).  Above it jet_refine degrades to LP
# refinement rounds (see entry point).
JET_DENSE_MAX_ENTRIES = 1 << 28


def _delta_slots(graph: DeviceGraph) -> int | None:
    m_slots = graph.src.shape[0]
    if m_slots < DELTA_MIN_EDGE_SLOTS:
        return None
    return m_slots // 4


def _full_ratings(graph: DeviceGraph, part: jax.Array, k: int,
                  plans=None) -> jax.Array:
    """Full dense rating table; routes the block lookup through the lane
    gather when the caller threaded the level's plans in (built eagerly
    outside jit — see ops/lane_gather.py and lp.lp_cluster)."""
    if plans is not None:
        from .lane_gather import routed_block_ratings

        return routed_block_ratings(plans, part, k, graph.n_pad)
    return dense_block_ratings(
        graph.src, graph.dst, graph.edge_w, part, graph.n_pad, k
    )


def _conn_cut(
    graph: DeviceGraph, conn: jax.Array, part: jax.Array, wdeg: jax.Array,
    k: int,
) -> jax.Array:
    """Exact cut of `part` from its conn table:
    sum over real nodes of (weighted degree - connection to own block),
    halved (each cut edge counts at both endpoints)."""
    is_real = jnp.arange(graph.n_pad, dtype=jnp.int32) < graph.n
    conn_own = jnp.take_along_axis(
        conn, jnp.clip(part, 0, k - 1)[:, None], axis=1
    )[:, 0]
    return jnp.sum(
        jnp.where(is_real, wdeg - conn_own, 0).astype(ACC_DTYPE)
    ) // 2


def _scatter_conn_delta_cols(
    conn: jax.Array,
    old_b: jax.Array,
    new_b: jax.Array,
    dst_b: jax.Array,
    w_b: jax.Array,
    k: int,
    n_pad: int,
) -> jax.Array:
    """Apply a bulk-move delta to the dense (n, k) connection table from
    prepared per-slot columns: for each edge (u, v) with u moved a->b,
    conn[v, a] -= w and conn[v, b] += w.  Exact integer arithmetic — the
    table stays bitwise equal to a full rebuild.  Callers zero w_b on
    edges whose owner did not move; `old_b`/`new_b` are the owner's
    before/after blocks PER SLOT (already gathered by the caller)."""
    flat_old = dst_b * k + jnp.clip(old_b, 0, k - 1)
    flat_new = dst_b * k + jnp.clip(new_b, 0, k - 1)
    flat_conn = conn.reshape(-1)
    flat_conn = flat_conn.at[flat_old].add(-w_b, mode="drop")
    flat_conn = flat_conn.at[flat_new].add(w_b, mode="drop")
    return flat_conn.reshape(n_pad, k)


def _conn_update_rows(
    graph: DeviceGraph,
    conn: jax.Array,
    part_before: jax.Array,
    part_after: jax.Array,
    k: int,
    dslots: int,
) -> jax.Array:
    """Expand the changed nodes' CSR rows and apply the conn-table delta
    (see _scatter_conn_delta_cols).  The owner's before/after blocks ride
    ONE gather, bit-packed as before * k + after (both < k, so the
    product stays far inside int32)."""
    n_pad = graph.n_pad
    changed = part_before != part_after
    owner_c, _, edge_id, valid, start, end = expand_active_rows(
        graph.row_ptr, graph.degrees, changed, dslots
    )
    eid = jnp.clip(edge_id, 0, graph.src.shape[0] - 1)
    dst_b = jnp.where(valid, graph.dst[eid], n_pad - 1)
    w_b = jnp.where(valid, graph.edge_w[eid], 0).astype(ACC_DTYPE)
    pb_c = jnp.clip(part_before, 0, k - 1)
    pa_c = jnp.clip(part_after, 0, k - 1)
    pba = (pb_c * k + pa_c)[owner_c]
    return _scatter_conn_delta_cols(
        conn, pba // k, pba % k, dst_b, w_b, k, n_pad
    )


def _jet_iteration(
    graph: DeviceGraph,
    part: jax.Array,
    lock: jax.Array,
    k: int,
    max_block_weights: jax.Array,
    gain_temp: jax.Array,
    salt: jax.Array,
    balancer_rounds: int,
    wdeg: jax.Array | None = None,
    conn: jax.Array | None = None,
    plans=None,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """One Jet move round.  Returns (new_part, new_lock, ext_sum,
    new_conn) where ext_sum = sum over real nodes of (weighted degree -
    connection to own block) in the INPUT partition — the rating table
    gives the input partition's edge cut for free as ext_sum / 2, saving
    the driver a separate edge-wide cut pass per iteration.  ext_sum =
    2*cut stays in int32 exactly when edge_cut itself would (unlike a
    total-edge-weight sum, which overflows first on heavy graphs).
    `wdeg` is the static per-node weighted degree; when None, ext_sum is
    returned as 0 (the caller does not use it).

    `conn` is the incrementally-maintained dense (n, k) connection table
    for the INPUT partition (the gain cache Jet's paper assumes).  When
    None it is built from scratch; the returned new_conn matches the
    OUTPUT partition bitwise either way (changed rows re-scattered, or a
    full rebuild when too many nodes moved — lax.cond picks)."""
    n_pad = graph.n_pad
    node_ids = jnp.arange(n_pad, dtype=jnp.int32)
    is_real = node_ids < graph.n
    dslots = _delta_slots(graph)

    # ---- find moves (jet_refiner.cc:104-131) ----
    # dense (n, k) rating table: one segment_sum, no edge-list sort (the
    # gain-cache strategy Jet's paper assumes; caps checked by the
    # balancer, so require_fit=False like the reference's candidate step)
    if conn is None:
        conn = _full_ratings(graph, part, k, plans)
    best, best_conn, conn_own = best_from_dense(
        conn, part, jnp.zeros((k,), ACC_DTYPE), graph.node_w,
        jnp.zeros((k,), ACC_DTYPE), salt, require_fit=False,
    )
    if wdeg is not None:
        ext_sum = jnp.sum(
            jnp.where(is_real, wdeg - conn_own, 0).astype(ACC_DTYPE)
        )
    else:
        ext_sum = jnp.int32(0)
    gain = best_conn - conn_own  # gain of moving to best external block
    is_border = best >= 0
    threshold = -jnp.floor(gain_temp * conn_own.astype(jnp.float32)).astype(
        jnp.int32
    )
    candidate = (
        is_real & is_border & (lock == 0) & (gain > threshold)
    )

    # ---- filter: afterburner (jet_refiner.cc:133-170) ----
    # bit-packed endpoint metadata + streaming row sums, with a runtime
    # clip-range guard; see segments.packed_afterburner_gain_rows
    # (shared with LP refinement).
    # Only edges of CANDIDATE rows contribute to the filter.  On large
    # graphs the candidate set is first PRUNED to the best-gain subset
    # whose rows fit the delta buffer (two-stage candidate pruning), so
    # the filter's two gathers ALWAYS run at buffer width — no edge-wide
    # fallback; pruned candidates compete again next iteration.
    if dslots is None:
        next_part = jnp.where(candidate, best, part)
        adj_gain = packed_afterburner_gain(
            graph.src, graph.dst, graph.edge_w, graph.row_ptr,
            part, next_part, gain, candidate, k,
        )
        owner_c = dst_b = w_b = from_u = to_u = None
    else:
        candidate = prune_candidates_to_budget(
            candidate, gain, graph.degrees, salt ^ 0x5BD1E995, dslots
        )
        next_part = jnp.where(candidate, best, part)
        owner_c, _, edge_id, valid, start, end = expand_active_rows(
            graph.row_ptr, graph.degrees, candidate, dslots
        )
        eid = jnp.clip(edge_id, 0, graph.src.shape[0] - 1)
        dst_b = jnp.where(valid, graph.dst[eid], n_pad - 1)
        w_b = jnp.where(valid, graph.edge_w[eid], 0)
        # bit-packed endpoint metadata: one gather per endpoint; the
        # owner's (from, to) blocks come back for the conn-delta reuse
        adj_gain, from_u, to_u = packed_afterburner_gain_rows(
            owner_c, dst_b, w_b, start, end,
            part, next_part, gain, candidate, k,
        )
    accept = candidate & (adj_gain > 0)

    # ---- execute (jet_refiner.cc:172-183) ----
    new_part = jnp.where(accept, next_part, part)
    new_lock = accept.astype(jnp.int32)  # moved nodes rest next iteration

    # ---- maintain the rating table across the jet moves ----
    # when few nodes changed, re-scatter only their rows
    def _conn_step(conn_, before, after):
        if dslots is None:
            return _full_ratings(graph, after, k, plans)
        # degree total <= m_pad < 2^31 (device layout)
        # tpulint: disable=R3
        changed_edges = jnp.sum(
            jnp.where(before != after, graph.degrees, 0), dtype=jnp.int32
        )
        return lax.cond(
            changed_edges <= dslots,
            lambda args: _conn_update_rows(graph, *args, k, dslots),
            lambda args: _full_ratings(graph, args[2], k, plans),
            (conn_, before, after),
        )

    if dslots is None:
        jet_conn = _conn_step(conn, part, new_part)
    else:
        # accepted movers are a subset of the pruned candidate set, whose
        # rows the afterburner ALREADY expanded and gathered — the conn
        # update reuses (owner_c, dst_b, w_b) and the (from, to) block
        # columns the afterburner returned; the only new irregular op is
        # the accept gather.  Edges of rejected candidates contribute
        # weight 0.
        acc_o = accept[owner_c]
        w_m = jnp.where(acc_o, w_b, 0).astype(ACC_DTYPE)
        new_b = jnp.where(acc_o, to_u, from_u)
        jet_conn = _scatter_conn_delta_cols(
            conn, from_u, new_b, dst_b, w_m, k, n_pad
        )

    # ---- rebalance (jet_refiner.cc:185-187) ----
    # while_loop, not fori: Jet iterations usually keep the partition
    # feasible, and a false condition skips the balancer body entirely.
    # Balancer rounds rate from the post-jet conn table — STALE within
    # the loop (the reference's balancer PQs also run on cached gains);
    # block-weight caps are recomputed fresh per round, so feasibility is
    # exact, and the table itself is reconciled ONCE after the loop from
    # the partition diff.  No edge-wide work anywhere in the loop.
    def _overload(p):
        bw = jax.ops.segment_sum(
            graph.node_w.astype(ACC_DTYPE), p, num_segments=k
        )
        return jnp.sum(
            jnp.maximum(bw - max_block_weights.astype(ACC_DTYPE), 0)
        )

    def bal_cond(state):
        i, p, moved, over = state
        return (i < balancer_rounds) & (over > 0) & (moved != 0)

    def bal_body(state):
        i, p, _, _ = state
        s = (salt + i * 7919) & 0x7FFFFFFF
        p2, moved = overload_balance_round(
            graph, p, k, max_block_weights, s, conn=jet_conn
        )
        return (i + 1, p2, moved, _overload(p2))

    _, bal_part, _, _ = lax.while_loop(
        bal_cond,
        bal_body,
        (jnp.int32(0), new_part, jnp.int32(1), _overload(new_part)),
    )
    # reconcile the table only when the balancer actually moved something
    # (the common case is a feasible partition and zero balancer rounds)
    new_conn = lax.cond(
        jnp.any(bal_part != new_part),
        lambda args: _conn_step(*args),
        lambda args: args[0],
        (jet_conn, new_part, bal_part),
    )
    return bal_part, new_lock, ext_sum, new_conn


@partial(
    jax.jit,
    static_argnames=("k", "max_fruitless", "balancer_rounds"),
)
def _jet_chunk(
    graph: DeviceGraph,
    part: jax.Array,
    lock: jax.Array,
    best: jax.Array,
    best_cut: jax.Array,
    fruitless: jax.Array,
    conn: jax.Array,
    i0: jax.Array,
    k: int,
    max_block_weights: jax.Array,
    gain_temp: jax.Array,
    fruitless_threshold: jax.Array,
    seed: jax.Array,
    rnd: jax.Array,
    limit: jax.Array,
    wdeg: jax.Array,
    max_fruitless: int,
    balancer_rounds: int,
    plans=None,
    stats=None,
):
    """A bounded chunk of Jet iterations in one device program.

    Jet used to run all (up to 64) iterations inside a single fused
    while_loop; at ~33M-edge shapes the multi-minute single launch
    reproducibly killed the TPU worker.  The host now drives the
    iteration loop in chunks, reading back the fruitless counter between
    chunks (one scalar sync per `chunk` iterations).

    `stats` is an optional progress buffer (telemetry/progress.py),
    row-indexed by the GLOBAL iteration `i0 + j` so it threads across
    chunks unchanged; None leaves the jaxpr identical to the
    uninstrumented loop."""

    def is_feasible(p):
        bw = jax.ops.segment_sum(
            graph.node_w.astype(ACC_DTYPE), p, num_segments=k
        )
        return jnp.all(bw <= max_block_weights.astype(ACC_DTYPE))

    def iter_cond(state):
        j, fruitless, part, lock, best, best_cut, conn, stats = state
        # `limit` is traced, so a short remainder chunk reuses the same
        # compiled program instead of triggering a second trace
        return (j < limit) & (fruitless < max_fruitless)

    def iter_body(state):
        j, fruitless, part, lock, best, best_cut, conn, stats = state
        i = i0 + j
        salt = (
            seed.astype(jnp.int32) * 31321 + rnd * 2221 + i * 1566083941
        ) & 0x7FFFFFFF
        new_part, lock, ext_sum, conn = _jet_iteration(
            graph,
            part,
            lock,
            k,
            max_block_weights,
            gain_temp,
            salt,
            balancer_rounds,
            wdeg=wdeg,
            conn=conn,
            plans=plans,
        )
        # snapshot the state ENTERING this iteration (its cut falls out
        # of the rating); the state leaving the round's final iteration
        # is closed out by _jet_round_close in the driver
        cut = ext_sum // 2
        # while best_cut is still the no-feasible-partition sentinel,
        # "improvement" means finding the first feasible partition —
        # comparing against the sentinel would defeat the fruitless
        # early-exit entirely
        has_best = best_cut < jnp.iinfo(ACC_DTYPE).max
        improved_enough = jnp.where(
            has_best,
            (best_cut - cut).astype(jnp.float32)
            > (1.0 - fruitless_threshold)
            * jnp.abs(best_cut).astype(jnp.float32),
            is_feasible(part),
        )
        fruitless = jnp.where(improved_enough, 0, fruitless + 1)
        is_best = (cut <= best_cut) & is_feasible(part)
        best = jnp.where(is_best, part, best)
        best_cut = jnp.where(is_best, cut, best_cut)
        if stats is not None:  # trace-time guard (None adds no carry)
            # cut of the state entering iteration i; moved = locked
            # (accepted) movers of this iteration; fruitless after the
            # improvement test — the convergence picture Jet's paper
            # plots (and the reference's statistics registry prints)
            stats = progress_mod.record(
                stats, i, cut, jnp.sum(lock), fruitless
            )
        return (j + 1, fruitless, new_part, lock, best, best_cut, conn,
                stats)

    _, fruitless, part, lock, best, best_cut, conn, stats = lax.while_loop(
        iter_cond,
        iter_body,
        (jnp.int32(0), fruitless, part, lock, best, best_cut, conn, stats),
    )
    return part, lock, best, best_cut, fruitless, conn, stats


@partial(jax.jit, static_argnames=("k",))
def _jet_round_close(
    graph: DeviceGraph,
    part: jax.Array,
    best: jax.Array,
    best_cut: jax.Array,
    k: int,
    max_block_weights: jax.Array,
    conn: jax.Array | None = None,
    wdeg: jax.Array | None = None,
):
    """Evaluate the round's final (post-move) state once: the in-loop
    snapshots cover every state except the last one.  When the caller
    passes the maintained conn table (which matches `part` exactly —
    every in-loop update is bitwise-equal to a rebuild), the cut falls
    out as sum(wdeg - conn[i, part[i]]) / 2 instead of an edge-wide
    pass (0.68 s -> ~0.1 s at 33.5M slots)."""
    from .metrics import is_feasible as feasibility

    if conn is not None:
        cut = _conn_cut(graph, conn, part, wdeg, k)
    else:
        cut = edge_cut(graph, part)
    is_best = (cut <= best_cut) & feasibility(graph, part, max_block_weights)
    return (
        jnp.where(is_best, part, best),
        jnp.where(is_best, cut, best_cut),
    )


@partial(jax.jit, static_argnames=("k",))
def _jet_build_conn(graph: DeviceGraph, part: jax.Array, k: int,
                    plans=None):
    """Fresh dense rating table — run once per Jet round (the in-round
    table is maintained incrementally; the round-end rollback to `best`
    invalidates it)."""
    return _full_ratings(graph, part, k, plans)


@partial(jax.jit, static_argnames=("k",))
def _jet_init(graph: DeviceGraph, partition: jax.Array, k: int,
              max_block_weights: jax.Array, wdeg: jax.Array, plans=None):
    """Clip the input partition, build the round-0 conn table, and derive
    the starting cut FROM the table (one segment_sum instead of a
    separate edge-wide cut pass — the table is needed anyway)."""
    part0 = jnp.clip(partition, 0, k - 1).astype(jnp.int32)
    bw = jax.ops.segment_sum(
        graph.node_w.astype(ACC_DTYPE), part0, num_segments=k
    )
    feasible = jnp.all(bw <= max_block_weights.astype(ACC_DTYPE))
    conn = _jet_build_conn(graph, part0, k, plans)  # nested jit inlines
    cut = _conn_cut(graph, conn, part0, wdeg, k)
    # snapshots track the best FEASIBLE cut; an infeasible input (e.g.
    # everything in one block, cut 0) must not pin the snapshot
    best_cut0 = jnp.where(feasible, cut, jnp.iinfo(ACC_DTYPE).max)
    return part0, best_cut0, conn


def _jet_refine_impl(
    graph: DeviceGraph,
    partition: jax.Array,
    k: int,
    max_block_weights: jax.Array,
    seed: jax.Array,
    initial_gain_temp,
    final_gain_temp,
    fruitless_threshold,
    num_rounds: int,
    max_iterations: int,
    max_fruitless: int,
    balancer_rounds: int,
    chunk: int = 4,
    plans=None,
) -> jax.Array:
    # static per-node weighted degree (one streaming pass per refine
    # call, via the CSR row spans): each iteration's rating table then
    # yields the visited partition's exact cut as sum(wdeg - conn_own)/2
    # — no per-iteration cut pass
    csum = jnp.cumsum(graph.edge_w.astype(ACC_DTYPE))
    csum0 = jnp.concatenate([jnp.zeros(1, dtype=csum.dtype), csum])
    row_ptr = jnp.clip(graph.row_ptr, 0, graph.edge_w.shape[0])
    wdeg = csum0[row_ptr[1:]] - csum0[row_ptr[:-1]]
    part, best_cut, conn = _jet_init(
        graph, partition, k, max_block_weights, wdeg, plans
    )
    best = part
    # scale the iteration chunk down with edge count so each launch
    # stays short (see segments.MAX_FUSED_EDGE_SLOTS)
    m_pad = graph.src.shape[0]
    if m_pad > MAX_FUSED_EDGE_SLOTS:
        chunk = 1
    elif m_pad > MAX_FUSED_EDGE_SLOTS // 2:
        chunk = min(chunk, 2)
    rec = progress_mod.capture()
    for rnd in range(num_rounds):
        if num_rounds > 1:
            gain_temp = initial_gain_temp + (
                final_gain_temp - initial_gain_temp
            ) * rnd / max(num_rounds - 1, 1)
        else:
            gain_temp = initial_gain_temp
        lock = jnp.zeros(graph.n_pad, dtype=jnp.int32)
        fruitless = jnp.int32(0)
        if conn is None:
            # only needed on round 0 and after a rollback — the in-round
            # table is maintained incrementally and stays valid across
            # rounds whenever the round ended on its best partition
            conn = _jet_build_conn(graph, part, k, plans)
        # per-round progress buffer, row-indexed by the global iteration
        # so it rides across host-driven chunks without a host pull
        stats = progress_mod.new_buffer(max_iterations, 3) if rec else None
        t0 = progress_mod.now()
        i = 0
        closed = False
        while i < max_iterations:
            part, lock, best, best_cut, fruitless, conn, stats = _jet_chunk(
                graph, part, lock, best, best_cut, fruitless, conn,
                jnp.int32(i), k, max_block_weights,
                jnp.float32(gain_temp), jnp.float32(fruitless_threshold),
                seed, jnp.int32(rnd),
                jnp.int32(min(chunk, max_iterations - i)), wdeg,
                max_fruitless, balancer_rounds, plans, stats,
            )
            i += chunk
            # the readback is a blocking device sync; skip it when the
            # fruitless early-exit is disabled so chunks enqueue
            # back-to-back
            if max_fruitless < max_iterations and int(fruitless) >= max_fruitless:
                # the in-loop snapshots lag one iteration; before giving
                # up, evaluate the (uncounted) final state — if it just
                # improved the best cut, the plateau was illusory and
                # the round keeps going (when iterations remain)
                prev_best = int(best_cut)
                best, best_cut = _jet_round_close(
                    graph, part, best, best_cut, k, max_block_weights,
                    conn=conn, wdeg=wdeg,
                )
                closed = True
                if int(best_cut) < prev_best and i < max_iterations:
                    fruitless = jnp.int32(0)
                    closed = False
                    continue
                break
        if not closed:
            # close out the round's final (post-move, unrated) state
            best, best_cut = _jet_round_close(
                graph, part, best, best_cut, k, max_block_weights,
                conn=conn, wdeg=wdeg,
            )
        if rec:
            # ONE host pull per round, after the loop exited (the chunk
            # driver's fruitless readback already synced the stream)
            progress_mod.emit(
                "jet", ("cut", "moved", "fruitless"), stats, t0,
                round=rnd, best_cut=int(best_cut),
            )
        # rollback to best (jet_refiner.cc:221-227): the round continues
        # from the best partition seen
        if bool(jnp.any(part != best)):
            conn = None  # table matches `part`, not the rolled-back best
        part = best
    return best


def jet_refine(
    graph: DeviceGraph,
    partition: jax.Array,
    k: int,
    max_block_weights: jax.Array,
    seed: jax.Array,
    ctx: JetRefinementContext,
    level: int = 0,
    num_levels: int = 1,
    balancer_rounds: int = 4,
) -> jax.Array:
    """Jet refinement entry point; picks coarse/fine temperatures by level
    (jet_refiner.cc:40-49: every level except the finest counts as coarse)."""
    if graph.n_pad * k > JET_DENSE_MAX_ENTRIES:
        # huge k: the dense (n, k) conn table Jet's incremental machinery
        # rides would not fit HBM (16 GB at n=1M, k=4096).  Degrade to
        # bulk-synchronous LP refinement rounds — the sort2 rating engine
        # is k-independent and the afterburner keeps gains exact — so the
        # strong preset completes at any k instead of OOMing (the
        # reference's large-k configs likewise swap refiner strategy,
        # gains/compact_hashing_gain_cache.h:34 lineage).
        from .lp import LPConfig, lp_refine

        cfg = LPConfig(
            num_iterations=8,
            participation=1.0,
            allow_tie_moves=False,
            use_active_set=True,
            refinement=True,
        )
        return lp_refine(graph, partition, k, max_block_weights, seed, cfg)
    is_coarse = level > 0
    if is_coarse:
        rounds = ctx.num_rounds_on_coarse_level
        t0, t1 = (
            ctx.initial_gain_temp_on_coarse_level,
            ctx.final_gain_temp_on_coarse_level,
        )
    else:
        rounds = ctx.num_rounds_on_fine_level
        t0, t1 = (
            ctx.initial_gain_temp_on_fine_level,
            ctx.final_gain_temp_on_fine_level,
        )
    # auto iteration budget: an iteration costs ~105 ns per edge SLOT on
    # v5e regardless of level (profiled at 0.26M..33M slots), and coarse
    # RMAT levels keep millions of edges — a 64-iteration coarse budget
    # was the single largest cost of the whole pipeline (~75 s per coarse
    # level at 4M slots).  Most of the cut gain arrives early: on the
    # medium RMAT bench 8 fine iters matches 16 within ±0.1% cut at half
    # the cost (and 32 was measurably worse than 16); coarse levels get
    # 16 — double the fine budget (they set up the solution structure).
    # Above the large-graph boundary (the delta-round threshold) the
    # coarse budget halves again: measured on the 10M bench, coarse 8
    # costs +0.2% cut for -18% total wall (140 s -> 115 s warm), while
    # small graphs keep 16 (their iterations are cheap and the extra
    # polish is free).
    if ctx.num_iterations > 0:
        max_iterations = ctx.num_iterations
    elif is_coarse:
        max_iterations = (
            8 if graph.src.shape[0] >= DELTA_MIN_EDGE_SLOTS else 16
        )
    else:
        max_iterations = 8
    max_fruitless = (
        ctx.num_fruitless_iterations
        if ctx.num_fruitless_iterations > 0
        else 2**30
    )
    from .lane_gather import maybe_edge_plans

    return _jet_refine_impl(
        graph,
        partition,
        k,
        max_block_weights,
        seed,
        jnp.float32(t0),
        jnp.float32(t1),
        jnp.float32(ctx.fruitless_threshold),
        int(rounds),
        int(max_iterations),
        int(max_fruitless),
        int(balancer_rounds),
        plans=maybe_edge_plans(graph),  # eager: host readbacks
    )
