from . import bfs, metrics, segments  # noqa: F401
