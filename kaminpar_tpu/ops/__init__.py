from . import metrics, segments  # noqa: F401
