"""Connected components on device.

The reference ships a connected-components tool (apps/tools/) built on its
CPU graph utilities.  The TPU version is the classic label-contraction
algorithm expressed in XLA: every node starts with its own id, each round
takes the min label over the neighborhood (one segment_min over the COO
edge list) followed by pointer jumping (label = label[label], doubling
convergence), inside a lax.while_loop — O(log diameter) rounds, every
round a fused gather/segment kernel.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..graphs.csr import DeviceGraph


@jax.jit
def connected_components(graph: DeviceGraph) -> jax.Array:
    """i32[n_pad]: per node, the minimum node id in its component (pad
    slots keep their own id)."""
    n_pad = graph.n_pad
    node_ids = jnp.arange(n_pad, dtype=jnp.int32)

    def body(state):
        labels, _ = state
        neigh_min = jax.ops.segment_min(
            labels[graph.dst], graph.src, num_segments=n_pad
        )
        new = jnp.minimum(labels, neigh_min)
        # pointer jumping: adopt the label's label until stable
        def jump_cond(s):
            l, changed = s
            return changed

        def jump_body(s):
            l, _ = s
            l2 = l[l]
            return l2, jnp.any(l2 != l)

        new, _ = lax.while_loop(jump_cond, jump_body, (new, jnp.bool_(True)))
        return new, jnp.any(new != labels)

    def cond(state):
        return state[1]

    labels, _ = lax.while_loop(cond, body, (node_ids, jnp.bool_(True)))
    return labels


def count_components(graph: DeviceGraph) -> int:
    """Number of connected components among real nodes."""
    import numpy as np

    labels = np.asarray(connected_components(graph))
    n = int(graph.n)
    return len(np.unique(labels[:n])) if n else 0
