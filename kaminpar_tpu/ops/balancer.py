"""Overload / underload balancers on device.

Analog of kaminpar-shm/refinement/balancer/:
  * OverloadBalancer (overload_balancer.h:25): the reference keeps one
    priority queue per overloaded block, ordered by *relative gain*
    (relative_gain.h: gain > 0 ? gain * weight : gain / weight) and pops
    until the block is feasible.  The TPU version is bulk-synchronous
    rounds: for every node of an overloaded block compute its best feasible
    target block, rank movers per source block by relative gain, accept
    per-source prefixes that cover the overload and per-target prefixes
    that fit the headroom (both via sorted prefix sums).
  * UnderloadBalancer: symmetric — pull weight into blocks below their min
    weight from neighboring blocks.

The device loop makes fast progress but may stall on adversarial instances
(e.g. when all movers of an overloaded block are individually too heavy for
every target); partitioning/refiner.py falls back to the exact host balancer
(`host_balance`) to provide the reference's strict balance guarantee
(README.MD:18).
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..graphs.csr import DeviceGraph
from ..telemetry import progress as progress_mod
from .segments import (
    ACC_DTYPE,
    INT32_MIN,
    accept_prefix_by_capacity,
    aggregate_by_key,
    argmax_per_segment,
    best_from_dense,
    connection_to_label,
    dense_block_ratings,
)

# Above this k a dense (n, k) rating table is shape-infeasible (the
# reference's large-k regime, sparse/compact gain caches —
# kaminpar-shm/refinement/gains/compact_hashing_gain_cache.h:34); the
# balancer rates via edge aggregation instead.
BALANCER_DENSE_MAX_K = 256


def relative_gain_key(gain: jax.Array, weight: jax.Array) -> jax.Array:
    """Sortable surrogate for compute_relative_gain (relative_gain.h):
    gain>0 -> gain*weight, else gain/weight.  Returned as a float32 to be
    used as a *descending* priority."""
    w = jnp.maximum(weight.astype(jnp.float32), 1.0)
    g = gain.astype(jnp.float32)
    return jnp.where(g > 0, g * w, g / w)


def _block_weights(graph: DeviceGraph, partition: jax.Array, k: int) -> jax.Array:
    return jax.ops.segment_sum(
        graph.node_w.astype(ACC_DTYPE),
        jnp.clip(partition, 0, k - 1),
        num_segments=k,
    )


def overload_balance_round(
    graph: DeviceGraph,
    partition: jax.Array,
    k: int,
    max_block_weights: jax.Array,
    salt: jax.Array,
    conn: jax.Array | None = None,
) -> Tuple[jax.Array, jax.Array]:
    """One bulk-synchronous balancing round; returns (partition, moved).

    `conn` is an optional PRE-BUILT dense (n, k) connection table for
    `partition` (the Jet refiner maintains one incrementally); when given,
    the round does NO edge-wide work at all — rating, commit, and weight
    arithmetic are all O(n*k)/O(n)."""
    n_pad = graph.n_pad
    node_ids = jnp.arange(n_pad, dtype=jnp.int32)
    is_real = node_ids < graph.n
    part = jnp.clip(partition, 0, k - 1).astype(jnp.int32)
    bw = _block_weights(graph, part, k)
    cap = max_block_weights.astype(ACC_DTYPE)
    overload = jnp.maximum(bw - cap, 0)
    headroom = jnp.maximum(cap - bw, 0)

    in_overloaded = (overload[part] > 0) & is_real

    # best feasible target per node: highest-connection non-overloaded block
    # with room for the node.  Small k: dense (n, k) rating (one
    # segment_sum, no sort).  Large k: the dense table is
    # shape-infeasible — rate by edge aggregation (sort-based, the
    # compact-gain-cache regime).
    if k <= BALANCER_DENSE_MAX_K:
        if conn is None:
            conn = dense_block_ratings(
                graph.src, graph.dst, graph.edge_w, part, n_pad, k
            )
        best, best_w, w_own = best_from_dense(
            conn, part, bw, graph.node_w, cap, salt
        )
    else:
        neigh_block = part[graph.dst]
        seg_g, key_g, w_g = aggregate_by_key(graph.src, neigh_block, graph.edge_w)
        key_c = jnp.clip(key_g, 0, k - 1)
        seg_c = jnp.clip(seg_g, 0, n_pad - 1)
        fits = (
            bw[key_c] + graph.node_w[seg_c].astype(ACC_DTYPE) <= cap[key_c]
        )
        feasible = (seg_g >= 0) & (key_g != part[seg_c]) & fits
        best, best_w = argmax_per_segment(
            seg_g, key_g, w_g, n_pad, tie_salt=salt, feasible=feasible
        )
        w_own = connection_to_label(seg_g, key_g, w_g, part, n_pad)
        # zero-connection escape (the dense table rates every block; the
        # edge aggregation only rates ADJACENT ones): movers with no
        # feasible neighbor block go to the max-headroom block if they fit
        headroom_now = jnp.maximum(cap - bw, 0)
        fallback = jnp.argmax(headroom_now).astype(jnp.int32)
        fb_ok = (
            graph.node_w.astype(ACC_DTYPE) <= headroom_now[fallback]
        ) & (part != fallback)
        use_fb = (best < 0) & fb_ok
        best = jnp.where(use_fb, fallback, best)
        best_w = jnp.where(use_fb, 0, best_w)

    # (no separate fallback needed: the dense table rates every fitting
    # block, including zero-connection ones, so best < 0 already means no
    # block can take the node)
    target = best
    gain = best_w - w_own

    mover = in_overloaded & (target >= 0)
    target = jnp.where(mover, target, -1)

    # per-source-block: accept movers by descending relative gain until the
    # overload is covered.  Encode descending order as ascending int key.
    rel = relative_gain_key(gain, graph.node_w)
    order_key = -rel  # float32; ascending sort = best relative gain first
    src_block = jnp.where(mover, part, -1)
    accept_out = accept_prefix_by_capacity(
        src_block, order_key, graph.node_w, overload, reach=True
    )

    # per-target-block: STRICT headroom admission — a previously feasible
    # block must never become overloaded by incoming movers
    target2 = jnp.where(accept_out, target, -1)
    accept_in = accept_prefix_by_capacity(
        target2, order_key, graph.node_w, headroom
    )
    accept = accept_out & accept_in

    new_part = jnp.where(accept, target, part)
    # moved-node count <= n, ID domain  # tpulint: disable=R3
    return new_part, jnp.sum(accept, dtype=jnp.int32)


@partial(jax.jit, static_argnames=("k", "max_rounds"))
def _overload_balance_impl(
    graph: DeviceGraph,
    partition: jax.Array,
    k: int,
    max_block_weights: jax.Array,
    seed: jax.Array,
    max_rounds: int = 8,
    stats=None,
):
    """Balancing rounds until feasible or stalled (OverloadBalancer::
    balance analog).  `stats` is an optional progress buffer (see
    telemetry/progress.py); None keeps the jaxpr identical to the
    uninstrumented loop.  The record variant carries the violation mass
    so the series costs no extra reduction: the body computes it once
    per round and the loop condition reuses the carried scalar."""

    def _violation(part):
        bw = _block_weights(graph, part, k)
        return jnp.sum(
            jnp.maximum(bw - max_block_weights.astype(ACC_DTYPE), 0)
        )

    def _round(i, part):
        salt = (seed.astype(jnp.int32) * 48271 + i * 1566083941) & 0x7FFFFFFF
        return overload_balance_round(
            graph, part, k, max_block_weights, salt
        )

    part0 = jnp.clip(partition, 0, k - 1)
    if stats is None:
        def cond(state):
            i, part, moved = state
            return (i < max_rounds) & (_violation(part) > 0) & (moved != 0)

        def body(state):
            i, part, _ = state
            part, moved = _round(i, part)
            return (i + 1, part, moved)

        _, part, _ = lax.while_loop(
            cond, body, (jnp.int32(0), part0, jnp.int32(1))
        )
        return part

    def cond(state):
        i, part, moved, stats, over = state
        return (i < max_rounds) & (over > 0) & (moved != 0)

    def body(state):
        i, part, _, stats, _ = state
        part, moved = _round(i, part)
        over = _violation(part)
        stats = progress_mod.record(stats, i, moved, over)
        return (i + 1, part, moved, stats, over)

    _, part, _, stats, _ = lax.while_loop(
        cond, body,
        (jnp.int32(0), part0, jnp.int32(1), stats, _violation(part0)),
    )
    return part, stats


def overload_balance(
    graph: DeviceGraph,
    partition: jax.Array,
    k: int,
    max_block_weights: jax.Array,
    seed: jax.Array,
    max_rounds: int = 8,
) -> jax.Array:
    """Public entry: runs the fused loop, emitting a per-round progress
    series (moved nodes, residual violation mass) when telemetry is on."""
    return progress_mod.instrumented(
        lambda stats: _overload_balance_impl(
            graph, partition, k, max_block_weights, seed, max_rounds, stats
        ),
        "balancer", ("moved", "violation"), rows=max_rounds,
        direction="overload",
    )


@partial(jax.jit, static_argnames=("k", "max_rounds"))
def _underload_balance_impl(
    graph: DeviceGraph,
    partition: jax.Array,
    k: int,
    max_block_weights: jax.Array,
    min_block_weights: jax.Array,
    seed: jax.Array,
    max_rounds: int = 8,
    stats=None,
):
    """UnderloadBalancer analog: pull weight into blocks below their min
    weight, taking the cheapest movers from blocks with surplus
    (weight > min).  `stats`: optional progress buffer; the record
    variant carries the deficit mass like _overload_balance_impl."""

    def _deficit_mass(part):
        bw = _block_weights(graph, part, k)
        return jnp.sum(
            jnp.maximum(min_block_weights.astype(ACC_DTYPE) - bw, 0)
        )

    def _round(i, part):
        salt = (seed.astype(jnp.int32) * 16807 + i * 1566083941) & 0x7FFFFFFF
        n_pad = graph.n_pad
        node_ids = jnp.arange(n_pad, dtype=jnp.int32)
        is_real = node_ids < graph.n
        bw = _block_weights(graph, part, k)
        deficit = jnp.maximum(min_block_weights.astype(ACC_DTYPE) - bw, 0)
        surplus = jnp.maximum(bw - min_block_weights.astype(ACC_DTYPE), 0)

        # candidates: nodes in surplus blocks adjacent to a deficit block
        # (dense rating restricted to deficit columns; large k rates by
        # edge aggregation — see BALANCER_DENSE_MAX_K)
        if k <= BALANCER_DENSE_MAX_K:
            conn = dense_block_ratings(
                graph.src, graph.dst, graph.edge_w, part, n_pad, k
            )
            best, best_w, _ = best_from_dense(
                conn, part, bw, graph.node_w, bw, salt,
                require_fit=False, allowed=deficit > 0,
            )
        else:
            neigh_block = part[graph.dst]
            seg_g, key_g, w_g = aggregate_by_key(
                graph.src, neigh_block, graph.edge_w
            )
            key_c = jnp.clip(key_g, 0, k - 1)
            seg_c = jnp.clip(seg_g, 0, n_pad - 1)
            feasible = (
                (seg_g >= 0)
                & (key_g != part[seg_c])
                & (deficit[key_c] > 0)
            )
            best, best_w = argmax_per_segment(
                seg_g, key_g, w_g, n_pad, tie_salt=salt, feasible=feasible
            )
        # fallback for deficit blocks with no adjacent candidates (e.g. an
        # empty block): pull arbitrary nodes into the most-deficient block
        fallback = jnp.argmax(deficit).astype(jnp.int32)
        use_fallback = (best < 0) & (deficit[fallback] > 0) & (part != fallback)
        best = jnp.where(use_fallback, fallback, best)
        best_w = jnp.where(use_fallback, 0, best_w)
        mover = (
            is_real
            & (best >= 0)
            & (surplus[part] >= graph.node_w.astype(ACC_DTYPE))
        )
        target = jnp.where(mover, best, -1)
        rel = relative_gain_key(best_w, graph.node_w)
        order_key = -rel
        # take out no more than the surplus, put in no more than the deficit
        accept_out = accept_prefix_by_capacity(
            jnp.where(mover, part, -1), order_key, graph.node_w, surplus
        )
        target2 = jnp.where(accept_out, target, -1)
        accept_in = accept_prefix_by_capacity(
            target2, order_key, graph.node_w, deficit, reach=True
        )
        accept = accept_out & accept_in
        new_part = jnp.where(accept, target, part)
        # moved-node count <= n, ID domain  # tpulint: disable=R3
        return new_part, jnp.sum(accept, dtype=jnp.int32)

    part0 = jnp.clip(partition, 0, k - 1)
    if stats is None:
        def cond(state):
            i, part, moved = state
            return (
                (i < max_rounds) & (_deficit_mass(part) > 0) & (moved != 0)
            )

        def body(state):
            i, part, _ = state
            part, moved = _round(i, part)
            return (i + 1, part, moved)

        _, part, _ = lax.while_loop(
            cond, body, (jnp.int32(0), part0, jnp.int32(1))
        )
        return part

    def cond(state):
        i, part, moved, stats, deficit = state
        return (i < max_rounds) & (deficit > 0) & (moved != 0)

    def body(state):
        i, part, _, stats, _ = state
        part, moved = _round(i, part)
        deficit = _deficit_mass(part)
        stats = progress_mod.record(stats, i, moved, deficit)
        return (i + 1, part, moved, stats, deficit)

    _, part, _, stats, _ = lax.while_loop(
        cond, body,
        (jnp.int32(0), part0, jnp.int32(1), stats, _deficit_mass(part0)),
    )
    return part, stats


def underload_balance(
    graph: DeviceGraph,
    partition: jax.Array,
    k: int,
    max_block_weights: jax.Array,
    min_block_weights: jax.Array,
    seed: jax.Array,
    max_rounds: int = 8,
) -> jax.Array:
    """Public entry (see overload_balance): per-round moved nodes and
    residual deficit mass land on the progress stream when telemetry is
    enabled."""
    return progress_mod.instrumented(
        lambda stats: _underload_balance_impl(
            graph, partition, k, max_block_weights, min_block_weights,
            seed, max_rounds, stats,
        ),
        "balancer", ("moved", "violation"), rows=max_rounds,
        direction="underload",
    )


def host_balance(
    node_w: np.ndarray,
    adjacency: Tuple[np.ndarray, np.ndarray, np.ndarray],
    partition: np.ndarray,
    max_block_weights: np.ndarray,
) -> np.ndarray:
    """Exact greedy host balancer — the strict-balance guarantee backstop
    (README.MD:18).  Moves the relatively-cheapest nodes out of overloaded
    blocks one at a time until feasible; always terminates feasible when
    sum(node weights) <= sum(max block weights) and node weights fit."""
    xadj, adjncy, edge_w = adjacency
    part = partition.copy()
    n = len(part)
    k = len(max_block_weights)
    bw = np.zeros(k, dtype=np.int64)
    np.add.at(bw, part, node_w)

    # internal connection weight per node: cut damage of moving it away
    src = np.repeat(np.arange(n), np.diff(xadj))
    internal = np.zeros(n, dtype=np.int64)
    same = part[src] == part[adjncy]
    np.add.at(internal, src[same], edge_w[same])

    # movers ordered by (internal connection, weight): cheapest cut damage
    # first, light nodes first
    order = np.lexsort((node_w, internal))
    for _ in range(n * 2):
        over_blocks = np.flatnonzero(bw > max_block_weights)
        if len(over_blocks) == 0:
            break
        b = int(
            over_blocks[np.argmax(bw[over_blocks] - max_block_weights[over_blocks])]
        )
        movers = order[part[order] == b]
        moved = False
        for u in movers:
            # best target with room: max connection among roomy blocks
            room = max_block_weights - bw
            room[b] = -1
            lo, hi = int(xadj[u]), int(xadj[u + 1])
            conn = np.zeros(k, dtype=np.int64)
            np.add.at(conn, part[adjncy[lo:hi]], edge_w[lo:hi])
            conn[room < node_w[u]] = -1
            conn[b] = -1
            t = int(np.argmax(conn))
            if conn[t] < 0:  # no adjacent roomy block: any roomy block
                t = int(np.argmax(room))
                if room[t] < node_w[u]:
                    continue
            part[u] = t
            bw[b] -= node_w[u]
            bw[t] += node_w[u]
            moved = True
            break
        if not moved:
            break
    return part
