"""Segmented sort/reduce primitives — the TPU-native RatingMap.

The reference accumulates neighbor→cluster ratings in per-thread adaptive
hash maps (kaminpar-common/datastructures/rating_map.h) inside a per-node
loop (kaminpar-shm/label_propagation.h:461-541 find_best_cluster).  On TPU
the same computation is expressed as whole-graph sort + segmented-reduction
programs over the COO edge list: XLA lowers sorts and segment ops onto the
vector units with static shapes, which beats any per-node control flow.

Primitives:
  * hash_u32               — stateless integer mixer for random tie-breaking
                             (replaces per-thread RNG in find_best_cluster)
  * aggregate_by_key       — group (seg, key) pairs, sum weights per group
  * argmax_per_segment     — per-segment argmax with hashed tie-breaking
  * accept_prefix_by_capacity — sort movers by (target, priority) and accept
                             the maximal prefix per target under a capacity;
                             the bulk-synchronous replacement for the
                             reference's CAS cluster-weight updates
                             (label_propagation.h:2139 move_cluster_weight)

All functions are jit-safe with static shapes; "invalid" is encoded as -1.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

# Weight/accumulator dtypes and the 64-bit build switch live in the leaf
# module kaminpar_tpu.dtypes (KAMINPAR_TPU_64BIT=1); re-exported here for
# every kernel module.
from ..dtypes import ACC_DTYPE, INT32_MIN, X64_WEIGHTS  # noqa: F401

# A single fused device launch that runs for many minutes reproducibly
# kills the TPU worker (observed at 33M edges with a fully fused Jet
# round and at 128M with 4-iteration chunks); refiners split their
# multi-round launches above this many edge slots.
MAX_FUSED_EDGE_SLOTS = 1 << 26


def pad_k_bucket(k, max_block_weights, min_block_weights=None):
    """Round k up to a power of two with zero-capacity phantom blocks.

    k is shape-defining for every refinement kernel ((n, k) tables,
    k-segment reductions), so each distinct k would compile its own
    executable per shape bucket — with deep k-doubling that is log2(k)
    recompiles of the largest programs.  Phantom blocks get zero max
    (and min) weight: no node can move into them, results are
    identical, and one compiled program serves every k in the bucket.

    Returns (k_pad, max_block_weights, min_block_weights).
    """
    k_pad = max(2, 1 << (int(k) - 1).bit_length())
    from ..caching import record_padding

    record_padding(k=int(k), k_pad=k_pad)
    if k_pad != k:
        pad = jnp.zeros(k_pad - int(k), dtype=ACC_DTYPE)
        max_block_weights = jnp.concatenate(
            [jnp.asarray(max_block_weights, dtype=ACC_DTYPE), pad]
        )
        if min_block_weights is not None:
            min_block_weights = jnp.concatenate(
                [jnp.asarray(min_block_weights, dtype=ACC_DTYPE), pad]
            )
    return k_pad, max_block_weights, min_block_weights


def hash_u32(x: jax.Array, salt) -> jax.Array:
    """murmur3-style finalizer; returns non-negative int32."""
    x = x.astype(jnp.uint32) * jnp.uint32(0x9E3779B1) + jnp.uint32(salt)
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    return (x >> jnp.uint32(1)).astype(jnp.int32)


def hash_tie16(x: jax.Array, salt) -> jax.Array:
    """Top 16 bits of hash_u32 as non-negative int16 — the narrow
    TIE-BREAK dtype for edge-wide sort operands (round-9 dtype packing:
    a tie key only needs enough entropy to order ties deterministically,
    and halving the operand width cuts the sort's streamed bytes; an
    equal-16-bit tie falls through to the sort's stable order, which is
    itself deterministic).  NEVER for weights/gains — those keep
    ACC_DTYPE per the dtypes.py policy (tpulint R3)."""
    return (hash_u32(x, salt) >> jnp.int32(16)).astype(jnp.int16)


def sort_by_two_keys(
    primary: jax.Array, secondary: jax.Array, *values: jax.Array
) -> Tuple[jax.Array, ...]:
    """Lexicographic sort by (primary, secondary), carrying values."""
    return lax.sort((primary, secondary) + values, num_keys=2)


def aggregate_by_key(
    seg: jax.Array, key: jax.Array, w: jax.Array
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Group entries by (seg, key) and sum weights per group.

    Returns (seg_g, key_g, w_g), all of length len(seg); group g occupies
    slot g, unused slots have seg_g == -1.  This is the whole-graph analog
    of one RatingMap fill: for LP, seg = edge source node, key = neighbor's
    cluster, w = edge weight, and (seg_g, key_g, w_g) enumerates each node's
    adjacent clusters with their connection weights.
    """
    m = seg.shape[0]
    seg_s, key_s, w_s = sort_by_two_keys(seg, key, w.astype(ACC_DTYPE))
    prev_seg = jnp.concatenate([jnp.array([-1], seg_s.dtype), seg_s[:-1]])
    prev_key = jnp.concatenate([jnp.array([-1], key_s.dtype), key_s[:-1]])
    is_new = (seg_s != prev_seg) | (key_s != prev_key)
    # group sums WITHOUT scatters (TPU scatters cost ~7.5 ns/index; these
    # are streaming passes): inclusive cumsum minus the cummax'd group
    # base (cum - w at group starts is monotone because weights >= 0);
    # the group's total sits at its last element
    cum = jnp.cumsum(w_s)
    base = lax.cummax(jnp.where(is_new, cum - w_s, 0))
    total = cum - base
    is_last = jnp.concatenate([is_new[1:], jnp.array([True])])
    # compact group-last entries to the front, preserving (seg, key)
    # order: one position scatter + three cheap gathers.  This replaced
    # a second 5-operand 2-key sort — bitwise-identical output (the
    # group prefix keeps its (seg, key) order, the suffix is the same
    # masked fill), at one indexed pass instead of a multi-operand
    # comparator sort (the round-9 CPU profile put that sort at ~45% of
    # aggregate_by_key's wall; on TPU a 1-index-per-slot scatter and
    # the sort price within noise of each other).
    pos = jnp.arange(m, dtype=jnp.int32)
    # group g's output slot; non-lasts routed to the dropped slot m
    out_slot = jnp.cumsum(is_last.astype(jnp.int32)) - 1
    dest = jnp.where(is_last, out_slot, m)
    src_pos = (
        jnp.full(m, m, dtype=jnp.int32).at[dest].set(pos, mode="drop")
    )
    in_groups = src_pos < m
    sp = jnp.clip(src_pos, 0, m - 1)
    seg_g = jnp.where(in_groups, seg_s[sp], -1)
    key_g = jnp.where(in_groups, key_s[sp], -1)
    w_g = jnp.where(in_groups, total[sp], 0)
    return seg_g, key_g, w_g


def argmax_per_segment(
    seg: jax.Array,
    key: jax.Array,
    score: jax.Array,
    num_segments: int,
    tie_salt,
    feasible: jax.Array | None = None,
) -> Tuple[jax.Array, jax.Array]:
    """For each segment, the key with max score among feasible entries,
    ties broken by a hashed pseudo-random priority (the TPU analog of the
    uniform random tie-breaking in label_propagation.h:461-541).

    Entries with seg < 0 are ignored.  Returns (best_key, best_score) of
    length num_segments; best_key = -1 / best_score = INT32_MIN where a
    segment has no feasible entry.
    """
    ok = seg >= 0
    if feasible is not None:
        ok = ok & feasible
    seg_c = jnp.where(ok, seg, num_segments)  # routed to an overflow slot
    masked = jnp.where(ok, score, INT32_MIN)
    best = jax.ops.segment_max(masked, seg_c, num_segments=num_segments + 1)[
        :num_segments
    ]
    has = best > INT32_MIN
    is_best = ok & (score == best[jnp.clip(seg_c, 0, num_segments - 1)]) & (
        seg_c < num_segments
    )
    tb = hash_u32(key, tie_salt)
    tb_m = jnp.where(is_best, tb, -1)
    # hashes and keys are int32 regardless of the weight build — their
    # sentinel must stay in the int32 domain
    i32_min = jnp.iinfo(jnp.int32).min
    best_tb = jax.ops.segment_max(
        jnp.where(is_best, tb_m, i32_min), seg_c, num_segments=num_segments + 1
    )[:num_segments]
    winner = is_best & (tb == best_tb[jnp.clip(seg_c, 0, num_segments - 1)])
    best_key = jax.ops.segment_max(
        jnp.where(winner, key, i32_min), seg_c, num_segments=num_segments + 1
    )[:num_segments]
    best_key = jnp.where(has, best_key, -1)
    best_score = jnp.where(has, best, INT32_MIN)
    return best_key, best_score


def accept_prefix_by_capacity(
    target: jax.Array,
    priority: jax.Array,
    weight: jax.Array,
    capacity: jax.Array,
    reach: bool = False,
) -> jax.Array:
    """Capacity-respecting parallel commit.

    Each entry i wants to add `weight[i]` to bucket `target[i]` (-1 = not
    moving).  Entries are ordered by (target, priority) and the maximal
    prefix per target whose cumulative weight fits `capacity[target]` is
    accepted.  Returns a bool mask over entries.

    With `reach=True` the prefix instead *reaches* the capacity: the last
    accepted entry may cross it (used by the balancer when shedding an
    overloaded block — the reference moves nodes until the block becomes
    feasible, overload_balancer.h:25).  The default strict mode never
    exceeds the capacity.

    This replaces the reference's relaxed CAS loop on cluster weights
    (label_propagation.h:818 try_node_move / :2139 move_cluster_weight):
    instead of racing threads, one deterministic sorted pass guarantees the
    cap is never exceeded.
    """
    nbuckets = capacity.shape[0]
    idx = jnp.arange(target.shape[0], dtype=jnp.int32)
    t = jnp.where(target >= 0, target, nbuckets).astype(jnp.int32)
    t_s, p_s, w_s, idx_s = lax.sort((t, priority, weight, idx), num_keys=2)
    c = jnp.cumsum(w_s.astype(ACC_DTYPE))
    prev_t = jnp.concatenate([jnp.array([-1], t_s.dtype), t_s[:-1]])
    is_first = t_s != prev_t
    gid = jnp.cumsum(is_first.astype(jnp.int32)) - 1
    seg_base = jax.ops.segment_min(
        jnp.where(is_first, c - w_s.astype(ACC_DTYPE), jnp.iinfo(ACC_DTYPE).max),
        gid,
        num_segments=target.shape[0],
    )
    cum_in_seg = c - seg_base[gid]
    cap_here = jnp.where(
        t_s < nbuckets, capacity[jnp.clip(t_s, 0, nbuckets - 1)], 0
    ).astype(ACC_DTYPE)
    if reach:
        accepted_sorted = (t_s < nbuckets) & (
            cum_in_seg - w_s.astype(ACC_DTYPE) < cap_here
        )
    else:
        accepted_sorted = (t_s < nbuckets) & (cum_in_seg <= cap_here)
    accept = jnp.zeros(target.shape[0], dtype=bool).at[idx_s].set(accepted_sorted)
    return accept


def move_weight_delta(
    labels: jax.Array,
    target: jax.Array,
    accept: jax.Array,
    node_w: jax.Array,
    num_clusters: int,
) -> jax.Array:
    """Per-cluster weight delta of a bulk move (movers leave `labels`,
    join `target`).  The distributed round psums this across devices
    before applying (the control_cluster_weights analog)."""
    moved_w = jnp.where(accept, node_w, 0).astype(ACC_DTYPE)
    out_w = jax.ops.segment_sum(
        moved_w, jnp.clip(labels, 0, num_clusters - 1), num_segments=num_clusters
    )
    in_w = jax.ops.segment_sum(
        moved_w, jnp.clip(target, 0, num_clusters - 1), num_segments=num_clusters
    )
    return in_w - out_w


def apply_move_weight_delta(
    cluster_weights: jax.Array,
    labels: jax.Array,
    target: jax.Array,
    accept: jax.Array,
    node_w: jax.Array,
) -> jax.Array:
    """Update per-cluster weights after a bulk move: subtract movers from
    their old cluster, add them to the new one.  Shared by LP rounds,
    isolated-node clustering, and two-hop clustering."""
    C = cluster_weights.shape[0]
    delta = move_weight_delta(labels, target, accept, node_w, C)
    return (cluster_weights + delta).astype(cluster_weights.dtype)


def connection_to_label(
    seg_g: jax.Array,
    key_g: jax.Array,
    w_g: jax.Array,
    labels: jax.Array,
    n_pad: int,
) -> jax.Array:
    """Per-node connection weight to its own current label (0 if none).
    Shared by LP, the balancers, and Jet."""
    cur_of_group = labels[jnp.clip(seg_g, 0, n_pad - 1)]
    match = (seg_g >= 0) & (key_g == cur_of_group)
    seg_c = jnp.where(match, seg_g, n_pad)
    w_cur = jax.ops.segment_max(
        jnp.where(match, w_g, 0), seg_c, num_segments=n_pad + 1
    )[:n_pad]
    # segment_max identity is INT32_MIN; empty segments mean no connection
    return jnp.maximum(w_cur, 0)


def combine_labels(l1: jax.Array, l2: jax.Array) -> jax.Array:
    """Intersect two clusterings: nodes end up together iff they share a
    cluster in BOTH inputs (the overlay/PASCO combination used by
    OverlayClusterCoarsener, kaminpar-shm/coarsening/overlay_cluster_
    coarsener.cc).  Returns labels whose values are node ids (the minimum
    node id of each (l1, l2) group), same convention as lp_cluster."""
    n = l1.shape[0]
    node = jnp.arange(n, dtype=jnp.int32)
    a, b, idx = lax.sort((l1, l2, node), num_keys=2)
    prev_a = jnp.concatenate([jnp.array([-1], a.dtype), a[:-1]])
    prev_b = jnp.concatenate([jnp.array([-1], b.dtype), b[:-1]])
    is_new = (a != prev_a) | (b != prev_b)
    gid = jnp.cumsum(is_new.astype(jnp.int32)) - 1
    leader = jax.ops.segment_min(idx, gid, num_segments=n)
    out = jnp.zeros(n, dtype=jnp.int32).at[idx].set(leader[gid])
    return out


def compact_unique(labels: jax.Array, n_pad: int) -> Tuple[jax.Array, jax.Array]:
    """Remap arbitrary label values in [0, n_pad) to dense ids [0, c).

    Returns (dense_label_per_slot, num_unique).  The analog of the
    reference's fill_leader_mapping + prefix sum
    (cluster_contraction_preprocessing.cc:17,69): mark used labels, prefix-
    sum the marks, gather.
    """
    used = jnp.zeros(n_pad, dtype=jnp.int32).at[labels].max(1, mode="drop")
    rank = jnp.cumsum(used) - used  # dense id of each used label
    dense = rank[labels].astype(jnp.int32)
    num = jnp.sum(used)
    return dense, num


# ---------------------------------------------------------------------------
# Sort-free rating engines
# ---------------------------------------------------------------------------
#
# aggregate_by_key is exact but costs a full 2-key sort of the edge list per
# LP round — the dominant cost of the whole framework on TPU (XLA sorts are
# many HBM passes; scatter-adds are one).  These engines produce the same
# per-node (best cluster, weight) decisions with segment_sum/segment_max
# only:
#
#   * hashed_rating_table — clustering (unbounded label space): per node, a
#     fixed row of `num_slots` hash slots; each slot's *winner* label gets
#     an EXACT connection-weight sum (every edge with that label lands in
#     the same slot).  Colliding (non-winning) labels are simply not rated
#     this round — the analog of the reference's two-phase rating-map
#     overflow handling (label_propagation.h:62 kRatingMapThreshold), and
#     the per-round salt rotates which label wins a contested slot.
#
#   * dense_block_ratings — refinement (labels are the k blocks): the full
#     exact (n_pad, k) connection table in one segment_sum, no slots, no
#     collisions.


def hashed_rating_table(
    src: jax.Array,
    neighbor_label: jax.Array,
    edge_w: jax.Array,
    n_pad: int,
    num_slots: int,
    salt,
) -> Tuple[jax.Array, jax.Array]:
    """Per-node hashed rating rows.

    Returns (slot_label, slot_w), both [n_pad, num_slots]: slot_label is
    the slot's winning label (-1 for empty slots) and slot_w its exact
    total connection weight from the row's node.
    """
    if n_pad * num_slots >= 2**31:
        raise ValueError("n_pad * num_slots must fit in int32")
    slot = hash_u32(neighbor_label, salt) % jnp.int32(num_slots)
    flat = src.astype(jnp.int32) * num_slots + slot
    total = n_pad * num_slots
    # winner of a contested slot: max hashed key, ties broken by max label
    key = hash_u32(neighbor_label, salt ^ 0x3779B97F)  # fits int32
    kmax = jax.ops.segment_max(key, flat, num_segments=total)
    is_kwin = key == kmax[flat]
    lwin = jax.ops.segment_max(
        jnp.where(is_kwin, neighbor_label, -1), flat, num_segments=total
    )
    is_win = is_kwin & (neighbor_label == lwin[flat])
    w = jax.ops.segment_sum(
        jnp.where(is_win, edge_w, 0).astype(ACC_DTYPE),
        flat,
        num_segments=total,
    )
    slot_label = jnp.where(kmax >= 0, lwin, -1)
    return (
        slot_label.reshape(n_pad, num_slots),
        w.reshape(n_pad, num_slots),
    )


def best_from_rating_table(
    slot_label: jax.Array,
    slot_w: jax.Array,
    labels: jax.Array,
    cluster_weights: jax.Array,
    node_w: jax.Array,
    cap: jax.Array,
    salt,
    communities: jax.Array | None = None,
    require_fit: bool = True,
    label_range: Tuple[jax.Array, jax.Array] | None = None,
) -> Tuple[jax.Array, jax.Array]:
    """Per-node best move target from a hashed rating table: the
    highest-weight slot whose label is not the node's own, fits under the
    weight cap (unless require_fit=False), and shares the node's community
    (when given).  `label_range=(lo, hi)` restricts targets to labels in
    [lo, hi) — the LocalLPClusterer device-owned restriction.  Hashed
    tie-breaking, same contract as argmax_per_segment: (best_label,
    best_w) with -1/INT32_MIN when none.
    """
    n_pad, H = slot_label.shape
    C = cluster_weights.shape[0]
    lab_c = jnp.clip(slot_label, 0, C - 1)
    feas = (slot_label >= 0) & (slot_label != labels[:, None])
    if label_range is not None:
        lo, hi = label_range
        feas = feas & (slot_label >= lo) & (slot_label < hi)
    if require_fit:
        cap_b = jnp.broadcast_to(cap, (C,))
        feas = feas & (
            cluster_weights[lab_c].astype(ACC_DTYPE)
            + node_w[:, None].astype(ACC_DTYPE)
            <= cap_b[lab_c]
        )
    if communities is not None:
        feas = feas & (communities[lab_c] == communities[:, None])
    score = jnp.where(feas, slot_w, INT32_MIN)
    best_w = jnp.max(score, axis=1)
    has = best_w > INT32_MIN
    is_best = feas & (score == best_w[:, None])
    tb = hash_u32(slot_label, salt)
    best_tb = jnp.max(jnp.where(is_best, tb, -1), axis=1)
    winner = is_best & (tb == best_tb[:, None])
    best = jnp.max(jnp.where(winner, slot_label, -1), axis=1)
    return (
        jnp.where(has, best, -1),
        jnp.where(has, best_w, INT32_MIN),
    )


def connection_to_own_label(
    src: jax.Array,
    neighbor_label: jax.Array,
    edge_w: jax.Array,
    labels: jax.Array,
    n_pad: int,
) -> jax.Array:
    """Exact per-node connection weight to the node's own label — one
    masked segment_sum (sort-free replacement for connection_to_label)."""
    match = neighbor_label == labels[jnp.clip(src, 0, n_pad - 1)]
    return jax.ops.segment_sum(
        jnp.where(match, edge_w, 0).astype(ACC_DTYPE),
        src,
        num_segments=n_pad,
    )


def dense_block_ratings(
    src: jax.Array,
    dst: jax.Array,
    edge_w: jax.Array,
    labels: jax.Array,
    n_pad: int,
    num_blocks: int,
) -> jax.Array:
    """Exact (n_pad, k) connection table in one flat segment_sum — the
    rating engine for refinement, where labels are the k blocks (no sort,
    no hash collisions; identical to gains.build_dense_gain_cache but on
    raw arrays)."""
    lab_c = jnp.clip(labels, 0, num_blocks - 1)
    flat = src.astype(jnp.int32) * num_blocks + lab_c[dst]
    conn = jax.ops.segment_sum(
        edge_w.astype(ACC_DTYPE), flat, num_segments=n_pad * num_blocks
    )
    return conn.reshape(n_pad, num_blocks)


def best_from_dense(
    conn: jax.Array,
    labels: jax.Array,
    cluster_weights: jax.Array,
    node_w: jax.Array,
    cap: jax.Array,
    salt,
    communities: jax.Array | None = None,
    require_fit: bool = True,
    allowed: jax.Array | None = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Per-node (best_block, best_w, w_own) from a dense rating table,
    excluding the node's own block, with hashed tie-breaking.

    `communities` (clustering only — there column j is node id j) masks
    columns whose community differs from the row node's; `allowed`
    (bool[k]) masks whole columns (balancer target restrictions)."""
    n_pad, k = conn.shape
    lab_col = jnp.clip(labels, 0, k - 1)
    w_own = jnp.take_along_axis(conn, lab_col[:, None], axis=1)[:, 0]
    cols = jnp.arange(k, dtype=jnp.int32)
    feas = cols[None, :] != lab_col[:, None]
    if allowed is not None:
        feas = feas & allowed[None, :]
    if require_fit:
        cap_b = jnp.broadcast_to(cap, (k,)).astype(ACC_DTYPE)
        feas = feas & (
            cluster_weights[None, :].astype(ACC_DTYPE)
            + node_w[:, None].astype(ACC_DTYPE)
            <= cap_b[None, :]
        )
    if communities is not None:
        feas = feas & (communities[:k][None, :] == communities[:, None])
    score = jnp.where(feas, conn, INT32_MIN)
    best_w = jnp.max(score, axis=1)
    has = best_w > INT32_MIN
    is_best = feas & (score == best_w[:, None])
    tb = hash_u32(jnp.broadcast_to(cols[None, :], conn.shape), salt)
    best_tb = jnp.max(jnp.where(is_best, tb, -1), axis=1)
    winner = is_best & (tb == best_tb[:, None])
    best = jnp.max(jnp.where(winner, cols[None, :], -1), axis=1)
    return (
        jnp.where(has, best, -1),
        jnp.where(has, best_w, INT32_MIN),
        w_own,
    )


def rating_top3_by_sort(
    graph,
    neighbor_label: jax.Array,
    salt,
    k_best: int = 3,
) -> Tuple[jax.Array, ...]:
    """Top-k_best rated clusters per node with NO scatters and NO
    node->edge label expansion — the fast clustering rating engine
    ("sort2").

    TPU cost model (measured on v5e): irregular gathers/scatters cost
    ~7.5 ns *per index* (a 33M-edge expansion is ~250 ms) while sorts are
    ~3 ns/element and streaming ops are free.  This engine therefore uses
    exactly ONE edge-wide gather (labels[dst], done by the caller) and two
    edge-wide sorts; every reduction is a cumsum/cummax trick on sorted
    data, and per-node results are read back with n-sized gathers at CSR
    row boundaries.

      sort1   order edges by (src, label): groups = (node, cluster) pairs
      stream  group sums via cumsum minus a cummax'd group base
              (cum - w at group starts is monotone because weights >= 0)
      sort2   order by (src, group_total, tie_hash): each node's top
              clusters land at the end of its CSR row span
      read    the k_best best (label, weight) pairs per node at row end-j

    Returns (lab1, w1, ..., lab_k, w_k) for the `k_best` top clusters,
    each [n_pad]; absent entries are (-1, INT32_MIN).  Own-cluster
    exclusion, feasibility, and the connection-to-own estimate are applied
    by the caller at node level (see ops/lp.py), trading the reference's
    exact rating-time feasibility (find_best_cluster:461-541) for a
    33M-gather-free round.  The extra top-j reads are n-sized gathers —
    nearly free — so a larger k_best costs almost nothing and improves the
    caller's own-connection estimate on dense (coarse) graphs.
    """
    n_pad = graph.n_pad
    src = graph.src
    w = graph.edge_w.astype(ACC_DTYPE)

    src_s, nb_s, w_s = lax.sort((src, neighbor_label, w), num_keys=2)
    prev_src = jnp.concatenate([jnp.array([-1], src_s.dtype), src_s[:-1]])
    prev_nb = jnp.concatenate([jnp.array([-1], nb_s.dtype), nb_s[:-1]])
    new_grp = (src_s != prev_src) | (nb_s != prev_nb)

    cum = jnp.cumsum(w_s)
    base = lax.cummax(jnp.where(new_grp, cum - w_s, 0))
    total = cum - base
    is_last = jnp.concatenate([new_grp[1:], jnp.array([True])])

    # 16-bit tie operand (hash_tie16): half the third sort key's bytes
    tb = hash_tie16(nb_s, salt)
    prio = jnp.where(is_last, total, -1)
    _, prio2, _, lab2 = lax.sort((src_s, prio, tb, nb_s), num_keys=3)

    # per-node top-j reads at CSR row ends (row spans survive any
    # src-ordered sort: each node's edges occupy the same index range)
    deg = graph.row_ptr[1:] - graph.row_ptr[:-1]
    end = graph.row_ptr[1:]
    out = []
    for j in range(k_best):
        pos = jnp.clip(end - 1 - j, 0, prio2.shape[0] - 1)
        valid = (deg > j) & (prio2[pos] >= 0)
        out.append(jnp.where(valid, lab2[pos], -1))
        out.append(jnp.where(valid, prio2[pos], INT32_MIN))
    return tuple(out)


def expand_active_rows(
    row_ptr: jax.Array,
    degrees: jax.Array,
    active: jax.Array,
    num_slots: int,
):
    """Compact the CSR rows of active nodes into a `num_slots` buffer.

    The delta-round primitive: after the first LP/Jet round only a small
    fraction of nodes (movers + their neighbors) need re-rating, yet every
    edge-wide op costs ~10-15 ns per SLOT regardless of how many slots
    matter.  This lays the active nodes' rows head-to-tail into a fixed
    small buffer so every downstream pass scales with the active-edge
    count, not m.

    Cost: O(n) streaming + one n-wide scatter + ONE buffer-wide gather —
    the edge id falls out of a single gather of the PRE-SUBTRACTED
    (row_ptr - start) array (edge_id = diff[owner] + slot), instead of
    separate row_ptr[owner] and start[owner] gathers.  Do NOT be tempted
    to widen this into (n, r) row tables: TPU pads the minor dimension
    to 128 lanes, so materialized small-r tables cost 128/r x the memory
    and bandwidth (measured OOM at the 33.5M-edge shape), and XLA
    un-fuses stacked-table gathers back into scalar gathers anyway.

    Returns (owner_c, owner_key, edge_id, valid, start, end):
      owner_c  i32[num_slots]  owning node of each slot (clipped)
      owner_key i32[num_slots] owner for valid slots, n_pad for pad slots
      edge_id  i32[num_slots]  index into the edge arrays (clip before use)
      valid    bool[num_slots]
      start/end i32[n_pad]     each ACTIVE node's row span in the buffer
    """
    n_pad = degrees.shape[0]
    act = active & (degrees > 0)
    act_deg = jnp.where(act, degrees, 0).astype(jnp.int32)
    end = jnp.cumsum(act_deg)
    start = end - act_deg
    node_ids = jnp.arange(n_pad, dtype=jnp.int32)
    do = act & (start < num_slots)
    pos = jnp.where(do, start, num_slots)
    owner0 = (
        jnp.full(num_slots, -1, dtype=jnp.int32)
        .at[pos]
        .max(jnp.where(do, node_ids, -1), mode="drop")
    )
    owner = lax.cummax(owner0)
    slot = jnp.arange(num_slots, dtype=jnp.int32)
    owner_c = jnp.clip(owner, 0, n_pad - 1)
    diff = row_ptr[:-1].astype(jnp.int32) - start
    edge_id = diff[owner_c] + slot
    valid = (owner >= 0) & (slot < end[n_pad - 1])
    owner_key = jnp.where(valid, owner_c, n_pad)
    return owner_c, owner_key, edge_id, valid, start, end


def prune_candidates_to_budget(
    candidate: jax.Array,
    gain: jax.Array,
    degrees: jax.Array,
    salt,
    budget: int,
) -> jax.Array:
    """Restrict `candidate` to the best-(gain, hashed tie) subset whose
    total degree fits `budget` edge slots.

    The two-stage candidate pruning of the Jet refiner: the gain
    temperature admits most border nodes on fine RMAT levels, so the
    candidate rows overflow the delta buffer and every pass falls back
    to full edge width (the round-2 wall-clock whale).  Keeping the
    top-gain candidates that fit guarantees the row-compacted path
    always fires; pruned candidates stay unlocked and compete again next
    iteration, so over a Jet round's 8-16 iterations the move order
    approaches the reference's gain-ordered afterburner sequence
    (jet_refiner.cc:133-170) rather than changing what can move.

    When the candidate set already fits, the result equals `candidate`
    exactly.  One n-wide 2-key sort + streaming passes + one n-wide
    scatter; no edge-wide work.
    """
    n_pad = candidate.shape[0]
    node_ids = jnp.arange(n_pad, dtype=jnp.int32)
    # sentinel INT32_MIN+1 for non-candidates keys them strictly below
    # every candidate and keeps the negation below overflow-free
    key = jnp.where(
        candidate, jnp.maximum(gain, INT32_MIN + 2), INT32_MIN + 1
    )
    tb = hash_u32(node_ids, salt)
    neg_key = -key
    neg_tb = -tb
    deg = jnp.where(candidate, degrees, 0).astype(jnp.int32)
    _, _, deg_s, id_s = lax.sort(
        (neg_key, neg_tb, deg, node_ids), num_keys=2
    )
    cum = jnp.cumsum(deg_s)
    keep_s = cum <= budget
    keep = (
        jnp.zeros(n_pad, dtype=jnp.bool_).at[id_s].set(keep_s, mode="drop")
    )
    return candidate & keep


def rating_topk_rows(
    owner_key: jax.Array,
    nb: jax.Array,
    w: jax.Array,
    end: jax.Array,
    deg: jax.Array,
    salt,
    k_best: int,
    w_own: jax.Array | None = None,
) -> Tuple[jax.Array, ...]:
    """Top-k_best rated clusters per row, from row-grouped
    (owner, neighbor-label, weight) triples.

    The row-buffer twin of rating_top3_by_sort: slots must already be
    grouped by owner (ascending, pad slots keyed n_pad); two buffer-wide
    sorts + streaming passes, no scatters.  Returns the flat tuple
    (lab1, w1, ..., lab_k, w_k), each [n_pad], read at row ends
    (end[i]-1-j); absent entries are (-1, INT32_MIN).

    Pad-slot invariant: callers may key pad slots with n_pad (the
    delta-round path) OR with n_pad-1 (the full-round path, which passes
    owner_key=graph.src where pad edges carry owner n_pad-1).  The
    latter is sound ONLY because node n_pad-1 is always a pad node with
    degree 0 and an empty row span, so (a) pad slots still sort after
    every real row's slots and (b) no real read position end[i]-1-j ever
    lands inside them (deg[n_pad-1] == 0 gates validj).  A graph layout
    change that gives node n_pad-1 real edges would silently corrupt the
    top-K reads — keep the last pad row empty (see
    DeviceGraph.from_host's padding contract).

    `w_own` (optional, per SLOT: the slot's weight where its neighbor
    label equals the owner's label, else 0) rides sort1 as an extra
    operand; the per-node own-connection then falls out of one cumsum at
    the row boundaries and the return becomes (topk_tuple, w_cur).  This
    serves the lane-routed rating path (ops/lane_gather.py), whose slot
    order is NOT row-grouped — the owner-sort both engines already do
    restores the spans.
    """
    has_own = w_own is not None
    if has_own:
        o_s, nb_s, w_s, wo_s = lax.sort(
            (owner_key, nb, w.astype(ACC_DTYPE), w_own.astype(ACC_DTYPE)),
            num_keys=2,
        )
    else:
        o_s, nb_s, w_s = sort_by_two_keys(owner_key, nb, w.astype(ACC_DTYPE))
    prev_o = jnp.concatenate([jnp.array([-1], o_s.dtype), o_s[:-1]])
    prev_nb = jnp.concatenate([jnp.array([-1], nb_s.dtype), nb_s[:-1]])
    new_grp = (o_s != prev_o) | (nb_s != prev_nb)
    cum = jnp.cumsum(w_s)
    base = lax.cummax(jnp.where(new_grp, cum - w_s, 0))
    total = cum - base
    is_last = jnp.concatenate([new_grp[1:], jnp.array([True])])
    # 16-bit tie operand (hash_tie16): half the third sort key's bytes
    tb = hash_tie16(nb_s, salt)
    prio = jnp.where(is_last, total, -1)
    _, prio2, _, lab2 = lax.sort((o_s, prio, tb, nb_s), num_keys=3)
    D = prio2.shape[0]
    out = []
    for j in range(k_best):
        posj = jnp.clip(end - 1 - j, 0, D - 1)
        validj = (deg > j) & (prio2[posj] >= 0)
        out.append(jnp.where(validj, lab2[posj], -1))
        out.append(jnp.where(validj, prio2[posj], INT32_MIN))
    if not has_own:
        return tuple(out)
    csum = jnp.cumsum(wo_s)
    csum0 = jnp.concatenate([jnp.zeros(1, dtype=csum.dtype), csum])
    start = jnp.clip(end - deg, 0, D)
    w_cur = csum0[jnp.clip(end, 0, D)] - csum0[start]
    return tuple(out), w_cur


def connection_to_own_rows(
    nb: jax.Array,
    w: jax.Array,
    own_of_slot: jax.Array,
    start: jax.Array,
    end: jax.Array,
) -> jax.Array:
    """Exact per-row connection weight to the row node's own label, via a
    streaming masked cumsum over row spans — no scatter, no sort.  `nb`
    and `w` are in buffer order, `own_of_slot` is the owner's label per
    slot, `start`/`end` the row spans."""
    D = nb.shape[0]
    match = nb == own_of_slot
    csum = jnp.cumsum(jnp.where(match, w, 0).astype(ACC_DTYPE))
    csum0 = jnp.concatenate([jnp.zeros(1, dtype=csum.dtype), csum])
    s = jnp.clip(start, 0, D)
    e = jnp.clip(end, 0, D)
    return csum0[e] - csum0[s]


def packed_afterburner_gain(
    src: jax.Array,
    dst: jax.Array,
    edge_w: jax.Array,
    row_ptr: jax.Array,
    part: jax.Array,
    next_part: jax.Array,
    gain: jax.Array,
    candidate: jax.Array,
    k: int,
) -> jax.Array:
    """Afterburner-adjusted gain per node, at TWO edge-wide gathers.

    The afterburner (jet_refiner.cc:133-170) re-evaluates each move
    candidate's gain assuming every neighbor ordering strictly before it —
    by (gain, smaller id) — already sits at its target block.  A naive
    implementation gathers gain/part/next_part for both endpoints of every
    edge (six edge-wide gathers — irregular gathers are charged per index
    on TPU and dominate the round).  Here the three per-node values are
    BIT-PACKED into ONE int32 per node, so each endpoint costs a single
    gather.  (n, r) row tables are NOT an alternative: TPU pads the minor
    dimension to 128 lanes — a materialized (m, 2) table is a 64x
    memory/bandwidth blowup (measured OOM at 33.5M edges) and XLA
    un-fuses in-loop stacked-table gathers back into scalar gathers.
    The per-node contribution sum is a streaming cumsum + CSR
    row-boundary diff (src must be CSR-sorted), not a scatter.

    The gain field is clipped to `31 - 2*ceil(log2 k)` bits; a runtime
    guard detects when any candidate |gain| exceeds the range (heavy
    edge weights) and dispatches the exact per-endpoint-gather fallback,
    so move SELECTION never silently diverges from the exact ordering.

    Returns adj_gain[n_pad]; entries for non-candidates are the plain
    neighborhood sum with no candidate mask applied to themselves (mask
    with `candidate` when accepting).  Shared by the Jet refiner and the
    bulk-synchronous LP refinement round.  A thin wrapper over the spans
    variant: a CSR edge list is a row buffer with owner=src and spans
    [row_ptr[i], row_ptr[i+1]).
    """
    adj, _, _ = packed_afterburner_gain_rows(
        src, dst, edge_w, row_ptr[:-1], row_ptr[1:],
        part, next_part, gain, candidate, k,
    )
    return adj


def packed_afterburner_gain_rows(
    owner: jax.Array,
    dst: jax.Array,
    edge_w: jax.Array,
    start: jax.Array,
    end: jax.Array,
    part: jax.Array,
    next_part: jax.Array,
    gain: jax.Array,
    candidate: jax.Array,
    k: int,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """packed_afterburner_gain over a row buffer: slots grouped by owner
    with spans [start, end) per node (see expand_active_rows).

    Returns (adj_gain[n_pad], from_u[slots], to_u[slots]): the owner's
    current and tentative blocks PER SLOT fall out of the endpoint
    gathers either branch takes, so the Jet conn-table delta reuses them
    without further irregular ops."""
    label_bits = max((k - 1).bit_length(), 1)
    gain_bits = 31 - 2 * label_bits

    def _row_sums(to_u, from_u, block_v, u_is_cand):
        contrib = jnp.where(
            to_u == block_v,
            edge_w,
            jnp.where(from_u == block_v, -edge_w, 0),
        )
        csum = jnp.cumsum(
            jnp.where(u_is_cand, contrib, 0).astype(ACC_DTYPE)
        )
        csum0 = jnp.concatenate([jnp.zeros(1, dtype=csum.dtype), csum])
        D = contrib.shape[0]
        return csum0[jnp.clip(end, 0, D)] - csum0[jnp.clip(start, 0, D)]

    def _packed(_):
        half = jnp.int32(1 << (gain_bits - 1))
        gain_clip = jnp.clip(gain, 1 - half, half - 1) + half
        # the clipped field fits its bit budget by construction; force
        # int32 so 64-bit weight builds produce the same meta dtype as
        # the exact branch's label columns (lax.cond requires it)
        gain_field = jnp.where(candidate, gain_clip, 0).astype(jnp.int32)
        meta = (
            (gain_field << (2 * label_bits))
            | (next_part << label_bits)
            | part
        )
        mu = meta[owner]
        mv = meta[dst]
        lab_mask = jnp.int32((1 << label_bits) - 1)
        gain_u = mu >> (2 * label_bits)
        gain_v = mv >> (2 * label_bits)
        v_is_cand = gain_v > 0
        v_before_u = v_is_cand & (
            (gain_v > gain_u) | ((gain_v == gain_u) & (dst < owner))
        )
        block_v = jnp.where(
            v_before_u, (mv >> label_bits) & lab_mask, mv & lab_mask
        )
        to_u = (mu >> label_bits) & lab_mask
        from_u = mu & lab_mask
        return _row_sums(to_u, from_u, block_v, gain_u > 0), from_u, to_u

    def _exact(_):
        gain_full = jnp.where(candidate, gain, INT32_MIN)
        gain_u = gain_full[owner]
        gain_v = gain_full[dst]
        v_is_cand = gain_v > INT32_MIN
        v_before_u = v_is_cand & (
            (gain_v > gain_u) | ((gain_v == gain_u) & (dst < owner))
        )
        block_v = jnp.where(v_before_u, next_part[dst], part[dst])
        from_u = part[owner]
        to_u = next_part[owner]
        return (
            _row_sums(to_u, from_u, block_v, gain_u > INT32_MIN),
            from_u,
            to_u,
        )

    if gain_bits < 15:
        # huge k: the packed layout has no room at all
        return _exact(None)
    # clip guard: the packed gain field only orders moves correctly while
    # every candidate's |gain| fits its `gain_bits - 1` bits.  Heavy edge
    # weights (or degrees >~16k at k=256) push gains past the clip range
    # and silently change move SELECTION vs the exact ordering — so the
    # regime is detected at runtime (an n-wide reduce on values already
    # in hand) and the exact path takes over.  Both branches compile
    # once; only one executes per call.
    half = jnp.int32(1 << (gain_bits - 1))
    max_abs_gain = jnp.max(
        jnp.where(candidate, jnp.abs(jnp.clip(gain, -2**30, 2**30)), 0)
    )
    return lax.cond(max_abs_gain < half, _packed, _exact, None)


def neighbor_any_true(
    flag: jax.Array,
    dst: jax.Array,
    row_ptr: jax.Array,
) -> jax.Array:
    """Per-node "any neighbor has `flag`", at one edge-wide gather plus
    streaming passes (cumsum + CSR row-boundary diff) — the scatter-free
    replacement for segment_max(flag[dst], src).  Requires the edge list
    in CSR order (contiguous row spans), which DeviceGraph guarantees."""
    f = flag[dst].astype(ACC_DTYPE)
    csum = jnp.cumsum(f)
    csum0 = jnp.concatenate([jnp.zeros(1, dtype=csum.dtype), csum])
    rp = jnp.clip(row_ptr, 0, f.shape[0])
    return (csum0[rp[1:]] - csum0[rp[:-1]]) > 0


def afterburner_filter(
    src: jax.Array,
    dst: jax.Array,
    edge_w: jax.Array,
    labels_of_src: jax.Array,
    labels_of_dst: jax.Array,
    gain_by_node: jax.Array,
    target_by_node: jax.Array,
    seg: jax.Array,
    num_segments: int,
    src_order: jax.Array | None = None,
    dst_order: jax.Array | None = None,
) -> jax.Array:
    """Jet's afterburner (jet_refiner.cc:133-170) as a reusable filter:
    re-evaluate each move candidate's gain assuming every neighbor that
    orders strictly before it — by (gain, smaller id) — is already at its
    target, and return the adjusted gain per segment (node).  Bulk-
    synchronous LP refinement needs this because simultaneous moves of
    adjacent nodes can jointly increase the cut even when each individual
    gain is positive.

    `gain_by_node` must be INT32_MIN for non-candidates; `labels_of_*`
    and `target_by_node` are indexed by the same space as `src`/`dst`;
    `seg` maps each edge to its output segment (local node id on sharded
    layouts).  `src_order`/`dst_order` override the ids used for the
    who-moves-first tie ordering — on ghost-halo layouts `src`/`dst` are
    LOCAL indices (not globally consistent), so callers pass the GLOBAL
    ids there to keep the order a total order across devices.
    """
    if src_order is None:
        src_order = src
    if dst_order is None:
        dst_order = dst
    gain_u = gain_by_node[src]
    gain_v = gain_by_node[dst]
    v_before_u = (gain_v > INT32_MIN) & (
        (gain_v > gain_u) | ((gain_v == gain_u) & (dst_order < src_order))
    )
    block_v = jnp.where(v_before_u, target_by_node[dst], labels_of_dst)
    to_u = target_by_node[src]
    from_u = labels_of_src
    contrib = jnp.where(
        to_u == block_v,
        edge_w,
        jnp.where(from_u == block_v, -edge_w, 0),
    )
    return jax.ops.segment_sum(
        jnp.where(gain_u > INT32_MIN, contrib, 0),
        jnp.clip(seg, 0, num_segments - 1),
        num_segments=num_segments,
    )
