"""Device BFS distances — frontier expansion as segmented reductions.

The kernel half of the BFS extractor (kaminpar-dist/graphutils/
bfs_extractor.{h,cc}): the reference grows a per-PE BFS around seed nodes
with explicit frontier queues and ghost-node exchanges
(bfs_extractor.cc:613).  On TPU the frontier is a whole-graph predicate and
one expansion step is a single `segment_min` over the COO edge list — no
queues, no per-node control flow; `max_hops` steps run inside one jitted
`lax.while_loop`.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from ..graphs.csr import DeviceGraph

UNREACHED = jnp.iinfo(jnp.int32).max


@partial(jax.jit, static_argnames=())
def bfs_hops(
    graph: DeviceGraph, seeds: jax.Array, max_hops: jax.Array
) -> jax.Array:
    """Hop distance from the seed set, capped at `max_hops`.

    Args:
      seeds:    i32[s] node ids (pad entries -1 are ignored)
      max_hops: i32 scalar — expansion stops after this many hops

    Returns i32[n_pad]: 0 for seeds, hop count for reached nodes within
    the cap, UNREACHED (INT32_MAX) otherwise (pad nodes included).
    """
    n_pad = graph.n_pad
    node_ids = jnp.arange(n_pad, dtype=jnp.int32)
    is_real = node_ids < graph.n

    dist0 = jnp.full(n_pad, UNREACHED, dtype=jnp.int32)
    valid_seed = (seeds >= 0) & (seeds < graph.n)
    dist0 = dist0.at[jnp.clip(seeds, 0, n_pad - 1)].min(
        jnp.where(valid_seed, 0, UNREACHED)
    )
    dist0 = jnp.where(is_real, dist0, UNREACHED)

    def cond(state):
        h, dist, changed = state
        return (h < max_hops) & changed

    def body(state):
        h, dist, _ = state
        in_frontier = dist[graph.src] == h
        cand = jnp.where(in_frontier, h + 1, UNREACHED)
        # pad edges point at the pad node; is_real masks it back out
        reached = jax.ops.segment_min(
            cand, graph.dst, num_segments=n_pad
        )
        new_dist = jnp.where(is_real, jnp.minimum(dist, reached), UNREACHED)
        return h + 1, new_dist, jnp.any(new_dist != dist)

    _, dist, _ = lax.while_loop(
        cond, body, (jnp.int32(0), dist0, jnp.array(True))
    )
    return dist
