"""Static-index gather at streaming speed via Mosaic's lane-wise
``tpu.dynamic_gather``.

The pipeline's hot irregular op is ``table[idx]`` with ``idx`` an
edge-wide index array (``labels[dst]`` in LP rating, block lookups in
Jet).  XLA lowers that gather index-serially on TPU: ~12.5 ns per index,
0.1% of HBM peak (scripts/microbench_gather.py, docs/performance.md) —
the round-4 speed floor.

Mosaic (JAX >= 0.9) *does* lower one gather shape to hardware:
``jnp.take_along_axis(x, q, axis=0)`` on 2D operands of identical shape
becomes ``tpu.dynamic_gather``:

    out[s, l] = x[q[s, l], l]          # per-LANE gather across sublanes

Element (s, l) can only read column l.  A general gather therefore
needs indices routed to their *native lane* (``idx % 128``) first —
normally a per-call reshuffle as expensive as the gather itself.  Two
properties of this pipeline break the deadlock:

  1. The index arrays are STATIC per graph level (CSR topology does not
     change between LP/Jet rounds; only the table — labels, blocks —
     changes).  The routing can be planned ONCE per level and reused by
     every round.
  2. The consumers are ORDER-AGNOSTIC: the sort2 rating engine re-sorts
     (owner, label, weight) triples anyway and the dense engine
     segment-sums them, so gathered values never need to return to edge
     order.  Static co-arrays (src, edge_w) are routed once at plan
     build and ride along.

``build_gather_plan`` sorts the indices by (table chunk, lane) on
device, pads each lane's run to a common per-chunk height, and records
(a) ``q``: the in-chunk row each routed slot reads, (b) ``inv``: the
original position each routed slot serves (-1 for pad).  ``lane_gather``
then streams the table chunk-by-chunk through VMEM with a
scalar-prefetched chunk id per grid tile; per round it moves
8 B/element instead of paying the 12.5 ns/element XLA loop.

Reference anchor: the op this accelerates is the neighbor-label lookup
of the reference's LP loop (kaminpar-shm/label_propagation.h:1682) and
Jet's block lookups (kaminpar-shm/refinement/jet/jet_refiner.cc).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..utils.math import ceil_div, round_up

L = 128  # TPU lane count — the native minor dimension of every table

# Rows per table chunk: 4096x128 int32 = 2 MiB.  With the (S, 128)
# q/out blocks double-buffered by the pallas pipeline this stays well
# inside the ~16 MiB VMEM budget.
DEFAULT_CHUNK_ROWS = 4096


@jax.tree_util.register_pytree_node_class
@dataclass(frozen=True)
class GatherPlan:
    """Static routing plan for gathers from a fixed index array.

    Leaves (device arrays):
      q          i32[H, 128]   in-chunk source row per routed slot
      tile_chunk i32[H // S]   table chunk id per grid tile
      inv        i32[H * 128]  original index position per routed slot
                               (-1 for pad slots)
    Static:
      S       rows per table chunk (grid tile height)
      C       number of table chunks
      H       routed rows (multiple of S)
      m       original index count
      n_rows  table rows (table_len // 128)
    """

    q: jax.Array
    tile_chunk: jax.Array
    inv: jax.Array
    S: int
    C: int
    H: int
    m: int
    n_rows: int

    def tree_flatten(self):
        return (
            (self.q, self.tile_chunk, self.inv),
            (self.S, self.C, self.H, self.m, self.n_rows),
        )

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(*leaves, *aux)

    @property
    def num_slots(self) -> int:
        return self.H * L


@functools.partial(jax.jit, static_argnames=("sl",))
def _sort_by_key(idx, sl):
    """Sort positions by (chunk, lane) key; return key_s, pos_s, qloc_s."""
    m = idx.shape[0]
    lane = idx % L
    chunk = idx // (sl * L)
    qloc = (idx // L) % sl
    key = chunk * L + lane
    pos = jnp.arange(m, dtype=jnp.int32)
    return lax.sort((key, pos, qloc), num_keys=1)


@functools.partial(jax.jit, static_argnames=("H",))
def _scatter_plan(key_s, pos_s, qloc_s, chunk_start, region_off, H):
    """Place sorted entries at their padded routed slots."""
    m = key_s.shape[0]
    iota = jnp.arange(m, dtype=jnp.int32)
    prev = jnp.concatenate([jnp.array([-1], key_s.dtype), key_s[:-1]])
    grp_start = key_s != prev
    rank = iota - lax.cummax(jnp.where(grp_start, iota, 0))
    lane_s = key_s % L
    # expand the (C,) region offsets to the m sorted slots without an
    # m-wide gather: drop each chunk's offset at its first sorted
    # position (a C-element scatter; empty chunks share a position, so
    # .max keeps the largest = the live one) and forward-fill by cummax
    marks = (
        jnp.zeros(m, dtype=jnp.int32)
        .at[chunk_start]
        .max(region_off, mode="drop")
    )
    row = lax.cummax(marks) + rank
    slot = row * L + lane_s
    q = (
        jnp.zeros(H * L, dtype=jnp.int32)
        .at[slot]
        .set(qloc_s, mode="drop")
        .reshape(H, L)
    )
    inv = (
        jnp.full(H * L, -1, dtype=jnp.int32).at[slot].set(pos_s, mode="drop")
    )
    return q, inv


from ..resilience.errors import PlanBlowup


class PlanBlowupError(PlanBlowup, ValueError):
    """build_gather_plan aborted: the routed plan would exceed max_slots.

    Raised BEFORE the H*128-wide q/inv arrays are materialized, so a
    hub-skewed level can be rejected without first allocating the very
    blowup the cap exists to prevent.  Subclasses the structured
    resilience.PlanBlowup, so the `lane-gather` site's with_fallback
    wrapper classifies it and degrades to the XLA gather (ValueError is
    kept for backward compatibility with pre-resilience callers)."""

    def __init__(self, num_slots: int, max_slots: int) -> None:
        self.num_slots = num_slots
        self.max_slots = max_slots
        super().__init__(
            f"routed plan needs {num_slots} slots > cap {max_slots}"
        )


def build_gather_plan(
    idx,
    table_len: int,
    chunk_rows: int = DEFAULT_CHUNK_ROWS,
    max_slots: Optional[int] = None,
) -> GatherPlan:
    """Plan lane-routed gathers from the static index array ``idx``.

    ``table_len`` must be a multiple of 128 (device arrays are padded
    to lane multiples already).  Values of ``idx`` must lie in
    [0, table_len).  Not jittable (the routed height depends on the
    lane-count histogram), but cheap: one m-wide sort, two m-wide
    scatters, and a 1 KiB histogram readback — amortized over every
    round at the level.

    With ``max_slots`` the plan aborts with PlanBlowupError as soon as
    the routed height is known (after the histogram, before any
    slot-wide array exists) when it would exceed the cap.
    """
    if table_len % L:
        raise ValueError(f"table_len {table_len} not a multiple of {L}")
    n_rows = table_len // L
    S = min(round_up(n_rows, 8), chunk_rows)
    C = ceil_div(n_rows, S)
    idx = jnp.asarray(idx, dtype=jnp.int32)
    m = idx.shape[0]
    if m:
        lo, hi = int(jnp.min(idx)), int(jnp.max(idx))
        if lo < 0 or hi >= table_len:
            raise ValueError(
                f"indices out of range [0, {table_len}): found "
                f"[{lo}, {hi}]"
            )
    key_s, pos_s, qloc_s = _sort_by_key(idx, S)

    # per-(chunk, lane) counts via boundary search on the sorted keys
    bounds = np.asarray(
        jnp.searchsorted(key_s, jnp.arange(C * L + 1, dtype=jnp.int32))
    )
    counts = (bounds[1:] - bounds[:-1]).reshape(C, L)
    # untouched chunks get NO region (no tile, no table-chunk stream)
    h_c = [
        0 if counts[c].max() == 0 else round_up(int(counts[c].max()), S)
        for c in range(C)
    ]
    if sum(h_c) == 0:
        h_c[0] = S  # degenerate m=0 plan: one all-pad tile
    # routed-row offsets <= H < 2^31 by construction  # tpulint: disable=R3
    region_off = np.concatenate([[0], np.cumsum(h_c)[:-1]]).astype(np.int32)
    chunk_start = bounds[: C * L : L].astype(np.int32)
    H = int(sum(h_c))
    if max_slots is not None and H * L > max_slots:
        raise PlanBlowupError(H * L, int(max_slots))

    q, inv = _scatter_plan(
        key_s,
        pos_s,
        qloc_s,
        jnp.asarray(chunk_start),
        jnp.asarray(region_off),
        H,
    )
    tiles: list[int] = []
    for c in range(C):
        tiles.extend([c] * (h_c[c] // S))  # empty chunks contribute none
    return GatherPlan(
        q=q,
        tile_chunk=jnp.asarray(tiles, dtype=jnp.int32),
        inv=inv,
        S=S,
        C=C,
        H=H,
        m=m,
        n_rows=n_rows,
    )


def route_codata(plan: GatherPlan, arr, fill):
    """Route a static edge-order co-array into the plan's slot order.

    Done once per level per array (an ordinary XLA gather); the result
    is reused by every round.  Pad slots get ``fill``.
    """
    arr = jnp.asarray(arr)
    safe = jnp.clip(plan.inv, 0, max(plan.m - 1, 0))
    return jnp.where(plan.inv >= 0, arr[safe], fill)


def _gather_kernel(tile_chunk_ref, table_ref, q_ref, out_ref):
    del tile_chunk_ref  # consumed by the index maps
    out_ref[...] = jnp.take_along_axis(table_ref[...], q_ref[...], axis=0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def lane_gather(table, plan: GatherPlan, interpret: bool = False):
    """Gather ``table[idx]`` in the plan's routed slot order.

    ``table`` is the flat i32[table_len] array (e.g. labels).  Returns
    i32[H * 128]; slot j serves original index position plan.inv[j]
    (-1 slots are pads).  Use ``route_codata`` at plan build to align
    per-edge companions.
    """
    S, C, H = plan.S, plan.C, plan.H
    tab = table.astype(jnp.int32)
    pad = C * S * L - tab.shape[0]
    if pad:
        tab = jnp.concatenate([tab, jnp.zeros(pad, jnp.int32)])
    tab3 = tab.reshape(C, S, L)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(H // S,),
        in_specs=[
            pl.BlockSpec((None, S, L), lambda t, tc: (tc[t], 0, 0)),
            pl.BlockSpec((S, L), lambda t, tc: (t, 0)),
        ],
        out_specs=pl.BlockSpec((S, L), lambda t, tc: (t, 0)),
    )
    out = pl.pallas_call(
        _gather_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((H, L), jnp.int32),
        interpret=interpret,
    )(plan.tile_chunk, tab3, plan.q)
    return out.reshape(H * L)


# ---------------------------------------------------------------------------
# Per-level edge-plan pack + cache
# ---------------------------------------------------------------------------
#
# The hot consumers (LP rating, Jet conn build) gather labels at
# graph.dst with co-data (src, edge_w) riding along.  One plan per graph
# level serves every round of LP clustering, LP refinement, and Jet at
# that level; the deep-multilevel driver revisits the same DeviceGraph
# objects during uncoarsening, so plans are cached by the identity of
# the level's dst array.

# routed slots used by the current jit trace run in interpreter mode
# when this is set (CPU tests of the integration)
INTERPRET = False

# plan building pays one m-wide sort + two m-wide scatters; below this
# many edge slots the per-round XLA gather is cheap enough that the
# plan never pays for itself (matches ops/lp.DELTA_MIN_EDGE_SLOTS).
MIN_EDGE_SLOTS = 1 << 22

# Routed-slot blowup cap: per-chunk heights round each chunk's max
# per-lane count up to S, so one high in-degree hub (RMAT-typical)
# can inflate H*128 to a multiple of m — five i32 arrays of that width
# pin HBM per cached level and every rating sort then runs over the
# inflated slot count (ADVICE round 5 medium).  Plans wider than this
# multiple of the index count are discarded in favor of the XLA gather.
PLAN_MAX_SLOT_RATIO = 2.0


def slot_cap(m: int) -> Optional[int]:
    """The num_slots budget for an m-wide index array
    (PLAN_MAX_SLOT_RATIO * m); None = uncapped (tests lift the ratio
    to inf).  The single source of the cap for plan_within_cap and
    edge_plans' build_gather_plan(max_slots=...) abort."""
    import math

    ratio = PLAN_MAX_SLOT_RATIO * max(int(m), 1)
    return None if math.isinf(ratio) or math.isnan(ratio) else int(ratio)


def plan_within_cap(plan: GatherPlan, m: int) -> bool:
    """True when the routed plan's slot count is affordable for an
    m-wide index array (num_slots <= slot_cap(m))."""
    cap = slot_cap(m)
    return cap is None or plan.num_slots <= cap


@jax.tree_util.register_pytree_node_class
@dataclass(frozen=True)
class EdgePlans:
    """Routed views of a level's static edge arrays."""

    plan: GatherPlan
    owner_key: jax.Array  # i32[H*128] src per routed slot (pad: n_pad-1)
    src_idx: jax.Array    # i32[H*128] src clipped for label lookups
    edge_w: jax.Array     # i32[H*128] edge weight per routed slot (pad: 0)

    def tree_flatten(self):
        return ((self.plan, self.owner_key, self.src_idx, self.edge_w), None)

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        del aux
        return cls(*leaves)


# key -> (dst_array, EdgePlans).  The dst array itself is stored and
# identity-checked on every hit: holding the reference prevents Python
# id recycling from ever matching a DIFFERENT topology's array, and the
# `is` check makes the id-based key safe even across cache clears.
# Entries pin device memory (O(m) per level), so the cache is small and
# the partitioner clears it at every compute_partition entry.
_PLAN_CACHE: dict = {}
_PLAN_CACHE_MAX = 4


def clear_plan_cache() -> None:
    _PLAN_CACHE.clear()


def edge_plans(graph):
    """The routed edge views of a DeviceGraph level (cached), or None
    when the plan blew past PLAN_MAX_SLOT_RATIO and the level must use
    the XLA-gather fallback.  The verdict (and the pad-overhead ratio)
    is emitted as a `lane-gather-plan` telemetry event either way, so
    run reports show how much slot padding each routed level carries."""
    key = (id(graph.dst), graph.dst.shape[0], graph.n_pad)
    hit = _PLAN_CACHE.get(key)
    if hit is not None and hit[0] is graph.dst:
        return hit[1]
    m = int(graph.dst.shape[0])
    cap = slot_cap(m)
    from .. import telemetry
    from ..resilience import with_fallback

    def _build_pack():
        # the cap aborts inside the builder, BEFORE the H*128-wide
        # q/inv arrays exist — a hub-skewed level must not allocate
        # the very blowup it is being rejected for
        plan = build_gather_plan(graph.dst, graph.n_pad, max_slots=cap)
        telemetry.event(
            "lane-gather-plan",
            m=m,
            num_slots=plan.num_slots,
            pad_overhead=round(plan.num_slots / max(m, 1), 4),
            capped=False,
        )
        n_pad = graph.n_pad
        owner_key = route_codata(plan, graph.src, n_pad - 1)
        return EdgePlans(
            plan=plan,
            owner_key=owner_key,
            src_idx=jnp.clip(owner_key, 0, n_pad - 1),
            edge_w=route_codata(plan, graph.edge_w, 0),
        )

    def _xla_fallback(exc):
        num_slots = getattr(exc, "num_slots", None)
        pad_overhead = (
            round(num_slots / max(m, 1), 4) if num_slots is not None
            else None
        )
        telemetry.event(
            "lane-gather-plan",
            m=m,
            num_slots=num_slots,
            pad_overhead=pad_overhead,
            capped=True,
        )
        from ..utils.logger import log_progress

        detail = (
            f"num_slots={num_slots} > {PLAN_MAX_SLOT_RATIO}x m={m}, "
            f"pad overhead {pad_overhead}x"
            if num_slots is not None
            else f"{type(exc).__name__}" if exc is not None
            else "circuit breaker open"
        )
        log_progress(
            f"lane-gather: plan discarded ({detail}); falling back to "
            "the XLA gather"
        )
        return None

    pack = with_fallback(_build_pack, _xla_fallback, site="lane-gather")
    if len(_PLAN_CACHE) >= _PLAN_CACHE_MAX:
        _PLAN_CACHE.pop(next(iter(_PLAN_CACHE)))
    _PLAN_CACHE[key] = (graph.dst, pack)
    return pack


def routed_block_ratings(plans: EdgePlans, labels, k: int, n_pad: int):
    """Dense (n_pad, k) rating table via the lane-routed block lookup —
    the routed twin of segments.dense_block_ratings (segment_sum is
    slot-order-agnostic; pad slots carry owner n_pad-1, weight 0)."""
    from .segments import ACC_DTYPE

    lab_c = jnp.clip(labels, 0, k - 1)
    nb_r = lane_gather(lab_c, plans.plan, interpret=INTERPRET)
    flat = plans.src_idx * k + jnp.clip(nb_r, 0, k - 1)
    return jax.ops.segment_sum(
        plans.edge_w.astype(ACC_DTYPE), flat, num_segments=n_pad * k
    ).reshape(n_pad, k)


# last probe / override decision, surfaced in run reports
# (telemetry.report `lane_gather` section) and by the probe event
_PROBE_STATUS: dict = {"mode": "not-probed"}


def probe_status() -> dict:
    """The current routing decision: probe verdict + timings when the
    support probe ran, or the env-override / not-probed state."""
    import os

    status = dict(_PROBE_STATUS)
    env = os.environ.get("KAMINPAR_TPU_LANE_GATHER", "")
    if env in ("0", "1"):
        status["env_override"] = env
        if env == "0":
            status["mode"] = "opt-out"
    return status


def maybe_edge_plans(graph):
    """EdgePlans for the level, or None when routing would not pay:
    backend without the Mosaic kernel, small levels, a plan over the
    PLAN_MAX_SLOT_RATIO blowup cap, or opted out via
    KAMINPAR_TPU_LANE_GATHER=0.  KAMINPAR_TPU_LANE_GATHER=1 force-enables
    routing past the size gate and the best-of-3 TIMING race — the
    symmetric override for noisy links where one slow probe round would
    otherwise disable routing for the whole process (ADVICE round 5 low
    #2).  The compile/correctness half of the probe still gates: forcing
    on a backend without the Mosaic kernel (a shell profile exported for
    TPU work, run on a CPU box) stays a no-op instead of a crash."""
    import os

    env = os.environ.get("KAMINPAR_TPU_LANE_GATHER", "")
    if env == "0":
        return None
    if env == "1":
        if _PROBE_STATUS.get("mode") != "forced-on":
            supported, status = _probe_support(skip_timing=True)
            status["mode"] = "forced-on"
            _PROBE_STATUS.clear()
            _PROBE_STATUS.update(status)
            from .. import telemetry
            from ..utils.logger import log_progress

            telemetry.event(
                "lane-gather-probe",
                verdict="forced-on",
                **{k: v for k, v in status.items() if k != "mode"},
            )
            log_progress(
                "lane-gather: force-enabled (KAMINPAR_TPU_LANE_GATHER=1)"
                + ("" if supported else
                   f" but unavailable: {status.get('reason')}")
            )
        return edge_plans(graph) if _PROBE_STATUS.get("supported") else None
    if graph.dst.shape[0] < MIN_EDGE_SLOTS:
        return None
    if not lane_gather_supported():
        return None
    return edge_plans(graph)


@functools.lru_cache(maxsize=1)
def lane_gather_supported() -> bool:
    """One-time probe: the backend must compile the dynamic_gather
    kernel, produce correct results on a multi-vreg (cross-sublane)
    table, AND actually beat the XLA gather at a representative shape —
    a lowering that emulates the gather slowly would silently regress
    every routed round otherwise.  The verdict (and both timings) is
    logged and recorded as a telemetry event: the probe is a single
    best-of-3 timing race cached for the process, and an operator must
    be able to see which way it went (ADVICE round 5 low #2)."""
    supported, status = _probe_support()
    _PROBE_STATUS.clear()
    _PROBE_STATUS.update(status)
    from .. import telemetry
    from ..utils.logger import log_progress

    telemetry.event(
        "lane-gather-probe",
        verdict="enabled" if supported else "disabled",
        **{k: v for k, v in status.items() if k != "mode"},
    )
    detail = ", ".join(
        f"{k}={v}" for k, v in status.items() if k not in ("mode",)
    )
    log_progress(
        f"lane-gather probe: {'enabled' if supported else 'disabled'}"
        + (f" ({detail})" if detail else "")
    )
    return supported


def _probe_support(skip_timing: bool = False):
    """Returns (supported, status dict with reason/timings).  With
    `skip_timing` (the =1 force-enable) only the platform and
    correctness halves gate — the timing race is not run."""
    try:
        from ..utils import platform as _platform

        platform = _platform.default_backend()
        if platform not in ("tpu", "axon"):
            return False, {
                "mode": "probed",
                "supported": False,
                "reason": f"platform {platform} lacks the Mosaic kernel",
            }
        # correctness at a small cross-sublane shape
        n = 16 * L
        rng = np.random.RandomState(0)
        idx = rng.randint(0, n, 4096).astype(np.int32)
        table = rng.randint(0, 1 << 30, n).astype(np.int32)
        # probe plan: fixed 4096-index uniform shape, blowup impossible
        # tpulint: disable=R5
        plan = build_gather_plan(jnp.asarray(idx), n)
        got = np.asarray(lane_gather(jnp.asarray(table), plan))
        inv = np.asarray(plan.inv)
        ok = inv >= 0
        if not np.array_equal(got[ok], table[idx[inv[ok]]]):
            return False, {
                "mode": "probed",
                "supported": False,
                "reason": "dynamic_gather produced incorrect results",
            }
        if skip_timing:
            return True, {"mode": "probed", "supported": True}
        # speed: routed gather must beat the XLA gather at 4M indices
        # from a 2^19-entry table (a mid-size level's shape)
        import time

        m_probe, n_probe = 1 << 22, 1 << 19
        idx2 = jnp.asarray(
            np.random.RandomState(1).randint(0, n_probe, m_probe), jnp.int32
        )
        tab2 = jnp.asarray(
            np.random.RandomState(2).randint(0, 1 << 30, n_probe), jnp.int32
        )
        # probe plan: fixed uniform 4M-index shape, blowup impossible
        # tpulint: disable=R5
        plan2 = build_gather_plan(idx2, n_probe)
        # one-shot probe (lru_cached), the per-call retrace never repeats
        # tpulint: disable=R4
        xla = jax.jit(lambda t, i: t[i])

        def _time(fn, *args):
            out = fn(*args)
            int(jnp.sum(out[:1]))  # force completion (axon-safe readback)
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                out = fn(*args)
                int(jnp.sum(out[:1]))
                best = min(best, time.perf_counter() - t0)
            return best

        t_routed = _time(lambda t: lane_gather(t, plan2), tab2)
        t_xla = _time(xla, tab2, idx2)
        status = {
            "mode": "probed",
            "supported": bool(t_routed < t_xla),
            "t_routed_s": round(t_routed, 6),
            "t_xla_s": round(t_xla, 6),
        }
        if not status["supported"]:
            status["reason"] = "routed gather lost the timing race"
        return status["supported"], status
    except Exception as e:  # pragma: no cover - backend specific
        return False, {
            "mode": "probed",
            "supported": False,
            "reason": f"probe raised {type(e).__name__}",
        }
