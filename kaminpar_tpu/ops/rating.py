"""Rating engines — the shared gather→rate→argmax core of LP and Jet.

The reference rates a node's adjacent clusters in per-thread adaptive
hash maps (kaminpar-common/datastructures/rating_map.h) that grow from a
small fixed map to a full-size table on overflow
(label_propagation.h:62 kRatingMapThreshold).  "Partitioning Complex
Networks via Size-constrained Clustering" (arXiv 1402.3281) is explicit
that the map STRATEGY must adapt to the graph: dense rows want dense
tables, sparse rows want small hashed maps.  This module is the TPU
analog of that adaptivity: one home for every whole-graph rating
strategy plus the density-adaptive selector that picks between them per
level.

Engines (see docs/performance.md "Rating engines"):

  * ``scatter``  — NEW: a hashed slot table filled with segment-sum
    scatter-adds.  Two elimination passes make every *uncontested*
    label's connection weight EXACT, and a per-node ``fully_rated``
    flag marks rows whose every adjacent cluster got rated; rows that
    stay contested are barred from moving this round (the per-round
    salt re-rolls the slots) and a round-level guard falls back to the
    exact sort engine when too many rows are barred — collision-safe
    by construction.  No edge-list sort anywhere: the round touches
    the edge list with ONE gather plus segment ops, which is why this
    is the coarsening hot-path engine (XLA sorts are many HBM passes;
    scatter-adds are one — BENCH_r04 utilization data).
  * ``sort2``    — top-K rated clusters per row via two buffer-wide
    sorts (ops/segments.rating_topk_rows); exact own-connection.
  * ``sort``     — exact enumeration of every adjacent cluster via the
    full 2-key COO sort (ops/segments.aggregate_by_key).  The fallback
    target of ``scatter`` and the reference semantics baseline.
  * ``hash``     — the legacy single-pass winner table
    (ops/segments.hashed_rating_table): contested labels are simply
    unrated for the round.  Kept as a forced option.
  * ``dense``    — the exact (n, k) table for refinement-sized label
    spaces (ops/segments.dense_block_ratings).

An optional Pallas kernel for the rate+argmax core over the slot tables
sits behind the same lazy platform gate as ops/lane_gather (TPU-class
backends only, env-gated); the fused-lax path is the portable default.

All engines share the SAME tie-break hash (hash_u32 of the candidate
label under the round salt), so two engines that rate the same
candidate set pick the SAME cluster — the engine-equivalence contract
tests/test_rating.py pins.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from .segments import (
    ACC_DTYPE,
    INT32_MIN,
    best_from_dense,
    dense_block_ratings,
    hash_u32,
)

ENGINES = ("auto", "scatter", "sort2", "sort", "hash", "dense")

#: Hashed slots per node row (per elimination pass).  32 keeps the slot
#: table at n_pad * 64 entries across both passes — small next to the
#: edge list — while two passes push the fully-rated fraction past ~95%
#: at average degrees up to ~20 (measured on the RMAT bench graphs).
DEFAULT_NUM_SLOTS = 32

#: Fall back to the exact sort engine when more than this fraction of
#: the round's active real nodes are barred (still-contested rows).
#: LPConfig.scatter_fallback defaults from this (0.5 measured best on
#: the 600k bench: barred rows concentrate in the active set over
#: rounds, and a lower threshold flips late rounds into paying BOTH
#: the table build and the sort).
SCATTER_FALLBACK_FRAC = 0.5

ENV_PALLAS = "KAMINPAR_TPU_RATING_PALLAS"


# ---------------------------------------------------------------------------
# density-adaptive engine selection
# ---------------------------------------------------------------------------


def select_engine(
    rating: str,
    num_clusters: int,
    n: int,
    m_slots: int,
    num_slots: int = DEFAULT_NUM_SLOTS,
    avg_degree: Optional[float] = None,
    degree_skew: Optional[float] = None,
    row_spans: bool = True,
) -> Tuple[str, str]:
    """Pick a rating engine for one level; returns (engine, reason).

    Trace-time static: every input is a host int/float (shapes, measured
    level stats), never a traced array.  ``avg_degree``/``degree_skew``
    are the measured per-level density stats (the coarsener reads them
    off the level before clustering; callers without measurements pass
    None and get the padded-shape approximation).  ``row_spans=False``
    (the sharded COO layout) removes the row-span engines (sort2).

    The rule, in order (the 1402.3281 adaptivity argument):
      * forced engine -> respected verbatim;
      * label space <= 256 (refinement-sized) -> dense exact table;
      * avg degree within the slot budget and skew moderate -> scatter
        (collisions stay rare enough that the two-pass elimination
        rates nearly every row; the fallback guard catches the rest);
      * otherwise -> sort2 (dense rows want the top-K sort, and its
        cost does not degrade with contention) — or sort when the
        layout has no row spans.
    """
    if rating != "auto":
        return rating, "forced"
    if num_clusters <= 256:
        return "dense", f"labels={num_clusters}<=256"
    if avg_degree is None:
        avg_degree = m_slots / max(n, 1)
    if degree_skew is None:
        degree_skew = 1.0
    # scatter preconditions, checked in order so the REASON names the
    # first one that failed (the rating-engine event/report row is an
    # audit surface — it must never claim a condition that held):
    #   * density within the slot budget;
    #   * skew window — BELOW it (uniform/geometric graphs, e.g. rgg2d
    #     at skew ~2.5) clustering rides zero-gain tie chains and even
    #     a few percent of barred rows measurably derail the
    #     trajectory (2x cut at 3% barred); ABOVE it, hub rows can
    #     never be fully rated and the fallback churns.  High-skew
    #     RMAT (the class that motivated the engine) tolerates barred
    #     rows: cut matched sort2 within 0.2%;
    #   * int32 packed-winner domain (scatter_slot_ratings' guard,
    #     with headroom for the pad bucket above n AND the coarsener's
    #     density-stepped slot doubling);
    #   * table (2 passes x num_slots per row) within ~6x the edge
    #     width: segment ops pay for their OUTPUT too, and on small
    #     shape-bucketed subgraphs (deep's bipartition coarseners) a
    #     table 30x the edge list costs more than the sorts it
    #     replaces (measured: +50% on extend-partition).
    scatter_reject = None
    if avg_degree > num_slots:
        scatter_reject = f"avg_degree={avg_degree:.1f}>slots={num_slots}"
    elif not (8 <= degree_skew <= 4096):
        scatter_reject = (
            f"degree_skew={degree_skew:.1f} outside [8, 4096]"
        )
    elif n * num_slots > (1 << 27):
        scatter_reject = f"n*slots={n * num_slots} past the int32 budget"
    elif 2 * n * num_slots > 12 * m_slots:
        scatter_reject = "slot table past 6x the edge width"
    if scatter_reject is None:
        return (
            "scatter",
            f"avg_degree={avg_degree:.1f}<=slots={num_slots}",
        )
    if row_spans:
        return "sort2", scatter_reject
    return "sort", f"{scatter_reject}; no row spans (sharded COO)"


# ---------------------------------------------------------------------------
# the scatter-add slot table (two-pass collision elimination)
# ---------------------------------------------------------------------------


def scatter_slot_ratings(
    owner: jax.Array,
    neighbor_label: jax.Array,
    edge_w: jax.Array,
    n_pad: int,
    num_slots: int,
    salt,
    valid: jax.Array | None = None,
    spans: Tuple[jax.Array, jax.Array] | None = None,
    label_space: int | None = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Exact-where-rated hashed rating rows via scatter-adds only.

    Every edge of one (node, label) pair hashes to the SAME slot, so a
    slot whose entries all carry one label holds that label's EXACT
    total connection weight after one segment-sum.  Contested slots
    (>= 2 distinct labels) are resolved by a hashed winner; the losing
    labels' edges are re-hashed under a second salt into a second
    table, where the game repeats.  Labels still contested after both
    passes stay unrated and flag their row.

    Returns (slot_label, slot_w, fully_rated):
      slot_label i32[n_pad, 2*num_slots]  rated label per slot (-1 empty)
      slot_w     ACC[n_pad, 2*num_slots]  exact connection weight
      fully_rated bool[n_pad]             every adjacent label was rated

    ``valid`` masks buffer slots (delta rounds); pad/invalid slots are
    routed to an overflow segment so they can never pollute a row.
    ``spans=(start, end)`` are the owner rows' contiguous slot spans
    (CSR row_ptr on full rounds, the compacted buffer spans on delta
    rounds): when given, the fully_rated flag falls out of a streaming
    cumsum + span diff instead of an n-wide scatter.  Per-edge
    intermediates stay narrow: slot ids and the packed winner keys are
    single int32 lanes (label low bits, hashed key high bits), weights
    keep ACC_DTYPE throughout (dtypes.py policy).

    ``label_space`` is the exclusive upper bound of the LABEL domain
    when it differs from the ROW domain — the owner-sharded dist layout
    has n_loc rows rating GLOBAL cluster ids (n_pad-wide); clipping
    labels to the row count there would silently merge every remote
    label into one.  Default: the row domain (the shm layout).
    """
    if label_space is None:
        label_space = n_pad
    if n_pad * num_slots >= 2**30:
        raise ValueError("n_pad * num_slots must stay well inside int32")
    total = n_pad * num_slots
    label_bits = max(int(label_space - 1).bit_length(), 1)
    key_bits = 31 - label_bits
    if key_bits < 4:
        raise ValueError(
            f"label_space={label_space} leaves {key_bits} winner-key "
            "bits; use the sort engine at this scale"
        )
    lab_mask = jnp.int32((1 << label_bits) - 1)
    nb_c = jnp.clip(neighbor_label, 0, label_space - 1)
    ok = neighbor_label >= 0
    if valid is not None:
        ok = ok & valid

    def one_pass(pass_salt, active_edge):
        """One elimination pass over the masked edges.  Returns
        (slot_label, slot_w, edge_lost): the pass's (n, num_slots)
        table and the mask of edges whose label lost its slot."""
        slot = hash_u32(nb_c, pass_salt) % jnp.int32(num_slots)
        flat = jnp.where(
            active_edge, owner.astype(jnp.int32) * num_slots + slot, total
        )
        # winner of a contested slot in ONE segment-max: hashed key in
        # the high bits, the label itself in the low bits (tie-break by
        # larger label, deterministic)
        key = (
            (hash_u32(nb_c, pass_salt ^ 0x3779B97F) & ((1 << key_bits) - 1))
            << label_bits
        ) | nb_c
        win = jax.ops.segment_max(
            jnp.where(active_edge, key, -1), flat, num_segments=total + 1
        )[:total]
        win_label = jnp.where(win >= 0, win & lab_mask, -1)
        flat_c = jnp.clip(flat, 0, total - 1)
        is_win = active_edge & (win_label[flat_c] == nb_c)
        w = jax.ops.segment_sum(
            jnp.where(is_win, edge_w, 0).astype(ACC_DTYPE),
            flat,
            num_segments=total + 1,
        )[:total]
        edge_lost = active_edge & ~is_win
        return (
            win_label.reshape(n_pad, num_slots),
            w.reshape(n_pad, num_slots),
            edge_lost,
        )

    lab1, w1, lost1 = one_pass(salt, ok)
    lab2, w2, lost2 = one_pass(
        jnp.asarray(salt, jnp.int32) ^ jnp.int32(0x5851F42D), lost1
    )
    # a row is fully rated iff no edge's label remained contested after
    # the second pass (all of a label's edges lose together, so one
    # surviving loser edge == one unrated adjacent cluster)
    if spans is not None:
        # streaming: cumsum of the loser mask + row-span diff (no
        # scatter; the same trick as segments.neighbor_any_true)
        start, end = spans
        csum = jnp.cumsum(lost2.astype(ACC_DTYPE))
        csum0 = jnp.concatenate([jnp.zeros(1, dtype=csum.dtype), csum])
        D = lost2.shape[0]
        fully_rated = (
            csum0[jnp.clip(end, 0, D)] - csum0[jnp.clip(start, 0, D)]
        ) == 0
    else:
        # non-lost edges route to slot n_pad-1 with VALUE 0 (a max
        # no-op), so every row's flag — including n_pad-1's own — is
        # exact from this single scatter
        owner_c = jnp.clip(owner, 0, n_pad - 1)
        unrated = (
            jnp.zeros(n_pad, dtype=jnp.int32)
            .at[jnp.where(lost2, owner_c, n_pad - 1)]
            .max(lost2.astype(jnp.int32), mode="drop")
        )
        fully_rated = unrated == 0
    return (
        jnp.concatenate([lab1, lab2], axis=1),
        jnp.concatenate([w1, w2], axis=1),
        fully_rated,
    )


def best_from_slots(
    slot_label: jax.Array,
    slot_w: jax.Array,
    labels: jax.Array,
    cluster_weights: jax.Array,
    node_w: jax.Array,
    cap: jax.Array,
    tie_salt,
    communities: jax.Array | None = None,
    require_fit: bool = True,
    label_range: Tuple[jax.Array, jax.Array] | None = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Per-node (best_label, best_w, w_own) from scatter slot tables.

    The feasibility chain and the tie-break are IDENTICAL to the sort
    engine's argmax_per_segment (max weight, then max hash_u32(label,
    tie_salt), then max label), so a fully-rated row picks the same
    cluster the sort engine would — the engine-equivalence contract.
    ``w_own`` is the row's exact connection to its own label (0 when
    the own label is absent; rows whose own label stayed contested are
    never fully rated, so callers bar them anyway).
    """
    n_pad = slot_label.shape[0]
    C = cluster_weights.shape[0]
    lab_c = jnp.clip(slot_label, 0, C - 1)
    own = labels[:, None]
    w_own = jnp.max(
        jnp.where(slot_label == own, slot_w, 0), axis=1
    )
    feas = (slot_label >= 0) & (slot_label != own)
    if label_range is not None:
        lo, hi = label_range
        feas = feas & (slot_label >= lo) & (slot_label < hi)
    if require_fit:
        cap_b = jnp.broadcast_to(cap, (C,))
        feas = feas & (
            cluster_weights[lab_c].astype(ACC_DTYPE)
            + node_w[:, None].astype(ACC_DTYPE)
            <= cap_b[lab_c]
        )
    if communities is not None:
        # clustering labels are node ids: a cluster's community is its
        # label node's community (same rule as every other engine)
        lab_n = jnp.clip(slot_label, 0, n_pad - 1)
        feas = feas & (communities[lab_n] == communities[:, None])
    score = jnp.where(feas, slot_w, INT32_MIN)
    best_w = jnp.max(score, axis=1)
    has = best_w > INT32_MIN
    is_best = feas & (score == best_w[:, None])
    tb = hash_u32(slot_label, tie_salt)
    best_tb = jnp.max(jnp.where(is_best, tb, -1), axis=1)
    winner = is_best & (tb == best_tb[:, None])
    best = jnp.max(jnp.where(winner, slot_label, -1), axis=1)
    return (
        jnp.where(has, best, -1),
        jnp.where(has, best_w, INT32_MIN),
        w_own,
    )


# ---------------------------------------------------------------------------
# optional Pallas rate+argmax core (lazy platform gate; lax is default)
# ---------------------------------------------------------------------------


def rating_pallas_requested() -> bool:
    """The opt-in env gate, mirroring ops/lane_gather's contract: the
    Pallas core only runs on TPU-class backends AND when explicitly
    requested — the fused-lax path is the portable default."""
    if os.environ.get(ENV_PALLAS, "") != "1":
        return False
    try:
        from ..utils import platform

        return platform.default_backend() in ("tpu", "axon")
    except Exception:
        return False


def best_from_slots_pallas(
    slot_label: jax.Array,
    slot_w: jax.Array,
    labels: jax.Array,
    tie_salt,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Pallas row-wise rate+argmax over the slot tables: per row the
    best non-own (label, weight) pair plus the own connection, with the
    shared tie-break hash.  Feasibility (weight caps, communities) is
    applied by the caller at node level — the kernel only needs the
    row-local reduction, which is the part worth keeping in VMEM.

    Unlike the full best_from_slots this does NOT mask infeasible
    targets, so it serves the unconstrained rating uses (two-hop
    favored clusters, candidate pre-ranking); `interpret=True` runs the
    same kernel through the Pallas interpreter for CPU tests.
    """
    from jax.experimental import pallas as pl

    n_pad, S = slot_label.shape

    def kernel(lab_ref, w_ref, own_ref, out_lab, out_w, out_own):
        lab = lab_ref[...]
        w = w_ref[...]
        own = own_ref[...]
        own_b = own[:, None]
        w_own = jnp.max(jnp.where(lab == own_b, w, 0), axis=1)
        feas = (lab >= 0) & (lab != own_b)
        score = jnp.where(feas, w, INT32_MIN)
        best_w = jnp.max(score, axis=1)
        is_best = feas & (score == best_w[:, None])
        tb = hash_u32(lab, tie_salt)
        best_tb = jnp.max(jnp.where(is_best, tb, -1), axis=1)
        winner = is_best & (tb == best_tb[:, None])
        best = jnp.max(jnp.where(winner, lab, -1), axis=1)
        has = best_w > INT32_MIN
        out_lab[...] = jnp.where(has, best, -1)
        out_w[...] = jnp.where(has, best_w, INT32_MIN)
        out_own[...] = w_own

    rows = min(512, n_pad)  # n_pad is a power-of-two bucket
    grid = (max(n_pad // rows, 1),)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((rows, S), lambda i: (i, 0)),
            pl.BlockSpec((rows, S), lambda i: (i, 0)),
            pl.BlockSpec((rows,), lambda i: (i,)),
        ],
        out_specs=[
            pl.BlockSpec((rows,), lambda i: (i,)),
            pl.BlockSpec((rows,), lambda i: (i,)),
            pl.BlockSpec((rows,), lambda i: (i,)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_pad,), jnp.int32),
            jax.ShapeDtypeStruct((n_pad,), slot_w.dtype),
            jax.ShapeDtypeStruct((n_pad,), slot_w.dtype),
        ],
        interpret=interpret,
    )(slot_label, slot_w, labels)


# Re-exports: the dense refinement core lives in segments.py for
# historical import-cycle reasons; rating.py is its public home so LP,
# Jet and the dist kernels share one rating surface.
__all__ = [
    "ENGINES",
    "DEFAULT_NUM_SLOTS",
    "SCATTER_FALLBACK_FRAC",
    "select_engine",
    "scatter_slot_ratings",
    "best_from_slots",
    "best_from_slots_pallas",
    "rating_pallas_requested",
    "dense_block_ratings",
    "best_from_dense",
]
